#!/usr/bin/env python
"""Headline bench: LLM decode throughput on the continuous-batching engine.

North star (BASELINE.md): Llama-2-7B tokens/sec/chip on TPU, vs the A100
class the reference's vLLM example assumes. Baseline constant below:
~1400 output tok/s is a representative public vLLM Llama-2-7B total decode
throughput on one A100-40GB at moderate batch. vs_baseline = value/1400.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Supervisor/child structure: the supervisor tries every model config its
wall-clock budget allows in subprocesses with timeouts (a wedged TPU or an
OOM must degrade, not hang the driver), then prints the BEST result —
round 2 printed the first success, which could never be the int8 config
that actually has headroom. Extra keys report every config tried
(``all_configs``), the achieved weight-streaming rate as a fraction of the
v5e HBM ceiling (``pct_hbm_ceiling``), and warm-boot timings measured with
the persistent XLA compile cache (``warm_build_s``/``warm_compile_s``).
BENCH_MODEL env forces a config; BENCH_CPU=1 forces the CPU backend.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

A100_LLAMA2_7B_TOK_S = 1400.0
V5E_HBM_GBPS = 819.0  # v5e HBM bandwidth ceiling, bytes streamed per second
V5E_HBM_BYTES = 16e9  # v5e HBM capacity: the slots-at-budget denominator

CONFIGS = {
    # name: engine kwargs + measurement shape. int8 weight-only quantization
    # halves weight-streaming bytes AND frees HBM for slots — the bf16 8-slot
    # config's ceiling is ~486 tok/s (8 tok per 16.5 ms weight read), so the
    # quantized high-slot configs are the only road to the 1400 target.
    "llama2-7b-int4-s36": dict(
        # int4 weights: ~3.5 GB floor (4.2 ms/step) — the unsloth 4-bit
        # load path analog (unsloth_finetune.py:187-197)
        slots=36, max_len=256, max_tokens=128, timeout=1200, quant="int4"
    ),
    "llama2-7b-int8-s36": dict(
        # 36 slots is the measured sweet spot with the ragged kernel; the
        # remote-compile helper crashes somewhere past ~40 (round-4 sweep)
        slots=36, max_len=256, max_tokens=128, timeout=1200, quant="int8"
    ),
    "llama2-7b-int8-kv8-s36": dict(
        # int8 KV on top of int8 weights: KV reads at the headline shape
        # are ~4.3 GB/step (comparable to the int8 weight floor); int8 KV
        # halves them AND halves residency (docs/kv_cache.md). Same 36-slot
        # sweet spot — the compile-helper cap (~40), not HBM, binds slots.
        slots=36, max_len=256, max_tokens=128, timeout=1200, quant="int8",
        kv_dtype="int8",
    ),
    "llama2-7b-int8-s44": dict(
        # the >=40-slot compile-helper ceiling repro (ROADMAP #1): the
        # round-4 sweep crashed the remote-compile helper somewhere past
        # ~40 slots, wedging the chip. NOT in the supervisor's default
        # order — run only by revalidate_chip.sh's compile-ledger stage
        # with MTPU_PROFILE=1 and a local MTPU_STATE_DIR: the profiler
        # writes a `begin` ledger event BEFORE each program build, so even
        # when this run dies mid-compile the ledger's begin-without-end
        # row names exactly which program/shape hit the ceiling —
        # diagnosable offline from compiles.jsonl alone.
        slots=44, max_len=256, max_tokens=32, timeout=1500, quant="int8",
        kv_dtype="int8",
    ),
    "llama2-7b-int8-kv8-ctx1024": dict(
        # long-context decode: at ctx 1024 KV reads are ~34 GB/step and
        # DOMINATE the step (NOTES r5) — the config where int8 KV is the
        # whole game. 16 slots x 1024 ctx = ~4 GB int8 KV (bf16 would be
        # ~8 GB next to the ~7 GB int8 weights: right at the HBM edge).
        # prompt_mult pushes real contexts to ~500+ tokens so decode runs
        # at long positions (chunked prefill path), not just long tables.
        slots=16, max_len=1024, max_tokens=128, timeout=1500, quant="int8",
        kv_dtype="int8", prompt_mult=40,
    ),
    "llama2-7b-tp2-int8-ctx1024": dict(
        # tensor parallelism on the sharded Pallas fast path (round 7): the
        # ROADMAP-named TP=2 on-chip A/B partner of the ctx-1024 int8
        # config — same slots/context/dtype, cache + kernels sharded over
        # the kv-head ICI axis via shard_map (ops.sharded). Per-shard
        # Hkv=16, so int8 runs the grouped ragged variant (the plan rides
        # in the json's impl_plan). Needs >= 2 chips; on a 1-chip host the
        # mesh build fails and the supervisor degrades to the next config.
        slots=16, max_len=1024, max_tokens=128, timeout=1500, quant="int8",
        kv_dtype="int8", prompt_mult=40, tp=2,
    ),
    "llama2-7b-int8-spec-ngram": dict(
        # speculative decoding as a measured lever (ROADMAP open item #4):
        # prompt-lookup ngram proposals against the repetitive bench prompt
        # give high acceptance, so this is the config where acceptance-rate
        # -> tok/s becomes a real, driver-captured delta vs
        # llama2-7b-int8-kv8-s36 (same shape, no spec). The json's `spec`
        # section carries {mode, gamma, acceptance_rate}.
        slots=16, max_len=256, max_tokens=128, timeout=1500, quant="int8",
        kv_dtype="int8", spec=("ngram", 4),
    ),
    "llama2-7b-int8-spec-draft1b": dict(
        # draft-model speculation: a 1B-shape draft (same 32000 vocab)
        # proposes, the 7B verifies. Random draft weights (zero-egress)
        # floor the acceptance rate, so this config measures the MECHANISM
        # cost (draft decode + verify pass per tick); the ngram config
        # above carries the acceptance-driven win. Real checkpoints would
        # only raise acceptance, never the per-tick cost.
        slots=16, max_len=256, max_tokens=128, timeout=1500, quant="int8",
        kv_dtype="int8", spec=("draft-1b", 4),
    ),
    "llama2-7b-mixed-ctx1024": dict(
        # stall-free admission at the long-context shape (docs/scheduling.md):
        # the ctx-1024 int8-KV config under MIXED traffic — one interactive
        # stream's observed TPOT captured while ~1k-token prompts arrive and
        # chunk-prefill, with the per-tick prefill budget ON (256 = one
        # chunk per tick) vs OFF. The json's `interference` section carries
        # both arms' p50/p95 plus the decode-stall histogram; staged into
        # revalidate_chip.sh as its own A/B stage.
        slots=16, max_len=1024, max_tokens=128, timeout=1500, quant="int8",
        kv_dtype="int8", prompt_mult=40, mixed=True, budget=256,
    ),
    "llama2-7b-disagg-2rep": dict(
        # disaggregated prefill/decode at the ctx-1024 int8-KV shape (the
        # A/B partner of llama2-7b-int8-kv8-ctx1024): a prefill replica
        # computes prompt KV and ships int8 pages + scale rows to the
        # decode replica (docs/disagg.md). Weights are SHARED between the
        # two in-process engines (params= alias, read-only in the jits) so
        # HBM pays one int8 weight set + two caches; the prefill replica
        # runs 4 slots of transient claims (prefills are serialized).
        slots=16, max_len=1024, max_tokens=128, timeout=1500, quant="int8",
        kv_dtype="int8", prompt_mult=40, disagg=True,
    ),
    "llama2-7b-int8-s32": dict(
        slots=32, max_len=256, max_tokens=128, timeout=1200, quant="int8"
    ),
    "llama2-7b-int8-s16": dict(
        slots=16, max_len=384, max_tokens=128, timeout=1200, quant="int8"
    ),
    "llama2-7b": dict(slots=8, max_len=256, max_tokens=128, timeout=1200),
    "llama3.1-8b-int8-s32": dict(
        # GQA on the fast path (VERDICT r4 #4): Hkv=8 runs the v4 "grouped"
        # ragged kernel (per-kv-head contraction — no Hkv%16 flatten). The
        # reference's serving targets are GQA-era (vllm_inference.py:54-58);
        # not baseline-comparable (different model) but must carry its own
        # on-chip number in all_configs.
        slots=32, max_len=256, max_tokens=128, timeout=1500, quant="int8"
    ),
    "llama-1b": dict(slots=16, max_len=512, max_tokens=128, timeout=900),
    "tiny": dict(slots=4, max_len=128, max_tokens=16, timeout=420),
    # CPU path-proof of the disagg pipeline (test_bench_contract): never the
    # headline, but the same two-replica code shape the 7B config runs
    "tiny-disagg": dict(
        slots=4, max_len=128, max_tokens=16, timeout=420, disagg=True
    ),
    # CPU path-proofs (test_bench_contract): the sharded-pallas TP=2 code
    # shape on a forced 8-device host mesh, and the ngram-spec code shape —
    # same engine wiring the 7B configs run on chip
    "tiny-tp2": dict(
        slots=4, max_len=128, max_tokens=16, timeout=420, tp=2,
    ),
    "tiny-spec-ngram": dict(
        slots=4, max_len=128, max_tokens=16, timeout=420, spec=("ngram", 2),
    ),
    # CPU path-proof of fused adaptive speculation (test_bench_contract,
    # docs/speculative.md#gamma-schedule): spec-off vs fixed-γ vs adaptive
    # on the same warm engine over a MIXED acceptance population
    # (repetitive prompts the n-gram proposer nails + prose it can't) —
    # the json's `spec` section carries gamma_p50 / acceptance_rate /
    # tokens_per_dispatch / fallback_rounds and the per-arm TPOT tails
    # benchdiff gates on (speculation pays where acceptance is high,
    # and the controller's retreat must keep the adaptive arm no slower
    # than spec-off where it isn't)
    # decode_block=1 isolates speculation from macro-step amortization
    # (same rationale as tiny-multistep): the spec-off arm pays one host
    # round-trip per token, so the A/B measures what the γ-deep verify
    # round buys, not what block fusion buys
    "tiny-spec-adaptive": dict(
        slots=4, max_len=128, max_tokens=16, timeout=420,
        spec=("ngram", 4), spec_ab=True, decode_block=1,
    ),
    # CPU path-proof of stall-free admission (test_bench_contract): the
    # same mixed-traffic interference A/B the 7B config above runs on chip
    # — an interactive stream's TPOT while long prompts chunk-prefill,
    # budget on (64 tokens/tick) vs off
    "tiny-mixed": dict(
        slots=4, max_len=512, max_tokens=16, timeout=420, prompt_mult=12,
        mixed=True, budget=64,
    ),
    # CPU path-proof of the macro-step decode runtime (test_bench_contract,
    # docs/multistep.md): decode_block=1 makes the classic arm pay one host
    # round-trip PER TOKEN, so the N=1 vs N=8 A/B on the same warm engine
    # exposes exactly the per-token host overhead ROADMAP #3 says to
    # amortize — the json's `multistep` section carries both arms'
    # host_fraction / tick_p95 and the deltas must favor the N=8 arm
    "tiny-multistep": dict(
        slots=4, max_len=128, max_tokens=16, timeout=420, multistep=8,
        decode_block=1,
    ),
    # the on-chip macro-step A/B at the int8 headline shape
    # (revalidate_chip.sh, behind the benchdiff gate): what N=8 fused
    # decode steps buy real llama2-7b streams — tokens-per-dispatch up,
    # host fraction down, with HBM-sized KV where every saved host
    # round-trip is real decode time
    "llama2-7b-int8-multistep": dict(
        slots=16, max_len=256, max_tokens=128, timeout=1500, quant="int8",
        kv_dtype="int8", multistep=8,
    ),
    # the on-chip adaptive-speculation A/B at the int8 headline shape
    # (revalidate_chip.sh, behind the benchdiff gate): prompt-lookup
    # proposals against real llama2-7b weights, spec-off vs fixed-γ vs
    # the acceptance-driven controller on the same warm engine
    "llama2-7b-int8-spec-adaptive": dict(
        slots=16, max_len=256, max_tokens=128, timeout=1500, quant="int8",
        kv_dtype="int8", spec=("ngram", 4), spec_ab=True, decode_block=1,
    ),
    # CPU path-proof of the chaos harness (test_bench_contract): after the
    # measured run, the seeded fault-injection episode schedule drives a
    # fresh tiny fleet through every cataloged fault point and the json
    # carries a `faults` section {injected, recovered, wedged: 0}
    # (docs/faults.md) — proving the failure contract alongside the
    # throughput number
    "tiny-chaos": dict(
        slots=4, max_len=128, max_tokens=16, timeout=420, chaos=True
    ),
    # CPU path-proof of in-flight failover (test_bench_contract,
    # docs/failover.md): after the measured run, streams are killed
    # mid-decode by an injected scheduler crash and checkpoint-resumed on
    # a second replica; the json carries a `failover` section
    # {takeover_latency p50/p95, tokens_replayed, resumed_identical} —
    # the takeover p95 is what bench_diff gates round over round
    "tiny-failover": dict(
        slots=4, max_len=192, max_tokens=32, timeout=420, failover=True
    ),
    # CPU path-proof of gray-failure recovery (test_bench_contract,
    # docs/health.md): after the measured run, a replica's scheduler is
    # SILENTLY frozen (no crash, no error) with streams mid-decode; the
    # progress watchdog must detect the wedge from stale watermarks,
    # error-stop the replica, and the PR-12 failover must resume every
    # stream token-identically. The json carries a `recovery` section
    # {time_to_detect p50/p95, time_to_mitigate p50/p95, goodput_dip,
    # wedged: 0} — the mitigation p95 is what bench_diff gates round over
    # round
    "tiny-recovery": dict(
        slots=4, max_len=192, max_tokens=32, timeout=420, recovery=True
    ),
    # the on-chip gray-failure recovery A/B at the int8 headline shape
    # (revalidate_chip.sh, behind the benchdiff gate): what a silently
    # wedged llama2-7b replica costs real streams — detection + mitigation
    # latency with HBM-sized KV and real replay work
    "llama2-7b-recovery": dict(
        slots=16, max_len=384, max_tokens=64, timeout=1500, quant="int8",
        kv_dtype="int8", recovery=True,
    ),
    # the on-chip failover A/B at the int8 headline shape
    # (revalidate_chip.sh, behind the benchdiff gate): what a mid-stream
    # replica death costs a real llama2-7b stream — takeover latency and
    # replayed-prefill work with HBM-sized KV
    "llama2-7b-failover": dict(
        slots=16, max_len=384, max_tokens=64, timeout=1500, quant="int8",
        kv_dtype="int8", failover=True,
    ),
    # CPU path-proof of the closed fleet loop (test_bench_contract,
    # docs/fleet.md): after the measured run, the open-loop load generator
    # drives a calibrated saturating sweep against an OpenAI server fronting
    # the engine — pinned single replica first, then with the FleetAutoscaler
    # scaling decode replicas out via snapshot-restored warm boots — and the
    # json carries a `fleet` section (goodput, p99 TTFT/TPOT vs offered
    # load, shed rate, scale events, A/B at the knee)
    # max_len 384: the byte-level tokenizer makes the loadgen's
    # shared-prefix prompts 100-300 TOKENS, and a clipped prompt would
    # finish after one token and measure nothing but prefill
    # fleet_max 2: scaled replicas share the host's cores with the primary
    # on the CPU path-proof, and a third engine is pure contention there.
    # ONE slot per replica: the pinned replica is then slot-bound while
    # the host keeps CPU headroom, so scale-out adds real capacity — with
    # 2+ slots a single tiny engine is CPU-bound and the A/B flatlines
    "tiny-fleet": dict(
        slots=1, max_len=384, max_tokens=8, timeout=420, fleet=True,
        fleet_step_s=4.0, fleet_max=2,
    ),
    # the on-chip fleet sweep (revalidate_chip.sh stage 14): the headline
    # int8 shape under production-shaped open-loop traffic. max 2 decode
    # replicas — each warm boot restores a full int8 weight set (~7 GB), so
    # v5e HBM holds two replicas plus caches and no more.
    "llama2-7b-fleet-sweep": dict(
        slots=16, max_len=384, max_tokens=64, timeout=1500, quant="int8",
        kv_dtype="int8", fleet=True, fleet_step_s=10.0, fleet_max=2,
    ),
}


def _measure_canary(engine) -> dict:
    """Golden-set canary rounds on the measured engine
    (docs/observability.md#correctness-canary): first contact with this
    (model, fingerprint) identity records the golden, then a compare round
    gates bit-exact — pass rate, probe latency quantiles, and a drift
    count (expected: 0) ride in every BENCH json, so a numerically
    drifting build fails loudly at bench time instead of in serving. A
    cross-identity golden raises CanaryIdentityError (the loud banner) —
    never a false drift verdict."""
    from modal_examples_tpu.observability import canary as _canary

    store = _canary.GoldenStore()
    model = _canary.model_id(engine.cfg)
    fp = _canary.fingerprint(engine)
    golden = store.load(model, fp)  # CanaryIdentityError propagates, loudly
    recorded_now = golden is None
    if recorded_now:
        rec = _canary.probe_engine(engine, replica="bench", golden=None)
        probes = {
            r["probe"]: {"tokens": r["tokens"]}
            for r in rec
            if r["result"] == "recorded"
        }
        if len(probes) == len(_canary.GOLDEN_SET):
            store.record(model, fp, probes)
            golden = store.load(model, fp)
    results = _canary.probe_engine(engine, replica="bench", golden=golden)

    def _q(vals: list, frac: float):
        vals = sorted(v for v in vals if v is not None)
        if not vals:
            return None
        return round(vals[min(len(vals) - 1, round(frac * (len(vals) - 1)))], 6)

    compared = [r for r in results if r["result"] in ("pass", "drift")]
    drifts = sum(1 for r in results if r["result"] == "drift")
    out = {
        "probes": len(results),
        "pass_rate": (
            round(sum(1 for r in compared if r["result"] == "pass")
                  / len(compared), 4)
            if compared else None
        ),
        "drift_count": drifts,
        "errors": sum(1 for r in results if r["result"] == "error"),
        "fingerprint": _canary.fingerprint_hash(fp),
        "recorded": recorded_now,
    }
    for key in ("ttft", "tpot", "e2e"):
        vals = [r.get(key) for r in results]
        out[f"{key}_p50"] = _q(vals, 0.5)
        out[f"{key}_p95"] = _q(vals, 0.95)
    return out


def _measure_interference(engine, spec: dict) -> dict:
    """Stall-free admission A/B (docs/scheduling.md): while one interactive
    stream decodes, long-prompt arrivals force chunked prefills; the gaps
    between the stream's emitted pieces are its OBSERVED inter-token
    latency. Arm one runs the classic unbudgeted admission, arm two the
    config's per-tick prefill budget — the p95 gap is exactly the
    prefill/decode interference the budget exists to bound (~one chunk
    instead of the whole prompt). Runs on the same warm engine as the
    measured throughput loop; chunk jits are pre-warmed so neither arm
    pays first-compile."""
    import time as _time

    from modal_examples_tpu.serving import SamplingParams

    budget = int(spec.get("budget") or engine.prefill_buckets[-1])
    long_prompt = (
        "The quick brown fox jumps over the lazy dog. "
        * spec.get("prompt_mult", 12)
    )
    warm = engine.submit(
        long_prompt, SamplingParams(max_tokens=2, temperature=1.0)
    )
    for _ in engine.stream(warm):
        pass

    def run_arm(arm_budget: int) -> dict:
        engine.prefill_budget = arm_budget
        fg = engine.submit(
            "interactive stream under interference",
            SamplingParams(max_tokens=6 * spec["max_tokens"], temperature=1.0),
            priority="interactive",
        )
        stamped: list[tuple[float, float]] = []  # (gap end, gap seconds)
        longs: list = []
        last = None
        t_submit = None
        n_pieces = 0
        for _piece in engine.stream(fg):
            now = _time.monotonic()
            if last is not None:
                stamped.append((now, now - last))
            last = now
            n_pieces += 1
            if n_pieces == 2:
                # the stream is demonstrably decoding: drop a burst of
                # long-prompt prefills on it
                t_submit = _time.monotonic()
                longs = [
                    engine.submit(
                        long_prompt,
                        SamplingParams(max_tokens=4, temperature=1.0),
                        priority="batch",
                    )
                    for _ in range(4)
                ]
        for r in longs:
            for _ in engine.stream(r):
                pass
        # quantiles over the INTERFERENCE WINDOW only — submission of the
        # long prompts until the last one's prefill completed (its first
        # token is engine-stamped) — so the stream's steady-state tail
        # can't dilute the stall the A/B exists to expose. A gap counts if
        # it overlaps the window.
        t_end = max(
            [r.first_token_at or 0.0 for r in longs] or [float("inf")]
        )
        gaps = [
            g for t, g in stamped
            if t_submit is not None and t >= t_submit and t - g <= t_end
        ] or [g for _, g in stamped]
        gaps.sort()

        def q(p: float) -> float:
            if not gaps:
                return 0.0
            return gaps[min(len(gaps) - 1, int(p * len(gaps)))]

        return {
            "tpot_p50": round(q(0.50), 6),
            "tpot_p95": round(q(0.95), 6),
            "tpot_max": round(gaps[-1], 6) if gaps else 0.0,
            "pieces": n_pieces,
        }

    from modal_examples_tpu.observability import catalog as _C
    from modal_examples_tpu.utils.prometheus import default_registry

    saved = engine.prefill_budget
    try:
        # budgeted arm FIRST: the decode-stall histogram snapshotted right
        # after it covers only budgeted traffic (the measured run + this
        # arm — mixed configs run the measured loop budgeted too), so its
        # quantiles can evidence the "no gap exceeds ~one chunk" contract.
        # Snapshotting after the unbudgeted arm would bake that arm's
        # whole-prompt stalls into the very histogram the budget exists to
        # bound.
        budgeted = run_arm(budget)
        stall_q = default_registry.histogram_quantiles(
            _C.DECODE_STALL_SECONDS
        )
        unbudgeted = run_arm(0)
    finally:
        engine.prefill_budget = saved
    return {
        "budget_tokens": budget,
        "chunk_tokens": engine.prefill_buckets[-1],
        "unbudgeted": unbudgeted,
        "budgeted": budgeted,
        # >1 means the budget cut the interactive stream's tail latency
        "improvement_p95": round(
            unbudgeted["tpot_p95"] / max(budgeted["tpot_p95"], 1e-9), 3
        ),
        **(
            {
                "decode_stall": {
                    k: stall_q[k]
                    for k in ("p50", "p95", "p99", "count")
                    if k in stall_q
                }
            }
            if stall_q
            else {}
        ),
    }


def _measure_multistep(engine, spec: dict) -> dict:
    """Macro-step decode A/B (docs/multistep.md): the same warm engine runs
    identical traffic twice — classic one-block-per-dispatch (N=1) vs the
    config's N-step macro dispatch — and per-arm profiler-ring slices put
    host_fraction and tick_p95 side by side. ``decode_steps`` is the
    runtime-mutable knob, so there is no rebuild between arms; each arm
    pre-warms one request outside its measured slice so a first-dispatch
    compile (ledgered as a miss) can't pollute the tick tail. On the N-step
    arm every harvested dispatch carries up to N tokens, so host_fraction
    and tick-per-token must DROP — the deltas in this section are the
    CPU path-proof benchdiff gates on."""
    from modal_examples_tpu.observability import catalog as _C
    from modal_examples_tpu.serving import SamplingParams
    from modal_examples_tpu.utils.prometheus import default_registry
    from modal_examples_tpu.utils.stats import percentile_nearest_rank as _pp

    steps = int(spec["multistep"])
    prof = engine.profiler
    sp = SamplingParams(max_tokens=spec["max_tokens"], temperature=1.0)

    def run_arm(n: int) -> dict:
        engine.decode_steps = n
        for _ in engine.stream(engine.submit("multistep arm warm", sp)):
            pass
        d0 = default_registry.total(_C.MULTISTEP_DISPATCHES_TOTAL)
        k0 = default_registry.total(_C.MULTISTEP_TOKENS_TOTAL)
        t_start = time.time()
        reqs = [
            engine.submit(f"macro step arm {n} prompt {i}", sp)
            for i in range(spec["slots"] * 2)
        ]
        for r in reqs:
            for _ in engine.stream(r):
                pass
        dispatches = default_registry.total(_C.MULTISTEP_DISPATCHES_TOTAL) - d0
        tokens = default_registry.total(_C.MULTISTEP_TOKENS_TOTAL) - k0
        out = {
            "dispatches": int(dispatches),
            "tokens": int(tokens),
            "tokens_per_dispatch": (
                round(tokens / dispatches, 3) if dispatches else None
            ),
        }
        if prof is not None:
            # the ring is shared across arms: slice this arm's busy ticks
            # by wall-clock start (each entry stamps `at` at end_tick)
            ticks = [
                e for e in prof.perfetto_snapshot()["ticks"]
                if e["at"] >= t_start
            ]
            totals = sorted(e["total"] for e in ticks)
            sum_total = sum(totals)
            if sum_total > 0 and tokens:
                sum_device = sum(e["device"] for e in ticks)
                out["host_fraction"] = round(
                    max(0.0, min(1.0, 1.0 - sum_device / sum_total)), 6
                )
                out["tick_p95"] = round(_pp(totals, 0.95), 6)
                # the quantity macro-stepping amortizes, robust even where
                # "device" is the host's own cores (the CPU path-proof):
                # scheduler-thread seconds spent per accepted token
                out["host_ms_per_token"] = round(
                    (sum_total - sum_device) / tokens * 1000, 4
                )
        return out

    saved = engine.decode_steps
    try:
        classic = run_arm(1)
        multi = run_arm(steps)
    finally:
        engine.decode_steps = saved
    section = {
        "steps": steps,
        "classic": classic,
        "multistep": multi,
        # the benchdiff-gated scalar (utils/bench_diff.py METRICS)
        "tokens_per_dispatch": multi.get("tokens_per_dispatch"),
    }
    if "host_fraction" in classic and "host_fraction" in multi:
        # positive = the macro-step arm spent a smaller host share. On a
        # real chip this is the headline drop; on the CPU path-proof the
        # "device" is the host's own cores, so wall-clock attribution is
        # contention noise there — the robust CPU direction check is
        # host_ms_per_token_delta below
        section["host_fraction_delta"] = round(
            classic["host_fraction"] - multi["host_fraction"], 6
        )
    if "tick_p95" in classic and "tick_p95" in multi:
        # per-TOKEN tick tail: an N-step tick hosts up to N tokens, so
        # normalize before comparing — positive = cheaper per token
        section["tick_p95_delta"] = round(
            classic["tick_p95"] - multi["tick_p95"] / steps, 6
        )
    if "host_ms_per_token" in classic and "host_ms_per_token" in multi:
        section["host_ms_per_token_delta"] = round(
            classic["host_ms_per_token"] - multi["host_ms_per_token"], 4
        )
    return section


def _measure_spec_adaptive(engine, spec: dict) -> dict:
    """Fused-speculation A/B (docs/speculative.md#gamma-schedule): the same
    warm engine runs an identical MIXED-acceptance population three times
    via the runtime-mutable spec knobs — spec off (depth 0), fixed full γ,
    and the adaptive controller — so both halves of the contract land in
    one json section: speculation pays where acceptance is high
    (``tokens_per_dispatch`` > 1 on the arms that speculate), and the
    controller's retreat means adaptivity can never cost latency (the
    adaptive arm's TPOT p95 vs the spec-off arm's is the benchdiff gate).
    Greedy traffic throughout — only greedy lanes speculate (the fused
    program's exactness contract, docs/speculative.md#exactness)."""
    import threading

    import numpy as _np

    from modal_examples_tpu.serving import SamplingParams

    sp = SamplingParams(max_tokens=spec["max_tokens"], temperature=0.0)
    # two acceptance regimes, measured separately because they gate two
    # DIFFERENT contracts: "accept" (looping text the n-gram proposer
    # nails → speculation must pay: tokens_per_dispatch > 1) and
    # "hostile" (the same bigram followed by a different token every
    # occurrence → proposals fire and miss, so the controller must
    # shrink γ and the adaptive arm must cost no more than spec-off)
    n = spec["slots"] * 2
    populations = {
        "accept": ["one two three " * 6 for _ in range(n)],
        "hostile": [
            "one two three one two four one two five one two six one two"
            for _ in range(n)
        ],
    }
    # bounded concurrency (slots-1 outstanding): a SATURATED batch is the
    # controller's global-pressure regime (it rightly speculates for no
    # one — verify flops scale with γ+1 per lane and a full batch is
    # already amortized), which would make every arm identical; the A/B
    # exists to expose the PER-REQUEST acceptance policy, so the traffic
    # keeps one slot of headroom like latency-bound serving does
    conc = threading.Semaphore(max(1, spec["slots"] - 1))

    def run_arm(depth: int, adaptive: bool, prompts: list) -> dict:
        engine.spec_depth = depth
        engine.spec_adaptive = adaptive
        for _ in engine.stream(engine.submit("spec arm warm " * 3, sp)):
            pass
        # freeze the gauge sweep so it can't drain the γ window mid-arm;
        # the arm computes its own p50 from the full window
        saved_wall = engine._metrics_wall
        engine._metrics_wall = time.monotonic() + 3600.0
        del engine._spec_gamma_window[:]
        r0 = engine._spec_rounds
        k0 = engine._spec_round_tokens
        f0 = engine._spec_fallbacks
        p0 = engine.stats.spec_proposed
        a0 = engine.stats.spec_accepted
        # per-REQUEST TPOT ((t_last - t_first) / (n - 1)), quantiles
        # across requests: spec rounds deliver tokens in bursts, so raw
        # inter-arrival gap quantiles would structurally punish any
        # multi-token dispatch (most gaps ~0, the tail = one whole round)
        # — the same reason _measure_multistep normalizes tick_p95 by N
        tpots: list[float] = []
        t0 = time.time()

        def drain(prompt):
            with conc:
                r = engine.submit(prompt, sp)
                first = last = None
                pieces = 0
                for _ in engine.stream(r):
                    last = time.monotonic()
                    if first is None:
                        first = last
                    pieces += 1
                n = max(r.n_generated, pieces)
                if first is not None and n > 1:
                    tpots.append((last - first) / (n - 1))

        threads = [
            threading.Thread(target=drain, args=(p,)) for p in prompts
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.time() - t0
        rounds = engine._spec_rounds - r0
        tokens = engine._spec_round_tokens - k0
        proposed = engine.stats.spec_proposed - p0
        accepted = engine.stats.spec_accepted - a0
        window = list(engine._spec_gamma_window)
        engine._metrics_wall = saved_wall
        tpots.sort()

        def q(p: float) -> float:
            if not tpots:
                return 0.0
            return tpots[min(len(tpots) - 1, int(p * len(tpots)))]

        return {
            "spec_rounds": int(rounds),
            "fallback_rounds": int(engine._spec_fallbacks - f0),
            "tokens_per_dispatch": (
                round(tokens / rounds, 3) if rounds else None
            ),
            "gamma_p50": (
                float(_np.median(window)) if window else 0.0
            ),
            "proposed": int(proposed),
            "accepted": int(accepted),
            "acceptance_rate": (
                round(accepted / proposed, 4) if proposed else 0.0
            ),
            "tpot_p50": round(q(0.50), 6),
            "tpot_p95": round(q(0.95), 6),
            "elapsed_s": round(elapsed, 3),
        }

    saved_depth, saved_adaptive = engine.spec_depth, engine.spec_adaptive
    section: dict = {}
    try:
        for name, prompts in populations.items():
            section[name] = {
                "off": run_arm(0, False, prompts),
                "fixed": run_arm(engine.spec_gamma, False, prompts),
                "adaptive": run_arm(engine.spec_gamma, True, prompts),
            }
    finally:
        engine.spec_depth = saved_depth
        engine.spec_adaptive = saved_adaptive
    accept, hostile = section["accept"], section["hostile"]
    section.update({
        # the benchdiff-gated scalars (utils/bench_diff.py METRICS): the
        # production mode is adaptive, so its numbers are the headline.
        # tokens_per_dispatch/gamma_p50 come from the regime speculation
        # exists for; fallback_rounds + the TPOT ratio from the regime
        # the controller exists for
        "gamma_p50": accept["adaptive"]["gamma_p50"],
        "tokens_per_dispatch": accept["adaptive"]["tokens_per_dispatch"],
        "fallback_rounds": hostile["adaptive"]["fallback_rounds"],
        # >= ~1 means the controller kept the hostile traffic free:
        # adaptive TPOT tail no worse than never speculating at all
        "adaptive_vs_off_tpot_p95": round(
            hostile["off"]["tpot_p95"]
            / max(hostile["adaptive"]["tpot_p95"], 1e-9),
            3,
        ),
    })
    return section


def _fleet_n_pages(spec: dict) -> int:
    """KV page pool for fleet-config engines: low-slot fleets keep
    multi-slot slack so prefix warmth and queued claims don't fight over
    one slot's pool — ONE formula for the primary and every scale-out
    replica, or their A/B would silently diverge."""
    pages_per_slot = (spec["max_len"] + 15) // 16
    return 1 + max(4, spec["slots"]) * pages_per_slot


def _measure_failover(engine, spec: dict, make_engine) -> dict:
    """In-flight failover A/B (docs/failover.md): greedy reference streams
    first, then the same streams killed mid-decode by an injected
    scheduler crash on their replica and checkpoint-resumed on a second
    one (weights shared — one set in HBM). Emits the `failover` section:
    client-observed takeover latency p50/p95, generated-prefix tokens
    replayed by the reactive re-prefill, and the exactness verdict
    (resumed output == fault-free reference, byte for byte)."""
    import queue as _queue
    import threading as _threading
    import time as _time

    from modal_examples_tpu.faults.inject import FaultPlan, active
    from modal_examples_tpu.observability import catalog as C
    from modal_examples_tpu.scheduling import (
        EngineReplica,
        PrefixAffinityRouter,
    )
    from modal_examples_tpu.serving import SamplingParams
    from modal_examples_tpu.utils.prometheus import default_registry

    eng_a = make_engine(params=engine.params)
    eng_b = make_engine(params=engine.params)
    rep_a = EngineReplica(eng_a, "fo-a", role="unified")
    rep_b = EngineReplica(eng_b, "fo-b", role="unified")
    router = PrefixAffinityRouter([rep_a, rep_b], reprobe_s=0.2)
    sp = SamplingParams(max_tokens=2 * spec["max_tokens"], temperature=0.0)
    prompts = [
        f"the quick brown fox jumps over the lazy dog variant {i}"
        for i in range(min(4, spec["slots"]))
    ]
    replayed0 = default_registry.total(C.FAILOVER_TOKENS_REPLAYED_TOTAL)
    failovers0 = default_registry.total(C.FAILOVER_TOTAL)
    try:
        eng_a.start()  # the victim; B boots lazily at takeover
        reference = {p: eng_a.generate(p, sp) for p in prompts}
        reqs, outs, threads = [], {}, []
        for p in prompts:
            req = rep_a.submit(p, sp)
            req._router_replica = rep_a
            reqs.append(req)
            outs[req.request_id] = pieces = []
            t = _threading.Thread(
                target=lambda r=req, buf=pieces: buf.extend(router.stream(r))
            )
            t.start()
            threads.append(t)
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline and not all(
            len(r.generated_tokens) >= 3 for r in reqs
        ):
            _time.sleep(0.002)
        # freeze the victim's scheduler (a blocking control command) so
        # the streams stay mid-decode, arm the crash, then release: the
        # next tick dies with every stream live — the kill is
        # deterministic, not a race against tiny-model decode speed
        freeze = _threading.Event()
        eng_a._ctrl.append((freeze.wait, _queue.Queue()))
        plan = FaultPlan({"engine.scheduler_crash": {"on_hit": 1}})
        with active(plan):
            freeze.set()
            deadline = _time.monotonic() + 60
            while not plan.fired() and _time.monotonic() < deadline:
                _time.sleep(0.002)
        for t in threads:
            t.join(timeout=300)
        identical = all(
            not t.is_alive() for t in threads
        ) and all(
            r.finish_reason in ("stop", "length")
            and "".join(outs[r.request_id]) == reference[r.prompt]
            for r in reqs
        )
        takeover = default_registry.histogram_quantiles(
            C.FAILOVER_TAKEOVER_SECONDS
        ) or {}
        return {
            "streams": len(reqs),
            "failovers": int(
                default_registry.total(C.FAILOVER_TOTAL) - failovers0
            ),
            "takeover_latency": {
                k: round(takeover[k], 6) if isinstance(takeover[k], float)
                else takeover[k]
                for k in ("p50", "p95", "count")
                if k in takeover
            },
            "tokens_replayed": int(
                default_registry.total(C.FAILOVER_TOKENS_REPLAYED_TOTAL)
                - replayed0
            ),
            "resumed_identical": bool(identical),
        }
    finally:
        eng_a.stop()
        eng_b.stop()


def _pct(values: list, q: float) -> float:
    """The repo-wide nearest-rank percentile (utils/stats.py) — one rank
    convention across every BENCH section benchdiff compares."""
    from modal_examples_tpu.utils.stats import percentile_nearest_rank

    return percentile_nearest_rank(values, q)


def _measure_recovery(engine, spec: dict, make_engine) -> dict:
    """Gray-failure recovery A/B (docs/health.md): greedy reference streams
    first, then the same streams with their replica's scheduler SILENTLY
    frozen mid-decode — no crash, no error, ``healthy()`` still true. The
    progress watchdog must classify the wedge from stale watermarks,
    error-stop the replica, and the reactive failover must resume every
    stream token-identically on the standby. Emits the `recovery` section:
    time_to_detect (freeze fired -> watchdog stop ladder action) and
    time_to_mitigate (freeze fired -> every stream resumed on the peer)
    p50/p95 over the episodes, the goodput dip the episode cost, and the
    exactness verdict."""
    import threading as _threading
    import time as _time

    from modal_examples_tpu.faults.inject import FaultPlan, active
    from modal_examples_tpu.observability import catalog as C
    from modal_examples_tpu.scheduling import (
        EngineReplica,
        PrefixAffinityRouter,
    )
    from modal_examples_tpu.serving import SamplingParams
    from modal_examples_tpu.serving.health import (
        FleetWatchdog,
        WatchdogPolicy,
    )
    from modal_examples_tpu.utils.prometheus import default_registry

    eng_a = make_engine(params=engine.params)
    eng_b = make_engine(params=engine.params)
    rep_a = EngineReplica(eng_a, "rec-a", role="unified")
    rep_b = EngineReplica(eng_b, "rec-b", role="unified")
    router = PrefixAffinityRouter([rep_a, rep_b], reprobe_s=0.2)
    sp = SamplingParams(max_tokens=2 * spec["max_tokens"], temperature=0.0)
    prompts = [
        f"the quick brown fox jumps over the lazy dog variant {i}"
        for i in range(min(4, spec["slots"]))
    ]
    episodes = int(spec.get("recovery_episodes", 3))
    detect_s: list[float] = []
    mitigate_s: list[float] = []
    episode_walls: list[float] = []
    wedged = 0
    identical = True
    watchdog = None
    try:
        eng_a.start()
        reference = {p: eng_a.generate(p, sp) for p in prompts}

        def _stream_episode(replica) -> float:
            """Run the episode's streams concurrently (the same shape the
            fault episodes use) and return the wall time — the fault-free
            arm of the goodput dip must batch exactly like the faulted
            arm, or the dip compares sequential against concurrent."""
            t0 = _time.monotonic()
            ths = []
            for p in prompts:
                r = replica.submit(p, sp)
                r._router_replica = replica
                th = _threading.Thread(
                    target=lambda rr=r: list(router.stream(rr))
                )
                th.start()
                ths.append(th)
            for th in ths:
                th.join(timeout=300)
            return _time.monotonic() - t0

        wall_ref = _stream_episode(rep_a)
        # warm the STANDBY too: it compiles its own jits (separate engine,
        # separate caches), and its first-ever trace happens at TAKEOVER —
        # under the watchdog, that compile stall reads as a wedge of the
        # engine the failover is recovering onto, and the error-stop
        # poisons it (the watchdog-vs-compile rule, docs/health.md,
        # applied to both replicas)
        eng_b.generate(prompts[0], sp)
        eng_b.stop()
        # the watchdog starts AFTER the warm reference runs: first-compile
        # stalls must never read as a wedge
        watchdog = FleetWatchdog(
            router,
            policy=WatchdogPolicy(
                degraded_after_s=0.75, wedged_after_s=1.5,
                quarantine_after=10_000,  # the bench measures stop/revive
            ),
            poll_s=0.05,
        ).start()
        victim, standby = rep_a, rep_b
        for _ep in range(episodes):
            t_ep = _time.monotonic()
            reqs, outs, threads = [], {}, []
            for p in prompts:
                req = victim.submit(p, sp)
                req._router_replica = victim
                reqs.append(req)
                outs[req.request_id] = pieces = []
                t = _threading.Thread(
                    target=lambda r=req, buf=pieces: buf.extend(
                        router.stream(r)
                    )
                )
                t.start()
                threads.append(t)
            deadline = _time.monotonic() + 120
            while _time.monotonic() < deadline and not all(
                len(r.generated_tokens) >= 3 for r in reqs
            ):
                _time.sleep(0.002)
            # the standby's loop must be quiet while the freeze arms (the
            # fault plan counts hits process-globally); the resumed
            # streams lazily restart it at takeover
            if standby.engine._running:
                standby.engine.stop()
            stops0 = len([
                e for e in watchdog.events if e["action"] == "stop_revive"
            ])
            failovers0 = default_registry.value(
                C.FAILOVER_TOTAL, labels={"mode": "reactive", "result": "ok"}
            ) or 0.0
            plan = FaultPlan(
                {"engine.scheduler_freeze": {"p": 1.0, "max_fires": 1}}
            )
            t_detect = t_mitigate = None
            with active(plan):
                arm_deadline = _time.monotonic() + 30
                while not plan.fired() and _time.monotonic() < arm_deadline:
                    _time.sleep(0.002)
                if not plan.fired():
                    # the victim's loop never hit the point within the
                    # bound (not running?): fall through WITHOUT waiting
                    # forever — the join + per-request identity check
                    # below stay honest, and the episode contributes no
                    # detect/mitigate sample (zero samples fail the
                    # contract loudly)
                    print(
                        f"recovery episode {_ep}: freeze never fired; "
                        f"victim={victim.name} "
                        f"running={victim.engine._running} "
                        f"poisoned={victim.engine._stopped_on_error} "
                        f"tokens={[len(r.generated_tokens) for r in reqs]}",
                        file=sys.stderr,
                    )
                else:
                    t_fire = _time.monotonic()
                    deadline = t_fire + 60
                    while _time.monotonic() < deadline:
                        if t_detect is None and len([
                            e for e in watchdog.events
                            if e["action"] == "stop_revive"
                        ]) > stops0:
                            t_detect = _time.monotonic() - t_fire
                        resumed = (
                            default_registry.value(
                                C.FAILOVER_TOTAL,
                                labels={"mode": "reactive", "result": "ok"},
                            ) or 0.0
                        ) - failovers0
                        if t_detect is not None and resumed >= len(reqs):
                            t_mitigate = _time.monotonic() - t_fire
                            break
                        _time.sleep(0.002)
            for t in threads:
                t.join(timeout=300)
            wedged += sum(1 for t in threads if t.is_alive())
            for r in reqs:
                got = "".join(outs[r.request_id])
                ok = (
                    r.finish_reason in ("stop", "length")
                    and got == reference[r.prompt]
                )
                identical = identical and ok
                if not ok:
                    # forensics on stderr (stdout stays the ONE json line)
                    print(
                        f"recovery episode {_ep}: {r.request_id} "
                        f"finish={r.finish_reason} "
                        f"out={got[-60:]!r} ref={reference[r.prompt][-60:]!r}",
                        file=sys.stderr,
                    )
            if t_detect is not None:
                detect_s.append(t_detect)
            if t_mitigate is not None:
                mitigate_s.append(t_mitigate)
            episode_walls.append(_time.monotonic() - t_ep)
            # revive the frozen victim for the next episode (the router's
            # probe path, driven directly) and swap roles: the streams now
            # live on the standby
            victim.probe()
            victim, standby = standby, victim
        wall_fault = sum(episode_walls) / max(1, len(episode_walls))
        return {
            "episodes": episodes,
            "streams": len(prompts),
            "time_to_detect": {
                "p50": round(_pct(detect_s, 0.5), 6),
                "p95": round(_pct(detect_s, 0.95), 6),
            },
            "time_to_mitigate": {
                "p50": round(_pct(mitigate_s, 0.5), 6),
                "p95": round(_pct(mitigate_s, 0.95), 6),
            },
            # fraction of fault-free throughput the episode cost: the same
            # streams took wall_fault instead of wall_ref
            "goodput_dip": round(
                max(0.0, 1.0 - wall_ref / wall_fault) if wall_fault else 0.0,
                6,
            ),
            "wedged": int(wedged),
            "resumed_identical": bool(identical),
        }
    finally:
        if watchdog is not None:
            watchdog.stop()
        eng_a.stop()
        eng_b.stop()


def _measure_fleet(engine, spec: dict, make_engine) -> dict:
    """Closed-loop fleet A/B (docs/fleet.md): front the warm engine with a
    router + OpenAI server, calibrate single-replica capacity with an
    overload burst, then run the same saturating open-loop sweep twice —
    pinned to one replica, and with the FleetAutoscaler growing decode
    replicas via snapshot-restored warm boots. The A/B at the pinned arm's
    knee is where closing the loop must pay: higher goodput, lower
    client-observed p99 TPOT, scale events journaled. Ends with an idle
    tail so the scale-back-in path is exercised too."""
    import time as _time

    from modal_examples_tpu.fleet import FleetAutoscaler, SnapshotWarmFactory
    from modal_examples_tpu.fleet.loadgen import (
        LoadGenerator,
        RequestClass,
        ab_index,
        fleet_section,
    )
    from modal_examples_tpu.scheduling import EngineReplica, PrefixAffinityRouter
    from modal_examples_tpu.serving.openai_api import OpenAIServer

    router = PrefixAffinityRouter(
        [EngineReplica(engine, "decode-0", role="unified")]
    )
    server = OpenAIServer(router=router, host="127.0.0.1", port=0).start()
    # the default class mix sized to this config's context budget (byte
    # tokenizer: prompts are CHARACTERS; prompt + max_tokens must fit
    # max_len or the engine clips the completion to nothing)
    classes = (
        RequestClass("interactive", "interactive", 0.5, (1, 2), 16, 2.0, 0.5),
        RequestClass("streaming", "default", 0.3, (1, 3), 32, 4.0, 0.5),
        RequestClass("batch", "batch", 0.2, (2, 4), 24, 30.0, 2.0,
                     stream=False),
    )

    def build(name, role, params=None):
        from modal_examples_tpu.serving import SamplingParams

        eng = make_engine(params=params)
        # compile-cache hits (the primary compiled the same shapes): the
        # replica joins the fleet jitted, not paying first-request
        # compiles. warmup() skips the chunk-offset jits long prompts hit,
        # so serve one short and one chunking prompt before placement too.
        eng.warmup()
        eng.start()
        for warm_prompt in ("warm " * 8, "boot warm long prompt " * 12):
            eng.generate(warm_prompt, SamplingParams(max_tokens=4))
        return EngineReplica(eng, name, role=role)

    factory = SnapshotWarmFactory(
        build, snapshot_key=f"fleet-bench-{os.getpid()}"
    )
    factory.prime(engine)  # scale-outs restore, never re-init
    lg = LoadGenerator(
        f"http://127.0.0.1:{server.port}", classes=classes, seed=0,
        request_timeout_s=90.0,
    )
    step_s = float(spec.get("fleet_step_s", 3.0))
    autoscaler = FleetAutoscaler(
        router,
        factory,
        max_replicas={"decode": int(spec.get("fleet_max", 3))},
        queue_high=2.0,
        up_ticks=1,
        down_ticks=4,
        cooldown_s=1.0,
        tick_s=0.2,
        slos=(),  # the bench registry carries warmup-phase latencies
    )
    try:
        lg.warm(n_per_class=1)
        # first closed-loop probe is a THROWAWAY: concurrent traffic is
        # what flushes the long tail of (bucket, chunk-offset) jit compiles
        # warm() cannot enumerate; the second probe measures the fleet
        lg.calibrate(duration_s=min(1.5, step_s))
        capacity = lg.calibrate(duration_s=min(2.5, step_s))
        rates = [0.6 * capacity, 1.25 * capacity, 2.5 * capacity]
        pinned = lg.sweep(rates, step_s)
        autoscaler.start()
        autoscaled = lg.sweep(rates, step_s)
        # the ascending ladder only scales out at its saturating step, so
        # re-measure the knee-adjacent rate NOW, fleet still scaled out —
        # the A/B the section headlines (see fleet_section)
        scaled_step = None
        if len(router.replicas) > 1:
            scaled_step = lg.run_step(
                rates[ab_index(pinned)], 1.5 * step_s, label="ab-scaled"
            )
        # idle tail: load is gone — the controller must scale back in
        deadline = _time.monotonic() + 30.0
        while len(router.replicas) > 1 and _time.monotonic() < deadline:
            _time.sleep(0.2)
        scaled_back_to = len(router.replicas)
    finally:
        autoscaler.stop()
        # anything the controller left registered (scale-in not reached
        # inside the tail window) is swept so the child exits clean
        for r in list(router.replicas):
            if r.name != "decode-0":
                try:
                    router.remove_replica(r.name)
                    r.engine.stop()
                except Exception:
                    pass
        factory.store.delete(factory.snapshot_key)  # bench key: no LRU churn
        # NOT server.stop(): that would also stop every replica engine,
        # including the primary the _child epilogue still reads/stops
        server.httpd.shutdown()
        server.httpd.server_close()
    section = fleet_section(
        pinned,
        autoscaled,
        scale_events=autoscaler.events,
        capacity_rps=capacity,
        scaled_step=scaled_step,
    )
    section["scaled_back_to"] = scaled_back_to
    return section


def _measure_shared_prefix(engine, spec: dict, make_engine) -> dict:
    """Shared prefix-store A/B (docs/prefix_store.md): the same
    two-replica fleet serving the same shared-prefix tenant traffic,
    once with per-replica PRIVATE volume tiers (the pre-store world:
    every replica recomputes or respills its own copy) and once with the
    fleet-wide SHARED store. Replicas A and B both serve and spill the
    corpus — between them every chain's rendezvous owner spills (the
    non-owner's puts defer), and the overlap is the dedup measurement:
    shared-arm puts dedup/defer down to ONE fleet-wide copy (ratio >
    1.0) while private-arm replicas each write their own. Then a COLD
    third replica (the scale-out case) serves the corpus — in the
    shared arm it promotes the fleet's spills (all peer hits), in the
    private arm its root is empty and every prefill recomputes. The
    cold replica's shared-arm TTFT p95 is the benchdiff-gated scalar
    (``fleet.shared_prefix_ttft_p95``)."""
    import time as _time

    from modal_examples_tpu.serving import SamplingParams
    from modal_examples_tpu.storage.volume import Volume

    # one multi-page shared prefix (byte tokenizer: characters ARE
    # tokens), fanned into per-tenant requests — the workload the
    # cross-replica store exists for
    prefix = (
        "shared system prompt: you are the fleet's serving benchmark; "
        "answer tersely and deterministically. " * 3
    )
    prompts = [f"{prefix}tenant request {i}" for i in range(4)]

    def _spill(eng) -> None:
        # evict the trie into the host tier, then demote every host
        # block — organic LRU overflow, forced so the A/B is
        # deterministic at bench scale (chaos uses the same lever)
        t = eng.tiered
        eng.prefix_cache.evict(10_000)
        with t._lock:
            items = list(t._host.items())
        for h, data in items:
            t._demote_to_volume(h, data)
            with t._lock:
                t._host.pop(h, None)
                t._host_used -= len(data)

    def _arm(shared: bool) -> dict:
        with Volume.ephemeral() as vol:
            def _mk(name: str):
                tp = {
                    "host_bytes": 1 << 20, "volume": vol,
                    "shared": shared, "replica": name,
                }
                if not shared:
                    # the pre-store world: one private root per replica
                    tp["volume_prefix"] = f"kv-tier-{name}"
                eng = make_engine(params=engine.params, tiered_prefix=tp)
                eng.warmup()
                eng.start()
                # jit the short-prompt path outside the measurement
                eng.generate("warm " * 8, SamplingParams(max_tokens=2))
                return eng

            engines = []
            try:
                eng_a = _mk("rep-a")
                eng_b = _mk("rep-b")
                engines += [eng_a, eng_b]
                for eng in (eng_a, eng_b):
                    for p in prompts:
                        eng.generate(p, SamplingParams(max_tokens=4))
                # both replicas spill: every chain's rendezvous owner is
                # one of the two, so one fleet-wide copy of every block
                # lands (the non-owner's puts defer/dedup against it)
                _spill(eng_a)
                _spill(eng_b)
                # the scale-out case: a COLD replica serves the corpus
                eng_c = _mk("rep-c")
                engines.append(eng_c)
                ttfts = []
                for p in prompts:
                    t0 = _time.perf_counter()
                    eng_c.generate(p, SamplingParams(max_tokens=1))
                    ttfts.append(_time.perf_counter() - t0)
                stats = [e.tiered.store.stats() for e in engines]
                puts = sum(s["puts"] for s in stats)
                writes = sum(s["writes"] for s in stats)
                c_s = stats[-1]
                return {
                    "ttft_p50": _pct(ttfts, 50),
                    "ttft_p95": _pct(ttfts, 95),
                    "cold_volume_hits": eng_c.tiered.tier_hits["volume"],
                    "peer_hits": c_s["hits"].get("peer", 0),
                    "puts": puts,
                    "writes": writes,
                    "dedup_ratio": round(puts / max(1, writes), 4),
                    "store_bytes": max(s["bytes"] for s in stats),
                }
            finally:
                for eng in engines:
                    eng.stop()

    private = _arm(shared=False)
    shared = _arm(shared=True)
    return {
        "private": private,
        "shared": shared,
        "ttft_p95_vs_private": round(
            shared["ttft_p95"] / max(private["ttft_p95"], 1e-9), 4
        ),
    }


def _child(model: str) -> None:
    spec = CONFIGS[model]
    # measured runs keep the distributed request tracer sampled OUT
    # (observability/reqtrace.py): the headline tok/s must not pay
    # per-request span file writes. Override with MTPU_TRACE_SAMPLE=1 to
    # bench-with-tracing deliberately; `tpurun benchdiff` then shows what
    # the instrumentation costs.
    os.environ.setdefault("MTPU_TRACE_SAMPLE", "0")
    # bench configs OPT IN to the hot-path profiler (the one explicit env,
    # resolved once in LLMEngine.__init__ — docs/observability.md): every
    # BENCH json carries an `overhead` section (host fraction, per-phase
    # tick p50/p95, compile totals), and the compile ledger captures every
    # program build. MTPU_PROFILE=0 in the environment still wins, so the
    # instrumentation cost itself stays A/B-able via `tpurun benchdiff`.
    os.environ.setdefault("MTPU_PROFILE", "1")
    # ... and to the flight recorder (docs/observability.md#metrics-history):
    # the engine starts the tsdb sampler once, so every bench run leaves a
    # replayable metrics history under <state_dir>/tsdb/ and the `overhead`
    # section gains the sampler's own cost — benchdiff's existing
    # overhead.host_fraction / overhead.tick_p95 gates are the proof the
    # recorder costs nothing measurable on the hot path. MTPU_TSDB=0 in the
    # environment still wins (the sampler-off A/B arm).
    os.environ.setdefault("MTPU_TSDB", "1")
    if spec.get("fleet"):
        # production admission shape for the open-loop sweep: bounded
        # queues turn sustained overload into honest 429s (the shed-rate
        # axis of the fleet section) instead of minutes-deep queue waits.
        # Must land before the engine builds its AdmissionController.
        os.environ.setdefault("MTPU_SCHED_MAX_QUEUE", str(4 * spec["slots"]))
    if spec.get("tp", 1) > 1 and os.environ.get("BENCH_CPU"):
        # CPU TP path-proof needs virtual devices BEFORE jax imports
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from modal_examples_tpu.models import llama
    from modal_examples_tpu.models.quantize import param_bytes
    from modal_examples_tpu.serving import LLMEngine, SamplingParams
    if model.startswith("llama2-7b"):
        cfg = llama.LlamaConfig.llama2_7b()
    elif model.startswith("llama3.1-8b"):
        cfg = llama.LlamaConfig.llama31_8b()
    elif model == "llama-1b":
        cfg = llama.LlamaConfig(
            vocab_size=32000, dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
            ffn_dim=5632, max_seq_len=2048,
        )
    else:
        cfg = llama.LlamaConfig.tiny()

    # tensor parallelism (round 7): a "tensor"-axis mesh shards weights,
    # cache, and — via ops.sharded's shard_map dispatch — the Pallas
    # kernels over the kv-head axis; the SAME engine flags otherwise
    mesh = None
    if spec.get("tp", 1) > 1:
        from modal_examples_tpu.parallel import make_mesh

        mesh = make_mesh(
            {"tensor": spec["tp"]}, devices=jax.devices()[: spec["tp"]]
        )

    # speculative decoding configs (ROADMAP open item #4): "ngram" =
    # prompt-lookup (no draft weights); "draft-1b" = a 1B-shape draft with
    # the target's 32000 vocab, random weights (mechanism-cost floor —
    # the engine random-inits the draft when no draft_params are given)
    speculative = None
    if spec.get("spec"):
        mode, gamma = spec["spec"]
        if mode == "ngram":
            speculative = ("ngram", gamma)
        else:
            draft_cfg = llama.LlamaConfig(
                vocab_size=cfg.vocab_size, dim=2048, n_layers=16,
                n_heads=16, n_kv_heads=8, ffn_dim=5632, max_seq_len=2048,
            )
            speculative = (draft_cfg, gamma)

    t0 = time.time()
    engine = LLMEngine(
        cfg,
        max_slots=spec["slots"],
        max_model_len=spec["max_len"],
        page_size=16,
        # fleet configs may run 1 slot/replica (see tiny-fleet): keep
        # multi-slot page slack so prefix warmth survives next to claims
        n_pages=_fleet_n_pages(spec) if spec.get("fleet") else None,
        prefill_buckets=(64, 128, 256),
        # "int8" = quantized paged KV (half the decode KV HBM traffic and
        # residency, docs/kv_cache.md); default bf16
        kv_dtype=spec.get("kv_dtype", jnp.bfloat16),
        quantization=spec.get("quant"),
        # the v3 ragged kernel + pallas scatter decode structure (round 4);
        # models whose shapes don't fit the kernel fall back to XLA inside
        # decode_step — under mesh= the kernels run per head shard
        paged_impl="pallas",
        mesh=mesh,
        speculative=speculative,
        # stall-free admission (docs/scheduling.md): mixed configs run the
        # measured traffic budgeted; 0 keeps the classic unlimited admit
        max_prefill_tokens_per_tick=spec.get("budget", 0),
        # macro-step decode (docs/multistep.md): multistep configs run the
        # measured traffic at the config's N; None resolves the env knob
        decode_steps=spec.get("multistep"),
        decode_block=spec.get("decode_block", 8),
    )
    build_s = time.time() - t0
    weight_bytes = param_bytes(engine.params)

    # disaggregated two-replica mode (docs/disagg.md): `engine` becomes the
    # DECODE replica; a second engine sharing the same (read-only) weight
    # buffers runs prefill only and ships finished KV pages over the chunked
    # wire. Traffic then flows through the coordinator, so the measured
    # tok/s includes prefill, migration, adoption, and decode.
    coord = None
    if spec.get("disagg"):
        from modal_examples_tpu.scheduling import EngineReplica
        from modal_examples_tpu.serving.disagg import DisaggCoordinator

        prefill_engine = LLMEngine(
            cfg,
            params=engine.params,  # alias, not a copy: one weight set in HBM
            max_slots=min(4, spec["slots"]),  # transient, serialized claims
            max_model_len=spec["max_len"],
            page_size=16,
            prefill_buckets=(64, 128, 256),
            kv_dtype=spec.get("kv_dtype", jnp.bfloat16),
            paged_impl="xla",  # never decodes; skip kernel-probe surface
            tiered_prefix=True,  # host-RAM spill tier under the trie
        )
        coord = DisaggCoordinator(
            [
                EngineReplica(prefill_engine, "prefill-0", role="prefill"),
                EngineReplica(engine, "decode-0", role="decode"),
            ]
        )

    def _submit(prompt_s, sampling):
        if coord is not None:
            return coord.submit(prompt_s, sampling)
        return engine.submit(prompt_s, sampling)

    def _stream(req):
        return coord.stream(req) if coord is not None else engine.stream(req)

    prompt = (
        "The quick brown fox jumps over the lazy dog. "
        * spec.get("prompt_mult", 2)
    )
    max_tokens = spec["max_tokens"]
    if os.environ.get("BENCH_WARM"):
        max_tokens = 16  # warm rerun only measures boot, not throughput
    params = SamplingParams(max_tokens=max_tokens, temperature=1.0)

    # boot-time compiles, then a live warmup round through the scheduler
    t0 = time.time()
    engine.warmup()
    engine.start()
    warm = [_submit(prompt, SamplingParams(max_tokens=8, temperature=1.0))
            for _ in range(2)]
    for r in warm:
        "".join(_stream(r))
    compile_s = time.time() - t0

    # timed: saturate all slots
    n_reqs = spec["slots"] * 2
    base_tokens = engine.stats.generated_tokens
    t0 = time.time()
    reqs = [_submit(prompt, params) for _ in range(n_reqs)]
    for r in reqs:
        for _ in _stream(r):
            pass
    elapsed = time.time() - t0
    generated = engine.stats.generated_tokens - base_tokens

    # per-phase latency distributions (p50/p95/p99) from the engine's
    # observability histograms — phase-attributed perf trajectory in every
    # BENCH_*.json from here on (docs/observability.md). Snapshotted NOW,
    # before the interference A/B below: its unbudgeted arm generates
    # deliberately-degraded traffic that must not pollute the headline
    # token_latency/scheduling sections benchdiff gates on.
    from modal_examples_tpu.observability import catalog as C
    from modal_examples_tpu.utils.prometheus import default_registry

    def _q(name, labels=None):
        q = default_registry.histogram_quantiles(name, labels=labels)
        if q is None:
            return None
        return {
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in q.items()
        }

    phase_latency = {}
    for phase in ("prefill", "prefill_chunked", "decode_wait"):
        q = _q(C.ENGINE_PHASE_SECONDS, {"phase": phase})
        if q:
            phase_latency[phase] = q
    for key, name in (
        ("queue_wait", C.ENGINE_QUEUE_WAIT_SECONDS),
        ("batch_size", C.ENGINE_BATCH_SIZE),
    ):
        q = _q(name)
        if q:
            phase_latency[key] = q
    # token-level serving latency (the vLLM-vs-TGI comparison axes): TTFT =
    # submit -> first token, TPOT = inter-token gap, from the engine's
    # per-request histograms — alongside aggregate tokens/s
    token_latency = {}
    for key, name in (("ttft", C.TTFT_SECONDS), ("tpot", C.TPOT_SECONDS)):
        q = _q(name)
        if q:
            token_latency[key] = {
                k: q[k] for k in ("p50", "p95", "count") if k in q
            }
    # scheduling telemetry (ISSUE-4): per-class admission queue-wait
    # distributions + the shed rate — the control layer's own trajectory
    # rides in every BENCH json alongside the kernel numbers
    sched_wait = {}
    for klass in ("interactive", "default", "batch"):
        q = _q(C.SCHED_QUEUE_WAIT_SECONDS, {"class": klass})
        if q:
            sched_wait[klass] = {
                k: q[k] for k in ("p50", "p95", "count") if k in q
            }
    sheds = default_registry.total(C.SHEDS_TOTAL)
    admitted = default_registry.total(C.REQUESTS_ADMITTED_TOTAL)
    offered = sheds + admitted
    scheduling = {
        "queue_wait": sched_wait,
        "shed_rate": round(sheds / offered, 6) if offered else 0.0,
        "sheds_total": int(sheds),
        "admitted_total": int(admitted),
    }

    # hot-path overhead attribution (docs/observability.md#hot-path-
    # profiling): host-vs-device fraction, per-phase tick p50/p95, detok
    # share, and compile totals from the engine's profiler ring —
    # snapshotted HERE, with the other latency sections and before the
    # interference/fleet/failover A/Bs, so the headline attribution
    # reflects the measured traffic rather than the deliberately-degraded
    # A/B arms. Children run MTPU_PROFILE=1 by default, so every config's
    # json carries the section; benchdiff gates overhead.host_fraction and
    # overhead.tick_p95 round over round.
    overhead = None
    if engine.profiler is not None:
        overhead = engine.profiler.overhead_summary()
        # flight-recorder ride-along (docs/observability.md#metrics-history):
        # the tsdb sampler's own telemetry lands NEXT TO the host-overhead
        # numbers it must not move — samples taken, scrape-cost p95, and
        # the series count, read from the same registry it scraped
        from modal_examples_tpu.observability import catalog as _cat
        from modal_examples_tpu.observability import timeseries as _tsm
        from modal_examples_tpu.utils.prometheus import (
            default_registry as _dreg,
        )

        if _tsm.global_sampler() is not None:
            scrape_q = _dreg.histogram_quantiles(
                _cat.TSDB_SCRAPE_SECONDS, quantiles=(0.5, 0.95), aggregate={}
            )
            overhead["tsdb"] = {
                "samples": int(_dreg.value(_cat.TSDB_SAMPLES_TOTAL)),
                "series": int(_dreg.value(_cat.TSDB_SERIES)),
                "scrape_p50": scrape_q["p50"] if scrape_q else None,
                "scrape_p95": scrape_q["p95"] if scrape_q else None,
            }

    # stall-free admission interference A/B (mixed configs): measured on
    # the same warm engine BEFORE it stops — budget on vs off TPOT for an
    # interactive stream under long-prompt arrivals (docs/scheduling.md)
    interference = None
    if spec.get("mixed"):
        interference = _measure_interference(engine, spec)

    # macro-step decode A/B (multistep configs, docs/multistep.md): N=1 vs
    # N=config on the same warm engine via the runtime-mutable knob —
    # host_fraction and per-token tick_p95 must favor the macro-step arm
    multistep_info = None
    if spec.get("multistep"):
        multistep_info = _measure_multistep(engine, spec)

    # fused adaptive speculation A/B (spec_ab configs,
    # docs/speculative.md#gamma-schedule): spec-off vs fixed-γ vs the
    # acceptance-driven controller on the same warm engine via the
    # runtime-mutable knobs — merged into the `spec` json section below
    spec_ab_info = None
    if spec.get("spec") and spec.get("spec_ab"):
        spec_ab_info = _measure_spec_adaptive(engine, spec)

    # correctness canary (docs/observability.md#correctness-canary): a
    # record-then-compare golden-set round on the same warm engine, BEFORE
    # the fleet/failover/recovery arms stop it — drift_count must be 0 on
    # a healthy build, and an identity-mismatched golden refuses loudly
    canary_info = _measure_canary(engine)

    # closed-loop fleet A/B (fleet configs, docs/fleet.md): saturating
    # open-loop sweep against an OpenAI front, pinned vs autoscaled —
    # scale-out replicas are built by this factory with snapshot-restored
    # params (quantization=None then: the restored tree is already
    # quantized; re-quantizing it would corrupt the weights)
    fleet_info = None
    if spec.get("fleet"):
        def _mk_fleet_engine(params=None, tiered_prefix=None):
            return LLMEngine(
                cfg,
                params=params,
                max_slots=spec["slots"],
                max_model_len=spec["max_len"],
                page_size=16,
                n_pages=_fleet_n_pages(spec),
                prefill_buckets=(64, 128, 256),
                kv_dtype=spec.get("kv_dtype", jnp.bfloat16),
                quantization=spec.get("quant") if params is None else None,
                paged_impl="pallas",
                mesh=mesh,
                max_prefill_tokens_per_tick=spec.get("budget", 0),
                tiered_prefix=tiered_prefix,
            )

        fleet_info = _measure_fleet(engine, spec, _mk_fleet_engine)
        # shared prefix-store A/B (docs/prefix_store.md): private vs
        # fleet-wide volume tiers on a two-replica fleet; the shared
        # arm's cold-replica TTFT is the benchdiff-gated scalar
        sp = _measure_shared_prefix(engine, spec, _mk_fleet_engine)
        fleet_info["shared_prefix"] = sp
        fleet_info["shared_prefix_ttft_p95"] = sp["shared"]["ttft_p95"]

    # in-flight failover A/B (failover configs, docs/failover.md): streams
    # killed mid-decode on one replica, checkpoint-resumed on another —
    # weights aliased (params=engine.params, already quantized) so HBM
    # holds one weight set plus the two caches
    failover_info = None
    if spec.get("failover"):
        # the measured engine's loop must be quiet first: the injected
        # scheduler crash counts hits process-globally, and the victim
        # replica's loop must be the ONLY one running for the kill to
        # land deterministically (the measured traffic is already done)
        engine.stop()

        def _mk_failover_engine(params=None):
            return LLMEngine(
                cfg,
                params=params,
                max_slots=spec["slots"],
                max_model_len=spec["max_len"],
                page_size=16,
                prefill_buckets=(64, 128, 256),
                kv_dtype=spec.get("kv_dtype", jnp.bfloat16),
                quantization=None if params is not None else spec.get("quant"),
                paged_impl="pallas",
                mesh=mesh,
            )

        failover_info = _measure_failover(engine, spec, _mk_failover_engine)

    # gray-failure recovery A/B (recovery configs, docs/health.md): a
    # replica's scheduler silently frozen with streams mid-decode — the
    # watchdog detects from stale watermarks, the failover resumes; same
    # weight-aliasing rules as the failover A/B
    recovery_info = None
    if spec.get("recovery"):
        # quiet loop first, same reason as the failover A/B: the injected
        # freeze counts hits process-globally and must land on the victim
        engine.stop()

        def _mk_recovery_engine(params=None):
            return LLMEngine(
                cfg,
                params=params,
                max_slots=spec["slots"],
                max_model_len=spec["max_len"],
                page_size=16,
                prefill_buckets=(64, 128, 256),
                kv_dtype=spec.get("kv_dtype", jnp.bfloat16),
                quantization=None if params is not None else spec.get("quant"),
                paged_impl="pallas",
                mesh=mesh,
            )

        recovery_info = _measure_recovery(engine, spec, _mk_recovery_engine)

    errors = engine.error_count
    engine.stop()

    tok_s = generated / elapsed
    # decode is weight-streaming-bound: every step reads the full weight set
    # once for up to `slots` tokens. steps/s * weight_bytes over the HBM
    # ceiling says how close the whole serving stack runs to the hardware.
    stream_gbps = (tok_s / spec["slots"]) * weight_bytes / 1e9

    # roofline position (docs/observability.md#roofline-and-usage-
    # accounting): the engine's usage meter joins its analytic work model
    # (FLOPs + dtype-aware bytes) with the device seconds it accounted —
    # MFU/MBU against the target generation's peaks plus the compute-vs-
    # bandwidth bound classification, gated release-to-release by
    # bench_diff. A pure function of token counts and the engine clock.
    utilization = engine.usage.utilization_section(tokens_per_second=tok_s)

    # KV-cache footprint (dtype-aware: int8 counts int8 payload + f32 scale
    # rows): the residency half of the int8-KV win. max_slots_at_hbm = how
    # many slots of THIS config's context length fit in v5e HBM after the
    # weights — ~2x at kv_dtype="int8", measurable the moment the bytes
    # halve, no chip required.
    cache_occ = engine.cache.occupancy()
    bytes_per_page = cache_occ["bytes_total"] // engine.cache.n_pages
    bytes_per_slot = engine.pages_per_slot * bytes_per_page
    kv_cache_info = {
        "dtype": engine.cache.kv_dtype,
        "bytes": int(cache_occ["bytes_total"]),
        "bytes_per_slot": int(bytes_per_slot),
        "max_slots_at_hbm": int(
            max(0.0, V5E_HBM_BYTES - weight_bytes) // max(bytes_per_slot, 1)
        ),
    }

    # speculative decoding (ROADMAP open item #4): the acceptance-rate ->
    # tok/s story needs both numbers in the same json line
    spec_info = None
    if engine.spec_gamma:
        spec_info = {
            "mode": engine.spec_mode,
            "gamma": engine.spec_gamma,
            "adaptive": bool(engine.spec_adaptive),
            "proposed": int(engine.stats.spec_proposed),
            "accepted": int(engine.stats.spec_accepted),
            "acceptance_rate": round(engine.stats.acceptance_rate(), 4),
            # spec_ab configs: the off/fixed/adaptive A/B arms + the
            # benchdiff-gated scalars (gamma_p50, tokens_per_dispatch,
            # fallback_rounds, adaptive_vs_off_tpot_p95)
            **(spec_ab_info or {}),
        }
    # disaggregated serving (docs/disagg.md): migration volume + latency and
    # the tiered prefix cache's per-tier hit mix, only for disagg configs
    disagg_info = None
    if coord is not None:
        mig = coord.stats()["migrations"]
        mq = _q(C.DISAGG_MIGRATION_SECONDS)
        tier_hits = {
            lbls.get("tier", "?"): int(v)
            for lbls, v in default_registry.series(C.PREFIX_TIER_HITS_TOTAL)
        }
        total_hits = sum(tier_hits.values())
        disagg_info = {
            "pages_migrated": int(mig["pages"]),
            "migration_bytes": int(mig["bytes"]),
            "migrations": {
                k: int(mig[k]) for k in ("ok", "fallback", "aborted")
            },
            "migration_latency": (
                {k: mq[k] for k in ("p50", "p95", "count") if k in mq}
                if mq
                else None
            ),
            "tier_hits": tier_hits,
            "tier_hit_rates": {
                k: round(v / total_hits, 6) for k, v in tier_hits.items()
            }
            if total_hits
            else {},
        }
    # chaos path-proof (docs/faults.md): for chaos configs the seeded
    # episode schedule runs a fresh tiny fleet through every cataloged
    # fault point AFTER the measured traffic (the measured number stays
    # fault-free); the report rides in the json so a failure-handling
    # regression breaks the bench contract, not just the test suite
    faults_info = None
    if spec.get("chaos"):
        from modal_examples_tpu.faults.chaos import run_chaos

        chaos_report = run_chaos(seed=0, strict=False)
        faults_info = {
            "injected": int(chaos_report["injected_total"]),
            "per_point": chaos_report["injected"],
            "recovered": int(chaos_report["recovered"]),
            "wedged": int(chaos_report["wedged"]),
            "points_missed": chaos_report["points_missed"],
            "episodes": len(chaos_report["episodes"]),
            "invariants": (
                "ok" if chaos_report["invariants"] == "ok" else "violated"
            ),
        }
    print(
        json.dumps(
            {
                "metric": f"{model} serving decode throughput (1 chip)",
                "value": round(tok_s, 2),
                "unit": "tok/s",
                "vs_baseline": round(tok_s / A100_LLAMA2_7B_TOK_S, 4),
                "model": model,
                "params": cfg.param_count,
                "weight_gb": round(weight_bytes / 1e9, 2),
                "backend": jax.default_backend(),
                "slots": spec["slots"],
                "generated_tokens": generated,
                "elapsed_s": round(elapsed, 2),
                "engine_build_s": round(build_s, 1),
                "compile_s": round(compile_s, 1),
                "pct_hbm_ceiling": round(stream_gbps / V5E_HBM_GBPS, 4),
                "engine_errors": errors,
                # the RESOLVED decode plan (paged_impl_plan(mesh=...)):
                # benches must report the per-shard variant actually run,
                # incl. the tensor-parallel degree, not the requested impl
                "tp": engine.impl_plan.get("tp", 1),
                "impl_plan": {
                    k: v
                    for k, v in engine.impl_plan.items()
                    if k != "downgraded"
                },
                "phase_latency": phase_latency,
                "token_latency": token_latency,
                "scheduling": scheduling,
                "kv_cache": kv_cache_info,
                "utilization": utilization,
                **({"overhead": overhead} if overhead else {}),
                "tokens_per_second": round(tok_s, 2),
                **({"spec": spec_info} if spec_info else {}),
                **({"disagg": disagg_info} if disagg_info else {}),
                **({"faults": faults_info} if faults_info else {}),
                **({"interference": interference} if interference else {}),
                **({"multistep": multistep_info} if multistep_info else {}),
                **({"canary": canary_info} if canary_info else {}),
                **({"fleet": fleet_info} if fleet_info else {}),
                **({"failover": failover_info} if failover_info else {}),
                **({"recovery": recovery_info} if recovery_info else {}),
            }
        )
    )


def _kill_stray_children() -> None:
    """Kill leftover bench/claim children from a previous wedged run.

    Round-1 postmortem (NOTES.md): a crash-looping child holding the chip's
    claim handshake wedged every later device attach. Sweep any prior
    `bench.py --child` / preflight processes before we touch the device.
    """
    me = os.getpid()
    try:
        out = subprocess.run(
            ["pgrep", "-f", "bench.py --child|_bench_preflight"],
            capture_output=True, text=True, timeout=10,
        ).stdout
    except Exception:
        return
    for pid_s in out.split():
        try:
            pid = int(pid_s)
            if pid in (me, os.getppid()):
                continue
            # only reap ORPHANS (reparented to init): a live bench's children
            # have their live supervisor as parent and must not be touched
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().rsplit(")", 1)[1].split()[1])
            if ppid == 1:
                os.kill(pid, 9)
        except (ValueError, OSError, IndexError):
            pass


def _preflight(timeout_s: int = 120) -> str:
    """Cheap device-attach probe in a subprocess; returns backend or ''.

    A wedged chip blocks *inside* device attach, so the probe must be a
    separate killable process (the round-1 failure burned every config's
    full timeout on exactly this block).
    """
    code = (
        "import jax; print('_bench_preflight', jax.default_backend(), "
        "len(jax.devices()))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return ""
    for line in proc.stdout.splitlines():
        if line.startswith("_bench_preflight"):
            return line.split()[1]
    return ""


def _extract_json(stdout: str) -> dict | None:
    for line in reversed(stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return None


def _slope_time(run, iters: int) -> float:
    """Per-iteration seconds via the two-point slope (cancels fixed
    dispatch cost), falling back to plain elapsed when tiny/fast runs make
    the slope non-positive on noise. ``run(n)`` executes n iterations and
    host-syncs; shared by every secondary bench child."""
    n1, n2 = max(1, iters // 2), iters
    t1, t2 = run(n1), run(n2)
    if t2 > t1 and n2 > n1:
        return (t2 - t1) / (n2 - n1)
    return t2 / n2


def _image_child() -> None:
    """Secondary metric (BASELINE.json: "SDXL images/sec"): full txt2img
    pipeline — SD3-Medium-shape MMDiT (24 blocks, width 1536, ~2B params,
    bf16) rectified-flow sampling at 4 steps (the reference's Turbo loop,
    stable_diffusion/text_to_image.py) + SD3 VAE decode to 512px — as ONE
    jitted program. Random weights (zero-egress: no checkpoints), which is
    perf-equivalent: the FLOPs/bytes don't depend on the values."""
    import dataclasses as _dc

    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import diffusion, vae
    from modal_examples_tpu.utils.sync import force

    tiny = bool(os.environ.get("BENCH_IMAGE_TINY"))
    if tiny:
        mcfg = diffusion.MMDiTConfig.tiny()
        vcfg = _dc.replace(
            vae.VAEConfig.tiny(), latent_channels=mcfg.channels
        )
        steps, B, iters, S_text = 2, 1, 2, 16
    else:
        mcfg = diffusion.MMDiTConfig.sd3_shape()
        vcfg = _dc.replace(vae.VAEConfig.sd3_shape(), dtype="bfloat16")
        steps, B, iters, S_text = 4, 1, 4, 154  # CLIP-L+G 77+77 joint tokens

    t0 = time.time()
    params = diffusion.mmdit_init(jax.random.PRNGKey(0), mcfg)
    vparams = vae.init_params(jax.random.PRNGKey(1), vcfg)
    force((params, vparams))
    build_s = time.time() - t0
    from modal_examples_tpu.models.quantize import param_bytes

    dt = mcfg.jnp_dtype
    text = jax.random.normal(jax.random.PRNGKey(2), (B, S_text, mcfg.text_dim), dt)
    pooled = jax.random.normal(jax.random.PRNGKey(3), (B, mcfg.pooled_dim), dt)
    null_t = jnp.zeros_like(text)
    null_p = jnp.zeros_like(pooled)

    def pipe(params, vparams, key, text, pooled, null_t, null_p):
        lat = diffusion.mmdit_sample(
            params, key, text, pooled, null_t, null_p, mcfg,
            steps=steps, guidance=4.0,
        )
        return vae.decode(vparams, lat.astype(vcfg.jnp_dtype), vcfg)

    fn = jax.jit(pipe)
    t0 = time.time()
    img = fn(params, vparams, jax.random.PRNGKey(4), text, pooled, null_t, null_p)
    np.asarray(img)  # host fetch: block_until_ready is a no-op on axon
    compile_s = time.time() - t0

    def run(n):
        t0 = time.time()
        img = None
        for i in range(n):
            img = fn(params, vparams, jax.random.PRNGKey(5 + i), text,
                     pooled, null_t, null_p)
        np.asarray(img[0, 0, 0])
        return time.time() - t0

    sec_per_img = _slope_time(run, iters) / B
    img_s = 1.0 / sec_per_img
    out_px = mcfg.img_size * vcfg.downscale
    print(
        json.dumps(
            {
                "metric": (
                    "tiny txt2img path-proof (NOT the SD metric)"
                    if tiny else "sd3-medium-shape txt2img (1 chip)"
                ),
                "value": round(img_s, 3),
                "unit": "img/s",
                # text_to_image.py:11-13: "an image in 1 to 2 seconds" on
                # H100 (SD3.5-Large-Turbo, 1024px) -> ~0.67 img/s midpoint.
                # The tiny path-proof config may never claim the baseline.
                "vs_baseline": 0.0 if tiny else round(img_s / (1 / 1.5), 4),
                "steps": steps,
                "resolution": f"{out_px}x{out_px}",
                "param_gb": round(
                    param_bytes(params) / 1e9 + param_bytes(vparams) / 1e9, 2
                ),
                "sec_per_image": round(sec_per_img, 3),
                "build_s": round(build_s, 1),
                "compile_s": round(compile_s, 1),
                "backend": jax.default_backend(),
            }
        ),
        flush=True,
    )


def _embed_child() -> None:
    """Secondary metric: sentence-embedding throughput (BASELINE config
    "bge-small-en sentence embeddings"; the reference's TEI tier —
    text_embeddings_inference.py, wikipedia/main.py's 575k tok/s fleet
    claim). bge-small geometry = models.bert defaults (384 dim, 12
    layers); random weights are perf-equivalent."""
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import bert
    from modal_examples_tpu.utils.sync import force

    tiny = bool(os.environ.get("BENCH_TINY"))
    cfg = bert.BertConfig.tiny() if tiny else bert.BertConfig()  # bge-small shape
    B, S, iters = (8, 64, 2) if tiny else (256, 512, 8)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    force(params)
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, cfg.vocab_size)
    mask = jnp.ones((B, S), jnp.int32)
    fn = jax.jit(lambda p, t, m: bert.embed(p, t, m, cfg))
    t0 = time.time()
    np.asarray(fn(params, toks, mask))
    compile_s = time.time() - t0

    def run(n):
        out = None
        t0 = time.time()
        for _ in range(n):
            out = fn(params, toks, mask)
        np.asarray(out[0, 0])
        return time.time() - t0

    tok_s = B * S / _slope_time(run, iters)
    print(json.dumps({
        "metric": ("tiny embed path-proof" if tiny
                   else "bge-small-shape embedding throughput (1 chip)"),
        "value": round(tok_s, 0), "unit": "tok/s",
        "vs_baseline": 0.0,  # the reference's 575k tok/s is a fleet number
        "batch": B, "seq": S, "compile_s": round(compile_s, 1),
        "backend": jax.default_backend(),
    }), flush=True)


def _asr_child() -> None:
    """Secondary metric: Whisper transcription speed as x-realtime
    (BASELINE config "Whisper-base audio transcription";
    openai_whisper/batched_whisper.py). whisper-base geometry, 30 s
    chunks, greedy decode of 64 tokens per chunk."""
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import whisper
    from modal_examples_tpu.utils.sync import force

    tiny = bool(os.environ.get("BENCH_TINY"))
    if tiny:
        cfg = whisper.WhisperConfig.test_tiny()
        B, frames, max_toks, iters = 2, 200, 8, 2
    else:
        cfg = whisper.WhisperConfig.base()
        B, frames, max_toks, iters = 8, 3000, 64, 4  # 8 x 30 s chunks
    params = whisper.init_params(jax.random.PRNGKey(0), cfg)
    force(params)
    mel = jax.random.normal(jax.random.PRNGKey(1), (B, frames, cfg.n_mels))
    fn = jax.jit(
        lambda p, m: whisper.greedy_transcribe(
            p, m, cfg, bos_id=0, eos_id=1, max_tokens=max_toks
        )
    )
    t0 = time.time()
    np.asarray(fn(params, mel))
    compile_s = time.time() - t0

    def run(n):
        out = None
        t0 = time.time()
        for _ in range(n):
            out = fn(params, mel)
        np.asarray(out[0, 0])
        return time.time() - t0

    audio_s = B * frames * 0.01  # 10 ms mel hop
    xrt = audio_s / _slope_time(run, iters)
    print(json.dumps({
        "metric": ("tiny asr path-proof" if tiny
                   else "whisper-base-shape transcription speed (1 chip)"),
        "value": round(xrt, 1), "unit": "x-realtime",
        "vs_baseline": 0.0,  # no hard reference number in BASELINE.md
        "batch": B, "chunk_s": frames * 0.01, "tokens_per_chunk": max_toks,
        "compile_s": round(compile_s, 1),
        "backend": jax.default_backend(),
    }), flush=True)


def _finetune_child() -> None:
    """Secondary metric: LoRA fine-tune step throughput (BASELINE config
    "Llama-2-7B LoRA fine-tune"; unsloth_finetune.py). Adapters train
    on-the-fly against a frozen int8 base (the memory trick that fits 7B
    on one 16 GB chip); tokens/sec = B*S / step."""
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from modal_examples_tpu.models import llama, lora
    from modal_examples_tpu.models.quantize import init_quantized_llama
    from modal_examples_tpu.training import cross_entropy_loss
    from modal_examples_tpu.utils.sync import force

    tiny = bool(os.environ.get("BENCH_TINY"))
    if tiny:
        # tiny path keeps the SAME quantized-base shape as the real run so
        # CI exercises it (a float-only tiny path masked an int8-adapter
        # crash here once)
        cfg = llama.LlamaConfig.tiny()
        B, S, iters = 2, 32, 2
    else:
        cfg = llama.LlamaConfig.llama2_7b()
        B, S, iters = 2, 512, 4
    base = init_quantized_llama(jax.random.PRNGKey(0), cfg, bits=8)
    force(base)
    lcfg = lora.LoRAConfig(rank=16)
    adapters = lora.init_lora(jax.random.PRNGKey(1), base, lcfg)
    opt = optax.adam(1e-4)
    opt_state = opt.init(adapters)
    toks = jax.random.randint(jax.random.PRNGKey(2), (B, S), 0, cfg.vocab_size)
    mask = jnp.ones((B, S), jnp.float32)

    @jax.jit
    def step(adapters, opt_state, toks, mask):
        def loss_fn(ad):
            logits = llama.forward(
                base, toks, cfg, attn_impl="xla", lora=ad,
                lora_scale=lcfg.scale,
            )
            return cross_entropy_loss(logits[:, :-1], toks[:, 1:], mask[:, 1:])

        loss, g = jax.value_and_grad(loss_fn)(adapters)
        upd, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(adapters, upd), opt_state, loss

    t0 = time.time()
    adapters, opt_state, loss = step(adapters, opt_state, toks, mask)
    np.asarray(loss)
    compile_s = time.time() - t0

    def run(n):
        nonlocal adapters, opt_state
        loss = None
        t0 = time.time()
        for _ in range(n):
            adapters, opt_state, loss = step(adapters, opt_state, toks, mask)
        np.asarray(loss)
        return time.time() - t0

    step_s = _slope_time(run, iters)
    print(json.dumps({
        "metric": ("tiny finetune path-proof" if tiny
                   else "llama2-7b-int8-base LoRA finetune (1 chip)"),
        "value": round(B * S / step_s, 1), "unit": "train tok/s",
        "vs_baseline": 0.0,  # reference publishes no single-GPU number
        "batch": B, "seq": S, "step_s": round(step_s, 3),
        "adapter_params": lora.param_count(adapters),
        "compile_s": round(compile_s, 1),
        "backend": jax.default_backend(),
    }), flush=True)


SECONDARY_CHILDREN = {
    "--child-image": _image_child,
    "--child-embed": _embed_child,
    "--child-asr": _asr_child,
    "--child-finetune": _finetune_child,
}


def _run_config(model: str, env: dict, timeout: float) -> tuple[dict | None, str]:
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", model],
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None, f"{model}: timeout"
    result = _extract_json(proc.stdout)
    if result is None:
        return None, f"{model}: exit={proc.returncode} stderr={proc.stderr[-400:]}"
    if proc.stderr:
        # forward the child's diagnostics (the stdout one-json-line
        # contract holds; stderr is where section forensics like the
        # recovery mismatch reports land — don't swallow them)
        sys.stderr.write(proc.stderr[-4000:])
    return result, ""


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        from modal_examples_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache()
        _child(sys.argv[2])
        return 0
    if len(sys.argv) > 1 and sys.argv[1] in SECONDARY_CHILDREN:
        from modal_examples_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache()
        SECONDARY_CHILDREN[sys.argv[1]]()
        return 0

    # Hard wall-clock budget for the WHOLE bench (driver runs us with its own
    # timeout; round 1 summed per-config timeouts to 72 min and got rc=124).
    deadline = time.time() + float(os.environ.get("BENCH_BUDGET_S", "1100"))
    _kill_stray_children()

    env = dict(os.environ)
    chip_unreachable = False
    if not os.environ.get("BENCH_CPU"):
        backend = _preflight(timeout_s=int(os.environ.get("BENCH_PREFLIGHT_S", "120")))
        if not backend or backend == "cpu":
            # Chip unreachable (or no TPU plugin): degrade to a measured CPU
            # number immediately instead of burning the budget on attach.
            chip_unreachable = not backend
            env["BENCH_CPU"] = "1"

    if env.get("BENCH_MODEL"):
        order = [env["BENCH_MODEL"]]
    elif env.get("BENCH_CPU"):
        order = ["tiny"]
    else:
        # canary-first: the tiny config proves the full engine path end to
        # end in ~1 min and becomes the guaranteed fallback line; then every
        # real target, best-expected first so budget exhaustion still leaves
        # the strongest measured number on the table.
        order = [
            "tiny",
            "llama2-7b-int8-kv8-s36",
            "llama2-7b-int4-s36",
            "llama2-7b-int8-s36",
            "llama2-7b-int8-kv8-ctx1024",
            "llama2-7b-tp2-int8-ctx1024",
            "llama2-7b-int8-spec-ngram",
            "llama2-7b-mixed-ctx1024",
            "llama2-7b-fleet-sweep",
            "llama2-7b-disagg-2rep",
            "llama2-7b-int8-spec-draft1b",
            "llama2-7b-int8-s32",
            "llama2-7b-int8-s16",
            "llama3.1-8b-int8-s32",
            "llama2-7b",
            "llama-1b",
        ]

    results: dict[str, dict] = {}
    last_err = ""
    # the LLM decode headline must not starve the other four BASELINE
    # configs (image/embeddings/ASR/finetune secondary children): a flat
    # 500s reserve is carved out of the deadline for the whole LLM-config
    # loop — both the break check and each config's timeout are computed
    # against (deadline - reserve), so the config in flight when budget
    # runs low cannot eat the breadth metrics' time either
    secondary_reserve = (
        0 if os.environ.get("BENCH_NO_SECONDARY") else 500
    )
    for i, model in enumerate(order):
        spec = CONFIGS.get(model)
        if spec is None:
            last_err = f"unknown config {model!r}"
            continue
        is_canary = len(order) > 1 and i == 0
        # the reserve binds BOTH the break check and each config's timeout —
        # otherwise the config in flight when budget ran low could run to
        # the wall and consume the breadth metrics' time anyway
        remaining = (deadline - secondary_reserve) - time.time() - 15
        if remaining < 60:
            last_err = last_err or "budget exhausted"
            break
        # a canary keeps >=60s reserved per pending config so it can't starve
        # them; real configs run with whatever remains (best-first order)
        reserve = 60 * (len(order) - i - 1) if is_canary else 0
        timeout = max(60, min(spec["timeout"], remaining - reserve))
        result, err = _run_config(model, env, timeout)
        if result is None:
            last_err = err
            continue
        results[model] = result
        if env.get("BENCH_FIRST_WIN") and not is_canary:
            break

    # the HEADLINE is pinned to the north-star family: vs_baseline compares
    # against the A100 Llama-2-7B number, so only llama2-7b* configs may
    # claim it (round-3 VERDICT: a 1B model must never be scored against
    # the 7B baseline). Other models still appear in all_configs.
    real = {k: v for k, v in results.items() if k.startswith("llama2-7b")}
    real = real or {k: v for k, v in results.items() if k != "tiny"} or results
    if not real:
        print(
            json.dumps(
                {
                    "metric": "serving decode throughput",
                    "value": 0.0,
                    "unit": "tok/s",
                    "vs_baseline": 0.0,
                    "error": last_err,
                }
            )
        )
        return 1

    best_name = max(real, key=lambda k: real[k]["value"])
    best = real[best_name]
    if chip_unreachable:
        # honest context, not a substitute number: vs_baseline stays 0.
        # Round-specific measurements live in NOTES.md, not here — a
        # hardcoded number would go stale and misreport future rounds.
        best["chip_note"] = os.environ.get(
            "BENCH_CHIP_NOTE",
            "TPU unreachable at bench time (device attach failed); this is "
            "a degraded CPU number. See NOTES.md for the round's measured "
            "on-chip results and the incident record.",
        )
    if not best_name.startswith("llama2-7b"):
        # fallback headline (7B configs all failed): vs_baseline against the
        # 7B A100 number would be dishonest for another model — null it out
        best["vs_baseline"] = 0.0
        best["baseline_note"] = (
            "no llama2-7b config completed; value is NOT comparable to the "
            "A100 llama2-7b baseline"
        )
    best["all_configs"] = {k: v["value"] for k, v in results.items()}

    # secondary metrics: one child per remaining BASELINE config —
    # images/sec (SDXL analog, text_to_image.py:11-13), embedding tok/s
    # (bge-small / TEI), ASR x-realtime (whisper-base), LoRA train tok/s
    # (llama2-7b fine-tune). On a degraded CPU run each child runs a tiny
    # path-proof instead so the METRIC PATHS stay proven end to end.
    secondary = {
        "image_gen": "--child-image",
        "embeddings": "--child-embed",
        "asr": "--child-asr",
        "finetune": "--child-finetune",
    }
    if not os.environ.get("BENCH_NO_SECONDARY"):
        for key, flag in secondary.items():
            if key == "image_gen" and os.environ.get("BENCH_NO_IMAGE"):
                continue  # BENCH_NO_IMAGE skips only the slow SD3 child
            if deadline - time.time() < 240:
                break
            child_env = dict(env)
            if env.get("BENCH_CPU"):
                child_env["BENCH_IMAGE_TINY"] = "1"  # image child's switch
                child_env["BENCH_TINY"] = "1"
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__), flag],
                    capture_output=True, text=True,
                    # keep ~180s in reserve so a slow compile can't starve
                    # the warm-boot proof that follows
                    timeout=max(120, min(600, deadline - time.time() - 180)),
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                    env=child_env,
                )
                result = _extract_json(proc.stdout)
                if result is not None:
                    best[key] = result
            except subprocess.TimeoutExpired:
                best[key] = {"error": "timeout"}

    # warm-boot proof for the compile cache: rerun the winner (tiny token
    # budget) — its compiles are now disk hits, so build+compile collapses.
    if deadline - time.time() > 150 and not env.get("BENCH_CPU"):
        warm_env = dict(env)
        warm_env["BENCH_WARM"] = "1"
        warm, _ = _run_config(
            best_name, warm_env, max(60, deadline - time.time() - 15)
        )
        if warm is not None:
            best["warm_build_s"] = warm["engine_build_s"]
            best["warm_compile_s"] = warm["compile_s"]

    print(json.dumps(best))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
