#!/usr/bin/env python
"""Headline bench: LLM decode throughput on the continuous-batching engine.

North star (BASELINE.md): Llama-2-7B tokens/sec/chip on TPU, vs the A100
class the reference's vLLM example assumes. Baseline constant below:
~1400 output tok/s is a representative public vLLM Llama-2-7B total decode
throughput on one A100-40GB at moderate batch. vs_baseline = value/1400.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Supervisor/child structure: the supervisor tries model configs largest-first
in subprocesses with timeouts (a wedged TPU or an OOM must degrade, not
hang the driver); the child measures engine decode throughput after a
compile warmup. BENCH_MODEL env forces a config; BENCH_CPU=1 forces the CPU
backend (for local smoke tests).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

A100_LLAMA2_7B_TOK_S = 1400.0

CONFIGS = {
    # name: (engine model preset/config kwargs, slots, max_model_len, max_tokens, timeout_s)
    "llama2-7b": dict(slots=8, max_len=256, max_tokens=128, timeout=1500),
    # int8 weights: ~7GB on HBM, leaves room for a bigger batch/KV on 16GB
    "llama2-7b-int8": dict(
        slots=16, max_len=384, max_tokens=128, timeout=1500, quant="int8"
    ),
    "llama-1b": dict(slots=16, max_len=512, max_tokens=128, timeout=900),
    "tiny": dict(slots=4, max_len=128, max_tokens=16, timeout=420),
}


def _child(model: str) -> None:
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine, SamplingParams

    spec = CONFIGS[model]
    if model.startswith("llama2-7b"):
        cfg = llama.LlamaConfig.llama2_7b()
    elif model == "llama-1b":
        cfg = llama.LlamaConfig(
            vocab_size=32000, dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
            ffn_dim=5632, max_seq_len=2048,
        )
    else:
        cfg = llama.LlamaConfig.tiny()

    t0 = time.time()
    engine = LLMEngine(
        cfg,
        max_slots=spec["slots"],
        max_model_len=spec["max_len"],
        page_size=16,
        prefill_buckets=(64, 128, 256),
        kv_dtype=jnp.bfloat16,
        quantization=spec.get("quant"),
    )
    build_s = time.time() - t0
    prompt = "The quick brown fox jumps over the lazy dog. " * 2
    params = SamplingParams(max_tokens=spec["max_tokens"], temperature=1.0)

    # boot-time compiles, then a live warmup round through the scheduler
    t0 = time.time()
    engine.warmup()
    engine.start()
    warm = [engine.submit(prompt, SamplingParams(max_tokens=8, temperature=1.0))
            for _ in range(2)]
    for r in warm:
        "".join(engine.stream(r))
    compile_s = time.time() - t0

    # timed: saturate all slots
    n_reqs = spec["slots"] * 2
    base_tokens = engine.stats.generated_tokens
    t0 = time.time()
    reqs = [engine.submit(prompt, params) for _ in range(n_reqs)]
    for r in reqs:
        for _ in engine.stream(r):
            pass
    elapsed = time.time() - t0
    generated = engine.stats.generated_tokens - base_tokens
    engine.stop()

    tok_s = generated / elapsed
    print(
        json.dumps(
            {
                "metric": f"{model} serving decode throughput (1 chip)",
                "value": round(tok_s, 2),
                "unit": "tok/s",
                "vs_baseline": round(tok_s / A100_LLAMA2_7B_TOK_S, 4),
                "model": model,
                "params": cfg.param_count,
                "backend": jax.default_backend(),
                "slots": spec["slots"],
                "generated_tokens": generated,
                "elapsed_s": round(elapsed, 2),
                "engine_build_s": round(build_s, 1),
                "compile_s": round(compile_s, 1),
            }
        )
    )


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        _child(sys.argv[2])
        return 0

    if os.environ.get("BENCH_MODEL"):
        order = [os.environ["BENCH_MODEL"]]
    elif os.environ.get("BENCH_CPU"):
        order = ["tiny"]
    else:
        order = ["llama2-7b", "llama2-7b-int8", "llama-1b", "tiny"]

    last_err = ""
    for model in order:
        spec = CONFIGS[model]
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child", model],
                capture_output=True,
                text=True,
                timeout=spec["timeout"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired:
            last_err = f"{model}: timeout after {spec['timeout']}s"
            continue
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                json.loads(line)
                print(line)
                return 0
            except json.JSONDecodeError:
                continue
        last_err = f"{model}: exit={proc.returncode} stderr={proc.stderr[-400:]}"
    print(
        json.dumps(
            {
                "metric": "serving decode throughput",
                "value": 0.0,
                "unit": "tok/s",
                "vs_baseline": 0.0,
                "error": last_err,
            }
        )
    )
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
