#!/usr/bin/env python
"""Headline bench: LLM decode throughput on the continuous-batching engine.

North star (BASELINE.md): Llama-2-7B tokens/sec/chip on TPU, vs the A100
class the reference's vLLM example assumes. Baseline constant below:
~1400 output tok/s is a representative public vLLM Llama-2-7B total decode
throughput on one A100-40GB at moderate batch. vs_baseline = value/1400.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Supervisor/child structure: the supervisor tries model configs largest-first
in subprocesses with timeouts (a wedged TPU or an OOM must degrade, not
hang the driver); the child measures engine decode throughput after a
compile warmup. BENCH_MODEL env forces a config; BENCH_CPU=1 forces the CPU
backend (for local smoke tests).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

A100_LLAMA2_7B_TOK_S = 1400.0

CONFIGS = {
    # name: (engine model preset/config kwargs, slots, max_model_len, max_tokens, timeout_s)
    "llama2-7b": dict(slots=8, max_len=256, max_tokens=128, timeout=1500),
    # int8 weights: ~7GB on HBM, leaves room for a bigger batch/KV on 16GB
    "llama2-7b-int8": dict(
        slots=16, max_len=384, max_tokens=128, timeout=1500, quant="int8"
    ),
    "llama-1b": dict(slots=16, max_len=512, max_tokens=128, timeout=900),
    "tiny": dict(slots=4, max_len=128, max_tokens=16, timeout=420),
}


def _child(model: str) -> None:
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine, SamplingParams

    spec = CONFIGS[model]
    if model.startswith("llama2-7b"):
        cfg = llama.LlamaConfig.llama2_7b()
    elif model == "llama-1b":
        cfg = llama.LlamaConfig(
            vocab_size=32000, dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
            ffn_dim=5632, max_seq_len=2048,
        )
    else:
        cfg = llama.LlamaConfig.tiny()

    t0 = time.time()
    engine = LLMEngine(
        cfg,
        max_slots=spec["slots"],
        max_model_len=spec["max_len"],
        page_size=16,
        prefill_buckets=(64, 128, 256),
        kv_dtype=jnp.bfloat16,
        quantization=spec.get("quant"),
    )
    build_s = time.time() - t0
    prompt = "The quick brown fox jumps over the lazy dog. " * 2
    params = SamplingParams(max_tokens=spec["max_tokens"], temperature=1.0)

    # boot-time compiles, then a live warmup round through the scheduler
    t0 = time.time()
    engine.warmup()
    engine.start()
    warm = [engine.submit(prompt, SamplingParams(max_tokens=8, temperature=1.0))
            for _ in range(2)]
    for r in warm:
        "".join(engine.stream(r))
    compile_s = time.time() - t0

    # timed: saturate all slots
    n_reqs = spec["slots"] * 2
    base_tokens = engine.stats.generated_tokens
    t0 = time.time()
    reqs = [engine.submit(prompt, params) for _ in range(n_reqs)]
    for r in reqs:
        for _ in engine.stream(r):
            pass
    elapsed = time.time() - t0
    generated = engine.stats.generated_tokens - base_tokens
    engine.stop()

    tok_s = generated / elapsed
    print(
        json.dumps(
            {
                "metric": f"{model} serving decode throughput (1 chip)",
                "value": round(tok_s, 2),
                "unit": "tok/s",
                "vs_baseline": round(tok_s / A100_LLAMA2_7B_TOK_S, 4),
                "model": model,
                "params": cfg.param_count,
                "backend": jax.default_backend(),
                "slots": spec["slots"],
                "generated_tokens": generated,
                "elapsed_s": round(elapsed, 2),
                "engine_build_s": round(build_s, 1),
                "compile_s": round(compile_s, 1),
            }
        )
    )


def _kill_stray_children() -> None:
    """Kill leftover bench/claim children from a previous wedged run.

    Round-1 postmortem (NOTES.md): a crash-looping child holding the chip's
    claim handshake wedged every later device attach. Sweep any prior
    `bench.py --child` / preflight processes before we touch the device.
    """
    me = os.getpid()
    try:
        out = subprocess.run(
            ["pgrep", "-f", "bench.py --child|_bench_preflight"],
            capture_output=True, text=True, timeout=10,
        ).stdout
    except Exception:
        return
    for pid_s in out.split():
        try:
            pid = int(pid_s)
            if pid in (me, os.getppid()):
                continue
            # only reap ORPHANS (reparented to init): a live bench's children
            # have their live supervisor as parent and must not be touched
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().rsplit(")", 1)[1].split()[1])
            if ppid == 1:
                os.kill(pid, 9)
        except (ValueError, OSError, IndexError):
            pass


def _preflight(timeout_s: int = 120) -> str:
    """Cheap device-attach probe in a subprocess; returns backend or ''.

    A wedged chip blocks *inside* device attach, so the probe must be a
    separate killable process (the round-1 failure burned every config's
    full timeout on exactly this block).
    """
    code = (
        "import jax; print('_bench_preflight', jax.default_backend(), "
        "len(jax.devices()))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return ""
    for line in proc.stdout.splitlines():
        if line.startswith("_bench_preflight"):
            return line.split()[1]
    return ""


def _extract_json(stdout: str) -> str | None:
    for line in reversed(stdout.strip().splitlines()):
        try:
            json.loads(line)
            return line
        except json.JSONDecodeError:
            continue
    return None


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        _child(sys.argv[2])
        return 0

    # Hard wall-clock budget for the WHOLE bench (driver runs us with its own
    # timeout; round 1 summed per-config timeouts to 72 min and got rc=124).
    deadline = time.time() + float(os.environ.get("BENCH_BUDGET_S", "1100"))
    _kill_stray_children()

    env = dict(os.environ)
    if not os.environ.get("BENCH_CPU"):
        backend = _preflight(timeout_s=int(os.environ.get("BENCH_PREFLIGHT_S", "120")))
        if not backend or backend == "cpu":
            # Chip unreachable (or no TPU plugin): degrade to a measured CPU
            # number immediately instead of burning the budget on attach.
            env["BENCH_CPU"] = "1"

    if env.get("BENCH_MODEL"):
        order = [env["BENCH_MODEL"]]
    elif env.get("BENCH_CPU"):
        order = ["tiny"]
    else:
        # canary-first: the tiny config proves the full engine path end to end
        # in ~1 min and becomes the guaranteed fallback line; then try the real
        # targets largest-first within the remaining budget.
        order = ["tiny", "llama2-7b", "llama2-7b-int8", "llama-1b"]

    fallback_line = None
    last_err = ""
    for i, model in enumerate(order):
        spec = CONFIGS[model]
        remaining = deadline - time.time() - 15
        if remaining < 60:
            last_err = last_err or "budget exhausted before any config ran"
            break
        # reserve >=60s for each config still behind this one, so one
        # hanging config can't starve smaller ones that would succeed
        reserve = 60 * (len(order) - i - 1)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child", model],
                capture_output=True,
                text=True,
                timeout=max(60, min(spec["timeout"], remaining - reserve)),
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env=env,
            )
        except subprocess.TimeoutExpired:
            last_err = f"{model}: timeout"
            continue
        line = _extract_json(proc.stdout)
        if line is None:
            last_err = f"{model}: exit={proc.returncode} stderr={proc.stderr[-400:]}"
            continue
        is_canary = len(order) > 1 and i == 0
        if not is_canary:
            print(line)
            return 0
        fallback_line = line

    if fallback_line is not None:
        print(fallback_line)
        return 0
    print(
        json.dumps(
            {
                "metric": "serving decode throughput",
                "value": 0.0,
                "unit": "tok/s",
                "vs_baseline": 0.0,
                "error": last_err,
            }
        )
    )
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
