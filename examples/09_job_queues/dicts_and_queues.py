# # Distributed coordination with Dicts and Queues
#
# Counterpart of 09_job_queues/dicts_and_queues.py:53-80 — a crawler-shaped
# workload: a shared Queue feeds worker containers, a shared Dict collects
# results and carries the termination signal.

import modal_examples_tpu as mtpu

app = mtpu.App("example-dicts-queues")

# a tiny synthetic "site graph" standing in for the web (zero-egress)
SITE = {
    "root": ["a", "b"],
    "a": ["c", "d"],
    "b": ["d", "e"],
    "c": [], "d": ["f"], "e": [], "f": [],
}


@app.function(timeout=120, max_containers=4)
def crawler_worker(worker_id: int, queue_name: str, dict_name: str) -> int:
    frontier = mtpu.Queue.from_name(queue_name)
    seen = mtpu.Dict.from_name(dict_name)
    crawled = 0
    while True:
        try:
            url = frontier.get(timeout=1.0)
        except Exception:
            break  # drained
        if url == "__stop__":
            break
        if not seen.put_if_absent(url, worker_id):
            continue  # another worker claimed it
        crawled += 1
        for link in SITE.get(url, []):
            if link not in seen:
                frontier.put(link)
    return crawled


@app.local_entrypoint()
def main(n_workers: int = 3):
    with mtpu.Queue.ephemeral() as frontier, mtpu.Dict.ephemeral() as seen:
        frontier.put("root")
        counts = list(
            crawler_worker.starmap(
                [(i, frontier.name, seen.name) for i in range(n_workers)]
            )
        )
        crawled = set(seen.keys())
    print(f"workers crawled {counts} -> {sorted(crawled)}")
    assert crawled == set(SITE)
    assert sum(counts) == len(SITE)
