# # Async job queue: web frontend spawns TPU jobs
#
# Counterpart of 09_job_queues/doc_ocr_jobs.py + doc_ocr_webapp.py — a web
# endpoint accepts work, `.spawn`s it onto accelerator containers, returns a
# call id immediately, and a second endpoint polls for the result
# (the 1M-queued-inputs pattern, amazon_embeddings.py:18).

import modal_examples_tpu as mtpu

app = mtpu.App("example-doc-jobs")


@app.function(timeout=300)
def process_document(text: str) -> dict:
    """The 'OCR' stage — here a cheap summarizer standing in for the model."""
    words = text.split()
    return {
        "words": len(words),
        "summary": " ".join(words[:8]) + ("..." if len(words) > 8 else ""),
    }


@app.function()
@mtpu.fastapi_endpoint(method="POST")
def submit(text: str) -> dict:
    call = process_document.spawn(text)
    return {"call_id": call.object_id}


@app.function()
@mtpu.fastapi_endpoint()
def result(call_id: str) -> dict:
    try:
        return {"status": "done", "result": mtpu.FunctionCall.from_id(call_id).get(timeout=0.1)}
    except TimeoutError:
        return {"status": "pending"}


@app.local_entrypoint()
def main():
    import time

    call = process_document.spawn("the quick brown fox jumps over the lazy dog " * 4)
    print("submitted:", call.object_id)
    while True:
        try:
            out = mtpu.FunctionCall.from_id(call.object_id).get(timeout=0.2)
            break
        except TimeoutError:
            print("pending...")
            time.sleep(0.2)
    print("result:", out)
    assert out["words"] == 36
