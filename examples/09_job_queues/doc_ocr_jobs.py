# ---
# env: {"MTPU_TRAIN_STEPS": "900"}
# timeout: 900
# ---
# # Document OCR job queue: a REAL recognizer behind spawn/poll
#
# TPU-native counterpart of the reference's 09_job_queues/doc_ocr_jobs.py
# + doc_ocr_webapp.py: a web app submits scanned documents, `.spawn()`s
# GPU OCR jobs (marker/datalab torch models there), and a results
# endpoint polls job status by call id. Here the OCR model is the
# framework's own `models.ocr` — a conv + transformer + CTC text-line
# recognizer (the CRNN/TrOCR architecture family) trained FROM SCRATCH on
# synthetically rendered text (zero egress: PIL rasterizes strings; the
# model genuinely learns glyphs). The job-queue mechanics are identical
# to the reference: submit -> spawn -> poll by id.
#
# Run: tpurun run examples/09_job_queues/doc_ocr_jobs.py

import os
import pickle
import time

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None
TRAIN_STEPS = int(os.environ.get("MTPU_TRAIN_STEPS", "1400"))

app = mtpu.App("example-doc-ocr-jobs")
model_vol = mtpu.Volume.from_name("ocr-model", create_if_missing=True)
jobs = mtpu.Dict.from_name("ocr-jobs", create_if_missing=True)


def _cfg():
    from modal_examples_tpu.models import ocr

    return ocr.OCRConfig(width=128)


@app.function(tpu=TPU, volumes={"/models": model_vol}, timeout=3600)
def train(steps: int = TRAIN_STEPS) -> dict:
    """Train the recognizer on rendered text lines; save to the Volume
    (the reference caches its pretrained weights on a Volume the same
    way, doc_ocr_jobs.py load_models)."""
    import jax
    import numpy as np
    import optax

    from modal_examples_tpu.models import ocr

    cfg = _cfg()
    params = ocr.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    warmup = min(100, max(1, steps // 10))  # steps<=100 must not crash
    sched = optax.warmup_cosine_decay_schedule(0, 3e-3, warmup, steps, 3e-4)
    opt = optax.adam(sched)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(ocr.ctc_loss)(
            params, images, labels, cfg
        )
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    for i in range(steps):
        # max_len 14 samples lines of 3..13 chars — covering the 11-char
        # demo documents (evaluating outside the trained length hurts CER)
        images, labels, _ = ocr.synthetic_batch(rng, 32, cfg, max_len=14)
        params, opt_state, loss = step(params, opt_state, images, labels)
        if i % 200 == 0:
            print(f"train step {i}: ctc loss {float(loss):.3f}")

    with open("/models/ocr.pkl", "wb") as f:
        pickle.dump(jax.tree.map(np.asarray, params), f)
    model_vol.commit()
    return {"final_loss": float(loss), "steps": steps}


@app.cls(tpu=TPU, volumes={"/models": model_vol}, scaledown_window=300)
class OCRWorker:
    """Load-once-serve-many (the reference's Model cls shape): the
    checkpoint loads and jits at container boot, not per document."""

    @mtpu.enter()
    def load(self):
        import jax
        import jax.numpy as jnp

        from modal_examples_tpu.models import ocr

        self.cfg = _cfg()
        model_vol.reload()  # see another container's committed checkpoint
        with open("/models/ocr.pkl", "rb") as f:
            self.params = jax.tree.map(jnp.asarray, pickle.load(f))
        # compile ONCE at boot: greedy_decode's forward runs under this jit
        # for every document the container serves
        self._logits = jax.jit(
            lambda imgs: ocr.forward(self.params, imgs, self.cfg)
        )

    @mtpu.method()
    def ocr_job(self, job_id: str, image_png_b64: str) -> str:
        """One OCR job: decode the submitted scan, run the recognizer,
        store the result under the job id (the parse_receipt shape)."""
        import base64
        import io

        import numpy as np
        from PIL import Image

        from modal_examples_tpu.models import ocr

        try:
            img = Image.open(
                io.BytesIO(base64.b64decode(image_png_b64))
            ).convert("L")
            img = img.resize((self.cfg.width, self.cfg.height))
            arr = np.asarray(img, np.float32)[None, :, :, None] / 255.0
            logits = np.asarray(self._logits(arr))
            # CTC greedy collapse on the jitted logits
            text = ocr.decode_labels(
                [t for t, prev in zip(
                    logits[0].argmax(-1).tolist(),
                    [-1] + logits[0].argmax(-1).tolist()[:-1],
                ) if t != prev and t != 0]
            )
        except Exception as e:  # noqa: BLE001 — status must never stick
            jobs.put(job_id, {
                "status": "error", "error": f"{type(e).__name__}: {e}",
            })
            raise
        jobs.put(job_id, {"status": "done", "text": text})
        return text


@app.function()
@mtpu.fastapi_endpoint(method="POST")
def submit(image_png_b64: str) -> dict:
    """The webapp's submit endpoint: enqueue the job, return its id
    immediately (doc_ocr_webapp.py:submit -> .spawn)."""
    import uuid

    job_id = f"job-{uuid.uuid4().hex[:10]}"
    jobs.put(job_id, {"status": "running"})
    OCRWorker().ocr_job.spawn(job_id, image_png_b64)
    return {"job_id": job_id}


@app.function()
@mtpu.fastapi_endpoint()
def result(job_id: str) -> dict:
    """Poll a job by id (doc_ocr_webapp.py:poll_results)."""
    return jobs.get(job_id, {"status": "unknown"})


@app.local_entrypoint()
def main(steps: int = TRAIN_STEPS):
    import base64
    import io
    import json
    import urllib.parse
    import urllib.request

    import numpy as np
    from PIL import Image

    from modal_examples_tpu.models import ocr
    from modal_examples_tpu.utils.metrics import character_error_rate
    from modal_examples_tpu.web.gateway import Gateway

    cfg = _cfg()
    print(f"training recognizer ({steps} steps, from scratch)...")
    stats = train.remote(steps)
    print("train:", stats)

    docs = ["TOTAL 42.50", "INVOICE #77", "DUE 2026-08"]
    with app.run():
        gw = Gateway(app).start()
        base = gw.base_url
        job_ids = []
        for text in docs:
            arr = (ocr.render_line(text, cfg)[:, :, 0] * 255).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="PNG")
            b64 = base64.b64encode(buf.getvalue()).decode()
            req = urllib.request.Request(
                f"{base}/submit",
                data=json.dumps({"image_png_b64": b64}).encode(),
                headers={"content-type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                job_ids.append(json.load(r)["job_id"])
        print(f"submitted {len(job_ids)} scans; polling...")

        results = {}
        deadline = time.time() + 300
        while len(results) < len(job_ids) and time.time() < deadline:
            for jid in job_ids:
                if jid in results:
                    continue
                q = urllib.parse.urlencode({"job_id": jid})
                with urllib.request.urlopen(
                    f"{base}/result?{q}", timeout=60
                ) as r:
                    status = json.load(r)
                if status["status"] == "done":
                    results[jid] = status["text"]
                elif status["status"] == "error":
                    raise RuntimeError(f"job {jid} failed: {status['error']}")
            time.sleep(0.3)
        gw.stop()

    missing = [j for j in job_ids if j not in results]
    assert not missing, f"jobs never completed within the deadline: {missing}"
    got = [results[j] for j in job_ids]
    for want, g in zip(docs, got):
        print(f"  scanned={want!r} ocr={g!r}")
    cer = character_error_rate(docs, got)
    print(f"character error rate: {cer:.3f}")
    assert cer < 0.35, f"OCR quality too low: CER {cer:.3f}"
