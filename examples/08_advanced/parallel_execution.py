# # Spawn, gather, and cross-process polling
#
# Counterpart of 08_advanced/parallel_execution.py:33-48 (spawn + gather)
# and poll_delayed_result.py (`FunctionCall.from_id` from another process).

import time

import modal_examples_tpu as mtpu

app = mtpu.App("example-parallel-execution")


@app.function(timeout=120)
def slow_square(x: int) -> int:
    time.sleep(0.5)
    return x * x


@app.local_entrypoint()
def main():
    t0 = time.monotonic()
    calls = [slow_square.spawn(i) for i in range(6)]
    # fire-and-forget: all six run concurrently across containers
    results = mtpu.gather(*calls)
    elapsed = time.monotonic() - t0
    print(f"gathered {results} in {elapsed:.2f}s")
    assert results == [i * i for i in range(6)]
    assert elapsed < 6 * 0.5  # genuinely parallel

    # poll a call by id, as a separate client process would
    # (poll_delayed_result.py pattern)
    call = slow_square.spawn(9)
    call_id = call.object_id
    print("polling call id:", call_id)
    assert mtpu.FunctionCall.from_id(call_id).get(timeout=30) == 81
