# # Profiling TPU workloads
#
# Counterpart of 06_gpu_and_ml/torch_profiling.py — a generic `profile`
# Function that wraps any registered Function by name (:131-135), runs it
# under the profiler with warmup/active scheduling (:141-161), writes
# TensorBoard-compatible traces to a Volume (:116), and prints a summary
# (:164-167). TPU flavor: jax.profiler XPlane traces + HBM stats instead of
# torch.profiler + nvidia-smi.
#
# Run: tpurun run examples/06_gpu_and_ml/tpu_profiling.py

import os

import modal_examples_tpu as mtpu
from modal_examples_tpu.utils.profiling import make_profile_function

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-tpu-profiling")
traces_vol = mtpu.Volume.from_name("profiler-traces", create_if_missing=True)


@app.function(tpu=TPU, timeout=600)
def matmul_workload(n: int = 512) -> float:
    """A candidate workload to profile."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((n, n), jnp.bfloat16)
    y = jax.jit(lambda a: a @ a @ a)(x)
    return float(jnp.sum(y.astype(jnp.float32)))


@app.function(tpu=TPU, timeout=120)
def hbm_stats() -> dict:
    from modal_examples_tpu.utils.profiling import device_memory_stats

    return device_memory_stats()


profile = make_profile_function(app, trace_volume=traces_vol)


@app.local_entrypoint()
def main():
    result = profile.remote("matmul_workload", 256, iterations=5)
    print("profile result:", {k: result[k] for k in ("iterations", "per_iter_s")})
    assert result["iterations"] == 5
    traces_vol.reload()
    traces = list(traces_vol.listdir("/", recursive=True))
    print(f"{len(traces)} trace files on the volume (serve with TensorBoard)")
    assert traces, "profiler wrote no trace"
    print("HBM stats:", hbm_stats.remote())
