# # LoRA fine-tuning with checkpoint/resume
#
# TPU-native counterpart of the reference's unsloth_finetune.py: LoRA
# adapters on q/k/v/o/gate/up/down (:205-213), interruption-tolerant
# training (`retries` + `single_use_containers` + `timeout`, :285-288 and
# long-training.py:109-137), checkpoint-resume from the latest step
# (:549-567), dataset + checkpoints on Volumes with explicit commits.
#
# Where unsloth patches torch modules with Triton kernels, here adapters are
# their own pytree applied on the fly inside the jitted step (x@W + (x@a)@b)
# and only adapter + optimizer-over-adapter state train — the base stays
# frozen bf16.
#
# Run: tpurun run examples/06_gpu_and_ml/llm-finetuning/lora_finetune.py \
#        --max-steps 30

import os

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-lora-finetune")
ckpt_vol = mtpu.Volume.from_name("lora-checkpoints", create_if_missing=True)

# synthetic instruction-ish dataset (zero-egress stand-in for the HF dataset
# the reference caches to a Volume, unsloth_finetune.py:130-176)
DATASET = [
    ("What is the MXU?", "The MXU is the TPU's 128x128 systolic matrix unit."),
    ("What feeds the MXU?", "VMEM feeds the MXU with operand tiles."),
    ("What is ICI?", "ICI is the inter-chip interconnect linking TPU chips."),
    ("What is HBM?", "HBM is the high-bandwidth memory attached to each chip."),
    ("What is XLA?", "XLA compiles JAX programs into fused TPU executables."),
    ("What is a mesh?", "A mesh names axes over devices for sharded arrays."),
] * 4


@app.function(
    tpu=TPU,
    volumes={"/ckpts": ckpt_vol},
    timeout=3600,
    retries=mtpu.Retries(initial_delay=0.0, max_retries=3),
    single_use_containers=True,  # fresh container per attempt
)
def finetune(max_steps: int = 30, lora_rank: int = 8, resume: bool = True) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import llama, lora
    from modal_examples_tpu.training import (
        CheckpointManager,
        Trainer,
        cross_entropy_loss,
        make_optimizer,
    )
    from modal_examples_tpu.utils.tokenizer import ByteTokenizer

    cfg = llama.LlamaConfig(
        vocab_size=512, dim=128, n_layers=4, n_heads=4, n_kv_heads=2,
        ffn_dim=256, max_seq_len=128, dtype="float32",
    )
    base = llama.init_params(jax.random.PRNGKey(0), cfg)
    lcfg = lora.LoRAConfig(rank=lora_rank)  # targets q/k/v/o/gate/up/down
    adapters = lora.init_lora(jax.random.PRNGKey(1), base, lcfg)

    tok = ByteTokenizer()
    S = 96

    def encode(q, a):
        ids = tok.encode(f"Q: {q}\nA: {a}")[: S]
        arr = np.full((S,), tok.pad_id, np.int32)
        arr[: len(ids)] = ids
        mask = np.zeros((S,), np.float32)
        mask[: len(ids)] = 1.0
        return arr, mask

    encoded = [encode(q, a) for q, a in DATASET]

    def batch_at(key, bs=4):
        ix = np.asarray(jax.random.randint(key, (bs,), 0, len(encoded)))
        toks = np.stack([encoded[i][0] for i in ix])
        mask = np.stack([encoded[i][1] for i in ix])
        return {"tokens": jnp.asarray(toks), "mask": jnp.asarray(mask)}

    def loss_fn(adapters, batch):
        logits = llama.forward(
            base, batch["tokens"], cfg, attn_impl="xla",
            lora=adapters, lora_scale=lcfg.scale,
        )
        return cross_entropy_loss(
            logits[:, :-1], batch["tokens"][:, 1:], batch["mask"][:, 1:]
        )

    trainer = Trainer(loss_fn, make_optimizer(1e-3))
    state = trainer.init_state(adapters)
    # reload FIRST: a fresh retry container must see commits from the dead
    # attempt before scanning for checkpoints (volume.reload contract)
    ckpt_vol.reload()
    ckpts = CheckpointManager("/ckpts/lora-run", keep_n=2, volume=ckpt_vol)

    # resume from the latest checkpoint (unsloth_finetune.py:549-567)
    start_step = 0
    if resume and ckpts.latest_step() is not None:
        template = {"adapters": state.params, "opt": state.opt_state}
        restored = ckpts.restore(template)
        state = state.__class__(
            params=restored["adapters"], opt_state=restored["opt"],
            step=state.step,
        )
        start_step = ckpts.latest_step()
        print(f"resumed from step {start_step}")

    if start_step >= max_steps:
        print(f"nothing to do: checkpoint at {start_step} >= max_steps {max_steps}")
        return {
            "trained_steps": 0, "resumed_from": start_step,
            "first_loss": None, "final_loss": None,
            "adapter_params": lora.param_count(state.params),
        }

    key = jax.random.PRNGKey(2)
    losses = []
    for step in range(start_step, max_steps):
        key, sub = jax.random.split(key)
        state, metrics = trainer.train_step(state, batch_at(sub))
        losses.append(float(metrics["loss"]))
        if (step + 1) % 10 == 0:
            ckpts.save(step + 1, {"adapters": state.params, "opt": state.opt_state})
            print(f"step {step + 1} loss {losses[-1]:.3f} (checkpointed)")

    ckpts.save(max_steps, {"adapters": state.params, "opt": state.opt_state})
    return {
        "trained_steps": max_steps - start_step,
        "resumed_from": start_step,
        "first_loss": losses[0] if losses else None,
        "final_loss": losses[-1] if losses else None,
        "adapter_params": lora.param_count(state.params),
    }


@app.local_entrypoint()
def main(max_steps: int = 30):
    result = finetune.remote(max_steps, 8, True)
    print("finetune result:", result)
    if result["trained_steps"] > 0:
        assert result["final_loss"] < result["first_loss"] * 1.5
    # run again: must resume from the checkpoint, not restart
    again = finetune.remote(max_steps + 10, 8, True)
    print("resume result:", again)
    assert again["resumed_from"] >= max_steps
