# ---
# env: {"MTPU_TRAIN_STEPS": "500"}
# timeout: 1000
# ---
# # Promptable segmentation service: embed once, segment per click
#
# TPU-native counterpart of the reference's 06_gpu_and_ml/sam/
# segment_anything.py (Meta's SAM on torch CUDA: load the checkpoint in
# @enter, embed the image once, then decode a mask for every interactive
# prompt). Here the model is the framework's own `models.segmentation`
# (SAM-family: reusable image embedding + prompt tokens + mask decoder
# with predicted IoU), trained from scratch on synthetic multi-object
# scenes (zero egress) — click a shape, get THAT shape's mask.
#
# The serving shape mirrors the reference: an @app.cls holds the params
# and per-image embedding cache across requests (the expensive encode
# happens once per image; each click is a cheap decode).
#
# Run: tpurun run examples/06_gpu_and_ml/vision/segment_anything.py

import os
import pickle

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None
TRAIN_STEPS = int(os.environ.get("MTPU_TRAIN_STEPS", "700"))

app = mtpu.App("example-segment-anything")
model_vol = mtpu.Volume.from_name("sam-model", create_if_missing=True)


def _cfg():
    from modal_examples_tpu.models import segmentation as sam

    return sam.SAMConfig(image_size=64, dim=96)


@app.function(tpu=TPU, volumes={"/models": model_vol}, timeout=3600)
def train(steps: int = TRAIN_STEPS) -> dict:
    import jax
    import numpy as np
    import optax

    from modal_examples_tpu.models import segmentation as sam

    cfg = _cfg()
    params = sam.init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(2e-3)
    opt_state = opt.init(params)
    batch_fn = jax.jit(lambda k: sam.synthetic_batch(k, 16, cfg))

    @jax.jit
    def step(params, opt_state, imgs, pts, msks):
        loss, grads = jax.value_and_grad(sam.segmentation_loss)(
            params, imgs, pts, msks, cfg
        )
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    key = jax.random.PRNGKey(1)
    for i in range(steps):
        key, sub = jax.random.split(key)
        imgs, pts, msks = batch_fn(sub)
        params, opt_state, loss = step(params, opt_state, imgs, pts, msks)
        if i % 200 == 0:
            print(f"train step {i}: loss {float(loss):.4f}")
    with open("/models/sam.pkl", "wb") as f:
        pickle.dump(jax.tree.map(np.asarray, params), f)
    model_vol.commit()
    return {"final_loss": float(loss)}


@app.cls(tpu=TPU, volumes={"/models": model_vol}, scaledown_window=300)
class Segmenter:
    @mtpu.enter()
    def load(self):
        import jax

        if not TPU:
            # cheap mode must not touch the chip (see streaming_asr_ws.py)
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        import jax.numpy as jnp

        from modal_examples_tpu.models import segmentation as sam

        self.sam = sam
        self.cfg = _cfg()
        model_vol.reload()
        with open("/models/sam.pkl", "rb") as f:
            self.params = jax.tree.map(jnp.asarray, pickle.load(f))
        self._encode = jax.jit(
            lambda img: sam.encode_image(self.params, img, self.cfg)
        )
        self._decode = jax.jit(
            lambda feats, pts: sam.decode_mask(
                self.params, feats, pts, self.cfg
            )
        )
        from collections import OrderedDict

        # image_id -> embedding (the SAM serving pattern); LRU-capped so a
        # long-lived container can't accumulate unbounded embeddings
        self._cache = OrderedDict()
        self._cache_cap = 32

    @mtpu.method()
    def segment(self, image_id: str, image: list | None, points: list) -> dict:
        """Embed once per image_id; decode a mask per click. ``image`` may
        be None on repeat calls for the same id (embedding reuse)."""
        import numpy as np

        if image_id not in self._cache:
            assert image is not None, "first call for an id must send pixels"
            arr = np.asarray(image, np.float32)[None]
            self._cache[image_id] = self._encode(arr)
            while len(self._cache) > self._cache_cap:
                self._cache.popitem(last=False)
        else:
            self._cache.move_to_end(image_id)
        feats = self._cache[image_id]
        pts = np.asarray(points, np.float32)[None]
        logits, iou = self._decode(feats, pts)
        mask = (np.asarray(logits)[0] > 0)
        # RLE-encode the mask (the compact transport the reference uses)
        flat = mask.reshape(-1)
        runs, val, count = [], False, 0
        for px in flat:
            if px == val:
                count += 1
            else:
                runs.append(count)
                val, count = px, 1
        runs.append(count)
        return {
            "rle": runs,
            "area": int(mask.sum()),
            "pred_iou": float(np.asarray(iou)[0]),
        }


@app.local_entrypoint()
def main(steps: int = TRAIN_STEPS):
    import jax

    if not TPU:
        # the entrypoint itself uses jax for the demo scene; keep the CLI
        # process off the chip in cheap mode
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    import numpy as np

    from modal_examples_tpu.models import segmentation as sam

    cfg = _cfg()
    print(f"training promptable segmenter ({steps} steps)...")
    print("train:", train.remote(steps))

    img, p0, m0 = sam.synthetic_scene(jax.random.PRNGKey(5), cfg)
    seg = Segmenter()
    # click shape A (pixels sent once), then shape B (embedding reused)
    r0 = seg.segment.remote("scene-1", np.asarray(img).tolist(),
                            np.asarray(p0).tolist())
    other = np.clip(1.0 - np.asarray(p0), 0.05, 0.95)
    r1 = seg.segment.remote("scene-1", None, other.tolist())

    def rle_to_mask(runs):
        out, val = [], False
        for n in runs:
            out += [val] * n
            val = not val
        return np.asarray(out, bool).reshape(cfg.image_size, cfg.image_size)

    mask0 = rle_to_mask(r0["rle"])
    gt = np.asarray(m0) > 0.5
    iou = (mask0 & gt).sum() / max((mask0 | gt).sum(), 1)
    print(f"click A: area={r0['area']} iou_vs_gt={iou:.2f} "
          f"pred_iou={r0['pred_iou']:.2f}")
    print(f"click B: area={r1['area']} (embedding reused)")
    diff = (mask0 ^ rle_to_mask(r1["rle"])).sum()
    print(f"masks differ by {diff} px — the click conditions the mask")
    assert iou > 0.3 and diff > 20
