# # Object-detection fine-tune (YOLO-family workload)
#
# TPU-native counterpart of the reference's vision family
# (yolo/finetune_yolo.py — an ultralytics fine-tune loop on GPU;
# sam/segment_anything.py — segmentation inference): a from-scratch JAX
# anchor-free detector (models/vision.py) fine-tuned on a synthetic
# geometric-shapes dataset generated on device, with the same Trainer,
# checkpoint Volume, and cheap-mode switches the LLM workloads use.
#
# The contract mirrors the reference's end-to-end checks: train briefly,
# then assert the model localizes held-out boxes (IoU > 0.5) — detection's
# version of the WER-after-finetune check
# (openai_whisper/finetuning/train/end_to_end_check.py:29-70).
#
# Run: tpurun run examples/06_gpu_and_ml/vision/finetune_detector.py \
#        --steps 60

import os

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-finetune-detector")
ckpt_vol = mtpu.Volume.from_name("detector-checkpoints", create_if_missing=True)


@app.function(
    tpu=TPU,
    volumes={"/ckpts": ckpt_vol},
    timeout=3600,
    retries=mtpu.Retries(initial_delay=0.0, max_retries=2),
)
def finetune(steps: int = 60, batch: int = 16) -> dict:
    import jax
    import numpy as np

    from modal_examples_tpu.models import vision
    from modal_examples_tpu.training import (
        CheckpointManager, Trainer, make_optimizer,
    )

    cfg = vision.DetectorConfig(image_size=64, n_classes=3, width=16, depth=1)
    params = vision.init_params(jax.random.PRNGKey(0), cfg)
    trainer = Trainer(
        lambda p, b: vision.detection_loss(p, b, cfg), make_optimizer(3e-3)
    )
    state = trainer.init_state(params)

    losses = []
    for step in range(steps):
        data = vision.synthetic_batch(jax.random.PRNGKey(100 + step), batch, cfg)
        state, metrics = trainer.train_step(state, data)
        losses.append(float(metrics["loss"]))
        if (step + 1) % 20 == 0:
            print(f"step {step + 1} loss {losses[-1]:.3f}")

    ckpts = CheckpointManager("/ckpts/detector-run", keep_n=1, volume=ckpt_vol)
    ckpts.save(steps, {"params": state.params})

    # held-out eval: top detection per image vs true boxes
    held = vision.synthetic_batch(jax.random.PRNGKey(999), 8, cfg)
    preds = vision.forward(state.params, held["images"], cfg)
    boxes, scores, classes = vision.decode_boxes(preds, cfg)

    def iou(a, b):
        x1, y1 = max(a[0], b[0]), max(a[1], b[1])
        x2, y2 = min(a[2], b[2]), min(a[3], b[3])
        inter = max(0.0, x2 - x1) * max(0.0, y2 - y1)
        ar = lambda r: (r[2] - r[0]) * (r[3] - r[1])  # noqa: E731
        return inter / (ar(a) + ar(b) - inter + 1e-6)

    hits = 0
    for b in range(8):
        best = int(np.argmax(np.asarray(scores[b])))
        pred = np.asarray(boxes[b, best])
        true = np.asarray(held["boxes"][b][np.asarray(held["box_mask"][b])])
        hits += max(iou(pred, t) for t in true) > 0.5
    return {
        "first_loss": losses[0],
        "final_loss": losses[-1],
        "holdout_hits": int(hits),
        "holdout_total": 8,
    }


@app.function(volumes={"/ckpts": ckpt_vol})
def detect(image_b64: str) -> list:
    """Inference service half (segment_anything.py-style): restore the
    fine-tuned weights from the checkpoint Volume, decode one image, return
    NMS-filtered detections. Accepts a base64 64x64x3 float image."""
    import base64

    import jax
    import numpy as np

    from modal_examples_tpu.models import vision
    from modal_examples_tpu.training import CheckpointManager

    cfg = vision.DetectorConfig(image_size=64, n_classes=3, width=16, depth=1)
    params = vision.init_params(jax.random.PRNGKey(0), cfg)
    ckpt_vol.reload()
    ckpts = CheckpointManager("/ckpts/detector-run", keep_n=1, volume=ckpt_vol)
    if ckpts.latest_step() is None:
        raise RuntimeError("no detector checkpoint; run finetune first")
    params = ckpts.restore({"params": params})["params"]
    raw = np.frombuffer(base64.b64decode(image_b64), np.float32)
    img = raw.reshape(1, 64, 64, 3)
    preds = vision.forward(params, jax.numpy.asarray(img), cfg)
    boxes, scores, classes = vision.decode_boxes(preds, cfg)
    keep = vision.nms_host(
        boxes[0], scores[0], classes[0], score_thresh=0.1, iou_thresh=0.5
    )
    return [
        {
            "box": [float(v) for v in np.asarray(boxes[0, i])],
            "score": float(scores[0, i]),
            "class": int(classes[0, i]),
        }
        for i in keep[:5]
    ]


@app.local_entrypoint()
def main(steps: int = 60):
    result = finetune.remote(steps, 16)
    print("finetune:", result)
    assert result["final_loss"] < result["first_loss"]
    assert result["holdout_hits"] >= result["holdout_total"] * 3 // 4, result

    import base64

    import numpy as np

    img = np.zeros((64, 64, 3), np.float32)
    img[20:40, 10:30] = 0.9  # a rectangle
    dets = detect.remote(base64.b64encode(img.tobytes()).decode())
    print(f"detect() returned {len(dets)} candidate boxes")
