# # Fast cold starts: snapshot-eligible setup + persistent compile cache
#
# Counterpart of 06_gpu_and_ml/gpu_snapshot.py:41-52 (bge-small served with
# `@modal.enter(snap=True)` + GPU memory snapshots). The TPU translation of
# "snapshot the device state": the expensive parts of a cold start are (1)
# weights to HBM and (2) the XLA compile — so `@mtpu.enter(snap=True)` marks
# the stage whose effects are captured, and the **XLA persistent compile
# cache on a Volume** makes recompiles cache hits across containers (the
# single biggest TPU cold-start lever, SURVEY.md §7).

import os
import time

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-tpu-snapshot")
compile_cache = mtpu.Volume.from_name("xla-compile-cache", create_if_missing=True)


@app.cls(
    tpu=TPU,
    volumes={"/xla-cache": compile_cache},
    enable_memory_snapshot=True,
    timeout=600,
)
class Embedder:
    @mtpu.enter(snap=True)
    def load(self):
        """Everything here is snapshot-eligible: model build + compile."""
        import jax

        try:
            jax.config.update("jax_compilation_cache_dir", "/xla-cache")
        except Exception:
            pass
        from modal_examples_tpu.models import bert

        self.cfg = bert.BertConfig.tiny()
        self.params = bert.init_params(jax.random.PRNGKey(0), self.cfg)
        t0 = time.time()
        self._embed = jax.jit(lambda p, t: bert.embed(p, t, None, self.cfg))
        import numpy as np

        from modal_examples_tpu.utils.sync import force

        # force(): block_until_ready is a no-op on the tunneled axon backend,
        # and compile_s below is a published measurement
        force(self._embed(self.params, np.zeros((4, 32), np.int32)))
        self.compile_s = time.time() - t0
        compile_cache.commit()  # publish cache entries for the next replica

    @mtpu.method()
    def embed(self, texts: list[str]) -> dict:
        import numpy as np

        from modal_examples_tpu.utils.tokenizer import ByteTokenizer

        tok = ByteTokenizer()
        ids = np.zeros((4, 32), np.int32)
        for i, t in enumerate(texts[:4]):
            enc = tok.encode(t)[:32]
            ids[i, : len(enc)] = enc
        out = self._embed(self.params, ids)
        return {"dim": int(out.shape[1]), "compile_s": self.compile_s}


@app.local_entrypoint()
def main():
    e = Embedder()
    r = e.embed.remote(["snapshot me"])
    print(f"embed dim={r['dim']}, enter-stage compile took {r['compile_s']:.2f}s")
    print("subsequent replicas hit the persistent compile cache on the volume")
