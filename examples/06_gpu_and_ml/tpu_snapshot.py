# # Fast cold starts: memory snapshots + persistent compile cache
#
# Counterpart of 06_gpu_and_ml/gpu_snapshot.py:41-52 (bge-small served with
# `@modal.enter(snap=True)` + GPU memory snapshots). `enable_memory_snapshot=
# True` is backed by a real checkpoint/restore subsystem
# (`modal_examples_tpu/snapshot/`): after the first container finishes its
# `@mtpu.enter(snap=True)` hooks, the worker serializes the object's state —
# the params pytree is captured as host numpy and re-put on device at restore
# — into a content-addressed store keyed by image digest + class source hash
# + env fingerprint + host-CPU tag. Every later cold start restores that
# state and **skips the snap hooks entirely**: `load()` below runs once per
# code/image/env fingerprint, not once per container.
#
# Attrs that can't cross the snapshot boundary (jitted callables, clients,
# locks) are recorded as rebuild-on-restore markers — which is why the jit
# build + warmup lives in its own non-snap hook: a restored boot re-runs only
# `warmup()`, and with the **XLA persistent compile cache on a Volume** that
# recompile is a disk hit (the single biggest TPU cold-start lever,
# SURVEY.md §7). Corrupted or stale snapshots fall back to a cold boot;
# restore is never less reliable than a cold start.
#
# Observe it: `tpurun snapshot list|inspect|clear` browses the store, and
# boot outcomes are exported as prometheus counters
# (`mtpu_snapshot_boots_total{result="hit|miss|fallback"}`).

import os

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-tpu-snapshot")
compile_cache = mtpu.Volume.from_name("xla-compile-cache", create_if_missing=True)


@app.cls(
    tpu=TPU,
    volumes={"/xla-cache": compile_cache},
    enable_memory_snapshot=True,
    timeout=600,
)
class Embedder:
    @mtpu.enter(snap=True)
    def load(self):
        """Snapshot-eligible: pure state (config + weights). A restored boot
        skips this hook — the captured pytree comes back from the store and
        is re-put on device."""
        import jax

        from modal_examples_tpu.models import bert

        self.cfg = bert.BertConfig.tiny()
        self.params = bert.init_params(jax.random.PRNGKey(0), self.cfg)

    @mtpu.enter()
    def warmup(self):
        """Runs on every boot — jitted callables can't cross the snapshot
        boundary. With the compile cache warm on the volume, the recompile
        here is a disk hit instead of an XLA compile."""
        import time

        import jax
        import numpy as np

        try:
            jax.config.update("jax_compilation_cache_dir", "/xla-cache")
        except Exception:
            pass
        from modal_examples_tpu.models import bert
        from modal_examples_tpu.utils.sync import force

        t0 = time.time()
        self._embed = jax.jit(lambda p, t: bert.embed(p, t, None, self.cfg))
        # force(): block_until_ready is a no-op on the tunneled axon backend
        force(self._embed(self.params, np.zeros((4, 32), np.int32)))
        self.compile_s = time.time() - t0
        compile_cache.commit()  # publish cache entries for the next replica

    @mtpu.method()
    def embed(self, texts: list[str]) -> dict:
        import numpy as np

        from modal_examples_tpu.utils.tokenizer import ByteTokenizer

        tok = ByteTokenizer()
        ids = np.zeros((4, 32), np.int32)
        for i, t in enumerate(texts[:4]):
            enc = tok.encode(t)[:32]
            ids[i, : len(enc)] = enc
        out = self._embed(self.params, ids)
        return {"dim": int(out.shape[1]), "compile_s": self.compile_s}


@app.local_entrypoint()
def main():
    from modal_examples_tpu.utils.metrics import SNAPSHOT_BOOTS_METRIC
    from modal_examples_tpu.utils.prometheus import default_registry

    e = Embedder()
    r = e.embed.remote(["snapshot me"])
    print(f"embed dim={r['dim']}, warmup compile took {r['compile_s']:.2f}s")
    tag = "example-tpu-snapshot.Embedder"
    for result in ("hit", "miss", "fallback"):
        n = default_registry.value(
            SNAPSHOT_BOOTS_METRIC, {"function": tag, "result": result}
        )
        if n:
            print(f"snapshot boots: {result}={n:.0f}")
    print("next container boot restores load() from the snapshot store;")
    print("inspect it with `tpurun snapshot list`")
