# # Hyperparameter sweep: pretrain a small GPT from scratch
#
# TPU-native counterpart of the reference's
# 06_gpu_and_ml/hyperparameter-sweep/hp_sweep_gpt.py (a from-scratch
# nanoGPT-style SLM swept 8-ways via `.starmap` :320, checkpointed to a
# Volume :768, "recognizable Shakespeare in ~15 min" :65-67). Here the model
# is `models.gpt` (JAX, flash attention, scan layers) trained by the jitted
# `Trainer` step; the sweep fans out over containers with `.starmap`; the
# winner checkpoints to a Volume and generates a sample.
#
# Run: tpurun run examples/06_gpu_and_ml/hyperparameter-sweep/hp_sweep_gpt.py \
#        --n-steps 50

import os

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-hp-sweep-gpt")
runs_vol = mtpu.Volume.from_name("gpt-sweep-runs", create_if_missing=True)

# A tiny public-domain training corpus, inlined (zero-egress environment;
# the reference downloads tinyshakespeare). Enough to overfit recognizably.
CORPUS = (
    """
To be, or not to be, that is the question:
Whether 'tis nobler in the mind to suffer
The slings and arrows of outrageous fortune,
Or to take arms against a sea of troubles
And by opposing end them. To die: to sleep;
No more; and by a sleep to say we end
The heart-ache and the thousand natural shocks
That flesh is heir to, 'tis a consummation
Devoutly to be wish'd. To die, to sleep;
To sleep: perchance to dream: ay, there's the rub;
For in that sleep of death what dreams may come
When we have shuffled off this mortal coil,
Must give us pause: there's the respect
That makes calamity of so long life;
All the world's a stage,
And all the men and women merely players:
They have their exits and their entrances;
And one man in his time plays many parts,
His acts being seven ages.
Friends, Romans, countrymen, lend me your ears;
I come to bury Caesar, not to praise him.
The evil that men do lives after them;
The good is oft interred with their bones.
"""
    * 8
)


@app.function(tpu=TPU, volumes={"/runs": runs_vol}, timeout=3600, max_containers=8)
def train_one(run_name: str, lr: float, dim: int, n_steps: int) -> dict:
    """Train one configuration; returns its final validation loss."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import gpt
    from modal_examples_tpu.training import (
        CheckpointManager,
        Trainer,
        cross_entropy_loss,
        make_optimizer,
        warmup_cosine,
    )

    tok = gpt.CharTokenizer(CORPUS)
    data = np.array(tok.encode(CORPUS), np.int32)
    split = int(len(data) * 0.9)
    train_data, val_data = data[:split], data[split:]

    cfg = gpt.GPTConfig(
        vocab_size=tok.vocab_size, block_size=128, n_layers=4,
        n_heads=4, dim=dim,
    )
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)

    def batch_from(arr, key, bs=8):
        ix = jax.random.randint(key, (bs,), 0, len(arr) - cfg.block_size - 1)
        toks = np.stack([arr[i : i + cfg.block_size + 1] for i in np.asarray(ix)])
        return {"tokens": jnp.asarray(toks)}

    def loss_fn(p, batch):
        logits = gpt.forward(p, batch["tokens"][:, :-1], cfg)
        return cross_entropy_loss(logits, batch["tokens"][:, 1:])

    trainer = Trainer(
        loss_fn, make_optimizer(warmup_cosine(lr, 10, n_steps))
    )
    state = trainer.init_state(params)
    ckpts = CheckpointManager(f"/runs/{run_name}", keep_n=1, volume=runs_vol)

    key = jax.random.PRNGKey(1)
    for step in range(n_steps):
        key, sub = jax.random.split(key)
        state, metrics = trainer.train_step(state, batch_from(train_data, sub))
        if step % 20 == 0:
            print(f"[{run_name}] step {step} loss {float(metrics['loss']):.3f}")

    val_loss = float(loss_fn(state.params, batch_from(val_data, key)))
    ckpts.save(n_steps, {"params": state.params})
    return {"run": run_name, "lr": lr, "dim": dim, "val_loss": val_loss}


@app.function(tpu=TPU, volumes={"/runs": runs_vol}, timeout=600)
def sample_from(run_name: str, dim: int, prompt: str = "To be") -> str:
    """Load the checkpointed winner and generate (inference Cls analog,
    hp_sweep_gpt.py:438+)."""
    import jax
    import jax.numpy as jnp

    from modal_examples_tpu.models import gpt
    from modal_examples_tpu.training import CheckpointManager

    runs_vol.reload()
    tok = gpt.CharTokenizer(CORPUS)
    cfg = gpt.GPTConfig(
        vocab_size=tok.vocab_size, block_size=128, n_layers=4, n_heads=4, dim=dim
    )
    template = {"params": gpt.init_params(jax.random.PRNGKey(0), cfg)}
    restored = CheckpointManager(f"/runs/{run_name}").restore(template)
    toks = gpt.generate(
        restored["params"], cfg, jnp.asarray(tok.encode(prompt)), 80,
        jax.random.PRNGKey(7), temperature=0.8,
    )
    return prompt + tok.decode(toks)


@app.local_entrypoint()
def main(n_steps: int = 100):
    import time

    # unique sweep id: run dirs never collide with a previous invocation's
    # checkpoints on the persistent volume
    sweep = time.strftime("%Y%m%d-%H%M%S")
    # the sweep grid: 4 configurations fanned out via .starmap
    # (hp_sweep_gpt.py:320)
    grid = [
        (f"{sweep}/run-lr{lr}-d{dim}", lr, dim, n_steps)
        for lr in (3e-3, 1e-3)
        for dim in (64, 128)
    ]
    results = list(train_one.starmap(grid))
    results.sort(key=lambda r: r["val_loss"])
    print("sweep results:")
    for r in results:
        print(f"  {r['run']}: val_loss={r['val_loss']:.3f}")
    best = results[0]
    text = sample_from.remote(best["run"], best["dim"])
    print(f"--- sample from {best['run']} ---")
    print(text)
