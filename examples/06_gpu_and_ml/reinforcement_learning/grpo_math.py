# # GRPO: reinforcement learning on math with sandboxed rewards
#
# Counterpart of the reference's RL stack (learn_math.py — GRPO with rewards
# from sandboxed code execution :7-9; grpo_trl.py / grpo_verl.py:153-202 —
# TRL/verl + vLLM rollouts + FSDP). Here the whole loop is framework-native:
# JAX rollouts, group-relative advantages, clipped policy update — and the
# reward is computed by executing checker code inside an mtpu.Sandbox, like
# the reference scores model-written code.
#
# Run: tpurun run examples/06_gpu_and_ml/reinforcement_learning/grpo_math.py

import os
import sys

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-grpo-math")

PROMPTS = ["2+3=", "4+1="]  # single-digit sums; answer is one byte token


@app.function(tpu=TPU, timeout=3600)
def train_grpo(steps: int = 24) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import llama
    from modal_examples_tpu.training.grpo import GRPOConfig, GRPOTrainer
    from modal_examples_tpu.utils.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    # ASCII math fits in 64 byte ids ('0'-'9','+','='); a small action space
    # keeps exploration tractable for the toy policy
    cfg = llama.LlamaConfig(
        vocab_size=64, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
        ffn_dim=128, max_seq_len=32, dtype="float32",
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)

    # reward: a sandboxed checker scores every completion in one exec
    # (learn_math.py's sandboxed scoring, batched)
    sandbox = mtpu.Sandbox.create(timeout=3600)

    def make_reward_fn(prompt_text: str, prompt_len: int):
        expected = str(eval(prompt_text.rstrip("=")))  # noqa: S307 — trusted example

        def reward_fn(tokens):
            # raw sampled bytes can be anything (incl. NUL): ship them as a
            # json file into the sandbox, not argv
            import json

            answers = [
                tok.decode([int(t)]) for t in np.asarray(tokens[:, prompt_len])
            ]
            checker = (
                "import json\n"
                f"expected = {expected!r}\n"
                "for a in json.load(open('answers.json')):\n"
                "    # shaped: full credit for the right digit, partial for\n"
                "    # any digit (dense enough for the toy policy to climb)\n"
                "    print(1.0 if a == expected else (0.2 if a.isdigit() else 0.0))\n"
            )
            with sandbox.open("check.py", "w") as f:
                f.write(checker)
            with sandbox.open("answers.json", "w") as f:
                json.dump(answers, f)
            p = sandbox.exec(sys.executable, "check.py")
            code = p.wait()
            if code != 0:
                raise RuntimeError(f"reward checker failed: {p.stderr.read()}")
            rewards = [float(line) for line in p.stdout.read().split()]
            assert len(rewards) == len(answers), (len(rewards), len(answers))
            return rewards

        return reward_fn

    encoded = []
    for text in PROMPTS:
        ids = tok.encode(text, add_bos=False)  # raw bytes, all < 64
        encoded.append((jnp.asarray(ids, jnp.int32), len(ids), make_reward_fn(text, len(ids))))

    trainer = GRPOTrainer(
        cfg, params, encoded[0][2],
        GRPOConfig(group_size=16, max_new=2, temperature=1.0, kl_coef=0.005),
        learning_rate=4e-3,
    )
    key = jax.random.PRNGKey(1)
    history = []
    for step in range(steps):
        prompt, plen, reward_fn = encoded[step % len(encoded)]
        key, sub = jax.random.split(key)
        m = trainer.step(prompt, plen, sub, reward_fn=reward_fn)
        history.append(m["mean_reward"])
        if (step + 1) % 8 == 0:
            print(f"step {step + 1}: mean reward {m['mean_reward']:.2f}")
    sandbox.cleanup()

    window = max(1, min(len(PROMPTS) * 2, len(history) // 2))
    early = sum(history[:window]) / window
    late = sum(history[-window:]) / window
    return {"early_reward": early, "late_reward": late, "history": history}


@app.local_entrypoint()
def main(steps: int = 24):
    out = train_grpo.remote(steps)
    print(f"reward: {out['early_reward']:.2f} -> {out['late_reward']:.2f}")
    assert out["late_reward"] > out["early_reward"], out["history"]
    print("GRPO improved the policy with sandboxed rewards")
