# ---
# timeout: 700
# ---
# # Retrieval-augmented document Q&A with sources
#
# TPU-native counterpart of the reference's
# 06_gpu_and_ml/langchains/potus_speech_qanda.py: ingest one document,
# chunk it, embed the chunks into a vector index, and answer questions by
# retrieving the top-k chunks and generating an answer that cites them.
# The reference wires LangChain + FAISS + the OpenAI API; here every
# stage is the framework's own machinery:
#
# - chunking: plain Python (the RecursiveCharacterTextSplitter analog);
# - embeddings: models.bert (the TEI/BGE analog), L2-normalized;
# - index: an [N, D] matrix on a Volume — top-k is ONE matvec, the
#   MXU-shaped exact search (see embeddings/vector_search.py);
# - answering: the continuous-batching LLMEngine with the retrieved
#   chunks packed into the prompt, sources returned alongside.
#
# Like the reference it exposes both a CLI entrypoint (--query) and a web
# endpoint (GET /qanda?query=...). Zero egress: the "speech" is inline,
# and cheap mode runs tiny random-weight models — retrieval quality
# assertions are by construction (token overlap with mean pooling), and
# swapping in real BGE + Llama checkpoints via model_dir changes no code.
#
# Run: tpurun run examples/06_gpu_and_ml/langchains/document_qa.py \
#        --query "How many oil barrels were released from reserves?"

import os
import pickle

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-document-qa")
index_vol = mtpu.Volume.from_name("document-qa-index", create_if_missing=True)

# the knowledge base: one address, distinct facts per paragraph (the
# reference scrapes the 2022 State of the Union; zero egress keeps it
# inline — same single-document shape)
DOCUMENT = """
Tonight I can announce that the United States has worked with thirty
countries to release sixty million barrels of oil from reserves around
the world.

We are providing more than one billion dollars in direct assistance to
Ukraine and will continue to aid the Ukrainian people as they defend
their country.

The American Rescue Plan helped create over six million new jobs last
year, more jobs created in one year than ever before in the history of
our country.

Our infrastructure law will rebuild four thousand miles of highway and
repair ten thousand bridges across the nation over the coming decade.

We will cut the cost of insulin so that no family pays more than
thirty five dollars a month for the medicine their loved ones need.

I am announcing a crackdown on shipping companies that overcharge
American businesses and consumers, cutting ocean freight costs.

Tonight we launch a new initiative to end cancer as we know it, aiming
to cut cancer death rates by half over the next twenty five years.
"""


def chunk_document(text: str, max_chars: int = 240) -> list[str]:
    """Paragraph-first splitting with a size cap — the text-splitter
    stage of the reference chain."""
    chunks = []
    for para in text.split("\n\n"):
        para = " ".join(para.split())
        if not para:
            continue
        while len(para) > max_chars:
            cut = para.rfind(" ", 0, max_chars)
            cut = cut if cut > 0 else max_chars
            chunks.append(para[:cut])
            para = para[cut:].strip()
        chunks.append(para)
    return chunks


def _embedder():
    """models.bert mean-pooled normalized sentence embeddings (cheap mode:
    tiny random weights — see embeddings/vector_search.py for why mean
    pooling keeps that discriminative; real BGE loads via
    bert.load_hf_weights with identical code)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import bert
    from modal_examples_tpu.utils.tokenizer import load_tokenizer

    cfg = dataclasses.replace(bert.BertConfig.tiny(), pooling="mean")
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    tok = load_tokenizer(None)
    embed = jax.jit(lambda t, m: bert.embed(params, t, m, cfg))

    def encode(texts: list[str], max_len: int = 256):
        ids, mask = tok.encode_batch(texts, max_len)
        ids = np.asarray(ids) % cfg.vocab_size
        return np.asarray(embed(jnp.asarray(ids), jnp.asarray(mask)))

    return encode


@app.function(tpu=TPU, volumes={"/index": index_vol}, timeout=600)
def ingest() -> dict:
    """Chunk + embed the document into the Volume index (the reference's
    scrape -> split -> FAISS.from_texts stage)."""
    chunks = chunk_document(DOCUMENT)
    vecs = _embedder()(chunks)
    with open("/index/index.pkl", "wb") as f:
        pickle.dump({"vectors": vecs, "chunks": chunks}, f)
    index_vol.commit()
    return {"chunks": len(chunks), "dim": int(vecs.shape[1])}


@app.cls(tpu=TPU, volumes={"/index": index_vol}, scaledown_window=300)
class DocQA:
    @mtpu.enter()
    def load(self):
        import jax

        if not TPU:
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine

        index_vol.reload()
        with open("/index/index.pkl", "rb") as f:
            idx = pickle.load(f)
        self.vectors = idx["vectors"]
        self.chunks = idx["chunks"]
        self.encode = _embedder()
        # cheap mode: tiny random-weight llama; production passes
        # model_dir= / a MODEL_PRESETS name exactly like the llm-serving
        # examples (the chain does not care which)
        self.engine = LLMEngine(
            llama.LlamaConfig.tiny(),
            max_slots=2, max_model_len=512, page_size=16,
            prefill_buckets=(128, 256, 512), kv_dtype=jnp.float32,
        )
        self.engine.start()

    @mtpu.method()
    def answer(self, query: str, k: int = 3, max_tokens: int = 48) -> dict:
        """Retrieve top-k chunks, answer with sources — the reference's
        RetrievalQA.from_chain_type(..., return_source_documents=True)."""
        import numpy as np

        from modal_examples_tpu.serving import SamplingParams

        q = self.encode([query])[0]
        scores = self.vectors @ q
        top = np.argsort(-scores)[:k]
        sources = [
            {"id": int(i), "score": float(scores[i]), "text": self.chunks[i]}
            for i in top
        ]
        context = "\n".join(f"[{n + 1}] {s['text']}" for n, s in enumerate(sources))
        prompt = (
            "Answer the question using only the sources; cite like [1].\n"
            f"Sources:\n{context}\nQuestion: {query}\nAnswer:"
        )
        req = self.engine.submit(
            prompt, SamplingParams(max_tokens=max_tokens, temperature=0.0)
        )
        return {"answer": "".join(self.engine.stream(req)), "sources": sources}


@app.function()
@mtpu.fastapi_endpoint()
def qanda(query: str, k: int = 3) -> dict:
    """GET /qanda?query=... — the reference's web_endpoint shape
    (potus_speech_qanda.py `web`)."""
    return DocQA().answer.remote(query, int(k))


@app.local_entrypoint()
def main(query: str = "How many oil barrels were released from reserves?"):
    print("ingest:", ingest.remote())
    qa = DocQA()

    result = qa.answer.remote(query)
    print(f"Q: {query}")
    print("A:", result["answer"][:200])
    for s in result["sources"]:
        print(f"   [{s['id']}] {s['score']:.3f} {s['text'][:70]}...")
    # retrieval correctness (by construction in cheap mode: token overlap)
    assert any("barrels" in s["text"] for s in result["sources"]), result

    spot_checks = [
        ("What will the infrastructure law rebuild?", "highway"),
        ("What is the monthly cap on insulin costs?", "insulin"),
        ("How many jobs did the American Rescue Plan create?", "jobs"),
        ("What happens to shipping companies that overcharge?", "shipping"),
    ]
    for q, must_cite in spot_checks:
        r = qa.answer.remote(q)
        # cheap mode runs a RANDOM-weight tiny llama: the generated text is
        # noise (can even decode to ""), so the contract checked here is
        # the CHAIN — retrieval cites the right evidence and the request
        # completes; answer quality needs real checkpoints (model_dir=)
        assert "answer" in r, r
        assert any(must_cite in s["text"] for s in r["sources"]), (q, r["sources"])
        print(f"ok: {q!r} -> cites a chunk containing {must_cite!r}")
    # different questions retrieve different evidence
    a = qa.answer.remote(spot_checks[0][0])["sources"][0]["id"]
    b = qa.answer.remote(spot_checks[1][0])["sources"][0]["id"]
    assert a != b, (a, b)
    print("document QA chain: ingest -> retrieve -> cite -> answer all green")
