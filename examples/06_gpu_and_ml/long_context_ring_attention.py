# # Long-context training with ring attention
#
# The reference has NO sequence-parallel machinery — its long-context story
# is engine flags (max_seq_length=32768, unsloth_finetune.py:386) delegated
# to vLLM/SGLang internals (SURVEY.md §5.7). This example is the framework's
# value-add: the sequence dimension sharded over a `seq` mesh axis, K/V
# shards rotating around the ring with `ppermute` (neighbor ICI hops on a
# TPU torus), exact online-softmax merging — no device ever holds the full
# sequence, and the whole thing is differentiable for training.
#
# Run: tpurun run examples/06_gpu_and_ml/long_context_ring_attention.py

import os

import modal_examples_tpu as mtpu

app = mtpu.App("example-ring-attention")

SEQ_SHARDS = 4
SEQ_LEN = 2048  # 4 shards x 512 — each device sees 1/4 of the sequence

# on a dev box the "slice" is a virtual CPU mesh; on a pod the tpu= spec's
# chips form it (SURVEY.md §4's fake-backend tier)
image = mtpu.Image.debian_slim().env(
    {"XLA_FLAGS": f"--xla_force_host_platform_device_count={SEQ_SHARDS}"}
)


@app.function(timeout=900, image=image)
def train_long_context(steps: int = 5) -> dict:
    import jax
    import jax.numpy as jnp

    from modal_examples_tpu.ops import reference, ring_attention_sharded
    from modal_examples_tpu.parallel import make_mesh

    mesh = make_mesh({"seq": SEQ_SHARDS})
    B, H, D = 1, 4, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(ks[0], (B, H, SEQ_LEN, D))
    k = jax.random.normal(ks[1], (B, H, SEQ_LEN, D))
    v = jax.random.normal(ks[2], (B, H, SEQ_LEN, D))

    # exactness: the ring result equals dense attention over the full seq
    ring = ring_attention_sharded(q, k, v, mesh, causal=True)
    dense = reference.attention(q, k, v, causal=True)
    max_err = float(jnp.abs(ring - dense).max())

    # and it trains: gradients flow through the ppermute ring
    def loss(qkv):
        q, k, v = qkv
        out = ring_attention_sharded(q, k, v, mesh, causal=True)
        return jnp.mean(out**2)

    val, grads = jax.value_and_grad(loss)((q, k, v))
    grad_norm = float(
        jnp.sqrt(sum(jnp.sum(g**2) for g in jax.tree.leaves(grads)))
    )
    return {
        "seq_len": SEQ_LEN,
        "shards": SEQ_SHARDS,
        "ring_vs_dense_max_err": max_err,
        "loss": float(val),
        "grad_norm": grad_norm,
    }


@app.local_entrypoint()
def main():
    out = train_long_context.remote()
    print("ring attention:", out)
    assert out["ring_vs_dense_max_err"] < 5e-5
    assert out["grad_norm"] > 0
    print(
        f"{out['seq_len']}-token context over {out['shards']} shards: "
        f"exact to {out['ring_vs_dense_max_err']:.1e}, differentiable"
    )
