# # Fine-tune Whisper-style ASR, end to end
#
# TPU-native counterpart of the reference's
# 06_gpu_and_ml/openai_whisper/fine_tune_asr.py + finetuning/train/train.py
# (HF Seq2SeqTrainer, WER eval :431-490, checkpoint-resume :175-194,
# volume.commit :469) and its end_to_end_check.py (train -> serialize ->
# reload in a DIFFERENT function -> transcribe -> assert WER < 1.0, :29-70).
#
# Zero-egress stand-in for the speech dataset: synthetic tone sequences with
# known transcripts (each word = a distinct tone), enough for the tiny model
# to overfit — the cheap-mode switch pattern (max_train_samples=5,
# train.py:76-77).
#
# Run: tpurun run examples/06_gpu_and_ml/openai_whisper/fine_tune_asr.py \
#        --train-steps 60

import os

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-whisper-finetune")
ckpt_vol = mtpu.Volume.from_name("whisper-checkpoints", create_if_missing=True)

WORD_TONES = {"alpha": 440.0, "bravo": 660.0, "charlie": 880.0, "delta": 1100.0}
SENTENCES = [
    "alpha bravo",
    "charlie delta",
    "alpha charlie",
    "bravo delta",
    "delta alpha",
    "bravo charlie",
]
MEL_FRAMES = 200  # 2s of audio -> 100 encoder frames (test_tiny geometry)


class WordTokenizer:
    """Word-level vocab for the tone task (whisper's real tokenizer is the
    HF BPE kept as a host dep, SURVEY.md §2.4; this is the dev-mode stand-in)."""

    def __init__(self, words):
        self.words = sorted(words)
        self.stoi = {w: i + 2 for i, w in enumerate(self.words)}
        self.bos_id, self.eos_id = 0, 1

    def encode(self, sent):
        return [self.stoi[w] for w in sent.split()]

    def decode(self, ids):
        itos = {v: k for k, v in self.stoi.items()}
        return " ".join(itos[i] for i in ids if i in itos)


def make_dataset():
    """(mel, token) pairs for the synthetic tone->word task."""
    import numpy as np

    from modal_examples_tpu.utils.audio import log_mel_spectrogram, synth_tone_audio

    tok = WordTokenizer(WORD_TONES)
    items = []
    for sent in SENTENCES:
        audio = np.concatenate(
            [synth_tone_audio([WORD_TONES[w]], 1.0) for w in sent.split()]
        )
        mel = log_mel_spectrogram(audio, pad_to_chunk=False)
        mel = np.pad(mel[:MEL_FRAMES], ((0, MEL_FRAMES - min(len(mel), MEL_FRAMES)), (0, 0)))
        ids = [tok.bos_id] + tok.encode(sent) + [tok.eos_id]
        items.append((mel, ids, sent))
    return tok, items


def model_config():
    import dataclasses

    from modal_examples_tpu.models import whisper

    return dataclasses.replace(
        whisper.WhisperConfig.test_tiny(), vocab_size=16, n_text_ctx=8
    )


@app.function(tpu=TPU, volumes={"/ckpts": ckpt_vol}, timeout=3600, retries=2)
def train(train_steps: int = 60) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import whisper
    from modal_examples_tpu.training import (
        CheckpointManager, Trainer, cross_entropy_loss, make_optimizer,
    )

    cfg = model_config()
    tok, items = make_dataset()
    params = whisper.init_params(jax.random.PRNGKey(0), cfg)

    S = cfg.n_text_ctx
    mels = jnp.asarray(np.stack([m for m, _, _ in items]))
    toks = np.full((len(items), S), tok.eos_id, np.int32)
    mask = np.zeros((len(items), S), np.float32)
    for i, (_, ids, _) in enumerate(items):
        toks[i, : len(ids)] = ids
        mask[i, : len(ids)] = 1.0
    toks, mask = jnp.asarray(toks), jnp.asarray(mask)

    def loss_fn(p, batch):
        logits = whisper.forward(p, batch["mel"], batch["tokens"], cfg)
        return cross_entropy_loss(
            logits[:, :-1], batch["tokens"][:, 1:], batch["mask"][:, 1:]
        )

    if train_steps < 1:
        raise ValueError("train_steps must be >= 1")
    trainer = Trainer(loss_fn, make_optimizer(3e-3))
    state = trainer.init_state(params)
    batch = {"mel": mels, "tokens": toks, "mask": mask}
    first = last = None
    for step in range(train_steps):
        state, metrics = trainer.train_step(state, batch)
        last = float(metrics["loss"])
        if first is None:
            first = last
        if (step + 1) % 20 == 0:
            print(f"step {step + 1} loss {last:.3f}")

    ckpts = CheckpointManager("/ckpts/whisper-tones", keep_n=1, volume=ckpt_vol)
    ckpts.save(train_steps, {"params": state.params})
    return {"first_loss": first, "final_loss": last}


@app.function(tpu=TPU, volumes={"/ckpts": ckpt_vol}, timeout=600)
def transcribe_eval() -> dict:
    """Reload the fine-tuned model in a DIFFERENT container and measure WER
    (end_to_end_check.py semantics)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import whisper
    from modal_examples_tpu.training import CheckpointManager
    from modal_examples_tpu.utils.metrics import word_error_rate

    ckpt_vol.reload()
    cfg = model_config()
    tok, items = make_dataset()
    template = {"params": whisper.init_params(jax.random.PRNGKey(0), cfg)}
    params = CheckpointManager("/ckpts/whisper-tones").restore(template)["params"]

    mels = jnp.asarray(np.stack([m for m, _, _ in items]))
    out = whisper.greedy_transcribe(
        params, mels, cfg, bos_id=tok.bos_id, eos_id=tok.eos_id
    )
    hyps = []
    for row in np.asarray(out):
        ids = [int(t) for t in row if int(t) != tok.eos_id]
        hyps.append(tok.decode(ids))
    refs = [sent for _, _, sent in items]
    wer = word_error_rate(refs, hyps)
    for r, h in zip(refs, hyps):
        print(f"  ref={r!r}  hyp={h!r}")
    return {"wer": wer, "n": len(refs)}


@app.function(tpu=TPU, volumes={"/ckpts": ckpt_vol}, timeout=600)
def aligned_transcribe() -> dict:
    """Word-level timestamps via cross-attention DTW — the
    audio-to-text/whisperx_transcribe.py capability, using Whisper's OWN
    alignment mechanism (models.whisper.align_tokens) instead of
    whisperx's bolted-on wav2vec2 aligner. Each word here is a 1 s tone,
    so the true spans are known: word k lives in [k, k+1] seconds."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import whisper
    from modal_examples_tpu.training import CheckpointManager

    ckpt_vol.reload()
    cfg = model_config()
    tok, items = make_dataset()
    template = {"params": whisper.init_params(jax.random.PRNGKey(0), cfg)}
    params = CheckpointManager("/ckpts/whisper-tones").restore(template)["params"]

    mels = jnp.asarray(np.stack([m for m, _, _ in items]))
    n_monotone = n_localized = 0
    out = []
    for i, (_, ids, sent) in enumerate(items):
        seq = jnp.asarray([ids], jnp.int32)
        times = whisper.align_tokens(params, mels[i : i + 1], seq, cfg)
        # ids = [bos, w1, w2, eos]; the words are positions 1..2
        words = [
            {"word": w, "start": float(times[0, 1 + k, 0]),
             "end": float(times[0, 1 + k, 1])}
            for k, w in enumerate(sent.split())
        ]
        out.append({"text": sent, "words": words})
        mids = [(w["start"] + w["end"]) / 2 for w in words]
        if mids[1] > mids[0]:
            n_monotone += 1
        if 0.0 <= mids[0] <= 1.0 and 1.0 <= mids[1] <= 2.0:
            n_localized += 1
        print(sent, [(w["word"], round(w["start"], 2), round(w["end"], 2))
                     for w in words])
    return {
        "segments": out, "n": len(items),
        "n_monotone": n_monotone, "n_localized": n_localized,
    }


@app.local_entrypoint()
def main(train_steps: int = 150):
    result = train.remote(train_steps)
    print("train:", result)
    assert result["final_loss"] < result["first_loss"]
    eval_out = transcribe_eval.remote()
    print("eval:", eval_out)
    # the reference's e2e bar after 1 step is WER < 1.0; after overfitting
    # the tiny task we expect far better
    assert eval_out["wer"] < 1.0, eval_out

    aligned = aligned_transcribe.remote()
    # word order is always recovered; absolute localization quality tracks
    # model quality (the overfit test-tiny model localizes a subset
    # cleanly — real checkpoints through load_hf_weights use the same
    # align_tokens path at full fidelity)
    assert aligned["n_monotone"] == aligned["n"], aligned
    assert aligned["n_localized"] >= aligned["n"] // 2, aligned
    print(
        f"word timestamps: {aligned['n_monotone']}/{aligned['n']} ordered, "
        f"{aligned['n_localized']}/{aligned['n']} localized to the true "
        "second"
    )
