# # TPU fallback lists
#
# Counterpart of 06_gpu_and_ml/gpu_fallbacks.py:20-23 — request an ordered
# preference list of accelerators; the scheduler takes the first with
# capacity. TPU-natively the list is topology-aware: each spec carries its
# generation, chip count, hosts, and HBM.

import modal_examples_tpu as mtpu
from modal_examples_tpu.core.resources import parse_tpu_request

app = mtpu.App("example-tpu-fallbacks")


@app.function(tpu=["v5e-8", "v4-8", "v5e"])
def chips_info() -> dict:
    import os

    spec = os.environ.get("MTPU_TPU_SPEC", "none")
    return {"granted_spec": spec}


@app.local_entrypoint()
def main():
    specs = parse_tpu_request(["v5e-8", "v4-8", "v5e"])
    for s in specs:
        print(
            f"candidate {s}: {s.chips} chips / {s.hosts} host(s), "
            f"{s.hbm_gib_per_chip} GiB HBM/chip, "
            f"{s.bf16_tflops_per_chip} bf16 TFLOP/s/chip"
        )
    assert [str(s) for s in specs] == ["v5e-8", "v4-8", "v5e-1"]
    print("preference order preserved; scheduler tries each in turn")
