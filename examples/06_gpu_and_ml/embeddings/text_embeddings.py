# # Text embeddings service (BGE on TPU)
#
# TPU-native counterpart of the reference's embeddings stack: where
# text_embeddings_inference.py:36-50 subprocess-spawns the TEI Rust/CUDA
# server and amazon_embeddings.py fans batches at it, this serves a JAX BGE
# encoder directly: an `@app.cls` with `@enter` weight load (load-once-serve-
# many), `@mtpu.batched` dynamic batching feeding fixed-shape TPU batches,
# `@mtpu.concurrent` input concurrency, and a web endpoint.
#
# Serve:  tpurun serve examples/06_gpu_and_ml/embeddings/text_embeddings.py
# Run:    tpurun run   examples/06_gpu_and_ml/embeddings/text_embeddings.py

import os

import modal_examples_tpu as mtpu

MODEL_DIR = os.environ.get("MTPU_MODEL_DIR")  # HF bge-small-en checkout
TPU = os.environ.get("MTPU_TPU", "") or None
MAX_SEQ = 128
MAX_BATCH = 32  # the ONE compiled batch shape: warmup, padding, batcher agree

app = mtpu.App("example-text-embeddings")

weights_vol = mtpu.Volume.from_name("bge-weights", create_if_missing=True)


def _build_model():
    import jax

    from modal_examples_tpu.models import bert

    if MODEL_DIR:
        cfg = bert.BertConfig.bge_small_en()
        params = bert.load_hf_weights(MODEL_DIR, cfg)
    else:  # dummy-weights dev mode (very_large_models.py:2-3 analog)
        cfg = bert.BertConfig.tiny()
        params = bert.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@app.cls(
    tpu=TPU,
    volumes={"/models": weights_vol},
    scaledown_window=300,
    max_containers=20,  # fleet scaling limits per text_embeddings_inference.py:79-87
    timeout=600,
)
@mtpu.concurrent(max_inputs=10)
class Embedder:
    @mtpu.enter()
    def load(self):
        import jax

        from modal_examples_tpu.models import bert
        from modal_examples_tpu.utils.tokenizer import load_tokenizer

        self.cfg, self.params = _build_model()
        self.tokenizer = load_tokenizer(MODEL_DIR)
        self.bert = bert
        self.jax = jax
        self._embed = jax.jit(
            lambda p, t, m: bert.embed(p, t, m, self.cfg)
        )
        # warmup compile at the one fixed batch shape
        import numpy as np

        t = np.zeros((MAX_BATCH, MAX_SEQ), np.int32)
        from modal_examples_tpu.utils.sync import force

        # force(): block_until_ready is a no-op on the tunneled axon backend
        force(self._embed(self.params, t, np.ones_like(t)))

    def _encode_batch(self, texts: list[str]):
        import numpy as np

        if hasattr(self.tokenizer, "encode_batch"):
            # one native call builds the padded id/mask matrices
            toks, mask = self.tokenizer.encode_batch(texts, MAX_SEQ)
            toks = toks % self.cfg.vocab_size
        else:
            toks = np.full((len(texts), MAX_SEQ), 0, np.int32)
            mask = np.zeros((len(texts), MAX_SEQ), np.int32)
            for i, s in enumerate(texts):
                ids = self.tokenizer.encode(s)[:MAX_SEQ]
                toks[i, : len(ids)] = ids
                mask[i, : len(ids)] = 1
        # always pad to the single compiled shape: no serve-time retraces
        assert len(texts) <= MAX_BATCH, (len(texts), MAX_BATCH)
        pad_to = MAX_BATCH
        if pad_to != len(texts):
            toks = np.pad(toks, ((0, pad_to - len(texts)), (0, 0)))
            mask = np.pad(mask, ((0, pad_to - len(texts)), (0, 0)))
        out = self._embed(self.params, toks, mask)
        return [list(map(float, row)) for row in out[: len(texts)]]

    @mtpu.method()
    def embed_one(self, text: str) -> list[float]:
        return self._encode_batch([text])[0]

    @mtpu.batched(max_batch_size=MAX_BATCH, wait_ms=50)
    @mtpu.method()
    def embed(self, texts: list[str]) -> list[list[float]]:
        """Dynamic batching: concurrent callers' singles coalesce into one
        fixed-shape TPU batch (batched_whisper.py:127 pattern)."""
        return self._encode_batch(texts)


@app.function()
@mtpu.fastapi_endpoint(method="POST")
def embeddings(texts: list[str]) -> dict:
    """HTTP surface (TEI's /embed analog): POST {"texts": [...]}."""
    vecs = list(Embedder().embed.map(texts))
    return {"embeddings": vecs, "dim": len(vecs[0]) if vecs else 0}


@app.local_entrypoint()
def main():
    import math

    emb = Embedder()
    sents = [
        "The TPU systolic array multiplies matrices.",
        "Matrix multiplication runs on the MXU.",
        "I had soup for lunch today.",
    ]
    vecs = list(emb.embed.map(sents))
    def cos(a, b):
        return sum(x * y for x, y in zip(a, b))

    sim_close = cos(vecs[0], vecs[1])
    sim_far = cos(vecs[0], vecs[2])
    print(f"dim={len(vecs[0])}  sim(0,1)={sim_close:.3f}  sim(0,2)={sim_far:.3f}")
    for v in vecs:
        assert abs(math.fsum(x * x for x in v) - 1.0) < 1e-3  # normalized
    print("embeddings OK")
