# # Semantic vector search: embed a corpus, serve top-k queries
#
# TPU-native counterpart of the reference's vector-search tier:
# 06_gpu_and_ml/embeddings/qdrant.py (a hosted vector DB fed by TEI
# embeddings) and embeddings/wikipedia/main.py (embed a corpus at scale,
# then query it). Zero egress and no vector-DB binary, so the index IS
# the TPU-friendly thing: an [N, D] matrix of normalized embeddings on a
# Volume, and top-k search is ONE batched matmul + top_k — exactly the
# shape the MXU wants (a brute-force exact search outperforms ANN up to
# millions of vectors on this hardware class).
#
# The embedder is the framework's own models.bert encoder (the
# BGE/TEI-analog the embeddings examples serve).
#
# Run: tpurun run examples/06_gpu_and_ml/embeddings/vector_search.py

import os
import pickle

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-vector-search")
index_vol = mtpu.Volume.from_name("vector-index", create_if_missing=True)

CORPUS = [
    "the serving engine batches decode steps across fixed slots",
    "paged attention reads exactly the context pages it needs",
    "lora adapters fine tune attention projections cheaply",
    "checkpoints resume training after interruptions",
    "the flash attention kernel tiles queries into vmem blocks",
    "tensor parallel sharding splits matmuls across chips",
    "volumes persist model weights between containers",
    "the scheduler scales containers with request load",
    "speculative decoding drafts tokens and verifies in one pass",
    "whisper transcribes audio with an encoder decoder transformer",
    "rectified flow generates images in a few euler steps",
    "the prefix cache shares prompt kv across requests",
]


def _embedder():
    """Tokenize-and-embed through models.bert with the framework's
    deterministic fallback tokenizer (utils.tokenizer.load_tokenizer —
    the same one the sibling embeddings example uses; swap
    load_hf_weights + a real WordPiece tokenizer for production).
    bert.embed returns L2-normalized vectors."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import bert
    from modal_examples_tpu.utils.tokenizer import load_tokenizer

    import dataclasses

    # mean pooling: with RANDOM weights the CLS state barely depends on
    # the input (cosine ~0.9999 between any two texts); mean-over-tokens
    # keeps cheap mode discriminative. Real BGE checkpoints use cls — set
    # it back when loading real weights.
    cfg = dataclasses.replace(bert.BertConfig.tiny(), pooling="mean")
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    tok = load_tokenizer(None)
    embed = jax.jit(lambda t, m: bert.embed(params, t, m, cfg))

    def encode(texts: list[str], max_len: int = 64):
        ids, mask = tok.encode_batch(texts, max_len)
        ids = np.asarray(ids) % cfg.vocab_size
        return np.asarray(embed(jnp.asarray(ids), jnp.asarray(mask)))

    return encode


@app.function(tpu=TPU, volumes={"/index": index_vol}, timeout=600)
def build_index() -> dict:
    """Embed the corpus into the [N, D] matrix (wikipedia/main.py's
    embed-everything job, minus the 575k tok/s fleet)."""
    encode = _embedder()
    vecs = encode(CORPUS)
    with open("/index/vectors.pkl", "wb") as f:
        pickle.dump({"vectors": vecs, "texts": CORPUS}, f)
    index_vol.commit()
    return {"indexed": len(CORPUS), "dim": int(vecs.shape[1])}


@app.cls(tpu=TPU, volumes={"/index": index_vol}, scaledown_window=300)
class VectorSearch:
    @mtpu.enter()
    def load(self):
        import jax

        if not TPU:
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        index_vol.reload()
        with open("/index/vectors.pkl", "rb") as f:
            idx = pickle.load(f)
        self.vectors = idx["vectors"]  # [N, D] normalized
        self.texts = idx["texts"]
        self.encode = _embedder()

    @mtpu.method()
    def search(self, query: str, k: int = 3) -> list[dict]:
        """Cosine top-k: one matvec against the whole index."""
        import numpy as np

        q = self.encode([query])[0]
        scores = self.vectors @ q  # [N] — the MXU-shaped search
        top = np.argsort(-scores)[:k]
        return [
            {"text": self.texts[i], "score": float(scores[i])} for i in top
        ]


@app.local_entrypoint()
def main():
    print("building index:", build_index.remote())
    vs = VectorSearch()
    # cheap mode runs RANDOM weights, so similarity reflects token and
    # word-order overlap rather than meaning — real semantic neighbors
    # need bert.load_hf_weights with a published BGE checkpoint (the
    # pipeline is identical either way)
    for query, expect_word in [
        ("whisper transcribes audio", "whisper"),
        ("rectified flow euler steps images", "images"),
        ("tensor parallel sharding chips", "sharding"),
    ]:
        hits = vs.search.remote(query, k=3)
        print(f"{query!r}:")
        for h in hits:
            print(f"   {h['score']:.3f}  {h['text']}")
        assert any(expect_word in h["text"] for h in hits), (query, hits)
    print("semantic neighbors retrieved for all queries")
