# # Embed a huge dataset with a spawn queue and an autoscaled fleet
#
# The counterpart of the reference's embeddings/amazon_embeddings.py (30M
# Amazon reviews at 575k tok/s, :6): a launcher function chunks the corpus
# and `.spawn`s one embedding call per batch from a thread pool
# (:108-112) — the spawned calls queue up while the autoscaler grows the
# embedder fleet (up to max_containers), and the client gathers results by
# FunctionCall id later, detached from the launcher.
#
# Cheap mode embeds a synthetic corpus with a tiny random-weight encoder;
# `down_scale`-style sizing (amazon_embeddings.py:55) keeps CI fast. The
# job shape — launcher → spawn-per-batch → gather — is the real pattern.

import time
from concurrent.futures import ThreadPoolExecutor

import modal_examples_tpu as mtpu

app = mtpu.App("example-mass-embeddings")

BATCH_SIZE = 16


@app.function(max_containers=4, timeout=600)
def embed_batch(batch_id: int, texts: list[str]) -> dict:
    """One fleet worker input: encode a batch, return stats + vectors.

    (The real deployment calls the Embedder Cls from text_embeddings.py;
    this inlines a tiny JAX encoder so the example is self-contained.)
    """
    import jax
    import jax.numpy as jnp

    from modal_examples_tpu.models import bert
    from modal_examples_tpu.utils.tokenizer import load_tokenizer

    cfg = bert.BertConfig.tiny()
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    tok = load_tokenizer(None)

    ids = [tok.encode(t)[:32] for t in texts]
    n_tokens = sum(len(i) for i in ids)
    width = max(len(i) for i in ids)
    padded = jnp.array([i + [0] * (width - len(i)) for i in ids])
    mask = jnp.array([[1] * len(i) + [0] * (width - len(i)) for i in ids])
    vecs = bert.embed(params, padded, mask, cfg)
    return {
        "batch_id": batch_id,
        "n_texts": len(texts),
        "n_tokens": n_tokens,
        "dim": int(vecs.shape[-1]),
    }


@app.function(timeout=3600)
def launch_job(n_docs: int = 48) -> list[str]:
    """The detached launcher (amazon_embeddings.py:56-60): chunk the corpus,
    spawn a call per batch from a thread pool, return the call ids."""
    corpus = [
        f"review {i}: the product arrived quickly and works as described"
        for i in range(n_docs)
    ]
    batches = [
        (i // BATCH_SIZE, corpus[i : i + BATCH_SIZE])
        for i in range(0, len(corpus), BATCH_SIZE)
    ]
    t0 = time.time()
    with ThreadPoolExecutor(max_workers=8) as pool:
        calls = list(
            pool.map(lambda b: embed_batch.spawn(b[0], b[1]), batches)
        )
    print(
        f"spawned {len(calls)} batches ({n_docs} docs) in "
        f"{time.time() - t0:.2f}s; fleet is processing"
    )
    return [c.object_id for c in calls]


@app.local_entrypoint()
def main(n_docs: int = 48):
    # the launcher itself runs remotely (run with --detach for long jobs)
    call_ids = launch_job.remote(n_docs)

    # gather later, by id — the spawn queue holds results for the client
    calls = [mtpu.FunctionCall.from_id(cid) for cid in call_ids]
    t0 = time.time()
    results = mtpu.gather(*calls)
    dt = time.time() - t0

    total_docs = sum(r["n_texts"] for r in results)
    total_tokens = sum(r["n_tokens"] for r in results)
    print(
        f"embedded {total_docs} docs / {total_tokens} tokens across "
        f"{len(results)} batches in {dt:.2f}s "
        f"({total_tokens / max(dt, 1e-9):.0f} tok/s)"
    )
    assert total_docs == n_docs
    assert all(r["dim"] > 0 for r in results)
    print("mass embeddings job OK")
