# # Max-throughput batch inference
#
# Counterpart of the reference's llm-serving/vllm_throughput.py (batch
# pipeline with throughput claims :26-37) and trtllm_throughput.py's
# measured tok/s print (:379): saturate the continuous-batching engine with
# a backlog of prompts and report aggregate input/output tokens per second.
#
# MTPU_MODEL=llama2-7b (+ a TPU) benches the real thing; the default tiny
# model exercises the measurement path anywhere.
#
# Run: tpurun run examples/06_gpu_and_ml/llm-serving/throughput_bench.py

import os
import time

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None
MODEL = os.environ.get("MTPU_MODEL", "tiny")

app = mtpu.App("example-llm-throughput")


@app.function(tpu=TPU, timeout=3600)
def bench(n_requests: int = 16, max_tokens: int = 32) -> dict:
    from modal_examples_tpu.serving import SamplingParams, build_engine

    engine = build_engine(
        MODEL,
        max_slots=8 if MODEL != "tiny" else 4,
        max_model_len=512 if MODEL != "tiny" else 128,
        prefill_buckets=(64, 128, 256),
    ).start()
    prompt = "Summarize the following filing: revenue grew due to " * 3
    params = SamplingParams(max_tokens=max_tokens, temperature=1.0)

    # warmup compiles
    for _ in engine.stream(engine.submit(prompt, SamplingParams(max_tokens=4))):
        pass

    base_out = engine.stats.generated_tokens
    base_in = engine.stats.prompt_tokens
    t0 = time.monotonic()
    reqs = [engine.submit(prompt, params) for _ in range(n_requests)]
    for r in reqs:
        for _ in engine.stream(r):
            pass
    dt = time.monotonic() - t0
    out_toks = engine.stats.generated_tokens - base_out
    in_toks = engine.stats.prompt_tokens - base_in
    engine.stop()
    return {
        "model": MODEL,
        "requests": n_requests,
        "input_tok_s": round(in_toks / dt, 1),
        "output_tok_s": round(out_toks / dt, 1),
        "wall_s": round(dt, 2),
    }


@app.local_entrypoint()
def main(n_requests: int = 16, max_tokens: int = 32):
    out = bench.remote(n_requests, max_tokens)
    print(
        f"{out['model']}: {out['input_tok_s']} input tok/s, "
        f"{out['output_tok_s']} output tok/s over {out['requests']} requests "
        f"({out['wall_s']}s)"
    )
    assert out["output_tok_s"] > 0
