# # OpenAI-compatible LLM serving on TPU
#
# The north-star serving example — the TPU-native counterpart of the
# reference's 06_gpu_and_ml/llm-serving/vllm_inference.py (structure cited
# per SURVEY.md §3.2). Where the reference subprocess-spawns `vllm serve`
# (CUDA paged attention + CUDA graphs), this serves through our own JAX
# engine: continuous batching over fixed decode slots, Pallas ragged paged
# attention, sampling fused into the jitted decode step.
#
# Deploy:  tpurun serve examples/06_gpu_and_ml/llm-serving/llm_inference.py
# Client:  tpurun run  examples/06_gpu_and_ml/llm-serving/llm_inference.py
#
# FAST_BOOT analog (vllm_inference.py:85-101): MTPU_MODEL=tiny serves a tiny
# random-weight model (the dummy-weights dev mode, very_large_models.py:2-3);
# point MTPU_MODEL_DIR at an HF llama checkout for real weights.

import json
import os
import time
import urllib.request

import modal_examples_tpu as mtpu

MODEL = os.environ.get("MTPU_MODEL", "tiny")
MODEL_DIR = os.environ.get("MTPU_MODEL_DIR")  # HF safetensors dir on a Volume
PORT = int(os.environ.get("MTPU_PORT", "8000"))
# resource spec; MTPU_TPU="" runs the server container on CPU (dev mode)
TPU = os.environ.get("MTPU_TPU", "v5e-1") or None
# tensor parallelism: one flag on the same engine, like the reference's
# --tensor-parallel-size (vllm_inference.py:179-180). MTPU_TP=2 shards
# weights (Megatron layout) + the paged KV cache (by kv head) over a
# "tensor" mesh axis; XLA inserts the ICI collectives.
TP = int(os.environ.get("MTPU_TP", "1"))
# speculative decoding: draft-model gamma, like the reference's
# --speculative-config (vllm_inference.py:196-205). MTPU_SPEC_GAMMA=4 with
# MTPU_SPEC_DRAFT naming a preset enables it; point MTPU_SPEC_DRAFT_DIR at
# an HF checkout for real draft weights. Draft and target must share a
# vocabulary (the engine validates).
SPEC_GAMMA = int(os.environ.get("MTPU_SPEC_GAMMA", "0"))
SPEC_DRAFT = os.environ.get("MTPU_SPEC_DRAFT", "tiny")
SPEC_DRAFT_DIR = os.environ.get("MTPU_SPEC_DRAFT_DIR")
# weight-only quantization (the bitsandbytes/unsloth 4-bit analog):
# MTPU_QUANT=int8|int4 halves/quarters weight HBM traffic and composes
# with MTPU_TP (quantized trees shard under tensor parallelism)
QUANT = os.environ.get("MTPU_QUANT") or None
MINUTES = 60

app = mtpu.App("example-llm-inference")

# HF weights + XLA compile cache live on Volumes, like the reference's
# huggingface-cache + vllm-cache volumes (vllm_inference.py:77-81)
hf_cache_vol = mtpu.Volume.from_name("huggingface-cache", create_if_missing=True)
compile_cache_vol = mtpu.Volume.from_name("xla-compile-cache", create_if_missing=True)

image = (
    mtpu.Image.tpu_base()
    .env({"JAX_COMPILATION_CACHE_DIR": "/root/.cache/xla"})
)


@app.server(
    port=PORT,
    tpu=TPU,
    image=image,
    volumes={
        "/root/.cache/huggingface": hf_cache_vol,
        "/root/.cache/xla": compile_cache_vol,
    },
    startup_timeout=20 * MINUTES,
    scaledown_window=15 * MINUTES,
    target_concurrency=100,
    unauthenticated=True,
)
class LLMServer:
    @mtpu.enter()
    def start(self):
        import jax

        # persistent compile cache: the single biggest cold-start lever on
        # TPU (the trtllm "engine build" / vllm-cache analog)
        try:
            jax.config.update(
                "jax_compilation_cache_dir",
                os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/xla-cache"),
            )
        except Exception:
            pass
        from modal_examples_tpu.serving import OpenAIServer, build_engine

        engine_kw = {}
        if TP > 1:
            from modal_examples_tpu.parallel import make_mesh

            engine_kw["mesh"] = make_mesh(
                {"tensor": TP}, devices=jax.devices()[:TP]
            )
        if SPEC_GAMMA > 0:
            engine_kw["speculative"] = (SPEC_DRAFT, SPEC_GAMMA)
            if SPEC_DRAFT_DIR:
                engine_kw["draft_model_dir"] = SPEC_DRAFT_DIR
        engine = build_engine(
            MODEL,
            model_dir=MODEL_DIR,
            max_slots=8 if MODEL != "tiny" else 4,
            max_model_len=1024 if MODEL != "tiny" else 128,
            quantization=QUANT,
            **engine_kw,
        )
        self.server = OpenAIServer(engine, model_name=MODEL, port=PORT)
        self.server.start()  # replica advertised once the port accepts

    @mtpu.exit()
    def shutdown(self):
        self.server.stop()


# ## Client — health-check then a real request, like the reference's
# local_entrypoint smoke test (vllm_inference.py:243-345)


@app.local_entrypoint()
def main(prompt: str = "A neutron star is", max_tokens: int = 32, stream: bool = False):
    url = LLMServer.serve()
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"{url}/health", timeout=2) as r:
                if json.load(r).get("status") == "ok":
                    break
        except Exception:
            time.sleep(1)
    else:
        raise TimeoutError("server never became healthy")
    print(f"server healthy at {url}")

    body = json.dumps(
        {
            "messages": [{"role": "user", "content": prompt}],
            "max_tokens": max_tokens,
            "temperature": 0.8,
            "stream": stream,
        }
    ).encode()
    req = urllib.request.Request(
        f"{url}/v1/chat/completions",
        data=body,
        headers={"content-type": "application/json"},
    )
    t0 = time.time()
    with urllib.request.urlopen(req) as r:
        if stream:
            for line in r:
                line = line.decode().strip()
                if line.startswith("data: ") and line != "data: [DONE]":
                    delta = json.loads(line[6:])["choices"][0]["delta"]
                    print(delta.get("content", ""), end="", flush=True)
            print()
        else:
            out = json.load(r)
            print("completion:", repr(out["choices"][0]["message"]["content"]))
            print("usage:", out["usage"])
    print(f"round-trip: {time.time() - t0:.2f}s")
    LLMServer.stop()
