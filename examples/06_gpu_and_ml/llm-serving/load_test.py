# # Load-testing the OpenAI-compatible server
#
# Counterpart of the reference's openai_compatible/load_test.py +
# locustfile.py (locust workers driving the served API) and
# trtllm_latency.py's round-trip target (:10-22): concurrent client threads
# hit /v1/chat/completions over HTTP and report throughput + latency
# percentiles. No locust dependency — threads and a shared histogram.
#
# Run: tpurun run examples/06_gpu_and_ml/llm-serving/load_test.py

import json
import os
import threading
import time
import urllib.request

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-llm-load-test")


@app.function(tpu=TPU, timeout=1800)
def run_load_test(
    users: int = 4, requests_per_user: int = 3, max_tokens: int = 8
) -> dict:
    import urllib.request  # submodule import must happen in THIS process

    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine, OpenAIServer

    engine = LLMEngine(
        llama.LlamaConfig.tiny(), max_slots=4, max_model_len=128,
        prefill_buckets=(32, 64),
    )
    server = OpenAIServer(engine, model_name="load-test", host="127.0.0.1", port=0)
    server.start()
    url = f"http://127.0.0.1:{server.port}/v1/chat/completions"

    latencies: list[float] = []
    errors: list[str] = []
    lock = threading.Lock()

    def user(uid: int):
        for i in range(requests_per_user):
            body = json.dumps(
                {
                    "messages": [{"role": "user", "content": f"u{uid} r{i}"}],
                    "max_tokens": max_tokens,
                    "temperature": 1.0,
                }
            ).encode()
            req = urllib.request.Request(
                url, data=body, headers={"content-type": "application/json"}
            )
            t0 = time.monotonic()
            try:
                with urllib.request.urlopen(req, timeout=120) as r:
                    json.load(r)
                with lock:
                    latencies.append(time.monotonic() - t0)
            except Exception as e:
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")

    # warmup (compile)
    user(-1)
    latencies.clear()

    t0 = time.monotonic()
    threads = [threading.Thread(target=user, args=(u,)) for u in range(users)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    server.stop()

    latencies.sort()
    n = len(latencies)
    pct = lambda p: round(latencies[min(int(p * n), n - 1)], 3) if n else None
    return {
        "completed": n,
        "errors": errors[:5],
        "rps": round(n / wall, 2),
        "p50_s": pct(0.50),
        "p95_s": pct(0.95),
        "tokens_per_s": round(engine.stats.tokens_per_second(), 1),
    }


@app.local_entrypoint()
def main(users: int = 4):
    out = run_load_test.remote(users)
    print(
        f"{out['completed']} requests, {out['rps']} req/s, "
        f"p50={out['p50_s']}s p95={out['p95_s']}s, errors={len(out['errors'])}"
    )
    assert out["completed"] == users * 3 and not out["errors"], out
