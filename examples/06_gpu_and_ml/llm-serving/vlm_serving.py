# # Vision-language serving: images in, streamed text out
#
# The TPU-native counterpart of the reference's VLM serving examples
# (06_gpu_and_ml/llm-serving/sglang_vlm.py — a Qwen-VL OpenAI endpoint via
# SGLang CUDA; chat_with_pdf_vision.py — image+text chat), built on our own
# stack end to end: a CLIP-style ViT tower + LLaVA projector (models.vlm)
# feeds projected patch embeddings into the llama engine's prefill as the
# first n_image_tokens positions, after which paged decode is completely
# unchanged — image tokens are just KV cache entries.
#
# Serve:   tpurun serve examples/06_gpu_and_ml/llm-serving/vlm_serving.py
# Client:  tpurun run   examples/06_gpu_and_ml/llm-serving/vlm_serving.py
#
# The OpenAI endpoint accepts standard multimodal content parts; images ride
# data: URIs (inline base64 — the server never fetches URLs). Cheap mode
# (default) serves a tiny random-weight model; point MTPU_MODEL_DIR /
# MTPU_VISION_DIR at HF checkouts (llama + CLIPVisionModel/LLaVA projector
# safetensors) for real weights.

import base64
import io
import json
import os
import time
import urllib.request

import modal_examples_tpu as mtpu

MODEL = os.environ.get("MTPU_MODEL", "tiny")
MODEL_DIR = os.environ.get("MTPU_MODEL_DIR")
VISION_DIR = os.environ.get("MTPU_VISION_DIR")  # CLIPVisionModel safetensors
PORT = int(os.environ.get("MTPU_PORT", "8000"))
TPU = os.environ.get("MTPU_TPU", "v5e-1") or None
MINUTES = 60

app = mtpu.App("example-vlm-serving")

hf_cache_vol = mtpu.Volume.from_name("huggingface-cache", create_if_missing=True)
compile_cache_vol = mtpu.Volume.from_name("xla-compile-cache", create_if_missing=True)

image = (
    mtpu.Image.tpu_base()
    .env({"JAX_COMPILATION_CACHE_DIR": "/root/.cache/xla"})
)


@app.server(
    port=PORT,
    tpu=TPU,
    image=image,
    volumes={
        "/root/.cache/huggingface": hf_cache_vol,
        "/root/.cache/xla": compile_cache_vol,
    },
    startup_timeout=20 * MINUTES,
    scaledown_window=15 * MINUTES,
    target_concurrency=100,
    unauthenticated=True,
)
class VLMServer:
    @mtpu.enter()
    def start(self):
        import jax

        from modal_examples_tpu.models import llama, vlm
        from modal_examples_tpu.serving import LLMEngine, OpenAIServer

        if MODEL_DIR:
            lcfg = llama.LlamaConfig.from_hf_config(f"{MODEL_DIR}/config.json")
        else:
            lcfg = llama.LlamaConfig.tiny()
        if VISION_DIR:
            vcfg = vlm.VLMConfig(
                vision=vlm.ViTConfig.clip_vit_l_14(), llm_dim=lcfg.dim
            )
            vparams = vlm.load_hf_vision_weights(VISION_DIR, vcfg)
        else:
            # dummy-weights dev mode (the reference's APP_USE_DUMMY_WEIGHTS
            # pattern, very_large_models.py:2-3)
            vcfg = vlm.VLMConfig(
                vision=vlm.ViTConfig.tiny(), llm_dim=lcfg.dim
            )
            vparams = vlm.init_vision_params(jax.random.PRNGKey(1), vcfg)

        engine = LLMEngine(
            lcfg,
            model_dir=MODEL_DIR,
            max_slots=8 if MODEL_DIR else 4,
            max_model_len=1024 if MODEL_DIR else 128,
            prefill_buckets=(128, 256, 512, 1024) if MODEL_DIR else (32, 64),
            vision=(vcfg, vparams),
        )
        self.server = OpenAIServer(engine, model_name=f"{MODEL}-vlm", port=PORT)
        self.server.start()

    @mtpu.exit()
    def shutdown(self):
        self.server.stop()


# ## Client — post a generated image as a data: URI content part


def _png_data_uri() -> str:
    """A tiny synthetic image (no egress): colored gradient PNG."""
    import numpy as np
    from PIL import Image

    h = w = 64
    y, x = np.mgrid[0:h, 0:w]
    arr = np.stack(
        [255 * x / w, 255 * y / h, 128 + 64 * np.sin(x / 7)], axis=-1
    ).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()


@app.local_entrypoint()
def main(prompt: str = "Describe this image.", max_tokens: int = 32):
    url = VLMServer.serve()
    deadline = time.time() + 180
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"{url}/health", timeout=2) as r:
                if json.load(r).get("status") == "ok":
                    break
        except Exception:
            time.sleep(1)
    else:
        raise TimeoutError("server never became healthy")
    print(f"server healthy at {url}")

    body = json.dumps(
        {
            "messages": [
                {
                    "role": "user",
                    "content": [
                        {"type": "text", "text": prompt},
                        {
                            "type": "image_url",
                            "image_url": {"url": _png_data_uri()},
                        },
                    ],
                }
            ],
            "max_tokens": max_tokens,
            "temperature": 0.0,
        }
    ).encode()
    req = urllib.request.Request(
        f"{url}/v1/chat/completions",
        data=body,
        headers={"content-type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        out = json.loads(r.read())
    print("assistant:", out["choices"][0]["message"]["content"])
    print("usage:", out["usage"])
