# ---
# env: {"MTPU_TRAIN_STEPS": "400"}
# timeout: 800
# ---
# # ControlNet-style structure-conditioned generation
#
# TPU-native counterpart of the reference's
# 06_gpu_and_ml/controlnet_gradio_demos.py (diffusers ControlNet on torch
# CUDA: generate images that FOLLOW a supplied edge/pose layout). Here the
# conditioning pathway is built into the framework's own DiT
# (models.diffusion): the control map patchifies like the image and enters
# through a ZERO-INITIALIZED projection — the ControlNet recipe, where a
# fresh model provably ignores the control and training grows the
# conditioning from the unconditional behavior.
#
# Cheap mode trains from scratch on synthetic outline->filled-shape scenes
# (zero egress) and then generates images for NEW layouts the model never
# saw; the service endpoint takes a layout and returns the generated image
# (base64 PNG), the reference demo's API shape minus the Gradio skin
# (UIs are cosmetic per OUT_OF_SCOPE.md).
#
# Run: tpurun run examples/06_gpu_and_ml/stable_diffusion/controlnet.py

import os
import pickle

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None
TRAIN_STEPS = int(os.environ.get("MTPU_TRAIN_STEPS", "400"))

app = mtpu.App("example-controlnet")
model_vol = mtpu.Volume.from_name("controlnet-dit", create_if_missing=True)

SIZE = 16


def _cfg():
    from modal_examples_tpu.models import diffusion

    return diffusion.DiTConfig(
        img_size=SIZE, patch=2, dim=96, n_layers=3, n_heads=4,
        text_dim=16, text_len=4, control=True,
    )


def _scene_batch(jax, jnp, key, bs=16):
    """Outline control -> filled-box target (the canny-edge -> image task
    at demo scale)."""
    ks = jax.random.split(key, 2)
    cx = jax.random.randint(ks[0], (bs,), 3, SIZE - 3)
    cy = jax.random.randint(ks[1], (bs,), 3, SIZE - 3)
    yy, xx = jnp.mgrid[0:SIZE, 0:SIZE]
    dx = jnp.abs(xx[None] - cx[:, None, None])
    dy = jnp.abs(yy[None] - cy[:, None, None])
    inside = ((dx <= 3) & (dy <= 3)).astype(jnp.float32)
    outline = (((dx == 3) & (dy <= 3)) | ((dy == 3) & (dx <= 3))).astype(
        jnp.float32
    )
    control = jnp.repeat(outline[:, :, :, None], 3, axis=-1)
    img = jnp.repeat((inside * 2.0 - 1.0)[:, :, :, None], 3, axis=-1)
    return img, control, inside


@app.function(tpu=TPU, volumes={"/models": model_vol}, timeout=3600)
def train(steps: int = TRAIN_STEPS) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from modal_examples_tpu.models import diffusion

    cfg = _cfg()
    params = diffusion.init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(2e-3)
    opt_state = opt.init(params)
    txt = jnp.zeros((16, cfg.text_len, cfg.text_dim))

    @jax.jit
    def step(params, opt_state, key):
        k1, k2 = jax.random.split(key)
        img, control, _ = _scene_batch(jax, jnp, k1)
        loss, grads = jax.value_and_grad(
            lambda p: diffusion.flow_loss(
                p, k2, img, txt, cfg, control=control, null_prob=0.0
            )
        )(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    key = jax.random.PRNGKey(1)
    for i in range(steps):
        key, sub = jax.random.split(key)
        params, opt_state, loss = step(params, opt_state, sub)
        if i % 100 == 0:
            print(f"train step {i}: loss {float(loss):.4f}")
    with open("/models/controlnet.pkl", "wb") as f:
        pickle.dump(jax.tree.map(np.asarray, params), f)
    model_vol.commit()
    return {"final_loss": float(loss)}


@app.cls(tpu=TPU, volumes={"/models": model_vol}, scaledown_window=300)
class ControlNet:
    @mtpu.enter()
    def load(self):
        import jax

        if not TPU:
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        import functools

        import jax.numpy as jnp

        from modal_examples_tpu.models import diffusion

        self.cfg = _cfg()
        model_vol.reload()
        with open("/models/controlnet.pkl", "rb") as f:
            self.params = jax.tree.map(jnp.asarray, pickle.load(f))
        self._sample = jax.jit(
            functools.partial(
                diffusion.sample, steps=6, guidance=1.0
            ),
            static_argnames=("cfg",),
        )

    @mtpu.method()
    def generate(self, control: list, seed: int = 0) -> dict:
        """control: [S, S] 0/1 layout -> generated image as base64 PNG."""
        import base64
        import io

        import jax
        import jax.numpy as jnp
        import numpy as np
        from PIL import Image

        ctrl = jnp.repeat(
            jnp.asarray(control, jnp.float32)[None, :, :, None], 3, axis=-1
        )
        txt = jnp.zeros((1, self.cfg.text_len, self.cfg.text_dim))
        out = self._sample(
            self.params, jax.random.PRNGKey(seed), txt, cfg=self.cfg,
            control=ctrl,
        )
        arr = ((np.asarray(out)[0] + 1.0) * 127.5).clip(0, 255).astype(
            np.uint8
        )
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="PNG")
        return {
            "image_png_b64": base64.b64encode(buf.getvalue()).decode(),
            "mean_brightness": float(arr.mean()),
        }


@app.local_entrypoint()
def main(steps: int = TRAIN_STEPS):
    import base64
    import io

    import numpy as np
    from PIL import Image

    print(f"training structure-conditioned DiT ({steps} steps)...")
    print("train:", train.remote(steps))

    # a NEW layout: box outline at a position chosen by hand
    control = np.zeros((SIZE, SIZE), np.float32)
    cx, cy, r = 5, 10, 3
    control[cy - r : cy + r + 1, [cx - r, cx + r]] = 1.0
    control[[cy - r, cy + r], cx - r : cx + r + 1] = 1.0

    net = ControlNet()
    out = net.generate.remote(control.tolist(), seed=3)
    img = np.asarray(
        Image.open(io.BytesIO(base64.b64decode(out["image_png_b64"])))
    ).astype(np.float32) / 255.0
    bright = img.mean(-1)
    yy, xx = np.mgrid[0:SIZE, 0:SIZE]
    inside = (np.abs(xx - cx) <= r) & (np.abs(yy - cy) <= r)
    in_mean, out_mean = bright[inside].mean(), bright[~inside].mean()
    print(f"generated: inside-layout brightness {in_mean:.2f} vs outside "
          f"{out_mean:.2f}")
    assert in_mean > out_mean + 0.2, (in_mean, out_mean)
    print("generation follows the control layout")
