# # Text-to-image generation
#
# TPU-native counterpart of the reference's
# 06_gpu_and_ml/stable_diffusion/text_to_image.py (SD3.5-Large-Turbo served
# by an `@app.cls` with `@enter` pipeline load :92-137, a generate method +
# web endpoint :107-137, few-step sampling :11-13). Here the pipeline is the
# framework's own DiT + rectified flow (the same model family as SD3/Flux),
# text-conditioned through the BERT encoder, trained end-to-end on a
# synthetic color corpus (zero-egress dev mode) and sampled with
# classifier-free guidance in a handful of Euler steps.
#
# Run:   tpurun run examples/06_gpu_and_ml/stable_diffusion/text_to_image.py
# Serve: tpurun serve examples/06_gpu_and_ml/stable_diffusion/text_to_image.py

import os

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-text-to-image")
model_vol = mtpu.Volume.from_name("dit-weights", create_if_missing=True)

COLORS = {
    "red": (1.0, -1.0, -1.0),
    "green": (-1.0, 1.0, -1.0),
    "blue": (-1.0, -1.0, 1.0),
    "yellow": (1.0, 1.0, -1.0),
}
TEXT_LEN = 16


def encode_text(texts: list[str], text_dim: int = 64):
    """Toy per-token text states via hashed byte embeddings (the CLIP/T5
    stand-in; swap in models.bert against real weights)."""
    import numpy as np

    out = np.zeros((len(texts), TEXT_LEN, text_dim), np.float32)
    for i, t in enumerate(texts):
        for j, ch in enumerate(t.encode()[:TEXT_LEN]):
            rng = np.random.default_rng(ch)
            out[i, j] = rng.standard_normal(text_dim) * 0.5
    return out


@app.function(tpu=TPU, volumes={"/models": model_vol}, timeout=3600)
def train(steps: int = 400) -> dict:
    """Pretrain the tiny DiT on solid-color images captioned by color name."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import diffusion
    from modal_examples_tpu.training import (
        CheckpointManager, Trainer, make_optimizer,
    )

    cfg = diffusion.DiTConfig.tiny()
    params = diffusion.init_params(jax.random.PRNGKey(0), cfg)

    names = list(COLORS)
    text_states = jnp.asarray(encode_text(names, cfg.text_dim))

    def make_batch(key, bs=32):
        k1, k2 = jax.random.split(key)
        idx = jax.random.randint(k1, (bs,), 0, len(names))
        base = jnp.asarray([COLORS[n] for n in names])[idx]  # [bs, 3]
        img = jnp.broadcast_to(
            base[:, None, None, :], (bs, cfg.img_size, cfg.img_size, 3)
        )
        img = img + 0.05 * jax.random.normal(k2, img.shape)
        return {"images": img, "text": text_states[idx], "key_idx": idx}

    def loss_fn(p, batch):
        return diffusion.flow_loss(
            p, batch["rng"], batch["images"], batch["text"], cfg
        )

    trainer = Trainer(loss_fn, make_optimizer(2e-3))
    state = trainer.init_state(params)
    key = jax.random.PRNGKey(1)
    first = last = None
    for step in range(steps):
        key, k1, k2 = jax.random.split(key, 3)
        batch = make_batch(k1)
        batch["rng"] = k2
        state, m = trainer.train_step(state, batch)
        last = float(m["loss"])
        first = first if first is not None else last
        if (step + 1) % 100 == 0:
            print(f"step {step + 1} flow loss {last:.4f}")

    CheckpointManager("/models/dit-colors", keep_n=1, volume=model_vol).save(
        steps, {"params": state.params}
    )
    return {"first_loss": first, "final_loss": last}


@app.cls(tpu=TPU, volumes={"/models": model_vol}, timeout=900, scaledown_window=300)
@mtpu.concurrent(max_inputs=8)
class TextToImage:
    @mtpu.enter()
    def load(self):
        import jax

        from modal_examples_tpu.models import diffusion
        from modal_examples_tpu.training import CheckpointManager

        model_vol.reload()
        self.cfg = diffusion.DiTConfig.tiny()
        template = {"params": diffusion.init_params(jax.random.PRNGKey(0), self.cfg)}
        self.params = CheckpointManager("/models/dit-colors").restore(template)[
            "params"
        ]
        self.diffusion = diffusion
        self._sample = jax.jit(
            lambda p, k, txt: diffusion.sample(p, k, txt, self.cfg, steps=8)
        )
        self._seed = [0]

    @mtpu.method()
    def generate(self, prompt: str, batch_size: int = 1) -> list[bytes]:
        """Prompt -> PNG bytes (1-2s/image at SD scale; instant here)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from modal_examples_tpu.utils.images import to_png

        self._seed[0] += 1
        text = jnp.asarray(
            np.repeat(encode_text([prompt], self.cfg.text_dim), batch_size, 0)
        )
        imgs = self._sample(self.params, jax.random.PRNGKey(self._seed[0]), text)
        return [to_png(np.asarray(img)) for img in imgs]


@app.function()
@mtpu.fastapi_endpoint()
def generate_web(prompt: str = "red") -> bytes:
    """GET /generate_web?prompt=blue -> image/png (web UI parity,
    text_to_image.py:228-266)."""
    return TextToImage().generate.remote(prompt)[0]


@app.local_entrypoint()
def main(steps: int = 400):
    import numpy as np

    from modal_examples_tpu.utils.images import from_png

    result = train.remote(steps)
    print("train:", result)
    assert result["final_loss"] < result["first_loss"]

    t2i = TextToImage()
    for prompt in ("red", "blue"):
        png = t2i.generate.remote(prompt, 1)[0]
        img = from_png(png).astype(np.float32) / 255.0
        means = img.mean(axis=(0, 1))
        dominant = ["red", "green", "blue"][int(np.argmax(means))]
        print(f"prompt={prompt!r}: channel means={np.round(means, 2)} -> {dominant}")
        assert dominant == prompt, (prompt, means)
    print("text-to-image conditioning OK")
