# # Image-to-image generation
#
# Counterpart of the reference's stable_diffusion/image_to_image.py: start
# from a source image instead of pure noise — noise it to an intermediate
# flow time t = strength, then integrate the remaining steps under a new
# prompt. Uses the DiT checkpoint trained by text_to_image.py (run that
# first, or this entrypoint trains a quick one).
#
# Run: tpurun run examples/06_gpu_and_ml/stable_diffusion/image_to_image.py

import os
import sys
from pathlib import Path

import modal_examples_tpu as mtpu

sys.path.insert(0, str(Path(__file__).parent))
from text_to_image import COLORS, encode_text, train  # noqa: E402  (shared corpus)

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-image-to-image")
model_vol = mtpu.Volume.from_name("dit-weights", create_if_missing=True)


@app.function(tpu=TPU, volumes={"/models": model_vol}, timeout=900)
def img2img(prompt: str, strength: float = 0.8, seed: int = 0) -> dict:
    """Repaint a source image toward ``prompt``; strength in (0,1] controls
    how much of the source survives (reference semantics)."""
    import jax
    import jax.numpy as jnp

    from modal_examples_tpu.models import diffusion
    from modal_examples_tpu.training import CheckpointManager

    model_vol.reload()
    cfg = diffusion.DiTConfig.tiny()
    template = {"params": diffusion.init_params(jax.random.PRNGKey(0), cfg)}
    params = CheckpointManager("/models/dit-colors").restore(template)["params"]

    # source image: solid green
    src = jnp.broadcast_to(
        jnp.asarray(COLORS["green"]), (1, cfg.img_size, cfg.img_size, 3)
    )
    text = jnp.asarray(encode_text([prompt], cfg.text_dim))

    # noise the source to t = strength, then integrate t: strength -> 0
    key = jax.random.PRNGKey(seed)
    k_noise, k_unused = jax.random.split(key)
    eps = jax.random.normal(k_noise, src.shape)
    t0 = float(strength)
    x = (1 - t0) * src + t0 * eps

    steps = 8
    ts = jnp.linspace(t0, 0.0, steps + 1)
    null = jnp.zeros_like(text)
    for i in range(steps):
        tb = jnp.full((1,), float(ts[i]))
        v_c = diffusion.forward(params, x, tb, text, cfg)
        v_n = diffusion.forward(params, x, tb, null, cfg)
        v = v_n + 3.0 * (v_c - v_n)
        x = x + (float(ts[i + 1]) - float(ts[i])) * v
    x = jnp.clip(x, -1, 1)
    means = [float(m) for m in ((x[0] + 1) / 2).mean(axis=(0, 1))]
    return {"prompt": prompt, "strength": strength, "channel_means": means}


@app.local_entrypoint()
def main():
    model_vol.reload()
    if not any("dit-colors" in p for p in model_vol.listdir("/", recursive=True)):
        print("no DiT checkpoint found; training one first...")
        train.remote(400)

    out = img2img.remote("red", strength=0.9)
    means = out["channel_means"]
    print(f"repainted green -> 'red': channel means {[round(m, 2) for m in means]}")
    assert means[0] > means[1] and means[0] > means[2], means

    # low strength: the source should survive (stay green-dominant)
    weak = img2img.remote("red", strength=0.2)
    wm = weak["channel_means"]
    print(f"strength=0.2 keeps source: {[round(m, 2) for m in wm]}")
    assert wm[1] > wm[2], wm
    print("image-to-image OK")
