# # Streaming transcription
#
# Counterpart of the reference's speech-to-text streaming tier
# (streaming_whisper.py, streaming_parakeet.py — websocket streaming ASR):
# long audio is windowed into chunks, each chunk transcribes as it arrives,
# and partial transcripts stream back — as a `.remote_gen` generator and as
# an SSE web endpoint (07_web/streaming.py:38-45 transport).
#
# Run:   tpurun run examples/06_gpu_and_ml/speech-to-text/streaming_whisper.py
# Serve: tpurun serve ... then curl -N '<url>/transcribe_stream'

import os

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None
CHUNK_SECONDS = 1.0
MEL_FRAMES = 200

app = mtpu.App("example-streaming-whisper")


def _model():
    import dataclasses

    import jax

    from modal_examples_tpu.models import whisper

    cfg = dataclasses.replace(
        whisper.WhisperConfig.test_tiny(), vocab_size=16, n_text_ctx=8
    )
    params = whisper.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@app.function(tpu=TPU, timeout=900)
def transcribe_stream(seconds: float = 4.0):
    """Generator: one partial transcript per audio window as it 'arrives'."""
    import numpy as np

    from modal_examples_tpu.models import whisper
    from modal_examples_tpu.utils.audio import (
        SAMPLE_RATE, log_mel_spectrogram, synth_tone_audio,
    )

    cfg, params = _model()
    # the "microphone": a long synthetic tone sweep
    audio = np.concatenate(
        [synth_tone_audio([440.0 * (1 + i)], CHUNK_SECONDS) for i in range(int(seconds))]
    )
    window = int(CHUNK_SECONDS * SAMPLE_RATE)
    for i in range(0, len(audio), window):
        chunk = audio[i : i + window]
        mel = log_mel_spectrogram(chunk, pad_to_chunk=False)
        mel = np.pad(
            mel[:MEL_FRAMES], ((0, MEL_FRAMES - min(len(mel), MEL_FRAMES)), (0, 0))
        )
        toks = whisper.greedy_transcribe(
            params, mel[None], cfg, bos_id=0, eos_id=1
        )
        text = " ".join(str(t) for t in np.asarray(toks[0]) if t != 1)
        yield {"t": round(i / SAMPLE_RATE, 1), "partial": f"[{text}]"}


@app.function()
@mtpu.fastapi_endpoint()
def transcribe_sse(seconds: float = 3.0):
    """The same stream over SSE (curl -N)."""
    yield from transcribe_stream.local(seconds)


@app.local_entrypoint()
def main(seconds: float = 3.0):
    n = 0
    for update in transcribe_stream.remote_gen(seconds):
        print(f"t={update['t']}s partial={update['partial']}")
        n += 1
    assert n == int(seconds)
    print(f"streamed {n} partial transcripts")
