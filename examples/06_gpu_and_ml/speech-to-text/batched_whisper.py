# # Batched Whisper transcription
#
# TPU-native counterpart of the reference's
# 06_gpu_and_ml/speech-to-text/batched_whisper.py: a transcription service
# whose `@mtpu.batched(max_batch_size=...)` method coalesces concurrent
# single-clip requests into one fixed-shape TPU batch (:127), behind an
# `@app.cls` with `@enter` model load.
#
# Run: tpurun run examples/06_gpu_and_ml/speech-to-text/batched_whisper.py

import os

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None
MEL_FRAMES = 200
MAX_BATCH = 8

app = mtpu.App("example-batched-whisper")


@app.cls(tpu=TPU, timeout=900, scaledown_window=300)
@mtpu.concurrent(max_inputs=MAX_BATCH)
class WhisperTranscriber:
    @mtpu.enter()
    def load(self):
        import dataclasses

        import jax
        import numpy as np

        from modal_examples_tpu.models import whisper

        self.cfg = dataclasses.replace(
            whisper.WhisperConfig.test_tiny(), vocab_size=16, n_text_ctx=8
        )
        # random weights in dev mode; point a CheckpointManager at a Volume
        # with fine_tune_asr.py's output for a trained model
        self.params = whisper.init_params(jax.random.PRNGKey(0), self.cfg)
        self.whisper = whisper
        self._transcribe = jax.jit(
            lambda p, m: whisper.greedy_transcribe(
                p, m, self.cfg, bos_id=0, eos_id=1
            )
        )
        # warm the fixed batch shape
        from modal_examples_tpu.utils.sync import force

        # force(), not block_until_ready: the latter is a no-op on the
        # tunneled axon backend, so the warmup would not actually compile+run
        force(self._transcribe(
            self.params, np.zeros((MAX_BATCH, MEL_FRAMES, 80), np.float32)
        ))

    @mtpu.batched(max_batch_size=MAX_BATCH, wait_ms=100)
    @mtpu.method()
    def transcribe(self, audios: list) -> list[str]:
        """Each input is one waveform; the scheduler batches them."""
        import numpy as np

        from modal_examples_tpu.utils.audio import log_mel_spectrogram

        mels = []
        for audio in audios:
            mel = log_mel_spectrogram(np.asarray(audio), pad_to_chunk=False)
            mel = np.pad(
                mel[:MEL_FRAMES],
                ((0, MEL_FRAMES - min(len(mel), MEL_FRAMES)), (0, 0)),
            )
            mels.append(mel)
        batch = np.stack(mels)
        pad_to = MAX_BATCH  # fixed compiled shape: pad the batch dim
        if len(batch) < pad_to:
            batch = np.pad(batch, ((0, pad_to - len(batch)), (0, 0), (0, 0)))
        out = np.asarray(self._transcribe(self.params, batch))[: len(audios)]
        return [" ".join(str(t) for t in row if t != 1) for row in out]


@app.local_entrypoint()
def main(n_clips: int = 6):
    from modal_examples_tpu.utils.audio import synth_tone_audio

    clips = [
        synth_tone_audio([440.0 * (1 + i % 3)], 1.0).tolist() for i in range(n_clips)
    ]
    t = WhisperTranscriber()
    # .map fans the clips out; the @batched method coalesces them server-side
    results = list(t.transcribe.map(clips))
    for i, r in enumerate(results):
        print(f"clip {i}: tokens [{r}]")
    assert len(results) == n_clips
    print("batched transcription OK")
