# # Websocket streaming ASR: partial transcripts while audio arrives
#
# TPU-native counterpart of the reference's streaming speech-to-text tier
# (06_gpu_and_ml/speech-to-text/streaming_kyutai_stt.py — a fastapi
# websocket endpoint streaming partial transcripts from browser
# microphones; streaming_parakeet.py; cache_aware_buffer.py — buffered
# incremental decoding). Here the whole stack is the framework's own:
#
# - `@mtpu.websocket_endpoint()` — the stdlib gateway speaks RFC 6455
#   itself (fastapi/uvicorn are optional in this image);
# - `serving.streaming_asr.StreamingTranscriber` — windowed incremental
#   Whisper with LocalAgreement-2 stabilization: stable text is committed
#   only once two consecutive updates agree on it, so committed text never
#   retracts;
# - the model is `models.whisper` (the same one the fine-tune and batched
#   examples use).
#
# Protocol (the streaming_kyutai_stt.py shape): the client streams binary
# float32 PCM chunks (16 kHz mono); the server answers with JSON events
# {"type": "partial" | "final", ...}; the text message "end" flushes.
#
# Run: tpurun run examples/06_gpu_and_ml/speech-to-text/streaming_asr_ws.py

import json
import os
import time

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None

app = mtpu.App("example-streaming-asr")

SR = 16000


def _make_transcriber():
    """Cheap-mode model: test-tiny whisper, random weights (the
    dummy-weights dev pattern); swap load_hf_weights for real ones."""
    import jax

    if not TPU:
        # cheap mode must not touch the chip: the env-var route
        # (JAX_PLATFORMS=cpu) is not reliable once the axon plugin is
        # importable (see __graft_entry__.dryrun_multichip)
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from modal_examples_tpu.models import whisper
    from modal_examples_tpu.serving.streaming_asr import StreamingTranscriber

    cfg = whisper.WhisperConfig.test_tiny()
    params = whisper.init_params(jax.random.PRNGKey(0), cfg)
    return StreamingTranscriber(
        params, cfg, bos_id=0, eos_id=1, sample_rate=SR,
        window_s=2.0, hop_s=0.5, max_tokens=16,
        decode_text=lambda toks: "".join(chr(97 + t % 26) for t in toks),
    )


@app.function()
@mtpu.websocket_endpoint()
def transcribe_ws(ws):
    """One connection = one stream: binary frames are PCM chunks, the text
    frame "end" finalizes. Emits {"type": "partial"} per update and one
    {"type": "final"} with the full committed transcript."""
    import numpy as np

    from modal_examples_tpu.web.websocket import ConnectionClosed

    t = _make_transcriber()
    try:
        while True:
            kind, payload = ws.receive()
            if kind == "text" and payload == b"end":
                res = t.flush()
                ws.send_json({
                    "type": "final", "text": res.committed_text,
                })
                return
            if kind == "binary":
                pcm = np.frombuffer(payload, np.float32)
                res = t.feed(pcm)
                if res is not None:
                    ws.send_json({
                        "type": "partial",
                        "stable": res.stable_text,
                        "pending": res.partial_text,
                        "committed": res.committed_text,
                    })
    except ConnectionClosed:
        pass


@app.local_entrypoint()
def main(seconds: float = 3.0, chunk_ms: int = 250):
    import numpy as np

    from modal_examples_tpu.utils.audio import synth_tone_audio
    from modal_examples_tpu.web.gateway import Gateway
    from modal_examples_tpu.web.websocket import connect

    with app.run():
        gw = Gateway(app).start()
        host, port = gw.httpd.server_address[:2]
        ws = connect(host, port, "/transcribe_ws")

        audio = synth_tone_audio([440.0, 660.0], seconds)
        chunk = int(SR * chunk_ms / 1000)
        hop = int(SR * 0.5)  # the server's update cadence (hop_s=0.5)
        partials = 0
        lat_ms = []
        got_updates = 0
        for i in range(0, len(audio), chunk):
            ws.send_bytes(audio[i : i + chunk].astype(np.float32).tobytes())
            # the server emits one event per full hop of audio, but at most
            # one per feed() call — drain exactly what is due so neither
            # side ever blocks on the other, for ANY chunk_ms
            chunks_sent = i // chunk + 1
            due = min(chunks_sent, (i + chunk) // hop)
            while got_updates < due:
                t0 = time.time()
                kind, payload = ws.receive()
                lat_ms.append((time.time() - t0) * 1e3)
                evt = json.loads(payload)
                assert evt["type"] == "partial"
                got_updates += 1
                partials += 1
                print(f"partial: committed={evt['committed']!r} "
                      f"pending={evt['pending']!r}")
        ws.send_text("end")
        while True:
            kind, payload = ws.receive()
            evt = json.loads(payload)
            if evt["type"] == "final":
                break
        ws.close()
        gw.stop()
        print(f"final transcript: {evt['text']!r}")
        print(f"partial events: {partials}, "
              f"median update latency {sorted(lat_ms)[len(lat_ms)//2]:.0f} ms")
        assert partials >= 2 and evt["text"]
