# ---
# env: {"MTPU_TRAIN_STEPS": "25"}
# timeout: 700
# ---
# # Text-to-video: a two-stage spawn-chained pipeline
#
# TPU-native counterpart of the reference's video/world-generation tier:
# 06_gpu_and_ml/world-models/text_to_world.py (a two-stage pipeline where
# stage 1 generates a reference video/frame and *spawns* stage 2 to lift
# it), text-to-video/ltx.py & ltx2_two_stage.py, and
# image-to-video/image_to_video.py — all of which delegate to torch/
# diffusers CUDA pipelines. Here both stages are the framework's own
# models:
#
#   1. **keyframe**: the image DiT (models.diffusion) generates a keyframe
#      from the prompt and writes it to a Volume, then `.spawn()`s stage 2
#      (fire-and-forget chaining — the text_to_world.py:9-12 shape);
#   2. **animate**: the latent video DiT (models.video, factorized
#      space-time attention) generates the remaining frames with frame 0
#      PINNED to the keyframe (image-to-video conditioning), and the
#      result is stored as an .npz on the output Volume.
#
# Both models train from scratch on a synthetic moving-square corpus in
# cheap mode (zero egress — the dummy-weights dev pattern). The chaining,
# conditioning, volumes, and spawn/poll surfaces are the real thing.
#
# Run: tpurun run examples/06_gpu_and_ml/text-to-video/text_to_video.py

import os
import time

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None
STEPS = int(os.environ.get("MTPU_TRAIN_STEPS", "60"))

app = mtpu.App("example-text-to-video")
weights_vol = mtpu.Volume.from_name("video-dit-weights", create_if_missing=True)
output_vol = mtpu.Volume.from_name("video-outputs", create_if_missing=True)

TEXT_DIM, TEXT_LEN = 32, 8


def encode_text(texts: list[str]):
    """Toy hashed-byte text states (the T5/CLIP stand-in; swap in
    models.bert against real weights)."""
    import numpy as np

    out = np.zeros((len(texts), TEXT_LEN, TEXT_DIM), np.float32)
    for i, t in enumerate(texts):
        for j, ch in enumerate(t.encode()[:TEXT_LEN]):
            rng = np.random.default_rng(ch)
            out[i, j] = rng.standard_normal(TEXT_DIM) * 0.5
    return out


def _square_video(key, cfg):
    """Synthetic corpus: a bright square drifting across dark frames."""
    import jax
    import jax.numpy as jnp

    S, T = cfg.img_size, cfg.frames
    k1, k2, k3 = jax.random.split(key, 3)
    x0 = jax.random.randint(k1, (), 0, S - 3)
    y0 = jax.random.randint(k2, (), 0, S - 3)
    dx = jax.random.randint(k3, (), -1, 2)
    frames = []
    for t in range(T):
        xs = jnp.clip(x0 + t * dx, 0, S - 3)
        col = jnp.arange(S)
        mask = (
            ((col >= xs) & (col < xs + 3))[None, :]
            & ((col >= y0) & (col < y0 + 3))[:, None]
        )
        frames.append(jnp.where(mask[:, :, None], 1.0, -1.0))
    return jnp.stack(frames)  # [T, S, S, 1] -> broadcast to channels


@app.function(tpu=TPU, volumes={"/models": weights_vol}, timeout=3600)
def train(steps: int = STEPS) -> dict:
    """Train BOTH stages on the synthetic corpus and save to the Volume."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import diffusion, video
    from modal_examples_tpu.training import Trainer, make_optimizer

    vcfg = video.VideoDiTConfig.tiny()
    icfg = diffusion.DiTConfig(
        img_size=vcfg.img_size, channels=vcfg.channels, patch=vcfg.patch,
        dim=96, n_layers=3, n_heads=4, text_dim=TEXT_DIM, text_len=TEXT_LEN,
    )

    prompts = ["a square drifting right", "a square holding still"]
    text = jnp.asarray(encode_text(prompts))

    def make_batch(key, bs=8):
        ks = jax.random.split(key, bs + 1)
        vids = jnp.stack([_square_video(k, vcfg) for k in ks[:bs]])
        vids = jnp.repeat(vids, vcfg.channels, axis=-1)[..., : vcfg.channels]
        idx = jax.random.randint(ks[-1], (bs,), 0, len(prompts))
        return vids, text[idx]

    # stage-2 video model
    vparams = video.init_params(jax.random.PRNGKey(0), vcfg)

    def vloss(p, batch):
        return video.flow_loss(p, batch["rng"], batch["v"], batch["t"], vcfg)

    vtrainer = Trainer(vloss, make_optimizer(2e-3))
    vstate = vtrainer.init_state(vparams)
    key = jax.random.PRNGKey(1)
    for _ in range(steps):
        key, k1, k2 = jax.random.split(key, 3)
        vids, txt = make_batch(k1)
        vstate, metrics = vtrainer.train_step(
            vstate, {"v": vids, "t": txt, "rng": k2}
        )

    # stage-1 keyframe model trains on FIRST frames
    iparams = diffusion.init_params(jax.random.PRNGKey(2), icfg)

    def iloss(p, batch):
        return diffusion.flow_loss(
            p, batch["rng"], batch["v"][:, 0], batch["t"], icfg
        )

    itrainer = Trainer(iloss, make_optimizer(2e-3))
    istate = itrainer.init_state(iparams)
    for _ in range(steps):
        key, k1, k2 = jax.random.split(key, 3)
        vids, txt = make_batch(k1)
        istate, imetrics = itrainer.train_step(
            istate, {"v": vids, "t": txt, "rng": k2}
        )

    # portable save: both trees as host arrays in one pickle
    import pickle

    with open("/models/video_pipeline.pkl", "wb") as f:
        pickle.dump(
            {
                "video": jax.tree.map(np.asarray, vstate.params),
                "image": jax.tree.map(np.asarray, istate.params),
            },
            f,
        )
    weights_vol.commit()
    return {
        "video_loss": float(metrics["loss"]),
        "image_loss": float(imetrics["loss"]),
    }


@app.function(
    tpu=TPU,
    volumes={"/models": weights_vol, "/outputs": output_vol},
    timeout=1800,
)
def animate(prompt: str, keyframe_path: str) -> str:
    """Stage 2: latent video DiT with frame 0 pinned to the keyframe."""
    import pickle

    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import video

    vcfg = video.VideoDiTConfig.tiny()
    with open("/models/video_pipeline.pkl", "rb") as f:
        params = jax.tree.map(jnp.asarray, pickle.load(f)["video"])
    keyframe = jnp.asarray(np.load(keyframe_path)["frame"])
    text = jnp.asarray(encode_text([prompt]))
    out = video.sample(
        params, jax.random.PRNGKey(7), text, vcfg,
        first_frame=keyframe[None], steps=8, guidance=2.0,
    )
    out_path = f"/outputs/video-{int(time.time())}.npz"
    np.savez(out_path, video=np.asarray(out[0]), prompt=prompt)
    output_vol.commit()
    print(f"stage 2 done: {out_path} frames={out.shape[1]}")
    return out_path


@app.function(
    tpu=TPU,
    volumes={"/models": weights_vol, "/outputs": output_vol},
    timeout=1800,
)
def generate_keyframe(prompt: str):
    """Stage 1: image DiT keyframe, then SPAWN stage 2 (fire-and-forget
    chaining across containers — text_to_world.py:9-12's shape)."""
    import pickle

    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import diffusion, video

    vcfg = video.VideoDiTConfig.tiny()
    icfg = diffusion.DiTConfig(
        img_size=vcfg.img_size, channels=vcfg.channels, patch=vcfg.patch,
        dim=96, n_layers=3, n_heads=4, text_dim=TEXT_DIM, text_len=TEXT_LEN,
    )
    with open("/models/video_pipeline.pkl", "rb") as f:
        params = jax.tree.map(jnp.asarray, pickle.load(f)["image"])
    text = jnp.asarray(encode_text([prompt]))
    frame = diffusion.sample(
        params, jax.random.PRNGKey(3), text, icfg, steps=8, guidance=2.0
    )[0]
    key_path = f"/outputs/keyframe-{int(time.time())}.npz"
    np.savez(key_path, frame=np.asarray(frame), prompt=prompt)
    output_vol.commit()
    print(f"stage 1 done: {key_path}")
    call = animate.spawn(prompt, key_path)
    return {"keyframe": key_path, "stage2_call_id": call.object_id}


@app.local_entrypoint()
def main(prompt: str = "a square drifting right"):
    print("training both stages (cheap mode)...")
    losses = train.remote()
    print("train:", losses)
    out = generate_keyframe.remote(prompt)
    print("stage 1:", out)
    # poll the spawned stage-2 call to completion (FunctionCall.from_id —
    # the poll_delayed_result pattern)
    call = mtpu.FunctionCall.from_id(out["stage2_call_id"])
    video_path = call.get(timeout=600)
    print("pipeline complete:", video_path)
