# ---
# env: {"MTPU_TRAIN_STEPS": "40"}
# timeout: 800
# ---
# # Animate a user-supplied image into a video
#
# TPU-native counterpart of the reference's
# 06_gpu_and_ml/image-to-video/image_to_video.py: take an IMAGE the user
# provides (plus a prompt), animate it into a short video, and expose the
# capability three ways like the reference does — a CLI entrypoint, a
# callable class method, and a web API (POST /animate with a base64
# image). The reference runs Lightricks LTX-Video through diffusers on
# CUDA; here the generator is the framework's own latent video DiT
# (models.video, factorized space-time attention) with the user image
# PINNED as frame 0 at every sampling step — the same
# conditioning-by-inpainting recipe LTX uses for its image conditioning.
#
# Cheap mode trains the tiny video DiT on a synthetic moving-square
# corpus first (zero egress — no published checkpoints), then animates a
# NEVER-SEEN user image. The conditioning proof is exact: frame 0 of the
# output IS the input image; later frames move.
#
# Run: tpurun run examples/06_gpu_and_ml/image-to-video/image_to_video.py

import base64
import os
import pickle

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None
STEPS = int(os.environ.get("MTPU_TRAIN_STEPS", "40"))

app = mtpu.App("example-image-to-video")
weights_vol = mtpu.Volume.from_name("i2v-weights", create_if_missing=True)
output_vol = mtpu.Volume.from_name("i2v-outputs", create_if_missing=True)

TEXT_DIM, TEXT_LEN = 32, 8


def encode_text(texts: list[str]):
    """Toy hashed-byte text states (T5/CLIP stand-in; swap models.bert +
    real weights in production)."""
    import numpy as np

    out = np.zeros((len(texts), TEXT_LEN, TEXT_DIM), np.float32)
    for i, t in enumerate(texts):
        for j, ch in enumerate(t.encode()[:TEXT_LEN]):
            rng = np.random.default_rng(ch)
            out[i, j] = rng.standard_normal(TEXT_DIM) * 0.5
    return out


def _square_video(key, cfg):
    """Synthetic corpus: a bright square drifting across dark frames."""
    import jax
    import jax.numpy as jnp

    S, T = cfg.img_size, cfg.frames
    k1, k2, k3 = jax.random.split(key, 3)
    x0 = jax.random.randint(k1, (), 0, S - 3)
    y0 = jax.random.randint(k2, (), 0, S - 3)
    dx = jax.random.randint(k3, (), -1, 2)
    frames = []
    for t in range(T):
        xs = jnp.clip(x0 + t * dx, 0, S - 3)
        col = jnp.arange(S)
        mask = (
            ((col >= xs) & (col < xs + 3))[None, :]
            & ((col >= y0) & (col < y0 + 3))[:, None]
        )
        frames.append(jnp.where(mask[:, :, None], 1.0, -1.0))
    return jnp.stack(frames)  # [T, S, S, 1]


@app.function(tpu=TPU, volumes={"/models": weights_vol}, timeout=1800)
def train(steps: int = STEPS) -> dict:
    """Cheap-mode stand-in for pulling LTX weights: train the video DiT on
    the synthetic corpus (with first-frame conditioning in the loss) and
    publish it to the Volume."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import video
    from modal_examples_tpu.training import Trainer, make_optimizer

    if os.path.exists("/models/i2v.pkl"):
        return {"trained": False}

    cfg = video.VideoDiTConfig.tiny()
    prompts = ["drift right", "hold still"]
    text = jnp.asarray(encode_text(prompts))

    def make_batch(key, bs=8):
        ks = jax.random.split(key, bs + 1)
        vids = jnp.stack([_square_video(k, cfg) for k in ks[:bs]])
        vids = jnp.repeat(vids, cfg.channels, axis=-1)[..., : cfg.channels]
        idx = jax.random.randint(ks[-1], (bs,), 0, len(prompts))
        return vids, text[idx]

    params = video.init_params(jax.random.PRNGKey(0), cfg)

    def loss(p, batch):
        return video.flow_loss(p, batch["rng"], batch["v"], batch["t"], cfg)

    trainer = Trainer(loss, make_optimizer(2e-3))
    state = trainer.init_state(params)
    key = jax.random.PRNGKey(1)
    metrics = {}
    for _ in range(steps):
        key, k1, k2 = jax.random.split(key, 3)
        vids, txt = make_batch(k1)
        state, metrics = trainer.train_step(
            state, {"v": vids, "t": txt, "rng": k2}
        )

    with open("/models/i2v.pkl", "wb") as f:
        pickle.dump(jax.tree.map(np.asarray, state.params), f)
    weights_vol.commit()
    return {"trained": True, "loss": float(metrics["loss"])}


@app.cls(
    tpu=TPU,
    volumes={"/models": weights_vol, "/outputs": output_vol},
    scaledown_window=300,
)
class ImageToVideo:
    @mtpu.enter()
    def load(self):
        import jax

        if not TPU:
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        import jax.numpy as jnp

        from modal_examples_tpu.models import video

        weights_vol.reload()
        self.cfg = video.VideoDiTConfig.tiny()
        with open("/models/i2v.pkl", "rb") as f:
            self.params = jax.tree.map(jnp.asarray, pickle.load(f))
        self.video = video
        self.jax, self.jnp = jax, jnp

    def _animate(self, image, prompt: str, seed: int = 0):
        import numpy as np

        jnp = self.jnp
        img = jnp.asarray(np.asarray(image, np.float32))[None]
        text = jnp.asarray(encode_text([prompt]))
        out = self.video.sample(
            self.params, self.jax.random.PRNGKey(seed), text, self.cfg,
            first_frame=img, steps=8, guidance=2.0,
        )
        return np.asarray(out[0])

    @mtpu.method()
    def animate(self, image, prompt: str = "drift right", seed: int = 0):
        """image [S, S, C] float in [-1, 1] -> video [T, S, S, C]; frame 0
        is the input image, held fixed at every sampling step (the
        reference pipeline's image conditioning)."""
        return self._animate(image, prompt, seed)

    @mtpu.method()
    def animate_to_volume(self, image, prompt: str, name: str) -> dict:
        """The reference's output-directory flow: write the result as an
        .npz plus a film-strip PNG on the outputs Volume."""
        import numpy as np

        from modal_examples_tpu.utils.images import to_png

        frames = self._animate(image, prompt)
        np.savez_compressed(f"/outputs/{name}.npz", video=frames)
        strip = np.concatenate(list(frames[..., :3]), axis=1)
        with open(f"/outputs/{name}.png", "wb") as f:
            f.write(to_png(strip))
        output_vol.commit()
        return {
            "frames": int(frames.shape[0]),
            "npz": f"{name}.npz",
            "strip_png": f"{name}.png",
        }


@app.function()
@mtpu.fastapi_endpoint(method="POST")
def animate(image_b64: str, prompt: str = "drift right") -> dict:
    """POST /animate {image_b64, prompt} — the reference's fastapi
    endpoint shape (image_to_video.py `/generate`). The image is a
    base64 .npy payload; the video comes back the same way."""
    import io

    import numpy as np

    arr = np.load(io.BytesIO(base64.b64decode(image_b64)), allow_pickle=False)
    frames = ImageToVideo().animate.remote(arr, prompt)
    buf = io.BytesIO()
    np.save(buf, frames)
    return {
        "video_b64": base64.b64encode(buf.getvalue()).decode(),
        "frames": int(frames.shape[0]),
    }


@app.local_entrypoint()
def main(prompt: str = "drift right"):
    import numpy as np

    print("train:", train.remote())

    # a NEVER-SEEN user image: square at a position the corpus RNG never
    # produced, plus a corner notch
    from modal_examples_tpu.models.video import VideoDiTConfig

    cfg = VideoDiTConfig.tiny()
    S = cfg.img_size
    img = -np.ones((S, S, cfg.channels), np.float32)
    img[2:5, 9:12] = 1.0
    img[0, 0] = 0.5

    i2v = ImageToVideo()
    frames = i2v.animate.remote(img, prompt)
    assert frames.shape == (cfg.frames, S, S, cfg.channels), frames.shape
    # exact conditioning: frame 0 IS the input image
    np.testing.assert_array_equal(frames[0], img.astype(frames.dtype))
    # and the video actually moves: later frames differ from frame 0
    deltas = [float(np.abs(frames[t] - frames[0]).mean()) for t in range(1, cfg.frames)]
    assert max(deltas) > 0.01, deltas
    assert np.isfinite(frames).all()
    print(f"animated: {frames.shape}, mean frame-0 delta {deltas}")

    out = i2v.animate_to_volume.remote(img, prompt, "demo")
    print("volume outputs:", out)
    assert out["frames"] == cfg.frames
    print("image-to-video: conditioning exact, motion present, outputs saved")
