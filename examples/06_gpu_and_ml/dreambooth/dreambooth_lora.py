# ---
# env: {"MTPU_PRETRAIN_STEPS": "300", "MTPU_LORA_STEPS": "300"}
# timeout: 900
# ---
# # Dreambooth: subject-personalization LoRA on a diffusion model
#
# TPU-native counterpart of the reference's
# 06_gpu_and_ml/dreambooth/diffusers_lora_finetune.py: teach a pretrained
# image model a NEW subject from a handful of instance images by training
# low-rank adapters bound to a rare token ("sks"), leaving the base
# frozen. Same recipe, framework-native pieces:
#
# - the model is our MMDiT (models.diffusion, the SD3-class transformer)
#   with rectified-flow training — not a torch UNet;
# - adapters target the attention + MLP projections
#   (lora.DIT_TARGETS — the to_q/to_k/to_v/to_out/ff set the reference
#   passes to LoraConfig at diffusers_lora_finetune.py:205-213) via the
#   generic tree-LoRA (lora.init_lora_tree/merge_tree);
# - training is interruption-tolerant: checkpoints + optimizer state live
#   on a Volume through CheckpointManager, retries resume from the latest
#   step (the reference's resume story, unsloth_finetune.py:549-567);
# - "instance images" are a few noisy views of one synthetic subject
#   (zero egress; the reference downloads instance_example_urls.txt).
#
# Proof of personalization: one-step rectified-flow denoising toward the
# subject improves by >1.5x after adapter training while the base tree
# stays bitwise frozen.
#
# Run: tpurun run examples/06_gpu_and_ml/dreambooth/dreambooth_lora.py

import os
import pickle

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None
PRETRAIN_STEPS = int(os.environ.get("MTPU_PRETRAIN_STEPS", "300"))
LORA_STEPS = int(os.environ.get("MTPU_LORA_STEPS", "300"))

app = mtpu.App("example-dreambooth")
vol = mtpu.Volume.from_name("dreambooth-lora", create_if_missing=True)

N_INSTANCE = 5  # instance images of the subject


def _cfg():
    from modal_examples_tpu.models import diffusion

    return diffusion.MMDiTConfig(
        img_size=16, channels=8, patch=2, dim=128, n_layers=2, n_heads=4,
        text_dim=32, pooled_dim=32,
    )


def _subject(jax, jnp, cfg):
    """The subject + its token embedding. The 'sks' rare-token recipe: a
    text embedding the base model never saw during pretraining."""
    subject = jnp.tanh(
        jax.random.normal(
            jax.random.PRNGKey(3), (cfg.img_size, cfg.img_size, cfg.channels)
        ) * 2.0
    )
    token = jax.random.normal(jax.random.PRNGKey(4), (1, 4, cfg.text_dim))
    return subject, token


def _instance_images(jax, jnp, subject):
    """A few 'photos' of the subject: the same object under small
    perturbations (lighting/pose stand-in)."""
    views = []
    for i in range(N_INSTANCE):
        noise = jax.random.normal(jax.random.PRNGKey(50 + i), subject.shape)
        views.append(jnp.clip(subject + 0.08 * noise, -1.0, 1.0))
    return jnp.stack(views)


def _denoise_err(diffusion, jax, jnp, params, cfg, subject, token):
    """One-step rectified-flow denoise x_hat = x_t - t*v at fixed (eps, t)
    vs the subject — the quantity personalization must improve."""
    t = 0.7
    eps = jax.random.normal(jax.random.PRNGKey(77), (4, *subject.shape))
    x_t = (1 - t) * subject[None] + t * eps
    ts = jnp.broadcast_to(token, (4, 4, cfg.text_dim))
    v = diffusion.mmdit_forward(
        params, x_t, jnp.full((4,), t), ts, jnp.zeros((4, cfg.pooled_dim)),
        cfg,
    )
    return float(jnp.mean((x_t - t * v - subject[None]) ** 2))


@app.function(tpu=TPU, volumes={"/data": vol}, timeout=600)
def prepare_base() -> dict:
    """Pretrain the base model on generic data (the stand-in for
    downloading SD3's pretrained weights — zero egress) and publish it to
    the Volume. Skips if already present."""
    import jax
    import jax.numpy as jnp
    import optax

    from modal_examples_tpu.models import diffusion

    if os.path.exists("/data/base.pkl"):
        return {"pretrained": False}

    cfg = _cfg()
    params = diffusion.mmdit_init(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(2e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, key):
        k1, k2 = jax.random.split(key)
        lat = jnp.tanh(
            jax.random.normal(k1, (8, cfg.img_size, cfg.img_size, cfg.channels))
        )
        loss, g = jax.value_and_grad(diffusion.mmdit_flow_loss)(
            params, k2, lat, jnp.zeros((8, 4, cfg.text_dim)),
            jnp.zeros((8, cfg.pooled_dim)), cfg,
        )
        upd, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, upd), opt_state, loss

    loss = None
    for i in range(PRETRAIN_STEPS):
        params, opt_state, loss = step(params, opt_state, jax.random.PRNGKey(100 + i))

    with open("/data/base.pkl", "wb") as f:
        pickle.dump(jax.tree.map(lambda x: __import__("numpy").asarray(x), params), f)
    vol.commit()
    return {"pretrained": True, "final_loss": float(loss)}


@app.function(
    tpu=TPU,
    volumes={"/data": vol},
    timeout=900,
    retries=mtpu.Retries(initial_delay=0.0, max_retries=3),
    single_use_containers=True,
)
def personalize(max_steps: int = LORA_STEPS, resume: bool = True) -> dict:
    """LoRA fine-tune on the instance images; resumable mid-run."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from modal_examples_tpu.models import diffusion, lora
    from modal_examples_tpu.training import CheckpointManager

    vol.reload()  # a retry container must see the dead attempt's commits
    cfg = _cfg()
    with open("/data/base.pkl", "rb") as f:
        base = jax.tree.map(jnp.asarray, pickle.load(f))
    base_fingerprint = float(
        sum(np.abs(np.asarray(x)).sum() for x in jax.tree.leaves(base))
    )

    subject, token = _subject(jax, jnp, cfg)
    instances = _instance_images(jax, jnp, subject)
    lcfg = lora.LoRAConfig(rank=16, alpha=32.0, targets=lora.DIT_TARGETS)
    adapters = lora.init_lora_tree(jax.random.PRNGKey(1), base, lcfg)
    opt = optax.adam(1e-2)
    opt_state = opt.init(adapters)

    err_base = _denoise_err(diffusion, jax, jnp, base, cfg, subject, token)

    ckpts = CheckpointManager("/data/lora-run", keep_n=2, volume=vol)
    start_step = 0
    if resume and ckpts.latest_step() is not None:
        restored = ckpts.restore({"adapters": adapters, "opt": opt_state})
        adapters, opt_state = restored["adapters"], restored["opt"]
        start_step = ckpts.latest_step()
        print(f"resumed from step {start_step}")

    @jax.jit
    def step(adapters, opt_state, key):
        def loss_fn(ad):
            merged = lora.merge_tree(base, ad, lcfg)
            k1, k2 = jax.random.split(key)
            ix = jax.random.randint(k1, (8,), 0, N_INSTANCE)
            lat = instances[ix]
            ts = jnp.broadcast_to(token, (8, 4, cfg.text_dim))
            return diffusion.mmdit_flow_loss(
                merged, k2, lat, ts, jnp.zeros((8, cfg.pooled_dim)), cfg
            )

        loss, g = jax.value_and_grad(loss_fn)(adapters)
        upd, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(adapters, upd), opt_state, loss

    for i in range(start_step, max_steps):
        adapters, opt_state, loss = step(
            adapters, opt_state, jax.random.PRNGKey(10 + i)
        )
        if (i + 1) % 50 == 0:
            ckpts.save(i + 1, {"adapters": adapters, "opt": opt_state})
            print(f"step {i + 1} loss {float(loss):.3f} (checkpointed)")
    ckpts.save(max_steps, {"adapters": adapters, "opt": opt_state})

    merged = lora.merge_tree(base, adapters, lcfg)
    err_lora = _denoise_err(diffusion, jax, jnp, merged, cfg, subject, token)
    # adapter-only training: the base on the volume is untouched
    base_after = float(
        sum(np.abs(np.asarray(x)).sum() for x in jax.tree.leaves(base))
    )
    with open("/data/adapters.pkl", "wb") as f:
        pickle.dump(jax.tree.map(lambda x: np.asarray(x), adapters), f)
    vol.commit()
    return {
        "trained_steps": max_steps - start_step,
        "resumed_from": start_step,
        "denoise_err_base": err_base,
        "denoise_err_lora": err_lora,
        "adapter_params": lora.param_count(adapters),
        "base_frozen": base_after == base_fingerprint,
    }


@app.function(tpu=TPU, volumes={"/data": vol}, timeout=600)
def generate() -> dict:
    """Generate with the subject token through the personalized model and
    save a gallery PNG (the reference's inference section)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import diffusion, lora
    from modal_examples_tpu.utils.images import to_png

    vol.reload()
    cfg = _cfg()
    with open("/data/base.pkl", "rb") as f:
        base = jax.tree.map(jnp.asarray, pickle.load(f))
    with open("/data/adapters.pkl", "rb") as f:
        adapters = jax.tree.map(jnp.asarray, pickle.load(f))
    lcfg = lora.LoRAConfig(rank=16, alpha=32.0, targets=lora.DIT_TARGETS)
    merged = lora.merge_tree(base, adapters, lcfg)
    subject, token = _subject(jax, jnp, cfg)

    # one-step denoise "views" of the subject at decreasing noise
    eps = jax.random.normal(jax.random.PRNGKey(9), (3, *subject.shape))
    outs = []
    for row, t in enumerate((0.9, 0.7, 0.5)):
        x_t = (1 - t) * subject[None] + t * eps
        ts = jnp.broadcast_to(token, (3, 4, cfg.text_dim))
        v = diffusion.mmdit_forward(
            merged, x_t, jnp.full((3,), t), ts,
            jnp.zeros((3, cfg.pooled_dim)), cfg,
        )
        outs.append(jnp.clip(x_t - t * v, -1, 1))
    grid = jnp.concatenate(
        [jnp.concatenate(list(o[:, :, :, :3]), axis=1) for o in outs], axis=0
    )
    png = to_png(np.asarray(grid))
    with open("/data/gallery.png", "wb") as f:
        f.write(png)
    vol.commit()
    return {"gallery_bytes": len(png), "grid_shape": list(grid.shape)}


@app.local_entrypoint()
def main():
    print("base:", prepare_base.remote())
    result = personalize.remote(LORA_STEPS, True)
    print("personalize:", {k: v for k, v in result.items()})
    assert result["base_frozen"]
    assert result["denoise_err_lora"] < result["denoise_err_base"] / 1.5, (
        result["denoise_err_base"], result["denoise_err_lora"],
    )
    # second call resumes from the checkpoint instead of restarting
    again = personalize.remote(LORA_STEPS + 20, True)
    print("resume:", again)
    assert again["resumed_from"] >= LORA_STEPS
    print("gallery:", generate.remote())
