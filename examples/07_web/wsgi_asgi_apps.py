# # Hosting WSGI and ASGI apps
#
# Counterpart of the reference's `@modal.wsgi_app` (torch_profiling.py:301
# hosts TensorBoard) and `@modal.asgi_app` (text_to_image.py:239 hosts a
# FastAPI UI): the decorated function RETURNS the app object, and the web
# layer serves it. Works with any WSGI/ASGI framework; shown here with
# dependency-free apps.
#
# Serve: tpurun serve examples/07_web/wsgi_asgi_apps.py

import json

import modal_examples_tpu as mtpu

app = mtpu.App("example-wsgi-asgi")


@app.function()
@mtpu.wsgi_app()
def wsgi_echo():
    """A minimal WSGI app (Flask & friends drop in the same way)."""

    def application(environ, start_response):
        body = json.dumps(
            {
                "framework": "wsgi",
                "path": environ["PATH_INFO"],
                "method": environ["REQUEST_METHOD"],
            }
        ).encode()
        start_response(
            "200 OK",
            [("content-type", "application/json")],
        )
        return [body]

    return application


@app.function()
@mtpu.asgi_app()
def asgi_echo():
    """A minimal ASGI app (FastAPI/Starlette drop in the same way)."""

    async def application(scope, receive, send):
        assert scope["type"] == "http"
        message = await receive()
        body = json.dumps(
            {
                "framework": "asgi",
                "path": scope["path"],
                "method": scope["method"],
                "received_bytes": len(message.get("body", b"")),
            }
        ).encode()
        await send(
            {
                "type": "http.response.start",
                "status": 200,
                "headers": [(b"content-type", b"application/json")],
            }
        )
        await send({"type": "http.response.body", "body": body})

    return application


@app.local_entrypoint()
def main():
    import urllib.request

    from modal_examples_tpu.web.gateway import Gateway

    with app.run():
        gw = Gateway(app).start()
        with urllib.request.urlopen(f"{gw.base_url}/wsgi_echo/hello") as r:
            out = json.load(r)
        print("wsgi:", out)
        assert out == {"framework": "wsgi", "path": "/hello", "method": "GET"}

        req = urllib.request.Request(
            f"{gw.base_url}/asgi_echo/items", data=b'{"x": 1}',
            headers={"content-type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            out = json.load(r)
        print("asgi:", out)
        assert out["framework"] == "asgi" and out["received_bytes"] == 8
        gw.stop()
        print("wsgi + asgi hosting OK")
