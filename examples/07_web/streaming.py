# # Deploy a web endpoint with streaming responses
#
# The deployed-streaming counterpart of the reference's 07_web/streaming.py
# (SSE StreamingResponse, :38-45): a generator Function streams results
# back progressively, both through the web gateway as server-sent events
# and directly to a Python client via `.remote_gen`.
#
# Serve:  tpurun serve examples/07_web/streaming.py
# Then:   curl -sN "http://127.0.0.1:<port>/fake_video?frames=5"

import time
import urllib.request

import modal_examples_tpu as mtpu

app = mtpu.App("example-streaming")


# A generator Function streams its yields; behind the web gateway each yield
# becomes one `data:` SSE event (the gateway sets text/event-stream).
@app.function()
@mtpu.fastapi_endpoint()
def fake_video(frames: int = 10):
    for i in range(frames):
        yield f"frame {i}: hello world!"
        time.sleep(0.05)


# The same streaming shape works container-to-client without HTTP: <br>
# `.remote_gen` yields each item as the container produces it.
@app.function()
def countdown(n: int = 5):
    for i in range(n, 0, -1):
        yield i
        time.sleep(0.02)


@app.local_entrypoint()
def main(frames: int = 4):
    # stream across the container boundary
    got = []
    for tick in countdown.remote_gen(3):
        print("tick", tick, flush=True)
        got.append(tick)
    assert got == [3, 2, 1], got

    # stream over HTTP: serve the app, consume the SSE event stream
    from modal_examples_tpu.web.gateway import Gateway

    with app.run():
        gw = Gateway(app).start()
        try:
            events = []
            req = urllib.request.Request(
                f"{gw.base_url}/fake_video?frames={frames}"
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                ctype = r.headers.get("content-type", "")
                assert ctype.startswith("text/event-stream"), ctype
                for raw in r:
                    line = raw.decode().strip()
                    if line.startswith("data: "):
                        events.append(line[6:])
            print("SSE events:", events)
            assert len(events) == frames, events
        finally:
            gw.stop()
    print("streaming OK")
