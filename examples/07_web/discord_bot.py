# # A Discord slash-command bot: signed webhooks + deferred replies
#
# TPU-native counterpart of the reference's 07_web/discord_bot.py (399
# LoC): a Discord Interactions endpoint that (1) verifies the Ed25519
# request signature, (2) ACKs within Discord's 3-second deadline with a
# DEFERRED response, and (3) `.spawn()`s the real work, which PATCHes the
# follow-up message to the interaction webhook afterwards — the
# slow-work-behind-a-fast-webhook pattern (discord_bot.py:60-140).
#
# Zero egress: instead of discord.com, the follow-up URL points at a mock
# Discord endpoint served BY THIS APP, which records messages in a Dict —
# the full signed-webhook -> deferred-ACK -> background-work -> follow-up
# loop runs and is asserted end to end. Point `DISCORD_API_BASE` at the
# real API (and set the real public key in a Secret) to go live.
#
# The bot's "work" is framework-flavored: it reports this app's own
# engine-bench-style stats (the reference hits a free public API instead).
#
# Run: tpurun run examples/07_web/discord_bot.py

import json
import os
import time

import modal_examples_tpu as mtpu

app = mtpu.App("example-discord-bot")
followups = mtpu.Dict.from_name("discord-followups", create_if_missing=True)

# Discord interaction types/results (the Interactions API contract)
PING, APPLICATION_COMMAND = 1, 2
PONG, DEFERRED = 1, 5


def _keys():
    """Demo keypair (a real deployment stores ONLY the public key, from
    the Discord developer portal, in a Secret)."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    seed = b"mtpu-discord-demo-keypair-seed!!"  # 32 bytes, fixed for the demo
    priv = Ed25519PrivateKey.from_private_bytes(seed)
    return priv, priv.public_key()


def verify_signature(public_key, signature_hex: str, timestamp: str,
                     body: bytes) -> bool:
    """Discord signs `timestamp + body` with the app's Ed25519 key; an
    endpoint MUST reject bad signatures (discord_bot.py does this with
    pynacl; `cryptography` ships in this image)."""
    from cryptography.exceptions import InvalidSignature

    try:
        public_key.verify(
            bytes.fromhex(signature_hex), timestamp.encode() + body
        )
        return True
    except (InvalidSignature, ValueError):
        return False


@app.function()
def bot_work() -> str:
    """The actual service behind the slash command (the reference hits a
    public API here; ours reports framework stats)."""
    import platform

    return (
        "**modal-examples-tpu status**\n"
        f"host: {platform.node() or 'container'} | "
        f"checkpoints of note: paged decode 1101 tok/s (7B int8, 1 v5e)"
    )


@app.function()
def reply(application_id: str, interaction_token: str, api_base: str) -> None:
    """Background worker: compute, then PATCH the follow-up message (the
    deferred-interaction completion, discord_bot.py:115-140)."""
    import urllib.request

    message = bot_work.local()
    url = (
        f"{api_base}/webhooks/{application_id}/{interaction_token}"
        "/messages/@original"
    )
    req = urllib.request.Request(
        url,
        data=json.dumps({"content": message}).encode(),
        headers={"content-type": "application/json"},
        method="PATCH",
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        r.read()


def _handle_interaction(body: dict) -> dict:
    itype = body.get("type")
    if itype == PING:
        return {"type": PONG}  # Discord's URL-validation handshake
    if itype == APPLICATION_COMMAND:
        reply.spawn(
            body["application_id"],
            body["token"],
            body.get("api_base", os.environ.get(
                "DISCORD_API_BASE", "https://discord.com/api/v10"
            )),
        )
        return {"type": DEFERRED}  # ACK within the 3 s deadline
    return {"error": f"unhandled interaction type {itype}"}


@app.function()
@mtpu.wsgi_app()
def interactions():
    """The Interactions endpoint Discord POSTs to — a WSGI app because
    signature verification needs the RAW body + headers (discord_bot.py
    verifies with the app public key and 401s forgeries; Discord's own
    endpoint validation requires unsigned requests to be rejected)."""
    _, public_key = _keys()

    def wsgi(environ, start_response):
        n = int(environ.get("CONTENT_LENGTH") or 0)
        raw = environ["wsgi.input"].read(n)
        sig = environ.get("HTTP_X_SIGNATURE_ED25519", "")
        ts = environ.get("HTTP_X_SIGNATURE_TIMESTAMP", "")
        if not verify_signature(public_key, sig, ts, raw):
            start_response("401 Unauthorized",
                           [("content-type", "application/json")])
            return [b'{"error": "invalid request signature"}']
        out = json.dumps(_handle_interaction(json.loads(raw))).encode()
        start_response("200 OK", [
            ("content-type", "application/json"),
            ("content-length", str(len(out))),
        ])
        return [out]

    return wsgi


@app.function()
@mtpu.fastapi_endpoint(method="POST")
def mock_discord_webhook(application_id: str, token: str, content: str = "") -> dict:
    """Stand-in for discord.com's webhook PATCH target (zero egress): the
    follow-up lands in a Dict the test asserts on."""
    followups.put(token, content)
    return {"ok": True}


@app.local_entrypoint()
def main():
    import threading
    import urllib.error
    import urllib.request

    from modal_examples_tpu.web.gateway import Gateway

    priv, pub = _keys()

    with app.run():
        gw = Gateway(app).start()
        base = gw.base_url

        # a thin adapter: PATCH {base}/webhooks/{app}/{tok}/messages/@original
        # -> our mock endpoint (URL shapes differ; a tiny proxy keeps the
        # reply() worker byte-identical to the real-API version)
        import http.server

        class Adapter(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_PATCH(self):
                parts = self.path.strip("/").split("/")
                app_id, tok = parts[1], parts[2]
                n = int(self.headers.get("content-length") or 0)
                content = json.loads(self.rfile.read(n))["content"]
                req = urllib.request.Request(
                    f"{base}/mock_discord_webhook",
                    data=json.dumps({
                        "application_id": app_id, "token": tok,
                        "content": content,
                    }).encode(),
                    headers={"content-type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=30):
                    pass
                self.send_response(200)
                self.send_header("content-length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

        adapter = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Adapter)
        threading.Thread(target=adapter.serve_forever, daemon=True).start()
        api_base = f"http://127.0.0.1:{adapter.server_address[1]}"

        def signed_post(payload: bytes):
            ts = str(int(time.time()))
            sig = priv.sign(ts.encode() + payload).hex()
            return urllib.request.Request(
                f"{base}/interactions", data=payload,
                headers={
                    "content-type": "application/json",
                    "X-Signature-Ed25519": sig,
                    "X-Signature-Timestamp": ts,
                },
            )

        # 1. Discord's PING handshake (signed)
        body = json.dumps({"type": PING}).encode()
        with urllib.request.urlopen(signed_post(body), timeout=30) as r:
            assert json.load(r)["type"] == PONG
        print("PING -> PONG handshake ok")

        # 2. forged requests are 401'd IN THE REQUEST PATH
        bad = urllib.request.Request(
            f"{base}/interactions", data=body,
            headers={
                "content-type": "application/json",
                "X-Signature-Ed25519": "00" * 64,
                "X-Signature-Timestamp": str(int(time.time())),
            },
        )
        try:
            urllib.request.urlopen(bad, timeout=30)
            raise AssertionError("forged signature accepted")
        except urllib.error.HTTPError as e:
            assert e.code == 401
        print("forged signature rejected with 401")

        # 3. a slash command: deferred ACK + spawned follow-up
        cmd = json.dumps({
            "type": APPLICATION_COMMAND,
            "application_id": "app123",
            "token": "interaction-tok-1",
            "api_base": api_base,
            "data": {"name": "status"},
        }).encode()
        t0 = time.time()
        with urllib.request.urlopen(signed_post(cmd), timeout=30) as r:
            ack = json.load(r)
        ack_ms = (time.time() - t0) * 1e3
        assert ack["type"] == DEFERRED
        assert ack_ms < 3000, f"missed Discord's 3 s deadline: {ack_ms:.0f} ms"
        print(f"slash command ACKed deferred in {ack_ms:.0f} ms")

        # 4. the background reply lands as the follow-up message
        deadline = time.time() + 60
        while time.time() < deadline:
            msg = followups.get("interaction-tok-1")
            if msg:
                break
            time.sleep(0.2)
        assert msg and "status" in msg, msg
        print(f"follow-up delivered: {msg.splitlines()[0]}")
        adapter.shutdown()
        gw.stop()
