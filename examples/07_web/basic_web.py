# # Basic web endpoints
#
# Mirrors the reference's 07_web/basic_web.py:43-46 and streaming.py:38-45:
# a GET endpoint, a POST endpoint, and a server-sent-events stream, all
# served by `tpurun serve examples/07_web/basic_web.py`.

import time

import modal_examples_tpu as mtpu

app = mtpu.App("example-basic-web")


@app.function()
@mtpu.fastapi_endpoint(docs=True)
def greet(user: str = "world") -> dict:
    return {"greeting": f"Hello, {user}!"}


@app.function()
@mtpu.fastapi_endpoint(method="POST")
def square(x: int) -> dict:
    return {"x": x, "squared": x * x}


@app.function()
@mtpu.fastapi_endpoint()
def stream(n: int = 3):
    """SSE stream: one event per count, 10 Hz."""
    for i in range(n):
        yield {"count": i}
        time.sleep(0.1)
