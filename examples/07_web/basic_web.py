# # Basic web endpoints
#
# Mirrors the reference's 07_web/basic_web.py:43-46 and streaming.py:38-45:
# a GET endpoint, a POST endpoint, and a server-sent-events stream, all
# served by `tpurun serve examples/07_web/basic_web.py`.

import time

import modal_examples_tpu as mtpu

app = mtpu.App("example-basic-web")


@app.function()
@mtpu.fastapi_endpoint(docs=True)
def greet(user: str = "world") -> dict:
    return {"greeting": f"Hello, {user}!"}


@app.function()
@mtpu.fastapi_endpoint(method="POST")
def square(x: int) -> dict:
    return {"x": x, "squared": x * x}


@app.function()
@mtpu.fastapi_endpoint()
def stream(n: int = 3):
    """SSE stream: one event per count, 10 Hz."""
    for i in range(n):
        yield {"count": i}
        time.sleep(0.1)


# ## Self-test entrypoint — `tpurun serve` hosts these endpoints for real
# traffic; `tpurun run` drives them through an ephemeral gateway.


@app.local_entrypoint()
def main():
    import json
    import urllib.request

    from modal_examples_tpu.web.gateway import Gateway

    with app.run():
        gw = Gateway(app).start()
        with urllib.request.urlopen(f"{gw.base_url}/greet?user=tpu") as r:
            assert json.load(r)["greeting"] == "Hello, tpu!"
        req = urllib.request.Request(
            f"{gw.base_url}/square", data=b'{"x": 12}',
            headers={"content-type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            assert json.load(r)["squared"] == 144
        with urllib.request.urlopen(f"{gw.base_url}/stream?n=2") as r:
            events = [l for l in r.read().decode().splitlines() if l.startswith("data:")]
        assert len(events) == 2
        gw.stop()
    print("GET, POST, and SSE endpoints OK")
