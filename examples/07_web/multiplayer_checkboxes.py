# # Multiplayer checkboxes: shared Dict state under concurrent writers
#
# TPU-native counterpart of the reference's
# 07_web/fasthtml-checkboxes/fasthtml_checkboxes.py — "deploy 100,000
# multiplayer checkboxes": a Dict-backed shared board that many clients
# mutate concurrently, with state surviving container restarts
# (fasthtml_checkboxes.py:30,52-60 keeps the board in a modal.Dict and
# restores it on boot). The reference renders FastHTML; per
# OUT_OF_SCOPE.md, UIs are cosmetic here — the API returns JSON and the
# *state semantics* (atomic toggles, diff polling, persistence,
# concurrent-writer correctness) are the point.
#
# Run: tpurun run examples/07_web/multiplayer_checkboxes.py

import os

import modal_examples_tpu as mtpu

N_CHECKBOXES = int(os.environ.get("MTPU_N_CHECKBOXES", "512"))

app = mtpu.App("example-multiplayer-checkboxes")
db = mtpu.Dict.from_name("checkboxes-db", create_if_missing=True)


def _board() -> list:
    """The board, restored from the Dict (the restart-survival path)."""
    board = db.get("board")
    if board is None or len(board) != N_CHECKBOXES:
        board = [False] * N_CHECKBOXES
        db.put("board", board)
        db.put("version", 0)
    return board


@app.function()
@mtpu.fastapi_endpoint()
def board() -> dict:
    """Full board state + version (clients diff-poll from here)."""
    return {
        "version": db.get("version", 0),
        "checked": [i for i, v in enumerate(_board()) if v],
        "n": N_CHECKBOXES,
    }


@app.function()
@mtpu.fastapi_endpoint(method="POST")
def toggle(i: int, client: str = "anon") -> dict:
    """Atomically toggle one checkbox; the Dict's put_if_absent-based lock
    serializes writers (many containers may run this concurrently)."""
    if not 0 <= i < N_CHECKBOXES:
        return {"error": f"index {i} out of range", "n": N_CHECKBOXES}
    # spin-lock via put_if_absent: the Dict is the only shared medium
    # between containers, so it is also the mutex
    import time as _t

    while not db.put_if_absent("lock", client):
        _t.sleep(0.001)
    try:
        board = _board()
        board[i] = not board[i]
        version = db.get("version", 0) + 1
        db.put("board", board)
        db.put("version", version)
        db.put(f"last_writer:{i}", client)
    finally:
        db.pop("lock", None)  # release (Dict.delete removes a whole dict)
    return {"i": i, "checked": board[i], "version": version}


@app.function()
@mtpu.fastapi_endpoint()
def stats() -> dict:
    board = _board()
    return {
        "version": db.get("version", 0),
        "n_checked": sum(board),
        "n": N_CHECKBOXES,
    }


@app.local_entrypoint()
def main(clients: int = 8, toggles_per_client: int = 40):
    import json
    import threading
    import urllib.request

    from modal_examples_tpu.web.gateway import Gateway

    # fresh board per invocation: the Dict is a persistent named store
    # (that's the point of the restart test below), so the deterministic
    # assertions reset it up front
    db.put("board", [False] * N_CHECKBOXES)
    db.put("version", 0)

    with app.run():
        gw = Gateway(app).start()
        base = gw.base_url

        def post(path):
            req = urllib.request.Request(base + path, data=b"{}")
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.load(r)

        def get(path):
            with urllib.request.urlopen(base + path, timeout=60) as r:
                return json.load(r)

        # concurrent writers: each client toggles a deterministic set, so
        # the final board state is exactly predictable regardless of
        # interleaving — every index i gets toggled count(i) times, and
        # checked(i) == count(i) % 2 == 1
        counts = [0] * N_CHECKBOXES
        plans = []
        for c in range(clients):
            plan = [(c * 7 + 3 * k) % N_CHECKBOXES
                    for k in range(toggles_per_client)]
            plans.append(plan)
            for i in plan:
                counts[i] += 1

        errors = []

        def run_client(c):
            try:
                for i in plans[c]:
                    post(f"/toggle?i={i}&client=client-{c}")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=run_client, args=(c,))
            for c in range(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors

        state = get("/board")
        want = {i for i, n in enumerate(counts) if n % 2 == 1}
        got = set(state["checked"])
        assert got == want, (
            f"lost updates: {len(want ^ got)} boxes diverged "
            f"(version={state['version']})"
        )
        assert state["version"] == clients * toggles_per_client
        print(
            f"{clients} concurrent clients x {toggles_per_client} toggles: "
            f"board consistent, version={state['version']}, "
            f"{len(got)} boxes checked"
        )
        gw.stop()

    # persistence across app runs: the Dict outlives the run context
    with app.run():
        gw = Gateway(app).start()
        with urllib.request.urlopen(gw.base_url + "/stats", timeout=60) as r:
            stats2 = json.load(r)
        assert stats2["n_checked"] == len(want)
        print(f"state survived restart: {stats2['n_checked']} still checked")
        gw.stop()
