# # Sticky routing for servers
#
# The counterpart of the reference's 07_web/server_sticky.py:16-27:
# sequential requests from the same client land on the same server replica
# via rendezvous (highest-random-weight) hashing — a performance
# optimization for stateful replicas (KV caches, session state), not a
# correctness guarantee. Replicas joining or leaving only move the keys
# they own.
#
# Here we boot several replicas of a tiny stateful HTTP server (each counts
# the requests it has seen per session), route a stream of sessions with
# `rendezvous_pick`, and then verify the two properties that matter:
# stickiness (one replica per session) and balance (sessions spread across
# replicas).

import collections
import http.server
import json
import threading
import urllib.request

import modal_examples_tpu as mtpu
from modal_examples_tpu.web.routing import rendezvous_pick, rendezvous_rank

app = mtpu.App("example-server-sticky")


# ## The replica: a raw-port server with per-session state
#
# `@app.server(sticky_header=...)` declares the header the router hashes on
# (the reference's sticky routing key). The server itself just remembers how
# many times each session hit it.


def make_replica(replica_id: str, port: int):
    seen: dict[str, int] = collections.Counter()

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            session = self.headers.get("x-session-id", "anon")
            seen[session] += 1
            body = json.dumps(
                {"replica": replica_id, "session": session, "hits": seen[session]}
            ).encode()
            self.send_response(200)
            self.send_header("content-type", "application/json")
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


@app.local_entrypoint()
def main(n_replicas: int = 3, n_sessions: int = 60, requests_per_session: int = 3):
    import socket

    # boot the replica set
    servers, urls = [], {}
    for i in range(n_replicas):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        rid = f"replica-{i}"
        servers.append(make_replica(rid, port))
        urls[rid] = f"http://127.0.0.1:{port}"

    replicas = sorted(urls)

    # route: same session key -> same replica, every time
    assignments: dict[str, set[str]] = collections.defaultdict(set)
    load = collections.Counter()
    for s_idx in range(n_sessions):
        session = f"session-{s_idx}"
        for _ in range(requests_per_session):
            rid = rendezvous_pick(session, replicas)
            req = urllib.request.Request(
                f"{urls[rid]}/", headers={"x-session-id": session}
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                out = json.load(r)
            assignments[session].add(out["replica"])
        load[rendezvous_pick(session, replicas)] += 1

    # stickiness: every session only ever saw one replica
    assert all(len(v) == 1 for v in assignments.values()), assignments
    # balance: no replica owns everything (HRW spreads keys ~uniformly)
    print("session load per replica:", dict(load))
    assert len(load) == n_replicas and max(load.values()) < n_sessions, load

    # elasticity: removing a replica only moves the sessions it owned
    survivor_set = replicas[:-1]
    moved = sum(
        1
        for s_idx in range(n_sessions)
        if rendezvous_pick(f"session-{s_idx}", replicas)
        != rendezvous_pick(f"session-{s_idx}", survivor_set)
    )
    owned_by_last = sum(
        1
        for s_idx in range(n_sessions)
        if rendezvous_pick(f"session-{s_idx}", replicas) == replicas[-1]
    )
    print(f"scale-down moved {moved} sessions (replica owned {owned_by_last})")
    assert moved == owned_by_last  # only orphaned keys re-home

    # a full preference order is also available for failover routing
    print("failover order for session-0:", rendezvous_rank("session-0", replicas))
    for srv in servers:
        srv.shutdown()
    print("sticky routing OK")
