# # Hello, world!
#
# The canonical first example, mirroring the reference's
# 01_getting_started/hello_world.py (cited lines per SURVEY.md §3.1): an App,
# a function, and the three invocation modes — `.local`, `.remote`, `.map` —
# driven from a `local_entrypoint` so `tpurun run examples/01_getting_started/
# hello_world.py` works end to end.

import sys

import modal_examples_tpu as mtpu

app = mtpu.App("example-hello-world")


@app.function()
def f(i: int) -> int:
    if i % 2 == 0:
        print("hello", i)
    else:
        print("world", i, file=sys.stderr)
    return i * i


@app.local_entrypoint()
def main(n: int = 20):
    # run the function locally, in-process
    print("local:", f.local(1000))

    # run the function remotely, in a container
    print("remote:", f.remote(1000))

    # fan out over containers, streaming ordered results back
    total = 0
    for ret in f.map(range(n)):
        total += ret
    print("map total:", total)
