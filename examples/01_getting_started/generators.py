# # Streaming generators
#
# Counterpart of the reference's 01_getting_started/generators.py:21 —
# a generator function streams results back with `.remote_gen`.

import modal_examples_tpu as mtpu

app = mtpu.App("example-generators")


@app.function()
def f(i: int):
    for j in range(i):
        yield j * j


@app.local_entrypoint()
def main():
    out = []
    for r in f.remote_gen(5):
        print("got", r)
        out.append(r)
    assert out == [0, 1, 4, 9, 16]
