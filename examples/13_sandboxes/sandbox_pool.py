# # Maintain a pool of warm sandboxes
#
# The counterpart of the reference's 13_sandboxes/sandbox_pool.py:6-30: a
# pool of pre-created ("warm") sandboxes registered in a Queue, so claiming
# one is instant — useful when sandboxes do significant setup (installing
# dependencies, starting a server) before they can serve.
#
# Mechanics mirrored from the reference: a Queue holds references to warm
# sandboxes with their expiry times; `claim` pops until it finds one with
# enough time-to-live left; a `fill` step tops the pool back up; expired or
# broken sandboxes are terminated and skipped.

import time

import modal_examples_tpu as mtpu

app = mtpu.App("example-sandbox-pool")

POOL_NAME = "sandbox-pool-demo"
SANDBOX_TTL = 120.0  # seconds each sandbox lives after creation
MIN_TTL_AT_CLAIM = 10.0  # don't hand out sandboxes about to expire


def _make_warm_sandbox() -> dict:
    """Create a sandbox and do its expensive warmup once, up front."""
    sb = mtpu.Sandbox.create(app=app, timeout=SANDBOX_TTL)
    # warmup: the reference installs deps / boots a server here; we stage a
    # workspace file the claimant will use
    with sb.open("workspace.txt", "w") as f:
        f.write("warmed\n")
    return {"sandbox_id": sb.object_id, "expires_at": time.time() + SANDBOX_TTL}


def fill_pool(pool: mtpu.Queue, target: int) -> int:
    """Top the pool up to `target` warm sandboxes."""
    added = 0
    while pool.len() < target:
        pool.put(_make_warm_sandbox())
        added += 1
    return added


def claim(pool: mtpu.Queue) -> mtpu.Sandbox | None:
    """Pop until a sandbox with enough TTL appears; terminate stale ones."""
    while True:
        try:
            entry = pool.get(block=False)
        except Exception:
            return None
        if entry is None:
            return None
        ttl = entry["expires_at"] - time.time()
        sb = mtpu.Sandbox.from_id(entry["sandbox_id"])
        if ttl < MIN_TTL_AT_CLAIM:
            sb.terminate()  # stale: drop and keep looking
            continue
        return sb


@app.local_entrypoint()
def main(pool_size: int = 3):
    pool = mtpu.Queue.from_name(POOL_NAME, create_if_missing=True)

    added = fill_pool(pool, pool_size)
    print(f"filled pool with {added} warm sandboxes (size={pool.len()})")
    assert pool.len() == pool_size

    # claiming is instant: the warmup already happened
    t0 = time.time()
    sb = claim(pool)
    claim_s = time.time() - t0
    assert sb is not None
    print(f"claimed {sb.object_id} in {claim_s * 1000:.0f}ms")

    # the claimed sandbox is warm: the staged workspace is there and it
    # executes immediately
    p = sb.exec("cat", "workspace.txt")
    assert p.wait() == 0 and "warmed" in p.stdout.read()
    print("claimed sandbox is warm and serving")
    sb.terminate()

    # top back up after the claim, like the reference's maintain step
    fill_pool(pool, pool_size)
    assert pool.len() == pool_size
    print(f"pool refilled to {pool.len()}")

    # drain on the way out
    while (left := claim(pool)) is not None:
        left.terminate()
    print("sandbox pool OK")
