# # Safe code execution in sandboxes
#
# Counterpart of 13_sandboxes/safe_code_execution.py:21-41 — run untrusted
# (e.g. LLM-generated) code in an isolated sandbox with an exec API and
# streamed output, plus the warm-pool pattern from sandbox_pool.py:6-30.

import sys

import modal_examples_tpu as mtpu

app = mtpu.App("example-safe-code-execution")

UNTRUSTED_CODE = """
import os
print("hello from the sandbox")
print("cwd:", os.getcwd())
print("secret env leaked:", "MTPU_STATE_DIR" in os.environ)
total = sum(i * i for i in range(10))
print("computed:", total)
"""


@app.local_entrypoint()
def main():
    sb = mtpu.Sandbox.create(timeout=60)
    try:
        # write the code into the sandbox filesystem, then execute it
        with sb.open("job.py", "w") as f:
            f.write(UNTRUSTED_CODE)
        proc = sb.exec(sys.executable, "job.py")
        out = proc.stdout.read()
        code = proc.wait()
        print(out)
        assert code == 0
        assert "computed: 285" in out
        assert "secret env leaked: False" in out  # env was scrubbed

        # a failing command surfaces its stderr and exit code
        bad = sb.exec(sys.executable, "-c", "raise ValueError('nope')")
        assert bad.wait() != 0
        assert "ValueError" in bad.stderr.read()

        # warm pool: sandboxes registered in a Queue, claimed by workers
        with mtpu.Queue.ephemeral() as pool:
            for _ in range(2):
                warm = mtpu.Sandbox.create(timeout=60)
                pool.put(warm.object_id)
            claimed = mtpu.Sandbox.from_id(pool.get())
            p = claimed.exec(sys.executable, "-c", "print(6*7)")
            assert p.stdout.read().strip() == "42"
            claimed.cleanup()
            mtpu.Sandbox.from_id(pool.get()).cleanup()
        print("sandbox exec, isolation, and warm pool OK")
    finally:
        sb.cleanup()
