# # Drive a sandbox with an agent loop
#
# The counterpart of the reference's 13_sandboxes/sandbox_agent.py:29-62: an
# agent operates an isolated sandbox through an observe → decide → act loop
# — it runs commands, reads their output, and decides the next action until
# the task is done. The reference puts a hosted coding agent in the loop;
# here the policy is a small deterministic planner (swap `policy` for a call
# to the llm_inference example's OpenAI endpoint to make it model-driven —
# the action protocol stays the same).
#
# The task: the sandbox contains a failing test. The agent explores the
# workspace, runs the test, localizes the bug from the traceback, patches
# the file, and re-runs the test until green.

import modal_examples_tpu as mtpu

app = mtpu.App("example-sandbox-agent")

BUGGY_MODULE = """\
def add(a, b):
    return a - b  # BUG
"""

TEST_FILE = """\
import mylib
assert mylib.add(2, 3) == 5, f"add(2,3) gave {mylib.add(2, 3)}"
print("TESTS PASSED")
"""


def policy(transcript: list[dict]) -> dict:
    """Decide the next action from what the agent has seen so far.

    Actions (the same shape an LLM tool-use loop would emit):
      {"run": [...argv]}                 — execute a command
      {"write": {"path":..., "text":..}} — write a file
      {"done": bool}                     — finish
    """
    if not transcript:
        return {"run": ["ls"]}  # observe the workspace first
    last = transcript[-1]
    if last["action"] == {"run": ["ls"]}:
        return {"run": ["python", "test_mylib.py"]}  # reproduce the failure
    if "TESTS PASSED" in last.get("stdout", ""):
        return {"done": True}
    if "AssertionError" in last.get("stderr", ""):
        # localize: the traceback names mylib.add; patch the implementation
        return {"write": {"path": "mylib.py", "text": "def add(a, b):\n    return a + b\n"}}
    if last["action"].get("write"):
        return {"run": ["python", "test_mylib.py"]}  # verify the fix
    return {"done": False}


@app.local_entrypoint()
def main(max_steps: int = 8):
    sb = mtpu.Sandbox.create(app=app, timeout=120)
    with sb.open("mylib.py", "w") as f:
        f.write(BUGGY_MODULE)
    with sb.open("test_mylib.py", "w") as f:
        f.write(TEST_FILE)

    transcript: list[dict] = []
    solved = False
    for step in range(max_steps):
        action = policy(transcript)
        print(f"step {step}: {action}")
        if "done" in action:
            solved = action["done"]
            break
        obs = {"action": action, "stdout": "", "stderr": ""}
        if "run" in action:
            p = sb.exec(*action["run"])
            p.wait()
            obs["stdout"] = p.stdout.read()
            obs["stderr"] = p.stderr.read()
        elif "write" in action:
            with sb.open(action["write"]["path"], "w") as f:
                f.write(action["write"]["text"])
        transcript.append(obs)

    sb.terminate()
    assert solved, "agent did not finish the task"
    assert any("TESTS PASSED" in t.get("stdout", "") for t in transcript)
    print(f"agent fixed the bug in {len(transcript)} actions")
