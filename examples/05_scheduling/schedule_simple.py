# # Scheduled functions
#
# Counterpart of 05_scheduling/schedule_simple.py:27,34 — `Period` and
# `Cron` schedules fire on deployed apps (`tpurun deploy` keeps the
# scheduler loop alive). The entrypoint demonstrates a bounded scheduler run.

import time

import modal_examples_tpu as mtpu

app = mtpu.App("example-schedules")
heartbeat_log = mtpu.Dict.from_name("schedule-heartbeats")


@app.function(schedule=mtpu.Period(seconds=2))
def heartbeat():
    ts = time.time()
    heartbeat_log[f"beat-{int(ts * 1000)}"] = ts
    print(f"heartbeat at {ts:.1f}")


@app.function(schedule=mtpu.Cron("0 9 * * 1-5"))
def weekday_report():
    print("good morning — weekday 9am report")


@app.local_entrypoint()
def main(seconds: float = 5.0):
    heartbeat_log.clear()
    fired = app.run_scheduler(duration=seconds)
    beats = len(heartbeat_log)
    print(f"scheduler fired {fired} times; {beats} heartbeats recorded")
    assert beats >= 1
