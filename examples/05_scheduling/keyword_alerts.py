# # Scheduled keyword alerts: a cron job that scans and notifies
#
# TPU-native counterpart of the reference's
# 05_scheduling/hackernews_alerts.py (a daily `modal.Cron` job that
# searches Hacker News for a keyword and sends Slack alerts). Zero
# egress, so the scanned feed is this app's own content stream (a Queue
# that producers append to) and the "Slack channel" is a Dict-backed
# notification inbox — the scheduling, scanning, dedup, and notification
# mechanics are the real thing:
#
# - `Period(seconds=N)`/`Cron` drive the scan on a schedule;
# - each scan drains new items, matches keywords, dedupes alerts
#   (put_if_absent — never alert the same item twice), and notifies;
# - state survives across scan invocations (Dict + Queue persistence).
#
# Run: tpurun run examples/05_scheduling/keyword_alerts.py

import modal_examples_tpu as mtpu

app = mtpu.App("example-keyword-alerts")
feed = mtpu.Queue.from_name("alerts-feed", create_if_missing=True)
inbox = mtpu.Dict.from_name("alerts-inbox", create_if_missing=True)
seen = mtpu.Dict.from_name("alerts-seen", create_if_missing=True)

KEYWORDS = ("tpu", "pallas")


# The reference scans daily (`modal.Cron`, hackernews_alerts.py:97); a
# 2-second Period here lets one `tpurun run` observe several scans.
# Swap `schedule=mtpu.Cron("0 9 * * *")` for the daily shape on deploy.
@app.function(schedule=mtpu.Period(seconds=2))
def scan() -> dict:
    """One scheduled scan: drain the feed, alert on keyword matches."""
    from modal_examples_tpu.storage.dict_queue import Empty

    matched = drained = 0
    while True:
        try:
            item = feed.get(block=False)
        except Empty:
            break
        drained += 1
        item_id, text = item["id"], item["text"]
        if not any(k in text.lower() for k in KEYWORDS):
            continue
        if not seen.put_if_absent(item_id, True):
            continue  # already alerted on this item
        # keyed by item id (put_if_absent already made this scan the sole
        # owner of item_id), so overlapping scans can never overwrite each
        # other's alerts; count is advisory display state
        inbox.put(f"alert:{item_id}", {"id": item_id, "text": text})
        inbox.put("count", inbox.get("count", 0) + 1)
        matched += 1
    return {"drained": drained, "alerted": matched}


@app.local_entrypoint()
def main():
    # reset persistent state for a deterministic, repeatable demo (the
    # dedup Dict survives runs by design — without the clear, the second
    # run would correctly alert on nothing)
    seen.clear()
    inbox.clear()
    inbox.put("count", 0)

    with app.run():
        # producers post items, then the scheduler runs scans over them
        items = [
            ("a1", "New TPU kernels land in the framework"),
            ("a2", "Totally unrelated cooking recipe"),
            ("a3", "Pallas guide updated with DMA patterns"),
            ("a4", "Another recipe, still no match"),
            ("a1", "New TPU kernels land in the framework"),  # duplicate
        ]
        for item_id, text in items[:2]:
            feed.put({"id": item_id, "text": text})
        app.run_scheduler(duration=3.0)
        for item_id, text in items[2:]:
            feed.put({"id": item_id, "text": text})
        app.run_scheduler(duration=3.0)

    alerts = [
        inbox.get(k) for k in sorted(inbox.keys()) if k.startswith("alert:")
    ]
    print(f"{len(alerts)} alerts delivered:")
    for a in alerts:
        print(f"  [{a['id']}] {a['text']}")
    ids = [a["id"] for a in alerts]
    assert set(ids) == {"a1", "a3"}, ids  # both keywords, deduped
    assert len(ids) == 2, ids  # the duplicate a1 alerted exactly once
    print("keyword matching, dedup, and scheduled scans OK")
