# # Ingest an image dataset into a bucket mount, with a disk-space watchdog
#
# The counterpart of the reference's 12_datasets/coco.py:26-54: a dataset
# ingestion job that downloads archives into scratch disk, extracts them,
# and lands the result in a CloudBucketMount — with a background thread
# logging free disk space the whole time (large-archive ingests are where
# containers quietly run out of disk; the watchdog makes it visible in the
# logs before the job dies).
#
# Cheap mode generates a small synthetic COCO-shaped archive instead of the
# real 25GB download; the pipeline (scratch -> extract -> bucket -> verify)
# is the same.

import io
import json
import os
import shutil
import sys
import tarfile
import threading
import time

import modal_examples_tpu as mtpu

bucket = mtpu.CloudBucketMount("example-datasets", key_prefix="coco")
app = mtpu.App("example-coco-ingest")


def start_monitoring_disk_space(interval: float = 5.0) -> None:
    """Log free disk space from a daemon thread while the ingest runs
    (coco.py:38-54's monitor, with the container's input id as the tag)."""
    task_id = mtpu.current_input_id() or "local"

    def log_disk_space() -> None:
        while True:
            statvfs = os.statvfs("/")
            free = statvfs.f_frsize * statvfs.f_bavail
            print(
                f"{task_id} free disk space: {free / 1024**3:.2f} GiB",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(interval)

    threading.Thread(target=log_disk_space, daemon=True).start()


def _synthetic_coco_archive(n_images: int) -> bytes:
    """A small tarball shaped like a COCO split: images + annotations."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        ann = {
            "images": [{"id": i, "file_name": f"{i:012d}.jpg"} for i in range(n_images)],
            "annotations": [],
        }
        data = json.dumps(ann).encode()
        info = tarfile.TarInfo("annotations/instances.json")
        info.size = len(data)
        tf.addfile(info, io.BytesIO(data))
        for i in range(n_images):
            pixels = bytes([i % 256]) * 1024  # stand-in JPEG payload
            info = tarfile.TarInfo(f"images/{i:012d}.jpg")
            info.size = len(pixels)
            tf.addfile(info, io.BytesIO(pixels))
    return buf.getvalue()


@app.function(volumes={"/mnt/datasets": bucket}, timeout=3600)
def ingest_split(split: str, n_images: int = 8) -> dict:
    start_monitoring_disk_space(interval=2.0)

    # 1) "download" into scratch disk (cheap mode synthesizes the archive;
    #    the real job wgets the 25GB zips here, which is why the watchdog
    #    and the scratch/bucket split exist)
    scratch = f"/tmp/coco-{split}"
    os.makedirs(scratch, exist_ok=True)
    archive_path = os.path.join(scratch, f"{split}.tar.gz")
    with open(archive_path, "wb") as f:
        f.write(_synthetic_coco_archive(n_images))

    # 2) extract in scratch, then move the tree into the bucket mount
    with tarfile.open(archive_path) as tf:
        tf.extractall(scratch, filter="data")
    dest = f"/mnt/datasets/{split}"
    os.makedirs(f"{dest}/images", exist_ok=True)
    os.makedirs(f"{dest}/annotations", exist_ok=True)
    n_moved = 0
    # shutil.move, not os.replace: scratch (/tmp, often tmpfs) and the bucket
    # mount are usually different filesystems (EXDEV)
    for name in sorted(os.listdir(f"{scratch}/images")):
        shutil.move(f"{scratch}/images/{name}", f"{dest}/images/{name}")
        n_moved += 1
    shutil.move(
        f"{scratch}/annotations/instances.json",
        f"{dest}/annotations/instances.json",
    )

    # 3) verify from the bucket side: annotation index matches the files
    with open(f"{dest}/annotations/instances.json") as f:
        ann = json.load(f)
    listed = set(os.listdir(f"{dest}/images"))
    missing = [im["file_name"] for im in ann["images"] if im["file_name"] not in listed]
    return {"split": split, "images": n_moved, "missing": len(missing)}


@app.local_entrypoint()
def main(n_images: int = 8):
    results = list(
        ingest_split.starmap(
            [("train2017", n_images), ("val2017", n_images)]
        )
    )
    for r in results:
        print(r)
        assert r["missing"] == 0, r
    print("coco-style ingest OK")
