# # Dataset ingest to a cloud bucket mount
#
# Counterpart of 12_datasets/coco.py:26-54 and s3_bucket_mount.py — ingest
# shards into a CloudBucketMount-backed path from parallel workers, with the
# disk-space watchdog pattern (coco.py:38-54).

import json

import modal_examples_tpu as mtpu

app = mtpu.App("example-dataset-ingest")
bucket = mtpu.CloudBucketMount("example-datasets", key_prefix="tone-corpus")


@app.function(timeout=600, max_containers=4)
def ingest_shard(shard_id: int, n_items: int) -> dict:
    """Generate one shard of (audio-features, transcript) records."""
    import shutil

    import numpy as np

    from modal_examples_tpu.utils.audio import log_mel_spectrogram, synth_tone_audio

    # disk-space watchdog (coco.py:38-54): bail before filling the disk
    free_gb = shutil.disk_usage(bucket.local_path).free / 1e9
    if free_gb < 1.0:
        raise RuntimeError(f"only {free_gb:.1f}GB free; aborting ingest")

    shard_dir = bucket.local_path / f"shard-{shard_id:04d}"
    shard_dir.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(shard_id)
    for i in range(n_items):
        freq = float(rng.uniform(200, 2000))
        mel = log_mel_spectrogram(synth_tone_audio([freq], 0.5), pad_to_chunk=False)
        np.save(shard_dir / f"mel-{i:05d}.npy", mel)
    (shard_dir / "manifest.json").write_text(
        json.dumps({"shard": shard_id, "items": n_items})
    )
    return {"shard": shard_id, "items": n_items}


@app.local_entrypoint()
def main(n_shards: int = 4, items_per_shard: int = 8):
    results = list(
        ingest_shard.starmap((i, items_per_shard) for i in range(n_shards))
    )
    total = sum(r["items"] for r in results)
    manifests = sorted(bucket.local_path.glob("shard-*/manifest.json"))
    print(f"ingested {total} items into {len(manifests)} shards at {bucket}")
    assert len(manifests) == n_shards
