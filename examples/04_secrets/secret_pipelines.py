# # Secrets in data pipelines
#
# Counterpart of 04_secrets/db_to_sheet.py — credentials for external
# systems (Postgres + Google Sheets there) arrive as named Secrets that
# materialize only inside the container's environment. The external systems
# are stood in by a credential-checking stub (zero-egress environment); the
# secret plumbing is the real thing.
#
# Run: tpurun run examples/04_secrets/secret_pipelines.py

import os

import modal_examples_tpu as mtpu

app = mtpu.App("example-secrets")

# register the named secrets the pipeline expects (in production:
# `tpurun secret create warehouse-creds DB_PASSWORD=...`)
mtpu.Secret.create("warehouse-creds", {"DB_USER": "analytics", "DB_PASSWORD": "s3cret"})
mtpu.Secret.create("report-sink-creds", {"SINK_TOKEN": "tok-123"})

warehouse = mtpu.Secret.from_name(
    "warehouse-creds", required_keys=["DB_USER", "DB_PASSWORD"]
)
sink = mtpu.Secret.from_name("report-sink-creds", required_keys=["SINK_TOKEN"])


@app.function(secrets=[warehouse])
def extract_rows() -> list[dict]:
    """'Query the warehouse' — creds come from the container env only."""
    assert os.environ["DB_USER"] == "analytics"
    assert os.environ["DB_PASSWORD"] == "s3cret"
    return [{"day": d, "requests": 100 + 7 * d} for d in range(5)]


def _isolated() -> bool:
    # per-function env isolation is a container property; the inline dev
    # backend shares one interpreter (and therefore one environ)
    from modal_examples_tpu._internal.config import backend

    return backend() == "process"


@app.function(secrets=[sink])
def publish_report(rows: list[dict]) -> str:
    """'Write the sheet' — a different function gets different creds."""
    assert os.environ["SINK_TOKEN"] == "tok-123"
    if _isolated():
        assert "DB_PASSWORD" not in os.environ  # least privilege per function
    total = sum(r["requests"] for r in rows)
    return f"published {len(rows)} rows, {total} total requests"


@app.local_entrypoint()
def main():
    rows = extract_rows.remote()
    result = publish_report.remote(rows)
    print(result)
    if _isolated():
        # the client process never saw the secret values in its env
        assert "DB_PASSWORD" not in os.environ
    assert result.startswith("published 5 rows")
