# # Cloud bucket mounts: datasets in object storage, read as files
#
# TPU-native counterpart of the reference's
# 10_integrations/s3_bucket_mount.py and
# 12_datasets/cloud_bucket_mount_loras.py: mount an object-store bucket
# at a path, read dataset files through the filesystem, write results
# back. The backing store here is GCS through the framework's own
# JSON-API client (storage.gcs — bearer/metadata auth, pagination);
# zero egress, so this example runs against a local fake-GCS server
# speaking the same protocol (the fake-gcs-server emulator pattern) —
# point `bucket_endpoint_url` at nothing to hit real
# storage.googleapis.com with TPU-VM metadata credentials.
#
# Run: tpurun run examples/10_integrations/bucket_mount.py

import modal_examples_tpu as mtpu

app = mtpu.App("example-bucket-mount")


@app.function()
def summarize(mount_path: str) -> dict:
    """A worker that only sees FILES — the mount abstraction's point
    (s3_bucket_mount.py's readers never talk to boto3)."""
    from pathlib import Path

    counts = {}
    for p in sorted(Path(mount_path).rglob("*.txt")):
        counts[p.name] = len(p.read_text().split())
    return counts


@app.local_entrypoint()
def main():
    import shutil
    import sys
    from pathlib import Path

    # the local fake GCS server from the test suite IS the demo backend
    # (path derived from __file__ so the example runs from any cwd)
    sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "tests"))
    from test_gcs import _FakeGCS

    from modal_examples_tpu.storage.gcs import GCSClient

    srv = _FakeGCS()
    try:
        # seed the bucket like a dataset upload job would
        seed = GCSClient(endpoint=srv.endpoint)
        seed.put_object(
            "datasets", "reviews/train/a.txt", b"five words are in here"
        )
        seed.put_object(
            "datasets", "reviews/train/b.txt", b"three more words"
        )
        seed.put_object("datasets", "other/skip.txt", b"wrong prefix")

        mount = mtpu.CloudBucketMount(
            "datasets", key_prefix="reviews",
            bucket_endpoint_url=srv.endpoint,
        )
        # the mount dir persists across runs by design; clear it so the
        # demo's exact-count asserts are repeatable
        shutil.rmtree(mount.local_path, ignore_errors=True)
        mount.local_path.mkdir(parents=True, exist_ok=True)
        n = mount.pull()
        print(f"pulled {n} objects into {mount.local_path}")
        assert n == 2

        with app.run():
            counts = summarize.remote(str(mount.local_path))
        print("word counts:", counts)
        assert counts == {"a.txt": 5, "b.txt": 3}

        # write back results under the prefix (the read-write half)
        (mount.local_path / "train" / "summary.txt").write_text(
            f"total {sum(counts.values())} words"
        )
        mount.push()
        back = seed.get_object("datasets", "reviews/train/summary.txt")
        print("wrote back:", back.decode())
        assert back == b"total 8 words"
        print("bucket mount pull/read/push OK")
    finally:
        srv.stop()
