# # Deploy a remote, stateless MCP server
#
# The counterpart of the reference's 10_integrations/mcp_server_stateless.py:
# a Model Context Protocol server hosted as a serverless web endpoint, using
# the stateless "streamable HTTP" transport (every request carries a full
# JSON-RPC message; no session state between requests — which is exactly
# what maps onto serverless Functions). The reference wraps the FastMCP
# library; here the protocol layer is small enough to speak directly: an
# ASGI app handling `initialize`, `tools/list`, and `tools/call`.
#
# The server exposes the same tool as the reference: current date and time
# in a requested timezone.

import datetime
import json
import urllib.request
import zoneinfo

import modal_examples_tpu as mtpu

app = mtpu.App("example-mcp-server")

PROTOCOL_VERSION = "2025-03-26"

TOOLS = [
    {
        "name": "current_date_and_time",
        "description": "Get the current date and time in a timezone "
        "(ISO 8601). Defaults to UTC.",
        "inputSchema": {
            "type": "object",
            "properties": {
                "timezone": {"type": "string", "description": "IANA timezone"}
            },
        },
    }
]


def _call_tool(name: str, arguments: dict) -> dict:
    if name != "current_date_and_time":
        return {
            "content": [{"type": "text", "text": f"unknown tool {name!r}"}],
            "isError": True,
        }
    tz_name = arguments.get("timezone", "UTC")
    try:
        tz = zoneinfo.ZoneInfo(tz_name)
    except Exception:
        return {
            "content": [
                {"type": "text", "text": f"Invalid timezone {tz_name!r}"}
            ],
            "isError": True,
        }
    now = datetime.datetime.now(tz).isoformat()
    return {"content": [{"type": "text", "text": now}], "isError": False}


def _handle_rpc(msg: dict) -> dict | None:
    """One stateless JSON-RPC 2.0 exchange (notifications return None)."""
    method = msg.get("method", "")
    rpc_id = msg.get("id")
    if rpc_id is None:
        return None  # notification (e.g. notifications/initialized)
    if method == "initialize":
        result = {
            "protocolVersion": PROTOCOL_VERSION,
            "capabilities": {"tools": {}},
            "serverInfo": {"name": "Date and Time MCP Server", "version": "1.0"},
        }
    elif method == "tools/list":
        result = {"tools": TOOLS}
    elif method == "tools/call":
        params = msg.get("params", {})
        result = _call_tool(params.get("name", ""), params.get("arguments", {}))
    else:
        return {
            "jsonrpc": "2.0",
            "id": rpc_id,
            "error": {"code": -32601, "message": f"method {method!r} not found"},
        }
    return {"jsonrpc": "2.0", "id": rpc_id, "result": result}


# ## The ASGI app — the streamable-HTTP endpoint at /mcp


@app.function()
@mtpu.asgi_app()
def mcp():
    async def asgi(scope, receive, send):
        if scope["type"] != "http" or scope["method"] != "POST":
            await send(
                {"type": "http.response.start", "status": 405, "headers": []}
            )
            await send({"type": "http.response.body", "body": b""})
            return
        body = b""
        while True:
            event = await receive()
            body += event.get("body", b"")
            if not event.get("more_body"):
                break
        reply = _handle_rpc(json.loads(body or b"{}"))
        payload = json.dumps(reply).encode() if reply else b""
        await send(
            {
                "type": "http.response.start",
                "status": 200 if reply else 202,
                "headers": [(b"content-type", b"application/json")],
            }
        )
        await send({"type": "http.response.body", "body": payload})

    return asgi


# ## Client smoke test — the reference's test_tool entrypoint shape:
# initialize, list tools, call the tool, check the answer


@app.local_entrypoint()
def main(timezone: str = "Europe/Istanbul"):
    from modal_examples_tpu.web.gateway import Gateway

    def rpc(url: str, method: str, params: dict | None = None, rpc_id=1):
        body = {"jsonrpc": "2.0", "id": rpc_id, "method": method}
        if params is not None:
            body["params"] = params
        req = urllib.request.Request(
            url,
            data=json.dumps(body).encode(),
            headers={"content-type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.load(r)

    with app.run():
        gw = Gateway(app).start()
        try:
            url = f"{gw.base_url}/mcp"
            init = rpc(url, "initialize", {"protocolVersion": PROTOCOL_VERSION})
            assert init["result"]["serverInfo"]["name"].startswith("Date")
            tools = rpc(url, "tools/list")["result"]["tools"]
            print("tools:", [t["name"] for t in tools])
            assert tools[0]["name"] == "current_date_and_time"

            out = rpc(
                url,
                "tools/call",
                {"name": "current_date_and_time", "arguments": {"timezone": timezone}},
            )["result"]
            stamp = out["content"][0]["text"]
            print(f"time in {timezone}: {stamp}")
            assert not out["isError"] and "T" in stamp

            bad = rpc(
                url,
                "tools/call",
                {"name": "current_date_and_time", "arguments": {"timezone": "Not/AZone"}},
            )["result"]
            assert bad["isError"]
        finally:
            gw.stop()
    print("MCP server OK")
