# ---
# env: {"MTPU_PRETRAIN_STEPS": "250", "MTPU_LORA_STEPS": "200"}
# timeout: 900
# ---
# # LoRA playground: adapters in a bucket, chosen per request
#
# TPU-native counterpart of the reference's
# 10_integrations/cloud_bucket_mount_loras.py ("LoRAs Galore"): a bucket
# holds a library of LoRA adapters; the inference service mounts the
# bucket, loads the adapter the REQUEST names, applies it to the shared
# base diffusion model, and generates. Same architecture, framework
# pieces: CloudBucketMount over the from-scratch GCS client (fake-GCS
# server backend in this zero-egress demo), the generic tree-LoRA
# (models.lora) on the MMDiT, and a web endpoint for the playground.
#
# The reference pulls published SDXL adapters from HuggingFace into S3;
# here the "library" is two subject adapters personalized on-the-spot
# (the dreambooth example's recipe) and pushed to the bucket — the
# serving path (mount -> pick adapter -> merge -> generate) is identical.
#
# Run: tpurun run examples/10_integrations/lora_playground.py

import io
import os
import pickle

import modal_examples_tpu as mtpu

TPU = os.environ.get("MTPU_TPU", "") or None
PRETRAIN_STEPS = int(os.environ.get("MTPU_PRETRAIN_STEPS", "250"))
LORA_STEPS = int(os.environ.get("MTPU_LORA_STEPS", "200"))

app = mtpu.App("example-lora-playground")
base_vol = mtpu.Volume.from_name("lora-playground-base", create_if_missing=True)

SUBJECTS = ("sks-crystal", "sks-lava")  # the adapter library


def _cfg():
    from modal_examples_tpu.models import diffusion

    return diffusion.MMDiTConfig(
        img_size=16, channels=8, patch=2, dim=128, n_layers=2, n_heads=4,
        text_dim=32, pooled_dim=32,
    )


def _lcfg():
    from modal_examples_tpu.models import lora

    return lora.LoRAConfig(rank=16, alpha=32.0, targets=lora.DIT_TARGETS)


def _subject(jax, jnp, cfg, name: str):
    import hashlib

    # stable across processes (builtin hash() is salted per interpreter —
    # the library builder and the serving container must agree)
    seed = int.from_bytes(hashlib.md5(name.encode()).digest()[:4], "little")
    pattern = jnp.tanh(
        jax.random.normal(
            jax.random.PRNGKey(seed),
            (cfg.img_size, cfg.img_size, cfg.channels),
        ) * 2.0
    )
    token = jax.random.normal(
        jax.random.PRNGKey(seed + 1), (1, 4, cfg.text_dim)
    )
    return pattern, token


def _denoise(diffusion, jax, jnp, params, cfg, token, seed=0):
    """One-step preview generation at t=0.7 (cheap-mode image)."""
    t = 0.7
    eps = jax.random.normal(jax.random.PRNGKey(100 + seed),
                            (1, cfg.img_size, cfg.img_size, cfg.channels))
    x_t = t * eps  # noise-only start: the subject must come from the model
    ts = jnp.broadcast_to(token, (1, 4, cfg.text_dim))
    v = diffusion.mmdit_forward(
        params, x_t, jnp.full((1,), t), ts, jnp.zeros((1, cfg.pooled_dim)),
        cfg,
    )
    return x_t[0] - t * v[0]


@app.function(tpu=TPU, volumes={"/base": base_vol}, timeout=900)
def build_library(endpoint: str) -> dict:
    """Pretrain the shared base, personalize one adapter per subject, and
    push the adapters to the bucket (the reference's download-loras-to-S3
    stage, with training standing in for the HF downloads)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from modal_examples_tpu.models import diffusion, lora
    from modal_examples_tpu.storage.gcs import GCSClient

    cfg, lcfg = _cfg(), _lcfg()
    base = diffusion.mmdit_init(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(2e-3)
    o = opt.init(base)

    @jax.jit
    def prestep(params, o, key):
        k1, k2 = jax.random.split(key)
        lat = jnp.tanh(jax.random.normal(
            k1, (8, cfg.img_size, cfg.img_size, cfg.channels)))
        loss, g = jax.value_and_grad(diffusion.mmdit_flow_loss)(
            params, k2, lat, jnp.zeros((8, 4, cfg.text_dim)),
            jnp.zeros((8, cfg.pooled_dim)), cfg,
        )
        upd, o = opt.update(g, o)
        return optax.apply_updates(params, upd), o, loss

    for i in range(PRETRAIN_STEPS):
        base, o, _ = prestep(base, o, jax.random.PRNGKey(1000 + i))
    with open("/base/base.pkl", "wb") as f:
        pickle.dump(jax.tree.map(np.asarray, base), f)
    base_vol.commit()

    gcs = GCSClient(endpoint=endpoint)
    for name in SUBJECTS:
        pattern, token = _subject(jax, jnp, cfg, name)
        adapters = lora.init_lora_tree(jax.random.PRNGKey(7), base, lcfg)
        aopt = optax.adam(1e-2)
        ao = aopt.init(adapters)

        @jax.jit
        def astep(adapters, ao, key, pattern=pattern, token=token):
            def loss_fn(ad):
                merged = lora.merge_tree(base, ad, lcfg)
                lat = jnp.broadcast_to(pattern[None], (8, *pattern.shape))
                ts = jnp.broadcast_to(token, (8, 4, cfg.text_dim))
                return diffusion.mmdit_flow_loss(
                    merged, key, lat, ts, jnp.zeros((8, cfg.pooled_dim)), cfg
                )

            loss, g = jax.value_and_grad(loss_fn)(adapters)
            upd, ao = aopt.update(g, ao)
            return optax.apply_updates(adapters, upd), ao, loss

        for i in range(LORA_STEPS):
            adapters, ao, _ = astep(adapters, ao, jax.random.PRNGKey(10 + i))
        buf = io.BytesIO()
        pickle.dump(jax.tree.map(np.asarray, adapters), buf)
        gcs.put_object("loras", f"v1/{name}.pkl", buf.getvalue())
    return {"adapters": list(SUBJECTS)}


@app.cls(tpu=TPU, volumes={"/base": base_vol}, scaledown_window=300)
class Playground:
    endpoint: str = mtpu.parameter(default="")

    @mtpu.enter()
    def load(self):
        import jax

        if not TPU:
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        import jax.numpy as jnp

        base_vol.reload()
        with open("/base/base.pkl", "rb") as f:
            self.base = jax.tree.map(jnp.asarray, pickle.load(f))
        # mount the adapter library (cloud_bucket_mount_loras.py's
        # LORAS_PATH) — pull-on-attach through the GCS client
        self._mount = mtpu.CloudBucketMount(
            "loras", key_prefix="v1", bucket_endpoint_url=self.endpoint
        )
        self._mount.pull()
        self.mount_dir = str(self._mount.local_path)
        self._adapters = {}  # name -> merged params (tiny; cache them all)

    def _merged(self, name: str):
        import jax
        import jax.numpy as jnp

        from modal_examples_tpu.models import lora

        if name not in self._adapters:
            path = os.path.join(self.mount_dir, f"{name}.pkl")
            if not os.path.exists(path):
                # the MOUNT is the source of truth for the library: on a
                # miss, re-pull so adapters pushed after container start
                # serve without a restart
                self._mount.pull()
            if not os.path.exists(path):
                have = sorted(
                    f[:-4] for f in os.listdir(self.mount_dir)
                    if f.endswith(".pkl")
                )
                raise ValueError(f"unknown LoRA {name!r}; have {have}")
            with open(path, "rb") as f:
                tree = jax.tree.map(jnp.asarray, pickle.load(f))
            self._adapters[name] = lora.merge_tree(self.base, tree, _lcfg())
        return self._adapters[name]

    @mtpu.method()
    def generate(self, lora_name: str, seed: int = 0) -> dict:
        """The reference UI's request shape: pick an adapter, generate."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from modal_examples_tpu.models import diffusion
        from modal_examples_tpu.utils.images import to_png

        cfg = _cfg()
        pattern, token = _subject(jax, jnp, cfg, lora_name)
        img = _denoise(diffusion, jax, jnp, self._merged(lora_name), cfg,
                       token, seed)
        base_img = _denoise(diffusion, jax, jnp, self.base, cfg, token, seed)
        d_lora = float(jnp.mean((img - pattern) ** 2))
        d_base = float(jnp.mean((base_img - pattern) ** 2))
        png = to_png(np.asarray(jnp.clip(img[..., :3], -1, 1)))
        return {
            "lora": lora_name,
            "png_bytes": len(png),
            "dist_to_subject": d_lora,
            "dist_base_to_subject": d_base,
        }


@app.function()
@mtpu.fastapi_endpoint()
def generate(lora: str, seed: int = 0, endpoint: str = "") -> dict:
    """GET /generate?lora=sks-crystal — the reference playground's request
    shape (its Gradio UI posts the adapter choice; UIs are cosmetic per
    OUT_OF_SCOPE.md). Unknown adapters surface as the error JSON/4xx."""
    return Playground(endpoint=endpoint).generate.remote(lora, int(seed))


@app.local_entrypoint()
def main():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "tests"))
    from test_gcs import _FakeGCS

    srv = _FakeGCS()
    try:
        print("library:", build_library.remote(srv.endpoint))
        pg = Playground(endpoint=srv.endpoint)
        results = {}
        for name in SUBJECTS:
            r = pg.generate.remote(name)
            results[name] = r
            print(f"{name}: dist {r['dist_to_subject']:.3f} "
                  f"(base {r['dist_base_to_subject']:.3f}), "
                  f"{r['png_bytes']}B png")
            # each adapter pulls generation toward ITS subject vs the base
            assert r["dist_to_subject"] < r["dist_base_to_subject"], r
        # unknown adapter -> clean error (the playground's 404 path)
        try:
            pg.generate.remote("sks-nonexistent")
            raise AssertionError("expected unknown-LoRA error")
        except Exception as e:
            assert "unknown LoRA" in str(e), e
        print("LoRA playground: bucket-mounted adapters serve per request")
    finally:
        srv.stop()
