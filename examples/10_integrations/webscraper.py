# # Web scraper: Queue-driven BFS crawl with link extraction
#
# TPU-native counterpart of the reference's 10_integrations/webscraper.py
# (317 LoC): fetch pages, extract links, store what you found, fan the
# frontier out through a Queue, and dedupe with a Dict so every page is
# scraped exactly once — the crawler shape 09_job_queues/
# dicts_and_queues.py sketches, upgraded with real HTTP fetching and HTML
# parsing (stdlib html.parser; the reference uses playwright/bs4).
#
# Zero egress: the app SERVES its own multi-page site (a tiny generated
# wiki with deterministic cross-links) and then crawls it over real HTTP
# through the gateway — fetch, parse, frontier, and storage are all the
# real mechanics.
#
# Run: tpurun run examples/10_integrations/webscraper.py

import modal_examples_tpu as mtpu

app = mtpu.App("example-webscraper")
pages_db = mtpu.Dict.from_name("scraper-results", create_if_missing=True)
seen = mtpu.Dict.from_name("scraper-seen", create_if_missing=True)
frontier = mtpu.Queue.from_name("scraper-frontier", create_if_missing=True)

N_PAGES = 24


@app.function()
@mtpu.fastapi_endpoint()
def wiki(page: int = 0) -> bytes:
    """The site under test: page i links to 2i+1, 2i+2 (a binary tree) and
    back to its parent — deterministic reachability for the assertion.
    Returned as bytes so the gateway serves raw HTML, not a JSON string."""
    links = [n for n in (2 * page + 1, 2 * page + 2) if n < N_PAGES]
    if page > 0:
        links.append((page - 1) // 2)
    body = "".join(
        f'<li><a href="/wiki?page={n}">node {n}</a></li>' for n in links
    )
    return (
        f"<html><head><title>Node {page}</title></head>"
        f"<body><h1>Node {page}</h1><p>content of node {page}</p>"
        f"<ul>{body}</ul></body></html>"
    ).encode()


class _LinkParser:
    """Extract hrefs + title with stdlib html.parser (no bs4 needed)."""

    def __init__(self):
        from html.parser import HTMLParser

        outer = self

        class P(HTMLParser):
            def handle_starttag(self, tag, attrs):
                if tag == "a":
                    href = dict(attrs).get("href")
                    if href:
                        outer.links.append(href)
                outer._tag = tag

            def handle_data(self, data):
                if getattr(outer, "_tag", None) == "title":
                    outer.title += data

        self.links: list[str] = []
        self.title = ""
        self._parser = P()

    def feed(self, html: str):
        self._parser.feed(html)
        return self


@app.function(max_containers=4)
def scrape(url: str, depth: int, max_depth: int) -> None:
    """Fetch one page, record it, and push unseen links onto the frontier.
    Exactly-once claiming rides Dict.put_if_absent (the dicts_and_queues
    crawler primitive)."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=30) as r:
        html = r.read().decode()
    parsed = _LinkParser().feed(html)
    pages_db.put(url, {
        "title": parsed.title.strip(),
        "n_links": len(parsed.links),
        "depth": depth,
    })
    if depth >= max_depth:
        return
    from urllib.parse import urljoin

    for href in parsed.links:
        nxt = urljoin(url, href)
        if seen.put_if_absent(nxt, True):  # first claim wins
            frontier.put((nxt, depth + 1))


@app.local_entrypoint()
def main(max_depth: int = 8):
    from modal_examples_tpu.web.gateway import Gateway

    with app.run():
        gw = Gateway(app).start()
        root = f"{gw.base_url}/wiki?page=0"

        seen.put_if_absent(root, True)
        frontier.put((root, 0))
        # BFS pump: drain the frontier into a wave, fan it out with .map
        # (the grid-search fan-out shape), and loop — each wave's link
        # pushes refill the frontier until the whole tree is claimed
        from modal_examples_tpu.storage.dict_queue import Empty

        while True:
            wave = []
            while True:
                try:
                    url, depth = frontier.get(block=False)
                except Empty:
                    break
                wave.append((url, depth, max_depth))
            if not wave:
                break
            list(scrape.starmap(wave))

        results = {k: pages_db.get(k) for k in pages_db.keys()}
        got_pages = {
            int(k.split("page=")[1]) for k in results
        }
        assert got_pages == set(range(N_PAGES)), (
            f"missed pages: {set(range(N_PAGES)) - got_pages}"
        )
        titles = {v["title"] for v in results.values()}
        assert f"Node {N_PAGES - 1}" in titles
        by_depth = {}
        for v in results.values():
            by_depth.setdefault(v["depth"], 0)
            by_depth[v["depth"]] += 1
        print(
            f"crawled {len(results)} pages exactly once "
            f"(depths: {dict(sorted(by_depth.items()))})"
        )
        gw.stop()
