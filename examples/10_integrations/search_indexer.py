# # Site search indexer: scheduled crawl -> full-text index -> search API
#
# TPU-native counterpart of the reference's
# 10_integrations/algolia_indexer.py ("we run the same code in production
# to power search on this page"): a crawler walks a site, pushes every
# page into a search index, and a search endpoint serves ranked queries.
# The reference delegates indexing to Algolia's hosted crawler; zero
# egress, so the index is SQLite FTS5 (BM25 ranking, stdlib) persisted on
# a Volume — the cron_sqlite_dashboard.py storage pattern — and the site
# being indexed is served by THIS app (the webscraper.py trick).
#
# The pieces: a `Cron`-schedulable `reindex` function (the reference
# deploys its crawler on a schedule), the crawl fan-out, the FTS index on
# a Volume with commit/reload, and a `/search` endpoint with snippets.
#
# Run: tpurun run examples/10_integrations/search_indexer.py

import modal_examples_tpu as mtpu

app = mtpu.App("example-search-indexer")
index_vol = mtpu.Volume.from_name("search-index", create_if_missing=True)

DB = "/index/site.db"
N_PAGES = 12

TOPICS = {
    0: ("home", "welcome to the tpu framework documentation portal"),
    1: ("serving", "continuous batching paged attention decode engine"),
    2: ("training", "lora fine tuning optimizer checkpoints resume"),
    3: ("kernels", "pallas flash attention mosaic ragged paged kernel"),
    4: ("sharding", "tensor parallel mesh collectives ici psum"),
    5: ("volumes", "persistent storage commit reload snapshots"),
    6: ("quantization", "int8 int4 weight only quantized matmul"),
    7: ("whisper", "speech recognition streaming transcription audio"),
    8: ("diffusion", "rectified flow text to image sampling guidance"),
    9: ("clusters", "multi host gang scheduling jax distributed"),
    10: ("webhooks", "discord interactions signed endpoints deferred"),
    11: ("search", "full text index bm25 snippets ranking"),
}


@app.function()
@mtpu.fastapi_endpoint()
def docs(page: int = 0) -> bytes:
    """The site under index: each page covers one topic and links onward."""
    title, body = TOPICS.get(page, ("void", ""))
    nxt = (page + 1) % N_PAGES
    return (
        f"<html><head><title>{title}</title></head><body>"
        f"<h1>{title}</h1><p>{body}</p>"
        f'<a href="/docs?page={nxt}">next</a></body></html>'
    ).encode()


@app.function(volumes={"/index": index_vol}, timeout=600)
def reindex(base_url: str) -> dict:
    """Crawl the site and rebuild the FTS index (schedule with
    mtpu.Cron('0 * * * *') on deploy — the reference runs its crawler on
    exactly this kind of schedule)."""
    import re
    import sqlite3
    import urllib.request

    con = sqlite3.connect(DB)
    con.execute("DROP TABLE IF EXISTS pages")
    con.execute(
        "CREATE VIRTUAL TABLE pages USING fts5(url, title, body)"
    )
    n = 0
    for page in range(N_PAGES):
        url = f"{base_url}/docs?page={page}"
        with urllib.request.urlopen(url, timeout=30) as r:
            html = r.read().decode()
        title = re.search(r"<title>(.*?)</title>", html).group(1)
        body = re.sub(r"<[^>]+>", " ", html)
        con.execute(
            "INSERT INTO pages VALUES (?, ?, ?)", (url, title, body)
        )
        n += 1
    con.commit()
    con.close()
    index_vol.commit()
    return {"indexed": n}


@app.function(volumes={"/index": index_vol})
@mtpu.fastapi_endpoint()
def search(q: str, limit: int = 5) -> dict:
    """BM25-ranked search with snippets (the Algolia query surface)."""
    import sqlite3

    # FTS5 MATCH has its own query syntax: quote each term so user
    # punctuation (hyphens, colons, quotes) can't crash the endpoint
    terms = [t.replace('"', "") for t in q.split()]
    match = " ".join(f'"{t}"' for t in terms if t)
    if not match:
        return {"query": q, "hits": []}

    index_vol.reload()
    con = sqlite3.connect(DB)
    rows = con.execute(
        "SELECT url, title, snippet(pages, 2, '[', ']', '…', 8), bm25(pages) "
        "FROM pages WHERE pages MATCH ? ORDER BY bm25(pages) LIMIT ?",
        (match, limit),
    ).fetchall()
    con.close()
    return {
        "query": q,
        "hits": [
            {"url": u, "title": t, "snippet": s, "score": -b}
            for u, t, s, b in rows
        ],
    }


@app.local_entrypoint()
def main():
    import json
    import urllib.parse
    import urllib.request

    from modal_examples_tpu.web.gateway import Gateway

    with app.run():
        gw = Gateway(app).start()
        stats = reindex.remote(gw.base_url)
        print(f"indexed {stats['indexed']} pages")

        def query(q):
            qs = urllib.parse.urlencode({"q": q})
            with urllib.request.urlopen(
                f"{gw.base_url}/search?{qs}", timeout=60
            ) as r:
                return json.load(r)

        out = query("paged attention")
        assert out["hits"], "no hits for an indexed phrase"
        top = out["hits"][0]
        print(f"'paged attention' -> {top['title']} ({top['snippet']!r})")
        assert top["title"] in ("serving", "kernels")

        out2 = query("lora checkpoints")
        assert out2["hits"][0]["title"] == "training"
        print(f"'lora checkpoints' -> {out2['hits'][0]['title']}")

        assert not query("zebra unicorns")["hits"]
        print("absent terms return no hits; search index OK")
        gw.stop()
