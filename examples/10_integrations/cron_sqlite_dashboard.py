# # Cron-refreshed SQLite database served as a web API
#
# The counterpart of the reference's 10_integrations/cron_datasette.py: a
# scheduled function periodically ingests fresh data, writes it into a
# SQLite database on a Volume (with commit), and a web app serves queries
# over that database — the classic cron → storage → dashboard pipeline
# (the reference refreshes COVID-19 data nightly and serves it with
# Datasette).
#
# Serve the dashboard:  tpurun serve examples/10_integrations/cron_sqlite_dashboard.py
# Deploy the refresher: tpurun deploy examples/10_integrations/cron_sqlite_dashboard.py

import datetime
import json
import os
import sqlite3
import urllib.request

import modal_examples_tpu as mtpu

app = mtpu.App("example-cron-sqlite")
db_volume = mtpu.Volume.from_name("sqlite-dashboard-db", create_if_missing=True)
DB_PATH = "/data/metrics.db"


def _synthetic_rows(day: datetime.date, n: int = 24) -> list[tuple]:
    """Stand-in for the reference's upstream fetch (a real deployment pulls
    an external dataset here)."""
    base = hash(day.isoformat()) % 100
    return [
        (day.isoformat(), f"{h:02d}:00", (base + 7 * h) % 250)
        for h in range(n)
    ]


# ## The refresher — runs on a schedule, rebuilds the table, commits the
# Volume so web replicas can `reload()` and see the new data


@app.function(volumes={"/data": db_volume}, schedule=mtpu.Cron("17 3 * * *"))
def refresh(days: int = 3) -> int:
    os.makedirs(os.path.dirname(DB_PATH), exist_ok=True)
    con = sqlite3.connect(DB_PATH)
    con.execute(
        "CREATE TABLE IF NOT EXISTS metrics ("
        "day TEXT, hour TEXT, value INTEGER, PRIMARY KEY (day, hour))"
    )
    today = datetime.date.today()
    n = 0
    for offset in range(days):
        day = today - datetime.timedelta(days=offset)
        rows = _synthetic_rows(day)
        con.executemany(
            "INSERT OR REPLACE INTO metrics VALUES (?, ?, ?)", rows
        )
        n += len(rows)
    con.commit()
    con.close()
    db_volume.commit()  # publish to other containers (train.py:469 pattern)
    print(f"refreshed {n} rows across {days} days")
    return n


# ## The dashboard — a read-only query endpoint over the same Volume


@app.function(volumes={"/data": db_volume})
@mtpu.fastapi_endpoint()
def query(day: str = "", limit: int = 10) -> dict:
    db_volume.reload()  # pick up the latest cron refresh
    con = sqlite3.connect(DB_PATH)
    con.row_factory = sqlite3.Row
    if day:
        rows = con.execute(
            "SELECT * FROM metrics WHERE day = ? ORDER BY hour LIMIT ?",
            (day, limit),
        ).fetchall()
    else:
        rows = con.execute(
            "SELECT day, COUNT(*) AS points, AVG(value) AS avg_value "
            "FROM metrics GROUP BY day ORDER BY day DESC LIMIT ?",
            (limit,),
        ).fetchall()
    con.close()
    return {"rows": [dict(r) for r in rows]}


@app.local_entrypoint()
def main():
    from modal_examples_tpu.web.gateway import Gateway

    # run the cron body once by hand (the scheduler would do this nightly)
    n = refresh.remote(days=2)
    assert n == 48

    with app.run():
        gw = Gateway(app).start()
        try:
            with urllib.request.urlopen(f"{gw.base_url}/query") as r:
                summary = json.load(r)["rows"]
            print("per-day summary:", summary)
            # >= 2: the named Volume persists across runs, so re-running on a
            # later calendar day legitimately accumulates more day-rows
            assert len(summary) >= 2 and all(s["points"] == 24 for s in summary)

            day = summary[0]["day"]
            with urllib.request.urlopen(
                f"{gw.base_url}/query?day={day}&limit=3"
            ) as r:
                detail = json.load(r)["rows"]
            print("detail:", detail)
            assert len(detail) == 3 and detail[0]["day"] == day
        finally:
            gw.stop()
    print("cron -> sqlite -> web pipeline OK")
