# # Metrics for ephemeral containers (pushgateway pattern)
#
# Counterpart of 10_integrations/pushgateway.py:8-12,62-69 — scrape-based
# Prometheus can't see short-lived containers, so workers PUSH metrics and a
# gateway endpoint exposes the merged view. Here the registry, text
# exposition, and aggregation are framework-native (no Go binary), with a
# shared Dict as the push sink and a web endpoint as /metrics.
#
# Run: tpurun run examples/10_integrations/metrics_gateway.py

import modal_examples_tpu as mtpu

app = mtpu.App("example-metrics-gateway")
metrics_store = mtpu.Dict.from_name("pushed-metrics")


@app.function(max_containers=4)
def worker(job_id: int, n_items: int) -> int:
    """An ephemeral batch worker pushing its counters before exit."""
    import time

    from modal_examples_tpu.utils.prometheus import Registry, push_to_dict

    reg = Registry()
    for i in range(n_items):
        time.sleep(0.01)
        reg.counter_inc("items_processed_total", labels={"job": str(job_id)},
                        help="items processed by batch workers")
    reg.gauge_set("last_batch_size", n_items, labels={"job": str(job_id)})
    push_to_dict(metrics_store, f"worker-{job_id}", reg)
    return n_items


@app.function()
@mtpu.fastapi_endpoint()
def metrics() -> str:
    """The aggregated /metrics endpoint a Prometheus server would scrape."""
    from modal_examples_tpu.utils.prometheus import aggregate_exposition

    return aggregate_exposition(metrics_store)


@app.local_entrypoint()
def main():
    metrics_store.clear()
    totals = list(worker.starmap([(i, 5 + i) for i in range(3)]))
    print("workers processed:", totals)
    text = metrics.local()
    print(text)
    assert "items_processed_total" in text
    assert all(f'job="{i}"' in text for i in range(3))
    print("metrics aggregation OK")
