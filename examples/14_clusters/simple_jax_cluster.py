# # Simple multi-host JAX cluster
#
# TPU-native redesign of the reference's 14_clusters/simple_torch_cluster.py
# (cited per SURVEY.md §3.4). Where the reference co-schedules containers,
# distributes rank-0's address via `get_cluster_info()` (:101-109), and
# launches torchrun with one process per GPU + NCCL (:118-130), the TPU
# version is: one process per host, `init_jax_distributed()` (coordinator =
# rank 0), a global `Mesh` spanning every chip in the slice, and XLA
# collectives over ICI. No torchrun, no NCCL.
#
# Run: `tpurun run examples/14_clusters/simple_jax_cluster.py`

import modal_examples_tpu as mtpu

app = mtpu.App("example-jax-cluster")

N_HOSTS = 2
CHIPS_PER_HOST = 4


@app.function(timeout=300)
@mtpu.experimental.clustered(size=N_HOSTS, chips_per_host=CHIPS_PER_HOST)
def all_reduce_demo():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from modal_examples_tpu.parallel import cluster, make_mesh

    info = cluster.init_jax_distributed()
    print(
        f"host {info.rank}/{info.size} up: "
        f"{jax.local_device_count()} local / {jax.device_count()} global chips"
    )

    # one global mesh across the slice; each host contributes its local shard
    mesh = make_mesh({"data": jax.device_count()})
    local = np.full(
        (jax.local_device_count(), 1024), float(info.rank + 1), np.float32
    )
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local
    )

    # the all-reduce: XLA inserts the cross-host collective
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
    print(f"host {info.rank}: global sum = {float(total)}")
    return float(total)


@app.local_entrypoint()
def main():
    total = all_reduce_demo.remote()
    expected = 1024 * CHIPS_PER_HOST * sum(r + 1 for r in range(N_HOSTS))
    assert total == expected, (total, expected)
    print(f"cluster all-reduce OK: {total}")
