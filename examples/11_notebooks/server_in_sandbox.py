# # Interactive servers in sandboxes (tunnels)
#
# Counterpart of 11_notebooks/jupyter_inside_modal.py — an interactive
# server (Jupyter there; a stdlib HTTP file server here, same mechanics)
# runs inside a sandbox and is published through an `mtpu.forward` tunnel
# (:9). The pattern: boot the process in the sandbox, wait for the port,
# hand the tunnel URL to the user.
#
# Run: tpurun run examples/11_notebooks/server_in_sandbox.py

import sys
import urllib.request

import modal_examples_tpu as mtpu
from modal_examples_tpu.web.gateway import wait_for_port

app = mtpu.App("example-server-in-sandbox")

PORT = 18777


@app.local_entrypoint()
def main():
    sb = mtpu.Sandbox.create(timeout=120)
    try:
        with sb.open("notebook.txt", "w") as f:
            f.write("pretend this is a notebook\n")
        proc = sb.exec(
            sys.executable, "-m", "http.server", str(PORT), "--bind", "127.0.0.1"
        )
        assert wait_for_port("127.0.0.1", PORT, timeout=20), "server never bound"
        with mtpu.forward(PORT) as tunnel:
            print(f"server tunneled at {tunnel.url}")
            with urllib.request.urlopen(f"{tunnel.url}/notebook.txt", timeout=5) as r:
                content = r.read().decode()
        assert "pretend" in content
        print("fetched through the tunnel:", content.strip())
        proc.kill()
    finally:
        sb.cleanup()
