# # Building container images
#
# Counterpart of 02_building_containers/*: the chainable Image DSL
# (import_sklearn.py:25-51, install_cuda.py:40 — except our base is
# JAX/libtpu, never CUDA), build-time `run_function` steps, env layers, and
# the `image.imports()` guard.

import modal_examples_tpu as mtpu


def prefetch_assets():
    """Build-time step (runs once, cached by layer digest) — the analog of
    weight pre-download steps baked into images."""
    print("prefetching assets into the image layer...")


image = (
    mtpu.Image.tpu_base()  # Python + jax[tpu] + flax: the CUDA-free base
    .apt_install("ffmpeg")
    .uv_pip_install("einops")
    .env({"EXAMPLE_MODE": "builder-demo"})
    .run_function(prefetch_assets)
)

app = mtpu.App("example-image-builder", image=image)

# container-only imports are guarded on the client (import_sklearn.py:25-27)
with image.imports():
    import some_container_only_package  # noqa: F401


@app.function()
def show_env() -> dict:
    import os

    return {
        "mode": os.environ.get("EXAMPLE_MODE"),
        "task": os.environ.get("MTPU_TASK_ID", "")[:6],
    }


@app.local_entrypoint()
def main():
    print("image digest:", image.digest())
    print("pip layers:", image.python_packages())
    out = show_env.remote()
    print("container env:", out)
    assert out["mode"] == "builder-demo"

    # export the chain as a spec-valid OCI image layout (core/oci.py):
    # local content becomes real layer blobs, network steps become
    # provenance history — consumable by skopeo/podman/crane. The
    # offline analog of the reference platform's server-side builder.
    import json
    import tempfile
    from pathlib import Path

    dest = Path(tempfile.mkdtemp(prefix="mtpu-oci-")) / "image"
    asset = Path(tempfile.mkdtemp()) / "hello.txt"
    asset.write_text("baked asset")
    summary = (
        image.add_local_file(str(asset), "/assets/hello.txt")
        .export_oci(str(dest), tag="builder-demo")
    )
    print("oci export:", summary)
    index = json.loads((dest / "index.json").read_text())
    assert index["manifests"][0]["digest"] == summary["manifest_digest"]
    assert summary["n_layers"] == 1  # the one local-content layer
    print("OCI layout written to", dest)
