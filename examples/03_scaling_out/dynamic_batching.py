# # Dynamic batching
#
# Counterpart of 03_scaling_out/dynamic_batching.py:29,57 — `@mtpu.batched`
# coalesces concurrent single inputs into server-side batches, and the async
# variant drives it from one coroutine (08_advanced usage :81-93).

import asyncio

import modal_examples_tpu as mtpu

app = mtpu.App("example-dynamic-batching")


@app.function()
@mtpu.batched(max_batch_size=4, wait_ms=100)
def batched_multiply(xs: list[int], ys: list[int]) -> list[int]:
    # the function sees lists; callers send scalars
    assert isinstance(xs, list)
    return [x * y for x, y in zip(xs, ys)]


@app.local_entrypoint()
def main():
    # sync fan-out: the scheduler groups these into batches of <= 4
    results = list(batched_multiply.map(range(8), range(8)))
    assert results == [i * i for i in range(8)]
    print("sync batched:", results)

    async def async_path():
        return await asyncio.gather(
            *(batched_multiply.remote.aio(i, 10) for i in range(4))
        )

    out = asyncio.run(async_path())
    assert out == [0, 10, 20, 30]
    print("async batched:", out)
