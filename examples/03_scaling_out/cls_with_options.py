# # Runtime-parameterized services: with_options and parameters
#
# Counterpart of 03_scaling_out/cls_with_options.py:57 — override a Cls's
# resources at call time with `.with_options`, and parameterize instances
# with `mtpu.parameter` (distinct containers per parameter set).

import os

import modal_examples_tpu as mtpu

app = mtpu.App("example-cls-options")


@app.cls(scaledown_window=60)
class Greeter:
    greeting: str = mtpu.parameter(default="Hello")

    @mtpu.enter()
    def setup(self):
        self.task_id = os.environ.get("MTPU_TASK_ID")

    @mtpu.method()
    def greet(self, name: str) -> str:
        return f"{self.greeting}, {name}! (from {self.task_id})"


@app.local_entrypoint()
def main():
    hello = Greeter()
    hola = Greeter(greeting="Hola")
    a = hello.greet.remote("world")
    b = hola.greet.remote("mundo")
    print(a)
    print(b)
    assert a.startswith("Hello,") and b.startswith("Hola,")
    # parameterized instances get separate containers
    assert a.split("from ")[1] != b.split("from ")[1]

    # with_options returns a re-resourced handle without redefining the class
    fast = Greeter.with_options(max_containers=2, scaledown_window=30)
    assert fast._spec.max_containers == 2
