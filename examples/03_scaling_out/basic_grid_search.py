# # Grid search with .map
#
# Counterpart of 03_scaling_out/basic_grid_search.py:48 — fan a parameter
# grid over autoscaled containers and reduce the streamed results.

import modal_examples_tpu as mtpu

app = mtpu.App("example-grid-search")


@app.function(max_containers=8)
def score(params: tuple) -> tuple:
    lr, width = params
    # a synthetic objective with a known optimum at (0.1, 64)
    value = -((lr - 0.1) ** 2) - ((width - 64) / 64) ** 2
    return params, value


@app.local_entrypoint()
def main():
    grid = [(lr, w) for lr in (0.01, 0.1, 1.0) for w in (16, 64, 256)]
    best = max(score.map(grid), key=lambda r: r[1])
    print("best:", best)
    assert best[0] == (0.1, 64)
