"""Failure detection & elastic recovery (SURVEY.md §5.3).

The reference's interruption tolerance is retry+resume plumbing
(long-training.py:109-137 deliberately times out to exercise it; preemption
handling is "same checkpoint/retry pattern", unsloth_finetune.py:99-101).
The TPU additions SURVEY calls for:

- :class:`PreemptionGuard` — SIGTERM/SIGINT => emergency checkpoint before
  the container dies (TPU spot/preemption notices arrive as SIGTERM);
- :func:`run_resilient` — the checkpoint-every-N + resume-from-latest loop
  as one function, with the guard installed, so every training example gets
  the full story in one call;
- :func:`device_health` — slice-health probe (a tiny collective/computation
  per device; a sick chip raises here rather than mid-step).
"""

from __future__ import annotations

import signal
import threading
from typing import Any, Callable, Iterable


class PreemptionGuard:
    """Install once around a training loop; ``should_stop`` flips on
    SIGTERM/SIGINT and ``on_preempt`` (e.g. emergency checkpoint save) runs
    exactly once, synchronously with the loop (not in the signal handler)."""

    def __init__(self, on_preempt: Callable[[], None] | None = None):
        self._stop = threading.Event()
        self._on_preempt = on_preempt
        self._ran_hook = False
        self._prev_handlers: dict[int, Any] = {}

    def __enter__(self) -> "PreemptionGuard":
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_handlers[sig] = signal.signal(sig, self._handler)
            except ValueError:  # not the main thread: polling still works
                pass
        return self

    def __exit__(self, *exc) -> bool:
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        return False

    def _handler(self, signum, frame) -> None:
        self._stop.set()

    @property
    def should_stop(self) -> bool:
        return self._stop.is_set()

    def checkpoint_now_if_preempted(self) -> bool:
        """Call between steps: runs the emergency hook once after a signal."""
        if self._stop.is_set() and not self._ran_hook:
            self._ran_hook = True
            if self._on_preempt is not None:
                self._on_preempt()
            return True
        return False


def run_resilient(
    trainer,
    state,
    batches: Iterable,
    ckpt_manager,
    *,
    start_step: int = 0,
    total_steps: int,
    save_every: int = 50,
    on_metrics: Callable[[int, dict], None] | None = None,
):
    """Train with periodic checkpoints + emergency save on preemption.

    Resume pattern: restore ``state`` + ``start_step`` from
    ``ckpt_manager.latest_step()`` BEFORE calling (see
    examples/06_gpu_and_ml/llm-finetuning/lora_finetune.py). Returns
    (state, last_step, preempted)."""
    step = start_step
    it = iter(batches)

    def emergency_save():
        ckpt_manager.save(step, {"state": state})

    with PreemptionGuard(emergency_save) as guard:
        while step < total_steps:
            if guard.checkpoint_now_if_preempted():
                return state, step, True
            try:
                batch = next(it)
            except StopIteration:
                break
            state, metrics = trainer.train_step(state, batch)
            step += 1
            if on_metrics is not None:
                on_metrics(step, metrics)
            if step % save_every == 0 or step == total_steps:
                ckpt_manager.save(step, {"state": state})
    return state, step, False


def device_health() -> dict:
    """Probe every visible device with a tiny computation; raises on a sick
    chip (the slice-health watcher primitive — run before long jobs and on a
    schedule)."""
    import jax
    import jax.numpy as jnp

    report = {}
    for d in jax.devices():
        x = jax.device_put(jnp.ones((8, 8)), d)
        y = jax.jit(lambda a: (a @ a).sum())(x)  # runs on x's device
        ok = bool(y == 8.0**3)  # (ones@ones)[i,j] = 8; 64 elements
        report[str(d)] = "ok" if ok else f"BAD result {float(y)}"
        if not ok:
            raise RuntimeError(f"device {d} failed health check: {float(y)}")
    return report
