"""Training: jitted train step with mesh-sharded data/tensor parallelism.

Replaces the reference's HF Trainer / TRL / Lightning training stacks
(SURVEY.md §3.3: SFTTrainer.train() is the hot loop -> "becomes jitted JAX
train_step with psum grad sync"). Design:

- one ``train_step`` compiled under jit with explicit in/out shardings:
  params follow the model's tensor-parallel ``partition_specs`` over the
  ``tensor`` axis, the batch shards over ``data`` — XLA inserts the gradient
  all-reduce over ICI (no DDP wrapper, no NCCL);
- gradient accumulation via ``lax.scan`` over microbatches inside the step;
- bf16 params with f32 optimizer state (optax handles the dtype split);
- optional ``jax.checkpoint`` rematerialization of the layer scan for
  long-sequence memory.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def cross_entropy_loss(logits: jax.Array, targets: jax.Array, mask=None):
    """Mean next-token cross entropy; logits [B,S,V] f32, targets [B,S]."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        return -jnp.mean(ll)
    mask = mask.astype(jnp.float32)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def make_optimizer(
    learning_rate: float | Callable = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def warmup_cosine(peak_lr: float, warmup_steps: int, total_steps: int, floor: float = 0.1):
    return optax.warmup_cosine_decay_schedule(
        0.0, peak_lr, warmup_steps, max(total_steps, warmup_steps + 1),
        end_value=peak_lr * floor,
    )


class Trainer:
    """Mesh-aware training driver around a pure loss function.

    ``loss_fn(params, batch) -> scalar`` defines the model; everything else
    (sharding, grad sync, accumulation, optimizer) lives here.

    ``train_step`` DONATES the incoming state (in-place update — at 7B the
    params+optimizer would not fit twice): after a step, use the returned
    state; the old one's buffers are gone.
    """

    def __init__(
        self,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        *,
        mesh: Mesh | None = None,
        param_specs: Any = None,  # pytree of PartitionSpec (tensor parallel)
        batch_spec: P = P("data"),
        grad_accum: int = 1,
        remat: bool = False,
    ):
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.param_specs = param_specs
        self.batch_spec = batch_spec
        self.grad_accum = grad_accum
        self.remat = remat
        self._step_fn = None

    # -- setup --------------------------------------------------------------

    def init_state(self, params) -> TrainState:
        params = self.shard_params(params)
        opt_state = jax.jit(self.optimizer.init)(params)
        return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))

    def shard_params(self, params):
        if self.mesh is None or self.param_specs is None:
            return params
        return jax.tree.map(
            lambda p, spec: jax.device_put(p, NamedSharding(self.mesh, spec)),
            params,
            self.param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def shard_batch(self, batch):
        if self.mesh is None:
            return batch
        return jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(self.mesh, self.batch_spec)),
            batch,
        )

    # -- the step ------------------------------------------------------------

    def _build_step(self):
        loss_fn = self.loss_fn
        if self.remat:
            loss_fn = jax.checkpoint(loss_fn)

        def step(state: TrainState, batch):
            def microbatch_grads(carry, micro):
                loss_sum, grad_sum = carry
                loss, grads = jax.value_and_grad(loss_fn)(state.params, micro)
                grad_sum = jax.tree.map(jnp.add, grad_sum, grads)
                return (loss_sum + loss, grad_sum), None

            if self.grad_accum > 1:
                micros = jax.tree.map(
                    lambda x: x.reshape(
                        (self.grad_accum, x.shape[0] // self.grad_accum) + x.shape[1:]
                    ),
                    batch,
                )
                zeros = jax.tree.map(jnp.zeros_like, state.params)
                (loss_sum, grads), _ = jax.lax.scan(
                    microbatch_grads, (jnp.zeros(()), zeros), micros
                )
                loss = loss_sum / self.grad_accum
                grads = jax.tree.map(lambda g: g / self.grad_accum, grads)
            else:
                loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)

            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            new_state = TrainState(
                params=params, opt_state=opt_state, step=state.step + 1
            )
            return new_state, {"loss": loss, "grad_norm": optax.global_norm(grads)}

        donate = (0,)
        if self.mesh is not None:
            with self.mesh:
                return jax.jit(step, donate_argnums=donate)
        return jax.jit(step, donate_argnums=donate)

    def train_step(self, state: TrainState, batch):
        if self._step_fn is None:
            self._step_fn = self._build_step()
        batch = self.shard_batch(batch)
        if self.mesh is not None:
            with self.mesh:
                return self._step_fn(state, batch)
        return self._step_fn(state, batch)

    # -- the loop ------------------------------------------------------------

    def fit(
        self,
        state: TrainState,
        batches,
        *,
        run_dir=None,
        logger=None,
        volume=None,
        log_every: int = 1,
    ) -> TrainState:
        """Drive ``train_step`` over ``batches``, recording loss/grad_norm to
        a ``utils.tracking.RunLogger``. Pass an open ``logger`` to share one
        across phases (the caller closes it), or just ``run_dir`` and the
        loop owns the logger — closed (file handle + TB writer released,
        Volume committed) even when a step raises."""
        from ..utils.tracking import RunLogger

        owned = None
        if logger is None and run_dir is not None:
            logger = owned = RunLogger(run_dir, volume=volume)
        try:
            for batch in batches:
                state, metrics = self.train_step(state, batch)
                if logger is not None:
                    step = int(state.step)
                    if step % max(1, log_every) == 0:
                        # float() host-syncs, so only convert on log steps
                        logger.log(
                            step, {k: float(v) for k, v in metrics.items()}
                        )
            return state
        finally:
            if owned is not None:
                owned.close()
