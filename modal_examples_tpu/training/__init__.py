"""Training: mesh-sharded train steps, optimizers, checkpoint/resume."""

from .checkpoints import CheckpointManager
from .trainer import (
    TrainState,
    Trainer,
    cross_entropy_loss,
    make_optimizer,
    warmup_cosine,
)

__all__ = [
    "CheckpointManager",
    "TrainState",
    "Trainer",
    "cross_entropy_loss",
    "make_optimizer",
    "warmup_cosine",
]
