"""Training: mesh-sharded train steps, optimizers, checkpoint/resume."""

from .checkpoints import CheckpointManager
from .resilience import PreemptionGuard, device_health, run_resilient
from .trainer import (
    TrainState,
    Trainer,
    cross_entropy_loss,
    make_optimizer,
    warmup_cosine,
)

__all__ = [
    "CheckpointManager",
    "PreemptionGuard",
    "TrainState",
    "Trainer",
    "cross_entropy_loss",
    "device_health",
    "make_optimizer",
    "run_resilient",
    "warmup_cosine",
]
