"""GRPO: group-relative policy optimization for LLM fine-tuning.

The reference's RL workloads delegate to verl/TRL with vLLM rollouts and
FSDP (06_gpu_and_ml/reinforcement-learning per SURVEY §2.2: learn_math.py,
grpo_trl.py, grpo_verl.py:153-202). JAX-native redesign:

- rollouts: batched stochastic sampling from the policy as a fixed-length
  scan (static shapes; the serving engine can stand in at scale);
- advantages: rewards normalized within each prompt's group of G
  completions (the GRPO trick — no value network);
- loss: PPO-style clipped importance ratio against the behavior logprobs,
  plus a k3 KL penalty to a frozen reference policy;
- one jitted update step via the same optax machinery as everything else.

Rewards are arbitrary Python (the reference scores sandboxed code execution,
learn_math.py:7-9 — our Sandbox API slots in the same way).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable

import jax
import jax.numpy as jnp
import optax

from ..models import llama


@dataclasses.dataclass(frozen=True)
class GRPOConfig:
    group_size: int = 8
    clip_eps: float = 0.2
    kl_coef: float = 0.02
    temperature: float = 1.0
    max_new: int = 8


@functools.partial(jax.jit, static_argnames=("cfg", "max_new", "temperature"))
def sample_group(
    params,
    cfg: llama.LlamaConfig,
    prompts: jax.Array,  # [G, S0] int32 (the same prompt tiled, or varied)
    prompt_len: int | jax.Array,
    key: jax.Array,
    *,
    max_new: int,
    temperature: float,
):
    """Stochastic rollouts: returns (tokens [G, S0+max_new], logprobs [G,
    max_new]) where logprobs are the behavior policy's per-token logprobs."""
    G, S0 = prompts.shape
    S = S0 + max_new
    buf = jnp.zeros((G, S), jnp.int32).at[:, :S0].set(prompts)

    def step(carry, k):
        buf, pos = carry
        logits = llama.forward(params, buf, cfg, attn_impl="xla")  # [G, S, V]
        lp = jax.nn.log_softmax(
            logits[:, pos - 1] / max(temperature, 1e-6), axis=-1
        )
        tok = jax.random.categorical(k, lp, axis=-1).astype(jnp.int32)
        tok_lp = jnp.take_along_axis(lp, tok[:, None], 1)[:, 0]
        buf = buf.at[:, pos].set(tok)
        return (buf, pos + 1), (tok, tok_lp)

    (buf, _), (toks, lps) = jax.lax.scan(
        step, (buf, jnp.asarray(prompt_len)), jax.random.split(key, max_new)
    )
    return buf, lps.T  # [G, max_new]


def _completion_logprobs(
    params, cfg, tokens, prompt_len: int, max_new: int, temperature: float = 1.0
):
    """Per-token logprobs of the completion region under ``params``, at the
    SAME temperature as the behavior policy (the importance ratio is only
    meaningful when both sides use one distribution)."""
    logits = llama.forward(params, tokens, cfg, attn_impl="xla")
    lp = jax.nn.log_softmax(logits / max(temperature, 1e-6), axis=-1)
    idx = prompt_len - 1 + jnp.arange(max_new)  # predicts positions idx+1
    targets = tokens[:, prompt_len : prompt_len + max_new]
    sel = jnp.take_along_axis(
        lp[:, idx], targets[..., None], axis=-1
    )[..., 0]
    return sel  # [G, max_new]


def grpo_advantages(rewards: jax.Array) -> jax.Array:
    """Group-normalized advantages: (r - mean) / (std + eps), one group."""
    mu = rewards.mean()
    sd = rewards.std()
    return (rewards - mu) / (sd + 1e-6)


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "prompt_len", "max_new", "clip_eps", "kl_coef", "temperature",
    ),
)
def grpo_loss(
    policy_params,
    ref_params,
    cfg: llama.LlamaConfig,
    tokens: jax.Array,  # [G, S]
    behavior_lps: jax.Array,  # [G, max_new]
    advantages: jax.Array,  # [G]
    *,
    prompt_len: int,
    max_new: int,
    clip_eps: float,
    kl_coef: float,
    temperature: float = 1.0,
):
    new_lps = _completion_logprobs(
        policy_params, cfg, tokens, prompt_len, max_new, temperature
    )
    ratio = jnp.exp(new_lps - behavior_lps)  # [G, max_new]
    adv = advantages[:, None]
    unclipped = ratio * adv
    clipped = jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * adv
    pg = -jnp.mean(jnp.minimum(unclipped, clipped))
    # k3 KL estimator vs the frozen reference (grpo convention)
    ref_lps = _completion_logprobs(
        ref_params, cfg, tokens, prompt_len, max_new, temperature
    )
    log_r = ref_lps - new_lps
    kl = jnp.mean(jnp.exp(log_r) - log_r - 1.0)
    return pg + kl_coef * kl, {"pg_loss": pg, "kl": kl}


class GRPOTrainer:
    """Rollout -> reward -> advantage -> clipped update, one prompt group at
    a time (the verl config's essential loop, without verl)."""

    def __init__(
        self,
        cfg: llama.LlamaConfig,
        params,
        reward_fn: Callable[[jax.Array], list[float]],  # tokens [G, S] -> rewards
        grpo: GRPOConfig = GRPOConfig(),
        learning_rate: float = 1e-4,
    ):
        self.cfg = cfg
        self.grpo = grpo
        self.reward_fn = reward_fn
        self.policy = params
        self.ref = jax.tree.map(lambda x: x, params)  # frozen snapshot
        self.opt = optax.adamw(learning_rate)
        self.opt_state = self.opt.init(self.policy)
        self._grad_fn = jax.grad(
            lambda p, *a, **k: grpo_loss(p, *a, **k)[0], argnums=0
        )

    def step(
        self,
        prompt: jax.Array,
        prompt_len: int,
        key: jax.Array,
        reward_fn: Callable | None = None,  # per-prompt override
    ) -> dict:
        g = self.grpo
        reward_fn = reward_fn or self.reward_fn
        prompts = jnp.tile(prompt[None], (g.group_size, 1))
        tokens, behavior_lps = sample_group(
            self.policy, self.cfg, prompts, prompt_len, key,
            max_new=g.max_new, temperature=g.temperature,
        )
        rewards = jnp.asarray(reward_fn(tokens), jnp.float32)
        if rewards.shape != (g.group_size,):
            raise ValueError(
                f"reward_fn returned shape {rewards.shape}, expected "
                f"({g.group_size},)"
            )
        adv = grpo_advantages(rewards)
        grads = self._grad_fn(
            self.policy, self.ref, self.cfg, tokens, behavior_lps, adv,
            prompt_len=prompt_len, max_new=g.max_new,
            clip_eps=g.clip_eps, kl_coef=g.kl_coef, temperature=g.temperature,
        )
        updates, self.opt_state = self.opt.update(
            grads, self.opt_state, self.policy
        )
        self.policy = optax.apply_updates(self.policy, updates)
        return {
            "mean_reward": float(rewards.mean()),
            "max_reward": float(rewards.max()),
            "adv_std": float(adv.std()),
        }
