"""Checkpoint/resume: orbax-backed sharded pytree checkpoints on a Volume.

Reference semantics (SURVEY.md §5.4): every training example checkpoints to a
Volume with an explicit commit and resumes from the latest checkpoint after
interruption (HF get_last_checkpoint train.py:175-194, TRL checkpoint-* glob
unsloth_finetune.py:589-607, Lightning last.ckpt long-training.py:40-54).
This module is the one implementation behind all of those patterns:
step-numbered directories, a ``latest`` scan, keep-N pruning, and
``volume.commit()`` after save when a Volume is attached.
"""

from __future__ import annotations

import re
import shutil
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

_STEP_RE = re.compile(r"^step_(\d+)$")


class CheckpointManager:
    def __init__(
        self,
        directory: str | Path,
        *,
        keep_n: int = 3,
        volume=None,  # modal_examples_tpu Volume: committed after save
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.keep_n = keep_n
        self.volume = volume
        self._ckptr = ocp.StandardCheckpointer()

    def _step_dir(self, step: int) -> Path:
        return self.directory / f"step_{step:08d}"

    def steps(self) -> list[int]:
        out = []
        for p in self.directory.iterdir():
            m = _STEP_RE.match(p.name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def save(self, step: int, state: Any, wait: bool = True) -> Path:
        path = self._step_dir(step)
        if path.exists():
            shutil.rmtree(path)
        self._ckptr.save(path.resolve(), state)
        if wait:
            self._ckptr.wait_until_finished()
        self._prune()
        if self.volume is not None:
            self.volume.commit()
        return path

    def restore(self, target: Any, step: int | None = None) -> Any:
        """Restore into the structure/shardings of ``target`` (an abstract or
        concrete pytree); defaults to the latest step."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        import jax

        def to_abstract(x):
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                sharding = getattr(x, "sharding", None)
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
            return x

        abstract = jax.tree.map(to_abstract, target)
        return self._ckptr.restore(self._step_dir(step).resolve(), abstract)

    def _prune(self) -> None:
        steps = self.steps()
        for old in steps[: -self.keep_n] if self.keep_n > 0 else []:
            shutil.rmtree(self._step_dir(old), ignore_errors=True)
