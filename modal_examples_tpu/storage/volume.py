"""Volume — shared durable filesystem with commit/reload semantics.

Reference spec: ``modal.Volume.from_name(name, create_if_missing=True)``
mounted at a path in the container (vllm_inference.py:77-81), with explicit
``volume.commit()`` after writes (openai_whisper/finetuning/train/train.py:469)
and ``volume.reload()`` to pick up other writers' commits
(torch_profiling.py:279). Volumes back HF weight caches, checkpoints, and —
critically on TPU — the **XLA persistent compile cache** (our analog of the
reference's vllm-cache volume, the single biggest cold-start lever; SURVEY.md
§7 step 3).

Local control plane: each volume is a directory under the state dir. commit()
fsyncs and bumps a version file; reload() re-reads it. A GCS-backed
implementation can replace :class:`_DirBackend` without changing callers.
"""

from __future__ import annotations

import json
import os
import re
import time
from pathlib import Path

from .._internal import config as _config

_NAME_RE = re.compile(r"^[a-zA-Z0-9][a-zA-Z0-9._-]*$")


class VolumeNotFound(KeyError):
    pass


def _volumes_root() -> Path:
    p = _config.state_dir() / "volumes"
    p.mkdir(parents=True, exist_ok=True)
    return p


class Volume:
    def __init__(self, name: str, path: Path):
        self.name = name
        self._path = path
        self._seen_version = self.version

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_name(cls, name: str, create_if_missing: bool = False, environment_name: str | None = None) -> "Volume":
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid volume name {name!r}")
        path = _volumes_root() / name
        if not path.exists():
            if not create_if_missing:
                raise VolumeNotFound(name)
            path.mkdir(parents=True, exist_ok=True)
            (path / ".version").write_text("0")
        return cls(name, path)

    @classmethod
    def ephemeral(cls):
        import contextlib
        import tempfile

        @contextlib.contextmanager
        def _ctx():
            with tempfile.TemporaryDirectory(prefix="mtpu-vol-") as d:
                p = Path(d)
                (p / ".version").write_text("0")
                yield cls(f"ephemeral-{os.path.basename(d)}", p)

        return _ctx()

    @staticmethod
    def delete(name: str) -> None:
        import shutil

        path = _volumes_root() / name
        if path.exists():
            shutil.rmtree(path)

    # -- filesystem ---------------------------------------------------------

    @property
    def local_path(self) -> Path:
        """Host path of the volume (containers mount this path)."""
        return self._path

    @property
    def version(self) -> int:
        vf = self._path / ".version"
        try:
            return int(vf.read_text() or "0")
        except (FileNotFoundError, ValueError):
            return 0

    def commit(self) -> None:
        """Flush writes; makes them visible to other readers at reload()."""
        vf = self._path / ".version"
        v = self.version + 1
        tmp = self._path / f".version.tmp.{os.getpid()}"
        tmp.write_text(str(v))
        os.replace(tmp, vf)
        self._seen_version = v

    def reload(self) -> None:
        """Pick up commits made by other containers since our last look."""
        self._seen_version = self.version

    # -- convenience API (modeled on modal's volume file API) ----------------

    def listdir(self, path: str = "/", recursive: bool = False):
        # dotfiles are volume internals (.version, in-flight .tmp-* atomic
        # writes) — listing them would hand readers a torn file
        if recursive:
            for root, _dirs, files in os.walk(self._resolve(path)):
                for f in sorted(files):
                    if f.startswith("."):
                        continue
                    full = Path(root) / f
                    yield str(full.relative_to(self._path))
        else:
            for entry in sorted(self._resolve(path).iterdir()):
                if entry.name.startswith("."):
                    continue
                yield str(entry.relative_to(self._path))

    def read_file(self, path: str) -> bytes:
        return self._resolve(path).read_bytes()

    def write_file(self, path: str, data: bytes) -> None:
        """Atomic durable write: uuid temp file, fsync, rename. A crash at
        ANY point leaves either the old content or the new — never a torn
        file that passes a size check (the KV spill tier and the shared
        prefix store both lean on this; a torn block would otherwise only
        be caught at crc time, after a wasted read)."""
        import uuid

        p = self._resolve(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.parent / f".tmp-{uuid.uuid4().hex}-{p.name}"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, p)
        except BaseException:
            tmp.unlink(missing_ok=True)
            raise

    def remove_file(self, path: str, recursive: bool = False) -> None:
        import shutil

        p = self._resolve(path)
        if p.is_dir():
            if not recursive:
                raise IsADirectoryError(path)
            shutil.rmtree(p)
        else:
            p.unlink()

    def restricted(self, subpath: str) -> "Volume":
        """A view of this volume rooted at ``subpath`` — per-user restricted
        mounts (08_advanced/restricted_volumes.py:8-35): mount
        ``vol.restricted(f"users/{user_id}")`` and the container can only
        see/write that subtree."""
        root = self._resolve(subpath)
        root.mkdir(parents=True, exist_ok=True)
        view = Volume(f"{self.name}/{subpath.strip('/')}", root)
        return view

    def _resolve(self, path: str) -> Path:
        p = (self._path / path.lstrip("/")).resolve()
        root = self._path.resolve()
        if p != root and root not in p.parents:
            raise PermissionError(f"path escapes volume: {path}")
        return p

    def __repr__(self) -> str:
        return f"Volume({self.name!r})"


class CloudBucketMount:
    """Mount an object-store bucket as a filesystem path.

    Reference: S3/GCS mounts in 12_datasets/coco.py:26-29 and
    10_integrations/s3_bucket_mount.py. TPU-natively this is a GCS bucket:
    ``pull()``/``push()`` sync objects through a real GCS JSON-API client
    (storage.gcs — stdlib urllib, bearer auth via Secret env or the TPU-VM
    metadata server). The mount path itself is a host directory, so dataset
    examples also run end-to-end with no cloud credentials at all (the
    zero-egress dev mode).
    """

    def __init__(
        self,
        bucket_name: str,
        *,
        bucket_endpoint_url: str | None = None,
        key_prefix: str | None = None,
        secret=None,
        read_only: bool = False,
    ):
        self.bucket_name = bucket_name
        self.key_prefix = key_prefix or ""
        self.read_only = read_only
        self.bucket_endpoint_url = bucket_endpoint_url
        self.secret = secret  # may carry GCS_TOKEN for authenticated pulls
        root = _config.state_dir() / "buckets" / bucket_name
        root.mkdir(parents=True, exist_ok=True)
        self.local_path = root / self.key_prefix if self.key_prefix else root
        self.local_path.mkdir(parents=True, exist_ok=True)

    def _client(self):
        """The real GCS JSON-API client (storage.gcs). ``bucket_endpoint_
        url`` overrides the endpoint — production GCS by default, a local
        fake-gcs-server in tests, an S3-compatible proxy if needed."""
        from .gcs import GCSClient

        kw = {}
        if self.bucket_endpoint_url:
            kw["endpoint"] = self.bucket_endpoint_url
        if self.secret is not None:
            # Secret-provided credential wins over process env / metadata
            token = self.secret.env_vars().get("GCS_TOKEN")
            if token:
                kw["token"] = token
        return GCSClient(**kw)

    def pull(self) -> int:
        """Materialize gs://bucket/prefix into the local mount path (the
        reference's read-mount semantics: coco.py:26-29 reads the bucket
        through the filesystem). Returns the number of objects pulled."""
        from .gcs import sync_prefix_to_dir

        return sync_prefix_to_dir(
            self._client(), self.bucket_name, self.key_prefix, self.local_path
        )

    def push(self) -> int:
        """Upload the local mount path back under gs://bucket/prefix (the
        write-back half for read-write mounts). Returns objects pushed."""
        if self.read_only:
            raise PermissionError("read_only mount cannot push")
        from .gcs import sync_dir_to_prefix

        return sync_dir_to_prefix(
            self._client(), self.local_path, self.bucket_name, self.key_prefix
        )

    def __repr__(self) -> str:
        return f"CloudBucketMount({self.bucket_name!r}, prefix={self.key_prefix!r})"
