"""Secret — named env-var bundles injected into containers.

Reference spec: ``modal.Secret.from_name("huggingface-secret",
required_keys=["HF_TOKEN"])`` (openai_whisper/finetuning/train/train.py:27),
``Secret.from_dict({...})``, and ``Secret.from_local_environ``. Secrets attach
to Functions/Apps and materialize as environment variables inside the
container only.

Local control plane: JSON files under the state dir with 0600 permissions.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from .._internal import config as _config


class SecretNotFound(KeyError):
    pass


def _secrets_root() -> Path:
    p = _config.state_dir() / "secrets"
    p.mkdir(parents=True, exist_ok=True)
    return p


class Secret:
    def __init__(self, name: str, env: dict[str, str]):
        self.name = name
        self._env = dict(env)

    @classmethod
    def from_dict(cls, env: dict[str, str]) -> "Secret":
        return cls("anonymous", env)

    @classmethod
    def from_local_environ(cls, keys: list[str]) -> "Secret":
        missing = [k for k in keys if k not in os.environ]
        if missing:
            raise KeyError(f"missing local environment keys: {missing}")
        return cls("local-environ", {k: os.environ[k] for k in keys})

    @classmethod
    def from_name(
        cls, name: str, required_keys: list[str] | None = None, environment_name: str | None = None
    ) -> "Secret":
        path = _secrets_root() / f"{name}.json"
        if not path.exists():
            # Graceful degradation matching dev ergonomics: if the named
            # secret isn't registered but its required keys are present in
            # the local environment, synthesize it from there.
            if required_keys and all(k in os.environ for k in required_keys):
                return cls(name, {k: os.environ[k] for k in required_keys})
            raise SecretNotFound(
                f"secret {name!r} not found; create it with "
                f"`tpurun secret create {name} KEY=VALUE ...`"
            )
        env = json.loads(path.read_text())
        if required_keys:
            missing = [k for k in required_keys if k not in env]
            if missing:
                raise KeyError(f"secret {name!r} missing required keys: {missing}")
        return cls(name, env)

    @staticmethod
    def create(name: str, env: dict[str, str], overwrite: bool = True) -> None:
        path = _secrets_root() / f"{name}.json"
        if path.exists() and not overwrite:
            raise FileExistsError(name)
        # create 0600 from the first byte — write_text-then-chmod leaves a
        # window where the plaintext is world-readable
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        # the mode arg only applies at creation: when overwriting a file that
        # already exists with looser permissions, tighten it too
        os.fchmod(fd, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps(env))

    def env_vars(self) -> dict[str, str]:
        return dict(self._env)

    def __repr__(self) -> str:
        return f"Secret({self.name!r}, keys={sorted(self._env)})"
