"""Dict / Queue — distributed KV and FIFO primitives.

Reference spec: ``modal.Queue.ephemeral()`` / ``modal.Dict.ephemeral()``,
``q.put_many``, blocking ``q.get``, dict-based coordination & termination
signalling in the distributed crawler (09_job_queues/dicts_and_queues.py:53-80)
and the sandbox warm-pool registry (13_sandboxes/sandbox_pool.py:20-24).

Local control plane: pickled state files under the state dir guarded by
``fcntl`` locks, so every container process on the host shares one view —
the same consistency contract (single linearizable store) the reference's
metadata service provides. Blocking reads poll; a networked service can
replace :class:`_Store` later.
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import pickle
import time
from collections import deque
from pathlib import Path
from typing import Any, Iterator

from .._internal import config as _config


class Empty(Exception):
    """Raised by non-blocking/timed-out queue reads."""


class _Store:
    """A pickled python object on disk with advisory-locked read-modify-write."""

    def __init__(self, path: Path, initial):
        self._path = path
        self._lock_path = path.with_suffix(".lock")
        self._initial = initial
        self._path.parent.mkdir(parents=True, exist_ok=True)
        self._lock_path.touch(exist_ok=True)

    @contextlib.contextmanager
    def locked(self):
        with open(self._lock_path, "r+") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

    def load(self):
        try:
            with open(self._path, "rb") as f:
                return pickle.load(f)
        except (FileNotFoundError, EOFError):
            return self._initial()

    def save(self, obj) -> None:
        tmp = self._path.with_suffix(f".tmp.{os.getpid()}")
        with open(tmp, "wb") as f:
            pickle.dump(obj, f)
        os.replace(tmp, self._path)

    def destroy(self) -> None:
        for p in (self._path, self._lock_path):
            try:
                p.unlink()
            except FileNotFoundError:
                pass


def _objects_root(kind: str) -> Path:
    p = _config.state_dir() / kind
    p.mkdir(parents=True, exist_ok=True)
    return p


class Dict:
    def __init__(self, name: str):
        self.name = name
        self._store = _Store(_objects_root("dicts") / f"{name}.pkl", dict)

    @classmethod
    def from_name(cls, name: str, create_if_missing: bool = True) -> "Dict":
        return cls(name)

    @classmethod
    @contextlib.contextmanager
    def ephemeral(cls) -> Iterator["Dict"]:
        name = f"ephemeral-{os.getpid()}-{time.monotonic_ns()}"
        d = cls(name)
        try:
            yield d
        finally:
            d._store.destroy()

    @staticmethod
    def delete(name: str) -> None:
        _Store(_objects_root("dicts") / f"{name}.pkl", dict).destroy()

    def __setitem__(self, key, value) -> None:
        self.put(key, value)

    def put(self, key, value) -> None:
        with self._store.locked():
            d = self._store.load()
            d[key] = value
            self._store.save(d)

    def __getitem__(self, key):
        with self._store.locked():
            return self._store.load()[key]

    def get(self, key, default=None):
        with self._store.locked():
            return self._store.load().get(key, default)

    def pop(self, key, *default):
        with self._store.locked():
            d = self._store.load()
            val = d.pop(key, *default)
            self._store.save(d)
            return val

    def put_if_absent(self, key, value) -> bool:
        """Atomically claim ``key``; True iff this caller won (the primitive
        behind exactly-once work claiming in the crawler pattern)."""
        with self._store.locked():
            d = self._store.load()
            if key in d:
                return False
            d[key] = value
            self._store.save(d)
            return True

    def update(self, **kwargs) -> None:
        with self._store.locked():
            d = self._store.load()
            d.update(kwargs)
            self._store.save(d)

    def __contains__(self, key) -> bool:
        with self._store.locked():
            return key in self._store.load()

    def contains(self, key) -> bool:
        return key in self

    def __len__(self) -> int:
        with self._store.locked():
            return len(self._store.load())

    def len(self) -> int:
        return len(self)

    def keys(self):
        with self._store.locked():
            return list(self._store.load().keys())

    def values(self):
        with self._store.locked():
            return list(self._store.load().values())

    def items(self):
        with self._store.locked():
            return list(self._store.load().items())

    def clear(self) -> None:
        with self._store.locked():
            self._store.save({})


class Queue:
    """FIFO queue with optional partitions (reference: partition kwarg)."""

    def __init__(self, name: str):
        self.name = name
        self._store = _Store(_objects_root("queues") / f"{name}.pkl", dict)

    @classmethod
    def from_name(cls, name: str, create_if_missing: bool = True) -> "Queue":
        return cls(name)

    @classmethod
    @contextlib.contextmanager
    def ephemeral(cls) -> Iterator["Queue"]:
        name = f"ephemeral-{os.getpid()}-{time.monotonic_ns()}"
        q = cls(name)
        try:
            yield q
        finally:
            q._store.destroy()

    @staticmethod
    def delete(name: str) -> None:
        _Store(_objects_root("queues") / f"{name}.pkl", dict).destroy()

    def _partition(self, d: dict, partition: str | None) -> deque:
        return d.setdefault(partition or "", deque())

    def put(self, item, partition: str | None = None) -> None:
        with self._store.locked():
            d = self._store.load()
            self._partition(d, partition).append(item)
            self._store.save(d)

    def put_many(self, items, partition: str | None = None) -> None:
        with self._store.locked():
            d = self._store.load()
            self._partition(d, partition).extend(items)
            self._store.save(d)

    def get(
        self,
        block: bool = True,
        timeout: float | None = None,
        partition: str | None = None,
    ):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._store.locked():
                d = self._store.load()
                dq = self._partition(d, partition)
                if dq:
                    item = dq.popleft()
                    self._store.save(d)
                    return item
            if not block:
                raise Empty(self.name)
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty(self.name)
            time.sleep(0.02)

    def get_many(
        self,
        n_values: int,
        block: bool = True,
        timeout: float | None = None,
        partition: str | None = None,
    ) -> list:
        """Up to ``n_values`` items; blocks for at least one if ``block``."""
        first = self.get(block=block, timeout=timeout, partition=partition)
        out = [first]
        with self._store.locked():
            d = self._store.load()
            dq = self._partition(d, partition)
            while dq and len(out) < n_values:
                out.append(dq.popleft())
            self._store.save(d)
        return out

    def __len__(self) -> int:
        return self.len()

    def len(self, partition: str | None = None, total: bool = False) -> int:
        with self._store.locked():
            d = self._store.load()
            if total:
                return sum(len(dq) for dq in d.values())
            return len(self._partition(d, partition))

    def clear(self, partition: str | None = None, all: bool = False) -> None:
        with self._store.locked():
            d = self._store.load()
            if all:
                d = {}
            else:
                d[partition or ""] = deque()
            self._store.save(d)
