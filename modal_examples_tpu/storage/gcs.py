"""GCS object-store client over the JSON API — stdlib urllib only.

CloudBucketMount's TPU-native backing store is a GCS bucket
(SURVEY.md §2.1: "CloudBucketMount ... GCS native"; the reference mounts
S3/GCS in 12_datasets/coco.py:26-29 and 10_integrations/
s3_bucket_mount.py). The google-cloud-storage SDK is not in this image and
the environment has zero egress, so this is a from-scratch client for the
`storage.googleapis.com` JSON/upload API surface the mount needs: list,
get, put, delete, with bearer-token auth.

Auth resolution (in order):
1. ``GCS_TOKEN`` env (a bearer token — e.g. from a mounted Secret);
2. the GCE/TPU-VM metadata server (the credential path a real v5e host
   uses — TPU VMs carry a service account);
3. anonymous (public buckets).

``endpoint`` is injectable so the client is fully testable against a local
fake GCS server (tests/test_gcs.py) — the same lever the official SDKs
expose for the fake-gcs-server emulator.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request

METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/"
    "service-accounts/default/token"
)


class GCSError(RuntimeError):
    def __init__(self, status: int, message: str):
        self.status = status
        super().__init__(f"GCS {status}: {message}")


class GCSClient:
    """Minimal JSON-API client: list/get/put/delete objects."""

    def __init__(
        self,
        *,
        endpoint: str = "https://storage.googleapis.com",
        token: str | None = None,
        timeout: float = 60.0,
    ):
        import os

        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout
        self._token = token or os.environ.get("GCS_TOKEN")
        self._tried_metadata = False

    # -- auth ---------------------------------------------------------------

    def _metadata_token(self) -> str | None:
        """TPU-VM/GCE metadata-server token (how a real v5e host signs)."""
        req = urllib.request.Request(
            METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
        )
        try:
            with urllib.request.urlopen(req, timeout=2) as r:
                return json.load(r).get("access_token")
        except Exception:
            return None

    def _headers(self) -> dict:
        if self._token is None and not self._tried_metadata:
            self._tried_metadata = True
            self._token = self._metadata_token()
        if self._token:
            return {"Authorization": f"Bearer {self._token}"}
        return {}

    def _request(
        self, method: str, url: str, data: bytes | None = None,
        headers: dict | None = None,
    ) -> bytes:
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={**self._headers(), **(headers or {})},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            raise GCSError(e.code, e.read().decode(errors="replace")) from e

    # -- object operations --------------------------------------------------

    def list_objects(
        self, bucket: str, prefix: str = "", max_results: int = 1000
    ) -> list[dict]:
        """All objects under a prefix (paginated)."""
        out: list[dict] = []
        page_token = None
        while True:
            params = {"prefix": prefix, "maxResults": str(max_results)}
            if page_token:
                params["pageToken"] = page_token
            url = (
                f"{self.endpoint}/storage/v1/b/{urllib.parse.quote(bucket)}/o"
                f"?{urllib.parse.urlencode(params)}"
            )
            body = json.loads(self._request("GET", url))
            out.extend(body.get("items", []))
            page_token = body.get("nextPageToken")
            if not page_token:
                return out

    def get_object(self, bucket: str, name: str) -> bytes:
        url = (
            f"{self.endpoint}/storage/v1/b/{urllib.parse.quote(bucket)}/o/"
            f"{urllib.parse.quote(name, safe='')}?alt=media"
        )
        return self._request("GET", url)

    def put_object(
        self, bucket: str, name: str, data: bytes,
        content_type: str = "application/octet-stream",
    ) -> dict:
        url = (
            f"{self.endpoint}/upload/storage/v1/b/"
            f"{urllib.parse.quote(bucket)}/o?uploadType=media&name="
            f"{urllib.parse.quote(name, safe='')}"
        )
        body = self._request(
            "POST", url, data=data, headers={"Content-Type": content_type}
        )
        return json.loads(body)

    def delete_object(self, bucket: str, name: str) -> None:
        url = (
            f"{self.endpoint}/storage/v1/b/{urllib.parse.quote(bucket)}/o/"
            f"{urllib.parse.quote(name, safe='')}"
        )
        self._request("DELETE", url)


def sync_prefix_to_dir(
    client: GCSClient, bucket: str, prefix: str, dest,
) -> int:
    """Materialize gs://bucket/prefix into a local directory (the mount's
    read path: examples read through the filesystem; datasets pull once).

    The prefix is matched at a '/' boundary (prefix 'coco' must not pull
    'coco2017/...'), and object names are contained to ``dest`` — a bucket
    object named 'a/../../etc/x' must never escape the mount directory
    (the same invariant Volume._resolve enforces for volume paths).
    """
    from pathlib import Path

    dest = Path(dest).resolve()
    want = prefix.rstrip("/") + "/" if prefix else ""
    n = 0
    for obj in client.list_objects(bucket, prefix):
        name = obj["name"]
        if want:
            if not name.startswith(want):
                continue  # sibling prefix ('coco2017' under prefix 'coco')
            rel = name[len(want):]
        else:
            rel = name
        if not rel or rel.endswith("/"):
            continue
        target = (dest / rel).resolve()
        if target != dest and dest not in target.parents:
            raise PermissionError(f"object name escapes the mount: {name!r}")
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_bytes(client.get_object(bucket, name))
        n += 1
    return n


def sync_dir_to_prefix(client: GCSClient, src, bucket: str, prefix: str) -> int:
    """Upload a local directory under gs://bucket/prefix (the write-back
    path for read-write mounts)."""
    from pathlib import Path

    src = Path(src)
    n = 0
    for p in sorted(src.rglob("*")):
        if not p.is_file():
            continue
        rel = p.relative_to(src).as_posix()
        name = f"{prefix.rstrip('/')}/{rel}" if prefix else rel
        client.put_object(bucket, name, p.read_bytes())
        n += 1
    return n
