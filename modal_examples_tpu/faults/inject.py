"""Deterministic fault injection: named fault points, seeded plans, and a
zero-cost activation gate.

The serving fleet's failure behavior (docs/disagg.md's failure matrix, the
scheduler's shed/requeue paths, the executor's retry policy) used to be
exercised only by hand-written unit cases that fake one failure each. This
module makes failure a first-class, *deterministic* input instead:

- :data:`POINTS` is the ONE catalog of every named ``FaultPoint`` in the
  package — the :mod:`..observability.catalog` pattern applied to failure.
  ``tests/test_static.py`` enforces that every ``_faults.fire("...")`` /
  ``_faults.check("...")`` call site anywhere in the package names a
  declared point AND that every declared point has at least one live call
  site, so dead injection points cannot rot. The seeded chaos plan
  (:mod:`.chaos`) additionally proves each point *fires* end to end.
- :class:`FaultPlan` decides, deterministically, WHICH hits of a point
  fail: ``{"on_hit": n}`` fires exactly on the nth time execution reaches
  the point (or each n in a list), ``{"p": x}`` flips a per-point
  seeded coin per hit (optionally capped with ``max_fires``). Two runs with
  the same seed and the same hit sequence make identical decisions — a
  chaos failure reproduces from ``(seed, plan)`` alone.
- The gate is **zero-cost when disabled**: with no active plan,
  :func:`fire` is one global read and a ``return False`` — no counters, no
  metrics, no allocation. Production code can therefore keep its injection
  points compiled in unconditionally (``tests/test_static.py`` asserts the
  no-op shape).

Activation is explicit (:func:`activate` / :func:`deactivate` / the
:func:`active` context manager) or environment-driven for child processes:
``MTPU_FAULT_PLAN`` (JSON spec) + ``MTPU_FAULT_SEED``. Every fired fault
counts in ``mtpu_faults_injected_total{point}``.

This module is jax-free and import-light: ``core/`` (the jax-free layer)
imports it. Production modules may import :mod:`.inject`; they must NEVER
import :mod:`.chaos` (the driver) — enforced statically.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading

from ..utils.determinism import unit_float as _hash_unit_float

#: the ONE catalog of fault points. name -> {component, effect, recovery}.
#: Names are ``<component>.<failure>``; the component prefix groups the
#: CLI/report rendering. Adding a point here without a production call site
#: (or vice versa) fails tests/test_static.py; a point the default chaos
#: plan cannot reach fails tests/test_chaos.py.
POINTS: dict[str, dict] = {
    "disagg.chunk_corrupt": {
        "component": "serving/disagg/transport.py",
        "effect": "one wire chunk's payload is flipped (stale crc)",
        "recovery": "crc mismatch -> resumable retry re-sends that chunk",
    },
    "disagg.chunk_drop": {
        "component": "serving/disagg/transport.py",
        "effect": "one wire chunk silently vanishes",
        "recovery": "gap detected -> next round re-sends the missing seq",
    },
    "disagg.replica_death": {
        "component": "serving/disagg/transport.py",
        "effect": "ConnectionError mid-transfer (peer died)",
        "recovery": "coordinator unified fallback: re-prefill on decode",
    },
    "disagg.transfer_stall": {
        "component": "serving/disagg/transport.py",
        "effect": "the sender goes quiet between chunks — no error, the "
                  "peer just never sees the next seq",
        "recovery": "watchdog aborts the stalled transfer (stale seq "
                    "watermark) -> TransportError -> unified fallback",
    },
    "disagg.adopt_corrupt": {
        "component": "serving/disagg/roles.py",
        "effect": "the reassembled block corrupts before adoption",
        "recovery": "loud TransportError -> unified fallback",
    },
    "disagg.reserve_shed": {
        "component": "serving/disagg/roles.py",
        "effect": "decode-side admission sheds the migration reservation",
        "recovery": "honest 429 before any byte moves (ShedError)",
    },
    "engine.out_of_pages": {
        "component": "serving/engine.py",
        "effect": "a page claim reports allocator exhaustion",
        "recovery": "preemption-safe requeue; admitted on a later tick",
    },
    "engine.scheduler_crash": {
        "component": "serving/engine.py",
        "effect": "the scheduler thread's step() raises",
        "recovery": "inflight/queued requests fail LOUDLY with "
                    "finish_reason='error'; the loop survives",
    },
    "engine.scheduler_freeze": {
        "component": "serving/engine.py",
        "effect": "the scheduler thread silently stops making progress "
                  "(no exception, healthy() stays true) until stop()",
        "recovery": "watchdog classifies wedged from stale watermarks -> "
                    "stop(reason='error') -> streams failover (health.py)",
    },
    "engine.slow_decode": {
        "component": "serving/engine.py",
        "effect": "one decode tick stalls (~50 ms)",
        "recovery": "latency only; requests still terminate",
    },
    "engine.canary_token_corrupt": {
        "component": "serving/engine.py",
        "effect": "one accepted decode token is deterministically flipped "
                  "(+1 mod vocab) — ONLY on __canary__ probe requests, so "
                  "user-visible streams are never corrupted",
        "recovery": "canary prober detects bit-exact drift vs the golden "
                    "store -> canary_drift alert + incident + router "
                    "down-weight (observability/canary.py)",
    },
    "router.health_flap": {
        "component": "scheduling/router.py",
        "effect": "a replica's health probe reports unhealthy once",
        "recovery": "evicted from candidates, re-probed, re-admitted",
    },
    "tiered.volume_corrupt": {
        "component": "serving/disagg/tiered_cache.py",
        "effect": "bytes read from the Volume tier are corrupted",
        "recovery": "corrupt block dropped; prefix KV recomputed",
    },
    "prefix_store.owner_death": {
        "component": "serving/prefix_store/store.py",
        "effect": "the chain's owner replica dies mid-spill: it drops out "
                  "of the store membership and the write never lands",
        "recovery": "atomic temp+rename leaves no torn block; rendezvous "
                    "remaps the chain and the survivor's next spill takes "
                    "the lease over (journaled owner_takeover)",
    },
    "executor.container_death": {
        "component": "core/executor.py",
        "effect": "the dispatched container dies while processing",
        "recovery": "retry with jittered backoff (mtpu_retries_total)",
    },
    "executor.timeout": {
        "component": "core/executor.py",
        "effect": "the dispatched input exceeds its timeout",
        "recovery": "retry with jittered backoff (mtpu_retries_total)",
    },
}

#: every declared fault-point name (the static guard's allowlist)
ALL_FAULT_POINTS = frozenset(POINTS)


class FaultError(RuntimeError):
    """An injected failure (never raised by real fault paths — catching it
    is how handlers distinguish chaos from genuine scheduler-logic bugs)."""


def _unit_float(seed: int, point: str, hit: int) -> float:
    """Deterministic uniform in [0, 1) from (seed, point, hit) — stable
    across processes and python hash randomization (the same hashing
    scheme retry jitter uses: utils.determinism)."""
    return _hash_unit_float(seed, point, hit)


class FaultPlan:
    """A seeded, deterministic decision table over :data:`POINTS`.

    ``spec`` maps point name -> one of:

    - ``{"on_hit": n}`` — fire exactly when the point is reached the nth
      time (1-based); ``n`` may be a list of hit numbers.
    - ``{"p": x}`` — fire each hit with probability ``x``, decided by a
      per-(seed, point, hit) hash — deterministic, order-independent
      across points. Optional ``"max_fires": k`` caps total fires.

    Unknown point names are rejected up front (the plan is checked against
    the catalog, like metric names are). Hits are counted for EVERY
    declared point the plan is active over — ``hits()`` is the
    reachability record even for points the plan never fires.
    """

    def __init__(self, spec: dict, *, seed: int = 0):
        unknown = set(spec) - ALL_FAULT_POINTS
        if unknown:
            raise ValueError(
                f"unknown fault points {sorted(unknown)}; declared points: "
                f"{sorted(ALL_FAULT_POINTS)}"
            )
        self.seed = int(seed)
        self._spec: dict[str, dict] = {}
        for point, cfg in spec.items():
            cfg = dict(cfg)
            if "on_hit" in cfg:
                n = cfg["on_hit"]
                cfg["on_hit"] = frozenset(
                    int(x) for x in (n if isinstance(n, (list, tuple)) else [n])
                )
            elif "p" not in cfg:
                raise ValueError(
                    f"fault spec for {point!r} needs 'on_hit' or 'p': {cfg}"
                )
            self._spec[point] = cfg
        self._lock = threading.Lock()
        self._hits: dict[str, int] = {}
        self._fired: dict[str, int] = {}

    def should_fire(self, point: str) -> bool:
        """Count one hit of ``point`` and decide whether it fails."""
        with self._lock:
            hit = self._hits.get(point, 0) + 1
            self._hits[point] = hit
            cfg = self._spec.get(point)
            if cfg is None:
                return False
            on_hit = cfg.get("on_hit")
            if on_hit is not None:
                fire = hit in on_hit
            else:
                fired = self._fired.get(point, 0)
                if fired >= cfg.get("max_fires", float("inf")):
                    return False
                fire = _unit_float(self.seed, point, hit) < cfg["p"]
            if fire:
                self._fired[point] = self._fired.get(point, 0) + 1
            return fire

    def hits(self) -> dict[str, int]:
        """Times each point was REACHED while this plan was active."""
        with self._lock:
            return dict(self._hits)

    def fired(self) -> dict[str, int]:
        """Times each point actually FIRED."""
        with self._lock:
            return dict(self._fired)


#: the active plan. A plain module global (not a contextvar): fault points
#: are hit from the engine's scheduler thread, server threads, and executor
#: workers — none of which inherit the activator's context.
_active_plan: FaultPlan | None = None


def activate(plan: FaultPlan) -> FaultPlan:
    """Arm ``plan`` process-wide. Returns it (chaining convenience)."""
    global _active_plan
    _active_plan = plan
    return plan


def deactivate() -> None:
    global _active_plan
    _active_plan = None


def active_plan() -> FaultPlan | None:
    return _active_plan


@contextlib.contextmanager
def active(plan: FaultPlan):
    """``with active(FaultPlan({...}, seed=7)) as plan:`` — scoped arming;
    always disarms, even when the driven code raises."""
    activate(plan)
    try:
        yield plan
    finally:
        deactivate()


def fire(point: str) -> bool:
    """True when the active plan says this hit of ``point`` should fail.

    THE gate: with no plan active this is one global read + return — the
    zero-cost-when-disabled contract tests/test_static.py pins down.
    """
    if _active_plan is None:
        return False
    # snapshot: deactivate() may race from another thread between the
    # None-check above and the call below (the engine's scheduler threads
    # keep ticking while the chaos runner disarms plans) — a torn read
    # must mean "disarmed", never an AttributeError inside a scheduler loop
    plan = _active_plan
    if plan is not None and plan.should_fire(point):
        from ..observability import metrics as _obs
        from ..observability import reqtrace as _reqtrace

        _obs.record_fault_injected(point)
        # a FIRED fault becomes a span event on the request whose
        # operation this thread is running (no-op without an ambient
        # frame) — the disabled gate above never reaches this branch
        _reqtrace.note_fault(point)
        return True
    return False


def check(point: str, exc: type = FaultError, message: str | None = None) -> None:
    """Raise ``exc`` when this hit of ``point`` fires (one-line call sites
    for raise-style faults)."""
    if fire(point):
        raise exc(message or f"injected fault: {point}")


def corrupt(point: str, data: bytes) -> bytes:
    """Return ``data`` with its last byte flipped when ``point`` fires
    (one-line call sites for corruption-style faults); unchanged otherwise.
    Empty payloads pass through — there is nothing to corrupt."""
    if data and fire(point):
        return data[:-1] + bytes([data[-1] ^ 0xFF])
    return data


def _activate_from_env() -> None:
    """Child-process activation: ``MTPU_FAULT_PLAN`` (JSON spec) +
    ``MTPU_FAULT_SEED``. A malformed plan is a loud error — a chaos run
    that silently injected nothing would 'pass' every invariant."""
    raw = os.environ.get("MTPU_FAULT_PLAN", "")
    if not raw:
        return
    seed = int(os.environ.get("MTPU_FAULT_SEED", "0") or 0)
    activate(FaultPlan(json.loads(raw), seed=seed))


_activate_from_env()
