"""Fault injection & chaos testing (docs/faults.md).

Two halves with a hard layering rule between them:

- :mod:`.inject` — the deterministic fault-point catalog, seeded
  :class:`~.inject.FaultPlan`, and the zero-cost activation gate.
  Production code wires its injection points through this module.
- :mod:`.chaos` — the seeded chaos runner that drives a multi-replica
  fleet through fault episodes and asserts fleet invariants. It is a
  DRIVER: tests, ``bench.py``, and operators import it; production modules
  never do (enforced by ``tests/test_static.py``).

Only the inject surface is re-exported here, so ``from
modal_examples_tpu.faults import fire`` can never drag the chaos driver
(and its serving imports) into a production module.
"""

from .inject import (  # noqa: F401
    ALL_FAULT_POINTS,
    POINTS,
    FaultError,
    FaultPlan,
    activate,
    active,
    active_plan,
    check,
    corrupt,
    deactivate,
    fire,
)
