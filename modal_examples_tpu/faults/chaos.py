"""Seeded chaos runner: drive a mixed-class replica fleet through fault
episodes and assert fleet invariants after every one.

The failure matrix in docs/disagg.md used to be prose plus hand-written
unit cases; this module makes it an *enforced contract* the same way PR 2's
metric catalog made observability structural. :func:`run_chaos` builds a
real fleet — a unified replica plus a disaggregated prefill/decode pair
(CPU-sized models) fronted by a :class:`~..serving.disagg.DisaggCoordinator`
— and runs a fixed schedule of **episodes**: each arms one small seeded
:class:`~.inject.FaultPlan`, drives traffic through the coordinator, and
then checks the **fleet invariants**:

- **terminal** — every submitted request reached a terminal
  ``finish_reason`` within a timeout: no wedged streams, ever.
- **drained** — on every replica, queues are empty, all slots are free,
  admission page reservations are back to zero, and every allocated KV page
  is accounted for by the prefix cache (nothing orphaned).
- **conservation** — ``submitted == finished + shed`` (aborted and
  deadline-expired requests still *finish*, with their honest reason).
- **router recovered** — no replica is stuck on the down list and a fresh
  placement succeeds.
- **token identity** — any request that finished normally
  (``stop``/``length``) produced output identical to a fault-free
  reference run; faults may kill requests, never corrupt survivors.

Episode results append to ``<state_dir>/chaos.jsonl`` (the autoscaler-
journal pattern) and the registry is pushed as job ``chaos``, so ``tpurun
chaos`` and the gateway's ``/chaos`` can answer "what did the last episode
inject and did the fleet hold?" after the fact. Reproduction is
``(seed, episode schedule)``: the schedule is fixed, so one seed replays
one chaos run.

LAYERING: this module is a DRIVER. Tests, ``bench.py``, and operators
import it; production modules never do (``tests/test_static.py`` enforces
it — production code may import :mod:`.inject` only).
"""

from __future__ import annotations

import queue as _queue
import time

from .inject import ALL_FAULT_POINTS, FaultPlan, active

#: per-request drain timeout: generous for CPU-compile stalls, small enough
#: that a genuinely wedged stream fails the run, not the CI timeout
DRAIN_TIMEOUT_S = 120.0

#: the chaos traffic prompt palette: shared prefixes (affinity + tiered
#: promotion) with distinct tails (distinct requests)
_BASE = "the quick brown fox jumps over the lazy dog "
_PROMPTS = [
    _BASE + "and then some more",
    _BASE + "and naps in the sun",
    _BASE + "and then some more",  # repeat: prefix-cache / tier hit
    "completely different prompt about thundering herds",
]


class ChaosInvariantError(AssertionError):
    """A fleet invariant failed after an episode (the report carries the
    violations; the episode name says which plan was armed)."""


# -- invariant checkers -------------------------------------------------------
#
# Standalone, side-effect-free, and duck-typed so tests can hand them
# violating states directly (tests/test_faults.py).


def check_terminal(results: list) -> list[str]:
    """Every result must carry a terminal finish_reason (no wedges)."""
    out = []
    for r in results:
        if r.get("wedged"):
            out.append(f"request {r.get('id')} wedged (no terminal marker)")
        elif not r.get("finish_reason"):
            out.append(f"request {r.get('id')} has no finish_reason")
    return out


def check_conservation(submitted: int, finished: int, shed: int) -> list[str]:
    """``submitted == finished + shed``: every request either terminated a
    stream or was honestly rejected at admission — nothing vanished."""
    if submitted != finished + shed:
        return [
            f"conservation violated: submitted={submitted} != "
            f"finished={finished} + shed={shed}"
        ]
    return []


def check_drained(engines: dict) -> list[str]:
    """Queues empty, slots free, reservations zero, and every allocated KV
    page accounted for by the prefix cache (non-destructive: cached
    zero-ref pages are warmth, not leaks)."""
    out = []
    for name, eng in engines.items():
        depth = eng.policy.total_depth()
        if depth:
            out.append(f"{name}: {depth} requests still queued")
        busy = sum(1 for s in eng.slots if not s.free)
        if busy:
            out.append(f"{name}: {busy} slots still occupied")
        reserved = eng.admission.reserved_pages
        if reserved:
            out.append(f"{name}: {reserved} KV pages still reserved")
        used = (eng.cache.n_pages - 1) - eng.cache.allocator.available
        cached = (
            eng.prefix_cache.cached_pages
            if eng.prefix_cache is not None
            else 0
        )
        if used != cached:
            out.append(
                f"{name}: {used} pages allocated but only {cached} "
                "prefix-cached — orphaned pages"
            )
    return out


def settle_drained(engines: dict, timeout: float = 10.0,
                   poll_s: float = 0.02) -> list[str]:
    """Poll :func:`check_drained` until clean or ``timeout``; returns the
    final violation list (empty on success). The finish marker is
    delivered to the client queue BEFORE the scheduler thread frees the
    slot and releases its pages, so an *instant* drain check right after
    the last stream joins is racy by construction — and on a starved CI
    box the scheduler thread may lag the client by whole ticks. Settling
    is the honest way to assert drain; a genuinely leaked slot or page
    still fails, just ``timeout`` seconds later."""
    deadline = time.monotonic() + timeout
    while True:
        violations = check_drained(engines)
        if not violations or time.monotonic() >= deadline:
            return violations
        time.sleep(poll_s)


def check_router_recovered(router) -> list[str]:
    """No replica stuck on the down list, and every replica healthy."""
    out = []
    stats = router.stats()
    for name, info in stats["replicas"].items():
        if info.get("down"):
            out.append(f"replica {name} still marked down")
        if not info.get("healthy"):
            out.append(f"replica {name} still unhealthy")
    return out


def settle_recovered(router, timeout: float = 10.0,
                     poll_s: float = 0.05) -> list[str]:
    """Poll :func:`check_router_recovered` until clean or ``timeout``,
    driving the router's re-probe pass (``router.reprobe()``) each round;
    returns the final violation list (empty on success). Re-admission —
    and the revival probe that restarts a stopped-on-error engine — only
    advances on a placement walk, so a replica that died near the END of
    a load window stays marked down once traffic stops, and an instant
    recovery check is racy by construction: the :func:`settle_drained`
    lesson applied to router health. A genuinely unrecoverable replica
    still fails, just ``timeout`` seconds later."""
    deadline = time.monotonic() + timeout
    while True:
        reprobe = getattr(router, "reprobe", None)
        if reprobe is not None:
            reprobe()
        violations = check_router_recovered(router)
        if not violations or time.monotonic() >= deadline:
            return violations
        time.sleep(poll_s)


def check_token_identity(results: list, reference: dict) -> list[str]:
    """Requests that finished normally must match the fault-free reference
    byte for byte — faults may kill requests, never corrupt survivors."""
    out = []
    for r in results:
        if r.get("finish_reason") in ("stop", "length") and not r.get("aborted"):
            ref = reference.get(r["prompt"])
            if ref is not None and r["output"] != ref:
                out.append(
                    f"request {r.get('id')} diverged from the fault-free "
                    f"run: {r['output']!r} != {ref!r}"
                )
    return out


# -- the runner ---------------------------------------------------------------


def _drain(req, timeout: float = DRAIN_TIMEOUT_S) -> dict:
    """Collect one request's stream with a wedge watchdog (the engine's
    ``stream()`` would block forever on a wedged queue — detecting exactly
    that is this harness's job)."""
    from ..serving.engine import _Finish

    out: list[str] = []
    deadline = time.monotonic() + timeout
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return {
                "id": req.request_id,
                "prompt": req.prompt,
                "output": "".join(out),
                "finish_reason": None,
                "wedged": True,
            }
        try:
            item = req.out_queue.get(timeout=min(remaining, 1.0))
        except _queue.Empty:
            continue
        if isinstance(item, _Finish):
            req.finish_reason = item.reason
            return {
                "id": req.request_id,
                "prompt": req.prompt,
                "output": "".join(out),
                "finish_reason": item.reason,
                "wedged": False,
            }
        out.append(item)


class _Fleet:
    """One unified + one disagg prefill/decode pair behind a coordinator,
    plus a fault-free reference engine — all tiny, all greedy."""

    def __init__(self, seed: int):
        from ..models import llama
        from ..scheduling import EngineReplica
        from ..serving import LLMEngine, SamplingParams
        from ..serving.disagg import DisaggCoordinator
        from ..serving.health import FleetWatchdog, WatchdogPolicy
        from ..storage.volume import Volume

        self.seed = seed
        self.params = SamplingParams(max_tokens=8, temperature=0.0)
        cfg = llama.LlamaConfig.tiny()

        def engine(**kw):
            kw.setdefault("max_slots", 2)
            kw.setdefault("max_model_len", 64)
            kw.setdefault("page_size", 8)
            kw.setdefault("prefill_buckets", (32,))
            return LLMEngine(cfg, seed=0, **kw)

        # fault-free reference outputs first (greedy: deterministic per
        # prompt, independent of which replica serves it)
        ref_engine = engine()
        try:
            self.reference = {
                p: ref_engine.generate(p, self.params)
                for p in set(_PROMPTS)
            }
        finally:
            ref_engine.stop()

        self.volume_cm = Volume.ephemeral()
        vol = self.volume_cm.__enter__()
        # pre and uni share ONE fleet-wide prefix store over the same
        # volume (docs/prefix_store.md): rendezvous spill ownership and
        # cross-replica promotion are live in every episode, and the
        # prefix-store-owner-death episode kills whichever of the two the
        # rendezvous made the warm chain's owner
        self.pre = engine(
            tiered_prefix={
                "host_bytes": 1 << 20, "volume": vol,
                "shared": True, "replica": "pre-0",
            }
        )
        self.dec = engine()
        self.uni = engine(
            tiered_prefix={
                "host_bytes": 1 << 20, "volume": vol,
                "shared": True, "replica": "uni-0",
            }
        )
        self.engines = {"pre-0": self.pre, "dec-0": self.dec,
                        "uni-0": self.uni}
        self.coord = DisaggCoordinator(
            [
                EngineReplica(self.pre, "pre-0", role="prefill"),
                EngineReplica(self.dec, "dec-0", role="decode"),
                EngineReplica(self.uni, "uni-0", role="unified"),
            ],
            chunk_bytes=256,
            reprobe_s=0.2,
        )
        # decode-capable loops run for the whole chaos run (the prefill
        # replica's engine must never start — docs/disagg.md)
        for eng in self.coord.serving_engines():
            eng.start()
        # the gray-failure watchdog supervises the whole run
        # (docs/health.md): the silent-freeze and transfer-stall episodes
        # are only recoverable because it turns stale watermarks into the
        # error-stop / transfer-abort ladder. Thresholds are generous
        # enough that a slow CI tick never false-positives (compiles are
        # disk-cache-warm after the reference run), small enough that
        # detection + recovery fit well inside DRAIN_TIMEOUT_S; quarantine
        # is effectively off — one freeze episode must take the
        # stop -> revive -> re-probe leg, not the bench.
        self.watchdog = FleetWatchdog(
            self.coord.router,
            policy=WatchdogPolicy(
                degraded_after_s=2.0,
                wedged_after_s=5.0,
                transfer_stall_s=1.5,
                quarantine_after=99,
            ),
            poll_s=0.1,
        ).start()

    def close(self) -> None:
        self.watchdog.stop()
        self.dec.stop()
        self.uni.stop()
        self.volume_cm.__exit__(None, None, None)


def _traffic(fleet: _Fleet, *, n: int, via: str = "coord",
             abort_index: int | None = None) -> tuple[list, int, int]:
    """Submit ``n`` seeded requests and drain them all. Returns
    ``(results, shed, attempted)`` — ``attempted`` is counted
    independently of the result/shed bookkeeping, so the conservation
    invariant (attempted == finished + shed) can actually catch a request
    that vanishes between submit and drain. ``via="uni"`` targets the
    unified replica directly (mixed-class traffic); ``abort_index`` aborts
    that submission right after submit (a client disconnect)."""
    from ..scheduling.admission import ShedError

    results, shed, attempted = [], 0, 0
    for i in range(n):
        attempted += 1
        prompt = _PROMPTS[i % len(_PROMPTS)]
        try:
            if via == "uni":
                req = fleet.uni.submit(prompt, fleet.params)
            else:
                req = fleet.coord.submit(prompt, fleet.params)
        except ShedError:
            shed += 1
            continue
        aborted = abort_index == i
        if aborted:
            (fleet.coord if via == "coord" else fleet.uni).abort(req)
        result = _drain(req)
        # runner-initiated aborts legitimately truncate output (partial or
        # empty text under finish_reason="stop"): exempt from the
        # token-identity invariant, which is about UNTOUCHED requests
        result["aborted"] = aborted
        results.append(result)
    return results, shed, attempted


def _force_spill(engine, *, rewrite: bool = False, only_chain=None) -> None:
    """Evict an idle engine's whole trie (spills into the host tier) and
    demote host blocks to the shared volume store — the chaos lever that
    makes spill ownership (and the armed owner-death fault) fire
    deterministically instead of waiting for host-LRU overflow.
    ``rewrite=True`` first invalidates the blocks from the store, so the
    demotes are real writes even when earlier episodes already spilled
    the same chains (a dedup skip never reaches the fault point).
    ``only_chain`` restricts the demotes to one chain's blocks — the
    owner-death episode needs the lease to land on a chain BOTH replicas
    hold, not whatever an earlier episode left oldest in the host LRU."""
    t = engine.tiered
    engine.prefix_cache.evict(10_000)
    with t._lock:
        items = [
            (h, d) for h, d in t._host.items()
            if only_chain is None or t._chain_of.get(h) == only_chain
        ]
    if rewrite:
        for h, _ in items:
            t.store.invalidate(h)
    for h, data in items:
        t._demote_to_volume(h, data)
        with t._lock:
            t._host.pop(h, None)
            t._host_used -= len(data)


def _owner_death_spill(fleet: _Fleet):
    """The controlled middle of the ``prefix-store-owner-death`` episode
    (both replicas already warm on the shared chain, the fault armed):
    force-spill the chain's rendezvous OWNER first — the injected crash
    fires mid-put, after the spill lease is taken but before the write
    lands, and deregisters the owner from the membership — then
    force-spill the survivor, whose put takes the dead owner's lease over
    (journaled ``owner_takeover``) and lands the block. Returns the
    surviving engine plus any episode-specific violations."""
    violations: list[str] = []
    pre_s, uni_s = fleet.pre.tiered.store, fleet.uni.tiered.store
    # earlier episodes may have outlived the membership TTL: refresh both
    # heartbeats so ownership math sees two live candidates
    pre_s.heartbeat()
    uni_s.heartbeat()
    # the episode must exercise ONE chain both replicas hold (the freshly
    # warmed shared prompt), or the dead owner's lease lands on a stale
    # chain the survivor never spills and no takeover can be observed
    with fleet.uni.tiered._lock:
        uni_heads = list(dict.fromkeys(fleet.uni.tiered._chain_of.values()))
    with fleet.pre.tiered._lock:
        pre_heads = set(fleet.pre.tiered._chain_of.values())
    shared_heads = [h for h in uni_heads if h in pre_heads]
    head = (shared_heads or uni_heads or [None])[-1]
    owner = pre_s.owner_for(head) if head is not None else None
    dead, survivor = (
        (fleet.uni, fleet.pre) if owner == "uni-0" else (fleet.pre, fleet.uni)
    )
    base_takeovers = survivor.tiered.store.board.takeovers
    # armed fault: the owner dies mid-spill of the shared chain
    _force_spill(dead, rewrite=True, only_chain=head)
    # lease takeover + the write that lands
    _force_spill(survivor, only_chain=head)
    if survivor.tiered.store.board.takeovers <= base_takeovers:
        violations.append(
            "owner died mid-spill but the survivor recorded no lease "
            "takeover"
        )
    # the dead replica's ENGINE kept serving (only its store membership
    # died): rejoin so later traffic sees a full membership again
    dead.tiered.store.register_replica()
    return survivor, violations


#: the fixed episode schedule: (name, fault spec, traffic kwargs). One
#: small plan per episode keeps every injection deterministic — the nth
#: hit of a point is the nth time THIS episode's traffic reaches it —
#: and invariants are asserted after each, per the docs/faults.md contract.
EPISODES: list[tuple[str, dict, dict]] = [
    ("transport-corrupt", {"disagg.chunk_corrupt": {"on_hit": 1}},
     {"n": 2}),
    ("transport-drop", {"disagg.chunk_drop": {"on_hit": 1}}, {"n": 2}),
    ("transport-death", {"disagg.replica_death": {"on_hit": 1}}, {"n": 2}),
    ("adopt-corrupt", {"disagg.adopt_corrupt": {"on_hit": 1}}, {"n": 2}),
    ("reserve-shed", {"disagg.reserve_shed": {"on_hit": 1}}, {"n": 2}),
    # out_of_pages hit 1 lands on the unified replica's slot-claim path
    # (the traffic drains request-by-request, so the claim order is fixed):
    # the preemption-safe requeue, then normal admission on a later tick
    ("engine-pressure",
     {"engine.out_of_pages": {"on_hit": 1},
      "engine.slow_decode": {"on_hit": 3}},
     {"n": 2, "via": "uni"}),
    # a client abort mid-fleet plus a decode stall: the abort path must
    # release reservations exactly like PR 4/6 promised
    ("client-abort", {"engine.slow_decode": {"on_hit": 2}},
     {"n": 3, "abort_index": 1}),
    ("router-flap", {"router.health_flap": {"on_hit": 1}}, {"n": 2}),
    ("tiered-corrupt", {"tiered.volume_corrupt": {"on_hit": 1}}, {"n": 2}),
    # scheduler crash: fires on whichever running engine's loop reaches the
    # hit first; its callers finish LOUDLY with "error", the loop survives
    ("scheduler-crash", {"engine.scheduler_crash": {"on_hit": 30}},
     {"n": 4}),
    # SILENT scheduler freeze (docs/health.md): p=1.0 x max_fires=1 freezes
    # whichever decode-capable loop hits step() first — no exception,
    # healthy() stays true, the gray failure only progress watermarks can
    # see. Requests that land on the frozen replica queue against a dead
    # scheduler; the fleet watchdog classifies it wedged once it holds
    # outstanding work, error-stops it (streams finish loudly, zero
    # wedges), and the router's re-probe cycle revives it. Freezing BOTH
    # loops would honestly leave no healthy replica to place on — a
    # different (shed-everything) contract than the recovery this episode
    # pins down.
    ("silent-freeze",
     {"engine.scheduler_freeze": {"p": 1.0, "max_fires": 1}},
     {"n": 3}),
    # mid-transfer chunk stall without an error (docs/health.md): the
    # sender goes quiet; the watchdog's stalled-seq-watermark abort turns
    # it into a TransportError and the coordinator's PR-6 unified fallback
    # completes the request token-identically on the decode side
    ("transfer-stall", {"disagg.transfer_stall": {"on_hit": 1}}, {"n": 2}),
    # the shared prefix store's chain OWNER dies mid-spill
    # (docs/prefix_store.md): membership drops, the write never lands
    # (atomic temp+rename: no torn block), and the survivor's next spill
    # of the chain takes the lease over — journaled owner_takeover — then
    # re-promotes the churned chain warm from the store. The post-traffic
    # leg lives in :func:`_owner_death_leg`.
    ("prefix-store-owner-death",
     {"prefix_store.owner_death": {"on_hit": 1}},
     {"n": 2}),
    # numeric drift (docs/observability.md#correctness-canary): ONE
    # accepted decode token flipped on a canary probe — tenant-gated, so
    # the concurrent user traffic (and its token-identity invariant) is
    # untouched. The golden is recorded OUTSIDE the armed plan; the
    # corrupted round must be detected as drift, capture a canary_drift
    # incident, and down-weight the drifting replica while the other
    # serving replica's probes keep passing.
    ("canary-numeric-drift",
     {"engine.canary_token_corrupt": {"on_hit": 1}},
     {"n": 2}),
]


def _run_episode(fleet: _Fleet, name: str, spec: dict, seed: int,
                 traffic_kw: dict) -> dict:
    plan = FaultPlan(spec, seed=seed)
    extra_violations: list[str] = []
    pre_results: list = []
    pre_shed = pre_attempted = 0
    survivor = None
    base_vol_hits = 0
    prober = None
    if name == "canary-numeric-drift":
        # pre-condition (the prefix-store-owner-death hazard, below): the
        # silent-freeze episode can leave a loop frozen-but-IDLE, and the
        # canary is the first thing since to hand dec-0 work directly —
        # probing a frozen loop wedges the probe requests and drags the
        # watchdog into the episode. Play the operator: restart any
        # serving loop that stopped ticking before probing it.
        from ..serving.health import replica_snapshot

        for eng in (fleet.dec, fleet.uni):
            rep = next(
                r for r in fleet.coord.replicas if r.engine is eng
            )
            seq0 = replica_snapshot(rep).get("tick_seq")
            deadline = time.monotonic() + 1.0
            while (
                replica_snapshot(rep).get("tick_seq") == seq0
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            if replica_snapshot(rep).get("tick_seq") == seq0:
                eng.stop()
                eng.start()
        # the clean round runs OUTSIDE the armed plan: the first serving
        # replica records the golden, the second compares against it —
        # the store must hold uncorrupted transcripts before the armed
        # round can be judged as drift rather than a fresh recording
        from ..observability.canary import CanaryProber

        prober = CanaryProber(
            fleet.coord.router, fail_threshold=1, interval_s=3600.0
        )
        prober.probe_once()
    if name == "prefix-store-owner-death":
        # pre-condition: the silent-freeze episode can leave a loop
        # frozen-but-IDLE (healthy() true, zero outstanding — the
        # watchdog ladder only fires once the engine holds work,
        # docs/health.md), and this episode direct-submits to uni-0,
        # bypassing the router probes that would otherwise revive it.
        # The harness plays the operator: restart a loop that stopped
        # ticking before building on it.
        from ..serving.health import replica_snapshot

        uni_rep = next(
            r for r in fleet.coord.replicas if r.name == "uni-0"
        )

        def _uni_tick_seq():
            return replica_snapshot(uni_rep).get("tick_seq")

        seq0 = _uni_tick_seq()
        deadline = time.monotonic() + 1.0
        while _uni_tick_seq() == seq0 and time.monotonic() < deadline:
            time.sleep(0.02)
        if _uni_tick_seq() == seq0:
            fleet.uni.stop()
            fleet.uni.start()
        # warm BOTH store members on the shared chain OUTSIDE the armed
        # plan: an organic host-overflow demote must not consume the
        # single owner-death charge before the controlled owner spill
        pre_results, pre_shed, pre_attempted = _traffic(fleet, **traffic_kw)
        more, more_shed, more_att = _traffic(fleet, n=2, via="uni")
        pre_results += more
        pre_shed += more_shed
        pre_attempted += more_att
    with active(plan):
        if name == "prefix-store-owner-death":
            survivor, extra_violations = _owner_death_spill(fleet)
            # re-drive the shared prefix at the SURVIVOR: its churned
            # fast tiers must promote the chain warm from the store
            base_vol_hits = survivor.tiered.tier_hits["volume"]
            traffic_kw = {
                "n": 2, "via": "uni" if survivor is fleet.uni else "coord"
            }
        if name == "tiered-corrupt":
            # chaos pressure: evict the prefill trie and demote the host
            # tier so the NEXT shared-prefix prompt promotes from the
            # Volume — where the corruption fires
            tiered = fleet.pre.tiered
            fleet.pre.prefix_cache.evict(10_000)
            for h, data in list(tiered._host.items()):
                # chain=None: chaos applies pressure as a driver — the
                # block must LAND for the promote-path corruption to fire,
                # so rendezvous spill ownership is deliberately bypassed
                tiered.store.put(h, data)
                with tiered._lock:
                    tiered._host.pop(h, None)
                    tiered._host_used -= len(data)
        results, shed, attempted = _traffic(fleet, **traffic_kw)
        if name == "prefix-store-owner-death":
            results = pre_results + results
            shed += pre_shed
            attempted += pre_attempted
            if survivor.tiered.tier_hits["volume"] <= base_vol_hits:
                extra_violations.append(
                    "churned chain did not re-promote from the shared "
                    "store on the surviving replica"
                )
        if name == "canary-numeric-drift":
            # armed round: the first canary token accepted fleet-wide is
            # flipped (+1 mod vocab) — the prober must see bit-exact drift
            # on that replica, down-weight it (fail_threshold=1 here; the
            # production default demands consecutive failing rounds), and
            # keep passing on the other serving replica. Probe requests
            # never enter ``results``: the token-identity invariant is
            # about user traffic, and the probe's whole job is to diverge.
            round2 = prober.probe_once()
            snap = prober.snapshot()
            if snap["drifts"] < 1:
                extra_violations.append(
                    "injected canary token corruption was never detected "
                    "as drift"
                )
            drifted = [
                rep for rep, probes in round2.items()
                if any(p["result"] == "drift" for p in probes)
            ]
            if drifted and sorted(drifted) != snap["downweighted"]:
                extra_violations.append(
                    f"drifting replica(s) {drifted} were not down-weighted "
                    f"(downweighted={snap['downweighted']})"
                )
            healthy = [rep for rep in round2 if rep not in drifted]
            for rep in healthy:
                if not all(p["result"] == "pass" for p in round2[rep]):
                    extra_violations.append(
                        f"non-drifting replica {rep} stopped passing its "
                        "canaries during the drift episode"
                    )
            # hand traffic back at full weight: the canary proved its
            # point; later invariants expect an evenly-weighted fleet
            for rep in snap["downweighted"]:
                fleet.coord.router.set_health_weight(rep, 1.0)
        if name in ("router-flap", "silent-freeze"):
            # let the down timer lapse, then place again: the re-probe
            # re-admission path (mtpu_router_readmissions_total). For the
            # freeze episode this is the ladder's last leg — the watchdog
            # error-stopped the wedged engines, and these placements
            # probe, revive, and restart them (docs/health.md)
            time.sleep(fleet.coord.router.reprobe_s + 0.3)
            more, more_shed, more_attempted = _traffic(fleet, n=2)
            results += more
            shed += more_shed
            attempted += more_attempted
    # recovery drive: the watchdog may have error-stopped a replica the
    # episode never scripted a recovery for (on a starved CI box a slow
    # tick can read as a wedge — a false positive the ladder still
    # handles). Re-probe + readmission only complete when a placement
    # actually lands on the revived replica, so play the operator for ANY
    # episode that ends with a replica down, not just router-flap /
    # silent-freeze: wait out the down timer and place fresh traffic.
    for _ in range(2):
        if not check_router_recovered(fleet.coord.router):
            break
        time.sleep(fleet.coord.router.reprobe_s + 0.3)
        more, more_shed, more_attempted = _traffic(fleet, n=2)
        results += more
        shed += more_shed
        attempted += more_attempted
    # settle: the finish marker reaches the client BEFORE the scheduler
    # frees the slot; the decode/unified loops run continuously so this
    # is bounded and short
    settle_drained(fleet.engines)

    violations = (
        check_terminal(results)
        + check_conservation(attempted, len(results), shed)
        + check_drained(fleet.engines)
        + check_router_recovered(fleet.coord.router)
        + check_token_identity(results, fleet.reference)
        + extra_violations
    )
    reasons: dict[str, int] = {}
    for r in results:
        key = r["finish_reason"] or "WEDGED"
        reasons[key] = reasons.get(key, 0) + 1
    fired = plan.fired()
    return {
        "at": time.time(),
        "episode": name,
        "seed": seed,
        "injected": fired,
        "hits": plan.hits(),
        "finished": reasons,
        "shed": shed,
        "wedged": sum(1 for r in results if r.get("wedged")),
        "recovered": sum(
            1 for r in results
            if r["finish_reason"] in ("stop", "length")
        ) if fired else 0,
        "invariants": violations or "ok",
    }


def _run_executor_episode(seed: int) -> dict:
    """Executor-layer chaos: a process-backend function pool, with one
    injected container death and one injected timeout — both recovered by
    the (now jittered) retry path."""
    import modal_examples_tpu as mtpu

    app = mtpu.App("chaos-exec")

    @app.function(
        timeout=30,
        retries=mtpu.Retries(max_retries=2, initial_delay=0.0),
    )
    def ping(x: int) -> int:
        return x + 1

    plan = FaultPlan(
        {
            "executor.container_death": {"on_hit": 1},
            "executor.timeout": {"on_hit": 2},
        },
        seed=seed,
    )
    finished = 0
    violations: list[str] = []
    # failures RECORD, never raise: run_chaos(strict=False) promises the
    # bench child a structured report, not a traceback and no JSON line
    with active(plan):
        try:
            with app.run():
                for i in range(3):
                    got = ping.remote(i)
                    if got != i + 1:
                        violations.append(
                            f"call {i} returned {got!r}, wanted {i + 1}"
                        )
                    finished += 1
        except Exception as e:
            violations.append(
                f"executor episode raised {type(e).__name__}: {e} — the "
                "retry path did not recover the injected failures"
            )
    fired = plan.fired()
    if len(fired) < 2:
        violations.append(f"executor faults did not all fire: {fired}")
    return {
        "at": time.time(),
        "episode": "executor-retry",
        "seed": seed,
        "injected": fired,
        "hits": plan.hits(),
        "finished": {"ok": finished},
        "shed": 0,
        "wedged": 0,
        "recovered": finished if fired else 0,
        "invariants": violations or "ok",
    }


def run_chaos(
    seed: int = 0,
    *,
    include_executor: bool = True,
    journal_path=None,
    strict: bool = True,
    push: bool = True,
) -> dict:
    """Run the full episode schedule against a fresh fleet; return the
    aggregated report (the ``faults`` section shape ``bench.py`` emits).

    ``strict=True`` raises :class:`ChaosInvariantError` on the first
    episode whose invariants fail; ``strict=False`` records the violations
    in the report instead (the CLI/bench path — the ``wedged``/
    ``invariants`` fields stay honest either way). Episode records append
    to ``<state_dir>/chaos.jsonl`` and the registry pushes as job
    ``chaos`` so ``tpurun chaos`` / ``/chaos`` render the run afterwards.
    """
    from ..observability import incident as _incident
    from ..observability.journal import named_journal

    journal = named_journal("chaos", path=journal_path)

    def _note_violation(rec: dict) -> None:
        # a failed fleet invariant IS the incident: capture the bundle
        # before the strict raise tears the run down (strict=False records
        # it too — the bench child's report and the bundle stay paired)
        _incident.capture(
            "chaos_invariant",
            reason=f"episode {rec['episode']}: {rec['invariants']}",
        )

    fleet = _Fleet(seed)
    episodes: list[dict] = []
    try:
        for name, spec, traffic_kw in EPISODES:
            rec = _run_episode(fleet, name, spec, seed, traffic_kw)
            journal.record(rec)
            episodes.append(rec)
            if rec["invariants"] != "ok":
                _note_violation(rec)
                if strict:
                    raise ChaosInvariantError(
                        f"episode {name!r}: {rec['invariants']}"
                    )
    finally:
        fleet.close()
    if include_executor:
        rec = _run_executor_episode(seed)
        journal.record(rec)
        episodes.append(rec)
        if rec["invariants"] != "ok":
            _note_violation(rec)
            if strict:
                raise ChaosInvariantError(
                    f"episode executor-retry: {rec['invariants']}"
                )

    injected: dict[str, int] = {}
    for rec in episodes:
        for point, n in rec["injected"].items():
            injected[point] = injected.get(point, 0) + n
    report = {
        "seed": seed,
        "episodes": episodes,
        "injected": injected,
        "injected_total": sum(injected.values()),
        "points_fired": sorted(injected),
        "points_missed": sorted(
            ALL_FAULT_POINTS - set(injected)
            - (set() if include_executor else
               {"executor.container_death", "executor.timeout"})
        ),
        "recovered": sum(rec["recovered"] for rec in episodes),
        "wedged": sum(rec["wedged"] for rec in episodes),
        "invariants": (
            "ok"
            if all(rec["invariants"] == "ok" for rec in episodes)
            else [
                {"episode": rec["episode"], "violations": rec["invariants"]}
                for rec in episodes
                if rec["invariants"] != "ok"
            ]
        ),
    }
    if push:
        from ..observability.export import push_metrics_file

        push_metrics_file("chaos")
    return report
