from .core.cli import main

raise SystemExit(main())
