"""Request-scoped distributed tracing across the serving fleet.

PR 2's tracer follows one EXECUTOR CALL (trace id == input id, spans
queue/boot/dispatch/execute). A serving request lives in a different
topology: it enters at a gateway, waits in a scheduler queue, is placed by
a router, prefills on one replica, migrates its KV pages over the MTKV1
wire, and decodes on another replica — hops owned by different threads,
different engines, and (in a real deployment) different processes. This
module is the request-side tracer over that fleet:

- a :class:`RequestTraceContext` is minted ONCE at the entry point
  (OpenAI server / router / disagg coordinator / a bare ``engine.submit``)
  and rides ON the request object — explicit propagation, not contextvars,
  because a request's spans are opened and closed from the submitting
  thread, the engine scheduler thread, and the migration thread;
- the serving trace id IS the request id (``req-…``), the same rule the
  executor tracer uses for calls (``in-…``): ``tpurun trace``/``explain``
  resolve either namespace from the same :class:`~.trace.TraceStore`;
- spans cross the disagg hop by riding the MTKV1 envelope's ``meta``
  (:func:`wire` / :func:`from_wire`): prefill-replica spans, per-chunk
  transfer spans, and decode-replica spans may land in DIFFERENT trace
  stores yet stitch into one trace id (:func:`read_trace` merges);
- span names and attribute keys are cataloged
  (:data:`~.catalog.SPAN_CATALOG`) and statically guarded, exactly like
  metric names — the schema ``tpurun explain`` parses cannot drift;
- fault firings (:mod:`...faults.inject`) and retry/backoff waits become
  span EVENTS on the affected request via the thread-ambient frame
  (:func:`active` / :func:`note_fault`), so a chaos episode exports as one
  fleet Perfetto timeline;
- sampling (``MTPU_TRACE_SAMPLE``, deterministic per request id) plus the
  ``MTPU_TRACE=0`` kill switch keep the hot path near-zero-cost when
  tracing is off: an unsampled request carries ``trace=None`` and every
  helper here is a None-safe no-op.

A context that never records a span leaves NO file behind — abandonment is
free. A context that did open spans is closed by
:func:`finish_request`, which sweeps any still-open spans with the
terminal status before recording the root: a scheduler crash, a
mid-transfer replica death, or an abort can never leak a dangling span.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import uuid

from ..utils.determinism import unit_float
from . import catalog as _C
from .trace import Span, TraceStore, default_store, tracing_enabled

#: the root span every request trace starts with (catalog-declared)
ROOT_SPAN = "request"

#: default for ``trace=`` kwargs down the submit chain: distinguishes "no
#: entry point minted yet — mint here" (UNSET) from "the entry point
#: already DECIDED and this request is untraced" (None). Without the
#: sentinel every layer would re-roll the sampling decision on a fresh id,
#: inflating the effective sample rate and splitting attribution.
UNSET = object()


def resolve_entry_trace(trace, entry: str, store=None):
    """The one rule every submit layer applies to its ``trace=`` kwarg:
    pass an upstream value through verbatim (including an explicit None —
    the upstream mint sampled the request OUT), mint only when no
    upstream entry point ran (``UNSET``)."""
    if trace is not UNSET:
        return trace
    return start_request_trace(entry=entry, store=store)

#: id-namespace prefixes: serving requests vs executor calls
REQUEST_PREFIX = "req-"
CALL_PREFIX = "in-"


def new_request_id() -> str:
    return f"{REQUEST_PREFIX}{uuid.uuid4().hex[:12]}"


def trace_kind(trace_id: str) -> str:
    """Which id namespace a trace id belongs to: ``request`` (serving,
    ``req-…``), ``call`` (executor, ``in-…``), or ``unknown``."""
    tid = str(trace_id)
    if tid.startswith(REQUEST_PREFIX):
        return "request"
    if tid.startswith(CALL_PREFIX):
        return "call"
    return "unknown"


def sample_rate() -> float:
    """``MTPU_TRACE_SAMPLE`` as a clamped fraction (default 1.0 — every
    request traced; 0 disables request tracing without touching the
    executor call tracer)."""
    raw = os.environ.get("MTPU_TRACE_SAMPLE", "")
    if not raw:
        return 1.0
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return 1.0


def sampled(request_id: str) -> bool:
    """Deterministic per-request sampling decision: hashed from the request
    id alone, so every replica/process that sees this id — including one
    that reconstructs the context :func:`from_wire` — agrees without
    coordination."""
    rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return unit_float("mtpu-trace-sample", request_id) < rate


class RequestTraceContext:
    """Identity + open-span registry for one traced request.

    The context itself is tiny: the trace id, the (still-open) root span,
    the minting store, and the set of spans currently open. Recording is
    done by the module helpers, which take the RECORDER's store — each
    replica writes its own spans to its own :class:`TraceStore`, and
    :func:`read_trace` stitches them back by trace id.
    """

    __slots__ = ("trace_id", "root", "store", "owns_root", "_lock", "_open",
                 "_done")

    def __init__(
        self,
        trace_id: str,
        root: Span,
        store: TraceStore,
        *,
        owns_root: bool = True,
    ):
        self.trace_id = trace_id
        self.root = root
        self.store = store
        #: False for wire-reconstructed contexts: the minting process owns
        #: (and records) the root span; this side only parents under it
        self.owns_root = owns_root
        self._lock = threading.Lock()
        self._open: dict[str, Span] = {}
        self._done = False

    @property
    def done(self) -> bool:
        return self._done

    def open_spans(self) -> list[str]:
        """Names of spans begun but not yet finished (test surface: the
        no-dangling-span invariant asserts this drains to [])."""
        with self._lock:
            return [sp.name for sp in self._open.values()]


def start_request_trace(
    request_id: str | None = None,
    *,
    entry: str = "api",
    store: TraceStore | None = None,
    **attrs,
) -> RequestTraceContext | None:
    """Mint the trace for one serving request at its entry point.

    Returns None when tracing is disabled (``MTPU_TRACE=0``) or the id is
    sampled out — callers thread the None through and every helper no-ops.
    When ``request_id`` is None a fresh ``req-…`` id is generated; the
    engine's ``make_request`` then ADOPTS it as the request id, so trace
    id == request id holds fleet-wide.
    """
    if not tracing_enabled():
        return None
    rid = request_id or new_request_id()
    if not sampled(rid):
        return None
    root = Span(
        trace_id=rid,
        name=ROOT_SPAN,
        attrs={"request_id": rid, "replica": entry, **attrs},
    )
    return RequestTraceContext(rid, root, store or default_store)


# --------------------------------------------------------------------------
# span helpers — all None-safe so untraced requests cost one `is None`
# --------------------------------------------------------------------------


def begin(
    ctx: RequestTraceContext | None,
    name: str,
    *,
    parent: str | None = None,
    **attrs,
) -> Span | None:
    """Open a span (recorded only when :func:`finish` closes it). The span
    registers as OPEN on the context so a crash path's sweep can close it."""
    if ctx is None:
        return None
    sp = Span(
        trace_id=ctx.trace_id,
        name=name,
        parent_id=parent or ctx.root.span_id,
        attrs=attrs,
    )
    with ctx._lock:
        # _done re-checked UNDER the lock: a span registered after the
        # terminal sweep cleared _open would dangle forever (the race is
        # real — the scheduler thread closes roots while the migration
        # thread opens spans)
        if ctx._done:
            return None
        ctx._open[sp.span_id] = sp
    return sp


def finish(
    ctx: RequestTraceContext | None,
    span: Span | None,
    status: str = "ok",
    *,
    store: TraceStore | None = None,
    **attrs,
) -> None:
    """Close + record a :func:`begin`-opened span. Idempotent: a span that
    was already closed (e.g. by the terminal sweep) is left alone, so
    failure paths may finish defensively."""
    if ctx is None or span is None:
        return
    with ctx._lock:
        if ctx._open.pop(span.span_id, None) is None:
            return
    span.finish(status, **attrs)
    (store or ctx.store).record(span)


def record_span(
    ctx: RequestTraceContext | None,
    name: str,
    *,
    start: float,
    end: float | None = None,
    status: str = "ok",
    parent: str | None = None,
    store: TraceStore | None = None,
    **attrs,
) -> Span | None:
    """Record a completed span post-hoc (wall-clock ``start``/``end``) —
    for phases whose boundaries are known only after the fact."""
    if ctx is None or ctx._done:
        return None
    sp = Span(
        trace_id=ctx.trace_id,
        name=name,
        parent_id=parent or ctx.root.span_id,
        start=start,
        attrs=attrs,
    )
    sp.end = end if end is not None else time.time()
    sp.status = status
    (store or ctx.store).record(sp)
    return sp


def event(
    ctx: RequestTraceContext | None,
    name: str,
    *,
    parent: str | None = None,
    store: TraceStore | None = None,
    **attrs,
) -> None:
    """Record an instantaneous span (start == end): fault firings, retry
    waits, sheds — the Perfetto export renders these as instant events."""
    if ctx is None or ctx._done:
        return
    now = time.time()
    record_span(
        ctx, name, start=now, end=now, parent=parent, store=store, **attrs
    )


def finish_root(
    ctx: RequestTraceContext | None,
    status: str = "ok",
    *,
    store: TraceStore | None = None,
    **attrs,
) -> None:
    """Terminal close: sweep every still-open span with ``status``, then
    finish + record the root (when this side owns it). Idempotent — the
    first terminal path wins, later ones no-op — which is what makes 'no
    dangling span, no double root' structural rather than per-call-site."""
    if ctx is None:
        return
    with ctx._lock:
        if ctx._done:
            return
        ctx._done = True
        leftovers = list(ctx._open.values())
        ctx._open.clear()
    st = store or ctx.store
    for sp in leftovers:
        sp.finish(status)
        st.record(sp)
    if ctx.owns_root:
        ctx.root.finish(status, **attrs)
        st.record(ctx.root)


def finish_request(req, reason: str, *, store: TraceStore | None = None) -> None:
    """Close a request's trace from its terminal stream marker: normal
    finishes (``stop``/``length``) close ok, everything else
    (``error``/``deadline``/…) closes with that status. Safe to call on
    untraced requests and to call twice."""
    ctx = getattr(req, "trace", None)
    if ctx is None:
        return
    status = "ok" if reason in ("stop", "length") else reason
    finish_root(
        ctx,
        status,
        store=store,
        finish_reason=reason,
        n_generated=int(getattr(req, "n_generated", 0) or 0),
    )


# --------------------------------------------------------------------------
# the disagg hop: trace context on the MTKV1 wire
# --------------------------------------------------------------------------


def wire(
    ctx: RequestTraceContext | None, *, parent: str | None = None
) -> dict | None:
    """The trace context as a JSON-safe dict for the MTKV1 envelope's
    ``meta`` — what a cross-process decode replica needs to keep stitching:
    the trace id and the span to parent under."""
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "parent_id": parent or ctx.root.span_id}


def from_wire(
    d: dict | None, *, store: TraceStore | None = None
) -> RequestTraceContext | None:
    """Reconstruct a context from :func:`wire` on the receiving replica.
    The reconstructed side does NOT own the root (the minting process
    records it); its spans parent under the wire's ``parent_id`` and land
    in ITS store — :func:`read_trace` merges the stores back into one
    tree."""
    if not d or not tracing_enabled():
        return None
    tid = str(d.get("trace_id") or "")
    # the wire is untrusted input (a peer process): the trace id becomes a
    # FILENAME under the store root, so it must look like a request id —
    # the same whitelist the read side applies (TraceStore.resolve)
    if not tid.startswith(REQUEST_PREFIX) or not TraceStore._ID_TOKEN_RE.match(
        tid
    ):
        return None
    root = Span(trace_id=tid, name=ROOT_SPAN)
    if d.get("parent_id"):
        root.span_id = d["parent_id"]
    return RequestTraceContext(
        tid, root, store or default_store, owns_root=False
    )


# --------------------------------------------------------------------------
# thread-ambient frame: fault firings / retry waits attach to the request
# whose operation is running on this thread
# --------------------------------------------------------------------------

_tl = threading.local()


@contextlib.contextmanager
def active(
    ctx: RequestTraceContext | None,
    *,
    parent: str | None = None,
    replica: str | None = None,
):
    """Scope ``ctx`` as this THREAD's ambient request: code that has no
    request in hand (the fault gate, the transfer loop) records events
    through :func:`note_fault` / :func:`ambient_event` onto whatever
    request the thread is currently working for. ``ctx=None`` scopes an
    EMPTY frame — an unsampled request must not inherit an outer one."""
    prev = getattr(_tl, "frame", None)
    _tl.frame = (ctx, parent, replica) if ctx is not None else None
    try:
        yield
    finally:
        _tl.frame = prev


def _frame():
    return getattr(_tl, "frame", None)


def current() -> RequestTraceContext | None:
    fr = _frame()
    return fr[0] if fr is not None else None


def begin_ambient(name: str, **attrs) -> Span | None:
    fr = _frame()
    if fr is None:
        return None
    ctx, parent, replica = fr
    if replica is not None:
        attrs.setdefault("replica", replica)
    return begin(ctx, name, parent=parent, **attrs)


def finish_ambient(span: Span | None, status: str = "ok", **attrs) -> None:
    fr = _frame()
    if fr is None or span is None:
        return
    finish(fr[0], span, status, **attrs)


def ambient_event(name: str, **attrs) -> None:
    fr = _frame()
    if fr is None:
        return
    ctx, parent, replica = fr
    if replica is not None:
        attrs.setdefault("replica", replica)
    event(ctx, name, parent=parent, **attrs)


def note_fault(point: str) -> None:
    """Called by :func:`...faults.inject.fire` ONLY when a fault actually
    fires (the disabled gate never reaches here): the firing becomes a
    ``fault`` event on the ambient request's trace, so a chaos episode's
    injections are visible per-request on the fleet timeline."""
    fr = _frame()
    if fr is None:
        return
    ctx, parent, replica = fr
    kw = {"replica": replica} if replica is not None else {}
    event(ctx, "fault", parent=parent, point=point, **kw)


# --------------------------------------------------------------------------
# multi-store reads: one trace id, N replica stores
# --------------------------------------------------------------------------

_MAX_EXTRA_STORES = 16
_extra_stores: list[TraceStore] = []
_extra_lock = threading.Lock()


def register_store(store: TraceStore | None) -> None:
    """Make a per-replica store visible to merged reads in THIS process
    (the gateway's ``/traces/<id>`` and ``tpurun explain`` run over every
    registered store plus the default). Bounded; duplicates ignored."""
    if store is None or store is default_store:
        return
    with _extra_lock:
        if any(s is store for s in _extra_stores):
            return
        _extra_stores.append(store)
        del _extra_stores[:-_MAX_EXTRA_STORES]


def known_stores() -> list[TraceStore]:
    with _extra_lock:
        return [default_store, *_extra_stores]


def read_trace(
    trace_id: str, stores: list[TraceStore] | None = None
) -> list[dict]:
    """One trace id's spans merged across stores (deduped by span id,
    sorted by start) — prefill-replica, transfer, and decode-replica spans
    stitch back into the single tree the trace id names."""
    seen: set = set()
    out: list[dict] = []
    for st in stores if stores is not None else known_stores():
        for s in st.read(trace_id):
            sid = s.get("span_id")
            if sid in seen:
                continue
            seen.add(sid)
            out.append(s)
    out.sort(key=lambda s: (s.get("start") or 0.0))
    return out


def list_traces(
    limit: int = 50, stores: list[TraceStore] | None = None
) -> list[str]:
    """Most recently active trace ids merged across stores (newest first,
    deduped) — the index view matching what :func:`read_trace` can serve:
    a request whose spans live only in a per-replica store must still
    appear in the gateway's ``/traces`` listing."""
    entries: list[tuple[float, str]] = []
    for st in stores if stores is not None else known_stores():
        try:
            for p in st.root.glob("*.jsonl"):
                entries.append((p.stat().st_mtime, p.stem))
        except OSError:
            continue
    entries.sort(reverse=True)
    seen: set = set()
    out: list[str] = []
    for _, tid in entries:
        if tid in seen:
            continue
        seen.add(tid)
        out.append(tid)
        if len(out) >= limit:
            break
    return out


def resolve(
    token: str, stores: list[TraceStore] | None = None
) -> str | None:
    """Resolve a full or unique-prefix trace id across stores — either id
    namespace (``in-…`` executor calls, ``req-…`` serving requests)."""
    for st in stores if stores is not None else known_stores():
        hit = st.resolve(token)
        if hit is not None:
            return hit
    return None


# --------------------------------------------------------------------------
# `tpurun explain`: merged span tree -> lifecycle narrative
# --------------------------------------------------------------------------


def _ms(x: float) -> float:
    return (x or 0.0) * 1000.0


def _dur_ms(s: dict) -> float:
    start = s.get("start") or 0.0
    return _ms(max(0.0, (s.get("end") or start) - start))


def explain_lines(spans: list[dict], trace_id: str) -> list[str]:
    """Render a merged request trace as a human-readable lifecycle
    narrative (``tpurun explain``); executor call traces get a one-line
    summary pointing at the phase-tree renderer instead."""
    if not spans:
        return [f"no spans recorded for {trace_id}"]
    kind = trace_kind(trace_id)
    by_name: dict[str, list[dict]] = {}
    for s in spans:
        by_name.setdefault(s.get("name", "?"), []).append(s)
    t0 = min(s.get("start") or 0.0 for s in spans)

    def attr(s, key, default="-"):
        return (s.get("attrs") or {}).get(key, default)

    if kind == "call" or (
        # no request root and the span names look like the executor
        # tracer's (catalog.CALL_SPAN_NAMES): an unprefixed/legacy id
        # still renders as a call trace ("queue" exists in both
        # namespaces, so a req-… id never takes this branch)
        kind != "request"
        and ROOT_SPAN not in by_name
        and set(by_name) & _C.CALL_SPAN_NAMES
    ):
        lines = [
            f"{trace_id}: executor call trace ({len(spans)} spans) — "
            f"`tpurun trace {trace_id}` renders the phase tree"
        ]
        for s in sorted(spans, key=lambda s: s.get("start") or 0.0):
            mark = "" if s.get("status") == "ok" else f" [{s.get('status')}]"
            lines.append(
                f"  +{_ms((s.get('start') or 0.0) - t0):>8.1f}ms  "
                f"{s.get('name', '?'):<12} {_dur_ms(s):>9.1f}ms{mark}"
            )
        return lines

    root = (by_name.get(ROOT_SPAN) or [None])[0]
    rattrs = (root or {}).get("attrs") or {}
    reason = rattrs.get("finish_reason", "?")
    header = f"request {trace_id}: serving request trace"
    if root is not None:
        header += (
            f" — {reason} in {_dur_ms(root):.1f}ms"
            f" (entry {rattrs.get('replica', '?')}"
        )
        if "priority" in rattrs:
            header += f", class={rattrs['priority']}"
        if "tenant" in rattrs:
            header += f", tenant={rattrs['tenant']}"
        header += ")"
    lines = [header]

    chunks = by_name.get("chunk", [])
    spec_events = by_name.get("spec_verify", [])
    for s in sorted(spans, key=lambda s: (s.get("start") or 0.0)):
        name = s.get("name", "?")
        if name in (ROOT_SPAN, "chunk", "spec_verify"):
            continue
        if name == "queue":
            text = (
                f"queued {_dur_ms(s):.1f}ms "
                f"(class={attr(s, 'priority')}, replica {attr(s, 'replica')})"
            )
        elif name == "placement":
            pre = attr(s, "prefill_replica")
            if pre != "-":
                text = (
                    f"placed: prefill={pre} "
                    f"decode={attr(s, 'decode_replica')}"
                )
            else:
                text = (
                    f"placed on {attr(s, 'decode_replica', attr(s, 'replica'))}"
                    f" (route={attr(s, 'route')})"
                )
        elif name == "prefill":
            chunked = attr(s, "chunked", False) is True
            sliced = attr(s, "sliced", False) is True
            detail = ""
            if chunked:
                detail = f", chunked x{attr(s, 'chunks', '?')}"
                if sliced:
                    detail += f" sliced (budget {attr(s, 'budget', '?')})"
            text = (
                f"prefill on {attr(s, 'replica')} {_dur_ms(s):.1f}ms "
                f"({attr(s, 'n_prompt', '?')} prompt tokens{detail})"
            )
        elif name == "prefill_wait":
            text = (
                f"prefill sliced over {attr(s, 'ticks', '?')} ticks "
                f"({attr(s, 'chunks', '?')} chunks interleaved with decode, "
                f"{_dur_ms(s):.1f}ms residency)"
            )
        elif name == "migrate":
            text = (
                f"migrated {attr(s, 'pages', '?')} pages "
                f"{attr(s, 'source')} -> {attr(s, 'target')} "
                f"{_dur_ms(s):.1f}ms ({attr(s, 'result', s.get('status'))})"
            )
        elif name == "transfer":
            n_chunks = attr(s, "chunks", None) or len(
                [c for c in chunks if c.get("parent_id") == s.get("span_id")]
            )
            text = (
                f"transfer {attr(s, 'wire_bytes', '?')} bytes in "
                f"{n_chunks} chunks {_dur_ms(s):.1f}ms"
            )
        elif name == "adopt":
            text = (
                f"adopted {attr(s, 'pages', '?')} pages on "
                f"{attr(s, 'replica')} {_dur_ms(s):.2f}ms"
            )
        elif name == "decode":
            ttft = rattrs.get("ttft_s")
            text = f"decode on {attr(s, 'replica')} {_dur_ms(s):.1f}ms"
            if ttft is not None:
                text += f": TTFT {_ms(ttft):.1f}ms"
            text += (
                f", {rattrs.get('n_generated', '?')} tokens, finish={reason}"
            )
        elif name == "fault":
            text = (
                f"fault injected: {attr(s, 'point')} "
                f"(replica {attr(s, 'replica')})"
            )
        elif name == "retry_wait":
            text = (
                f"transfer retry round {attr(s, 'round')}: "
                f"{attr(s, 'pending')} chunks pending, "
                f"{attr(s, 'delay_s')}s backoff"
            )
        elif name == "shed":
            text = f"shed by admission ({attr(s, 'reason')})"
        elif name == "tier_promote":
            text = (
                f"prefix tier promote: {attr(s, 'pages')} pages from "
                f"{attr(s, 'tier')}"
            )
        else:
            extras = " ".join(
                f"{k}={v}" for k, v in (s.get("attrs") or {}).items()
            )
            text = f"{name} {_dur_ms(s):.1f}ms {extras}".rstrip()
        mark = "" if s.get("status") in ("ok", None) else f" [{s.get('status')}]"
        lines.append(
            f"  +{_ms((s.get('start') or 0.0) - t0):>8.1f}ms  {text}{mark}"
        )
    if spec_events:
        proposed = sum(int(attr(s, "proposed", 0) or 0) for s in spec_events)
        accepted = sum(int(attr(s, "accepted", 0) or 0) for s in spec_events)
        lines.append(
            f"  spec verify: {len(spec_events)} ticks, "
            f"{accepted}/{proposed} draft tokens accepted"
        )
    return lines


#: catalog cross-check convenience (the static guard imports the catalog
#: directly; this keeps the two modules' views trivially identical)
ALL_SPAN_NAMES = _C.ALL_SPAN_NAMES
