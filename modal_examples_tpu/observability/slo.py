"""SLO evaluator: declared latency/error targets vs the live histograms.

PR 2 gave the framework real latency distributions; this layer turns them
into a pass/fail answer. An :class:`SLO` declares a target over one catalog
series — a quantile bound on a histogram (``p95 TTFT <= 2 s``) or a ratio
bound between two counters (``scheduler errors / decode steps <= 1%``) —
and :func:`evaluate` compares each against the registry, computing a **burn
rate** (observed / target; > 1.0 means the target is being violated, 0.5
means half the budget is consumed). Surfaced three ways:

- gateway ``GET /healthz`` returns ``{"status": ok|degraded, "slos": [...]}``
  (degraded = any SLO violating with data present);
- ``tpurun top`` renders the same reports from pushed metrics;
- each evaluation writes ``mtpu_slo_burn_rate{slo=...}`` back into the
  registry so burn rates are themselves scrapeable.

Targets are overridable per-process via ``MTPU_SLO_<NAME>`` env vars (e.g.
``MTPU_SLO_TTFT_P95_S=0.5``); a series with no observations reports
``observed=None`` and passes (no data is not an outage).
"""

from __future__ import annotations

import dataclasses
import os

from ..utils.prometheus import Registry, default_registry
from . import catalog as C


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declared objective.

    ``kind="latency"``: ``quantile`` of histogram ``series`` must stay
    <= ``target`` (seconds). ``kind="ratio"``: ``total(series)`` over
    ``total(denom_series)`` must stay <= ``target`` (a fraction).
    ``aggregate`` sums the histogram across label sets containing the given
    items ({} = all of them) before taking the quantile.
    """

    name: str
    series: str
    target: float
    kind: str = "latency"  # "latency" | "ratio"
    quantile: float = 0.95
    aggregate: dict | None = dataclasses.field(default_factory=dict)
    denom_series: str | None = None
    #: label filter applied to the denominator sum (ratio kind) — e.g.
    #: {"phase": "total"} so a per-phase histogram counts calls, not phases
    denom_match: dict | None = None
    env: str | None = None  # override env var name

    def resolved_target(self) -> float:
        if self.env:
            raw = os.environ.get(self.env, "")
            if raw:
                try:
                    return float(raw)
                except ValueError:
                    pass
        return self.target


#: default objectives: serving TTFT, end-to-end call latency, engine error
#: budget, and call retry budget — the ROADMAP's "fast as the hardware
#: allows" scorecard
DEFAULT_SLOS: tuple[SLO, ...] = (
    SLO(
        name="ttft_p95",
        series=C.TTFT_SECONDS,
        target=2.0,
        env="MTPU_SLO_TTFT_P95_S",
    ),
    SLO(
        name="tpot_p95",
        series=C.TPOT_SECONDS,
        target=0.25,
        env="MTPU_SLO_TPOT_P95_S",
    ),
    SLO(
        name="call_total_p95",
        series=C.CALL_DURATION_SECONDS,
        target=30.0,
        aggregate={"phase": "total"},
        env="MTPU_SLO_CALL_P95_S",
    ),
    SLO(
        name="scheduler_error_rate",
        series=C.SCHEDULER_ERRORS_TOTAL,
        denom_series=C.DECODE_STEPS_TOTAL,
        target=0.01,
        kind="ratio",
        env="MTPU_SLO_ERROR_RATE",
    ),
    SLO(
        name="call_retry_rate",
        series=C.RETRIES_TOTAL,
        denom_series=C.CALL_DURATION_SECONDS,
        # phase=total only: the histogram holds ~6 phase observations per
        # call, and dividing by all of them would dilute the rate ~6x
        denom_match={"phase": "total"},
        target=0.2,
        kind="ratio",
        env="MTPU_SLO_RETRY_RATE",
    ),
    SLO(
        # scheduling (PR 4): deadline-armed requests that blew their budget
        # (queued-cancelled + inflight-aborted) over admitted load — the
        # scheduler's own SLO: shedding and priority exist to keep this low
        name="deadline_miss_rate",
        series=C.DEADLINE_MISSES_TOTAL,
        denom_series=C.REQUESTS_ADMITTED_TOTAL,
        target=0.05,
        kind="ratio",
        env="MTPU_SLO_DEADLINE_MISS_RATE",
    ),
)


def evaluate(
    registry: Registry | None = None,
    slos: tuple[SLO, ...] | None = None,
    *,
    burn_rate_registry: Registry | None = None,
) -> list[dict]:
    """Evaluate each SLO against ``registry``; returns one report dict per
    SLO: ``{"name", "kind", "target", "observed", "ok", "burn_rate"}``.

    ``burn_rate_registry`` (default: the evaluated registry) receives the
    ``mtpu_slo_burn_rate`` gauge writes — pass ``None``-able here matters
    when evaluating a *parsed* registry (tpurun top) where writing back
    would be pointless.
    """
    reg = registry if registry is not None else default_registry
    sink = burn_rate_registry if burn_rate_registry is not None else reg
    reports = []
    for slo in slos or DEFAULT_SLOS:
        target = slo.resolved_target()
        observed: float | None
        if slo.kind == "ratio":
            num = reg.total(slo.series)
            den = (
                reg.total(slo.denom_series, slo.denom_match)
                if slo.denom_series
                else 0.0
            )
            observed = (num / den) if den > 0 else None
        else:
            q = reg.histogram_quantiles(
                slo.series,
                quantiles=(slo.quantile,),
                aggregate=slo.aggregate,
            )
            observed = (
                q[f"p{int(slo.quantile * 100)}"] if q is not None else None
            )
        burn = (
            observed / target if (observed is not None and target > 0) else None
        )
        ok = burn is None or burn <= 1.0
        reports.append(
            {
                "name": slo.name,
                "kind": slo.kind,
                "target": target,
                "observed": observed,
                "ok": ok,
                "burn_rate": round(burn, 4) if burn is not None else None,
            }
        )
        if burn is not None:
            sink.gauge_set(
                C.SLO_BURN_RATE,
                burn,
                labels={"slo": slo.name},
                help=C.CATALOG[C.SLO_BURN_RATE]["help"],
            )
    return reports


def healthz(registry: Registry | None = None) -> dict:
    """The gateway ``/healthz`` payload: overall status + per-SLO reports.
    ``degraded`` only when an SLO with actual observations is violating.

    With no explicit ``registry``, evaluation runs over this process's live
    registry MERGED with every pushed job file (the same view ``/metrics``
    serves) — in the deployed shape the serving engine's TTFT/TPOT
    histograms live in a container process and arrive via the pushgateway,
    and a health check blind to them would report "ok" forever. Burn-rate
    gauges still land in the live default registry.
    """
    if registry is None:
        from ..utils.prometheus import parse_exposition
        from .export import live_and_pushed_metrics

        merged = parse_exposition(live_and_pushed_metrics())
        reports = evaluate(merged, burn_rate_registry=default_registry)
    else:
        reports = evaluate(registry)
    status = "ok" if all(r["ok"] for r in reports) else "degraded"
    return {"status": status, "slos": reports}
