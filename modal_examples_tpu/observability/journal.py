"""Autoscaler decision journal: every scale-up / scale-down / kill the
executor's ``_autoscale`` loop takes, with its rationale.

The autoscaler used to be a black box: a pool would boot three containers or
reap a warm one and the only evidence was the container count moving. Every
decision now appends a structured record — trigger, queue depth, inflight
count, idle ages, pool size before/after — to a bounded in-memory ring
buffer AND a JSONL file under ``<state_dir>/scaler.jsonl``, so both a live
gateway (``GET /autoscaler``) and a later CLI process (``tpurun scaler``)
can answer "why did the pool scale?".

Records are plain dicts (one JSON object per line, same greppable shape as
trace files). The file is bounded: when it grows past ``_MAX_FILE_RECORDS``
lines it is atomically rewritten keeping the newest half.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path

from .._internal import config as _config

#: in-memory ring-buffer capacity (per journal instance)
RING_CAPACITY = 512
#: JSONL file bound: rewrite keeping the newest half past this many lines
_MAX_FILE_RECORDS = 4096


def make_record(
    *,
    function: str,
    action: str,
    trigger: str,
    queue_depth: int = 0,
    inflight: int = 0,
    free_slots: int = 0,
    containers_before: int = 0,
    containers_after: int = 0,
    idle_ages: list[float] | None = None,
    **extra,
) -> dict:
    """One journal record. ``action`` is what the autoscaler did
    (``scale_up`` | ``scale_down`` | ``kill``), ``trigger`` why
    (``queue_pressure`` | ``min_containers`` | ``idle`` | ``single_use_spent``
    | ``timeout``)."""
    rec = {
        "at": time.time(),
        "function": function,
        "action": action,
        "trigger": trigger,
        "queue_depth": queue_depth,
        "inflight": inflight,
        "free_slots": free_slots,
        "containers_before": containers_before,
        "containers_after": containers_after,
    }
    if idle_ages:
        rec["idle_ages_s"] = [round(a, 3) for a in idle_ages]
    rec.update(extra)
    return rec


class DecisionJournal:
    """Ring buffer + JSONL sink for autoscaler decisions."""

    def __init__(self, path: str | Path | None = None):
        self._path = Path(path) if path else None
        self._resolved: Path | None = None
        self._ring: deque[dict] = deque(maxlen=RING_CAPACITY)
        self._lock = threading.Lock()
        self._appended = 0

    @property
    def path(self) -> Path:
        if self._resolved is None:
            p = self._path or (_config.state_dir() / "scaler.jsonl")
            p.parent.mkdir(parents=True, exist_ok=True)
            self._resolved = p
        return self._resolved

    def record(self, rec: dict) -> None:
        """Append one record (never raises — the journal runs inside the
        scheduler tick)."""
        line = json.dumps(rec)
        with self._lock:
            self._ring.append(rec)
            try:
                with open(self.path, "a") as f:
                    f.write(line + "\n")
                self._appended += 1
                if self._appended >= 256:
                    self._appended = 0
                    self._compact_locked()
            except OSError:
                pass

    def _compact_locked(self) -> None:
        """Bound the JSONL file: keep the newest half once past the cap."""
        try:
            lines = self.path.read_text().splitlines()
        except OSError:
            return
        if len(lines) <= _MAX_FILE_RECORDS:
            return
        keep = lines[-_MAX_FILE_RECORDS // 2 :]
        tmp = self.path.with_suffix(f".tmp.{os.getpid()}")
        try:
            tmp.write_text("\n".join(keep) + "\n")
            os.replace(tmp, self.path)
        except OSError:
            pass

    def tail(
        self, n: int = 50, *, function: str | None = None
    ) -> list[dict]:
        """Newest-last slice of the journal. The JSONL file is the superset
        (every record lands in both ring and file), so it is the primary
        source — the 512-entry ring alone would silently drop a function's
        older decisions once busier pools evict them. The ring covers the
        case where file writes are failing (read-only state dir)."""
        recs = self._read_file()
        with self._lock:
            ring = list(self._ring)
        if len(recs) < len(ring):
            recs = ring  # file writes failing: the ring is all we have
        if function is not None:
            recs = [r for r in recs if r.get("function") == function]
        return recs[-n:]

    def _read_file(self) -> list[dict]:
        try:
            text = self.path.read_text()
        except OSError:
            return []
        out = []
        for line in text.splitlines():
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return out


#: process-wide default journal (state-dir backed)
default_journal = DecisionJournal()
