"""THE bounded-JSONL decision journal every subsystem writes through.

Grown from the autoscaler's decision journal (PR 3), this is now the one
append-only record sink for every "why did the system do that?" surface:
autoscaler decisions, fleet scale events, watchdog ladder actions, chaos
episodes, compile-ledger events, and alert fire/clear transitions each get
a named JSONL file under ``<state_dir>`` — the :data:`JOURNALS` table owns
the name -> filename mapping, and :func:`named_journal` is the ONLY way a
writer or reader resolves one (``tests/test_static.py`` bans direct
:class:`DecisionJournal` construction outside this module, so the file
names can't drift call-site by call-site the way metric names used to).

Records are plain dicts (one JSON object per line, same greppable shape as
trace files), buffered in a bounded in-memory ring AND appended to the
file, so both a live gateway route and a later CLI process can read them.
The file is bounded: when it grows past ``_MAX_FILE_RECORDS`` lines it is
atomically rewritten keeping the newest half.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path

from .._internal import config as _config

#: in-memory ring-buffer capacity (per journal instance)
RING_CAPACITY = 512
#: JSONL file bound: rewrite keeping the newest half past this many lines
_MAX_FILE_RECORDS = 4096

#: every journal the framework writes: name -> file under ``<state_dir>``.
#: One table, like the metric catalog — writers AND readers (CLI, gateway,
#: incident bundles) resolve through :func:`named_journal`, never a
#: hand-built path.
JOURNALS: dict[str, str] = {
    "scaler": "scaler.jsonl",      # executor autoscaler (core/executor.py)
    "fleet": "fleet.jsonl",        # fleet autoscaler (fleet/autoscaler.py)
    "watchdog": "watchdog.jsonl",  # gray-failure ladder (serving/health.py)
    "chaos": "chaos.jsonl",        # chaos episodes (faults/chaos.py)
    "compiles": "compiles.jsonl",  # compile ledger (observability/profiler.py)
    "alerts": "alerts.jsonl",      # alert fire/clear (observability/alerts.py)
    # shared prefix store: lease takeovers + GC sweeps (serving/prefix_store/)
    "prefix_store": "prefix_store.jsonl",
    # per-request usage records (observability/usage.py)
    "usage": "usage.jsonl",
    # golden-set probe results (observability/canary.py)
    "canary": "canary.jsonl",
}


def journal_path(name: str, root=None) -> Path:
    """The JSONL path for a named journal — ``<root or state_dir>/<file>``.
    ``name`` must be a :data:`JOURNALS` key (typos fail loudly, not as a
    silently empty journal)."""
    return Path(root or _config.state_dir()) / JOURNALS[name]


def named_journal(name: str, root=None, *, path=None) -> "DecisionJournal":
    """Resolve a named journal. ``path`` (an explicit file, e.g. a test's
    tmp file or a bench run's local ledger) wins over ``root`` (an
    alternate state dir, the CLI's ``--dir``); with neither, the state
    dir resolves LAZILY at first use, so a module-level journal built at
    import time still honors a later ``MTPU_STATE_DIR``."""
    if name not in JOURNALS:
        raise KeyError(
            f"unknown journal {name!r}; one of {sorted(JOURNALS)}"
        )
    if path is not None:
        return DecisionJournal(path)
    if root is not None:
        return DecisionJournal(journal_path(name, root))
    return DecisionJournal(name=name)


def make_record(
    *,
    function: str,
    action: str,
    trigger: str,
    queue_depth: int = 0,
    inflight: int = 0,
    free_slots: int = 0,
    containers_before: int = 0,
    containers_after: int = 0,
    idle_ages: list[float] | None = None,
    **extra,
) -> dict:
    """One journal record. ``action`` is what the autoscaler did
    (``scale_up`` | ``scale_down`` | ``kill``), ``trigger`` why
    (``queue_pressure`` | ``min_containers`` | ``idle`` | ``single_use_spent``
    | ``timeout``)."""
    rec = {
        "at": time.time(),
        "function": function,
        "action": action,
        "trigger": trigger,
        "queue_depth": queue_depth,
        "inflight": inflight,
        "free_slots": free_slots,
        "containers_before": containers_before,
        "containers_after": containers_after,
    }
    if idle_ages:
        rec["idle_ages_s"] = [round(a, 3) for a in idle_ages]
    rec.update(extra)
    return rec


class DecisionJournal:
    """Ring buffer + JSONL sink for one named journal's records.

    Build instances through :func:`named_journal` — direct construction
    outside this module is banned by ``tests/test_static.py`` (the file
    names live in :data:`JOURNALS`, nowhere else)."""

    def __init__(self, path: str | Path | None = None, *, name: str = "scaler"):
        self._path = Path(path) if path else None
        self._name = name
        self._resolved: Path | None = None
        self._ring: deque[dict] = deque(maxlen=RING_CAPACITY)
        self._lock = threading.Lock()
        self._appended = 0

    @property
    def path(self) -> Path:
        if self._resolved is None:
            p = self._path or journal_path(self._name)
            p.parent.mkdir(parents=True, exist_ok=True)
            self._resolved = p
        return self._resolved

    def record(self, rec: dict) -> None:
        """Append one record (never raises — the journal runs inside the
        scheduler tick)."""
        line = json.dumps(rec)
        with self._lock:
            self._ring.append(rec)
            try:
                with open(self.path, "a") as f:
                    f.write(line + "\n")
                self._appended += 1
                if self._appended >= 256:
                    self._appended = 0
                    self._compact_locked()
            except OSError:
                pass

    def _compact_locked(self) -> None:
        """Bound the JSONL file: keep the newest half once past the cap."""
        try:
            lines = self.path.read_text().splitlines()
        except OSError:
            return
        if len(lines) <= _MAX_FILE_RECORDS:
            return
        keep = lines[-_MAX_FILE_RECORDS // 2 :]
        tmp = self.path.with_suffix(f".tmp.{os.getpid()}")
        try:
            tmp.write_text("\n".join(keep) + "\n")
            os.replace(tmp, self.path)
        except OSError:
            pass

    def tail(
        self, n: int = 50, *, function: str | None = None
    ) -> list[dict]:
        """Newest-last slice of the journal. The JSONL file is the superset
        (every record lands in both ring and file), so it is the primary
        source — the 512-entry ring alone would silently drop a function's
        older decisions once busier pools evict them. The ring covers the
        case where file writes are failing (read-only state dir)."""
        recs = self._read_file()
        with self._lock:
            ring = list(self._ring)
        if len(recs) < len(ring):
            recs = ring  # file writes failing: the ring is all we have
        if function is not None:
            recs = [r for r in recs if r.get("function") == function]
        return recs[-n:]

    def _read_file(self) -> list[dict]:
        try:
            text = self.path.read_text()
        except OSError:
            return []
        out = []
        for line in text.splitlines():
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return out


#: process-wide default journal (state-dir backed): the executor
#: autoscaler's sink, read back by ``tpurun scaler`` / ``/autoscaler``
default_journal = named_journal("scaler")
