"""Declarative alert rules evaluated over tsdb windows
(docs/observability.md#alert-rules).

The SLO layer answers "is the target violated *right now*"; dashboards
answer "what does the operator see when they look". Neither pages anyone,
and neither captures the evidence. An :class:`AlertRule` is a declarative
condition over a :mod:`.timeseries` window — threshold (a sustained level),
rate (a burn-window: per-second increase of a counter), or absence (a
counter that stopped moving while a guard series says there is work) —
with fire/clear hysteresis, so a single noisy scrape cannot flap a page.

Discipline, same as every other schema in the package: every series an
:class:`AlertRule` references must be declared in :mod:`.catalog`
(``tests/test_static.py`` closes the loop), transitions emit the cataloged
``mtpu_alerts_active{rule}`` / ``mtpu_alerts_fired_total{rule}`` series,
and every fire/clear appends to the ``alerts`` journal
(:func:`~.journal.named_journal`) so ``tpurun alerts`` and the gateway's
``/alerts`` can replay the history after the process is gone. A rule with
``capture=True`` snapshots an incident bundle (:mod:`.incident`) at the
fire transition — the alert IS the trigger that preserves its own
evidence.

The evaluator normally rides the :class:`~.timeseries.TsdbSampler` (one
scrape, one evaluation, no second thread); tests drive
:meth:`AlertEvaluator.evaluate_once` directly with a fake clock and a
hand-built record window.
"""

from __future__ import annotations

import dataclasses
import time

from . import catalog as C
from . import metrics as _obs
from . import timeseries as _ts
from .journal import named_journal

#: rule kinds (the evaluation semantics, below)
KINDS = ("threshold", "rate", "absence")


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative alert.

    - ``kind="threshold"`` — the newest point inside ``window_s`` satisfies
      ``value <op> threshold``; the evaluator's state machine then holds
      the condition for ``for_s`` before firing (``for_s=0`` fires on the
      first true evaluation).
    - ``kind="rate"`` — the per-second increase of the series over
      ``window_s`` (:func:`~.timeseries.rate`; ``field="sum"`` reads a
      histogram's cumulative seconds) satisfies ``<op> threshold``.
    - ``kind="absence"`` — the series did not increase over ``window_s``
      while the guard condition held at the newest scrape (absence of
      progress only means anything against outstanding work — the
      watchdog's idle-is-healthy rule).

    Clearing is hysteretic: the condition must stay false for ``clear_s``
    before a firing rule clears.
    """

    name: str
    series: str
    kind: str = "threshold"
    op: str = ">="  # ">=" | "<="
    threshold: float = 1.0
    labels: dict | None = None
    #: fold across matching label sets: "max" for 0..1 gauges (a fraction
    #: must never sum across replicas), "sum" for counters/counts
    agg: str = "max"
    field: str = "value"  # "value" | "sum" (histogram cumulative seconds)
    window_s: float = 60.0
    for_s: float = 0.0
    clear_s: float = 0.0
    #: absence-kind guard: only alert while guard_series (latest point,
    #: same agg rules) is > guard_threshold
    guard_series: str | None = None
    guard_labels: dict | None = None
    guard_threshold: float = 0.0
    #: capture an incident bundle at the fire transition (opt-in per rule)
    capture: bool = False
    description: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown alert kind {self.kind!r}; one of {KINDS}")
        if self.op not in (">=", "<="):
            raise ValueError(f"unknown alert op {self.op!r}; one of >=, <=")


def rule_series(rule: AlertRule) -> tuple[str, ...]:
    """Every catalog series the rule reads — the static guard's closure
    surface (``tests/test_static.py``)."""
    out = [rule.series]
    if rule.guard_series:
        out.append(rule.guard_series)
    return tuple(out)


#: the starter rule set: SLO burn, host-overhead regression, decode-stall
#: burn, a wedged replica, KV-page pressure, and absence-of-token-progress.
#: Thresholds are deliberately conservative — a rule that cries wolf
#: teaches operators to ignore the recorder.
DEFAULT_RULES: tuple[AlertRule, ...] = (
    AlertRule(
        name="slo_burn",
        series=C.SLO_BURN_RATE,
        threshold=1.0,
        for_s=10.0,
        clear_s=10.0,
        description="any declared SLO burning above 1.0 sustained",
    ),
    AlertRule(
        name="host_overhead",
        series=C.HOST_OVERHEAD_RATIO,
        threshold=0.97,
        for_s=30.0,
        clear_s=15.0,
        description="scheduler ticks ~entirely host-bound (device starved)",
    ),
    AlertRule(
        name="decode_stall_burn",
        series=C.DECODE_STALL_SECONDS,
        kind="rate",
        field="sum",
        agg="sum",
        threshold=0.5,
        window_s=30.0,
        clear_s=15.0,
        description="decode dispatch gaps burning >0.5 stall-seconds/s",
    ),
    AlertRule(
        name="replica_wedged",
        series=C.WATCHDOG_REPLICA_STATE,
        labels={"state": "wedged"},
        threshold=1.0,
        clear_s=5.0,
        # the watchdog's own ladder already captures the wedge bundle;
        # a second capture here would only duplicate it
        description="a replica classified wedged by the progress watchdog",
    ),
    AlertRule(
        name="kv_pressure",
        series=C.KV_PAGE_OCCUPANCY,
        threshold=0.98,
        for_s=10.0,
        clear_s=10.0,
        description="KV page pool ~exhausted sustained (sheds imminent)",
    ),
    AlertRule(
        name="mbu_collapse",
        series=C.HBM_BW_UTIL,
        labels={"phase": "decode"},
        op="<=",
        threshold=0.01,
        for_s=20.0,
        clear_s=10.0,
        guard_series=C.ACTIVE_SLOTS,
        guard_threshold=0.0,
        description=(
            "decode bandwidth utilization collapsed while decodable slots "
            "exist — the wedge precursor (work admitted, HBM idle)"
        ),
    ),
    AlertRule(
        name="spec_acceptance_collapse",
        series=C.SPEC_ACCEPTANCE_RATE,
        op="<=",
        threshold=0.3,
        for_s=20.0,
        clear_s=10.0,
        # guard on dispatched depth: acceptance is only meaningful while
        # the engine is actually speculating — once the adaptive controller
        # drives gamma to 0 the rate freezes and must not keep paging
        guard_series=C.SPEC_GAMMA,
        guard_threshold=0.0,
        description=(
            "draft acceptance collapsed while speculation is still being "
            "dispatched — the draft stopped predicting the target "
            "(docs/speculative.md#gamma-schedule); expect the adaptive "
            "controller to drive gamma down, else spec is a latency tax"
        ),
    ),
    AlertRule(
        name="no_token_progress",
        series=C.GENERATED_TOKENS_TOTAL,
        kind="absence",
        agg="sum",
        window_s=30.0,
        clear_s=5.0,
        guard_series=C.ACTIVE_SLOTS,
        guard_threshold=0.0,
        capture=True,
        description="active slots but zero tokens generated over the window",
    ),
    AlertRule(
        name="canary_drift",
        series=C.CANARY_DRIFT_TOTAL,
        kind="rate",
        agg="sum",
        threshold=0.001,
        window_s=60.0,
        clear_s=30.0,
        # the prober already captures a canary_drift incident per drifted
        # probe (with the mismatching request id in the reason); capturing
        # here too would duplicate the bundle
        description=(
            "a replica's golden-set probe diverged bit-exact from its "
            "golden transcript (numeric drift sentinel)"
        ),
    ),
    AlertRule(
        name="canary_latency_burn",
        series=C.CANARY_E2E_SECONDS,
        kind="rate",
        field="sum",
        agg="sum",
        threshold=2.0,
        window_s=60.0,
        clear_s=30.0,
        description=(
            "canary probes burning >2 probe-seconds/s — the fleet is slow "
            "from the client's seat even if no tenant is complaining yet"
        ),
    ),
)


def _cmp(value: float, op: str, threshold: float) -> bool:
    return value >= threshold if op == ">=" else value <= threshold


class AlertEvaluator:
    """Fire/clear state machine over a record window.

    ``source`` is a :class:`~.timeseries.TsdbSampler` (its in-memory ring)
    or any object with ``recent(window_s) -> [records]``; tests pass a
    stub. Transitions journal to ``alerts`` (``path``/``root`` override for
    tests) and emit the cataloged gauge/counter into ``registry``.
    """

    def __init__(
        self,
        rules: tuple[AlertRule, ...] | None = None,
        *,
        source=None,
        registry=None,
        root=None,
        journal_path=None,
        clock=None,
    ):
        self.rules = tuple(rules) if rules is not None else DEFAULT_RULES
        self._source = source
        self._registry = registry
        self._root = root
        self._journal = named_journal("alerts", root, path=journal_path)
        self._clock = clock or time.time
        #: rule name -> {"firing", "since", "clear_since"}
        self._state: dict[str, dict] = {
            r.name: {"firing": False, "since": None, "clear_since": None}
            for r in self.rules
        }

    # -- condition evaluation ------------------------------------------------

    def _condition(
        self, rule: AlertRule, records: list[dict], now: float
    ) -> tuple[bool, float | None]:
        """(condition holds, the value that decided it)."""
        pts = _ts.series_points(
            rule.series, records,
            labels=rule.labels, agg=rule.agg, field=rule.field,
        )
        if rule.kind == "rate":
            window = [p for p in pts if p[0] >= now - rule.window_s]
            r = _ts.rate(window)
            return (r is not None and _cmp(r, rule.op, rule.threshold)), r
        if rule.kind == "absence":
            guard_pts = _ts.series_points(
                rule.guard_series or rule.series, records,
                labels=rule.guard_labels, agg=rule.agg,
            )
            if not guard_pts or guard_pts[-1][1] <= rule.guard_threshold:
                return False, None  # no outstanding work: silence is healthy
            window = [p for p in pts if p[0] >= now - rule.window_s]
            if len(window) < 2:
                return False, None  # not enough history to claim stagnation
            # counter-reset aware (rate() convention): a window spanning a
            # process restart shows last < first while tokens ARE flowing —
            # endpoint comparison would falsely page the capture rule
            increase = _ts.rate(window)
            if increase is None:
                return False, None  # zero elapsed: cannot claim stagnation
            return (increase <= 0.0), window[-1][1]
        # threshold: the NEWEST point inside window_s decides; sustainment
        # is the state machine's job (evaluate_once holds for_s before the
        # fire) — requiring the data window to ALSO hold for_s would double
        # the fire latency. window_s here only bounds staleness: a series
        # that stopped reporting cannot keep deciding the condition.
        if rule.guard_series:
            # guarded threshold (same semantics as absence): the condition
            # only holds while the guard's latest point shows outstanding
            # work — a "<=" rule over a utilization gauge must not page an
            # idle engine whose meters legitimately read zero
            guard_pts = _ts.series_points(
                rule.guard_series, records,
                labels=rule.guard_labels, agg=rule.agg,
            )
            if not guard_pts or guard_pts[-1][1] <= rule.guard_threshold:
                return False, None
        window = [p for p in pts if p[0] >= now - rule.window_s]
        if not window:
            return False, None
        value = window[-1][1]
        return _cmp(value, rule.op, rule.threshold), value

    def condition_now(
        self, rule: AlertRule, records: list[dict], now: float | None = None
    ) -> tuple[bool, float | None]:
        """One-shot condition check over an offline window (``tpurun
        alerts`` rendering the on-disk tsdb without evaluator state)."""
        now = self._clock() if now is None else now
        return self._condition(rule, records, now)

    # -- the state machine ---------------------------------------------------

    def evaluate_once(self, now: float | None = None) -> list[dict]:
        """Fold one window into every rule's state; returns the transitions
        (also journaled and counted). Safe to call from the sampler thread."""
        now = self._clock() if now is None else now
        horizon = max(
            (max(r.window_s, r.for_s) for r in self.rules), default=60.0
        )
        records = (
            self._source.recent(horizon + 5.0) if self._source is not None
            else _ts.read_window(start=now - horizon - 5.0, root=self._root)
        )
        out: list[dict] = []
        for rule in self.rules:
            st = self._state[rule.name]
            try:
                cond, value = self._condition(rule, records, now)
            except Exception:
                continue  # a malformed window must not kill the sampler
            if cond:
                st["clear_since"] = None
                if st["since"] is None:
                    st["since"] = now
                held = now - st["since"]
                if not st["firing"] and held >= rule.for_s:
                    st["firing"] = True
                    out.append(self._transition(rule, "fire", value, now))
            else:
                st["since"] = None
                if st["firing"]:
                    if st["clear_since"] is None:
                        st["clear_since"] = now
                    if now - st["clear_since"] >= rule.clear_s:
                        st["firing"] = False
                        st["clear_since"] = None
                        out.append(self._transition(rule, "clear", value, now))
            _obs.set_alert_active(
                rule.name, st["firing"], registry=self._registry
            )
        return out

    def _transition(
        self, rule: AlertRule, event: str, value, now: float
    ) -> dict:
        rec = {
            "at": now,
            "event": event,
            "rule": rule.name,
            "series": rule.series,
            "kind": rule.kind,
            "threshold": rule.threshold,
            "value": round(value, 6) if isinstance(value, float) else value,
        }
        self._journal.record(rec)
        if event == "fire":
            _obs.record_alert_fired(rule.name, registry=self._registry)
            if rule.capture:
                from . import incident as _incident

                _incident.capture(
                    "alert",
                    reason=f"rule {rule.name}: {rule.description}",
                    root=self._root,
                    registry=self._registry,
                )
        return rec

    def active(self) -> list[str]:
        """Names of currently-firing rules."""
        return [n for n, st in self._state.items() if st["firing"]]

    def snapshot(self) -> list[dict]:
        """Per-rule state for the gateway's ``/alerts`` payload."""
        return [
            {
                "rule": r.name,
                "kind": r.kind,
                "series": r.series,
                "threshold": r.threshold,
                "firing": self._state[r.name]["firing"],
                "capture": r.capture,
                "description": r.description,
            }
            for r in self.rules
        ]


def evaluate_offline(
    records: list[dict],
    now: float | None = None,
    rules: tuple[AlertRule, ...] | None = None,
) -> list[dict]:
    """One-shot per-rule condition rows over an offline record window —
    the ONE read shared by ``tpurun alerts`` and the gateway's ``/alerts``
    when no live evaluator runs in-process (schema matches
    :meth:`AlertEvaluator.snapshot` plus the deciding ``value``)."""
    ev = AlertEvaluator(rules)
    if now is None and records:
        now = records[-1]["at"]
    out: list[dict] = []
    for rule in ev.rules:
        cond, value = (
            ev.condition_now(rule, records, now=now)
            if records
            else (False, None)
        )
        out.append({
            "rule": rule.name,
            "kind": rule.kind,
            "series": rule.series,
            "threshold": rule.threshold,
            "firing": cond,
            "value": value,
            "capture": rule.capture,
            "description": rule.description,
        })
    return out


def read_alert_journal(n: int = 50, root=None) -> list[dict]:
    """Newest-last fire/clear history (jax-free — the CLI/gateway read)."""
    return named_journal("alerts", root).tail(n)
