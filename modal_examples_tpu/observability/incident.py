"""Incident bundles: the flight recorder's capture leg
(docs/observability.md#incident-bundles).

When the chip wedges mid-revalidation, the scheduler crash-poisons, a
chaos invariant fails, or an alert rule fires, the evidence is spread
across a dozen live surfaces that die with the process: the tsdb ring,
the journals, the open request traces, the profiler ring, the engine's
watermarks. :func:`capture` snapshots all of them into one
content-addressed directory under ``<state_dir>/incidents/<id>/`` with a
``MANIFEST.json`` naming every file and its sha256 — the bundle IS the
bug report, replayable offline by ``tpurun incidents show`` long after
the chip was power-cycled.

Triggers (the ``mtpu_incidents_captured_total{trigger}`` label set):

- ``watchdog_wedge`` / ``watchdog_quarantine`` — the gray-failure ladder
  (serving/health.py) captures BEFORE it error-stops the victim, so the
  bundle holds the victim's still-open request traces.
- ``scheduler_crash`` — a strict-mode scheduler-loop exception or a dying
  scheduler thread (serving/engine.py) poisons the engine AND preserves
  the minutes that led up to it.
- ``chaos_invariant`` — a failed fleet invariant (faults/chaos.py).
- ``alert`` — an :class:`~.alerts.AlertRule` with ``capture=True`` at its
  fire transition.
- ``canary_drift`` — the correctness canary (observability/canary.py)
  caught a replica generating tokens that diverge bit-exact from its
  golden transcript; the bundle's reason names the mismatching probe
  request so its trace is findable in the open-trace section.
- ``stage_failure`` — ``benchmarks/revalidate_chip.sh``'s stage wrapper on
  any nonzero exit (the next chip wedge ships a bundle, not a shrug).
- ``manual`` — ``tpurun incidents capture``.

Bundles are LRU-bounded like the TraceStore (:data:`MAX_INCIDENTS`,
oldest-mtime pruned) and per-(trigger, replica) debounced
(:data:`COOLDOWN_S`) so a wedge storm cannot fill the disk while a
correlated wedge still bundles every victim. Capture never raises — it runs inside
failure paths that must stay on their own recovery ladder.

jax-free and import-light: the read side (``tpurun incidents``, the
gateway's ``/incidents``) never touches an engine.
"""

from __future__ import annotations

import hashlib
import json
import os
import platform
import shutil
import sys
import threading
import time
import weakref
from pathlib import Path

from .._internal import config as _config
from . import metrics as _obs
from . import timeseries as _ts
from .journal import JOURNALS, named_journal

#: the incidents directory name under ``<state_dir>``
DIR_NAME = "incidents"

#: every capture trigger (closed set — the catalog's
#: ``mtpu_incidents_captured_total{trigger}`` labels enumerate it)
TRIGGERS = (
    "watchdog_wedge", "watchdog_quarantine", "scheduler_crash",
    "chaos_invariant", "alert", "canary_drift", "stage_failure", "manual",
)

#: tsdb window a bundle snapshots (the last N minutes before the event)
WINDOW_S = float(os.environ.get("MTPU_INCIDENT_WINDOW_S", 300.0))
#: bundles kept on disk; the oldest is LRU-pruned past this (the
#: TraceStore discipline)
MAX_INCIDENTS = int(os.environ.get("MTPU_INCIDENT_MAX", 16))
#: per-trigger debounce: a wedge storm (every poll re-fires the ladder)
#: must not write a bundle per poll
COOLDOWN_S = 10.0
#: journal records per bundled tail
JOURNAL_TAIL_N = 200
#: open request traces per bundle (a 64-slot engine's full slot sweep
#: would dominate the bundle)
MAX_OPEN_TRACES = 32

_lock = threading.Lock()
#: trigger -> monotonic time of the last capture (the debounce state)
_last_capture: dict[str, float] = {}

# -- live-engine registry (the watermark / impl_plan / open-trace source) ----

#: weak refs so the registry never pins a dead engine (the profiler's
#: registry discipline)
_engines: list = []
_engines_lock = threading.Lock()


def register_engine(engine) -> None:
    """Called by ``LLMEngine.__init__`` — bundles then snapshot every live
    engine's watermarks, impl plan, and open requests without any global
    fleet object existing."""
    with _engines_lock:
        _engines.append(weakref.ref(engine))
        _engines[:] = [r for r in _engines if r() is not None][-64:]


def live_engines() -> list:
    with _engines_lock:
        return [e for e in (r() for r in _engines) if e is not None]


def incidents_dir(root=None) -> Path:
    return Path(root or _config.state_dir()) / DIR_NAME


# -- the section gatherers (each best-effort: a broken surface costs its
#    section, never the bundle) ----------------------------------------------


def _tsdb_section(now: float, window_s: float, root) -> list[dict]:
    records = _ts.read_window(start=now - window_s, end=now + 1.0, root=root)
    if not records:
        # disk writes failing (read-only state dir) or a capture from a
        # process whose sampler never rotated a segment out: the live
        # ring is all there is
        sampler = _ts.global_sampler()
        if sampler is not None:
            records = sampler.recent(window_s)
    return records


def _journal_sections(root) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for name in JOURNALS:
        try:
            recs = named_journal(name, root).tail(JOURNAL_TAIL_N)
        except OSError:
            recs = []
        if recs:
            out[name] = recs
    return out


def _engine_section() -> list[dict]:
    out = []
    for eng in live_engines():
        try:
            snap = {
                "replica": getattr(eng, "trace_name", "engine"),
                "running": bool(getattr(eng, "_running", False)),
                "stopped_on_error": bool(
                    getattr(eng, "_stopped_on_error", False)
                ),
                "impl_plan": _jsonable(getattr(eng, "impl_plan", None)),
                "paged_impl": getattr(eng, "paged_impl", None),
                "scatter_impl": getattr(eng, "scatter_impl", None),
                "decode_block": getattr(eng, "decode_block", None),
                "error_count": getattr(eng, "error_count", 0),
                "error_log_tail": list(getattr(eng, "error_log", ()))[-3:],
            }
            wm = getattr(eng, "watermarks", None)
            if wm is not None:
                snap["watermarks"] = wm.snapshot()
            slots = []
            for i, s in enumerate(getattr(eng, "slots", ())):
                req = s.request
                if req is None:
                    continue
                slots.append({
                    "slot": i,
                    "request_id": getattr(req, "request_id", None),
                    "trace_id": getattr(
                        getattr(req, "trace", None), "trace_id", None
                    ),
                })
            snap["occupied_slots"] = slots
            out.append(snap)
        except Exception:
            continue
    return out


def _open_traces_section(engines: list[dict]) -> dict:
    """The victim's open request traces: every occupied slot's trace id
    across the live engines, with the spans recorded so far (finished
    spans + events — an open span shows up once its parent store flushed
    it; the watchdog marks live traces before the stop sweep exactly so
    this snapshot carries its intervention)."""
    from . import reqtrace as _rt

    ids: list[str] = []
    for snap in engines:
        for slot in snap.get("occupied_slots", ()):
            tid = slot.get("trace_id")
            if tid and tid not in ids:
                ids.append(tid)
    ids = ids[:MAX_OPEN_TRACES]
    traces = {}
    for tid in ids:
        try:
            traces[tid] = _rt.read_trace(tid)
        except Exception:
            traces[tid] = []
    try:
        recent = _rt.list_traces(limit=20)
    except Exception:
        recent = []
    return {"open": traces, "recent": recent}


def _profiler_section() -> list[dict]:
    from . import profiler as _profiler

    out = []
    for p in _profiler.active_profilers():
        try:
            out.append({
                "replica": p.replica,
                "overhead": p.overhead_summary(),
                **p.perfetto_snapshot(),
            })
        except Exception:
            continue
    return out


def _env_section(now: float) -> dict:
    keep = ("MTPU_", "JAX_", "TPU_", "XLA_", "LIBTPU")
    return {
        "at": now,
        "pid": os.getpid(),
        "argv": sys.argv,
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "env": {
            k: v
            for k, v in sorted(os.environ.items())
            if k.startswith(keep)
        },
    }


def _jsonable(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return repr(obj)


# -- capture ------------------------------------------------------------------


def capture(
    trigger: str,
    *,
    reason: str = "",
    replica: str | None = None,
    root=None,
    registry=None,
    window_s: float | None = None,
    extra: dict | None = None,
    force: bool = False,
) -> Path | None:
    """Snapshot everything into ``<state_dir>/incidents/<id>/``; returns
    the bundle directory, or None (debounced, or the disk refused).

    ``trigger`` must be a :data:`TRIGGERS` member (the catalog closes the
    label set). ``force=True`` skips the debounce (the manual CLI path).
    Never raises — capture runs inside failure paths.
    """
    if trigger not in TRIGGERS:
        raise ValueError(
            f"unknown incident trigger {trigger!r}; one of {TRIGGERS}"
        )
    # debounce per (trigger, replica): a correlated wedge hitting two
    # replicas inside COOLDOWN_S must bundle BOTH victims' open traces —
    # the second error-stop sweeps its slots either way
    key = (trigger, replica)
    now_mono = time.monotonic()
    with _lock:
        last = _last_capture.get(key)
        if not force and last is not None and now_mono - last < COOLDOWN_S:
            return None
        _last_capture[key] = now_mono
    try:
        bundle = _capture_locked(
            trigger, reason, replica, root, registry,
            window_s if window_s is not None else WINDOW_S, extra,
        )
    except Exception:
        bundle = None
    if bundle is None:
        with _lock:  # a failed capture must not consume the debounce slot
            if _last_capture.get(key) == now_mono:
                if last is None:
                    _last_capture.pop(key, None)
                else:
                    _last_capture[key] = last
    return bundle


def _capture_locked(
    trigger, reason, replica, root, registry, window_s, extra
) -> Path | None:
    now = time.time()
    tsdb = _tsdb_section(now, window_s, root)
    journals = _journal_sections(root)
    engines = _engine_section()
    traces = _open_traces_section(engines)
    files: dict[str, str] = {}
    files["tsdb.jsonl"] = "".join(json.dumps(r) + "\n" for r in tsdb)
    for name, recs in journals.items():
        files[f"journal_{name}.jsonl"] = "".join(
            json.dumps(r) + "\n" for r in recs
        )
    files["traces.json"] = json.dumps(traces, indent=1)
    files["engines.json"] = json.dumps(engines, indent=1)
    files["profiler.json"] = json.dumps(_profiler_section(), indent=1)
    files["env.json"] = json.dumps(_env_section(now), indent=1)

    digests = {
        name: {
            "bytes": len(body.encode()),
            "sha256": hashlib.sha256(body.encode()).hexdigest(),
        }
        for name, body in files.items()
    }
    # content address: the id carries a digest over every file's digest,
    # so two bundles with identical evidence collide into the same id
    # instead of duplicating, and a tampered bundle no longer matches
    content = hashlib.sha256(
        json.dumps(digests, sort_keys=True).encode()
    ).hexdigest()[:12]
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(now))
    incident_id = f"inc-{stamp}-{content}"

    manifest = {
        "id": incident_id,
        "at": now,
        "trigger": trigger,
        "reason": reason,
        "replica": replica,
        "window_s": window_s,
        "tsdb_records": len(tsdb),
        "journals": {name: len(recs) for name, recs in journals.items()},
        "open_traces": sorted(traces.get("open", ())),
        "engines": [e.get("replica") for e in engines],
        "files": digests,
        **({"extra": _jsonable(extra)} if extra else {}),
    }

    d = incidents_dir(root)
    bundle = d / incident_id
    try:
        tmp = d / f".{incident_id}.tmp.{os.getpid()}"
        tmp.mkdir(parents=True, exist_ok=True)
        for name, body in files.items():
            (tmp / name).write_text(body)
        (tmp / "MANIFEST.json").write_text(json.dumps(manifest, indent=1))
        if bundle.exists():
            shutil.rmtree(tmp, ignore_errors=True)  # identical evidence
        else:
            os.replace(tmp, bundle)
    except OSError:
        return None
    _prune(d)
    _obs.record_incident_captured(trigger, registry=registry)
    return bundle


#: a tmp dir younger than this is a CONCURRENT capture mid-write (two
#: triggers firing together, or revalidate_chip.sh capturing from another
#: process), not an orphan — sweeping it would silently lose that bundle
_TMP_GRACE_S = 120.0


def _prune(d: Path) -> None:
    """LRU-bound the incidents directory (oldest mtime first), and sweep
    orphaned tmp dirs from a capture that died mid-write."""
    try:
        for tmp in d.glob(".inc-*.tmp.*"):
            try:
                if time.time() - tmp.stat().st_mtime < _TMP_GRACE_S:
                    continue
            except OSError:
                continue  # racing its own os.replace/rmtree: leave it
            shutil.rmtree(tmp, ignore_errors=True)
        bundles = sorted(
            (p for p in d.glob("inc-*") if p.is_dir()),
            key=lambda p: p.stat().st_mtime,
        )
        for p in bundles[: max(0, len(bundles) - MAX_INCIDENTS)]:
            shutil.rmtree(p, ignore_errors=True)
    except OSError:
        pass


# -- read surfaces (jax-free: `tpurun incidents`, the gateway) ----------------


def list_incidents(root=None) -> list[dict]:
    """Every bundle's manifest, newest first."""
    out = []
    try:
        dirs = sorted(incidents_dir(root).glob("inc-*"), reverse=True)
    except OSError:
        return out
    for p in dirs:
        m = _read_json(p / "MANIFEST.json")
        if m is not None:
            out.append(m)
    return out


def read_manifest(incident_id: str, root=None) -> dict | None:
    p = _resolve(incident_id, root)
    return _read_json(p / "MANIFEST.json") if p is not None else None


def read_bundle_file(incident_id: str, name: str, root=None) -> str | None:
    """One bundle file's content. ``name`` must appear in the manifest —
    the manifest whitelists exactly what :func:`capture` wrote, so a
    crafted name can never traverse out of the bundle."""
    p = _resolve(incident_id, root)
    if p is None:
        return None
    manifest = _read_json(p / "MANIFEST.json")
    if manifest is None or name not in manifest.get("files", {}):
        return None
    try:
        return (p / name).read_text()
    except OSError:
        return None


def _resolve(incident_id: str, root=None) -> Path | None:
    """Exact id first, then a unique prefix (the TraceStore.resolve rule);
    rejects anything that isn't a plain ``inc-…`` token."""
    if (
        not incident_id
        or not incident_id.replace("-", "").replace("_", "").isalnum()
    ):
        return None
    d = incidents_dir(root)
    p = d / incident_id
    if p.is_dir():
        return p
    try:
        matches = sorted(x for x in d.glob(f"{incident_id}*") if x.is_dir())
    except OSError:
        return None
    return matches[0] if len(matches) == 1 else None


def _read_json(path: Path) -> dict | None:
    try:
        return json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None
