"""File-backed metrics push gateway for ephemeral processes.

A scraper can hit a live ``/metrics`` endpoint, but the processes that emit
most series here — app runs, bench children, short CLI invocations — are
gone before any scrape interval fires. The reference solves this with a
Pushgateway app (10_integrations/pushgateway.py); the local analog is a
directory of per-job exposition files under ``<state_dir>/metrics/``:

- each process *pushes* its registry on shutdown (``push_metrics_file``,
  called from ``AppRun.close``), atomically (write + rename);
- ``tpurun metrics`` *merges* every pushed file into one valid exposition
  (job label per source, deduplicated headers) via
  :func:`modal_examples_tpu.utils.prometheus.merge_expositions`.

Stale jobs age out after ``_PUSH_RETENTION_S``.
"""

from __future__ import annotations

import os
import time
from pathlib import Path

from .._internal import config as _config
from ..utils.prometheus import Registry, default_registry, merge_expositions

_PUSH_RETENTION_S = 7 * 86400


def _metrics_dir(root: str | Path | None = None) -> Path:
    p = Path(root) if root else (_config.state_dir() / "metrics")
    p.mkdir(parents=True, exist_ok=True)
    return p


def _safe_job(job: str) -> str:
    return "".join(c if (c.isalnum() or c in "-_.") else "-" for c in job)


def push_metrics_file(
    job: str,
    registry: Registry | None = None,
    *,
    root: str | Path | None = None,
) -> Path | None:
    """Write this process's exposition to ``<state_dir>/metrics/<job>.prom``
    (atomic replace; each push overwrites the job's slot). Returns the path,
    or None when the registry holds no series (nothing to push — an empty
    file would only add noise to the merge)."""
    reg = registry if registry is not None else default_registry
    text = reg.expose()
    if text.strip() == "":
        return None
    d = _metrics_dir(root)
    path = d / f"{_safe_job(job)}.prom"
    tmp = path.with_suffix(f".tmp.{os.getpid()}")
    tmp.write_text(text)
    os.replace(tmp, path)
    _gc(d)
    return path


def pushed_jobs(root: str | Path | None = None) -> dict[str, str]:
    """job name -> raw exposition text, one entry per pushed ``.prom`` file."""
    d = _metrics_dir(root)
    jobs: dict[str, str] = {}
    for p in sorted(d.glob("*.prom")):
        try:
            jobs[p.stem] = p.read_text()
        except OSError:
            continue
    return jobs


def read_pushed_metrics(root: str | Path | None = None) -> str:
    """Merge every pushed job file into one exposition (the gateway's
    /metrics view). Empty string when nothing was ever pushed."""
    jobs = pushed_jobs(root)
    if not jobs:
        return ""
    return merge_expositions(jobs)


def live_and_pushed_metrics(
    registry: Registry | None = None,
    *,
    job: str = "live",
    root: str | Path | None = None,
) -> str:
    """One exposition covering this process's live registry (under ``job``)
    plus every previously pushed job file — what a scraper hitting the
    gateway's ``/metrics`` should see."""
    reg = registry if registry is not None else default_registry
    jobs = pushed_jobs(root)
    live = reg.expose()
    if live.strip():
        jobs[job] = live
    if not jobs:
        return ""
    return merge_expositions(jobs)


def _gc(d: Path) -> None:
    cutoff = time.time() - _PUSH_RETENTION_S
    for p in d.glob("*.prom"):
        try:
            if p.stat().st_mtime < cutoff:
                p.unlink()
        except OSError:
            pass
