"""File-backed metrics push gateway for ephemeral processes.

A scraper can hit a live ``/metrics`` endpoint, but the processes that emit
most series here — app runs, bench children, short CLI invocations — are
gone before any scrape interval fires. The reference solves this with a
Pushgateway app (10_integrations/pushgateway.py); the local analog is a
directory of per-job exposition files under ``<state_dir>/metrics/``:

- each process *pushes* its registry on shutdown (``push_metrics_file``,
  called from ``AppRun.close``), atomically (write + rename);
- ``tpurun metrics`` *merges* every pushed file into one valid exposition
  (job label per source, deduplicated headers) via
  :func:`modal_examples_tpu.utils.prometheus.merge_expositions`.

Stale jobs age out after ``_PUSH_RETENTION_S``.
"""

from __future__ import annotations

import os
import time
from pathlib import Path

from .._internal import config as _config
from ..utils.prometheus import Registry, default_registry, merge_expositions

_PUSH_RETENTION_S = 7 * 86400


def _metrics_dir(root: str | Path | None = None) -> Path:
    p = Path(root) if root else (_config.state_dir() / "metrics")
    p.mkdir(parents=True, exist_ok=True)
    return p


def _safe_job(job: str) -> str:
    return "".join(c if (c.isalnum() or c in "-_.") else "-" for c in job)


def push_metrics_file(
    job: str,
    registry: Registry | None = None,
    *,
    root: str | Path | None = None,
) -> Path | None:
    """Write this process's exposition to ``<state_dir>/metrics/<job>.prom``
    (atomic replace; each push overwrites the job's slot). Returns the path,
    or None when the registry holds no series (nothing to push — an empty
    file would only add noise to the merge)."""
    reg = registry if registry is not None else default_registry
    text = reg.expose()
    if text.strip() == "":
        return None
    d = _metrics_dir(root)
    path = d / f"{_safe_job(job)}.prom"
    tmp = path.with_suffix(f".tmp.{os.getpid()}")
    tmp.write_text(text)
    os.replace(tmp, path)
    _gc(d)
    return path


def pushed_jobs(root: str | Path | None = None) -> dict[str, str]:
    """job name -> raw exposition text, one entry per pushed ``.prom`` file."""
    d = _metrics_dir(root)
    jobs: dict[str, str] = {}
    for p in sorted(d.glob("*.prom")):
        try:
            jobs[p.stem] = p.read_text()
        except OSError:
            continue
    return jobs


def read_pushed_metrics(root: str | Path | None = None) -> str:
    """Merge every pushed job file into one exposition (the gateway's
    /metrics view). Empty string when nothing was ever pushed."""
    jobs = pushed_jobs(root)
    if not jobs:
        return ""
    return merge_expositions(jobs)


def live_and_pushed_metrics(
    registry: Registry | None = None,
    *,
    job: str = "live",
    root: str | Path | None = None,
) -> str:
    """One exposition covering this process's live registry (under ``job``)
    plus every previously pushed job file — what a scraper hitting the
    gateway's ``/metrics`` should see."""
    reg = registry if registry is not None else default_registry
    jobs = pushed_jobs(root)
    live = reg.expose()
    if live.strip():
        jobs[job] = live
    if not jobs:
        return ""
    return merge_expositions(jobs)


def _gc(d: Path) -> None:
    cutoff = time.time() - _PUSH_RETENTION_S
    for p in d.glob("*.prom"):
        try:
            if p.stat().st_mtime < cutoff:
                p.unlink()
        except OSError:
            pass


# --------------------------------------------------------------------------
# Perfetto / chrome://tracing export
# --------------------------------------------------------------------------

#: span names recorded by the container worker process (everything nested
#: under them — user spans — is container-side too)
_CONTAINER_SPAN_NAMES = ("execute", "serialize")


#: the counter tracks a tsdb ride-along renders by default — the serving
#: trajectory an incident reader wants next to the spans (a full window
#: export would be hundreds of tracks; pass ``names=`` for more)
TSDB_COUNTER_SERIES = (
    "mtpu_tokens_per_second",
    "mtpu_active_slots",
    "mtpu_waiting_requests",
    "mtpu_kv_page_occupancy",
    "mtpu_host_overhead_ratio",
    "mtpu_generated_tokens_total",
    "mtpu_decode_stall_seconds",
    "mtpu_alerts_active",
)


def tsdb_counter_events(
    records: list[dict],
    names: tuple[str, ...] | None = None,
    *,
    t0: float = 0.0,
    pid: int = 1,
    tid: int = 0,
) -> list[dict]:
    """Chrome-trace counter ("C") events from a tsdb window
    (:func:`~.timeseries.read_window` records): one counter track per
    series name, values folded across label sets per the ``tpurun top``
    rule (gauges take the max — a 0..1 fraction must never sum across
    replicas; counters and histogram counts sum). Timestamps are
    microseconds relative to ``t0`` (wall-clock seconds)."""
    names = TSDB_COUNTER_SERIES if names is None else tuple(names)
    events: list[dict] = []
    for rec in records:
        at = rec.get("at")
        if not isinstance(at, (int, float)):
            continue
        folded: dict[str, float] = {}
        kinds: dict[str, str] = {}
        for entry in rec.get("series", ()):
            try:
                name, _labels, kind, value, _hsum = entry
            except (ValueError, TypeError):
                continue
            if name not in names:
                continue
            kinds[name] = kind
            if name in folded and kind == "gauge":
                folded[name] = max(folded[name], float(value))
            else:
                folded[name] = folded.get(name, 0.0) + float(value)
        for name, value in sorted(folded.items()):
            events.append({
                "ph": "C", "pid": pid, "tid": tid, "cat": "mtpu",
                "name": name,
                "ts": round((at - t0) * 1e6, 3),
                "args": {kinds.get(name, "value"): round(value, 6)},
            })
    return events


def spans_to_chrome_trace(
    spans: list[dict],
    trace_id: str = "",
    profile: dict | None = None,
    tsdb: list[dict] | None = None,
) -> dict:
    """Convert one trace's JSONL spans to Chrome-trace / Perfetto JSON.

    Output is the Trace Event Format object (``{"traceEvents": [...]}``)
    that loads directly in ``chrome://tracing`` and ui.perfetto.dev.
    Complete ("X") events nest by timestamp within a track, instantaneous
    spans (retry markers, fault events) become instant ("i") events.
    Timestamps are microseconds relative to the earliest span.

    Track assignment is REPLICA-AWARE and deterministic: request-scoped
    spans (observability/reqtrace.py) carry a ``replica`` attribute, and
    each distinct replica gets its own named track — tids assigned in
    sorted replica order, so a merged FLEET trace (gateway + prefill
    replica + decode replica stores) renders one track per replica
    instead of interleaving every event onto one. Executor call traces
    (no replica attrs) keep the legacy two-track layout: supervisor-side
    phases (queue/boot/dispatch/retry) on tid 1, container-worker phases
    (execute/serialize + user spans) on tid 2. Migrations additionally get
    span LINKS: a flow arrow from the transfer (or prefill) span on the
    source replica's track to the adopt span on the destination's.

    ``profile`` (hot-path profiler ride-along, docs/observability.md):
    ``{replica: {"ticks": [...], "compiles": [...]}}`` snapshots from
    :meth:`~.profiler.HotPathProfiler.perfetto_snapshot` — tick-phase
    COUNTER tracks ("C" events, one series per phase in milliseconds) and
    compile SLICES ("X" events named ``compile:<program>``) render on the
    owning replica's track; replicas appearing only in the profile get
    their own track after the span replicas, in the same deterministic
    sorted order.

    ``tsdb`` (flight-recorder ride-along, docs/observability.md
    #metrics-history): a :func:`~.timeseries.read_window` record list —
    the window's :data:`TSDB_COUNTER_SERIES` render as counter tracks on
    one dedicated "tsdb" track next to the tick-phase tracks, so the
    serving trajectory (tokens/s, occupancy, overhead ratio) lines up
    under the spans of the request that died inside it.
    """
    import zlib as _zlib

    # tolerate hand-saved --profile files: a record without a numeric
    # wall-clock "at" cannot be placed on the timeline, so it is dropped
    # here instead of KeyError-ing the whole export (every other field is
    # already optional via .get)
    profile = {
        name: {
            "ticks": [
                t for t in (snap or {}).get("ticks", ())
                if isinstance(t, dict)
                and isinstance(t.get("at"), (int, float))
            ],
            "compiles": [
                c for c in (snap or {}).get("compiles", ())
                if isinstance(c, dict)
                and isinstance(c.get("at"), (int, float))
            ],
        }
        for name, snap in (profile or {}).items()
    }
    tsdb = [
        r for r in (tsdb or ())
        if isinstance(r, dict) and isinstance(r.get("at"), (int, float))
    ]
    if not spans and not profile and not tsdb:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    by_id = {s.get("span_id"): s for s in spans}

    def is_container_side(span: dict) -> bool:
        seen = set()
        cur: dict | None = span
        while cur is not None and id(cur) not in seen:
            seen.add(id(cur))
            if cur.get("name") in _CONTAINER_SPAN_NAMES:
                return True
            cur = by_id.get(cur.get("parent_id"))
        return False

    starts = [s.get("start") or 0.0 for s in spans]
    for snap in profile.values():
        starts += [
            t["at"] - (t.get("total") or 0.0) for t in snap.get("ticks", [])
        ]
        starts += [
            c["at"] - (c.get("seconds") or 0.0)
            for c in snap.get("compiles", [])
        ]
    starts += [r["at"] for r in tsdb]
    t0 = min(starts) if starts else 0.0
    replicas = sorted(
        {
            (s.get("attrs") or {}).get("replica")
            for s in spans
            if (s.get("attrs") or {}).get("replica")
        }
        | set(profile)
    )
    events: list[dict] = [
        {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
         "args": {"name": f"mtpu trace {trace_id}".strip()}},
    ]
    if replicas:
        # one track per replica, deterministic: sorted name order
        tid_of_replica = {name: i + 1 for i, name in enumerate(replicas)}
        other_tid = len(replicas) + 1
        for name, tid in tid_of_replica.items():
            events.append(
                {"ph": "M", "pid": 1, "tid": tid, "name": "thread_name",
                 "args": {"name": name}}
            )

        def tid_for(span: dict) -> int:
            return tid_of_replica.get(
                (span.get("attrs") or {}).get("replica"), other_tid
            )

        if any(tid_for(s) == other_tid for s in spans):
            events.append(
                {"ph": "M", "pid": 1, "tid": other_tid,
                 "name": "thread_name", "args": {"name": "other"}}
            )
    else:
        events += [
            {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
             "args": {"name": "supervisor"}},
            {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
             "args": {"name": "container"}},
        ]

        def tid_for(span: dict) -> int:
            return 2 if is_container_side(span) else 1

    for s in sorted(spans, key=lambda s: s.get("start") or 0.0):
        start = s.get("start") or t0
        end = s.get("end")
        args = dict(s.get("attrs") or {})
        args["span_id"] = s.get("span_id")
        if s.get("status") and s["status"] != "ok":
            args["status"] = s["status"]
        ev = {
            "name": s.get("name", "?"),
            "cat": "mtpu",
            "pid": 1,
            "tid": tid_for(s),
            "ts": round((start - t0) * 1e6, 3),
            "args": args,
        }
        dur_us = round(((end if end is not None else start) - start) * 1e6, 3)
        if dur_us <= 0:
            ev["ph"] = "i"
            ev["s"] = "t"  # thread-scoped instant
        else:
            ev["ph"] = "X"
            ev["dur"] = dur_us
        events.append(ev)

    if replicas:
        # span links for migrations: flow arrows source -> destination,
        # binding the k-th transfer (falling back to the k-th prefill) to
        # the k-th adopt — perfetto draws the cross-track arrow
        def of_name(name):
            return sorted(
                (s for s in spans if s.get("name") == name),
                key=lambda s: s.get("start") or 0.0,
            )

        transfers, prefills, adopts = (
            of_name("transfer"), of_name("prefill"), of_name("adopt")
        )
        for k, adopt in enumerate(adopts):
            src = (
                transfers[k]
                if k < len(transfers)
                else (prefills[k] if k < len(prefills) else None)
            )
            if src is None:
                continue
            fid = _zlib.crc32(f"{trace_id}:migration:{k}".encode())
            src_end = src.get("end") or src.get("start") or t0
            events.append(
                {"ph": "s", "id": fid, "pid": 1, "tid": tid_for(src),
                 "ts": round((src_end - t0) * 1e6, 3), "name": "migration",
                 "cat": "mtpu"}
            )
            events.append(
                {"ph": "f", "bp": "e", "id": fid, "pid": 1,
                 "tid": tid_for(adopt),
                 "ts": round(((adopt.get("start") or t0) - t0) * 1e6, 3),
                 "name": "migration", "cat": "mtpu"}
            )

    # hot-path profiler ride-along: tick-phase counter tracks + compile
    # slices on each owning replica's track, deterministic ordering (sorted
    # replicas; ticks/compiles sorted by wall timestamp)
    for replica in sorted(profile):
        snap = profile[replica] or {}
        tid = tid_of_replica.get(replica, other_tid)
        for t in sorted(
            snap.get("ticks", ()), key=lambda t: t.get("at") or 0.0
        ):
            total = t.get("total") or 0.0
            args = {
                phase: round(seconds * 1e3, 6)
                for phase, seconds in sorted(
                    (t.get("phases") or {}).items()
                )
            }
            events.append({
                "ph": "C", "pid": 1, "tid": tid, "cat": "mtpu",
                "name": "tick_phase_ms",
                "ts": round((t["at"] - total - t0) * 1e6, 3),
                "args": args,
            })
        for c in sorted(
            snap.get("compiles", ()), key=lambda c: c.get("at") or 0.0
        ):
            seconds = c.get("seconds") or 0.0
            events.append({
                "ph": "X", "pid": 1, "tid": tid, "cat": "mtpu",
                "name": f"compile:{c.get('program', '?')}",
                "ts": round((c["at"] - seconds - t0) * 1e6, 3),
                "dur": round(seconds * 1e6, 3),
                "args": {"shape_key": c.get("shape_key")},
            })
    if tsdb:
        # the flight-recorder trajectory on its own dedicated track,
        # after every replica track (legacy layout uses tids 1/2)
        tsdb_tid = (len(replicas) + 2) if replicas else 3
        events.append(
            {"ph": "M", "pid": 1, "tid": tsdb_tid, "name": "thread_name",
             "args": {"name": "tsdb"}}
        )
        events += tsdb_counter_events(tsdb, t0=t0, pid=1, tid=tsdb_tid)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"trace_id": trace_id, "epoch_start_s": t0},
    }


def export_chrome_trace(
    trace_id: str,
    out_path: str | Path | None = None,
    *,
    store=None,
) -> dict | None:
    """Read one trace from the (default) TraceStore and convert it; when
    ``out_path`` is given the JSON is also written there. Returns the trace
    dict, or None when no such trace exists."""
    import json

    if store is None:
        from .trace import default_store as store  # noqa: F811
    spans = store.read(trace_id)
    if not spans:
        return None
    doc = spans_to_chrome_trace(spans, trace_id)
    if out_path is not None:
        Path(out_path).write_text(json.dumps(doc, indent=1))
    return doc
