"""Call-lifecycle tracing: spans, JSONL trace store, context propagation.

Every ``.remote/.map/.spawn`` call gets a trace whose id IS the call's input
id (``in-...``), so ``tpurun trace <call_id>`` needs no lookup table. The
executor opens phase spans on the supervisor side (queue, boot, dispatch);
the container worker emits its spans (execute, serialize) in the child
process and ships them back over the existing message pipe, where they
stitch into the same trace — one JSONL file per call under
``<state_dir>/traces/``, one JSON object per span (greppable, same spirit
as ``utils/tracking.RunLogger``).

Span timestamps are wall-clock (``time.time()``): supervisor and containers
share a host, so child spans land on the parent's timeline without clock
translation.

``MTPU_TRACE=0`` disables tracing entirely (span helpers return ``None``
and the executor skips every span call site).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import os
import re
import threading
import time
import uuid
from pathlib import Path
from typing import Callable

from .._internal import config as _config

#: traces are retained this long (mirrors the spawned-call record retention)
_TRACE_RETENTION_S = 7 * 86400


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


#: hard bounds on the traces directory — age alone is not enough on a
#: long-running gateway (a week of traffic is unbounded files); LRU-deleted
#: oldest-first past either cap
_MAX_TRACE_FILES = _env_int("MTPU_TRACE_MAX_FILES", 2000)
_MAX_TRACE_BYTES = _env_int("MTPU_TRACE_MAX_BYTES", 256 * 1024 * 1024)


def tracing_enabled() -> bool:
    return os.environ.get("MTPU_TRACE", "1") not in ("0", "false", "off")


def _new_span_id() -> str:
    return f"sp-{uuid.uuid4().hex[:12]}"


@dataclasses.dataclass
class Span:
    """One timed phase of a call. ``finish()`` stamps the end and returns the
    duration; recording (JSONL write or cross-process shipping) is the
    caller's job via :class:`TraceStore` or a child-side buffer."""

    trace_id: str
    name: str
    span_id: str = dataclasses.field(default_factory=_new_span_id)
    parent_id: str | None = None
    start: float = dataclasses.field(default_factory=time.time)
    end: float | None = None
    status: str = "ok"
    attrs: dict = dataclasses.field(default_factory=dict)

    def finish(self, status: str = "ok", **attrs) -> float:
        if self.end is None:
            self.end = time.time()
        self.status = status
        if attrs:
            self.attrs.update(attrs)
        return self.duration

    @property
    def duration(self) -> float:
        return max(0.0, (self.end or time.time()) - self.start)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "status": self.status,
            "attrs": self.attrs,
        }


class TraceStore:
    """Per-trace JSONL files under ``<state_dir>/traces/``.

    Only *finished* spans are recorded; an abandoned span (e.g. a dispatch
    span whose container vanished without a death notification) simply never
    appears, it can't corrupt the file. Writes are append-only and
    line-atomic, so a concurrent ``tpurun trace`` reader sees a valid prefix.
    """

    def __init__(self, root: str | Path | None = None):
        self._root = Path(root) if root else None
        self._resolved: Path | None = None  # root after its one-time mkdir
        self._lock = threading.Lock()
        self._last_gc = 0.0

    @property
    def root(self) -> Path:
        if self._resolved is None:
            root = self._root or (_config.state_dir() / "traces")
            root.mkdir(parents=True, exist_ok=True)
            self._resolved = root
        return self._resolved

    def record(self, span: "Span | dict") -> None:
        d = span.to_dict() if isinstance(span, Span) else dict(span)
        if d.get("end") is None:
            d["end"] = time.time()
        path = self.root / f"{d['trace_id']}.jsonl"
        line = json.dumps(d) + "\n"
        with self._lock:
            try:
                with open(path, "a") as f:
                    f.write(line)
            except FileNotFoundError:
                # traces dir deleted out from under us: re-create and retry
                # (record runs in the result-delivery path — never raise)
                self._resolved = None
                try:
                    with open(self.root / path.name, "a") as f:
                        f.write(line)
                except OSError:
                    pass
        self._maybe_gc()

    def read(self, trace_id: str) -> list[dict]:
        path = self.root / f"{trace_id}.jsonl"
        if not path.exists():
            return []
        spans = []
        for line in path.read_text().splitlines():
            if not line.strip():
                continue
            try:
                spans.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail line from a concurrent writer
        return spans

    #: the only shape a trace id can have (both namespaces); resolve()
    #: rejects anything else up front — the token reaches Path/glob, so a
    #: separator or glob metachar must mean "no such trace", not a
    #: traversal or an unhandled pattern error
    _ID_TOKEN_RE = re.compile(r"^[A-Za-z0-9._-]+$")

    def resolve(self, token: str) -> str | None:
        """Resolve ``token`` to a stored trace id: exact match first, then
        a UNIQUE prefix. Both id namespaces live in one store — executor
        calls (``in-…``) and serving requests (``req-…``) — so ``tpurun
        trace``/``explain`` take either kind, abbreviated."""
        if not token or not self._ID_TOKEN_RE.match(token):
            return None
        if (self.root / f"{token}.jsonl").exists():
            return token
        matches = sorted(p.stem for p in self.root.glob(f"{token}*.jsonl"))
        return matches[0] if len(matches) == 1 else None

    def list_traces(self, limit: int = 50) -> list[str]:
        files = sorted(
            self.root.glob("*.jsonl"),
            key=lambda p: p.stat().st_mtime,
            reverse=True,
        )
        return [p.stem for p in files[:limit]]

    def _maybe_gc(self) -> None:
        now = time.monotonic()
        with self._lock:
            if now - self._last_gc < 300:
                return
            self._last_gc = now
        # the sweep globs+stats the whole trace dir — run it off-thread so a
        # recording thread (often the container reader delivering a result)
        # never stalls on it
        threading.Thread(target=self._gc_sweep, daemon=True).start()

    def _gc_sweep(self) -> None:
        """Age out old traces, then enforce the count/byte caps LRU-first
        (oldest mtime deleted first) so a long-running gateway's traces
        directory stays bounded no matter the traffic rate."""
        cutoff = time.time() - _TRACE_RETENTION_S
        survivors: list[tuple[float, int, Path]] = []  # (mtime, size, path)
        for p in self.root.glob("*.jsonl"):
            try:
                st = p.stat()
                if st.st_mtime < cutoff:
                    p.unlink()
                else:
                    survivors.append((st.st_mtime, st.st_size, p))
            except OSError:
                pass
        survivors.sort()  # oldest first
        total = sum(size for _, size, _ in survivors)
        excess = len(survivors) - _MAX_TRACE_FILES
        for mtime, size, p in survivors:
            if excess <= 0 and total <= _MAX_TRACE_BYTES:
                break
            try:
                p.unlink()
            except OSError:
                continue
            excess -= 1
            total -= size


#: process-wide default store (state-dir backed)
default_store = TraceStore()


# --------------------------------------------------------------------------
# Context propagation — supervisor -> container worker -> user code
# --------------------------------------------------------------------------


@dataclasses.dataclass
class TraceContext:
    """The ambient trace for the current execution context: new spans created
    with :func:`span` become children of ``span_id`` and are delivered to
    ``sink`` when finished (the store's ``record`` in the supervisor, a
    buffer shipped over the pipe in a container worker)."""

    trace_id: str
    span_id: str | None
    sink: Callable[[dict], None]


_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "mtpu-trace-ctx", default=None
)


def current_context() -> TraceContext | None:
    return _current.get()


def current_trace_id() -> str | None:
    ctx = _current.get()
    return ctx.trace_id if ctx else None


def set_context(ctx: TraceContext | None) -> contextvars.Token:
    return _current.set(ctx)


@contextlib.contextmanager
def span(name: str, **attrs):
    """User-facing span context manager: nests under the ambient trace (a
    no-op yielding None outside one). Works inside container workers — the
    span ships back with the call's execute/serialize spans — and in the
    supervisor process."""
    ctx = _current.get()
    if ctx is None or not tracing_enabled():
        yield None
        return
    sp = Span(
        trace_id=ctx.trace_id, name=name, parent_id=ctx.span_id, attrs=attrs
    )
    token = _current.set(TraceContext(ctx.trace_id, sp.span_id, ctx.sink))
    try:
        yield sp
        sp.finish("ok")
    except BaseException:
        sp.finish("error")
        raise
    finally:
        _current.reset(token)
        ctx.sink(sp.to_dict())
