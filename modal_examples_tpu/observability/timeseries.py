"""Metrics history: the flight recorder's time-series leg
(docs/observability.md#metrics-history).

Every observability surface before this PR was point-in-time: the registry
holds the CURRENT gauge/histogram state, journals hold per-subsystem
decisions, and the event that matters most — a chip wedging mid-run — left
no artifact of the minutes leading up to it. This module is the black box:
a background :class:`TsdbSampler` scrapes the in-process
:class:`~..utils.prometheus.Registry` every ``MTPU_TS_INTERVAL`` seconds
(default 1 s) into a bounded on-disk segment ring under
``<state_dir>/tsdb/`` — append-only JSONL segments plus a tiny
``index.json`` — so latency-vs-load *trajectories* survive the process
that produced them and a later ``tpurun tsdb`` / incident bundle can
replay them offline.

**Zero-cost when off** (the ``MTPU_PROFILE`` rule): ``LLMEngine.__init__``
resolves ``MTPU_TSDB`` ONCE and only then starts the process-wide sampler
thread — nothing on the scheduler hot path either way; the sampler's whole
cost is one locked registry pass per interval, and that cost is itself
recorded (``mtpu_tsdb_scrape_seconds``) so "does the flight recorder cost
anything?" is answerable from the recorder.

On-disk shape: one JSON object per scrape, ``{"at": wall_seconds,
"series": [[name, labels, kind, value, hsum], ...]}`` — counters/gauges
carry their value, histograms their cumulative count with ``hsum`` the
cumulative sum, so ``rate()`` over the window recovers both event rates
and per-second time spent. Segments rotate at
:data:`SEGMENT_MAX_RECORDS` records and the ring keeps the newest
:data:`MAX_SEGMENTS` (LRU prune, the TraceStore discipline).

jax-free and import-light: the read side (``tpurun tsdb``, incident
bundles, the alert evaluator) never touches an engine.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path

from .._internal import config as _config
from . import metrics as _obs

#: the one env switch (resolved once per process, like MTPU_PROFILE):
#: unset/0 = off — bench children and the chaos harness opt in
TSDB_ENV = "MTPU_TSDB"
#: scrape interval in seconds (float); default 1.0
INTERVAL_ENV = "MTPU_TS_INTERVAL"
#: the tsdb directory name under ``<state_dir>``
DIR_NAME = "tsdb"

#: records per segment before rotation (at the 1 s default interval one
#: segment is ~8.5 minutes of history)
SEGMENT_MAX_RECORDS = int(os.environ.get("MTPU_TSDB_SEGMENT_RECORDS", 512))
#: segments kept on disk; the oldest is LRU-pruned past this
MAX_SEGMENTS = int(os.environ.get("MTPU_TSDB_MAX_SEGMENTS", 16))
#: scrape records kept in memory (the alert evaluator's window source —
#: rule evaluation must not re-read disk every second)
RING_RECORDS = 600
#: a segment this recently written that THIS sampler did not create is a
#: concurrent writer's active segment (two MTPU_TSDB=1 processes sharing
#: one state dir) — unlinking it would silently drop its newest samples
SEGMENT_PRUNE_GRACE_S = 60.0


def sampling_enabled(explicit=None) -> bool:
    """Resolve the tsdb switch ONCE: explicit arg beats :data:`TSDB_ENV`
    beats off (the MTPU_PROFILE rule — the env is never re-read on a hot
    path)."""
    if explicit is not None:
        return bool(explicit)
    return os.environ.get(TSDB_ENV, "") not in ("", "0")


def default_interval() -> float:
    raw = os.environ.get(INTERVAL_ENV, "")
    try:
        return max(0.05, float(raw)) if raw else 1.0
    except ValueError:
        return 1.0


def tsdb_dir(root=None) -> Path:
    """The segment directory — ``<root or state_dir>/tsdb``."""
    return Path(root or _config.state_dir()) / DIR_NAME


class TsdbSampler:
    """Background registry scraper writing the on-disk segment ring.

    ``clock`` is an injectable monotonic clock (fake-clock tests drive
    :meth:`sample_once` directly); record timestamps are wall-clock
    (``time.time()``) so windows align with journal records and trace
    spans. ``evaluate_alerts=True`` lazily attaches an
    :class:`~.alerts.AlertEvaluator` over the in-memory ring, so any
    process running the sampler also evaluates the starter rule set — no
    second thread, no second scrape.
    """

    def __init__(
        self,
        *,
        registry=None,
        root=None,
        interval: float | None = None,
        clock=None,
        evaluate_alerts: bool = True,
        segment_records: int = SEGMENT_MAX_RECORDS,
        max_segments: int = MAX_SEGMENTS,
    ):
        from ..utils.prometheus import default_registry

        self._registry = registry if registry is not None else default_registry
        self._root = root
        self._resolved: Path | None = None
        self.interval = interval if interval is not None else default_interval()
        self._clock = clock or time.monotonic
        self._segment_records = max(1, int(segment_records))
        self._max_segments = max(1, int(max_segments))
        self._lock = threading.Lock()
        self.ring: deque[dict] = deque(maxlen=RING_RECORDS)
        self._seg_path: Path | None = None
        self._seg_count = 0
        self._seg_seq = 0
        self._own_segs: list[Path] = []
        self._samples = 0
        self._evaluator = None
        if evaluate_alerts:
            from .alerts import AlertEvaluator

            self._evaluator = AlertEvaluator(
                source=self, registry=self._registry, root=root
            )
        self._running = False
        self._thread: threading.Thread | None = None

    @property
    def root(self) -> Path:
        if self._resolved is None:
            d = tsdb_dir(self._root)
            d.mkdir(parents=True, exist_ok=True)
            self._resolved = d
        return self._resolved

    @property
    def evaluator(self):
        return self._evaluator

    # -- one scrape ----------------------------------------------------------

    def sample_once(self) -> dict:
        """Scrape the registry into one record: append it to the current
        segment (rotating/pruning as needed), the in-memory ring, and the
        sampler's own telemetry; then evaluate the attached alert rules.
        Never raises — the sampler thread must survive a read-only disk."""
        t0 = self._clock()
        series = self._registry.all_series()
        rec = {
            "at": time.time(),
            "series": [
                [name, labels, kind, value, hsum]
                for name, labels, kind, value, hsum in series
            ],
        }
        with self._lock:
            self.ring.append(rec)
            self._samples += 1
            try:
                self._append_locked(rec)
            except OSError:
                pass
        _obs.record_tsdb_sample(
            len(series), max(0.0, self._clock() - t0), registry=self._registry
        )
        if self._evaluator is not None:
            self._evaluator.evaluate_once()
        return rec

    def _append_locked(self, rec: dict) -> None:
        rotated = (
            self._seg_path is None or self._seg_count >= self._segment_records
        )
        if rotated:
            self._rotate_locked()
        with open(self._seg_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        self._seg_count += 1
        # index writes ride rotations (plus stop()), AFTER the new
        # segment's first append so the glob sees it — rewriting the index
        # on every 1 s scrape was a glob+write+replace under the sampler
        # lock that recent()/the gateway block on, for a file whose
        # segment list only changes on rotation
        if rotated:
            self._write_index_locked(rec["at"])

    def _rotate_locked(self) -> None:
        """Open a fresh segment and prune the oldest past the ring bound.
        Segment names are ``seg-<epoch_ms>-<seq>.jsonl`` — lexicographic
        sort IS chronological sort, and the per-process seq disambiguates
        two rotations inside one millisecond."""
        first = self._seg_path is None
        self._seg_seq += 1
        self._seg_path = (
            self.root / f"seg-{int(time.time() * 1000):013d}-{self._seg_seq:04d}.jsonl"
        )
        self._seg_count = 0
        self._own_segs.append(self._seg_path)
        self._own_segs = self._own_segs[-(self._max_segments + 4):]
        segs = sorted(self.root.glob("seg-*.jsonl"))
        own = set(self._own_segs)
        for p in segs[: max(0, len(segs) + 1 - self._max_segments)]:
            try:
                # own segments prune unconditionally (the hard ring bound);
                # a foreign segment gets a recency grace — it may be a
                # concurrent writer's ACTIVE segment
                if (
                    p not in own
                    and time.time() - p.stat().st_mtime < SEGMENT_PRUNE_GRACE_S
                ):
                    continue
                p.unlink()
            except OSError:
                pass
        if not first:
            _obs.record_tsdb_rotation(registry=self._registry)

    def _write_index_locked(self, last_at: float) -> None:
        """A tiny index next to the segments: enough for a reader to know
        the window on disk without parsing every line, accurate as of the
        last rotation (or :meth:`stop`). Best-effort and rewritten in
        place — a torn index never corrupts the segments."""
        try:
            segs = sorted(p.name for p in self.root.glob("seg-*.jsonl"))
            tmp = self.root / f".index.tmp.{os.getpid()}"
            tmp.write_text(json.dumps({
                "segments": segs,
                "last_at": last_at,
                "samples": self._samples,
            }))
            os.replace(tmp, self.root / "index.json")
        except OSError:
            pass

    # -- read surfaces -------------------------------------------------------

    def recent(self, window_s: float | None = None) -> list[dict]:
        """Newest-last ring slice covering the trailing ``window_s``
        wall-clock seconds (None = the whole ring) — the alert evaluator's
        source: no disk read on the evaluation path."""
        with self._lock:
            recs = list(self.ring)
        if window_s is None or not recs:
            return recs
        lo = recs[-1]["at"] - window_s
        return [r for r in recs if r["at"] >= lo]

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "TsdbSampler":
        if self._running:
            return self

        self._running = True

        def loop():
            while self._running:
                try:
                    self.sample_once()
                except Exception:  # never kill the recorder
                    pass
                time.sleep(self.interval)

        self._thread = threading.Thread(
            target=loop, name="tsdb-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            if self.ring:  # final index: last_at/samples exact at shutdown
                self._write_index_locked(self.ring[-1]["at"])


# -- the process-wide sampler (the MTPU_TSDB=1 singleton) ---------------------

_sampler_lock = threading.Lock()
_sampler: TsdbSampler | None = None


def ensure_sampler(
    registry=None, *, interval: float | None = None
) -> TsdbSampler | None:
    """Start the process-wide sampler once (idempotent); returns None when
    :func:`sampling_enabled` says off. ``LLMEngine.__init__`` calls this
    under its resolved-once gate, so any process that builds an engine with
    ``MTPU_TSDB=1`` records history without further wiring."""
    global _sampler
    if not sampling_enabled():
        return None
    with _sampler_lock:
        if _sampler is None:
            _sampler = TsdbSampler(registry=registry, interval=interval).start()
        return _sampler


def global_sampler() -> TsdbSampler | None:
    return _sampler


def stop_sampler() -> None:
    """Stop and forget the process-wide sampler (test isolation)."""
    global _sampler
    with _sampler_lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None


# -- offline reads (jax-free: `tpurun tsdb`, incident bundles) ----------------


def read_window(
    start: float | None = None,
    end: float | None = None,
    *,
    root=None,
    limit: int | None = None,
) -> list[dict]:
    """Scrape records with ``start <= at <= end`` merged across segments,
    oldest first. ``limit`` keeps the NEWEST n records (an incident bundle
    wants the minutes before the event, not the whole ring)."""
    d = tsdb_dir(root)
    out: list[dict] = []
    try:
        segs = sorted(d.glob("seg-*.jsonl"))
    except OSError:
        return out
    for p in segs:
        try:
            text = p.read_text()
        except OSError:
            continue
        for line in text.splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line from the live writer
            at = rec.get("at")
            if not isinstance(at, (int, float)):
                continue
            if start is not None and at < start:
                continue
            if end is not None and at > end:
                continue
            out.append(rec)
    out.sort(key=lambda r: r["at"])
    return out[-limit:] if limit else out


def read_latest(root=None) -> dict | None:
    """The newest scrape record, reading only the newest segment
    (segment names sort chronologically) — the ``tpurun metrics --watch``
    refresh; re-parsing the whole ring every second to display one sample
    would burn a core on the operator's box mid-incident."""
    d = tsdb_dir(root)
    try:
        segs = sorted(d.glob("seg-*.jsonl"), reverse=True)
    except OSError:
        return None
    for p in segs:
        try:
            lines = p.read_text().splitlines()
        except OSError:
            continue
        for line in reversed(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line from the live writer
            if isinstance(rec.get("at"), (int, float)):
                return rec
    return None


def series_names(records: list[dict]) -> list[str]:
    """Distinct series names across the records, sorted."""
    names = set()
    for rec in records:
        for entry in rec.get("series", ()):
            names.add(entry[0])
    return sorted(names)


def _labels_match(stored: dict, want: dict | None) -> bool:
    if not want:
        return True
    return all(stored.get(k) == v for k, v in want.items())


def series_points(
    name: str,
    records: list[dict],
    *,
    labels: dict | None = None,
    agg: str | None = None,
    field: str = "value",
) -> list[tuple[float, float]]:
    """``(at, value)`` points for one series over the records. ``labels``
    is a subset match; multiple matching label sets fold per record by
    ``agg`` (``sum`` for counters/counts, ``max`` for 0..1 gauges — a
    fraction must never sum across replicas, the ``tpurun top`` rule).
    ``agg=None`` picks by the stored series kind: gauges fold by max,
    everything else sums. ``field="sum"`` reads a histogram's cumulative
    sum instead of its count (seconds spent, not events seen)."""
    idx = 4 if field == "sum" else 3
    out: list[tuple[float, float]] = []
    for rec in records:
        vals = []
        fold_max = agg == "max"
        for entry in rec.get("series", ()):
            if entry[0] == name and _labels_match(entry[1], labels):
                vals.append(entry[idx])
                if agg is None and entry[2] == "gauge":
                    fold_max = True
        if vals:
            out.append(
                (rec["at"], max(vals) if fold_max else sum(vals))
            )
    return out


def rate(points: list[tuple[float, float]]) -> float | None:
    """Per-second increase over the points, counter-reset aware: negative
    deltas (a process restart zeroed the counter) contribute the new
    absolute value, the prometheus ``rate()`` convention. None with fewer
    than two points or zero elapsed time."""
    if len(points) < 2:
        return None
    elapsed = points[-1][0] - points[0][0]
    if elapsed <= 0:
        return None
    total = 0.0
    for (_, prev), (_, cur) in zip(points, points[1:]):
        total += (cur - prev) if cur >= prev else cur
    return total / elapsed
