"""Correctness canary: always-on golden-set probing with numeric drift
sentinels (docs/observability.md#correctness-canary).

Every other observability organ is *passive* — it measures whatever
traffic arrives, so a replica that serves fast-but-*wrong* tokens (the
psum/bf16-reordering failure class docs/tensor_parallel.md documents: a
single ulp flips a greedy argmax) is invisible until a user complains.
The canary closes that gap with an *active* probe: a background
:class:`CanaryProber` submits a small pinned golden set — seeded prompts,
greedy sampling, short ``max_tokens`` — through the REAL router/engine
path on every serving replica at ``MTPU_CANARY_INTERVAL``, measures
TTFT/TPOT/e2e from the client's seat into the dedicated canary series
(``mtpu_canary_probes_total`` and friends),
and checks the generated token ids BIT-EXACT against a content-addressed
golden store.

Identity discipline (the benchdiff rule, PR 17): a golden transcript is
only comparable against the exact numeric identity that recorded it.
Golden files live at ``<state_dir>/canary/golden-<model>-<fp>.json``
where ``<fp>`` hashes the backend, chip generation, kv dtype, tensor-
parallel degree, and resolved decode impl plan — so a CPU-recorded golden
can never gate a TPU run, and a TP=1 golden can never gate a TP=2 replica
(cross-TP token exactness is UNDEFINED; those configs fall back to the
documented logit-tolerance contract instead of bit-exact gating). A
stored file whose embedded fingerprint disagrees with the live engine's
raises :class:`CanaryIdentityError` with a loud banner instead of
producing a false drift verdict.

Synthetic-traffic hygiene: probes run as tenant ``__canary__`` in the
dedicated lowest-rank ``canary`` priority class, are excluded from
per-tenant usage billing and the usage journal (counted in
``mtpu_canary_tokens_total`` instead so conservation stays closed), skip
the unlabeled TTFT/TPOT histograms that feed the SLO burn gauges, and are
subtracted from the fleet autoscaler's shed/queue signals — the canary
observes the fleet without steering it.

Drift handling walks the same ladder as the gray-failure watchdog
(docs/health.md): journal the probe, capture a ``canary_drift`` incident
bundle naming the mismatching probe request, and after
``fail_threshold`` consecutive failing rounds down-weight the replica via
``router.set_health_weight`` so a wrong-answer replica loses traffic
before users see it; a passing round restores the weight.

jax-light and engine-lazy: importable without jax (the CLI/gateway read
side), touching jax only inside a probe where an engine already exists.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from pathlib import Path

from .._internal import config as _config
from . import metrics as _obs
from .journal import named_journal

#: the synthetic probe tenant — excluded from usage billing, gates the
#: chaos corruption fault point (engine.canary_token_corrupt)
CANARY_TENANT = "__canary__"
#: the probe priority class (scheduling/policy.py PRIORITY_CLASSES member,
#: lowest rank: probes never starve real traffic)
CANARY_CLASS = "canary"
#: probe-round interval override (seconds)
INTERVAL_ENV = "MTPU_CANARY_INTERVAL"
DEFAULT_INTERVAL_S = 30.0
#: the golden-store directory name under ``<state_dir>``
DIR_NAME = "canary"

#: the pinned golden set: seeded greedy probes, short enough that a full
#: round is a few dozen decode ticks. Prompts are fixed forever — a probe
#: is only comparable to a golden recorded from the SAME prompt/seed/
#: max_tokens triple, so editing one means re-recording every golden.
GOLDEN_SET = (
    {"id": "g0", "prompt": "The quick brown fox", "max_tokens": 8, "seed": 11},
    {"id": "g1", "prompt": "Counting up: one two three", "max_tokens": 8,
     "seed": 23},
    {"id": "g2", "prompt": "A canary in a coal mine", "max_tokens": 8,
     "seed": 37},
)


class CanaryIdentityError(RuntimeError):
    """A golden transcript and a live engine disagree on numeric identity
    (backend/generation/kv_dtype/tp/impl plan) — comparing them would
    produce a false drift verdict, so the store refuses loudly."""


def _backend() -> str:
    try:
        import jax

        return str(jax.default_backend())
    except Exception:
        return "unknown"


def fingerprint(engine) -> dict:
    """The numeric identity a golden transcript is pinned to: everything
    that can legitimately change the bit pattern of a greedy decode."""
    from .usage import resolve_peaks

    plan = dict(getattr(engine, "impl_plan", None) or {})
    return {
        "backend": _backend(),
        "generation": resolve_peaks()["generation"],
        "attention": plan.get("attention"),
        "ragged_variant": plan.get("ragged_variant"),
        "scatter": plan.get("scatter"),
        "kv_dtype": plan.get("kv_dtype", getattr(engine, "kv_dtype", None)),
        "tp": int(plan.get("tp", 1) or 1),
    }


def fingerprint_hash(fp: dict) -> str:
    return hashlib.sha1(
        json.dumps(fp, sort_keys=True).encode()
    ).hexdigest()[:12]


def model_id(cfg) -> str:
    """A compact model identity from the config dims (the engine does not
    know its checkpoint name; two different geometries can never collide)."""
    return (
        f"l{cfg.n_layers}d{cfg.dim}h{cfg.n_heads}"
        f"kv{cfg.n_kv_heads}v{cfg.vocab_size}"
    )


def verify_identity(stored: dict, live: dict) -> None:
    """Refuse a cross-identity comparison with a loud banner naming every
    differing key — the benchdiff discipline, not a tolerance knob."""
    diffs = {
        k: (stored.get(k), live.get(k))
        for k in sorted(set(stored) | set(live))
        if stored.get(k) != live.get(k)
    }
    if not diffs:
        return
    lines = [
        "=" * 66,
        "CANARY IDENTITY REFUSED: golden transcript does not match the",
        "live engine's numeric identity — comparing them would report",
        "false drift. Record a fresh golden for this identity instead.",
    ]
    for k, (s, l) in diffs.items():
        lines.append(f"  {k}: golden={s!r} live={l!r}")
    if stored.get("tp") != live.get("tp"):
        lines.append(
            "  cross-TP token exactness is UNDEFINED (psum/bf16 reordering"
        )
        lines.append(
            "  flips greedy argmaxes) — use the logit-tolerance contract,"
        )
        lines.append("  docs/tensor_parallel.md")
    lines.append("=" * 66)
    raise CanaryIdentityError("\n".join(lines))


class GoldenStore:
    """Content-addressed golden transcripts under ``<state_dir>/canary``.

    One JSON file per (model, fingerprint): the fingerprint is both in the
    file NAME (so two identities never race one path) and in the file BODY
    (so a hand-copied file from another chip still refuses at load)."""

    def __init__(self, root=None):
        self.dir = Path(root or _config.state_dir()) / DIR_NAME

    def path_for(self, model: str, fp: dict) -> Path:
        return self.dir / f"golden-{model}-{fingerprint_hash(fp)}.json"

    def load(self, model: str, fp: dict) -> dict | None:
        """The golden document for this identity, or None when unrecorded.
        Raises :class:`CanaryIdentityError` when the stored fingerprint
        disagrees with ``fp`` (a copied/tampered file)."""
        path = self.path_for(model, fp)
        try:
            doc = json.loads(path.read_text())
        except FileNotFoundError:
            return None
        except (OSError, json.JSONDecodeError) as e:
            raise CanaryIdentityError(
                f"golden store file {path} is unreadable/corrupt: {e}"
            )
        verify_identity(doc.get("fingerprint", {}), fp)
        return doc

    def record(self, model: str, fp: dict, probes: dict) -> Path:
        """Write (atomically) the golden document for this identity.
        ``probes`` maps probe id -> {"tokens": [...], "text": ...}."""
        self.dir.mkdir(parents=True, exist_ok=True)
        path = self.path_for(model, fp)
        doc = {
            "model": model,
            "fingerprint": fp,
            "fp": fingerprint_hash(fp),
            "recorded_at": time.time(),
            "probes": probes,
        }
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(doc, indent=1, sort_keys=True))
        tmp.replace(path)
        return path


def probe_engine(
    engine, *, submit=None, replica: str = "engine", golden: dict | None,
    registry=None, clock=time.monotonic,
) -> list[dict]:
    """Run the full golden set once against one engine and return per-probe
    results. ``submit`` defaults to ``engine.submit`` — the prober passes
    ``replica.submit`` so the probe pays the router's admission path too.

    Without a ``golden`` document every probe reports ``"recorded"`` and
    carries its tokens for :meth:`GoldenStore.record`; with one, tokens are
    compared bit-exact and report ``"pass"`` or ``"drift"``. A probe that
    dies (shed, engine error) reports ``"error"`` — an unreachable replica
    is a health problem, not numeric drift."""
    from ..serving.sampling import SamplingParams

    submit = submit or engine.submit
    results = []
    for g in GOLDEN_SET:
        params = SamplingParams(
            temperature=0.0, max_tokens=g["max_tokens"], seed=g["seed"]
        )
        t0 = clock()
        ttft = None
        gaps = []
        rec: dict = {"probe": g["id"], "replica": replica}
        try:
            req = submit(
                g["prompt"], params, tenant=CANARY_TENANT,
                priority=CANARY_CLASS,
            )
            last = t0
            for _piece in engine.stream(req):
                now = clock()
                if ttft is None:
                    ttft = now - t0
                else:
                    gaps.append(now - last)
                last = now
            e2e = clock() - t0
            tokens = [int(t) for t in req.generated_tokens]
            rec.update(
                request_id=req.request_id,
                finish_reason=req.finish_reason,
                tokens=tokens,
                ttft=ttft, e2e=e2e,
                tpot=(sum(gaps) / len(gaps)) if gaps else None,
            )
            if req.finish_reason not in ("stop", "length"):
                rec["result"] = "error"
            elif golden is None:
                rec["result"] = "recorded"
            else:
                expected = [
                    int(t)
                    for t in golden["probes"][g["id"]]["tokens"]
                ]
                if tokens == expected:
                    rec["result"] = "pass"
                else:
                    rec["result"] = "drift"
                    rec["expected"] = expected
                    rec["mismatch_at"] = next(
                        (
                            i
                            for i, (a, b) in enumerate(zip(tokens, expected))
                            if a != b
                        ),
                        min(len(tokens), len(expected)),
                    )
        except Exception as e:  # shed / engine stopped: health, not drift
            rec.update(result="error", error=f"{type(e).__name__}: {e}")
        _obs.record_canary_probe(replica, rec["result"], registry=registry)
        if rec["result"] == "drift":
            _obs.record_canary_drift(replica, registry=registry)
        if rec.get("e2e") is not None:
            _obs.record_canary_latency(
                replica, ttft=rec.get("ttft"), tpot=rec.get("tpot"),
                e2e=rec.get("e2e"), registry=registry,
            )
        results.append(rec)
    return results


# -- the fleet prober ---------------------------------------------------------

#: the live prober (gateway /canary and tpurun canary read it when the
#: serving process answers its own snapshot) — the incident live-engine
#: registry pattern, single-slot because one process runs one prober
_live_lock = threading.Lock()
_live_prober = None


def live_prober():
    with _live_lock:
        return _live_prober


class CanaryProber:
    """Background golden-set prober over a router's serving replicas.

    Each round probes every healthy non-prefill replica; the first contact
    with a (model, fingerprint) identity records the golden instead of
    gating. Consecutive failing rounds (any drift in the round) walk the
    watchdog's graded ladder: at ``fail_threshold`` the replica is
    down-weighted to ``degraded_weight`` via ``router.set_health_weight``;
    the first passing round restores weight 1.0. Every round lands in the
    ``canary`` journal; every drift captures a ``canary_drift`` incident
    bundle whose reason names the mismatching probe request id, so the
    bundle's open-trace section contains the probe's trace."""

    def __init__(
        self, router, *, interval_s=None, store=None, registry=None,
        journal_path=None, fail_threshold: int = 2,
        degraded_weight: float = 0.25, clock=time.monotonic,
    ):
        if interval_s is None:
            raw = os.environ.get(INTERVAL_ENV, "")
            interval_s = float(raw) if raw else DEFAULT_INTERVAL_S
        self.router = router
        self.interval_s = float(interval_s)
        self.store = store or GoldenStore()
        self.registry = registry
        self.fail_threshold = max(1, int(fail_threshold))
        self.degraded_weight = float(degraded_weight)
        self._clock = clock
        self._journal = named_journal("canary", path=journal_path)
        self._lock = threading.Lock()
        #: replica -> consecutive failing rounds (any drift in the round)
        self._streaks: dict[str, int] = {}
        #: replicas this prober down-weighted (so it only restores its own)
        self._downweighted: set[str] = set()
        #: replica -> last round's per-probe results
        self._last: dict[str, list[dict]] = {}
        self.rounds = 0
        self.drifts = 0
        self._stop = threading.Event()
        self._thread = None

    # -- journal plumbing (the watchdog's "at"-stamped record convention) -----

    def _record(self, **rec) -> None:
        self._journal.record({"at": time.time(), **rec})

    # -- one round ------------------------------------------------------------

    def _serving_replicas(self) -> list:
        return [
            r for r in self.router.replicas
            if getattr(r, "role", "unified") != "prefill" and r.healthy()
        ]

    def probe_replica(self, replica) -> list[dict]:
        engine = replica.engine
        model = model_id(engine.cfg)
        fp = fingerprint(engine)
        golden = self.store.load(model, fp)  # CanaryIdentityError is loud
        results = probe_engine(
            engine, submit=replica.submit, replica=replica.name,
            golden=golden, registry=self.registry, clock=self._clock,
        )
        if golden is None:
            recorded = {
                r["probe"]: {"tokens": r["tokens"]}
                for r in results
                if r["result"] == "recorded"
            }
            if len(recorded) == len(GOLDEN_SET):
                path = self.store.record(model, fp, recorded)
                self._record(
                    action="recorded", replica=replica.name, model=model,
                    fp=fingerprint_hash(fp), path=str(path),
                )
        self._note_round(replica, results)
        return results

    def _note_round(self, replica, results: list[dict]) -> None:
        name = replica.name
        drifted = [r for r in results if r["result"] == "drift"]
        compared = [r for r in results if r["result"] in ("pass", "drift")]
        with self._lock:
            if drifted:
                self.drifts += len(drifted)
                self._streaks[name] = self._streaks.get(name, 0) + 1
            elif compared:
                self._streaks[name] = 0
            streak = self._streaks.get(name, 0)
            self._last[name] = results
        _obs.set_canary_failing(name, streak, registry=self.registry)
        self._record(
            action="round", replica=name, streak=streak,
            results={r["probe"]: r["result"] for r in results},
        )
        if drifted:
            worst = drifted[0]
            # lazy: the capture leg pulls in the tsdb/trace machinery the
            # pure probe path never needs
            from . import incident as _incident

            _incident.capture(
                "canary_drift", replica=name,
                reason=(
                    f"canary probe {worst['probe']} ({worst['request_id']}) "
                    f"drifted at token {worst.get('mismatch_at')} "
                    f"(streak {streak})"
                ),
            )
            if streak >= self.fail_threshold and hasattr(
                self.router, "set_health_weight"
            ):
                self.router.set_health_weight(name, self.degraded_weight)
                with self._lock:
                    self._downweighted.add(name)
                self._record(
                    action="down_weight", replica=name,
                    weight=self.degraded_weight, streak=streak,
                )
        elif compared:
            with self._lock:
                restore = name in self._downweighted
                self._downweighted.discard(name)
            if restore:
                self.router.set_health_weight(name, 1.0)
                self._record(
                    action="restore_weight", replica=name, weight=1.0
                )

    def probe_once(self) -> dict:
        """One full round over every healthy serving replica."""
        per_replica = {}
        for replica in self._serving_replicas():
            try:
                per_replica[replica.name] = self.probe_replica(replica)
            except CanaryIdentityError as e:
                # refusal is a configuration fault, not drift: journal the
                # banner and keep probing the rest of the fleet
                self._record(
                    action="identity_refused", replica=replica.name,
                    error=str(e),
                )
                _obs.record_canary_probe(
                    replica.name, "error", registry=self.registry
                )
        with self._lock:
            self.rounds += 1
        return per_replica

    # -- the background loop --------------------------------------------------

    def start(self):
        global _live_prober
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="canary-prober", daemon=True
        )
        self._thread.start()
        with _live_lock:
            _live_prober = self
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.probe_once()
            except Exception as e:  # a probe round must never kill the loop
                try:
                    self._record(
                        action="round_error",
                        error=f"{type(e).__name__}: {e}",
                    )
                except Exception:
                    pass

    def stop(self):
        global _live_prober
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        with _live_lock:
            if _live_prober is self:
                _live_prober = None

    # -- read side ------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "rounds": self.rounds,
                "drifts": self.drifts,
                "fail_threshold": self.fail_threshold,
                "streaks": dict(self._streaks),
                "downweighted": sorted(self._downweighted),
                "last": {
                    name: [
                        {
                            k: r.get(k)
                            for k in (
                                "probe", "result", "request_id",
                                "mismatch_at", "ttft", "tpot", "e2e",
                            )
                            if r.get(k) is not None
                        }
                        for r in results
                    ]
                    for name, results in self._last.items()
                },
            }
