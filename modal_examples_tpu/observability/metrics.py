"""Recorders: the narrow API the executor and serving engine call to emit
metric series from :mod:`.catalog` into the process-wide prometheus registry
(:mod:`modal_examples_tpu.utils.prometheus`).

Keeping every write behind a named function means call sites stay one line,
label sets can't drift between emitters, and tests can read series back via
``default_registry.value(...)`` with the same constants.
"""

from __future__ import annotations

from ..utils.prometheus import Registry, default_registry
from . import catalog as C


def _reg(registry: Registry | None) -> Registry:
    return registry if registry is not None else default_registry


# -- call lifecycle (executor) ----------------------------------------------


def record_phase(
    function: str, phase: str, seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.CALL_DURATION_SECONDS,
        seconds,
        labels={"function": function, "phase": phase},
        help=C.CATALOG[C.CALL_DURATION_SECONDS]["help"],
    )


def record_queue_wait(
    function: str, seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.QUEUE_WAIT_SECONDS,
        seconds,
        labels={"function": function},
        help=C.CATALOG[C.QUEUE_WAIT_SECONDS]["help"],
    )
    record_phase(function, "queue", seconds, registry=registry)


def set_inflight(
    function: str, n: int, *, registry: Registry | None = None
) -> None:
    _reg(registry).gauge_set(
        C.INFLIGHT_INPUTS,
        float(n),
        labels={"function": function},
        help=C.CATALOG[C.INFLIGHT_INPUTS]["help"],
    )


def record_retry(
    function: str, reason: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.RETRIES_TOTAL,
        1.0,
        labels={"function": function, "reason": reason},
        help=C.CATALOG[C.RETRIES_TOTAL]["help"],
    )


def record_container_kill(
    function: str, reason: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.CONTAINER_KILLS_TOTAL,
        1.0,
        labels={"function": function, "reason": reason},
        help=C.CATALOG[C.CONTAINER_KILLS_TOTAL]["help"],
    )


# -- serving engine ---------------------------------------------------------


def record_engine_phase(
    phase: str, seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.ENGINE_PHASE_SECONDS,
        seconds,
        labels={"phase": phase},
        help=C.CATALOG[C.ENGINE_PHASE_SECONDS]["help"],
    )


def record_engine_batch(n: int, *, registry: Registry | None = None) -> None:
    _reg(registry).histogram_observe(
        C.ENGINE_BATCH_SIZE,
        float(n),
        buckets=C.COUNT_BUCKETS,
        help=C.CATALOG[C.ENGINE_BATCH_SIZE]["help"],
    )


def record_engine_queue_wait(
    seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.ENGINE_QUEUE_WAIT_SECONDS,
        seconds,
        help=C.CATALOG[C.ENGINE_QUEUE_WAIT_SECONDS]["help"],
    )


def set_engine_gauges(
    *,
    waiting: int,
    active_slots: int,
    tokens_per_second: float,
    registry: Registry | None = None,
) -> None:
    reg = _reg(registry)
    reg.gauge_set(
        C.WAITING_REQUESTS, float(waiting),
        help=C.CATALOG[C.WAITING_REQUESTS]["help"],
    )
    reg.gauge_set(
        C.ACTIVE_SLOTS, float(active_slots),
        help=C.CATALOG[C.ACTIVE_SLOTS]["help"],
    )
    reg.gauge_set(
        C.TOKENS_PER_SECOND, tokens_per_second,
        help=C.CATALOG[C.TOKENS_PER_SECOND]["help"],
    )


def set_decode_impl(plan: dict, *, registry: Registry | None = None) -> None:
    """Info gauge for the engine's resolved decode plan: the attention /
    scatter impls, cache dtype, tensor-parallel degree, and the PER-SHARD
    ragged variant (``paged_impl_plan(mesh=...)``) — so dashboards and
    benches report the sharded plan actually run, not the requested one."""
    _reg(registry).gauge_set(
        C.DECODE_IMPL,
        1.0,
        labels={
            "attention": str(plan["attention"]),
            "scatter": str(plan["scatter"]),
            "kv_dtype": str(plan["kv_dtype"]),
            "tp": str(plan.get("tp", 1)),
            "variant": str(plan.get("ragged_variant") or "-"),
        },
        help=C.CATALOG[C.DECODE_IMPL]["help"],
    )


def record_decode_stall(
    seconds: float, *, registry: Registry | None = None
) -> None:
    """One gap between consecutive decode-block dispatches while decodable
    slots existed — the stall-free admission contract's measurement: under
    a prefill budget this stays bounded by ~one prefill chunk."""
    _reg(registry).histogram_observe(
        C.DECODE_STALL_SECONDS,
        seconds,
        buckets=C.TOKEN_TIME_BUCKETS,
        help=C.CATALOG[C.DECODE_STALL_SECONDS]["help"],
    )


def set_prefill_backlog(tokens: int, *, registry: Registry | None = None) -> None:
    _reg(registry).gauge_set(
        C.PREFILL_BACKLOG_TOKENS, float(tokens),
        help=C.CATALOG[C.PREFILL_BACKLOG_TOKENS]["help"],
    )


def record_prefill_sliced(*, registry: Registry | None = None) -> None:
    _reg(registry).counter_inc(
        C.PREFILL_SLICED_TOTAL, 1.0,
        help=C.CATALOG[C.PREFILL_SLICED_TOTAL]["help"],
    )


def record_scheduler_error(*, registry: Registry | None = None) -> None:
    _reg(registry).counter_inc(
        C.SCHEDULER_ERRORS_TOTAL,
        1.0,
        help=C.CATALOG[C.SCHEDULER_ERRORS_TOTAL]["help"],
    )


# -- token-level serving telemetry ------------------------------------------


def record_ttft(seconds: float, *, registry: Registry | None = None) -> None:
    _reg(registry).histogram_observe(
        C.TTFT_SECONDS,
        seconds,
        buckets=C.TOKEN_TIME_BUCKETS,
        help=C.CATALOG[C.TTFT_SECONDS]["help"],
    )


def record_tpot(seconds: float, *, registry: Registry | None = None) -> None:
    _reg(registry).histogram_observe(
        C.TPOT_SECONDS,
        seconds,
        buckets=C.TOKEN_TIME_BUCKETS,
        help=C.CATALOG[C.TPOT_SECONDS]["help"],
    )


def record_token_totals(
    *, prompt: int = 0, generated: int = 0, steps: int = 0,
    registry: Registry | None = None,
) -> None:
    """Increment the prefill-vs-decode token counters (deltas, not totals —
    the engine accumulates and flushes from its gauge-refresh throttle)."""
    reg = _reg(registry)
    if prompt:
        reg.counter_inc(
            C.PROMPT_TOKENS_TOTAL, float(prompt),
            help=C.CATALOG[C.PROMPT_TOKENS_TOTAL]["help"],
        )
    if generated:
        reg.counter_inc(
            C.GENERATED_TOKENS_TOTAL, float(generated),
            help=C.CATALOG[C.GENERATED_TOKENS_TOTAL]["help"],
        )
    if steps:
        reg.counter_inc(
            C.DECODE_STEPS_TOTAL, float(steps),
            help=C.CATALOG[C.DECODE_STEPS_TOTAL]["help"],
        )


# -- request scheduler (modal_examples_tpu/scheduling) -----------------------


def record_shed(
    klass: str, reason: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.SHEDS_TOTAL, 1.0,
        labels={"class": klass, "reason": reason},
        help=C.CATALOG[C.SHEDS_TOTAL]["help"],
    )


def record_admitted(klass: str, *, registry: Registry | None = None) -> None:
    _reg(registry).counter_inc(
        C.REQUESTS_ADMITTED_TOTAL, 1.0,
        labels={"class": klass},
        help=C.CATALOG[C.REQUESTS_ADMITTED_TOTAL]["help"],
    )


def set_sched_queue_depths(
    depths: dict, *, registry: Registry | None = None
) -> None:
    reg = _reg(registry)
    for klass, depth in depths.items():
        reg.gauge_set(
            C.SCHED_QUEUE_DEPTH, float(depth),
            labels={"class": klass},
            help=C.CATALOG[C.SCHED_QUEUE_DEPTH]["help"],
        )


def record_sched_queue_wait(
    klass: str, seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.SCHED_QUEUE_WAIT_SECONDS, seconds,
        labels={"class": klass},
        help=C.CATALOG[C.SCHED_QUEUE_WAIT_SECONDS]["help"],
    )


def set_kv_pages_reserved(n: int, *, registry: Registry | None = None) -> None:
    _reg(registry).gauge_set(
        C.KV_PAGES_RESERVED, float(n),
        help=C.CATALOG[C.KV_PAGES_RESERVED]["help"],
    )


def record_deadline_miss(
    stage: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.DEADLINE_MISSES_TOTAL, 1.0,
        labels={"stage": stage},
        help=C.CATALOG[C.DEADLINE_MISSES_TOTAL]["help"],
    )


def record_router_route(
    route: str, *, affinity_hit: bool = False,
    registry: Registry | None = None,
) -> None:
    reg = _reg(registry)
    reg.counter_inc(
        C.ROUTER_REQUESTS_TOTAL, 1.0,
        labels={"route": route},
        help=C.CATALOG[C.ROUTER_REQUESTS_TOTAL]["help"],
    )
    if affinity_hit:
        reg.counter_inc(
            C.ROUTER_AFFINITY_HITS_TOTAL, 1.0,
            help=C.CATALOG[C.ROUTER_AFFINITY_HITS_TOTAL]["help"],
        )


def record_router_readmission(*, registry: Registry | None = None) -> None:
    _reg(registry).counter_inc(
        C.ROUTER_READMISSIONS_TOTAL, 1.0,
        help=C.CATALOG[C.ROUTER_READMISSIONS_TOTAL]["help"],
    )


# -- fault injection (modal_examples_tpu/faults) ------------------------------


def record_fault_injected(
    point: str, *, registry: Registry | None = None
) -> None:
    """One fired fault point (faults/inject.py). Only FIRES count — a
    reached-but-passing point is free, preserving the zero-cost gate."""
    _reg(registry).counter_inc(
        C.FAULTS_INJECTED_TOTAL, 1.0,
        labels={"point": point},
        help=C.CATALOG[C.FAULTS_INJECTED_TOTAL]["help"],
    )


# -- disaggregated serving (serving/disagg) ----------------------------------


def record_migration(
    result: str, *, pages: int = 0, wire_bytes: int = 0,
    registry: Registry | None = None,
) -> None:
    """One finished migration attempt (result = ok|fallback|aborted); a
    successful one also counts its pages and wire bytes."""
    reg = _reg(registry)
    reg.counter_inc(
        C.DISAGG_MIGRATIONS_TOTAL, 1.0,
        labels={"result": result},
        help=C.CATALOG[C.DISAGG_MIGRATIONS_TOTAL]["help"],
    )
    if pages:
        reg.counter_inc(
            C.DISAGG_PAGES_MIGRATED_TOTAL, float(pages),
            help=C.CATALOG[C.DISAGG_PAGES_MIGRATED_TOTAL]["help"],
        )
    if wire_bytes:
        reg.counter_inc(
            C.DISAGG_MIGRATION_BYTES_TOTAL, float(wire_bytes),
            help=C.CATALOG[C.DISAGG_MIGRATION_BYTES_TOTAL]["help"],
        )


def record_migration_seconds(
    seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.DISAGG_MIGRATION_SECONDS, seconds,
        help=C.CATALOG[C.DISAGG_MIGRATION_SECONDS]["help"],
    )


def set_migrations_inflight(
    n: int, *, registry: Registry | None = None
) -> None:
    _reg(registry).gauge_set(
        C.DISAGG_MIGRATIONS_INFLIGHT, float(n),
        help=C.CATALOG[C.DISAGG_MIGRATIONS_INFLIGHT]["help"],
    )


def record_disagg_chunk_retries(
    n: int, *, registry: Registry | None = None
) -> None:
    if n > 0:
        _reg(registry).counter_inc(
            C.DISAGG_CHUNK_RETRIES_TOTAL, float(n),
            help=C.CATALOG[C.DISAGG_CHUNK_RETRIES_TOTAL]["help"],
        )


def set_replica_role(
    replica: str, role: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).gauge_set(
        C.REPLICA_ROLE, 1.0,
        labels={"replica": replica, "role": role},
        help=C.CATALOG[C.REPLICA_ROLE]["help"],
    )


# -- in-flight request failover (serving/failover.py) -------------------------


def record_failover(
    mode: str, result: str, *, tokens_replayed: int = 0,
    registry: Registry | None = None,
) -> None:
    """One in-flight takeover attempt (mode=reactive|migrate); a reactive
    resume also counts the generated-prefix tokens it re-prefilled."""
    reg = _reg(registry)
    reg.counter_inc(
        C.FAILOVER_TOTAL, 1.0,
        labels={"mode": mode, "result": result},
        help=C.CATALOG[C.FAILOVER_TOTAL]["help"],
    )
    if tokens_replayed:
        reg.counter_inc(
            C.FAILOVER_TOKENS_REPLAYED_TOTAL, float(tokens_replayed),
            help=C.CATALOG[C.FAILOVER_TOKENS_REPLAYED_TOTAL]["help"],
        )


def record_failover_takeover(
    seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.FAILOVER_TAKEOVER_SECONDS, seconds,
        buckets=C.TOKEN_TIME_BUCKETS,
        help=C.CATALOG[C.FAILOVER_TAKEOVER_SECONDS]["help"],
    )


def record_live_migration(
    result: str, *, tokens: int = 0, registry: Registry | None = None
) -> None:
    """One proactive live migration of a mid-decode request; a successful
    one counts the decode tokens it carried (fleet.jsonl's
    ``tokens_migrated`` source)."""
    reg = _reg(registry)
    reg.counter_inc(
        C.MIGRATION_LIVE_TOTAL, 1.0,
        labels={"result": result},
        help=C.CATALOG[C.MIGRATION_LIVE_TOTAL]["help"],
    )
    if tokens:
        reg.counter_inc(
            C.MIGRATION_LIVE_TOKENS_TOTAL, float(tokens),
            help=C.CATALOG[C.MIGRATION_LIVE_TOKENS_TOTAL]["help"],
        )


def record_live_migration_seconds(
    seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.MIGRATION_LIVE_SECONDS, seconds,
        help=C.CATALOG[C.MIGRATION_LIVE_SECONDS]["help"],
    )


def record_tier_hit(
    tier: str, *, n: int = 1, registry: Registry | None = None
) -> None:
    """``n`` prefix PAGES served from ``tier`` — page units on every tier
    (hbm counts the trie-shared pages of a claim, host/volume count
    promoted pages), so the per-tier rates are comparable fractions."""
    _reg(registry).counter_inc(
        C.PREFIX_TIER_HITS_TOTAL, float(n),
        labels={"tier": tier},
        help=C.CATALOG[C.PREFIX_TIER_HITS_TOTAL]["help"],
    )


def set_tier_occupancy(
    tier: str, *, pages: int, total_bytes: int,
    registry: Registry | None = None,
) -> None:
    reg = _reg(registry)
    reg.gauge_set(
        C.PREFIX_TIER_PAGES, float(pages),
        labels={"tier": tier},
        help=C.CATALOG[C.PREFIX_TIER_PAGES]["help"],
    )
    reg.gauge_set(
        C.PREFIX_TIER_BYTES, float(total_bytes),
        labels={"tier": tier},
        help=C.CATALOG[C.PREFIX_TIER_BYTES]["help"],
    )


# -- shared prefix store (serving/prefix_store/, docs/prefix_store.md) --------


def record_prefix_store_hit(
    origin: str, *, n: int = 1, registry: Registry | None = None
) -> None:
    """``n`` blocks served by the fleet-shared store; ``origin`` is
    ``"self"`` (this replica's own spill) or ``"peer"`` (another
    replica's — the cross-replica warmth the store exists for)."""
    _reg(registry).counter_inc(
        C.PREFIX_STORE_HITS_TOTAL, float(n),
        labels={"origin": origin},
        help=C.CATALOG[C.PREFIX_STORE_HITS_TOTAL]["help"],
    )


def record_prefix_store_miss(
    *, n: int = 1, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.PREFIX_STORE_MISSES_TOTAL, float(n),
        help=C.CATALOG[C.PREFIX_STORE_MISSES_TOTAL]["help"],
    )


def set_prefix_store_occupancy(
    *, total_bytes: int, dedup_ratio: float,
    registry: Registry | None = None,
) -> None:
    reg = _reg(registry)
    reg.gauge_set(
        C.PREFIX_STORE_BYTES, float(total_bytes),
        help=C.CATALOG[C.PREFIX_STORE_BYTES]["help"],
    )
    reg.gauge_set(
        C.PREFIX_STORE_DEDUP_RATIO, float(dedup_ratio),
        help=C.CATALOG[C.PREFIX_STORE_DEDUP_RATIO]["help"],
    )


def record_prefix_store_takeover(
    *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.PREFIX_STORE_OWNER_TAKEOVERS_TOTAL, 1.0,
        help=C.CATALOG[C.PREFIX_STORE_OWNER_TAKEOVERS_TOTAL]["help"],
    )


# -- hot-path profiler (observability/profiler.py) ----------------------------


def record_tick_phase(
    phase: str, seconds: float, *, registry: Registry | None = None
) -> None:
    """One scheduler tick's host time attributed to ``phase`` (a
    ``catalog.TICK_PHASES`` member, or ``"total"`` for the whole tick).
    Called only by the hot-path profiler — with MTPU_PROFILE unset nothing
    reaches here (the zero-cost gate)."""
    _reg(registry).histogram_observe(
        C.TICK_PHASE_SECONDS,
        seconds,
        labels={"phase": phase},
        buckets=C.TICK_PHASE_BUCKETS,
        help=C.CATALOG[C.TICK_PHASE_SECONDS]["help"],
    )


def set_host_overhead_ratio(
    ratio: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).gauge_set(
        C.HOST_OVERHEAD_RATIO, float(ratio),
        help=C.CATALOG[C.HOST_OVERHEAD_RATIO]["help"],
    )


def record_compile(
    program: str, seconds: float, cache_hit: bool, *,
    registry: Registry | None = None,
) -> None:
    """One program-cache lookup at a jit dispatch site: every lookup
    counts under its outcome label; only misses (fresh builds) carry a
    build-seconds observation."""
    reg = _reg(registry)
    reg.counter_inc(
        C.COMPILES_TOTAL, 1.0,
        labels={"program": program, "cache": "hit" if cache_hit else "miss"},
        help=C.CATALOG[C.COMPILES_TOTAL]["help"],
    )
    if not cache_hit:
        reg.histogram_observe(
            C.COMPILE_SECONDS, seconds,
            labels={"program": program},
            help=C.CATALOG[C.COMPILE_SECONDS]["help"],
        )


# -- flight recorder (observability/timeseries.py / alerts.py / incident.py) --


def record_tsdb_sample(
    series: int, seconds: float, *, registry: Registry | None = None
) -> None:
    """One sampler scrape cycle: the series count it captured and the wall
    time it cost. Called only by the tsdb sampler — with MTPU_TSDB unset
    nothing reaches here (the zero-cost gate)."""
    reg = _reg(registry)
    reg.counter_inc(
        C.TSDB_SAMPLES_TOTAL, 1.0,
        help=C.CATALOG[C.TSDB_SAMPLES_TOTAL]["help"],
    )
    reg.gauge_set(
        C.TSDB_SERIES, float(series),
        help=C.CATALOG[C.TSDB_SERIES]["help"],
    )
    reg.histogram_observe(
        C.TSDB_SCRAPE_SECONDS, seconds,
        # µs-scale buckets (the tick-phase rationale): a scrape costs
        # well under a millisecond — default buckets would collapse every
        # observation into their first bound
        buckets=C.TICK_PHASE_BUCKETS,
        help=C.CATALOG[C.TSDB_SCRAPE_SECONDS]["help"],
    )


def record_tsdb_rotation(*, registry: Registry | None = None) -> None:
    _reg(registry).counter_inc(
        C.TSDB_ROTATIONS_TOTAL, 1.0,
        help=C.CATALOG[C.TSDB_ROTATIONS_TOTAL]["help"],
    )


def set_alert_active(
    rule: str, firing: bool, *, registry: Registry | None = None
) -> None:
    _reg(registry).gauge_set(
        C.ALERTS_ACTIVE, 1.0 if firing else 0.0,
        labels={"rule": rule},
        help=C.CATALOG[C.ALERTS_ACTIVE]["help"],
    )


def record_alert_fired(
    rule: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.ALERTS_FIRED_TOTAL, 1.0,
        labels={"rule": rule},
        help=C.CATALOG[C.ALERTS_FIRED_TOTAL]["help"],
    )


def record_incident_captured(
    trigger: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.INCIDENTS_CAPTURED_TOTAL, 1.0,
        labels={"trigger": trigger},
        help=C.CATALOG[C.INCIDENTS_CAPTURED_TOTAL]["help"],
    )


# -- gray-failure watchdog (serving/health.py) --------------------------------


def set_watchdog_state(
    replica: str, state: str, active: bool, *,
    registry: Registry | None = None,
) -> None:
    """One cell of the one-hot per-replica classification gauge — callers
    sweep every state so exactly one reads 1 (stale states read 0, never
    linger at their old value)."""
    _reg(registry).gauge_set(
        C.WATCHDOG_REPLICA_STATE, 1.0 if active else 0.0,
        labels={"replica": replica, "state": state},
        help=C.CATALOG[C.WATCHDOG_REPLICA_STATE]["help"],
    )


def set_watchdog_progress_age(
    replica: str, seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).gauge_set(
        C.WATCHDOG_PROGRESS_AGE_SECONDS, float(seconds),
        labels={"replica": replica},
        help=C.CATALOG[C.WATCHDOG_PROGRESS_AGE_SECONDS]["help"],
    )


def record_watchdog_transition(
    state: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.WATCHDOG_TRANSITIONS_TOTAL, 1.0,
        labels={"state": state},
        help=C.CATALOG[C.WATCHDOG_TRANSITIONS_TOTAL]["help"],
    )


def record_watchdog_recovery(
    action: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.WATCHDOG_RECOVERIES_TOTAL, 1.0,
        labels={"action": action},
        help=C.CATALOG[C.WATCHDOG_RECOVERIES_TOTAL]["help"],
    )


# -- resource occupancy ------------------------------------------------------


def set_kv_occupancy(
    *, used: int, free: int, total_usable: int,
    registry: Registry | None = None,
) -> None:
    """KV page-allocator occupancy (``total_usable`` excludes the reserved
    trash page). Emitted by the allocator on alloc/free — per-request, not
    per-token, frequency."""
    reg = _reg(registry)
    reg.gauge_set(
        C.KV_PAGES_USED, float(used),
        help=C.CATALOG[C.KV_PAGES_USED]["help"],
    )
    reg.gauge_set(
        C.KV_PAGES_FREE, float(free),
        help=C.CATALOG[C.KV_PAGES_FREE]["help"],
    )
    reg.gauge_set(
        C.KV_PAGE_OCCUPANCY,
        used / total_usable if total_usable else 0.0,
        help=C.CATALOG[C.KV_PAGE_OCCUPANCY]["help"],
    )


def set_kv_cache_bytes(
    total_bytes: int, dtype: str, *, registry: Registry | None = None
) -> None:
    """Total HBM bytes of the paged KV cache arrays, labeled by the page
    dtype ("bfloat16" | "int8" | ...). Dtype-aware (int8 counts the int8
    payload + f32 scale rows), so the gauge shows the ~2x footprint
    headroom the quantized cache buys (docs/kv_cache.md)."""
    _reg(registry).gauge_set(
        C.KV_CACHE_BYTES, float(total_bytes), labels={"dtype": dtype},
        help=C.CATALOG[C.KV_CACHE_BYTES]["help"],
    )


def set_prefix_cache_pages(
    cached_pages: int, *, registry: Registry | None = None
) -> None:
    _reg(registry).gauge_set(
        C.PREFIX_CACHED_PAGES, float(cached_pages),
        help=C.CATALOG[C.PREFIX_CACHED_PAGES]["help"],
    )


def record_prefix_evictions(
    n: int, *, registry: Registry | None = None
) -> None:
    if n > 0:
        _reg(registry).counter_inc(
            C.PREFIX_CACHE_EVICTIONS_TOTAL, float(n),
            help=C.CATALOG[C.PREFIX_CACHE_EVICTIONS_TOTAL]["help"],
        )


def set_snapshot_store_size(
    *, entries: int, total_bytes: int, registry: Registry | None = None
) -> None:
    reg = _reg(registry)
    reg.gauge_set(
        C.SNAPSHOT_STORE_ENTRIES, float(entries),
        help=C.CATALOG[C.SNAPSHOT_STORE_ENTRIES]["help"],
    )
    reg.gauge_set(
        C.SNAPSHOT_STORE_BYTES, float(total_bytes),
        help=C.CATALOG[C.SNAPSHOT_STORE_BYTES]["help"],
    )


def record_snapshot_store_get(
    result: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.SNAPSHOT_STORE_GETS_TOTAL, 1.0,
        labels={"result": result},
        help=C.CATALOG[C.SNAPSHOT_STORE_GETS_TOTAL]["help"],
    )


def sample_host_rss(*, registry: Registry | None = None) -> float | None:
    """Current process RSS in bytes into the gauge (Linux: /proc/self/statm;
    silently a no-op elsewhere). Returns the sampled value."""
    import os as _os

    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        rss = rss_pages * _os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None
    _reg(registry).gauge_set(
        C.HOST_RSS_BYTES, float(rss),
        help=C.CATALOG[C.HOST_RSS_BYTES]["help"],
    )
    return float(rss)


# -- autoscaler --------------------------------------------------------------


def record_scaler_decision(
    function: str, action: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.SCALER_DECISIONS_TOTAL, 1.0,
        labels={"function": function, "action": action},
        help=C.CATALOG[C.SCALER_DECISIONS_TOTAL]["help"],
    )


# -- fleet autoscaler (modal_examples_tpu/fleet) ------------------------------


def set_fleet_replicas(
    role: str, n: int, *, registry: Registry | None = None
) -> None:
    _reg(registry).gauge_set(
        C.FLEET_REPLICAS, float(n),
        labels={"role": role},
        help=C.CATALOG[C.FLEET_REPLICAS]["help"],
    )


def record_fleet_decision(
    action: str, trigger: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.FLEET_DECISIONS_TOTAL, 1.0,
        labels={"action": action, "trigger": trigger},
        help=C.CATALOG[C.FLEET_DECISIONS_TOTAL]["help"],
    )


def record_fleet_boot(
    seconds: float, boot: str, *, registry: Registry | None = None
) -> None:
    """One replica build+start at scale-out; ``boot`` says whether the
    params came back from a memory snapshot (``warm``) or full init
    (``cold``) — the near-instant-scale-out evidence."""
    _reg(registry).histogram_observe(
        C.FLEET_BOOT_SECONDS, seconds,
        labels={"boot": boot},
        help=C.CATALOG[C.FLEET_BOOT_SECONDS]["help"],
    )


# -- roofline / usage accounting (observability/usage.py) ---------------------


def set_roofline(
    phase: str, *, mfu: float, mbu: float, tflops: float,
    registry: Registry | None = None,
) -> None:
    """One phase's roofline position (``catalog.ROOFLINE_PHASES``): MFU and
    MBU as 0..1 fractions of the resolved generation's peaks, plus the
    absolute achieved TFLOP/s. Called from the usage meter's throttled
    flush — never per token."""
    reg = _reg(registry)
    reg.gauge_set(
        C.MFU, float(mfu),
        labels={"phase": phase},
        help=C.CATALOG[C.MFU]["help"],
    )
    reg.gauge_set(
        C.HBM_BW_UTIL, float(mbu),
        labels={"phase": phase},
        help=C.CATALOG[C.HBM_BW_UTIL]["help"],
    )
    reg.gauge_set(
        C.ACHIEVED_TFLOPS, float(tflops),
        labels={"phase": phase},
        help=C.CATALOG[C.ACHIEVED_TFLOPS]["help"],
    )


def record_usage_tokens(
    tenant: str, klass: str, *, prompt: int = 0, generated: int = 0,
    registry: Registry | None = None,
) -> None:
    """Per-tenant/class token counters (deltas, not totals — the usage
    meter accumulates and flushes from the engine's gauge-refresh
    throttle, the ``record_token_totals`` pattern)."""
    reg = _reg(registry)
    if prompt:
        reg.counter_inc(
            C.USAGE_PROMPT_TOKENS_TOTAL, float(prompt),
            labels={"tenant": tenant, "class": klass},
            help=C.CATALOG[C.USAGE_PROMPT_TOKENS_TOTAL]["help"],
        )
    if generated:
        reg.counter_inc(
            C.USAGE_GENERATED_TOKENS_TOTAL, float(generated),
            labels={"tenant": tenant, "class": klass},
            help=C.CATALOG[C.USAGE_GENERATED_TOKENS_TOTAL]["help"],
        )


def record_usage_seconds(
    tenant: str, klass: str, *, device_seconds: float = 0.0,
    kv_page_seconds: float = 0.0, registry: Registry | None = None,
) -> None:
    """Per-tenant residency deltas: slot-occupancy seconds and KV
    page-seconds (pages held x hold time), flushed with the token deltas."""
    reg = _reg(registry)
    if device_seconds > 0:
        reg.counter_inc(
            C.USAGE_DEVICE_SECONDS_TOTAL, float(device_seconds),
            labels={"tenant": tenant, "class": klass},
            help=C.CATALOG[C.USAGE_DEVICE_SECONDS_TOTAL]["help"],
        )
    if kv_page_seconds > 0:
        reg.counter_inc(
            C.USAGE_KV_PAGE_SECONDS_TOTAL, float(kv_page_seconds),
            labels={"tenant": tenant, "class": klass},
            help=C.CATALOG[C.USAGE_KV_PAGE_SECONDS_TOTAL]["help"],
        )


def record_usage_shed(
    tenant: str, klass: str, *, registry: Registry | None = None
) -> None:
    """One admission shed charged to the rejected tenant (the per-tenant
    split of ``record_shed`` — sheds are rare, so this one is immediate,
    not delta-flushed)."""
    _reg(registry).counter_inc(
        C.USAGE_SHEDS_TOTAL, 1.0,
        labels={"tenant": tenant, "class": klass},
        help=C.CATALOG[C.USAGE_SHEDS_TOTAL]["help"],
    )


def record_canary_probe(
    replica: str, result: str, *, registry: Registry | None = None
) -> None:
    """One completed golden-set probe (result=pass|drift|error|recorded)."""
    _reg(registry).counter_inc(
        C.CANARY_PROBES_TOTAL, 1.0,
        labels={"replica": replica, "result": result},
        help=C.CATALOG[C.CANARY_PROBES_TOTAL]["help"],
    )


def record_canary_drift(
    replica: str, *, registry: Registry | None = None
) -> None:
    """One probe whose tokens diverged from the golden transcript."""
    _reg(registry).counter_inc(
        C.CANARY_DRIFT_TOTAL, 1.0,
        labels={"replica": replica},
        help=C.CATALOG[C.CANARY_DRIFT_TOTAL]["help"],
    )


def record_canary_latency(
    replica: str, *, ttft: float | None = None, tpot: float | None = None,
    e2e: float | None = None, registry: Registry | None = None,
) -> None:
    """Client-observed probe latencies — measured from the canary's side
    of the stream, so they price the full router/engine path, not just the
    decode tick."""
    reg = _reg(registry)
    labels = {"replica": replica}
    if ttft is not None:
        reg.histogram_observe(
            C.CANARY_TTFT_SECONDS, float(ttft), labels=labels,
            buckets=C.TOKEN_TIME_BUCKETS,
            help=C.CATALOG[C.CANARY_TTFT_SECONDS]["help"],
        )
    if tpot is not None:
        reg.histogram_observe(
            C.CANARY_TPOT_SECONDS, float(tpot), labels=labels,
            buckets=C.TOKEN_TIME_BUCKETS,
            help=C.CATALOG[C.CANARY_TPOT_SECONDS]["help"],
        )
    if e2e is not None:
        reg.histogram_observe(
            C.CANARY_E2E_SECONDS, float(e2e), labels=labels,
            buckets=C.TOKEN_TIME_BUCKETS,
            help=C.CATALOG[C.CANARY_E2E_SECONDS]["help"],
        )


def record_canary_tokens(
    replica: str, *, prompt: int = 0, generated: int = 0,
    registry: Registry | None = None,
) -> None:
    """Synthetic canary token deltas — the conservation-closing partner of
    the per-tenant usage counters the canary tenant is excluded from."""
    reg = _reg(registry)
    if prompt:
        reg.counter_inc(
            C.CANARY_TOKENS_TOTAL, float(prompt),
            labels={"replica": replica, "kind": "prompt"},
            help=C.CATALOG[C.CANARY_TOKENS_TOTAL]["help"],
        )
    if generated:
        reg.counter_inc(
            C.CANARY_TOKENS_TOTAL, float(generated),
            labels={"replica": replica, "kind": "generated"},
            help=C.CATALOG[C.CANARY_TOKENS_TOTAL]["help"],
        )


def set_canary_failing(
    replica: str, streak: int, *, registry: Registry | None = None
) -> None:
    """Consecutive failing canary rounds (0 clears)."""
    _reg(registry).gauge_set(
        C.CANARY_FAILING, float(streak),
        labels={"replica": replica},
        help=C.CATALOG[C.CANARY_FAILING]["help"],
    )


def record_multistep_dispatch(
    *, tokens: int, steps_saved: int = 0, registry: Registry | None = None
) -> None:
    """One harvested decode dispatch: ``tokens`` accepted across its
    slots, ``steps_saved`` whole macro-steps the on-device early-exit
    skipped (0 on the classic one-block path — both paths report here so
    tokens-per-dispatch is one series across the A/B bench arms)."""
    reg = _reg(registry)
    reg.counter_inc(
        C.MULTISTEP_DISPATCHES_TOTAL, 1.0,
        help=C.CATALOG[C.MULTISTEP_DISPATCHES_TOTAL]["help"],
    )
    if tokens:
        reg.counter_inc(
            C.MULTISTEP_TOKENS_TOTAL, float(tokens),
            help=C.CATALOG[C.MULTISTEP_TOKENS_TOTAL]["help"],
        )
    if steps_saved:
        reg.counter_inc(
            C.MULTISTEP_EARLY_EXIT_STEPS_TOTAL, float(steps_saved),
            help=C.CATALOG[C.MULTISTEP_EARLY_EXIT_STEPS_TOTAL]["help"],
        )


def set_multistep_gauges(
    *, decode_steps: int, tokens_per_dispatch: float,
    detok_queue_depth: int, registry: Registry | None = None,
) -> None:
    """Macro-step runtime gauges, refreshed with the engine's gauge sweep."""
    reg = _reg(registry)
    reg.gauge_set(
        C.MULTISTEP_DECODE_STEPS, float(decode_steps),
        help=C.CATALOG[C.MULTISTEP_DECODE_STEPS]["help"],
    )
    reg.gauge_set(
        C.MULTISTEP_TOKENS_PER_DISPATCH, float(tokens_per_dispatch),
        help=C.CATALOG[C.MULTISTEP_TOKENS_PER_DISPATCH]["help"],
    )
    reg.gauge_set(
        C.MULTISTEP_DETOK_QUEUE_DEPTH, float(detok_queue_depth),
        help=C.CATALOG[C.MULTISTEP_DETOK_QUEUE_DEPTH]["help"],
    )


def set_spec_gauges(
    *, gamma: float, tokens_per_dispatch: float, acceptance_rate: float,
    registry: Registry | None = None,
) -> None:
    """Fused speculative-round gauges (docs/speculative.md#series),
    refreshed with the engine's gauge sweep. ``gamma`` is the p50 of the
    per-slot depths actually dispatched over the window — the adaptive
    controller's output, not the configured cap."""
    reg = _reg(registry)
    reg.gauge_set(
        C.SPEC_GAMMA, float(gamma),
        help=C.CATALOG[C.SPEC_GAMMA]["help"],
    )
    reg.gauge_set(
        C.SPEC_TOKENS_PER_DISPATCH, float(tokens_per_dispatch),
        help=C.CATALOG[C.SPEC_TOKENS_PER_DISPATCH]["help"],
    )
    reg.gauge_set(
        C.SPEC_ACCEPTANCE_RATE, float(acceptance_rate),
        help=C.CATALOG[C.SPEC_ACCEPTANCE_RATE]["help"],
    )


def record_spec_fallback(
    n: int = 1, *, registry: Registry | None = None
) -> None:
    """Whole spec rounds that fell through to the classic block program
    (every live lane at γ=0 — collapse, pressure, or temp>0 lanes)."""
    if n <= 0:
        return
    _reg(registry).counter_inc(
        C.SPEC_FALLBACK_TOTAL, float(n),
        help=C.CATALOG[C.SPEC_FALLBACK_TOTAL]["help"],
    )
