"""Recorders: the narrow API the executor and serving engine call to emit
metric series from :mod:`.catalog` into the process-wide prometheus registry
(:mod:`modal_examples_tpu.utils.prometheus`).

Keeping every write behind a named function means call sites stay one line,
label sets can't drift between emitters, and tests can read series back via
``default_registry.value(...)`` with the same constants.
"""

from __future__ import annotations

from ..utils.prometheus import Registry, default_registry
from . import catalog as C


def _reg(registry: Registry | None) -> Registry:
    return registry if registry is not None else default_registry


# -- call lifecycle (executor) ----------------------------------------------


def record_phase(
    function: str, phase: str, seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.CALL_DURATION_SECONDS,
        seconds,
        labels={"function": function, "phase": phase},
        help=C.CATALOG[C.CALL_DURATION_SECONDS]["help"],
    )


def record_queue_wait(
    function: str, seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.QUEUE_WAIT_SECONDS,
        seconds,
        labels={"function": function},
        help=C.CATALOG[C.QUEUE_WAIT_SECONDS]["help"],
    )
    record_phase(function, "queue", seconds, registry=registry)


def set_inflight(
    function: str, n: int, *, registry: Registry | None = None
) -> None:
    _reg(registry).gauge_set(
        C.INFLIGHT_INPUTS,
        float(n),
        labels={"function": function},
        help=C.CATALOG[C.INFLIGHT_INPUTS]["help"],
    )


def record_retry(
    function: str, reason: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.RETRIES_TOTAL,
        1.0,
        labels={"function": function, "reason": reason},
        help=C.CATALOG[C.RETRIES_TOTAL]["help"],
    )


def record_container_kill(
    function: str, reason: str, *, registry: Registry | None = None
) -> None:
    _reg(registry).counter_inc(
        C.CONTAINER_KILLS_TOTAL,
        1.0,
        labels={"function": function, "reason": reason},
        help=C.CATALOG[C.CONTAINER_KILLS_TOTAL]["help"],
    )


# -- serving engine ---------------------------------------------------------


def record_engine_phase(
    phase: str, seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.ENGINE_PHASE_SECONDS,
        seconds,
        labels={"phase": phase},
        help=C.CATALOG[C.ENGINE_PHASE_SECONDS]["help"],
    )


def record_engine_batch(n: int, *, registry: Registry | None = None) -> None:
    _reg(registry).histogram_observe(
        C.ENGINE_BATCH_SIZE,
        float(n),
        buckets=C.COUNT_BUCKETS,
        help=C.CATALOG[C.ENGINE_BATCH_SIZE]["help"],
    )


def record_engine_queue_wait(
    seconds: float, *, registry: Registry | None = None
) -> None:
    _reg(registry).histogram_observe(
        C.ENGINE_QUEUE_WAIT_SECONDS,
        seconds,
        help=C.CATALOG[C.ENGINE_QUEUE_WAIT_SECONDS]["help"],
    )


def set_engine_gauges(
    *,
    waiting: int,
    active_slots: int,
    tokens_per_second: float,
    registry: Registry | None = None,
) -> None:
    reg = _reg(registry)
    reg.gauge_set(
        C.WAITING_REQUESTS, float(waiting),
        help=C.CATALOG[C.WAITING_REQUESTS]["help"],
    )
    reg.gauge_set(
        C.ACTIVE_SLOTS, float(active_slots),
        help=C.CATALOG[C.ACTIVE_SLOTS]["help"],
    )
    reg.gauge_set(
        C.TOKENS_PER_SECOND, tokens_per_second,
        help=C.CATALOG[C.TOKENS_PER_SECOND]["help"],
    )


def record_scheduler_error(*, registry: Registry | None = None) -> None:
    _reg(registry).counter_inc(
        C.SCHEDULER_ERRORS_TOTAL,
        1.0,
        help=C.CATALOG[C.SCHEDULER_ERRORS_TOTAL]["help"],
    )
