"""Hot-path time attribution: per-tick host/device phase accounting and
compile telemetry for the serving engine (docs/observability.md).

ROADMAP #3 claims the biggest remaining throughput lever is amortizing the
per-token host overhead — one Python tick of dispatch/harvest/detokenize
per generated token — and ROADMAP #1 needs the ≥40-slot compile-helper
ceiling diagnosable offline. Neither was measurable: request traces show
WHERE a request went, progress watermarks show THAT the scheduler moves,
but nothing attributed where a scheduler tick's time actually goes or
recorded when/what XLA compiles. This module is that instrument — the
measurement foundation every subsequent perf PR (multi-step decode, spec
adaptivity) is judged against.

Three legs:

- **Tick anatomy** — the scheduler thread accounts each ``step()`` into
  named phases (:data:`~.catalog.TICK_PHASES`) via monotonic deltas on the
  engine's injectable clock: :meth:`HotPathProfiler.begin_tick` hands the
  tick a :class:`TickProfile`, the engine's ``_tm(tick, "phase")`` helper
  closes intervals into phases, and :meth:`~HotPathProfiler.end_tick`
  aggregates busy ticks into a ring buffer plus the
  ``mtpu_tick_phase_seconds{phase}`` histograms. Blocking device reads
  mark with ``device=True``, so the ring carries a host-vs-device split
  and the ``mtpu_host_overhead_ratio`` gauge falls out: 1 - device-blocked
  over total — the number the multi-step decode loop must shrink.
- **Compile telemetry** — every jitted-program build site reports through
  ONE chokepoint, :meth:`~HotPathProfiler.note_compile`: first dispatch of
  a (program, shape_key) is timed (``mtpu_compile_seconds{program}``,
  ``mtpu_compiles_total{program,cache="miss"}``) and appended to the
  ``<state_dir>/compiles.jsonl`` ledger (the journal pattern); later
  dispatches count as cache hits. The ledger writes a ``begin`` event
  BEFORE the build and an ``end`` event after — so a compile helper that
  crashes or hangs mid-build (the ≥40-slot ceiling) leaves a
  begin-without-end row naming exactly which program/shape killed it,
  diagnosable offline from the ledger alone.
- **Surfaces** — ``tpurun profile`` (phase table, host fraction, top
  compiles), the gateway's ``/profile`` route, Perfetto counter tracks +
  compile slices merged into the replica-aware trace export, and the
  BENCH ``overhead`` section via :meth:`~HotPathProfiler.overhead_summary`.

**Zero-cost when disabled** (the ``faults/inject.py`` gate pattern):
``LLMEngine.__init__`` resolves ``MTPU_PROFILE`` ONCE (explicit arg beats
env beats off) and keeps ``self.profiler = None`` when off — every hot-path
touch point is then a ``tick is None`` branch with no timestamp, no
allocation, no dict write. ``tests/test_profiler.py`` pins the no-op shape
at the AST level like the faults gate.

jax-free and import-light: ``observability/`` is imported by the jax-free
``core/`` layer, and ``tpurun profile`` must not attach a chip to render a
ledger.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque

from ..utils.stats import percentile_nearest_rank as _pct
from . import catalog as C
from . import metrics as _obs
from .journal import JOURNALS, DecisionJournal, named_journal

#: the one env switch (resolved once in ``LLMEngine.__init__``, the
#: MTPU_KV_DTYPE rule): unset/0 = off — bench configs opt in explicitly
PROFILE_ENV = "MTPU_PROFILE"

#: busy ticks retained in the in-memory ring (per profiler)
RING_TICKS = 512
#: completed compile records retained in memory for the Perfetto export
#: (the JSONL ledger is the unbounded-ish superset)
COMPILE_LOG_KEEP = 256
#: refresh the host-overhead gauge every N busy ticks (a gauge write per
#: tick would be pure lock traffic for a value that moves slowly)
_GAUGE_EVERY = 32

#: the ledger file name under ``<state_dir>`` — owned by the
#: ``JOURNALS`` table (journal.py) and resolved through
#: ``named_journal("compiles")``; re-exported here for readers
LEDGER_NAME = JOURNALS["compiles"]


def profiling_enabled(explicit=None) -> bool:
    """Resolve the profile switch ONCE: explicit arg beats
    :data:`PROFILE_ENV` beats off (the MTPU_KV_DTYPE rule — the env is
    never re-read on the hot path)."""
    import os

    if explicit is not None:
        return bool(explicit)
    return os.environ.get(PROFILE_ENV, "") not in ("", "0")


class TickProfile:
    """One scheduler tick's phase accumulator.

    Interval semantics: :meth:`mark` closes the time since the PREVIOUS
    mark (or the tick's start) into the named phase — the scheduler runs
    one thread, so sequential marks partition the tick exactly and the
    per-phase sums can never exceed the tick total. ``device=True``
    additionally counts the interval as device-blocked time (the host
    waiting on a device array), feeding the host-vs-device split.
    """

    __slots__ = ("_clock", "t0", "_last", "phases", "device_s")

    def __init__(self, clock):
        self._clock = clock
        self.t0 = self._last = clock()
        self.phases: dict[str, float] = {}
        self.device_s = 0.0

    def mark(self, phase: str, device: bool = False) -> None:
        now = self._clock()
        dt = now - self._last
        self._last = now
        if dt > 0:
            self.phases[phase] = self.phases.get(phase, 0.0) + dt
            if device:
                self.device_s += dt


class HotPathProfiler:
    """Per-engine hot-path profiler: tick ring + compile telemetry.

    ``clock`` is the engine's injectable monotonic clock (fake-clock tests
    see real phase deltas); ``name`` is the replica name, or a zero-arg
    callable resolving it lazily (the engine's ``trace_name`` is assigned
    by the fleet AFTER construction). All methods are safe from the
    scheduler thread plus concurrent ``prefill_sync`` server threads.
    """

    def __init__(
        self,
        *,
        clock=None,
        name="engine",
        registry=None,
        ledger_path=None,
        ring: int = RING_TICKS,
    ):
        self._clock = clock or time.monotonic
        self._name = name
        self._registry = registry
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=ring)
        self._busy_ticks = 0
        #: (program, shape_key str) pairs already built in this process
        self._seen: set[tuple[str, str]] = set()
        self._compiles = 0
        self._compile_s = 0.0
        self._dispatches = 0
        self._dispatch_tokens = 0
        self._decode_steps = 1
        self._compile_log: deque[dict] = deque(maxlen=COMPILE_LOG_KEEP)
        self._ledger_path = ledger_path
        self._ledger: DecisionJournal | None = None
        register(self)

    @property
    def replica(self) -> str:
        return str(self._name() if callable(self._name) else self._name)

    # -- tick anatomy --------------------------------------------------------

    def begin_tick(self) -> TickProfile:
        return TickProfile(self._clock)

    def end_tick(self, tick: TickProfile, worked: bool) -> None:
        """Close one tick. Idle ticks (``worked=False``, or nothing marked)
        record NOTHING — the ring and histograms carry only ticks that did
        work, so an idle engine's profile stays empty instead of drowning
        the signal in sub-millisecond no-op loops."""
        if not worked or not tick.phases:
            return
        total = max(0.0, self._clock() - tick.t0)
        entry = {
            "at": time.time(),  # wall clock: aligns with trace span starts
            "total": total,
            "device": tick.device_s,
            "phases": dict(tick.phases),
        }
        with self._lock:
            self._ring.append(entry)
            self._busy_ticks += 1
            refresh = self._busy_ticks % _GAUGE_EVERY == 0
        for phase, seconds in tick.phases.items():
            _obs.record_tick_phase(phase, seconds, registry=self._registry)
        _obs.record_tick_phase(
            C.TICK_TOTAL_PHASE, total, registry=self._registry
        )
        if refresh:
            self._refresh_ratio()

    def note_dispatch_tokens(self, n: int, steps: int | None = None) -> None:
        """One harvested decode dispatch accepted ``n`` tokens (both the
        classic block path and the macro-step path report here, so the
        BENCH ``multistep`` section's tokens-per-dispatch compares across
        arms); ``steps`` is the configured ``decode_steps`` at dispatch
        time."""
        with self._lock:
            self._dispatches += 1
            self._dispatch_tokens += int(n)
            if steps is not None:
                self._decode_steps = max(1, int(steps))

    def flush(self) -> None:
        """Force the host-overhead gauge current (engine stop / push time:
        a short run may never cross the every-N-ticks refresh)."""
        self._refresh_ratio()

    def _refresh_ratio(self) -> None:
        with self._lock:
            total = sum(e["total"] for e in self._ring)
            device = sum(e["device"] for e in self._ring)
        if total > 0:
            _obs.set_host_overhead_ratio(
                max(0.0, min(1.0, 1.0 - device / total)),
                registry=self._registry,
            )

    # -- compile telemetry ---------------------------------------------------

    def compile_begin(self, program: str, shape_key) -> float | None:
        """First half of the build-site chokepoint: None when this
        (program, shape_key) was already built in this process (the caller
        records a cache hit via :meth:`compile_end`); otherwise the start
        timestamp — and a ``begin`` ledger event, written BEFORE the build
        so a crash/hang mid-compile still names its program/shape."""
        key = (program, str(shape_key))
        with self._lock:
            if key in self._seen:
                return None
            self._seen.add(key)
        self._ledger_record({
            "at": time.time(),
            "event": "begin",
            "replica": self.replica,
            "program": program,
            "shape_key": str(shape_key),
        })
        return self._clock()

    def compile_abort(self, program: str, shape_key) -> None:
        """A build that raised: forget the (program, shape_key) so the
        next dispatch is timed as a fresh miss again — without this, the
        successful retry would be misreported as a cache hit and its
        ``begin`` row would read as a crash forever. The open ``begin``
        stays in the ledger; the retry's own begin/end pair supersedes it
        in :func:`unfinished_builds`, and a never-retried failure keeps
        reading as unfinished — which it is."""
        with self._lock:
            self._seen.discard((program, str(shape_key)))

    def compile_end(self, program: str, shape_key, t0: float | None) -> None:
        if t0 is None:
            self.note_compile(program, shape_key, 0.0, cache_hit=True)
        else:
            self.note_compile(
                program, shape_key, self._clock() - t0, cache_hit=False
            )

    def note_compile(
        self, program: str, shape_key, seconds: float, cache_hit: bool
    ) -> None:
        """THE chokepoint every build site reports through: counts the
        lookup (``mtpu_compiles_total{program,cache}``); a miss (fresh
        build) also observes ``mtpu_compile_seconds{program}`` and appends
        the ``end`` event to the ledger."""
        _obs.record_compile(
            program, seconds, cache_hit, registry=self._registry
        )
        if cache_hit:
            return
        rec = {
            "at": time.time(),
            "event": "end",
            "replica": self.replica,
            "program": program,
            "shape_key": str(shape_key),
            "seconds": round(float(seconds), 6),
            "cache": "miss",
        }
        with self._lock:
            self._compiles += 1
            self._compile_s += float(seconds)
            self._compile_log.append(rec)
        self._ledger_record(rec)

    def _ledger_record(self, rec: dict) -> None:
        if self._ledger is None:
            self._ledger = named_journal(
                "compiles", path=self._ledger_path
            )
        self._ledger.record(rec)

    # -- read surfaces -------------------------------------------------------

    def overhead_summary(self) -> dict:
        """The BENCH ``overhead`` section / ``/profile`` payload: per-phase
        tick p50/p95 over the ring, the host fraction, the detokenize
        share, attribution coverage (attributed/total — structurally ≤ 1),
        and compile totals."""
        with self._lock:
            ring = list(self._ring)
            compiles_n, compile_s = self._compiles, self._compile_s
            dispatches = self._dispatches
            dispatch_tokens = self._dispatch_tokens
            decode_steps = self._decode_steps
        tokens_per_dispatch = (
            round(dispatch_tokens / dispatches, 3) if dispatches else None
        )
        if not ring:
            return {
                "ticks": 0,
                "host_fraction": None,
                "tick_p50": None,
                "tick_p95": None,
                "detok_share": None,
                "attribution_cover": None,
                "phases": {},
                "compile_total_s": round(compile_s, 3),
                "compiles_n": compiles_n,
                "decode_steps": decode_steps,
                "dispatches": dispatches,
                "tokens_per_dispatch": tokens_per_dispatch,
            }
        totals = sorted(e["total"] for e in ring)
        sum_total = sum(totals)
        sum_device = sum(e["device"] for e in ring)
        sum_detok = sum(e["phases"].get("detokenize", 0.0) for e in ring)
        sum_attr = sum(sum(e["phases"].values()) for e in ring)
        phases: dict[str, dict] = {}
        for phase in C.TICK_PHASES:
            vals = sorted(
                e["phases"][phase] for e in ring if phase in e["phases"]
            )
            if vals:
                phases[phase] = {
                    "p50": round(_pct(vals, 0.50), 6),
                    "p95": round(_pct(vals, 0.95), 6),
                    "count": len(vals),
                }
        return {
            "ticks": len(ring),
            "host_fraction": round(
                max(0.0, min(1.0, 1.0 - sum_device / sum_total)), 6
            ) if sum_total > 0 else None,
            "tick_p50": round(_pct(totals, 0.50), 6),
            "tick_p95": round(_pct(totals, 0.95), 6),
            "detok_share": round(sum_detok / sum_total, 6)
            if sum_total > 0 else None,
            "attribution_cover": round(sum_attr / sum_total, 6)
            if sum_total > 0 else None,
            "phases": phases,
            "compile_total_s": round(compile_s, 3),
            "compiles_n": compiles_n,
            "decode_steps": decode_steps,
            "dispatches": dispatches,
            "tokens_per_dispatch": tokens_per_dispatch,
        }

    def perfetto_snapshot(self) -> dict:
        """Ring + in-memory compile log in the shape the Perfetto export's
        ``profile=`` parameter takes (wall-clock ``at`` fields align with
        request-span timestamps)."""
        with self._lock:
            return {
                "ticks": [dict(e) for e in self._ring],
                "compiles": [dict(r) for r in self._compile_log],
            }


# -- process registry (the gateway's /profile source) ------------------------

_registry_lock = threading.Lock()
#: weak refs so the registry never pins a dead engine's profiler (the
#: profiler's lazy-name callable holds the engine)
_profilers: list = []


def register(profiler: HotPathProfiler) -> None:
    with _registry_lock:
        _profilers.append(weakref.ref(profiler))
        # drop dead refs opportunistically; cap the list
        _profilers[:] = [r for r in _profilers if r() is not None][-64:]


def active_profilers() -> list[HotPathProfiler]:
    with _registry_lock:
        return [p for p in (r() for r in _profilers) if p is not None]


def read_ledger(path=None, n: int = 200) -> list[dict]:
    """Newest-last slice of the compile ledger (jax-free — `tpurun
    profile` and the gateway read it without touching an engine)."""
    return named_journal("compiles", path=path).tail(n)


def unfinished_builds(records: list[dict]) -> list[dict]:
    """``begin`` events with no matching LATER ``end`` — the offline
    diagnosis for a compile helper that crashed or hung mid-build (the
    ≥40-slot ceiling's smoking gun). Pairing is strictly ordered: an
    ``end`` closes only begins that precede it, so a ledger spanning
    several runs (revalidate rounds append) still reports a later run's
    mid-build crash of a program/shape that built fine earlier."""
    open_begins: dict[tuple, dict] = {}
    for rec in records:
        key = (rec.get("replica"), rec.get("program"), rec.get("shape_key"))
        if rec.get("event") == "begin":
            open_begins[key] = rec
        elif rec.get("event") == "end":
            open_begins.pop(key, None)
    return list(open_begins.values())
