"""Central catalog of every ``mtpu_*`` metric series the framework emits.

ONE module owns every metric name: code imports the constant, docs render
:data:`CATALOG`, and ``tests/test_static.py`` enforces that no ``mtpu_*``
metric-name literal exists anywhere else in the package — stringly-typed
metric drift (two spellings of one series, phantom names in comments) is
unrepresentable.

Conventions (Prometheus): ``_total`` counters, ``_seconds`` histograms,
unsuffixed gauges. Labels are listed per series in :data:`CATALOG`.
"""

from __future__ import annotations

# -- call lifecycle (core/executor.py) --------------------------------------

#: histogram {function, phase}: per-phase call latency; phases are
#: queue | boot | dispatch | execute | serialize | total
CALL_DURATION_SECONDS = "mtpu_call_duration_seconds"
#: histogram {function}: submit -> dispatch wait (the queue phase, dedicated
#: series so queue-wait distributions can be scraped without a phase filter)
QUEUE_WAIT_SECONDS = "mtpu_queue_wait_seconds"
#: gauge {function}: inputs submitted but not yet completed
INFLIGHT_INPUTS = "mtpu_inflight_inputs"
#: counter {function, reason}: retry attempts scheduled;
#: reason = timeout | container_death | user_error
RETRIES_TOTAL = "mtpu_retries_total"
#: counter {function, reason}: containers killed by the supervisor
#: (reason = timeout is the only kill the scheduler issues today)
CONTAINER_KILLS_TOTAL = "mtpu_container_kills_total"

# -- memory snapshots (modal_examples_tpu/snapshot, PR 1) -------------------

#: counter {function, result}: snapshot-enabled container boots;
#: result = hit | miss | fallback
SNAPSHOT_BOOTS_METRIC = "mtpu_snapshot_boots_total"
#: counter {function}: snapshots captured and published to the store
SNAPSHOT_CAPTURES_METRIC = "mtpu_snapshot_captures_total"

# -- serving engine (serving/engine.py batch loop) --------------------------

#: histogram {phase}: engine hot-loop phase latency;
#: phase = prefill | prefill_chunked | decode_wait
ENGINE_PHASE_SECONDS = "mtpu_engine_phase_seconds"
#: histogram: slots active per dispatched decode block (batch composition)
ENGINE_BATCH_SIZE = "mtpu_engine_batch_size"
#: histogram: request submit -> prefill admission wait
ENGINE_QUEUE_WAIT_SECONDS = "mtpu_engine_queue_wait_seconds"
#: gauge: requests waiting for admission (engine queue depth)
WAITING_REQUESTS = "mtpu_waiting_requests"
#: gauge: slots currently decoding
ACTIVE_SLOTS = "mtpu_active_slots"
#: gauge: generated tokens per second since engine start
TOKENS_PER_SECOND = "mtpu_tokens_per_second"
#: counter: scheduler-loop exceptions (engine.error_count mirror)
SCHEDULER_ERRORS_TOTAL = "mtpu_scheduler_errors_total"

# -- OpenAI-compatible server /metrics (serving/openai_api.py) --------------

GENERATED_TOKENS_TOTAL = "mtpu_generated_tokens_total"
PROMPT_TOKENS_TOTAL = "mtpu_prompt_tokens_total"
DECODE_STEPS_TOTAL = "mtpu_decode_steps_total"
KV_PAGES_FREE = "mtpu_kv_pages_free"
DECODE_IMPL = "mtpu_decode_impl"
SPEC_PROPOSED_TOTAL = "mtpu_spec_proposed_total"
SPEC_ACCEPTED_TOTAL = "mtpu_spec_accepted_total"
SPEC_ACCEPTANCE_RATE = "mtpu_spec_acceptance_rate"
PREFIX_CACHE_HITS_TOTAL = "mtpu_prefix_cache_hits_total"
PREFIX_CACHE_MISSES_TOTAL = "mtpu_prefix_cache_misses_total"
PREFIX_CACHED_PAGES = "mtpu_prefix_cached_pages"


#: machine-readable catalog: name -> {type, labels, help}. docs/observability
#: renders this; the static guard asserts every emitted name appears here.
CATALOG: dict[str, dict] = {
    CALL_DURATION_SECONDS: {
        "type": "histogram",
        "labels": ["function", "phase"],
        "help": "per-phase call latency "
                "(queue|boot|dispatch|execute|serialize|total)",
    },
    QUEUE_WAIT_SECONDS: {
        "type": "histogram",
        "labels": ["function"],
        "help": "submit-to-dispatch queue wait",
    },
    INFLIGHT_INPUTS: {
        "type": "gauge",
        "labels": ["function"],
        "help": "inputs submitted but not yet completed",
    },
    RETRIES_TOTAL: {
        "type": "counter",
        "labels": ["function", "reason"],
        "help": "retry attempts scheduled "
                "(reason=timeout|container_death|user_error)",
    },
    CONTAINER_KILLS_TOTAL: {
        "type": "counter",
        "labels": ["function", "reason"],
        "help": "containers killed by the supervisor",
    },
    SNAPSHOT_BOOTS_METRIC: {
        "type": "counter",
        "labels": ["function", "result"],
        "help": "snapshot-enabled container boots (result=hit|miss|fallback)",
    },
    SNAPSHOT_CAPTURES_METRIC: {
        "type": "counter",
        "labels": ["function"],
        "help": "memory snapshots captured and published to the store",
    },
    ENGINE_PHASE_SECONDS: {
        "type": "histogram",
        "labels": ["phase"],
        "help": "engine hot-loop phase latency "
                "(prefill|prefill_chunked|decode_wait)",
    },
    ENGINE_BATCH_SIZE: {
        "type": "histogram",
        "labels": [],
        "help": "active slots per dispatched decode block",
    },
    ENGINE_QUEUE_WAIT_SECONDS: {
        "type": "histogram",
        "labels": [],
        "help": "request submit-to-admission wait",
    },
    WAITING_REQUESTS: {
        "type": "gauge",
        "labels": [],
        "help": "requests waiting for admission",
    },
    ACTIVE_SLOTS: {
        "type": "gauge",
        "labels": [],
        "help": "slots currently decoding",
    },
    TOKENS_PER_SECOND: {
        "type": "gauge",
        "labels": [],
        "help": "generated tokens per second since engine start",
    },
    SCHEDULER_ERRORS_TOTAL: {
        "type": "counter",
        "labels": [],
        "help": "engine scheduler-loop exceptions",
    },
    GENERATED_TOKENS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "tokens generated by the engine",
    },
    PROMPT_TOKENS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "prompt tokens prefilled by the engine",
    },
    DECODE_STEPS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "decode steps executed",
    },
    KV_PAGES_FREE: {
        "type": "gauge", "labels": [],
        "help": "free pages in the paged KV cache",
    },
    DECODE_IMPL: {
        "type": "gauge", "labels": ["attention", "scatter"],
        "help": "resolved decode implementation plan (info metric, value 1)",
    },
    SPEC_PROPOSED_TOTAL: {
        "type": "counter", "labels": [],
        "help": "draft tokens proposed (speculative mode)",
    },
    SPEC_ACCEPTED_TOTAL: {
        "type": "counter", "labels": [],
        "help": "draft tokens accepted by the target",
    },
    SPEC_ACCEPTANCE_RATE: {
        "type": "gauge", "labels": [],
        "help": "speculative acceptance rate",
    },
    PREFIX_CACHE_HITS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "prefix-cache admission hits",
    },
    PREFIX_CACHE_MISSES_TOTAL: {
        "type": "counter", "labels": [],
        "help": "prefix-cache admission misses",
    },
    PREFIX_CACHED_PAGES: {
        "type": "gauge", "labels": [],
        "help": "pages currently held by the prefix cache",
    },
}

#: every declared metric name (the static guard's allowlist)
ALL_METRIC_NAMES = frozenset(CATALOG)

#: buckets for batch-size-style histograms (counts, not seconds)
COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)
