"""Central catalog of every ``mtpu_*`` metric series the framework emits.

ONE module owns every metric name: code imports the constant, docs render
:data:`CATALOG`, and ``tests/test_static.py`` enforces that no ``mtpu_*``
metric-name literal exists anywhere else in the package — stringly-typed
metric drift (two spellings of one series, phantom names in comments) is
unrepresentable.

Conventions (Prometheus): ``_total`` counters, ``_seconds`` histograms,
unsuffixed gauges. Labels are listed per series in :data:`CATALOG`.
"""

from __future__ import annotations

# -- call lifecycle (core/executor.py) --------------------------------------

#: histogram {function, phase}: per-phase call latency; phases are
#: queue | boot | dispatch | execute | serialize | total
CALL_DURATION_SECONDS = "mtpu_call_duration_seconds"
#: histogram {function}: submit -> dispatch wait (the queue phase, dedicated
#: series so queue-wait distributions can be scraped without a phase filter)
QUEUE_WAIT_SECONDS = "mtpu_queue_wait_seconds"
#: gauge {function}: inputs submitted but not yet completed
INFLIGHT_INPUTS = "mtpu_inflight_inputs"
#: counter {function, reason}: retry attempts scheduled;
#: reason = timeout | container_death | user_error
RETRIES_TOTAL = "mtpu_retries_total"
#: counter {function, reason}: containers killed by the supervisor
#: (reason = timeout is the only kill the scheduler issues today)
CONTAINER_KILLS_TOTAL = "mtpu_container_kills_total"

# -- memory snapshots (modal_examples_tpu/snapshot, PR 1) -------------------

#: counter {function, result}: snapshot-enabled container boots;
#: result = hit | miss | fallback
SNAPSHOT_BOOTS_METRIC = "mtpu_snapshot_boots_total"
#: counter {function}: snapshots captured and published to the store
SNAPSHOT_CAPTURES_METRIC = "mtpu_snapshot_captures_total"

# -- serving engine (serving/engine.py batch loop) --------------------------

#: histogram {phase}: engine hot-loop phase latency;
#: phase = prefill | prefill_chunked | decode_wait
ENGINE_PHASE_SECONDS = "mtpu_engine_phase_seconds"
#: histogram: slots active per dispatched decode block (batch composition)
ENGINE_BATCH_SIZE = "mtpu_engine_batch_size"
#: histogram: request submit -> prefill admission wait
ENGINE_QUEUE_WAIT_SECONDS = "mtpu_engine_queue_wait_seconds"
#: gauge: requests waiting for admission (engine queue depth)
WAITING_REQUESTS = "mtpu_waiting_requests"
#: gauge: slots currently decoding
ACTIVE_SLOTS = "mtpu_active_slots"
#: gauge: generated tokens per second since engine start
TOKENS_PER_SECOND = "mtpu_tokens_per_second"
#: counter: scheduler-loop exceptions (engine.error_count mirror)
SCHEDULER_ERRORS_TOTAL = "mtpu_scheduler_errors_total"

# -- stall-free admission (serving/engine.py prefill budget, PR 10) ---------

#: histogram: gap between consecutive decode-block dispatches while
#: decodable slots exist (the stall-free admission contract: bounded by
#: ~one prefill chunk under a budget — docs/scheduling.md)
DECODE_STALL_SECONDS = "mtpu_decode_stall_seconds"
#: gauge: prompt tokens admitted to a slot whose chunked prefill has not
#: finished yet (the sliced-prefill remainder summed over slots)
PREFILL_BACKLOG_TOKENS = "mtpu_prefill_backlog_tokens"
#: counter: sliced-prefill suspensions — a chunked prefill paused
#: mid-prompt because the per-tick token budget was spent
PREFILL_SLICED_TOTAL = "mtpu_prefill_sliced_total"

# -- token-level serving telemetry (serving/engine.py) ----------------------

#: histogram: request submit -> first generated token emitted (TTFT)
TTFT_SECONDS = "mtpu_ttft_seconds"
#: histogram: inter-token interval between consecutive generated tokens
#: of one request (TPOT / time-per-output-token)
TPOT_SECONDS = "mtpu_tpot_seconds"

# -- resource occupancy (kv cache / prefix cache / snapshot store / host) ---

#: gauge: pages currently allocated out of the paged KV cache
KV_PAGES_USED = "mtpu_kv_pages_used"
#: gauge: allocated fraction of the usable KV page pool (0..1)
KV_PAGE_OCCUPANCY = "mtpu_kv_page_occupancy"
#: gauge {dtype}: total HBM bytes of the paged KV cache arrays (dtype-aware:
#: int8 caches report ~half the bf16 footprint — docs/kv_cache.md)
KV_CACHE_BYTES = "mtpu_kv_cache_bytes"
#: counter: zero-ref prefix-cache pages reclaimed under allocator pressure
PREFIX_CACHE_EVICTIONS_TOTAL = "mtpu_prefix_cache_evictions_total"
#: gauge: total payload bytes resident in the memory-snapshot store
SNAPSHOT_STORE_BYTES = "mtpu_snapshot_store_bytes"
#: gauge: entries resident in the memory-snapshot store
SNAPSHOT_STORE_ENTRIES = "mtpu_snapshot_store_entries"
#: counter {result}: snapshot-store lookups (result = hit | miss)
SNAPSHOT_STORE_GETS_TOTAL = "mtpu_snapshot_store_gets_total"
#: gauge: supervisor-process resident set size, sampled by the executor
HOST_RSS_BYTES = "mtpu_host_rss_bytes"

# -- autoscaler decision journal (core/executor.py _autoscale) --------------

#: counter {function, action}: autoscaler decisions recorded to the journal;
#: action = scale_up | scale_down | kill
SCALER_DECISIONS_TOTAL = "mtpu_scaler_decisions_total"

# -- request scheduler (modal_examples_tpu/scheduling, PR 4) ----------------

#: counter {class, reason}: requests shed by admission control;
#: reason = queue_full | kv_pressure | too_large | injected (chaos)
SHEDS_TOTAL = "mtpu_sheds_total"
#: counter {class}: requests accepted by admission control
REQUESTS_ADMITTED_TOTAL = "mtpu_requests_admitted_total"
#: gauge {class}: requests queued per priority class (policy depth)
SCHED_QUEUE_DEPTH = "mtpu_sched_queue_depth"
#: histogram {class}: per-class submit -> prefill-admission wait
SCHED_QUEUE_WAIT_SECONDS = "mtpu_sched_queue_wait_seconds"
#: gauge: KV pages reserved by queued (not-yet-claimed) admissions
KV_PAGES_RESERVED = "mtpu_kv_pages_reserved"
#: counter {stage}: requests that blew their deadline;
#: stage = queued (cancelled before a slot) | prefill (aborted while the
#: sliced prefill was still filling KV) | inflight (aborted mid-decode) |
#: migrating (aborted during a disagg page migration)
DEADLINE_MISSES_TOTAL = "mtpu_deadline_misses_total"
#: counter {route}: router placements; route = affinity | fallback
ROUTER_REQUESTS_TOTAL = "mtpu_router_requests_total"
#: counter: repeated shared-prefix prompts landed on their affinity replica
ROUTER_AFFINITY_HITS_TOTAL = "mtpu_router_affinity_hits_total"
#: counter: unhealthy replicas re-admitted to the candidate set after a
#: successful health re-probe (docs/faults.md: unhealthy is not a one-way
#: door — flapped replicas rejoin route()/plan() once they probe healthy)
ROUTER_READMISSIONS_TOTAL = "mtpu_router_readmissions_total"

# -- fault injection (modal_examples_tpu/faults, docs/faults.md) ------------

#: counter {point}: injected faults that FIRED, by catalog point name
#: (faults/inject.py POINTS); the chaos runner's reachability record
FAULTS_INJECTED_TOTAL = "mtpu_faults_injected_total"

# -- disaggregated serving (serving/disagg, docs/disagg.md) -----------------

#: counter {result}: page migrations between replicas;
#: result = ok | fallback (unified re-prefill) | aborted (client/deadline)
DISAGG_MIGRATIONS_TOTAL = "mtpu_disagg_migrations_total"
#: counter: KV pages successfully migrated prefill -> decode
DISAGG_PAGES_MIGRATED_TOTAL = "mtpu_disagg_pages_migrated_total"
#: counter: serialized wire bytes of successful migrations (int8 caches
#: ship ~half the bf16 bytes — the PR 5 residency win on the wire)
DISAGG_MIGRATION_BYTES_TOTAL = "mtpu_disagg_migration_bytes_total"
#: histogram: end-to-end migration latency (prefill start -> adopt/fail)
DISAGG_MIGRATION_SECONDS = "mtpu_disagg_migration_seconds"
#: gauge: migrations currently in flight (prefilling or on the wire)
DISAGG_MIGRATIONS_INFLIGHT = "mtpu_disagg_migrations_inflight"
#: counter: transfer chunks re-sent after loss/corruption (resumable retry)
DISAGG_CHUNK_RETRIES_TOTAL = "mtpu_disagg_chunk_retries_total"
#: gauge {replica, role}: info metric (value 1) — each replica's serving
#: role (prefill | decode | unified)
REPLICA_ROLE = "mtpu_replica_role"

# -- in-flight request failover (serving/failover.py, docs/failover.md) -----

#: counter {mode, result}: in-flight request takeovers; mode = reactive
#: (replica died — re-prefill prompt+generated-prefix from the decode
#: checkpoint) | migrate (proactive live KV migration on drain/rebalance);
#: result = ok | failed (no healthy target / resubmission shed)
FAILOVER_TOTAL = "mtpu_failover_total"
#: counter: generated-prefix tokens replayed (teacher-forced through the
#: decode program) on reactive failover — the work redone because the dead
#: replica's KV was lost; the prompt half re-prefills from the (often
#: warm) prefix cache — docs/failover.md
FAILOVER_TOKENS_REPLAYED_TOTAL = "mtpu_failover_tokens_replayed_total"
#: histogram: client-observed takeover latency — stream error detected (or
#: migration started) to the resumed request accepted on the new replica
FAILOVER_TAKEOVER_SECONDS = "mtpu_failover_takeover_seconds"
#: counter {result}: proactive live migrations of MID-DECODE requests
#: (result = ok | fallback (reactive resume carried it after a wire/adopt
#: failure) | aborted (client abort / deadline during the migration) |
#: failed (reservation shed, victim unresponsive, or the fallback resume
#: itself refused — the request was NOT moved))
MIGRATION_LIVE_TOTAL = "mtpu_migration_live_total"
#: counter: decode tokens carried across live migrations (each migrated
#: request contributes its generated-so-far count — the work scale-in no
#: longer throws away; ``fleet.jsonl``'s ``tokens_migrated`` source)
MIGRATION_LIVE_TOKENS_TOTAL = "mtpu_migration_live_tokens_total"
#: histogram: live-migration latency, checkpoint extraction -> adopted on
#: the target (the bound on drain time per request)
MIGRATION_LIVE_SECONDS = "mtpu_migration_live_seconds"

# -- tiered prefix cache (serving/disagg/tiered_cache.py) -------------------

#: counter {tier}: prefix PAGES served per tier (page units on every tier,
#: so rates are comparable); tier = hbm (trie-shared pages) | host (RAM
#: promotes) | volume (spill promotes). Only tiered engines emit it.
PREFIX_TIER_HITS_TOTAL = "mtpu_prefix_tier_hits_total"
#: gauge {tier}: prefix blocks resident per spill tier (host | volume)
PREFIX_TIER_PAGES = "mtpu_prefix_tier_pages"
#: gauge {tier}: serialized bytes resident per spill tier (host | volume)
PREFIX_TIER_BYTES = "mtpu_prefix_tier_bytes"

# -- shared prefix store (serving/prefix_store/, docs/prefix_store.md) ------

#: counter {origin}: blocks served by the fleet-shared store; origin =
#: self (this replica wrote it) | peer (another replica's spill — the
#: cross-replica warmth the store exists for)
PREFIX_STORE_HITS_TOTAL = "mtpu_prefix_store_hits_total"
#: counter: store lookups that found nothing (or a torn block, dropped)
PREFIX_STORE_MISSES_TOTAL = "mtpu_prefix_store_misses_total"
#: gauge: logical spill attempts per physical write (> 1.0 = the fleet
#: stopped paying N copies of shared chains)
PREFIX_STORE_DEDUP_RATIO = "mtpu_prefix_store_dedup_ratio"
#: gauge: serialized bytes resident in the shared store
PREFIX_STORE_BYTES = "mtpu_prefix_store_bytes"
#: counter: spill leases taken over from a dead/expired owner replica
#: (journaled in prefix_store.jsonl; the chaos owner-death episode's proof)
PREFIX_STORE_OWNER_TAKEOVERS_TOTAL = "mtpu_prefix_store_owner_takeovers_total"

# -- fleet autoscaler (modal_examples_tpu/fleet, docs/fleet.md) -------------

#: gauge {role}: replicas currently registered in the fleet, by serving
#: role (prefill | decode | unified) — the closed-loop autoscaler's output
FLEET_REPLICAS = "mtpu_fleet_replicas"
#: counter {action, trigger}: fleet autoscaler decisions journaled to
#: <state_dir>/fleet.jsonl; action = scale_up | scale_down, trigger =
#: slo_burn | queue_pressure | kv_pressure | shed_pressure | idle |
#: min_replicas (floor fill) | drain_timeout (forced reap) | quarantine
#: (the watchdog benched a replica — replace its capacity, docs/health.md)
FLEET_DECISIONS_TOTAL = "mtpu_fleet_decisions_total"
#: histogram {boot}: replica build+start seconds at scale-out;
#: boot = warm (snapshot-restored params) | cold (full init)
FLEET_BOOT_SECONDS = "mtpu_fleet_boot_seconds"

# -- gray-failure watchdog (serving/health.py, docs/health.md) ---------------

#: gauge {replica, state}: one-hot replica classification by the progress
#: watchdog (state = healthy | degraded | wedged | quarantined; exactly one
#: state reads 1 per replica)
WATCHDOG_REPLICA_STATE = "mtpu_watchdog_replica_state"
#: gauge {replica}: worst stale age (seconds) among the replica's mandatory
#: progress watermarks — 0 while idle (staleness only counts against
#: outstanding work)
WATCHDOG_PROGRESS_AGE_SECONDS = "mtpu_watchdog_progress_age_seconds"
#: counter {state}: classification transitions (entering the labeled state)
WATCHDOG_TRANSITIONS_TOTAL = "mtpu_watchdog_transitions_total"
#: counter {action}: recovery-ladder actions taken; action = down_weight |
#: restore_weight | abort_transfer | stop_revive | quarantine | unquarantine
WATCHDOG_RECOVERIES_TOTAL = "mtpu_watchdog_recoveries_total"

# -- hot-path profiler (observability/profiler.py, docs/observability.md) ---

#: the scheduler-tick phase taxonomy the hot-path profiler attributes —
#: THE phase vocabulary: ``serving/engine.py`` marks phases only through
#: these names and ``tests/test_static.py`` enforces the closure in both
#: directions, so a phase the scheduler stops marking (or marks under a
#: new ad-hoc spelling) fails the suite instead of rotting in dashboards.
#: Rendering order is anatomical: control -> admission -> prefill ->
#: decode -> harvest -> emit.
TICK_PHASES = (
    "ctrl",              # scheduler control commands (migration extraction)
    "policy",            # deadline expiry, abort reaps, gauge refresh
    "admit",             # policy pops, page claims, slot installs
    "prefill_resume",    # budgeted sliced-prefill chunk advance
    "prefill_dispatch",  # batched/chunked prefill program dispatch
    "decode_dispatch",   # decode-block program dispatch (async)
    "harvest",           # blocking device reads (tokens ready on host)
    "detokenize",        # incremental tokenizer.decode per accepted token
    "accept",            # token bookkeeping, stop handling, stream emit
)
#: extra ``{phase}`` label value carrying the WHOLE-tick duration, so
#: ``overhead.tick_p95`` is one histogram read (not declared in
#: TICK_PHASES: it is the denominator, not an attribution)
TICK_TOTAL_PHASE = "total"

#: histogram {phase}: per-tick host time attributed to one scheduler phase
#: (phase = TICK_PHASES, plus "total" for the whole-tick duration).
#: Emitted ONLY under MTPU_PROFILE — the disabled hot path takes zero new
#: timestamps (the faults-gate zero-cost contract)
TICK_PHASE_SECONDS = "mtpu_tick_phase_seconds"
#: gauge: host share of busy-tick time over the profiler ring —
#: 1 - (device-blocked seconds / total tick seconds); the per-token host
#: overhead ROADMAP #3's multi-step decode loop exists to amortize
HOST_OVERHEAD_RATIO = "mtpu_host_overhead_ratio"
#: histogram {program}: seconds spent building one jitted program at its
#: first dispatch of a (program, shape_key); program = block | prefill |
#: prefill_mm | prefill_chunk | draft_prefill | spec_verify | ngram_verify
#: | sample (the ops-level first-token helper) | multistep (the N-step
#: macro-dispatch scan, serving/multistep/)
COMPILE_SECONDS = "mtpu_compile_seconds"
#: counter {program, cache}: program-cache lookups at the engine's jit
#: dispatch sites; cache = miss (a fresh build — timed and appended to the
#: <state_dir>/compiles.jsonl ledger) | hit (served already-compiled)
COMPILES_TOTAL = "mtpu_compiles_total"

# -- macro-step decode runtime (serving/multistep/, docs/multistep.md) -------

#: gauge: the configured decode steps per dispatch (the runtime-mutable
#: ``decode_steps`` knob / MTPU_DECODE_STEPS; 1 = classic block path)
MULTISTEP_DECODE_STEPS = "mtpu_multistep_decode_steps"
#: gauge: accepted tokens per decode dispatch over the last gauge window —
#: the headline amortization number (classic path reports it too, so the
#: A/B bench reads one series across both arms)
MULTISTEP_TOKENS_PER_DISPATCH = "mtpu_multistep_tokens_per_dispatch"
#: counter: decode dispatches harvested (one per blocking device read)
MULTISTEP_DISPATCHES_TOTAL = "mtpu_multistep_dispatches_total"
#: counter: tokens accepted from harvested decode dispatches
MULTISTEP_TOKENS_TOTAL = "mtpu_multistep_tokens_total"
#: counter: whole macro-steps the on-device early-exit skipped (every lane
#: dead — the ``masked_scan`` hold branch ran instead of the transformer)
MULTISTEP_EARLY_EXIT_STEPS_TOTAL = "mtpu_multistep_early_exit_steps_total"
#: gauge: events pending on the detokenization worker's queue (a growing
#: depth means text emission is falling behind the scheduler)
MULTISTEP_DETOK_QUEUE_DEPTH = "mtpu_multistep_detok_queue_depth"

# -- fused speculative decoding (serving/spec_runtime/, docs/speculative.md) --

#: gauge: dispatched per-slot speculation depth, p50 over the last gauge
#: window (the adaptive controller's OUTPUT — 0 means lanes are riding the
#: classic γ=0 path inside the fused round)
SPEC_GAMMA = "mtpu_spec_gamma"
#: gauge: harvested tokens per speculative round over the last gauge window
#: (>1 is the whole point; held when idle)
SPEC_TOKENS_PER_DISPATCH = "mtpu_spec_tokens_per_dispatch"
#: gauge: lifetime draft-token acceptance rate (accepted / proposed) — the
#: ``spec_acceptance_collapse`` alert's series, guarded on SPEC_GAMMA > 0
SPEC_ACCEPTANCE_RATE = "mtpu_spec_acceptance_rate"
#: counter: whole decode rounds where NO slot speculated (pressure or
#: acceptance collapse) and the engine fell through to the classic block
#: program — the "spec never costs latency" escape hatch firing
SPEC_FALLBACK_TOTAL = "mtpu_spec_fallback_total"

# -- flight recorder (observability/timeseries.py / alerts.py / incident.py,
#    docs/observability.md#metrics-history) ----------------------------------

#: counter: sampler scrape cycles completed into the on-disk tsdb
#: (emitted only while MTPU_TSDB is on — the zero-cost-when-off gate)
TSDB_SAMPLES_TOTAL = "mtpu_tsdb_samples_total"
#: histogram: wall seconds one scrape cycle spent snapshotting the registry
#: and appending its record (the sampler's own overhead, so "does the
#: flight recorder cost anything?" is itself answerable from the recorder)
TSDB_SCRAPE_SECONDS = "mtpu_tsdb_scrape_seconds"
#: counter: tsdb segment rotations (a new JSONL segment opened; old
#: segments LRU-pruned past the ring bound)
TSDB_ROTATIONS_TOTAL = "mtpu_tsdb_rotations_total"
#: gauge: distinct (series, label set) pairs captured by the last scrape
TSDB_SERIES = "mtpu_tsdb_series"
#: gauge {rule}: 1 while the named alert rule is firing, 0 otherwise
ALERTS_ACTIVE = "mtpu_alerts_active"
#: counter {rule}: fire transitions of the named alert rule (clears don't
#: count — the journal carries the full fire/clear history)
ALERTS_FIRED_TOTAL = "mtpu_alerts_fired_total"
#: counter {trigger}: incident bundles captured; trigger = watchdog_wedge |
#: watchdog_quarantine | scheduler_crash | chaos_invariant | alert |
#: canary_drift | stage_failure | manual
INCIDENTS_CAPTURED_TOTAL = "mtpu_incidents_captured_total"

# -- SLO engine (observability/slo.py) --------------------------------------

#: gauge {slo}: observed/target burn rate per declared SLO (>1 = violating)
SLO_BURN_RATE = "mtpu_slo_burn_rate"

# -- OpenAI-compatible server /metrics (serving/openai_api.py) --------------

GENERATED_TOKENS_TOTAL = "mtpu_generated_tokens_total"
PROMPT_TOKENS_TOTAL = "mtpu_prompt_tokens_total"
DECODE_STEPS_TOTAL = "mtpu_decode_steps_total"
KV_PAGES_FREE = "mtpu_kv_pages_free"
DECODE_IMPL = "mtpu_decode_impl"
SPEC_PROPOSED_TOTAL = "mtpu_spec_proposed_total"
SPEC_ACCEPTED_TOTAL = "mtpu_spec_accepted_total"
# (SPEC_ACCEPTANCE_RATE lives in the fused-speculative section above — the
# /metrics hand-built exposition and the gauge sweep share one name)
PREFIX_CACHE_HITS_TOTAL = "mtpu_prefix_cache_hits_total"
PREFIX_CACHE_MISSES_TOTAL = "mtpu_prefix_cache_misses_total"
PREFIX_CACHED_PAGES = "mtpu_prefix_cached_pages"

# -- roofline / usage accounting (observability/usage.py,
#    docs/observability.md#roofline-and-usage-accounting) --------------------

#: the work-model phase vocabulary the roofline gauges label by: prefill
#: and decode are attributed separately (their roofline positions differ —
#: prefill is compute-rich, decode streams weights+KV), "total" is the
#: flops/bytes-weighted combination the BENCH `utilization` headline uses
ROOFLINE_PHASES = ("prefill", "decode", "total")

#: gauge {phase}: model FLOPs utilization — analytic FLOPs accounted to
#: the phase over (device seconds x peak TFLOP/s x chips), against the
#: core/resources.py bf16 peak for the resolved generation (MTPU_TPU_GEN)
MFU = "mtpu_mfu"
#: gauge {phase}: HBM bandwidth utilization (MBU) — analytic bytes moved
#: (weight stream + kv_dtype-aware KV reads) over (device seconds x peak
#: HBM GB/s x chips); sustained collapse while decodable slots exist is
#: the wedge-precursor signature the mbu_collapse alert rule watches
HBM_BW_UTIL = "mtpu_hbm_bw_util"
#: gauge {phase}: achieved TFLOP/s over the phase's accounted device time
#: (the numerator MFU normalizes — kept as its own series so dashboards
#: can plot absolute roofline position, not just the ratio)
ACHIEVED_TFLOPS = "mtpu_achieved_tflops"

#: counter {tenant, class}: prompt tokens prefilled, attributed to the
#: submitting tenant and priority class (Σ tenants == engine totals —
#: the conservation contract tests/test_usage.py asserts)
USAGE_PROMPT_TOKENS_TOTAL = "mtpu_usage_prompt_tokens_total"
#: counter {tenant, class}: generated tokens accepted per tenant/class
USAGE_GENERATED_TOKENS_TOTAL = "mtpu_usage_generated_tokens_total"
#: counter {tenant, class}: decode-slot occupancy seconds (install ->
#: release on the engine clock) — the device-seconds a tenant held
USAGE_DEVICE_SECONDS_TOTAL = "mtpu_usage_device_seconds_total"
#: counter {tenant, class}: KV page-seconds (pages held x hold seconds)
#: — the HBM-residency integral behind per-tenant memory billing
USAGE_KV_PAGE_SECONDS_TOTAL = "mtpu_usage_kv_page_seconds_total"
#: counter {tenant, class}: admission sheds charged to the tenant whose
#: request was rejected (the per-tenant split of mtpu_sheds_total)
USAGE_SHEDS_TOTAL = "mtpu_usage_sheds_total"

# -- correctness canary (observability/canary.py,
#    docs/observability.md#correctness-canary) -------------------------------

#: counter {replica, result}: golden-set probes completed per replica;
#: result = pass (bit-exact vs golden) | drift (token mismatch) | error
#: (probe died before finishing) | recorded (golden captured on first
#: contact with this model+fingerprint — never compared, never gated)
CANARY_PROBES_TOTAL = "mtpu_canary_probes_total"
#: counter {replica}: probes whose generated tokens diverged bit-exact
#: from the pinned golden transcript — the numeric-drift sentinel the
#: canary_drift alert rule and the router down-weight key on
CANARY_DRIFT_TOTAL = "mtpu_canary_drift_total"
#: histogram {replica}: client-observed TTFT of canary probes (submit ->
#: first streamed piece) — active latency probing on the real serving path
CANARY_TTFT_SECONDS = "mtpu_canary_ttft_seconds"
#: histogram {replica}: client-observed inter-piece latency of canary
#: probes (the probe-side TPOT proxy)
CANARY_TPOT_SECONDS = "mtpu_canary_tpot_seconds"
#: histogram {replica}: end-to-end canary probe latency (submit -> stream
#: drained) — the canary_latency_burn alert rule's input
CANARY_E2E_SECONDS = "mtpu_canary_e2e_seconds"
#: counter {replica, kind}: synthetic canary tokens (kind=prompt|generated)
#: — excluded from per-tenant usage billing and the usage journal, counted
#: here instead so conservation stays closed: Σ usage tenants + canary ==
#: engine totals
CANARY_TOKENS_TOTAL = "mtpu_canary_tokens_total"
#: gauge {replica}: consecutive failing canary rounds (0 = passing);
#: reaching the prober's fail threshold drives router.set_health_weight
CANARY_FAILING = "mtpu_canary_failing"


#: machine-readable catalog: name -> {type, labels, help}. docs/observability
#: renders this; the static guard asserts every emitted name appears here.
CATALOG: dict[str, dict] = {
    CALL_DURATION_SECONDS: {
        "type": "histogram",
        "labels": ["function", "phase"],
        "help": "per-phase call latency "
                "(queue|boot|dispatch|execute|serialize|total)",
    },
    QUEUE_WAIT_SECONDS: {
        "type": "histogram",
        "labels": ["function"],
        "help": "submit-to-dispatch queue wait",
    },
    INFLIGHT_INPUTS: {
        "type": "gauge",
        "labels": ["function"],
        "help": "inputs submitted but not yet completed",
    },
    RETRIES_TOTAL: {
        "type": "counter",
        "labels": ["function", "reason"],
        "help": "retry attempts scheduled "
                "(reason=timeout|container_death|user_error)",
    },
    CONTAINER_KILLS_TOTAL: {
        "type": "counter",
        "labels": ["function", "reason"],
        "help": "containers killed by the supervisor",
    },
    SNAPSHOT_BOOTS_METRIC: {
        "type": "counter",
        "labels": ["function", "result"],
        "help": "snapshot-enabled container boots (result=hit|miss|fallback)",
    },
    SNAPSHOT_CAPTURES_METRIC: {
        "type": "counter",
        "labels": ["function"],
        "help": "memory snapshots captured and published to the store",
    },
    ENGINE_PHASE_SECONDS: {
        "type": "histogram",
        "labels": ["phase"],
        "help": "engine hot-loop phase latency "
                "(prefill|prefill_chunked|decode_wait)",
    },
    ENGINE_BATCH_SIZE: {
        "type": "histogram",
        "labels": [],
        "help": "active slots per dispatched decode block",
    },
    ENGINE_QUEUE_WAIT_SECONDS: {
        "type": "histogram",
        "labels": [],
        "help": "request submit-to-admission wait",
    },
    WAITING_REQUESTS: {
        "type": "gauge",
        "labels": [],
        "help": "requests waiting for admission",
    },
    ACTIVE_SLOTS: {
        "type": "gauge",
        "labels": [],
        "help": "slots currently decoding",
    },
    TOKENS_PER_SECOND: {
        "type": "gauge",
        "labels": [],
        "help": "generated tokens per second since engine start",
    },
    SCHEDULER_ERRORS_TOTAL: {
        "type": "counter",
        "labels": [],
        "help": "engine scheduler-loop exceptions",
    },
    DECODE_STALL_SECONDS: {
        "type": "histogram",
        "labels": [],
        "help": "gap between consecutive decode-block dispatches while "
                "decodable slots exist (stall-free admission contract)",
    },
    PREFILL_BACKLOG_TOKENS: {
        "type": "gauge",
        "labels": [],
        "help": "prompt tokens admitted to slots but not yet prefilled "
                "(sliced-prefill remainder)",
    },
    PREFILL_SLICED_TOTAL: {
        "type": "counter",
        "labels": [],
        "help": "chunked prefills suspended mid-prompt by the per-tick "
                "token budget",
    },
    TTFT_SECONDS: {
        "type": "histogram",
        "labels": [],
        "help": "request submit to first generated token (TTFT)",
    },
    TPOT_SECONDS: {
        "type": "histogram",
        "labels": [],
        "help": "inter-token interval between generated tokens (TPOT)",
    },
    KV_PAGES_USED: {
        "type": "gauge", "labels": [],
        "help": "pages currently allocated out of the paged KV cache",
    },
    KV_PAGE_OCCUPANCY: {
        "type": "gauge", "labels": [],
        "help": "allocated fraction of the usable KV page pool (0..1)",
    },
    KV_CACHE_BYTES: {
        "type": "gauge", "labels": ["dtype"],
        "help": "total HBM bytes of the paged KV cache (dtype-aware)",
    },
    PREFIX_CACHE_EVICTIONS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "zero-ref prefix-cache pages reclaimed under pressure",
    },
    SNAPSHOT_STORE_BYTES: {
        "type": "gauge", "labels": [],
        "help": "total payload bytes resident in the snapshot store",
    },
    SNAPSHOT_STORE_ENTRIES: {
        "type": "gauge", "labels": [],
        "help": "entries resident in the snapshot store",
    },
    SNAPSHOT_STORE_GETS_TOTAL: {
        "type": "counter", "labels": ["result"],
        "help": "snapshot-store lookups (result=hit|miss)",
    },
    HOST_RSS_BYTES: {
        "type": "gauge", "labels": [],
        "help": "supervisor-process resident set size (bytes)",
    },
    SCALER_DECISIONS_TOTAL: {
        "type": "counter", "labels": ["function", "action"],
        "help": "autoscaler decisions journaled "
                "(action=scale_up|scale_down|kill)",
    },
    SHEDS_TOTAL: {
        "type": "counter", "labels": ["class", "reason"],
        "help": "requests shed by admission control "
                "(reason=queue_full|kv_pressure|too_large|injected)",
    },
    REQUESTS_ADMITTED_TOTAL: {
        "type": "counter", "labels": ["class"],
        "help": "requests accepted by admission control",
    },
    SCHED_QUEUE_DEPTH: {
        "type": "gauge", "labels": ["class"],
        "help": "requests queued per priority class",
    },
    SCHED_QUEUE_WAIT_SECONDS: {
        "type": "histogram", "labels": ["class"],
        "help": "per-class request submit-to-admission wait",
    },
    KV_PAGES_RESERVED: {
        "type": "gauge", "labels": [],
        "help": "KV pages reserved by queued (not-yet-claimed) admissions",
    },
    DEADLINE_MISSES_TOTAL: {
        "type": "counter", "labels": ["stage"],
        "help": "requests past their deadline "
                "(stage=queued|prefill|inflight|migrating)",
    },
    ROUTER_REQUESTS_TOTAL: {
        "type": "counter", "labels": ["route"],
        "help": "router placements (route=affinity|fallback)",
    },
    ROUTER_AFFINITY_HITS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "repeated shared-prefix prompts landed on their affinity "
                "replica",
    },
    ROUTER_READMISSIONS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "unhealthy replicas re-admitted after a health re-probe",
    },
    FAULTS_INJECTED_TOTAL: {
        "type": "counter", "labels": ["point"],
        "help": "injected faults fired, by faults/inject.py catalog point",
    },
    DISAGG_MIGRATIONS_TOTAL: {
        "type": "counter", "labels": ["result"],
        "help": "page migrations between replicas "
                "(result=ok|fallback|aborted)",
    },
    DISAGG_PAGES_MIGRATED_TOTAL: {
        "type": "counter", "labels": [],
        "help": "KV pages successfully migrated prefill -> decode",
    },
    DISAGG_MIGRATION_BYTES_TOTAL: {
        "type": "counter", "labels": [],
        "help": "serialized wire bytes of successful page migrations",
    },
    DISAGG_MIGRATION_SECONDS: {
        "type": "histogram", "labels": [],
        "help": "end-to-end migration latency (prefill start to adopt/fail)",
    },
    DISAGG_MIGRATIONS_INFLIGHT: {
        "type": "gauge", "labels": [],
        "help": "migrations currently in flight",
    },
    DISAGG_CHUNK_RETRIES_TOTAL: {
        "type": "counter", "labels": [],
        "help": "transfer chunks re-sent after loss/corruption",
    },
    REPLICA_ROLE: {
        "type": "gauge", "labels": ["replica", "role"],
        "help": "replica serving role, info metric "
                "(role=prefill|decode|unified, value 1)",
    },
    FAILOVER_TOTAL: {
        "type": "counter", "labels": ["mode", "result"],
        "help": "in-flight request takeovers "
                "(mode=reactive|migrate, result=ok|failed)",
    },
    FAILOVER_TOKENS_REPLAYED_TOTAL: {
        "type": "counter", "labels": [],
        "help": "generated-prefix tokens replayed through the decode "
                "program on reactive failover",
    },
    FAILOVER_TAKEOVER_SECONDS: {
        "type": "histogram", "labels": [],
        "help": "takeover latency: failure detected to resumed request "
                "accepted on the new replica",
    },
    MIGRATION_LIVE_TOTAL: {
        "type": "counter", "labels": ["result"],
        "help": "proactive live migrations of mid-decode requests "
                "(result=ok|fallback|aborted|failed)",
    },
    MIGRATION_LIVE_TOKENS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "decode tokens carried across live migrations",
    },
    MIGRATION_LIVE_SECONDS: {
        "type": "histogram", "labels": [],
        "help": "live-migration latency: checkpoint extraction to adopted "
                "on the target",
    },
    PREFIX_TIER_HITS_TOTAL: {
        "type": "counter", "labels": ["tier"],
        "help": "prefix pages served per tier (tier=hbm|host|volume)",
    },
    PREFIX_TIER_PAGES: {
        "type": "gauge", "labels": ["tier"],
        "help": "prefix blocks resident per spill tier",
    },
    PREFIX_TIER_BYTES: {
        "type": "gauge", "labels": ["tier"],
        "help": "serialized bytes resident per spill tier",
    },
    PREFIX_STORE_HITS_TOTAL: {
        "type": "counter", "labels": ["origin"],
        "help": "shared prefix-store blocks served (origin=self|peer; "
                "peer = another replica's spill promoted here)",
    },
    PREFIX_STORE_MISSES_TOTAL: {
        "type": "counter", "labels": [],
        "help": "shared prefix-store lookups that found nothing "
                "(torn blocks dropped count here too)",
    },
    PREFIX_STORE_DEDUP_RATIO: {
        "type": "gauge", "labels": [],
        "help": "logical spill attempts per physical store write "
                "(> 1.0 = cross-replica dedup is paying)",
    },
    PREFIX_STORE_BYTES: {
        "type": "gauge", "labels": [],
        "help": "serialized bytes resident in the shared prefix store",
    },
    PREFIX_STORE_OWNER_TAKEOVERS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "spill leases taken over from dead/expired owner replicas",
    },
    FLEET_REPLICAS: {
        "type": "gauge", "labels": ["role"],
        "help": "replicas registered in the fleet, by serving role",
    },
    FLEET_DECISIONS_TOTAL: {
        "type": "counter", "labels": ["action", "trigger"],
        "help": "fleet autoscaler decisions journaled "
                "(action=scale_up|scale_down, trigger=slo_burn|"
                "queue_pressure|kv_pressure|shed_pressure|idle|"
                "min_replicas|drain_timeout|quarantine)",
    },
    FLEET_BOOT_SECONDS: {
        "type": "histogram", "labels": ["boot"],
        "help": "replica build+start seconds at scale-out "
                "(boot=warm snapshot-restored | cold full init)",
    },
    WATCHDOG_REPLICA_STATE: {
        "type": "gauge", "labels": ["replica", "state"],
        "help": "one-hot watchdog classification per replica "
                "(state=healthy|degraded|wedged|quarantined)",
    },
    WATCHDOG_PROGRESS_AGE_SECONDS: {
        "type": "gauge", "labels": ["replica"],
        "help": "worst stale age among a replica's mandatory progress "
                "watermarks (0 while idle)",
    },
    WATCHDOG_TRANSITIONS_TOTAL: {
        "type": "counter", "labels": ["state"],
        "help": "watchdog classification transitions (entering the state)",
    },
    WATCHDOG_RECOVERIES_TOTAL: {
        "type": "counter", "labels": ["action"],
        "help": "watchdog recovery-ladder actions (action=down_weight|"
                "restore_weight|abort_transfer|stop_revive|quarantine|"
                "unquarantine)",
    },
    TICK_PHASE_SECONDS: {
        "type": "histogram", "labels": ["phase"],
        "help": "scheduler-tick host time per phase (phase=ctrl|policy|"
                "admit|prefill_resume|prefill_dispatch|decode_dispatch|"
                "harvest|detokenize|accept, plus total); emitted only "
                "under MTPU_PROFILE",
    },
    HOST_OVERHEAD_RATIO: {
        "type": "gauge", "labels": [],
        "help": "host share of busy-tick time over the profiler ring "
                "(1 - device-blocked/total) — ROADMAP #3's amortization "
                "target",
    },
    COMPILE_SECONDS: {
        "type": "histogram", "labels": ["program"],
        "help": "jitted-program build seconds at first dispatch "
                "(program=block|prefill|prefill_mm|prefill_chunk|"
                "draft_prefill|spec_verify|ngram_verify|sample|multistep)",
    },
    COMPILES_TOTAL: {
        "type": "counter", "labels": ["program", "cache"],
        "help": "program-cache lookups at jit dispatch sites "
                "(cache=miss fresh build, ledgered | hit served compiled)",
    },
    TSDB_SAMPLES_TOTAL: {
        "type": "counter", "labels": [],
        "help": "sampler scrape cycles appended to the on-disk tsdb",
    },
    TSDB_SCRAPE_SECONDS: {
        "type": "histogram", "labels": [],
        "help": "wall seconds per tsdb scrape cycle (sampler overhead)",
    },
    TSDB_ROTATIONS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "tsdb segment rotations (ring-bounded JSONL segments)",
    },
    TSDB_SERIES: {
        "type": "gauge", "labels": [],
        "help": "distinct series captured by the last tsdb scrape",
    },
    ALERTS_ACTIVE: {
        "type": "gauge", "labels": ["rule"],
        "help": "1 while the named alert rule is firing, 0 otherwise",
    },
    ALERTS_FIRED_TOTAL: {
        "type": "counter", "labels": ["rule"],
        "help": "fire transitions of the named alert rule",
    },
    INCIDENTS_CAPTURED_TOTAL: {
        "type": "counter", "labels": ["trigger"],
        "help": "incident bundles captured (trigger=watchdog_wedge|"
                "watchdog_quarantine|scheduler_crash|chaos_invariant|"
                "alert|canary_drift|stage_failure|manual)",
    },
    SLO_BURN_RATE: {
        "type": "gauge", "labels": ["slo"],
        "help": "observed/target burn rate per declared SLO (>1 violating)",
    },
    GENERATED_TOKENS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "tokens generated by the engine",
    },
    PROMPT_TOKENS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "prompt tokens prefilled by the engine",
    },
    DECODE_STEPS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "decode steps executed",
    },
    KV_PAGES_FREE: {
        "type": "gauge", "labels": [],
        "help": "free pages in the paged KV cache",
    },
    DECODE_IMPL: {
        "type": "gauge",
        "labels": ["attention", "scatter", "kv_dtype", "tp", "variant"],
        "help": (
            "resolved decode implementation plan (info metric, value 1); "
            "tp = tensor-parallel degree, variant = the PER-SHARD ragged "
            "kernel formulation actually run"
        ),
    },
    SPEC_PROPOSED_TOTAL: {
        "type": "counter", "labels": [],
        "help": "draft tokens proposed (speculative mode)",
    },
    SPEC_ACCEPTED_TOTAL: {
        "type": "counter", "labels": [],
        "help": "draft tokens accepted by the target",
    },
    PREFIX_CACHE_HITS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "prefix-cache admission hits",
    },
    PREFIX_CACHE_MISSES_TOTAL: {
        "type": "counter", "labels": [],
        "help": "prefix-cache admission misses",
    },
    PREFIX_CACHED_PAGES: {
        "type": "gauge", "labels": [],
        "help": "pages currently held by the prefix cache",
    },
    MFU: {
        "type": "gauge", "labels": ["phase"],
        "help": "model FLOPs utilization vs the resolved generation's bf16 "
                "peak (phase=prefill|decode|total)",
    },
    HBM_BW_UTIL: {
        "type": "gauge", "labels": ["phase"],
        "help": "HBM bandwidth utilization (MBU): analytic bytes streamed "
                "over device-seconds x peak GB/s (phase=prefill|decode|total)",
    },
    ACHIEVED_TFLOPS: {
        "type": "gauge", "labels": ["phase"],
        "help": "achieved TFLOP/s over the phase's accounted device time",
    },
    USAGE_PROMPT_TOKENS_TOTAL: {
        "type": "counter", "labels": ["tenant", "class"],
        "help": "prompt tokens prefilled per tenant/class (conserved: "
                "sums to the engine's prefill counter)",
    },
    USAGE_GENERATED_TOKENS_TOTAL: {
        "type": "counter", "labels": ["tenant", "class"],
        "help": "generated tokens accepted per tenant/class (conserved: "
                "sums to the engine's decode counter)",
    },
    USAGE_DEVICE_SECONDS_TOTAL: {
        "type": "counter", "labels": ["tenant", "class"],
        "help": "decode-slot occupancy seconds per tenant/class "
                "(install -> release on the engine clock)",
    },
    USAGE_KV_PAGE_SECONDS_TOTAL: {
        "type": "counter", "labels": ["tenant", "class"],
        "help": "KV page-seconds held per tenant/class (pages x seconds)",
    },
    USAGE_SHEDS_TOTAL: {
        "type": "counter", "labels": ["tenant", "class"],
        "help": "admission sheds charged to the rejected tenant/class",
    },
    CANARY_PROBES_TOTAL: {
        "type": "counter", "labels": ["replica", "result"],
        "help": "golden-set canary probes per replica "
                "(result=pass|drift|error|recorded)",
    },
    CANARY_DRIFT_TOTAL: {
        "type": "counter", "labels": ["replica"],
        "help": "canary probes whose generated tokens diverged from the "
                "pinned golden transcript",
    },
    CANARY_TTFT_SECONDS: {
        "type": "histogram", "labels": ["replica"],
        "help": "client-observed TTFT of canary probes",
    },
    CANARY_TPOT_SECONDS: {
        "type": "histogram", "labels": ["replica"],
        "help": "client-observed inter-piece latency of canary probes",
    },
    CANARY_E2E_SECONDS: {
        "type": "histogram", "labels": ["replica"],
        "help": "end-to-end canary probe latency (submit -> stream drained)",
    },
    CANARY_TOKENS_TOTAL: {
        "type": "counter", "labels": ["replica", "kind"],
        "help": "synthetic canary tokens, excluded from tenant billing "
                "(kind=prompt|generated; closes usage conservation)",
    },
    CANARY_FAILING: {
        "type": "gauge", "labels": ["replica"],
        "help": "consecutive failing canary rounds per replica (0=passing)",
    },
    MULTISTEP_DECODE_STEPS: {
        "type": "gauge", "labels": [],
        "help": "configured decode steps fused per dispatch "
                "(decode_steps / MTPU_DECODE_STEPS; 1=classic block path)",
    },
    MULTISTEP_TOKENS_PER_DISPATCH: {
        "type": "gauge", "labels": [],
        "help": "accepted tokens per decode dispatch over the last gauge "
                "window (the macro-step amortization headline)",
    },
    MULTISTEP_DISPATCHES_TOTAL: {
        "type": "counter", "labels": [],
        "help": "decode dispatches harvested (one blocking device read "
                "each)",
    },
    MULTISTEP_TOKENS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "tokens accepted from harvested decode dispatches",
    },
    MULTISTEP_EARLY_EXIT_STEPS_TOTAL: {
        "type": "counter", "labels": [],
        "help": "whole macro-steps skipped by on-device early-exit "
                "(all lanes dead; masked_scan hold branch)",
    },
    MULTISTEP_DETOK_QUEUE_DEPTH: {
        "type": "gauge", "labels": [],
        "help": "events pending on the detokenization worker queue",
    },
    SPEC_GAMMA: {
        "type": "gauge", "labels": [],
        "help": "dispatched per-slot speculation depth, p50 over the last "
                "gauge window (adaptive controller output; 0=classic lane)",
    },
    SPEC_TOKENS_PER_DISPATCH: {
        "type": "gauge", "labels": [],
        "help": "harvested tokens per speculative round over the last "
                "gauge window",
    },
    SPEC_ACCEPTANCE_RATE: {
        "type": "gauge", "labels": [],
        "help": "lifetime draft-token acceptance rate (accepted/proposed)",
    },
    SPEC_FALLBACK_TOTAL: {
        "type": "counter", "labels": [],
        "help": "whole rounds where no slot speculated and the engine fell "
                "through to the classic block program",
    },
}

#: every declared metric name (the static guard's allowlist)
ALL_METRIC_NAMES = frozenset(CATALOG)


# -- request-trace span schema (observability/reqtrace.py, docs/observability)
#
# The metric-catalog discipline applied to the distributed request tracer:
# ONE table owns every span NAME the serving fleet may mint and the
# ATTRIBUTE KEYS each span may carry. ``tests/test_static.py`` enforces the
# closure in both directions (every reqtrace call site names a declared
# span with declared attrs; every declared span has a live call site), so
# the trace schema — what `tpurun explain` parses, what the Perfetto
# export groups into tracks — cannot drift span-by-span the way metric
# names used to.

SPAN_CATALOG: dict[str, dict] = {
    "request": {
        "attrs": ["request_id", "priority", "tenant", "replica",
                  "finish_reason", "n_generated", "ttft_s"],
        "help": "root: one serving request end to end (trace id == request "
                "id); finish_reason lands at close",
    },
    "queue": {
        "attrs": ["priority", "tenant", "replica", "wait_s"],
        "help": "admission queue residency on one replica (opened at "
                "submit, closed when the scheduler pops the entry)",
    },
    "placement": {
        "attrs": ["replica", "route", "prefill_replica", "decode_replica"],
        "help": "router placement decision (route() or disagg plan())",
    },
    "prefill": {
        "attrs": ["replica", "n_prompt", "bucket", "chunked", "chunks",
                  "budget", "sliced"],
        "help": "prompt KV fill on the owning replica (slot, chunked, or "
                "slot-free disagg path); sliced=True when the per-tick "
                "budget spread the chunks over several scheduler ticks",
    },
    "prefill_wait": {
        "attrs": ["replica", "ticks", "chunks"],
        "help": "a sliced (budgeted) chunked prefill's multi-tick "
                "residency: admission to last chunk, spanning the decode "
                "ticks interleaved between its chunks",
    },
    "decode": {
        "attrs": ["replica", "spec_mode"],
        "help": "first token to finish on the decoding replica",
    },
    "migrate": {
        "attrs": ["replica", "source", "target", "pages", "wire_bytes",
                  "result"],
        "help": "one disagg page migration end to end "
                "(result=ok|fallback|aborted)",
    },
    "transfer": {
        "attrs": ["replica", "chunks", "rounds", "wire_bytes"],
        "help": "chunked wire transfer of a serialized page block",
    },
    "chunk": {
        "attrs": ["replica", "seq", "nbytes", "round"],
        "help": "one wire chunk send (child of transfer)",
    },
    "adopt": {
        "attrs": ["replica", "pages"],
        "help": "migrated block scattered into the decode replica's cache "
                "(on its scheduler thread)",
    },
    "failover": {
        "attrs": ["replica", "source", "target", "mode", "position",
                  "tokens_replayed", "result"],
        "help": "an in-flight request's takeover by another replica "
                "(mode=reactive re-prefill | migrate live KV move); "
                "extends the SAME trace id past the failed replica's root "
                "close, so `tpurun explain` shows death and resumption on "
                "one timeline",
    },
    "spec_verify": {
        "attrs": ["replica", "proposed", "accepted", "gamma"],
        "help": "one fused speculative round's outcome for this request "
                "(event; gamma = the depth the adaptive controller "
                "dispatched, docs/speculative.md#gamma-schedule)",
    },
    "fault": {
        "attrs": ["replica", "point"],
        "help": "an injected fault (faults/inject.py POINTS) fired on this "
                "request's path (event)",
    },
    "watchdog": {
        "attrs": ["replica", "state", "action"],
        "help": "the gray-failure watchdog intervened on this request's "
                "replica (serving/health.py ladder: state=wedged, "
                "action=stop_revive|quarantine) — shows between the hang "
                "and the failover seam on the stitched timeline (event)",
    },
    "retry_wait": {
        "attrs": ["replica", "round", "pending", "delay_s"],
        "help": "jittered backoff before a transfer chunk-retry round "
                "(event)",
    },
    "shed": {
        "attrs": ["replica", "reason"],
        "help": "admission rejected the request (the 429 path; event)",
    },
    "tier_promote": {
        "attrs": ["replica", "tier", "pages"],
        "help": "prefix pages promoted from a lower cache tier during the "
                "claim (event)",
    },
}

#: every declared request-span name (the static guard's allowlist)
ALL_SPAN_NAMES = frozenset(SPAN_CATALOG)

#: span names the EXECUTOR call tracer mints (PR 2; core/executor.py +
#: container worker) — a separate namespace from the request spans above
#: (trace id ``in-…`` vs ``req-…``), listed so renderers/exporters can
#: tell the two trace kinds apart
CALL_SPAN_NAMES = frozenset(
    {"call", "queue", "boot", "dispatch", "execute", "serialize", "retry"}
)

#: buckets for batch-size-style histograms (counts, not seconds)
COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

#: buckets for token-level latency (TTFT/TPOT): finer sub-ms resolution at
#: the low end than the boot-scale default buckets, topping out at 30 s
TOKEN_TIME_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)

#: buckets for mtpu_tick_phase_seconds: most scheduler-tick phases are
#: tens of MICROseconds (ctrl/policy/harvest bookkeeping) while dispatch
#: phases reach tens of milliseconds — TOKEN_TIME_BUCKETS' 0.5 ms floor
#: would collapse every cheap phase into its first bucket and the
#: `tpurun profile` p50/p95 table (the ROADMAP #3 ranking instrument)
#: could not tell a 5 us phase from a 400 us one
TICK_PHASE_BUCKETS = (
    0.000005, 0.00001, 0.000025, 0.00005, 0.0001, 0.00025, 0.0005,
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0,
)
