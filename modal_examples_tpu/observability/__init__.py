"""Observability: call-lifecycle tracing + metric series for the framework.

The pieces (all stdlib-only — core/ imports this layer and must stay
jax-free):

- :mod:`.catalog` — the ONE place every ``mtpu_*`` metric name is declared
  (enforced by ``tests/test_static.py``);
- :mod:`.trace`   — span model, per-call JSONL trace files, cross-process
  context propagation (``tpurun trace <call_id>`` reads these);
- :mod:`.reqtrace` — request-scoped DISTRIBUTED tracing over the serving
  fleet: one trace id per request (== the request id) stitched across
  gateway, scheduler queues, router, prefill/decode replicas, and the
  disagg page-migration wire (``tpurun explain <request_id>``);
- :mod:`.metrics` — recorder functions the executor/engine call to emit
  catalog series into the prometheus registry;
- :mod:`.export`  — file-backed push gateway for ephemeral processes
  (``tpurun metrics`` merges the pushed expositions) + the Perfetto /
  chrome://tracing converter (``tpurun trace <id> --perfetto``);
- :mod:`.journal` — the autoscaler decision journal (``tpurun scaler``,
  gateway ``/autoscaler``);
- :mod:`.slo`     — declared latency/error targets evaluated against the
  live histograms (gateway ``/healthz``, ``tpurun top``).

User code inside a remote function can nest its own spans::

    from modal_examples_tpu.observability import span

    @app.function()
    def work(x):
        with span("load-model"):
            ...
"""

from __future__ import annotations

from . import catalog
from .export import (
    export_chrome_trace,
    live_and_pushed_metrics,
    push_metrics_file,
    pushed_jobs,
    read_pushed_metrics,
    spans_to_chrome_trace,
)
from . import alerts
from . import incident
from . import timeseries
from .alerts import DEFAULT_RULES, AlertEvaluator, AlertRule
from .incident import capture as capture_incident, list_incidents
from .journal import (
    JOURNALS,
    DecisionJournal,
    default_journal,
    named_journal,
)
from .timeseries import TsdbSampler, ensure_sampler, read_window
from .metrics import (
    record_container_kill,
    record_engine_batch,
    record_engine_phase,
    record_engine_queue_wait,
    record_phase,
    record_prefix_evictions,
    record_queue_wait,
    record_retry,
    record_scaler_decision,
    record_scheduler_error,
    record_snapshot_store_get,
    record_token_totals,
    record_tpot,
    record_ttft,
    sample_host_rss,
    set_engine_gauges,
    set_inflight,
    set_kv_occupancy,
    set_prefix_cache_pages,
    set_snapshot_store_size,
)
from . import profiler
from .profiler import HotPathProfiler
from . import reqtrace
from .reqtrace import explain_lines, finish_request, start_request_trace
from .slo import DEFAULT_SLOS, SLO, evaluate as evaluate_slos, healthz
from .trace import (
    Span,
    TraceContext,
    TraceStore,
    current_context,
    current_trace_id,
    default_store,
    set_context,
    span,
    tracing_enabled,
)

__all__ = [
    "DEFAULT_RULES",
    "DEFAULT_SLOS",
    "AlertEvaluator",
    "AlertRule",
    "DecisionJournal",
    "HotPathProfiler",
    "JOURNALS",
    "TsdbSampler",
    "alerts",
    "capture_incident",
    "ensure_sampler",
    "incident",
    "list_incidents",
    "named_journal",
    "read_window",
    "timeseries",
    "SLO",
    "Span",
    "TraceContext",
    "TraceStore",
    "catalog",
    "current_context",
    "current_trace_id",
    "default_journal",
    "default_store",
    "evaluate_slos",
    "explain_lines",
    "export_chrome_trace",
    "finish_request",
    "healthz",
    "live_and_pushed_metrics",
    "push_metrics_file",
    "pushed_jobs",
    "read_pushed_metrics",
    "record_container_kill",
    "record_engine_batch",
    "record_engine_phase",
    "record_engine_queue_wait",
    "record_phase",
    "record_prefix_evictions",
    "record_queue_wait",
    "record_retry",
    "record_scaler_decision",
    "record_scheduler_error",
    "record_snapshot_store_get",
    "record_token_totals",
    "record_tpot",
    "record_ttft",
    "profiler",
    "reqtrace",
    "sample_host_rss",
    "set_context",
    "start_request_trace",
    "set_engine_gauges",
    "set_inflight",
    "set_kv_occupancy",
    "set_prefix_cache_pages",
    "set_snapshot_store_size",
    "span",
    "spans_to_chrome_trace",
    "tracing_enabled",
]
