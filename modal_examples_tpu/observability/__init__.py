"""Observability: call-lifecycle tracing + metric series for the framework.

The pieces (all stdlib-only — core/ imports this layer and must stay
jax-free):

- :mod:`.catalog` — the ONE place every ``mtpu_*`` metric name is declared
  (enforced by ``tests/test_static.py``);
- :mod:`.trace`   — span model, per-call JSONL trace files, cross-process
  context propagation (``tpurun trace <call_id>`` reads these);
- :mod:`.metrics` — recorder functions the executor/engine call to emit
  catalog series into the prometheus registry;
- :mod:`.export`  — file-backed push gateway for ephemeral processes
  (``tpurun metrics`` merges the pushed expositions).

User code inside a remote function can nest its own spans::

    from modal_examples_tpu.observability import span

    @app.function()
    def work(x):
        with span("load-model"):
            ...
"""

from __future__ import annotations

from . import catalog
from .export import (
    live_and_pushed_metrics,
    push_metrics_file,
    pushed_jobs,
    read_pushed_metrics,
)
from .metrics import (
    record_container_kill,
    record_engine_batch,
    record_engine_phase,
    record_engine_queue_wait,
    record_phase,
    record_queue_wait,
    record_retry,
    record_scheduler_error,
    set_engine_gauges,
    set_inflight,
)
from .trace import (
    Span,
    TraceContext,
    TraceStore,
    current_context,
    current_trace_id,
    default_store,
    set_context,
    span,
    tracing_enabled,
)

__all__ = [
    "Span",
    "TraceContext",
    "TraceStore",
    "catalog",
    "current_context",
    "current_trace_id",
    "default_store",
    "live_and_pushed_metrics",
    "push_metrics_file",
    "pushed_jobs",
    "read_pushed_metrics",
    "record_container_kill",
    "record_engine_batch",
    "record_engine_phase",
    "record_engine_queue_wait",
    "record_phase",
    "record_queue_wait",
    "record_retry",
    "record_scheduler_error",
    "set_context",
    "set_engine_gauges",
    "set_inflight",
    "span",
    "tracing_enabled",
]
