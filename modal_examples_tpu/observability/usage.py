"""Hardware-utilization accounting: roofline MFU/MBU meters and
per-tenant usage metering (docs/observability.md#roofline-and-usage-accounting).

The north star is "as fast as the hardware allows", and PR 14's profiler
can attribute WHERE time goes — but nothing converted device time plus the
analytic cost models already in the repo (the ``flops=`` estimates on the
attention kernels, ``core/resources.py``'s per-generation peaks) into
achieved-vs-peak utilization, and the multi-tenant scheduler tracked
tenants without ever metering what each consumed. This module closes both
gaps with three cooperating pieces:

- :class:`WorkModel` — the analytic per-request cost model, derived ONCE
  per engine from the model config and cache geometry: prefill FLOPs ≈
  2·N_params·T plus the causal-attention term, decode bytes/token ≈
  weight bytes + kv_dtype-aware KV-read bytes (the ``kv_cache`` section's
  bytes-per-page math, so int8 KV halves the modeled traffic exactly like
  it halves the real traffic). Pure integer/float arithmetic —
  hand-checkable in tests and deterministic by construction.
- the **roofline meter** — cheap integer accumulators fed from the
  engine's existing token-accounting sites (no new timestamps on the per
  -token path; device seconds are bracketed around the two blocking
  reads on the engine's injectable clock), lazily joined with the work
  model into cataloged MFU / MBU / achieved-TFLOP/s gauges per phase and
  a compute-vs-bandwidth bound classification against the
  ``core/resources.py`` peaks (generation resolved from ``MTPU_TPU_GEN``,
  default v5e).
- the **usage meter** — per-(tenant, class) buckets (prompt + generated
  tokens, slot device-seconds, KV page-seconds, sheds) updated at the
  SAME sites that update ``EngineStats``, so conservation (Σ tenants ==
  engine totals) is structural, not reconciled; per-request records land
  in the ``usage.jsonl`` journal at stream finish.

Counter emission rides the engine's throttled gauge refresh (the
``record_token_totals`` delta-flush pattern); the per-token hot-path cost
is a handful of integer adds under one small lock.

jax-free and import-light, like the rest of ``observability/``.
"""

from __future__ import annotations

import threading
import time

from . import catalog as C
from . import metrics as _obs
from .canary import CANARY_TENANT
from .journal import JOURNALS, DecisionJournal, named_journal

#: generation override for peak resolution (one env, read once per engine
#: at meter construction — the MTPU_KV_DTYPE rule)
GENERATION_ENV = "MTPU_TPU_GEN"
#: the fleet's deploy target; also the honest CPU-run denominator — a CPU
#: bench reports MFU against the chip it is standing in for
DEFAULT_GENERATION = "v5e"

#: the journal file name under ``<state_dir>`` — owned by the JOURNALS
#: table and resolved through ``named_journal("usage")``
USAGE_JOURNAL_NAME = JOURNALS["usage"]


def resolve_peaks(generation: str | None = None, chips: int = 1) -> dict:
    """Peak FLOP/s and HBM bandwidth for the accounting denominator:
    explicit arg beats :data:`GENERATION_ENV` beats :data:`DEFAULT_GENERATION`;
    an unknown generation falls back to the default instead of refusing to
    meter. ``chips`` scales both peaks (tensor parallelism spreads one
    model's work over the mesh)."""
    import os

    from ..core.resources import TPU_GENERATIONS, TPU_HBM_GBPS

    gen = (
        generation or os.environ.get(GENERATION_ENV) or DEFAULT_GENERATION
    ).lower()
    if gen not in TPU_GENERATIONS:
        gen = DEFAULT_GENERATION
    return {
        "generation": gen,
        "chips": max(1, int(chips)),
        "tflops_per_chip": TPU_GENERATIONS[gen][2],
        "hbm_gbps_per_chip": TPU_HBM_GBPS[gen],
    }


class WorkModel:
    """Analytic per-request work model, frozen at engine build.

    FLOPs follow the standard transformer accounting (2 multiply-adds per
    weight per token) plus the attention terms the weight count misses —
    the same formulation as the kernel-level ``flops=`` estimates on
    ``ops/flash_attention.py`` (causal: half the S×S score matrix) and
    ``ops/paged_attention.py`` (decode: one query row over the context):

    - prefill:  ``2·N·T  +  2·L·D·T²``   per request of T prompt tokens
    - decode:   ``2·N    +  4·L·D·ctx``  per generated token at context ctx

    Bytes model the two HBM streams decode actually pays: the full weight
    read per token and the KV history read, where ``kv_bytes_per_token``
    comes from the cache's own dtype-aware byte count divided by its token
    capacity — int8 KV (payload + f32 scale rows) prices itself. Prefill
    bytes are one weight stream per dispatched program plus the KV written.
    """

    __slots__ = (
        "n_params", "n_layers", "dim", "weight_bytes", "kv_bytes_per_token",
    )

    def __init__(
        self, *, n_params: int, n_layers: int, dim: int,
        weight_bytes: int, kv_bytes_per_token: float,
    ):
        self.n_params = int(n_params)
        self.n_layers = int(n_layers)
        self.dim = int(dim)
        self.weight_bytes = int(weight_bytes)
        self.kv_bytes_per_token = float(kv_bytes_per_token)

    @classmethod
    def from_engine(cls, cfg, *, cache, weight_bytes: int) -> "WorkModel":
        """Derive the model from a built engine's pieces: the llama config
        (parameter count, layer geometry) and the paged cache (dtype-aware
        total bytes over ``n_pages × page_size`` token capacity)."""
        return cls(
            n_params=int(cfg.param_count),
            n_layers=int(cfg.n_layers),
            dim=int(cfg.dim),
            weight_bytes=int(weight_bytes),
            kv_bytes_per_token=(
                cache.bytes() / float(cache.n_pages * cache.page_size)
            ),
        )

    # -- FLOPs ---------------------------------------------------------------

    def prefill_flops(self, n_tokens: int, sq_tokens: int = 0) -> int:
        """FLOPs to prefill prompts totalling ``n_tokens`` whose per-request
        squared lengths sum to ``sq_tokens`` (the causal-attention term is
        quadratic per request, so Σ T² must be accumulated, not (Σ T)²)."""
        return int(
            2 * self.n_params * n_tokens
            + 2 * self.n_layers * self.dim * sq_tokens
        )

    def decode_flops(self, n_tokens: int, ctx_sum: int = 0) -> int:
        """FLOPs to decode ``n_tokens`` whose context lengths at decode
        time sum to ``ctx_sum`` (QK over the history + AV back: 4·ctx·D
        per layer per token)."""
        return int(
            2 * self.n_params * n_tokens
            + 4 * self.n_layers * self.dim * ctx_sum
        )

    # -- bytes ---------------------------------------------------------------

    def prefill_bytes(self, n_tokens: int, n_calls: int = 0) -> int:
        """HBM bytes for prefill: one weight stream per dispatched prefill
        program (batched admissions share the read) plus the KV written."""
        return int(
            n_calls * self.weight_bytes
            + self.kv_bytes_per_token * n_tokens
        )

    def decode_bytes(self, n_tokens: int, ctx_sum: int = 0) -> int:
        """HBM bytes for decode: the ISSUE's per-token model — weight bytes
        plus the kv_dtype-aware KV history read (an upper bound at batch >
        1, where concurrent slots amortize the weight stream; the bound is
        what MBU must be honest against)."""
        return int(
            n_tokens * self.weight_bytes
            + self.kv_bytes_per_token * ctx_sum
        )


def _bucket() -> dict:
    return {
        "prompt_tokens": 0,
        "generated_tokens": 0,
        "device_seconds": 0.0,
        "kv_page_seconds": 0.0,
        "sheds": 0,
        "requests": 0,
    }


class EngineUsage:
    """Per-engine accountant: roofline accumulators + per-tenant meters.

    Every hook is a few integer adds under one lock — safe from the
    scheduler thread plus concurrent ``prefill_sync`` server threads, and
    cheap enough to run unconditionally (no zero-cost-off gate: unlike the
    profiler there are no extra timestamps on the per-token path)."""

    def __init__(
        self,
        model: WorkModel,
        *,
        clock=None,
        name="engine",
        chips: int = 1,
        generation: str | None = None,
        registry=None,
        journal_path=None,
    ):
        self.model = model
        self.peaks = resolve_peaks(generation, chips=chips)
        self._clock = clock or time.monotonic
        self._name = name
        self._registry = registry
        self._journal_path = journal_path
        self._journal: DecisionJournal | None = None
        self._lock = threading.Lock()
        # roofline work accumulators (plain ints: deterministic, no floats
        # on the token path except phase seconds from the injectable clock)
        self._prefill_tokens = 0
        self._prefill_sq_tokens = 0
        self._prefill_calls = 0
        self._decode_tokens = 0
        self._decode_ctx_sum = 0
        self._phase_seconds = {"prefill": 0.0, "decode": 0.0}
        # per-(tenant, class) buckets + the last-flushed mirror (counters
        # take deltas; the buckets hold the running totals)
        self._buckets: dict[tuple[str, str], dict] = {}
        self._flushed: dict[tuple[str, str], dict] = {}
        # synthetic canary probes (observability/canary.py): excluded from
        # the tenant buckets and the usage journal — nobody is billed for
        # the fleet probing itself — but the tokens are REAL device work, so
        # they keep feeding the roofline accumulators and land in their own
        # mtpu_canary_tokens_total series; conservation stays closed as
        # Σ tenant buckets + canary == the engine's stats counters
        self._canary = {"prompt_tokens": 0, "generated_tokens": 0}
        self._canary_flushed = {"prompt_tokens": 0, "generated_tokens": 0}

    @property
    def replica(self) -> str:
        return str(self._name() if callable(self._name) else self._name)

    def _b(self, tenant: str, klass: str) -> dict:
        key = (str(tenant), str(klass))
        b = self._buckets.get(key)
        if b is None:
            b = self._buckets[key] = _bucket()
        return b

    # -- hot-path hooks (mirror the EngineStats sites exactly) ---------------

    def note_prompt(self, req, n_tokens: int, *, calls: int = 1) -> None:
        """Prompt tokens accepted into KV — called at BOTH engine sites
        that bump ``stats.prompt_tokens`` (slot harvest and the slot-free
        disagg prefill), so Σ tenants == the engine counter."""
        n = int(n_tokens)
        with self._lock:
            if req.tenant == CANARY_TENANT:
                self._canary["prompt_tokens"] += n
            else:
                b = self._b(req.tenant, req.priority)
                b["prompt_tokens"] += n
                b["requests"] += 1
            self._prefill_tokens += n
            self._prefill_sq_tokens += n * n
            self._prefill_calls += int(calls)
        # the journal records what was ACCOUNTED, not what was submitted —
        # a request shed before prefill must journal 0 prompt tokens or
        # the Σ-journal == engine-counter conservation breaks
        req._usage_prompt = getattr(req, "_usage_prompt", 0) + n

    def note_token(self, req, ctx: int) -> None:
        """One generated token accepted at context length ``ctx`` — called
        from the ONE site that bumps ``stats.generated_tokens``."""
        with self._lock:
            if req.tenant == CANARY_TENANT:
                self._canary["generated_tokens"] += 1
            else:
                self._b(req.tenant, req.priority)["generated_tokens"] += 1
            self._decode_tokens += 1
            self._decode_ctx_sum += int(ctx)

    def note_phase_seconds(self, phase: str, seconds: float) -> None:
        """Device-attributed seconds for ``phase`` ("prefill" | "decode"),
        measured by the engine around its blocking reads on the injectable
        clock — the denominator under MFU/MBU."""
        if seconds > 0:
            with self._lock:
                self._phase_seconds[phase] = (
                    self._phase_seconds.get(phase, 0.0) + float(seconds)
                )

    def note_slot_release(self, req, *, pages: int, held_s: float) -> None:
        """A decode slot released its pages: charge the occupancy interval
        (device-seconds) and its KV-residency integral (page-seconds)."""
        if req.tenant == CANARY_TENANT:
            return  # probe residency bills nobody
        held = max(0.0, float(held_s))
        with self._lock:
            b = self._b(req.tenant, req.priority)
            b["device_seconds"] += held
            b["kv_page_seconds"] += held * int(pages)

    def note_shed(self, tenant: str, klass: str) -> None:
        """Admission rejected a request: charge the tenant. Sheds are rare,
        so the cataloged counter increments immediately (no delta flush)."""
        with self._lock:
            self._b(tenant, klass)["sheds"] += 1
        _obs.record_usage_shed(tenant, klass, registry=self._registry)

    def note_finish(self, req, reason: str) -> None:
        """Terminal delivery: one ``usage.jsonl`` record per request (the
        billing line). Guarded so a request that finishes through more than
        one path journals exactly once."""
        if getattr(req, "_usage_journaled", False):
            return
        req._usage_journaled = True
        if req.tenant == CANARY_TENANT:
            return  # probes never land a billing line; see canary.jsonl
        self._journal_record({
            "at": time.time(),
            "replica": self.replica,
            "request_id": req.request_id,
            "tenant": req.tenant,
            "class": req.priority,
            "prompt_tokens": int(getattr(req, "_usage_prompt", 0)),
            "generated_tokens": int(req.n_generated),
            "cached_prompt_tokens": int(
                getattr(req, "cached_prompt_tokens", 0)
            ),
            "finish_reason": reason,
        })

    def _journal_record(self, rec: dict) -> None:
        if self._journal is None:
            self._journal = named_journal("usage", path=self._journal_path)
        self._journal.record(rec)

    # -- read surfaces -------------------------------------------------------

    def summary(self) -> dict:
        """The roofline position: per-phase analytic FLOPs/bytes joined
        with the accounted device seconds against the resolved peaks. A
        pure function of the accumulators — fake-clock runs are exactly
        reproducible."""
        with self._lock:
            pt, psq, pcalls = (
                self._prefill_tokens, self._prefill_sq_tokens,
                self._prefill_calls,
            )
            dt, dctx = self._decode_tokens, self._decode_ctx_sum
            secs = dict(self._phase_seconds)
        m = self.model
        chips = self.peaks["chips"]
        peak_flops = self.peaks["tflops_per_chip"] * 1e12 * chips
        peak_bps = self.peaks["hbm_gbps_per_chip"] * 1e9 * chips
        work = {
            "prefill": (
                m.prefill_flops(pt, psq), m.prefill_bytes(pt, pcalls),
                secs.get("prefill", 0.0),
            ),
            "decode": (
                m.decode_flops(dt, dctx), m.decode_bytes(dt, dctx),
                secs.get("decode", 0.0),
            ),
        }
        work["total"] = tuple(
            sum(w[i] for w in work.values()) for i in range(3)
        )
        phases = {}
        for phase, (flops, nbytes, s) in work.items():
            if s > 0:
                tflops = flops / s / 1e12
                gbps = nbytes / s / 1e9
                mfu = flops / (s * peak_flops)
                mbu = nbytes / (s * peak_bps)
                bound = "compute" if mfu >= mbu else "bandwidth"
            else:
                tflops = gbps = mfu = mbu = 0.0
                bound = None
            phases[phase] = {
                "flops": int(flops),
                "bytes": int(nbytes),
                "device_seconds": round(s, 6),
                "achieved_tflops": round(tflops, 6),
                "achieved_gbps": round(gbps, 6),
                "mfu": round(mfu, 6),
                "mbu": round(mbu, 6),
                "bound": bound,
            }
        return {
            "generation": self.peaks["generation"],
            "chips": chips,
            "phases": phases,
        }

    def utilization_section(
        self, *, tokens_per_second: float | None = None
    ) -> dict:
        """The BENCH ``utilization`` section ``bench_diff`` gates: headline
        MFU/MBU from the combined phase, the bound classification (decode
        dominates serving, so a phase-less run defaults to bandwidth), and
        tok/s normalized per chip."""
        s = self.summary()
        tot = s["phases"]["total"]
        return {
            "mfu": tot["mfu"],
            "mbu": tot["mbu"],
            "bound": tot["bound"] or "bandwidth",
            "tokens_per_second_per_chip": (
                round(float(tokens_per_second) / s["chips"], 2)
                if tokens_per_second is not None else None
            ),
            "generation": s["generation"],
            "chips": s["chips"],
            "per_phase": {
                k: s["phases"][k] for k in ("prefill", "decode")
            },
            "work_model": {
                "n_params": self.model.n_params,
                "weight_bytes": self.model.weight_bytes,
                "kv_bytes_per_token": round(
                    self.model.kv_bytes_per_token, 3
                ),
            },
        }

    def tenants(self) -> dict:
        """Per-(tenant, class) running totals plus the conservation sums —
        the gateway's ``/usage`` payload and the CLI's table source."""
        with self._lock:
            rows = [
                {"tenant": t, "class": k, **{
                    f: (round(v, 6) if isinstance(v, float) else v)
                    for f, v in b.items()
                }}
                for (t, k), b in sorted(self._buckets.items())
            ]
            totals = _bucket()
            for b in self._buckets.values():
                for f in totals:
                    totals[f] += b[f]
        totals = {
            f: (round(v, 6) if isinstance(v, float) else v)
            for f, v in totals.items()
        }
        with self._lock:
            canary = dict(self._canary)
        return {"tenants": rows, "totals": totals, "canary": canary}

    def flush(self, registry=None) -> None:
        """Push accumulated deltas into the cataloged per-tenant counters
        and refresh the roofline gauges — called from the engine's
        throttled gauge refresh and unthrottled from ``stop()`` (the
        ``_flush_token_counters`` contract: the final sub-throttle window
        is never lost from a pushed exposition)."""
        reg = registry if registry is not None else self._registry
        with self._lock:
            deltas = []
            for key, b in self._buckets.items():
                last = self._flushed.setdefault(key, _bucket())
                d = {f: b[f] - last[f] for f in b}
                if any(d[f] for f in (
                    "prompt_tokens", "generated_tokens",
                    "device_seconds", "kv_page_seconds",
                )):
                    deltas.append((key, d))
                self._flushed[key] = dict(b)
            canary_d = {
                f: self._canary[f] - self._canary_flushed[f]
                for f in self._canary
            }
            self._canary_flushed = dict(self._canary)
        if any(canary_d.values()):
            _obs.record_canary_tokens(
                self.replica,
                prompt=canary_d["prompt_tokens"],
                generated=canary_d["generated_tokens"],
                registry=reg,
            )
        for (tenant, klass), d in deltas:
            _obs.record_usage_tokens(
                tenant, klass,
                prompt=d["prompt_tokens"], generated=d["generated_tokens"],
                registry=reg,
            )
            _obs.record_usage_seconds(
                tenant, klass,
                device_seconds=d["device_seconds"],
                kv_page_seconds=d["kv_page_seconds"],
                registry=reg,
            )
        s = self.summary()
        for phase, p in s["phases"].items():
            _obs.set_roofline(
                phase, mfu=p["mfu"], mbu=p["mbu"],
                tflops=p["achieved_tflops"], registry=reg,
            )


def read_usage_journal(path=None, n: int = 500) -> list[dict]:
    """Newest-last slice of the usage journal (jax-free — ``tpurun usage``
    and the gateway read it without touching an engine)."""
    return named_journal("usage", path=path).tail(n)


def journal_tenant_totals(records: list[dict]) -> dict:
    """Fold per-request journal records into per-tenant token totals — the
    offline half of the conservation contract (Σ journal == the engine's
    prefill+decode counters for the same run)."""
    out: dict[str, dict] = {}
    for rec in records:
        t = str(rec.get("tenant", "default"))
        b = out.setdefault(
            t, {"prompt_tokens": 0, "generated_tokens": 0, "requests": 0}
        )
        b["prompt_tokens"] += int(rec.get("prompt_tokens", 0) or 0)
        b["generated_tokens"] += int(rec.get("generated_tokens", 0) or 0)
        b["requests"] += 1
    return out
