"""Llama-family decoder LM — the framework's flagship model.

Serves the north-star config (BASELINE.md: Llama-2-7B at >= A100-class
tok/s/chip on v5e) and the LLM workloads the reference delegates to
vLLM/SGLang/TRT-LLM (06_gpu_and_ml/llm-serving/vllm_inference.py,
unsloth_finetune.py). Architecture covers Llama 2/3 and friends: RMSNorm,
RoPE, GQA, SwiGLU.

TPU-first design:
- parameters are a pytree of bf16 arrays; ``partition_specs()`` gives the
  tensor-parallel NamedSharding layout (column-parallel wq/wk/wv/gate/up,
  row-parallel wo/down — XLA inserts the psum over the ``tensor`` ICI axis);
- training/prefill attention is the Pallas flash kernel; serving decode is
  the Pallas ragged paged kernel against an HBM page cache;
- per-layer weights are stacked along a leading axis and the layer loop is a
  ``lax.scan`` — one compiled layer body instead of n_layers copies (compile
  time and code size stay O(1) in depth);
- init is sharded: each weight is created directly on its target devices via
  jit so a 7B model never materializes on one host.

HF interop: ``load_hf_weights()`` maps safetensors checkpoints (the HF cache
volume pattern, vllm_inference.py:77) into this tree without a 2x RAM spike.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops import (
    is_quantized,
    kv_gather,
    kv_scatter,
    mesh_tp_degree,
    paged_decode_attention_inflight,
    sharded_flash_attention,
    sharded_flash_attention_chunked,
    sharded_paged_decode_attention,
    sharded_ragged_decode,
    sharded_scatter_kv_pages,
)
from . import layers


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 4096
    dtype: str = "bfloat16"
    tie_embeddings: bool = False
    # MoE (Mixtral-style): n_experts > 0 replaces the dense SwiGLU MLP with
    # a routed expert MLP (models.moe); serving decode for MoE is a
    # round-2 item — training/forward support here.
    n_experts: int = 0
    top_k_experts: int = 2
    expert_capacity_factor: float = 1.5
    # llama3.1-style rope scaling (HF config 'rope_scaling'); hashable for
    # static jit args
    rope_scaling: tuple | None = None  # tuple(sorted(dict.items())) or None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def param_count(self) -> int:
        emb = self.vocab_size * self.dim * (1 if self.tie_embeddings else 2)
        if self.n_experts > 0:
            mlp = self.n_experts * 3 * self.dim * self.ffn_dim + self.dim * self.n_experts
        else:
            mlp = 3 * self.dim * self.ffn_dim  # gate/up/down
        per_layer = (
            self.dim * self.head_dim * (self.n_heads + 2 * self.n_kv_heads)  # qkv
            + self.n_heads * self.head_dim * self.dim  # o
            + mlp
            + 2 * self.dim  # norms
        )
        return emb + self.n_layers * per_layer + self.dim

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            ffn_dim=14336, rope_theta=500000.0, max_seq_len=8192,
        )

    @staticmethod
    def llama31_8b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            ffn_dim=14336, rope_theta=500000.0, max_seq_len=131072,
            rope_scaling=(
                ("factor", 8.0), ("high_freq_factor", 4.0),
                ("low_freq_factor", 1.0),
                ("original_max_position_embeddings", 8192),
            ),
        )

    @staticmethod
    def llama32_1b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
            ffn_dim=8192, rope_theta=500000.0, max_seq_len=131072,
            tie_embeddings=True,
            rope_scaling=(
                ("factor", 32.0), ("high_freq_factor", 4.0),
                ("low_freq_factor", 1.0),
                ("original_max_position_embeddings", 8192),
            ),
        )

    @staticmethod
    def mistral_7b() -> "LlamaConfig":
        # sliding-window attention not yet modeled; full attention within
        # max_seq_len is exact for contexts <= the window (4096)
        return LlamaConfig(
            vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            ffn_dim=14336, rope_theta=10000.0, max_seq_len=4096,
        )

    @staticmethod
    def mixtral_8x7b() -> "LlamaConfig":
        # the Mixtral-shape MoE (reference serves MoE models engine-side:
        # vllm_inference.py:54-58, sglang_low_latency.py:67)
        return LlamaConfig(
            vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            ffn_dim=14336, rope_theta=1e6, max_seq_len=32768,
            n_experts=8, top_k_experts=2,
        )

    @staticmethod
    def tiny_moe(vocab_size: int = 512) -> "LlamaConfig":
        """Test-tier Mixtral-shape config (cheap-mode switch, SURVEY.md §4)."""
        return LlamaConfig(
            vocab_size=vocab_size, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=256, max_seq_len=256, n_experts=4, top_k_experts=2,
        )

    @staticmethod
    def tiny(vocab_size: int = 512) -> "LlamaConfig":
        """Test-tier config (the reference's cheap-mode switch, SURVEY.md §4)."""
        return LlamaConfig(
            vocab_size=vocab_size, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=256, max_seq_len=256,
        )

    @staticmethod
    def from_hf_config(path: str | Path) -> "LlamaConfig":
        cfg = json.loads(Path(path).read_text())
        return LlamaConfig(
            vocab_size=cfg["vocab_size"],
            dim=cfg["hidden_size"],
            n_layers=cfg["num_hidden_layers"],
            n_heads=cfg["num_attention_heads"],
            n_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
            ffn_dim=cfg["intermediate_size"],
            rope_theta=cfg.get("rope_theta", 10000.0),
            norm_eps=cfg.get("rms_norm_eps", 1e-5),
            max_seq_len=cfg.get("max_position_embeddings", 4096),
            tie_embeddings=cfg.get("tie_word_embeddings", False),
            n_experts=cfg.get("num_local_experts", 0),
            top_k_experts=cfg.get("num_experts_per_tok", 2),
            rope_scaling=(
                tuple(sorted(cfg["rope_scaling"].items()))
                if isinstance(cfg.get("rope_scaling"), dict)
                and cfg["rope_scaling"].get("rope_type", cfg["rope_scaling"].get("type")) == "llama3"
                else None
            ),
        )


# -- parameters -------------------------------------------------------------


def init_params(key: jax.Array, cfg: LlamaConfig) -> dict:
    """Random init; per-layer weights stacked on axis 0 for the scan."""
    dt = cfg.jnp_dtype
    D, H, KVH, hd, F, L = (
        cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.ffn_dim,
        cfg.n_layers,
    )
    keys = jax.random.split(key, 10)

    def dense(k, *shape):
        return layers.init_dense(k, shape, dtype=dt)

    if cfg.n_experts > 0:
        E = cfg.n_experts
        k9 = jax.random.split(keys[9])[0]
        mlp = {
            "router": dense(keys[5], L, D, E),
            "moe_gate": dense(keys[6], L, E, D, F),
            "moe_up": dense(keys[7], L, E, D, F),
            "moe_down": dense(k9, L, E, F, D),
        }
    else:
        mlp = {
            "gate": dense(keys[5], L, D, F),
            "up": dense(keys[6], L, D, F),
            "down": dense(keys[7], L, F, D),
        }
    params = {
        "embed": layers.init_dense(keys[0], (cfg.vocab_size, D), scale=0.02, dtype=dt),
        "layers": {
            "attn_norm": jnp.ones((L, D), dt),
            "wq": dense(keys[1], L, D, H * hd),
            "wk": dense(keys[2], L, D, KVH * hd),
            "wv": dense(keys[3], L, D, KVH * hd),
            "wo": dense(keys[4], L, H * hd, D),
            "mlp_norm": jnp.ones((L, D), dt),
            **mlp,
        },
        "final_norm": jnp.ones((D,), dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(keys[8], D, cfg.vocab_size)
    return params


def partition_specs(cfg: LlamaConfig) -> dict:
    """Tensor-parallel PartitionSpecs over the ``tensor`` mesh axis.

    Column-parallel in-projections, row-parallel out-projections — the
    Megatron layout expressed as sharding annotations; XLA inserts the
    all-reduce over ICI (replaces the reference's engine-internal NCCL TP,
    vllm_inference.py:179-180).
    """
    if cfg.n_experts > 0:
        # MoE: shard the ffn dim over tensor (expert-axis sharding goes
        # through moe.moe_mlp_ep / shard_map, not these specs)
        mlp_specs = {
            "router": P(None, None, None),
            "moe_gate": P(None, None, None, "tensor"),
            "moe_up": P(None, None, None, "tensor"),
            "moe_down": P(None, None, "tensor", None),
        }
    else:
        mlp_specs = {
            "gate": P(None, None, "tensor"),
            "up": P(None, None, "tensor"),
            "down": P(None, "tensor", None),
        }
    specs = {
        "embed": P("tensor", None),  # vocab-sharded
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "tensor"),
            "wk": P(None, None, "tensor"),
            "wv": P(None, None, "tensor"),
            "wo": P(None, "tensor", None),
            "mlp_norm": P(None, None),
            **mlp_specs,
        },
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tensor")
    return specs


def _layer_stack(params: dict):
    """[(leaf_name -> [L, ...])] -> per-layer pytrees for lax.scan."""
    return params["layers"]


def _mlp_block(
    layer: dict, h: jax.Array, cfg: LlamaConfig, *, lora=None, lora_scale=1.0,
    moe_impl: str = "nodrop",
) -> tuple[jax.Array, jax.Array]:
    """Post-norm MLP for one layer: dense SwiGLU, or — when cfg.n_experts > 0
    — top-k routed SwiGLU experts (the reference's served MoE lives inside
    vLLM/SGLang: vllm_inference.py:54-58). ``moe_impl="nodrop"`` (serving
    default) runs every expert so incremental decode reproduces the dense
    forward token-for-token; ``"capacity"`` is the GShard-dispatched
    formulation at ~top_k/E the FLOPs for compute-bound training forward.
    Returns (out, aux_load_balance_loss)."""
    if cfg.n_experts > 0:
        if lora is not None and any(
            f"{n}_a" in lora for n in ("gate", "up", "down")
        ):
            # silently skipping MLP adapters on the expert branch would make
            # "LoRA fine-tune a MoE model" train only the attention adapters
            # with no signal anything was dropped (ADVICE r2)
            raise ValueError(
                "LoRA MLP adapters (gate/up/down) are not supported for MoE "
                "expert MLPs; restrict LoRAConfig.targets to attention "
                "projections (wq/wk/wv/wo) for n_experts > 0"
            )
        from . import moe as _moe

        shape = h.shape
        if moe_impl == "capacity":
            flat, aux = _moe.moe_swiglu_capacity(
                layer["router"], layer["moe_gate"], layer["moe_up"],
                layer["moe_down"], h.reshape(-1, cfg.dim), cfg.top_k_experts,
                cfg.expert_capacity_factor,
            )
        else:
            flat, aux = _moe.moe_swiglu_nodrop(
                layer["router"], layer["moe_gate"], layer["moe_up"],
                layer["moe_down"], h.reshape(-1, cfg.dim), cfg.top_k_experts,
            )
        return flat.reshape(shape).astype(h.dtype), aux
    out = layers.swiglu_mlp(
        {k: layer[k] for k in ("gate", "up", "down")}, h,
        lora=lora, lora_scale=lora_scale,
    )
    return out, jnp.zeros((), jnp.float32)


# -- forward (training / prefill) ------------------------------------------


def forward(
    params: dict,
    tokens: jax.Array,  # [B, S] int32
    cfg: LlamaConfig,
    *,
    positions: jax.Array | None = None,  # [B, S] (defaults to arange)
    attn_impl: str = "flash",
    lora: dict | None = None,  # adapter pytree (models.lora), applied on the fly
    lora_scale: float = 1.0,
    return_aux: bool = False,  # MoE: also return the mean load-balance loss
    moe_impl: str = "nodrop",  # "capacity": GShard dispatch (training scale)
    input_embeds: jax.Array | None = None,  # [B, P, D]: multimodal prefix
):  # [B, S, vocab] (, aux)
    """Full-sequence forward with causal attention (flash or xla impl).

    ``input_embeds`` replaces the embedding lookup for the first P
    positions (same contract as ``prefill`` — the multimodal path)."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    x = params["embed"][tokens]  # [B, S, D]
    if input_embeds is not None:
        P = input_embeds.shape[1]
        x = jnp.concatenate([input_embeds.astype(x.dtype), x[:, P:]], axis=1)
    cos, sin = layers.rotary_embedding(
        positions, cfg.head_dim, cfg.rope_theta, dtype=jnp.float32,
        rope_scaling=dict(cfg.rope_scaling) if cfg.rope_scaling else None,
    )  # [B, S, hd/2]

    def layer_fn(x, scanned):
        layer = scanned[0] if lora is not None else scanned
        llayer = scanned[1] if lora is not None else None
        h = layers.rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        attn_params = {k: layer[k] for k in ("wq", "wk", "wv", "wo")}
        h = layers.causal_self_attention(
            attn_params, h,
            n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads,
            cos=cos, sin=sin, causal=True, attn_impl=attn_impl,
            lora=llayer, lora_scale=lora_scale,
        )
        x = x + h
        h = layers.rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        h, aux = _mlp_block(
            layer, h, cfg, lora=llayer, lora_scale=lora_scale, moe_impl=moe_impl
        )
        return x + h, aux

    xs = (
        (_layer_stack(params), lora["layers"]) if lora is not None
        else _layer_stack(params)
    )
    x, aux_per_layer = jax.lax.scan(layer_fn, x, xs)
    x = layers.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = layers.mm(x, head)
    if return_aux:
        return logits, jnp.mean(aux_per_layer)
    return logits


# -- serving: prefill + paged decode ----------------------------------------


def prefill(
    params: dict,
    tokens: jax.Array,  # [B, S] padded
    k_pages: jax.Array,  # [L, n_pages, page_size, Hkv, hd]
    v_pages: jax.Array,
    page_tables: jax.Array,  # [B, pages_per_seq]
    seq_lens: jax.Array,  # [B] true lengths
    cfg: LlamaConfig,
    attn_impl: str = "flash",  # "xla": the einsum reference path
    input_embeds: jax.Array | None = None,  # [B, P, D]: multimodal prefix
    mesh=None,  # jax Mesh with a "tensor" axis: flash runs per head shard
):
    """Process prompts, filling the paged KV cache; returns (logits_last,
    k_pages, v_pages). Padded positions write to reserved trash page 0.

    Under ``mesh=`` tensor parallelism the flash kernel runs inside
    ``shard_map`` over the kv-head axis (ops.sharded) — TP prefill keeps
    the Pallas fast path instead of downgrading to the XLA attention.

    ``input_embeds`` replaces the embedding lookup for the FIRST P
    positions — the multimodal path (models.vlm image tokens occupy
    positions 0..P-1; tokens[:, :P] are placeholders). Everything after the
    embedding — RoPE positions, causal attention, page scatter — already
    operates on the full sequence, so image tokens become ordinary KV cache
    entries and decode needs no changes at all (the LLaVA recipe, serving
    the reference's sglang_vlm.py workload)."""
    B, S = tokens.shape
    page_size = k_pages.shape[2]
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    valid = positions < seq_lens[:, None]
    cos, sin = layers.rotary_embedding(
        positions, cfg.head_dim, cfg.rope_theta, dtype=jnp.float32,
        rope_scaling=dict(cfg.rope_scaling) if cfg.rope_scaling else None,
    )
    x = params["embed"][tokens]
    if input_embeds is not None:
        P = input_embeds.shape[1]
        x = jnp.concatenate(
            [input_embeds.astype(x.dtype), x[:, P:]], axis=1
        )

    page_idx = jnp.take_along_axis(
        page_tables, positions // page_size, axis=1
    )  # [B, S]
    page_idx = jnp.where(valid, page_idx, 0)
    slot = jnp.where(valid, positions % page_size, 0)

    def layer_fn(carry, layer):
        x = carry
        h = layers.rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        D = cfg.head_dim
        q = layers.mm(h, layer["wq"]).astype(x.dtype)
        k = layers.mm(h, layer["wk"]).astype(x.dtype)
        v = layers.mm(h, layer["wv"]).astype(x.dtype)
        q = q.reshape(B, S, cfg.n_heads, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        q = layers.apply_rope(q, cos, sin)
        k = layers.apply_rope(k, cos, sin)
        if attn_impl == "flash":
            o = sharded_flash_attention(mesh, q, k, v, True)
        else:
            from ..ops import reference as _ref

            o = _ref.attention(q, k, v, causal=True)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, cfg.n_heads * D)
        x = x + layers.mm(o, layer["wo"]).astype(x.dtype)
        h = layers.rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        h, _ = _mlp_block(layer, h, cfg)
        x = x + h
        # stack KV for a single scatter outside the scan: [Hkv, B, S, D]
        return x, (k.transpose(1, 0, 2, 3), v.transpose(1, 0, 2, 3))

    x, (k_all, v_all) = jax.lax.scan(layer_fn, x, _layer_stack(params))
    # k_all: [L, Hkv, B, S, D] -> pages at (page_idx[b,s], slot[b,s])
    k_pages, v_pages = _scatter_pages(k_pages, v_pages, k_all, v_all, page_idx, slot)

    x = layers.rms_norm(x, params["final_norm"], cfg.norm_eps)
    last_idx = jnp.maximum(seq_lens - 1, 0)  # [B]
    x_last = jnp.take_along_axis(x, last_idx[:, None, None].repeat(x.shape[-1], -1), 1)[
        :, 0
    ]  # [B, D]
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = layers.mm(x_last, head)
    return logits, k_pages, v_pages


def _scatter_pages(k_pages, v_pages, k_all, v_all, page_idx, slot):
    """Write [L, Hkv, B, S, D] new KV into [L, P, page_size, Hkv, D] pages
    at (page_idx[b,s], slot[b,s]). int8 (QuantizedKV) caches quantize at
    this write — per token-head amax/127 over D, fused by XLA into the
    prefill program — and scatter the f32 scale rows alongside."""
    # adjacent advanced indices (page_idx, slot) at dims 1, 2 keep their
    # position: the target block is [L, B, S, Hkv, D]
    upd_k = k_all.transpose(0, 2, 3, 1, 4)
    upd_v = v_all.transpose(0, 2, 3, 1, 4)
    k_pages = kv_scatter(k_pages, upd_k, page_idx, slot)
    v_pages = kv_scatter(v_pages, upd_v, page_idx, slot)
    return k_pages, v_pages


def prefill_chunk(
    params: dict,
    tokens: jax.Array,  # [B, C] — one chunk of the prompt
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_tables: jax.Array,  # [B, pages_per_seq]
    chunk_lens: jax.Array,  # [B] valid tokens in THIS chunk
    cfg: LlamaConfig,
    *,
    q_offset: int,  # global position of the chunk's first token (static)
    attn_impl: str = "flash",  # "xla": the einsum reference path
    mesh=None,  # jax Mesh with a "tensor" axis: flash runs per head shard
):
    """One chunk of a long prompt: attends to the already-cached prefix (via
    page gather) + itself (rectangular flash kernel with q_offset), writes
    its K/V into the pages. Bounded VMEM for arbitrarily long prompts —
    the chunked-prefill half of the serving engine (vLLM chunked prefill
    analog). Under ``mesh=`` the chunked flash kernel runs per head shard
    (ops.sharded), so TP chunked prefill stays on the fast path. Returns
    (last_logits [B, vocab], k_pages, v_pages)."""
    B, C = tokens.shape
    page_size = k_pages.shape[2]
    positions = q_offset + jnp.broadcast_to(jnp.arange(C), (B, C))
    valid = jnp.arange(C)[None, :] < chunk_lens[:, None]
    cos, sin = layers.rotary_embedding(
        positions, cfg.head_dim, cfg.rope_theta, dtype=jnp.float32,
        rope_scaling=dict(cfg.rope_scaling) if cfg.rope_scaling else None,
    )
    x = params["embed"][tokens]

    page_idx = jnp.take_along_axis(page_tables, positions // page_size, axis=1)
    page_idx = jnp.where(valid, page_idx, 0)
    slot = jnp.where(valid, positions % page_size, 0)

    # dense gather of the cached prefix (page-aligned: q_offset % page_size
    # == 0 by construction — chunks are bucket-sized)
    n_prefix_pages = q_offset // page_size
    prefix_tables = page_tables[:, :n_prefix_pages] if n_prefix_pages else None

    def layer_fn(carry, layer_with_pages):
        x = carry
        layer, k_pg, v_pg = layer_with_pages  # [P, ps, Hkv, D]
        D = cfg.head_dim
        h = layers.rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = layers.mm(h, layer["wq"]).astype(x.dtype)
        k = layers.mm(h, layer["wk"]).astype(x.dtype)
        v = layers.mm(h, layer["wv"]).astype(x.dtype)
        q = q.reshape(B, C, cfg.n_heads, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, C, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, C, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        q = layers.apply_rope(q, cos, sin)
        k = layers.apply_rope(k, cos, sin)

        if n_prefix_pages:
            # [B, n_pp, ps, Hkv, D] -> [B, Hkv, prefix, D]; int8 caches
            # dequantize in the gather (one multiply at the chunk's dtype)
            pk = kv_gather(
                k_pg, prefix_tables, dtype=k.dtype
            ).transpose(0, 3, 1, 2, 4).reshape(
                B, cfg.n_kv_heads, n_prefix_pages * page_size, D
            )
            pv = kv_gather(
                v_pg, prefix_tables, dtype=v.dtype
            ).transpose(0, 3, 1, 2, 4).reshape(
                B, cfg.n_kv_heads, n_prefix_pages * page_size, D
            )
            k_full = jnp.concatenate([pk, k], axis=2)
            v_full = jnp.concatenate([pv, v], axis=2)
        else:
            k_full, v_full = k, v
        if attn_impl == "flash":
            o = sharded_flash_attention_chunked(
                mesh, q, k_full, v_full, q_offset=q_offset
            )
        else:
            from ..ops import reference as _ref

            o = _ref.attention_chunked(q, k_full, v_full, q_offset=q_offset)
        o = o.transpose(0, 2, 1, 3).reshape(B, C, cfg.n_heads * D)
        x = x + layers.mm(o, layer["wo"]).astype(x.dtype)
        h = layers.rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        h, _ = _mlp_block(layer, h, cfg)
        x = x + h
        return x, (k.transpose(1, 0, 2, 3), v.transpose(1, 0, 2, 3))

    x, (k_all, v_all) = jax.lax.scan(
        layer_fn, x, (_layer_stack(params), k_pages, v_pages)
    )
    k_pages, v_pages = _scatter_pages(k_pages, v_pages, k_all, v_all, page_idx, slot)

    x = layers.rms_norm(x, params["final_norm"], cfg.norm_eps)
    last_idx = jnp.maximum(chunk_lens - 1, 0)
    x_last = jnp.take_along_axis(
        x, last_idx[:, None, None].repeat(x.shape[-1], -1), 1
    )[:, 0]
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = layers.mm(x_last, head)
    return logits, k_pages, v_pages


_impl_downgrades_warned: set = set()


def tp_shard_ok(cfg: LlamaConfig, tp: int) -> bool:
    """Whether this model's heads divide the tensor-parallel degree — the
    ONE predicate behind every head-sharding legality decision
    (``paged_impl_plan`` and the writeback dispatch share it, so the plan
    and the runtime path cannot drift)."""
    return cfg.n_kv_heads % tp == 0 and cfg.n_heads % tp == 0


def paged_impl_plan(
    cfg: LlamaConfig,
    page_size: int,
    impl: str = "xla",
    scatter_impl: str = "xla",
    *,
    kv_dtype="bfloat16",
    mesh=None,
    warn: bool = True,
) -> dict:
    """Resolve the decode structure that will ACTUALLY run for these shapes
    on the current backend — the single source of truth shared by
    ``decode_step`` and the engine's stats/metrics, so a requested pallas
    impl that gets shape-downgraded (GQA Hkv<16, sub-128 head_dim) is
    visible instead of silently benchmarking the XLA path (ADVICE r4).

    ``kv_dtype`` ("int8" = the quantized QuantizedKV cache) affects the
    flat-variant Hkv legality (int8 page flattens need Hkv%32, not %16).

    ``mesh`` (a jax Mesh with a "tensor" axis) makes the plan PER-SHARD
    aware: under ``shard_map`` tensor parallelism the kernels see
    ``Hkv // tp`` / ``Hq // tp`` heads, so flat-variant legality and GQA
    grouping evaluate against the shard-local head counts — the plan
    reports the variant each device actually runs, with ``"tp"`` carrying
    the degree. Head counts not divisible by tp downgrade loudly to the
    auto-partitioned XLA paths (the only genuinely illegal sharding).

    Returns ``{"attention": "ragged"|"xla-gather"|"writeback",
    "ragged_variant": "flat"|"grouped"|None, "scatter": "pallas"|"xla",
    "kv_dtype": str, "tp": int, "downgraded": [...]}``.
    """
    from ..ops.kv_quant import resolve_kv_dtype

    kvd = resolve_kv_dtype(kv_dtype)
    kvd_name = "int8" if kvd == "int8" else str(kvd)
    on_tpu = jax.default_backend() == "tpu"
    tp = mesh_tp_degree(mesh)
    shard_ok = tp_shard_ok(cfg, tp)
    hkv_shard = cfg.n_kv_heads // tp if shard_ok else cfg.n_kv_heads
    downgraded = []
    ragged_variant = None
    if impl in ("xla-writeback", "pallas-writeback"):
        attention = "writeback"
        if impl == "pallas-writeback" and not shard_ok:
            downgraded.append(
                f"pallas-writeback -> xla-writeback (n_kv_heads="
                f"{cfg.n_kv_heads}/n_heads={cfg.n_heads} not divisible by "
                f"tp={tp})"
            )
    elif impl == "pallas":
        # legality predicates live with the kernels (ops.paged_attention)
        # so the plan and the wrappers cannot drift. Hkv no longer gates
        # the kernel (round 5): Hkv%16 shapes take the "flat" all-heads
        # formulation, others (GQA Hkv=8, the llama-3-era serving targets)
        # the "grouped" per-kv-head one. Under TP the SHARD-local Hkv
        # decides (round 7): the kernel inside shard_map sees Hkv // tp.
        from ..ops.paged_attention import ragged_shapes_ok, ragged_variant_for

        ok = (not on_tpu or ragged_shapes_ok(cfg.head_dim, page_size)) and (
            shard_ok
        )
        attention = "ragged" if ok else "xla-gather"
        if ok:
            ragged_variant = ragged_variant_for(hkv_shard, kvd_name)
        elif not shard_ok:
            downgraded.append(
                f"paged_impl=pallas -> xla-gather (n_kv_heads="
                f"{cfg.n_kv_heads}/n_heads={cfg.n_heads} not divisible by "
                f"tp={tp}: head-sharded kernels need whole heads per shard)"
            )
        else:
            downgraded.append(
                f"paged_impl=pallas -> xla-gather (head_dim={cfg.head_dim}, "
                f"page_size={page_size} fail D%128/ps%16 Mosaic tiling)"
            )
    else:
        attention = "xla-gather"
    scatter = "xla"
    if scatter_impl == "pallas":
        from ..ops.paged_attention import scatter_shapes_ok

        if (not on_tpu or scatter_shapes_ok(cfg.head_dim)) and shard_ok:
            scatter = "pallas"
        elif not shard_ok:
            downgraded.append(
                f"scatter_impl=pallas -> xla (n_kv_heads={cfg.n_kv_heads} "
                f"not divisible by tp={tp})"
            )
        else:
            downgraded.append(
                f"scatter_impl=pallas -> xla (head_dim={cfg.head_dim} "
                "fails D%128 tiling)"
            )
    if warn and downgraded:
        import warnings

        for msg in downgraded:
            if msg not in _impl_downgrades_warned:
                _impl_downgrades_warned.add(msg)
                warnings.warn(
                    "requested Pallas impl downgraded: " + msg, stacklevel=2
                )
    return {
        "attention": attention, "ragged_variant": ragged_variant,
        "scatter": scatter, "kv_dtype": kvd_name, "tp": tp,
        "downgraded": downgraded,
    }


def decode_step(
    params: dict,
    tokens: jax.Array,  # [B] int32 — current token per slot
    positions: jax.Array,  # [B] int32 — its position
    k_pages: jax.Array,  # [L, P, page_size, Hkv, hd]
    v_pages: jax.Array,
    page_tables: jax.Array,  # [B, pages_per_seq]
    active: jax.Array,  # [B] bool — live slots (dead slots write trash page 0)
    cfg: LlamaConfig,
    impl: str = "xla",
    scatter_impl: str = "xla",
    ragged_variant: str | None = None,  # None: auto (flat | grouped by Hkv)
    mesh=None,  # jax Mesh with a "tensor" axis: kernels run per head shard
):
    """One token of batched decode against the paged cache.

    Returns (logits [B, vocab], k_pages, v_pages). Pass donated pages for
    in-place updates under jit.

    ``impl`` selects the decode structure ("xla" default, "pallas",
    "xla-writeback"). There is deliberately NO env-var fallback here: this
    function is jitted by its callers, an env read would happen at trace
    time and not be part of any jit cache key, so toggling the env after a
    trace would silently keep the previously compiled implementation
    (ADVICE r3/r4). The engine resolves MTPU_PAGED_IMPL once in
    ``LLMEngine.__init__`` and passes it explicitly; use
    ``paged_impl_plan`` to see what will actually run for given shapes.

    Structure (round-3 rework): the page arrays are READ-ONLY inside the
    layer scan — attention sees the cached prefix via a fused gather plus
    the current token's K/V still in registers
    (ops.paged_decode_attention_inflight) — and every layer's new KV is
    scattered into the pages in ONE update after the scan (the same shape
    ``prefill`` uses). Round 2 threaded the full caches through the scan as
    stacked ys, which XLA materialized as cache-slice copies every layer of
    every step — the main gap between the measured 28 ms decode step and the
    16.5 ms weight-streaming floor (NOTES.md round 2).

    impl="pallas" (round 4) keeps this same read-only structure but swaps
    the attention for the v3 ragged kernel (ops.paged_decode_attention_ragged)
    — it reads exactly ceil(ctx/page_size) pages per sequence where the XLA
    gather reads and materializes ALL pages_per_seq pages (measured as the
    dominant, superlinear-in-slots step cost: benchmarks/decode_ablate.py).
    ``impl="xla-writeback"`` keeps the round-2 write-then-attend structure
    as the A/B lever for benchmarks/decode_micro.py.
    """
    if impl in ("xla-writeback", "pallas-writeback"):
        return _decode_step_writeback(
            params, tokens, positions, k_pages, v_pages, page_tables, active,
            cfg, impl=impl, mesh=mesh,
        )
    B = tokens.shape[0]
    page_size = k_pages.shape[2]
    # "pallas" = the v3 ragged kernel in the SAME read-only-pages structure
    # as the default path (in-flight token as an extra softmax column, one
    # scatter after the scan); shape legality + downgrade reporting live in
    # paged_impl_plan (single source of truth with the engine's stats).
    # mesh= makes both per-shard aware: the pallas paths go through the
    # ops.sharded shard_map dispatchers, so TP serving keeps the kernels.
    kv_dtype = "int8" if is_quantized(k_pages) else str(k_pages.dtype)
    plan = paged_impl_plan(
        cfg, page_size, impl, scatter_impl, kv_dtype=kv_dtype, mesh=mesh
    )
    use_ragged = plan["attention"] == "ragged"
    x = params["embed"][tokens]  # [B, D]
    cos, sin = layers.rotary_embedding(
        positions[:, None], cfg.head_dim, cfg.rope_theta, dtype=jnp.float32,
        rope_scaling=dict(cfg.rope_scaling) if cfg.rope_scaling else None,
    )  # [B, 1, hd/2]

    page_idx = jnp.take_along_axis(
        page_tables, (positions // page_size)[:, None], axis=1
    )[:, 0]
    page_idx = jnp.where(active, page_idx, 0)
    slot = jnp.where(active, positions % page_size, 0)
    prefix_lens = jnp.where(active, positions, 0).astype(jnp.int32)
    L = cfg.n_layers

    def layer_fn(carry, scanned):
        x = carry
        layer, li = scanned
        D = cfg.head_dim
        h = layers.rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = layers.mm(h, layer["wq"]).astype(x.dtype)
        k = layers.mm(h, layer["wk"]).astype(x.dtype)
        v = layers.mm(h, layer["wv"]).astype(x.dtype)
        q = q.reshape(B, 1, cfg.n_heads, D).transpose(0, 2, 1, 3)  # [B,H,1,D]
        k = k.reshape(B, 1, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, 1, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        q = layers.apply_rope(q, cos, sin)
        k = layers.apply_rope(k, cos, sin)
        k_tok, v_tok = k[:, :, 0], v[:, :, 0]  # [B, Hkv, D]
        if use_ragged:
            # kernel reads exactly ceil(prefix/ps) pages straight from the
            # full [L, P, ...] cache (layer via scalar prefetch — no slice
            # copy, no gather materialization). Under mesh= TP the dispatch
            # shard_maps over the kv-head axis: each device's kernel reads
            # only its local head shard of the cache (auto-variant inside
            # the shard resolves against the LOCAL Hkv — what plan reports)
            o = sharded_ragged_decode(
                mesh, q[:, :, 0], k_pages, v_pages, li, page_tables,
                prefix_lens, k_tok, v_tok, variant=ragged_variant,
            )  # [B, H, D]
        else:
            # one gather from the full [L, P, ...] arrays (layer scalar +
            # table array fuse into a single XLA gather — no per-layer slice
            # copy); int8 caches dequantize in the gather (one multiply at
            # the model dtype, fused into the same bandwidth-bound loop)
            ks = kv_gather(
                k_pages, page_tables, layer=li, dtype=x.dtype
            )  # [B, pp, ps, Hkv, D]
            vs = kv_gather(v_pages, page_tables, layer=li, dtype=x.dtype)
            o = paged_decode_attention_inflight(
                q[:, :, 0], ks, vs, prefix_lens, k_tok, v_tok
            )  # [B, H, D]
        o = o.reshape(B, cfg.n_heads * D)
        x = x + layers.mm(o, layer["wo"]).astype(x.dtype)
        h = layers.rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        h, _ = _mlp_block(layer, h, cfg)
        return x + h, (k_tok, v_tok)

    x, (k_all, v_all) = jax.lax.scan(
        layer_fn, x, (_layer_stack(params), jnp.arange(L))
    )
    # k_all: [L, B, Hkv, D] -> one scatter for every layer's token.
    # The pallas scatter (in-place strided DMAs; XLA's scatter for this
    # update measured 4.8 ms/step at 7B/32 slots, decode_ablate.py) is
    # opt-in (scatter_impl="pallas", resolved above — callers that jit must
    # pass it explicitly, same trap as impl=) until it is revalidated on a
    # healthy chip: its first on-chip run this round wedged the device
    # mid-compile, and a wedged chip poisons every later bench config.
    # Independent of the attention impl — both structures end in the same
    # post-scan scatter; only the (Hkv, D) minor-dim tile legality gates it.
    if plan["scatter"] == "pallas":
        k_pages, v_pages = sharded_scatter_kv_pages(
            mesh, k_pages, v_pages, k_all, v_all, page_idx, slot
        )
    else:
        # XLA scatter: adjacent advanced indices (dims 1, 2) keep their
        # position, so the [L, B, Hkv, D] scan ys line up directly.
        # Auto-partitionable (TP serving). int8 caches quantize at this
        # write (kv_scatter fuses the per token-head amax/127 into the
        # decode program and scatters data + scale rows).
        k_pages = kv_scatter(k_pages, k_all, page_idx, slot)
        v_pages = kv_scatter(v_pages, v_all, page_idx, slot)
    x = layers.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = layers.mm(x, head)
    return logits, k_pages, v_pages


def _decode_step_writeback(
    params, tokens, positions, k_pages, v_pages, page_tables, active, cfg,
    impl: str = "xla-writeback", mesh=None,
):
    """Write-then-attend decode (Pallas paged kernel path): each layer lands
    its KV in the pages before calling the kernel, which reads the current
    token back from the cache. See ``decode_step`` for why the default path
    avoids threading the caches through the scan."""
    B = tokens.shape[0]
    page_size = k_pages.shape[2]
    # the plan's downgrade contract via the SHARED predicate: heads not
    # divisible by tp fall back to the auto-partitioned xla-writeback
    # (exactly what paged_impl_plan reports), never a trace error
    pallas_wb = impl == "pallas-writeback" and tp_shard_ok(
        cfg, mesh_tp_degree(mesh)
    )
    x = params["embed"][tokens]  # [B, D]
    cos, sin = layers.rotary_embedding(
        positions[:, None], cfg.head_dim, cfg.rope_theta, dtype=jnp.float32,
        rope_scaling=dict(cfg.rope_scaling) if cfg.rope_scaling else None,
    )  # [B, 1, hd/2]

    page_idx = jnp.take_along_axis(
        page_tables, (positions // page_size)[:, None], axis=1
    )[:, 0]
    page_idx = jnp.where(active, page_idx, 0)
    slot = jnp.where(active, positions % page_size, 0)
    ctx_lens = jnp.where(active, positions + 1, 1).astype(jnp.int32)

    def layer_fn(carry, layer_with_pages):
        x = carry
        layer, k_pg, v_pg = layer_with_pages
        D = cfg.head_dim
        h = layers.rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = layers.mm(h, layer["wq"]).astype(x.dtype)
        k = layers.mm(h, layer["wk"]).astype(x.dtype)
        v = layers.mm(h, layer["wv"]).astype(x.dtype)
        q = q.reshape(B, 1, cfg.n_heads, D).transpose(0, 2, 1, 3)  # [B,H,1,D]
        k = k.reshape(B, 1, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, 1, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        q = layers.apply_rope(q, cos, sin)
        k = layers.apply_rope(k, cos, sin)
        # write this token's KV into the page cache ([P, ps, Hkv, D] layout:
        # adjacent advanced indices at dims 0, 1 land the [B, Hkv, D]
        # update); int8 caches quantize at the write
        k_pg = kv_scatter(k_pg, k[:, :, 0], page_idx, slot,
                          leading_layer=False)
        v_pg = kv_scatter(v_pg, v[:, :, 0], page_idx, slot,
                          leading_layer=False)
        # xla-writeback stays auto-partitioned (the gather needs no manual
        # sharding); pallas-writeback goes through the shard_map dispatch
        o = sharded_paged_decode_attention(
            mesh if pallas_wb else None,
            q[:, :, 0], k_pg, v_pg, page_tables, ctx_lens,
            impl="pallas" if pallas_wb else "xla",
        )  # [B, H, D]
        o = o.reshape(B, cfg.n_heads * D)
        x = x + layers.mm(o, layer["wo"]).astype(x.dtype)
        h = layers.rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        h, _ = _mlp_block(layer, h, cfg)
        return x + h, (k_pg, v_pg)

    x, (k_pages, v_pages) = jax.lax.scan(
        layer_fn, x, (_layer_stack(params), k_pages, v_pages)
    )
    x = layers.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = layers.mm(x, head)
    return logits, k_pages, v_pages


def verify_step(
    params: dict,
    tokens: jax.Array,  # [B, T] int32 — chain: committed token then proposals
    positions0: jax.Array,  # [B] int32 — global position of tokens[:, 0]
    k_pages: jax.Array,  # [L, P, page_size, Hkv, hd]
    v_pages: jax.Array,
    page_tables: jax.Array,  # [B, pages_per_seq]
    active: jax.Array,  # [B] bool
    cfg: LlamaConfig,
):
    """T tokens of teacher-forced decode against the paged cache — the
    target-model scoring half of speculative decoding (the reference enables
    this engine-side: vllm_inference.py:196-205, sglang_low_latency.py:194).

    Writes KV for ALL T chain tokens at positions0..positions0+T-1 (rejected
    tokens' entries are overwritten by later steps and never attended past
    the accept point), and returns logits for every chain position:
    ``logits[:, t]`` is the target's distribution for position
    positions0+t+1. Returns (logits [B, T, vocab], k_pages, v_pages).
    """
    from ..ops import reference as _ref

    B, T = tokens.shape
    page_size = k_pages.shape[2]
    cap = page_tables.shape[1] * page_size
    positions = positions0[:, None] + jnp.arange(T)[None, :]  # [B, T]
    # positions beyond the table capacity write to the trash page (a slot
    # near max length can overshoot by <= T-1 rejected tokens)
    valid = active[:, None] & (positions < cap)
    pos_c = jnp.minimum(positions, cap - 1)
    cos, sin = layers.rotary_embedding(
        pos_c, cfg.head_dim, cfg.rope_theta, dtype=jnp.float32,
        rope_scaling=dict(cfg.rope_scaling) if cfg.rope_scaling else None,
    )  # [B, T, hd/2]
    x = params["embed"][tokens]  # [B, T, D]

    page_idx = jnp.take_along_axis(page_tables, pos_c // page_size, axis=1)
    page_idx = jnp.where(valid, page_idx, 0)
    slot = jnp.where(valid, pos_c % page_size, 0)

    def layer_fn(carry, layer_with_pages):
        x = carry
        layer, k_pg, v_pg = layer_with_pages  # [P, ps, Hkv, D]
        D = cfg.head_dim
        h = layers.rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = layers.mm(h, layer["wq"]).astype(x.dtype)
        k = layers.mm(h, layer["wk"]).astype(x.dtype)
        v = layers.mm(h, layer["wv"]).astype(x.dtype)
        q = q.reshape(B, T, cfg.n_heads, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, cfg.n_kv_heads, D).transpose(0, 2, 1, 3)
        q = layers.apply_rope(q, cos, sin)
        k = layers.apply_rope(k, cos, sin)
        # write the whole chain's KV, then attend (the per-t causal mask in
        # the verify attention keeps token t from seeing tokens > t).
        # Adjacent advanced indices (dims 0, 1): result is [B, T, Hkv, D].
        # int8 caches quantize the chain writes so verification scores
        # proposals against exactly the (dequantized) KV decode will read.
        k_pg = kv_scatter(k_pg, k.transpose(0, 2, 1, 3), page_idx, slot,
                          leading_layer=False)
        v_pg = kv_scatter(v_pg, v.transpose(0, 2, 1, 3), page_idx, slot,
                          leading_layer=False)
        o = _ref.paged_verify_attention(
            q.transpose(0, 2, 1, 3), k_pg, v_pg, page_tables, positions
        )  # [B, T, Hq, D]
        o = o.reshape(B, T, cfg.n_heads * D)
        x = x + layers.mm(o, layer["wo"]).astype(x.dtype)
        h = layers.rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        h, _ = _mlp_block(layer, h, cfg)
        return x + h, (k_pg, v_pg)

    x, (k_pages, v_pages) = jax.lax.scan(
        layer_fn, x, (_layer_stack(params), k_pages, v_pages)
    )
    x = layers.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = layers.mm(x, head)  # [B, T, vocab]
    return logits, k_pages, v_pages


# -- HF safetensors interop -------------------------------------------------


def load_hf_weights(
    model_dir: str | Path, cfg: LlamaConfig, dtype=None,
    quantization: str | None = None,
) -> dict:
    """Stream HF llama safetensors into this tree (no 2x RAM: tensors are
    read file-by-file and stacked per layer).

    ``quantization="int8"`` / ``"int4"`` quantizes each matmul weight ON
    THE HOST before the device transfer (models.quantize.
    quantize_weight_host), so a 7B load costs ~7 GB (int8) / ~3.5 GB (int4)
    of HBM — the bf16 tensors never exist on device.
    """
    import numpy as np
    from safetensors import safe_open

    quant_targets = set()
    quant_bits = 8
    if quantization is not None:
        from .quantize import LLAMA_TARGETS, bits_of, quantize_weight_host

        quant_bits = bits_of(quantization)
        # the ONE shared target set (models.quantize.LLAMA_TARGETS) plus the
        # head; router/norms stay high precision (tiny, precision-critical)
        quant_targets = set(LLAMA_TARGETS) | {"lm_head"}

    model_dir = Path(model_dir)
    dt = dtype or cfg.jnp_dtype
    files = sorted(model_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no safetensors under {model_dir}")

    raw: dict[str, np.ndarray] = {}
    for f in files:
        with safe_open(str(f), framework="np") as sf:
            for name in sf.keys():
                raw[name] = sf.get_tensor(name)

    def dev(arr: np.ndarray, target: str):
        if target in quant_targets:
            return quantize_weight_host(arr, bits=quant_bits)
        return jnp.asarray(arr, dtype=dt)

    def t(name, target="_"):  # HF stores [out, in]; we use [in, out]
        return dev(raw.pop(name).T, target)

    def stack(fmt, transpose=True, target="_"):
        mats = []
        for li in range(cfg.n_layers):
            arr = raw.pop(fmt.format(li))
            mats.append(arr.T if transpose else arr)
        return dev(np.stack(mats), target)

    def stack_experts(fmt, target="_"):
        # [L, E, D, F] from per-(layer, expert) HF [F, D] matrices
        mats = [
            np.stack([raw.pop(fmt.format(li, e)).T for e in range(cfg.n_experts)])
            for li in range(cfg.n_layers)
        ]
        return dev(np.stack(mats), target)

    if cfg.n_experts > 0:
        # Mixtral layout: block_sparse_moe.gate (router) + experts.{e}.w1/w3/w2
        mlp = {
            "router": stack("model.layers.{}.block_sparse_moe.gate.weight"),
            "moe_gate": stack_experts(
                "model.layers.{}.block_sparse_moe.experts.{}.w1.weight",
                "moe_gate",
            ),
            "moe_up": stack_experts(
                "model.layers.{}.block_sparse_moe.experts.{}.w3.weight",
                "moe_up",
            ),
            "moe_down": stack_experts(
                "model.layers.{}.block_sparse_moe.experts.{}.w2.weight",
                "moe_down",
            ),
        }
    else:
        mlp = {
            "gate": stack("model.layers.{}.mlp.gate_proj.weight", target="gate"),
            "up": stack("model.layers.{}.mlp.up_proj.weight", target="up"),
            "down": stack("model.layers.{}.mlp.down_proj.weight", target="down"),
        }
    params = {
        "embed": jnp.asarray(raw.pop("model.embed_tokens.weight"), dtype=dt),
        "layers": {
            "attn_norm": stack("model.layers.{}.input_layernorm.weight", False),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight", target="wq"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight", target="wk"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight", target="wv"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight", target="wo"),
            "mlp_norm": stack("model.layers.{}.post_attention_layernorm.weight", False),
            **mlp,
        },
        "final_norm": jnp.asarray(raw.pop("model.norm.weight"), dtype=dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = t("lm_head.weight", "lm_head")
    return params
