"""nanoGPT-style small LM — the SLM pretraining workload.

Parity target: the reference's from-scratch GPT in
06_gpu_and_ml/hyperparameter-sweep/src/model.py (MultiHeadFast with SDPA
:14-30) trained by hp_sweep_gpt.py ("recognizable Shakespeare SLM in ~15
min", :65-67). Same shape of model — learned positional embeddings, pre-LN,
GELU MLP, tied output head — but JAX: scan over layers, flash-attention
kernel, hyperparameters as a frozen config swept via ``.starmap``
(hp_sweep_gpt.py:320).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import layers


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 96  # char-level
    block_size: int = 256
    n_layers: int = 6
    n_heads: int = 6
    dim: int = 384
    dropout: float = 0.0  # handled by caller via rng if nonzero
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def tiny() -> "GPTConfig":
        return GPTConfig(vocab_size=96, block_size=64, n_layers=2, n_heads=2, dim=64)


def init_params(key: jax.Array, cfg: GPTConfig) -> dict:
    dt = cfg.jnp_dtype
    D, F, L = cfg.dim, 4 * cfg.dim, cfg.n_layers
    ks = jax.random.split(key, 8)

    def dense(k, *shape, scale=0.02):
        return layers.init_dense(k, shape, scale=scale, dtype=dt)

    return {
        "tok_emb": dense(ks[0], cfg.vocab_size, D),
        "pos_emb": dense(ks[1], cfg.block_size, D),
        "layers": {
            "ln1_w": jnp.ones((L, D), dt),
            "ln1_b": jnp.zeros((L, D), dt),
            "wq": dense(ks[2], L, D, D),
            "wk": dense(ks[3], L, D, D),
            "wv": dense(ks[4], L, D, D),
            "wo": dense(ks[5], L, D, D, scale=0.02 / (2 * L) ** 0.5),
            "ln2_w": jnp.ones((L, D), dt),
            "ln2_b": jnp.zeros((L, D), dt),
            "fc_w": dense(ks[6], L, D, F),
            "fc_b": jnp.zeros((L, F), dt),
            "proj_w": dense(ks[7], L, F, D, scale=0.02 / (2 * L) ** 0.5),
            "proj_b": jnp.zeros((L, D), dt),
        },
        "final_ln_w": jnp.ones((D,), dt),
        "final_ln_b": jnp.zeros((D,), dt),
    }


def forward(
    params: dict, tokens: jax.Array, cfg: GPTConfig, *, attn_impl: str = "flash"
) -> jax.Array:  # [B, S, vocab]
    B, S = tokens.shape
    x = params["tok_emb"][tokens] + params["pos_emb"][jnp.arange(S)][None]

    def layer_fn(x, layer):
        h = layers.layer_norm(x, layer["ln1_w"], layer["ln1_b"])
        h = layers.causal_self_attention(
            {k: layer[k] for k in ("wq", "wk", "wv", "wo")},
            h,
            n_heads=cfg.n_heads,
            n_kv_heads=cfg.n_heads,
            causal=True,
            attn_impl=attn_impl,
        )
        x = x + h
        h = layers.layer_norm(x, layer["ln2_w"], layer["ln2_b"])
        h = layers.gelu_mlp(
            {k: layer[k] for k in ("fc_w", "fc_b", "proj_w", "proj_b")}, h
        )
        return x + h, None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    x = layers.layer_norm(x, params["final_ln_w"], params["final_ln_b"])
    return jnp.dot(x, params["tok_emb"].T, preferred_element_type=jnp.float32)


def generate(
    params: dict,
    cfg: GPTConfig,
    prompt: jax.Array,  # [S0] int32
    n_tokens: int,
    key: jax.Array,
    temperature: float = 1.0,
) -> jax.Array:
    """Autoregressive sampling via a fixed-window scan (kv-cache-free — at
    SLM scale recompute is cheaper than cache bookkeeping)."""
    S = cfg.block_size
    buf = jnp.zeros((S,), jnp.int32).at[: prompt.shape[0]].set(prompt)

    def step(carry, k):
        buf, pos = carry
        logits = forward(params, buf[None], cfg, attn_impl="xla")[0]
        logits_last = logits[jnp.clip(pos - 1, 0, S - 1)]
        nxt = jax.random.categorical(k, logits_last / max(temperature, 1e-6))
        buf = buf.at[jnp.clip(pos, 0, S - 1)].set(nxt.astype(jnp.int32))
        return (buf, jnp.minimum(pos + 1, S)), nxt

    (buf, _), toks = jax.lax.scan(
        step, (buf, prompt.shape[0]), jax.random.split(key, n_tokens)
    )
    return toks


class CharTokenizer:
    """Char-level tokenizer for the Shakespeare-style corpus (hp_sweep's
    src/tokenizer.py analog)."""

    def __init__(self, text: str):
        chars = sorted(set(text))
        self.stoi = {c: i for i, c in enumerate(chars)}
        self.itos = {i: c for i, c in enumerate(chars)}
        self.vocab_size = len(chars)

    def encode(self, s: str) -> list[int]:
        return [self.stoi[c] for c in s if c in self.stoi]

    def decode(self, ids) -> str:
        return "".join(self.itos.get(int(i), "") for i in ids)
