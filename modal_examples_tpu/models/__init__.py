"""Model zoo (pure-functional JAX, pytree params, Pallas hot ops).

JAX-native replacements for the model families the reference serves through
CUDA engines (SURVEY.md §2.2): llama (LLM serving + fine-tuning), gpt
(nanoGPT-style SLM pretraining, hp_sweep parity), bert (BGE embeddings),
whisper (ASR).
"""

from . import (
    bert,
    diffusion,
    gpt,
    layers,
    llama,
    lora,
    moe,
    ocr,
    segmentation,
    video,
    vlm,
    whisper,
)

__all__ = [
    "bert", "diffusion", "gpt", "layers", "llama", "lora", "moe",
    "ocr", "segmentation", "video", "vlm", "whisper",
]
