"""Vision: single-stage anchor-free object detector (YOLO/FCOS family).

The reference's vision workloads delegate to torch CUDA models
(/root/reference/06_gpu_and_ml/yolo/finetune_yolo.py — ultralytics YOLO
fine-tune; sam/segment_anything.py — SAM inference). This module is the
TPU-native counterpart: a from-scratch JAX detector whose convolutions XLA
maps onto the MXU, trained/fine-tuned with the same Trainer the LLM
workloads use.

Architecture (anchor-free, FCOS-style single level):
- conv backbone: stride-2 conv stem + N conv blocks with group norm + silu
  (NHWC layout — the TPU-friendly convention; channels-last keeps the MXU
  contraction on the last dim);
- detection head per grid cell: objectness logit, class logits, and an
  ltrb box regressed via softplus (distances from the cell center, in
  cell units — always positive, no anchors to tune);
- loss: BCE on objectness (all cells), CE on class + IoU-loss on boxes
  (positive cells only) — the standard one-positive-per-target assignment
  (the cell containing the box center).

Everything is jit-compatible with static shapes: images are [B, H, W, 3],
targets are padded to ``max_boxes`` with a validity mask.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DetectorConfig:
    image_size: int = 64  # square inputs
    n_classes: int = 3
    width: int = 32  # stem channels
    depth: int = 2  # conv blocks after the stem
    stride: int = 8  # total downsample: grid = image_size // stride
    max_boxes: int = 8  # padded targets per image
    dtype: str = "float32"

    @property
    def grid(self) -> int:
        return self.image_size // self.stride

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)


def _conv(key, k, cin, cout, dtype):
    scale = (k * k * cin) ** -0.5
    return jax.random.normal(key, (k, k, cin, cout), dtype) * scale


def init_params(key: jax.Array, cfg: DetectorConfig) -> dict:
    dt = cfg.jnp_dtype
    w = cfg.width
    keys = jax.random.split(key, cfg.depth + 4)
    # stem: two stride-2 convs (x4 down), then blocks; remaining stride via
    # a final stride-2 conv when cfg.stride == 8
    params = {
        "stem1": _conv(keys[0], 3, 3, w, dt),
        "stem2": _conv(keys[1], 3, w, 2 * w, dt),
        "down": _conv(keys[2], 3, 2 * w, 2 * w, dt),
        "blocks": [
            {"conv": _conv(keys[3 + i], 3, 2 * w, 2 * w, dt),
             "gn_scale": jnp.ones((2 * w,), dt),
             "gn_bias": jnp.zeros((2 * w,), dt)}
            for i in range(cfg.depth)
        ],
        # head: 1x1 conv -> [obj(1), classes, ltrb(4)]
        "head": _conv(keys[-1], 1, 2 * w, 1 + cfg.n_classes + 4, dt),
        "head_bias": jnp.zeros((1 + cfg.n_classes + 4,), dt),
    }
    return params


def _conv2d(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _group_norm(x, scale, bias, groups=8, eps=1e-5):
    B, H, W, C = x.shape
    xg = x.reshape(B, H, W, groups, C // groups)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    return xg.reshape(B, H, W, C) * scale + bias


def forward(params: dict, images: jax.Array, cfg: DetectorConfig) -> dict:
    """images [B, S, S, 3] in [0, 1] -> per-cell predictions.

    Returns dict with obj [B, G, G], cls [B, G, G, n_classes],
    ltrb [B, G, G, 4] (positive distances in cell units).
    """
    x = images.astype(cfg.jnp_dtype)
    x = jax.nn.silu(_conv2d(x, params["stem1"], stride=2))
    x = jax.nn.silu(_conv2d(x, params["stem2"], stride=2))
    if cfg.stride == 8:
        x = jax.nn.silu(_conv2d(x, params["down"], stride=2))
    for blk in params["blocks"]:
        h = _group_norm(x, blk["gn_scale"], blk["gn_bias"])
        x = x + jax.nn.silu(_conv2d(h, blk["conv"]))
    out = _conv2d(x, params["head"]) + params["head_bias"]
    n_cls = cfg.n_classes
    return {
        "obj": out[..., 0],
        "cls": out[..., 1 : 1 + n_cls],
        "ltrb": jax.nn.softplus(out[..., 1 + n_cls :]),
    }


# -- target assignment + loss ------------------------------------------------


def _cell_targets(boxes, labels, mask, cfg: DetectorConfig):
    """Rasterize padded targets onto the grid (one positive cell per box:
    the cell containing the box center). boxes are [max_boxes, 4] xyxy in
    image pixels; returns (obj_t [G,G], cls_t [G,G], ltrb_t [G,G,4],
    pos [G,G])."""
    G, s = cfg.grid, cfg.stride
    obj_t = jnp.zeros((G, G))
    cls_t = jnp.zeros((G, G), jnp.int32)
    ltrb_t = jnp.zeros((G, G, 4))

    def add_box(carry, i):
        obj_t, cls_t, ltrb_t = carry
        x1, y1, x2, y2 = boxes[i]
        cx, cy = (x1 + x2) / 2, (y1 + y2) / 2
        gx = jnp.clip((cx / s).astype(jnp.int32), 0, G - 1)
        gy = jnp.clip((cy / s).astype(jnp.int32), 0, G - 1)
        # distances from the positive cell's center, in cell units
        ccx, ccy = (gx + 0.5) * s, (gy + 0.5) * s
        tgt = jnp.stack([ccx - x1, ccy - y1, x2 - ccx, y2 - ccy]) / s
        valid = mask[i]
        obj_t = obj_t.at[gy, gx].set(jnp.where(valid, 1.0, obj_t[gy, gx]))
        cls_t = cls_t.at[gy, gx].set(jnp.where(valid, labels[i], cls_t[gy, gx]))
        ltrb_t = ltrb_t.at[gy, gx].set(
            jnp.where(valid, tgt, ltrb_t[gy, gx])
        )
        return (obj_t, cls_t, ltrb_t), None

    (obj_t, cls_t, ltrb_t), _ = jax.lax.scan(
        add_box, (obj_t, cls_t, ltrb_t), jnp.arange(cfg.max_boxes)
    )
    return obj_t, cls_t, ltrb_t, obj_t > 0.5


def _iou_ltrb(a, b, eps=1e-6):
    """IoU of two ltrb distance-boxes around a shared center point."""
    inter_w = jnp.minimum(a[..., 0], b[..., 0]) + jnp.minimum(a[..., 2], b[..., 2])
    inter_h = jnp.minimum(a[..., 1], b[..., 1]) + jnp.minimum(a[..., 3], b[..., 3])
    inter = jnp.clip(inter_w, 0) * jnp.clip(inter_h, 0)
    area_a = (a[..., 0] + a[..., 2]) * (a[..., 1] + a[..., 3])
    area_b = (b[..., 0] + b[..., 2]) * (b[..., 1] + b[..., 3])
    return inter / (area_a + area_b - inter + eps)


def detection_loss(params, batch, cfg: DetectorConfig):
    """batch: images [B,S,S,3], boxes [B,max_boxes,4] xyxy px,
    labels [B,max_boxes] int32, box_mask [B,max_boxes] bool."""
    preds = forward(params, batch["images"], cfg)
    obj_t, cls_t, ltrb_t, pos = jax.vmap(
        lambda b, l, m: _cell_targets(b, l, m, cfg)
    )(batch["boxes"], batch["labels"], batch["box_mask"])

    obj = preds["obj"].astype(jnp.float32)
    obj_loss = jnp.mean(
        jnp.maximum(obj, 0) - obj * obj_t + jnp.log1p(jnp.exp(-jnp.abs(obj)))
    )
    n_pos = jnp.maximum(pos.sum(), 1.0)

    logp = jax.nn.log_softmax(preds["cls"].astype(jnp.float32), axis=-1)
    cls_nll = -jnp.take_along_axis(logp, cls_t[..., None], axis=-1)[..., 0]
    cls_loss = jnp.sum(cls_nll * pos) / n_pos

    iou = _iou_ltrb(preds["ltrb"].astype(jnp.float32), ltrb_t)
    box_loss = jnp.sum((1.0 - iou) * pos) / n_pos
    return obj_loss + cls_loss + 2.0 * box_loss


# -- inference ---------------------------------------------------------------


def decode_boxes(preds: dict, cfg: DetectorConfig):
    """Per-cell predictions -> (boxes [B,G*G,4] xyxy px, scores [B,G*G],
    classes [B,G*G]). Static shapes: all cells are returned — callers filter
    by score via nms_host (cheap on the host at G*G<=256 candidates,
    matching how the reference's exported models postprocess
    off-accelerator)."""
    G, s = cfg.grid, cfg.stride
    cy, cx = jnp.mgrid[0:G, 0:G]
    ccx = (cx + 0.5) * s
    ccy = (cy + 0.5) * s
    ltrb = preds["ltrb"].astype(jnp.float32) * s
    boxes = jnp.stack(
        [ccx - ltrb[..., 0], ccy - ltrb[..., 1],
         ccx + ltrb[..., 2], ccy + ltrb[..., 3]],
        axis=-1,
    )  # [B, G, G, 4]
    scores = jax.nn.sigmoid(preds["obj"].astype(jnp.float32))
    classes = jnp.argmax(preds["cls"], axis=-1)
    B = boxes.shape[0]
    return (
        boxes.reshape(B, G * G, 4),
        scores.reshape(B, G * G),
        classes.reshape(B, G * G),
    )


def nms_host(boxes, scores, classes, *, score_thresh=0.5, iou_thresh=0.5):
    """Greedy per-class NMS on the host (numpy); boxes [N,4] xyxy."""
    import numpy as np

    boxes, scores, classes = map(np.asarray, (boxes, scores, classes))
    keep = []
    order = np.argsort(-scores)
    order = [i for i in order if scores[i] >= score_thresh]
    while order:
        i = order.pop(0)
        keep.append(i)
        rest = []
        for j in order:
            if classes[j] != classes[i]:
                rest.append(j)
                continue
            xx1 = max(boxes[i, 0], boxes[j, 0])
            yy1 = max(boxes[i, 1], boxes[j, 1])
            xx2 = min(boxes[i, 2], boxes[j, 2])
            yy2 = min(boxes[i, 3], boxes[j, 3])
            inter = max(0.0, xx2 - xx1) * max(0.0, yy2 - yy1)
            a = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            b = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            if inter / (a + b - inter + 1e-6) < iou_thresh:
                rest.append(j)
        order = rest
    return keep


# -- synthetic shapes dataset (cheap-mode fine-tune data) --------------------


def synthetic_batch(key: jax.Array, batch: int, cfg: DetectorConfig) -> dict:
    """Geometric-shapes detection data, generated on device: each image has
    1..max shapes (filled rectangle=0 / cross=1 / stripe=2) on a noisy
    background — the cheap-mode stand-in for a real labeled dataset, playing
    the role of the reference's tiny-split fine-tune switches (SURVEY.md §4:
    max_train_samples=5, down_scale=0.001)."""
    S = cfg.image_size
    kb, kn, kc = jax.random.split(key, 3)
    n_boxes = min(2, cfg.max_boxes)
    keys = jax.random.split(kb, batch * n_boxes * 2).reshape(batch, n_boxes, 2, 2)

    def one_box(k):
        kxy, kwh = k
        wh = jax.random.uniform(kwh, (2,), minval=12.0, maxval=24.0)
        xy = jax.random.uniform(kxy, (2,), minval=2.0, maxval=S - 26.0)
        return jnp.concatenate([xy, xy + wh])  # xyxy

    boxes = jax.vmap(jax.vmap(one_box))(keys)  # [B, n, 4]
    labels = jax.random.randint(kc, (batch, n_boxes), 0, cfg.n_classes)

    yy, xx = jnp.mgrid[0:S, 0:S]

    def paint(boxes_i, labels_i):
        img = jnp.zeros((S, S))

        def add(img, bl):
            box, lab = bl
            x1, y1, x2, y2 = box
            inside = (xx >= x1) & (xx < x2) & (yy >= y1) & (yy < y2)
            cx, cy = (x1 + x2) / 2, (y1 + y2) / 2
            cross = inside & (
                (jnp.abs(xx - cx) < 2) | (jnp.abs(yy - cy) < 2)
            )
            stripe = inside & (((xx + yy) % 8) < 4)
            shape = jnp.where(
                lab == 0, inside, jnp.where(lab == 1, cross, stripe)
            )
            return jnp.maximum(img, shape.astype(jnp.float32)), None

        img, _ = jax.lax.scan(add, img, (boxes_i, labels_i))
        return img

    imgs = jax.vmap(paint)(boxes, labels)  # [B, S, S]
    noise = 0.1 * jax.random.uniform(kn, (batch, S, S))
    imgs = jnp.clip(imgs * 0.9 + noise, 0, 1)
    images = jnp.repeat(imgs[..., None], 3, axis=-1)

    pad = cfg.max_boxes - n_boxes
    return {
        "images": images,
        "boxes": jnp.pad(boxes, ((0, 0), (0, pad), (0, 0))),
        "labels": jnp.pad(labels, ((0, 0), (0, pad))),
        "box_mask": jnp.pad(
            jnp.ones((batch, n_boxes), bool), ((0, 0), (0, pad))
        ),
    }
