"""Latent video generation: factorized space-time DiT + first-frame
conditioning — the TPU-native counterpart of the reference's video/world
generation tier, which delegates to torch/diffusers CUDA pipelines
(/root/reference/06_gpu_and_ml/world-models/text_to_world.py — a two-stage
spawn-chained pipeline; text-to-video/ltx.py, mochi.py,
ltx2_two_stage.py; image-to-video/image_to_video.py).

TPU-first design:
- video lives as latents [B, T, S, S, C] (per-frame VAE latents — the same
  ``models.vae`` the image pipelines use, vmapped over time);
- the denoiser is a DiT with FACTORIZED space-time attention: each block
  runs spatial attention (tokens within a frame, batched over frames) then
  temporal attention (same patch position across frames, batched over
  positions) — both are dense, mask-free MXU matmuls with static shapes,
  which is exactly what XLA tiles best; full 3D attention costs
  (T*N)^2 while factorized costs T*N^2 + N*T^2;
- first-frame conditioning (the image-to-video / two-stage recipe): frame 0
  is pinned to a clean keyframe latent during training AND sampling, with a
  per-frame conditioning indicator folded into the adaLN signal, so one
  model serves text-to-video (frame 0 from the image DiT) and
  image-to-video (frame 0 from a user image);
- rectified-flow training + few-step Euler sampling with classifier-free
  guidance, matching ``models.diffusion``'s conventions.

Demo-scale like the rest of the diffusion tier: the architecture is the
real one (the same structure scales by config), proven on synthetic data in
tests; no published video checkpoint is loadable here (zero egress).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import layers
from .diffusion import timestep_embedding


@dataclasses.dataclass(frozen=True)
class VideoDiTConfig:
    frames: int = 8  # T
    img_size: int = 16  # latent spatial side
    channels: int = 4  # latent channels (VAE z)
    patch: int = 2
    dim: int = 256
    n_layers: int = 6
    n_heads: int = 8
    text_dim: int = 64
    text_len: int = 16
    norm_eps: float = 1e-6
    dtype: str = "float32"

    @property
    def n_patches(self) -> int:  # spatial tokens per frame
        return (self.img_size // self.patch) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch * self.patch * self.channels

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def tiny() -> "VideoDiTConfig":
        return VideoDiTConfig(
            frames=4, img_size=8, channels=4, patch=2, dim=96, n_layers=3,
            n_heads=4, text_dim=32, text_len=8,
        )


def init_params(key: jax.Array, cfg: VideoDiTConfig) -> dict:
    dt = cfg.jnp_dtype
    D, L = cfg.dim, cfg.n_layers
    ks = iter(jax.random.split(key, 24))

    def dense(*shape, scale=None):
        return layers.init_dense(next(ks), shape, scale=scale, dtype=dt)

    return {
        "patch_proj": dense(cfg.patch_dim, D, scale=0.02),
        "pos_emb": dense(cfg.n_patches, D, scale=0.02),  # spatial
        "frame_emb": dense(cfg.frames, D, scale=0.02),  # temporal
        "t_mlp1": dense(D, D),
        "t_mlp2": dense(D, D),
        # conditioning indicator (is this frame pinned?) joins adaLN
        "cond_emb": dense(2, D, scale=0.02),
        "text_proj": dense(cfg.text_dim, D, scale=0.02),
        "null_text": dense(cfg.text_len, cfg.text_dim, scale=0.02),
        "layers": {
            # adaLN-zero: 9 modulation vectors per block (3 per branch:
            # spatial attn, temporal attn, MLP), zero-init gates
            "mod_w": jnp.zeros((L, D, 9 * D), dt),
            "mod_b": jnp.zeros((L, 9 * D), dt),
            "s_wq": dense(L, D, D), "s_wk": dense(L, D, D),
            "s_wv": dense(L, D, D), "s_wo": dense(L, D, D),
            "t_wq": dense(L, D, D), "t_wk": dense(L, D, D),
            "t_wv": dense(L, D, D), "t_wo": dense(L, D, D),
            "xwq": dense(L, D, D), "xwk": dense(L, D, D),
            "xwv": dense(L, D, D),
            "xwo": jnp.zeros((L, D, D), dt),  # zero-init cross-attn out
            "fc_w": dense(L, D, 4 * D),
            "fc_b": jnp.zeros((L, 4 * D), dt),
            "proj_w": dense(L, 4 * D, D),
            "proj_b": jnp.zeros((L, D), dt),
        },
        "final_mod_w": jnp.zeros((D, 2 * D), dt),
        "final_mod_b": jnp.zeros((2 * D,), dt),
        "final_proj": jnp.zeros((D, cfg.patch_dim), dt),
    }


def patchify(x: jax.Array, cfg: VideoDiTConfig) -> jax.Array:
    """[B, T, H, W, C] -> [B, T, n_patches, patch_dim]."""
    B, T, H, W, C = x.shape
    p = cfg.patch
    x = x.reshape(B, T, H // p, p, W // p, p, C)
    x = x.transpose(0, 1, 2, 4, 3, 5, 6)
    return x.reshape(B, T, (H // p) * (W // p), p * p * C)


def unpatchify(x: jax.Array, cfg: VideoDiTConfig) -> jax.Array:
    B, T = x.shape[:2]
    p, C = cfg.patch, cfg.channels
    hw = cfg.img_size // p
    x = x.reshape(B, T, hw, hw, p, p, C)
    x = x.transpose(0, 1, 2, 4, 3, 5, 6)
    return x.reshape(B, T, cfg.img_size, cfg.img_size, C)


def _mha(q, k, v, n_heads):
    B, Sq, D = q.shape
    Sk = k.shape[1]
    hd = D // n_heads
    q = q.reshape(B, Sq, n_heads, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, Sk, n_heads, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, Sk, n_heads, hd).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    p = jax.nn.softmax(s * hd**-0.5, axis=-1).astype(v.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o.transpose(0, 2, 1, 3).reshape(B, Sq, D)


def forward(
    params: dict,
    x_t: jax.Array,  # [B, T, S, S, C] noised latents (frame 0 may be clean)
    t: jax.Array,  # [B] flow time in [0, 1]
    cond_mask: jax.Array,  # [B, T] 1.0 where the frame is PINNED (clean)
    text_states: jax.Array,  # [B, S_text, text_dim]
    cfg: VideoDiTConfig,
) -> jax.Array:  # predicted velocity [B, T, S, S, C]
    B, T = x_t.shape[:2]
    N, D = cfg.n_patches, cfg.dim
    h = patchify(x_t, cfg) @ params["patch_proj"]  # [B, T, N, D]
    h = h + params["pos_emb"][None, None] + params["frame_emb"][None, :, None]
    temb = timestep_embedding(t, D)
    temb = jnp.dot(jax.nn.silu(temb @ params["t_mlp1"]), params["t_mlp2"])
    text = text_states @ params["text_proj"]  # [B, S_text, D]
    # conditioning signal: per-FRAME (pinned frames get the "clean" row)
    cemb = params["cond_emb"][cond_mask.astype(jnp.int32)]  # [B, T, D]
    cond = temb[:, None] + text.mean(axis=1)[:, None] + cemb  # [B, T, D]

    def norm(v):
        mu = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        return (v - mu) * jax.lax.rsqrt(var + cfg.norm_eps)

    def layer_fn(h, l):
        # h: [B, T, N, D]; per-frame modulation [B, T, 9D]
        mod = jax.nn.silu(cond) @ l["mod_w"] + l["mod_b"]
        (s1, sc1, g1, s2, sc2, g2, s3, sc3, g3) = jnp.split(mod, 9, axis=-1)

        def modulate(v, shift, scale):
            return v * (1 + scale[:, :, None]) + shift[:, :, None]

        # spatial attention: tokens within a frame, frames batched
        a = modulate(norm(h), s1, sc1).reshape(B * T, N, D)
        a = _mha(a @ l["s_wq"], a @ l["s_wk"], a @ l["s_wv"], cfg.n_heads)
        a = a.reshape(B, T, N, D) @ l["s_wo"]
        h = h + g1[:, :, None] * a

        # temporal attention: same patch position across frames, positions
        # batched — [B, T, N, D] -> [B*N, T, D]
        a = modulate(norm(h), s2, sc2).transpose(0, 2, 1, 3).reshape(
            B * N, T, D
        )
        a = _mha(a @ l["t_wq"], a @ l["t_wk"], a @ l["t_wv"], cfg.n_heads)
        a = a.reshape(B, N, T, D).transpose(0, 2, 1, 3) @ l["t_wo"]
        h = h + g2[:, :, None] * a

        # cross-attention to text over the flattened space-time tokens
        xq = norm(h).reshape(B, T * N, D) @ l["xwq"]
        xk, xv = text @ l["xwk"], text @ l["xwv"]
        x = _mha(xq, xk, xv, cfg.n_heads).reshape(B, T, N, D) @ l["xwo"]
        h = h + x

        # MLP
        m = modulate(norm(h), s3, sc3)
        m = jax.nn.gelu(m @ l["fc_w"] + l["fc_b"]) @ l["proj_w"] + l["proj_b"]
        return h + g3[:, :, None] * m, None

    h, _ = jax.lax.scan(layer_fn, h, params["layers"])
    fmod = jax.nn.silu(cond) @ params["final_mod_w"] + params["final_mod_b"]
    shift, scale = jnp.split(fmod, 2, axis=-1)
    h = (norm(h) * (1 + scale[:, :, None]) + shift[:, :, None]) @ params[
        "final_proj"
    ]
    return unpatchify(h, cfg)


def _null_text(params: dict, shape: tuple) -> jax.Array:
    B, S, Dt = shape
    stored = params["null_text"]
    n = min(S, stored.shape[0])
    base = jnp.zeros((S, Dt), stored.dtype).at[:n].set(stored[:n])
    return jnp.broadcast_to(base[None], (B, S, Dt))


def flow_loss(
    params: dict,
    key: jax.Array,
    video: jax.Array,  # [B, T, S, S, C] clean latents
    text_states: jax.Array,
    cfg: VideoDiTConfig,
    *,
    null_prob: float = 0.1,
    first_frame_prob: float = 0.7,
) -> jax.Array:
    """Rectified-flow loss with first-frame conditioning: with probability
    ``first_frame_prob`` frame 0 stays clean (cond_mask=1) and is excluded
    from the loss — teaching the model to propagate a pinned keyframe, the
    image-to-video / two-stage training recipe."""
    B, T = video.shape[:2]
    k1, k2, k3, k4 = jax.random.split(key, 4)
    t = jax.random.uniform(k1, (B,))
    eps = jax.random.normal(k2, video.shape)
    tb = t[:, None, None, None, None]
    x_t = (1 - tb) * video + tb * eps
    target_v = eps - video

    pin = jax.random.bernoulli(k3, first_frame_prob, (B,))
    cond_mask = jnp.zeros((B, T)).at[:, 0].set(pin.astype(jnp.float32))
    # pinned frame 0 is presented clean
    x_t = x_t.at[:, 0].set(
        jnp.where(pin[:, None, None, None], video[:, 0], x_t[:, 0])
    )

    drop = jax.random.bernoulli(k4, null_prob, (B,))
    null = _null_text(params, text_states.shape)
    text_in = jnp.where(drop[:, None, None], null, text_states)

    pred = forward(params, x_t, t, cond_mask, text_in, cfg)
    # pinned frames don't contribute loss (their input was clean)
    w = 1.0 - cond_mask[:, :, None, None, None]
    return jnp.sum(w * (pred - target_v) ** 2) / jnp.maximum(
        jnp.sum(w) * video[0, 0].size, 1.0
    )


def sample(
    params: dict,
    key: jax.Array,
    text_states: jax.Array,  # [B, S_text, text_dim]
    cfg: VideoDiTConfig,
    *,
    first_frame: jax.Array | None = None,  # [B, S, S, C] keyframe latent
    steps: int = 8,
    guidance: float = 3.0,
) -> jax.Array:  # [B, T, S, S, C]
    """Euler flow sampling; when ``first_frame`` is given, frame 0 is held
    fixed at every step (the two-stage text->image->video chain,
    text_to_world.py's stage-2 shape)."""
    B = text_states.shape[0]
    shape = (B, cfg.frames, cfg.img_size, cfg.img_size, cfg.channels)
    x = jax.random.normal(key, shape)
    cond_mask = jnp.zeros((B, cfg.frames))
    if first_frame is not None:
        x = x.at[:, 0].set(first_frame)
        cond_mask = cond_mask.at[:, 0].set(1.0)
    null = _null_text(params, text_states.shape)
    ts = jnp.linspace(1.0, 0.0, steps + 1)

    def step_fn(x, i):
        t_cur, t_nxt = ts[i], ts[i + 1]
        tb = jnp.full((B,), t_cur)
        v_cond = forward(params, x, tb, cond_mask, text_states, cfg)
        v_null = forward(params, x, tb, cond_mask, null, cfg)
        v = v_null + guidance * (v_cond - v_null)
        x = x + (t_nxt - t_cur) * v
        if first_frame is not None:
            x = x.at[:, 0].set(first_frame)  # re-pin after the step
        return x, None

    x, _ = jax.lax.scan(step_fn, x, jnp.arange(steps))
    return x
