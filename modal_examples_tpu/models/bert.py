"""BERT-family encoder — text embeddings (BGE) on TPU.

The model behind the reference's embeddings north-star config: bge-small-en
(gpu_snapshot.py:52, text_embeddings_inference.py:18 serves bge-base via the
TEI Rust/CUDA server; amazon_embeddings.py drives it at fleet scale). Here
the encoder is JAX: bidirectional attention with an additive padding mask
(XLA fuses this fine at BERT sizes — the flash kernel is reserved for the
causal LMs), CLS or mean pooling, L2 normalization.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import jax
import jax.numpy as jnp

from . import layers


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 384
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 1536
    max_position: int = 512
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    dtype: str = "float32"
    pooling: str = "cls"  # bge uses CLS pooling

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def bge_small_en() -> "BertConfig":
        return BertConfig()  # bge-small-en-v1.5 == BERT-small geometry

    @staticmethod
    def bge_base_en() -> "BertConfig":
        return BertConfig(dim=768, n_layers=12, n_heads=12, ffn_dim=3072)

    @staticmethod
    def tiny() -> "BertConfig":
        return BertConfig(vocab_size=512, dim=64, n_layers=2, n_heads=2, ffn_dim=128)


def init_params(key: jax.Array, cfg: BertConfig) -> dict:
    dt = cfg.jnp_dtype
    D, F, L = cfg.dim, cfg.ffn_dim, cfg.n_layers
    ks = jax.random.split(key, 12)

    def dense(k, *shape, scale=0.02):
        return layers.init_dense(k, shape, scale=scale, dtype=dt)

    return {
        "word_emb": dense(ks[0], cfg.vocab_size, D),
        "pos_emb": dense(ks[1], cfg.max_position, D),
        "type_emb": dense(ks[2], cfg.type_vocab_size, D),
        "emb_norm_w": jnp.ones((D,), dt),
        "emb_norm_b": jnp.zeros((D,), dt),
        "layers": {
            "wq": dense(ks[3], L, D, D),
            "bq": jnp.zeros((L, D), dt),
            "wk": dense(ks[4], L, D, D),
            "bk": jnp.zeros((L, D), dt),
            "wv": dense(ks[5], L, D, D),
            "bv": jnp.zeros((L, D), dt),
            "wo": dense(ks[6], L, D, D),
            "bo": jnp.zeros((L, D), dt),
            "attn_norm_w": jnp.ones((L, D), dt),
            "attn_norm_b": jnp.zeros((L, D), dt),
            "fc_w": dense(ks[7], L, D, F),
            "fc_b": jnp.zeros((L, F), dt),
            "proj_w": dense(ks[8], L, F, D),
            "proj_b": jnp.zeros((L, D), dt),
            "mlp_norm_w": jnp.ones((L, D), dt),
            "mlp_norm_b": jnp.zeros((L, D), dt),
        },
    }


def forward(
    params: dict,
    tokens: jax.Array,  # [B, S] int32
    attention_mask: jax.Array | None = None,  # [B, S] 1=real, 0=pad
    cfg: BertConfig = BertConfig(),
) -> jax.Array:  # [B, S, D] final hidden states
    B, S = tokens.shape
    if attention_mask is None:
        attention_mask = jnp.ones((B, S), jnp.int32)
    pos = jnp.arange(S)
    x = (
        params["word_emb"][tokens]
        + params["pos_emb"][pos][None, :, :]
        + params["type_emb"][jnp.zeros_like(tokens)]
    )
    x = layers.layer_norm(x, params["emb_norm_w"], params["emb_norm_b"], cfg.norm_eps)

    # additive mask: [B, 1, 1, S]
    bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, -1e9).astype(
        jnp.float32
    )
    scale = cfg.head_dim**-0.5

    def layer_fn(x, layer):
        # post-LN transformer (BERT convention)
        q = jnp.dot(x, layer["wq"]) + layer["bq"]
        k = jnp.dot(x, layer["wk"]) + layer["bk"]
        v = jnp.dot(x, layer["wv"]) + layer["bv"]
        q = q.reshape(B, S, cfg.n_heads, cfg.head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, cfg.n_heads, cfg.head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, cfg.n_heads, cfg.head_dim).transpose(0, 2, 1, 3)
        s = (
            jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
            * scale
            + bias
        )
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, cfg.dim)
        o = jnp.dot(o, layer["wo"]) + layer["bo"]
        x = layers.layer_norm(
            x + o, layer["attn_norm_w"], layer["attn_norm_b"], cfg.norm_eps
        )
        h = layers.gelu_mlp(
            {n: layer[n] for n in ("fc_w", "fc_b", "proj_w", "proj_b")}, x,
            exact=True,  # BERT uses erf-GELU
        )
        return layers.layer_norm(
            x + h, layer["mlp_norm_w"], layer["mlp_norm_b"], cfg.norm_eps
        ), None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    return x


def embed(
    params: dict,
    tokens: jax.Array,
    attention_mask: jax.Array | None = None,
    cfg: BertConfig = BertConfig(),
) -> jax.Array:  # [B, D] L2-normalized sentence embeddings
    B, S = tokens.shape
    if attention_mask is None:
        attention_mask = jnp.ones((B, S), jnp.int32)
    h = forward(params, tokens, attention_mask, cfg)
    if cfg.pooling == "cls":
        pooled = h[:, 0]
    else:  # mean over real tokens
        m = attention_mask[..., None].astype(h.dtype)
        pooled = (h * m).sum(1) / jnp.maximum(m.sum(1), 1.0)
    norm = jnp.linalg.norm(pooled.astype(jnp.float32), axis=-1, keepdims=True)
    return (pooled / jnp.maximum(norm, 1e-9)).astype(jnp.float32)


def load_hf_weights(model_dir: str | Path, cfg: BertConfig, dtype=None) -> dict:
    """Map an HF BERT checkpoint (bge-*) into this tree."""
    import numpy as np
    from safetensors import safe_open

    dt = dtype or cfg.jnp_dtype
    files = sorted(Path(model_dir).glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no safetensors under {model_dir}")
    raw: dict[str, np.ndarray] = {}
    for f in files:
        with safe_open(str(f), framework="np") as sf:
            for name in sf.keys():
                raw[name.removeprefix("bert.")] = sf.get_tensor(name)

    def g(name, transpose=False):
        arr = raw[name]
        return jnp.asarray(arr.T if transpose else arr, dtype=dt)

    def stack(fmt, transpose=False):
        return jnp.asarray(
            np.stack(
                [
                    raw[fmt.format(i)].T if transpose else raw[fmt.format(i)]
                    for i in range(cfg.n_layers)
                ]
            ),
            dtype=dt,
        )

    pre = "encoder.layer.{}."
    return {
        "word_emb": g("embeddings.word_embeddings.weight"),
        "pos_emb": g("embeddings.position_embeddings.weight"),
        "type_emb": g("embeddings.token_type_embeddings.weight"),
        "emb_norm_w": g("embeddings.LayerNorm.weight"),
        "emb_norm_b": g("embeddings.LayerNorm.bias"),
        "layers": {
            "wq": stack(pre + "attention.self.query.weight", True),
            "bq": stack(pre + "attention.self.query.bias"),
            "wk": stack(pre + "attention.self.key.weight", True),
            "bk": stack(pre + "attention.self.key.bias"),
            "wv": stack(pre + "attention.self.value.weight", True),
            "bv": stack(pre + "attention.self.value.bias"),
            "wo": stack(pre + "attention.output.dense.weight", True),
            "bo": stack(pre + "attention.output.dense.bias"),
            "attn_norm_w": stack(pre + "attention.output.LayerNorm.weight"),
            "attn_norm_b": stack(pre + "attention.output.LayerNorm.bias"),
            "fc_w": stack(pre + "intermediate.dense.weight", True),
            "fc_b": stack(pre + "intermediate.dense.bias"),
            "proj_w": stack(pre + "output.dense.weight", True),
            "proj_b": stack(pre + "output.dense.bias"),
            "mlp_norm_w": stack(pre + "output.LayerNorm.weight"),
            "mlp_norm_b": stack(pre + "output.LayerNorm.bias"),
        },
    }
