"""LoRA: low-rank adapters as a separate pytree.

Replaces the reference's unsloth/TRL LoRA stack (unsloth_finetune.py:205-213
targets q/k/v/o/gate/up/down; dreambooth/diffusers_lora_finetune.py). The
TPU-native shape: adapters are their OWN pytree — the frozen base params are
never touched, the optimizer state covers only the adapters (rank*d instead
of d^2), and inference either merges (``merge``) or applies the low-rank
delta on the fly inside the jitted forward (``llama.forward(lora=...)``:
x@(W + aXb) computed as x@W + (x@a)@b, never materializing W + delta).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo", "gate", "up", "down")


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: tuple[str, ...] = DEFAULT_TARGETS

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def init_lora(key: jax.Array, params: dict, lcfg: LoRAConfig) -> dict:
    """Adapters for the stacked layer weights: a ~ N(0, 1/r), b = 0 (so the
    model starts exactly at the base)."""
    lora_layers = {}
    keys = jax.random.split(key, len(lcfg.targets))
    for k, name in zip(keys, lcfg.targets):
        w = params["layers"][name]  # [L, din, dout]
        L, din, dout = w.shape
        lora_layers[f"{name}_a"] = (
            jax.random.normal(k, (L, din, lcfg.rank), jnp.float32) / lcfg.rank
        ).astype(w.dtype)
        lora_layers[f"{name}_b"] = jnp.zeros((L, lcfg.rank, dout), w.dtype)
    return {"layers": lora_layers}


def delta(x: jax.Array, a: jax.Array, b: jax.Array, scale: float) -> jax.Array:
    """(x @ a) @ b * scale in f32 — the on-the-fly low-rank path."""
    xa = jnp.dot(x, a, preferred_element_type=jnp.float32).astype(x.dtype)
    return jnp.dot(xa, b, preferred_element_type=jnp.float32) * scale


def merge(params: dict, lora_params: dict, lcfg: LoRAConfig) -> dict:
    """Fold adapters into a copy of the base weights (for serving)."""
    merged_layers = dict(params["layers"])
    for name in lcfg.targets:
        a = lora_params["layers"][f"{name}_a"]
        b = lora_params["layers"][f"{name}_b"]
        w = params["layers"][name]
        merged_layers[name] = (
            w.astype(jnp.float32)
            + jnp.einsum("lir,lro->lio", a.astype(jnp.float32), b.astype(jnp.float32))
            * lcfg.scale
        ).astype(w.dtype)
    out = dict(params)
    out["layers"] = merged_layers
    return out


def param_count(lora_params: dict) -> int:
    return sum(x.size for x in jax.tree.leaves(lora_params))
