"""LoRA: low-rank adapters as a separate pytree.

Replaces the reference's unsloth/TRL LoRA stack (unsloth_finetune.py:205-213
targets q/k/v/o/gate/up/down; dreambooth/diffusers_lora_finetune.py). The
TPU-native shape: adapters are their OWN pytree — the frozen base params are
never touched, the optimizer state covers only the adapters (rank*d instead
of d^2), and inference either merges (``merge``) or applies the low-rank
delta on the fly inside the jitted forward (``llama.forward(lora=...)``:
x@(W + aXb) computed as x@W + (x@a)@b, never materializing W + delta).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo", "gate", "up", "down")


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: tuple[str, ...] = DEFAULT_TARGETS

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def init_lora(key: jax.Array, params: dict, lcfg: LoRAConfig) -> dict:
    """Adapters for the stacked layer weights: a ~ N(0, 1/r), b = 0 (so the
    model starts exactly at the base)."""
    lora_layers = {}
    keys = jax.random.split(key, len(lcfg.targets))
    for k, name in zip(keys, lcfg.targets):
        w = params["layers"][name]  # [L, din, dout]
        if hasattr(w, "q"):
            # QuantizedWeight base (int8/int4 serving or memory-frugal
            # fine-tuning): adapters must stay REAL-valued — int8 adapters
            # would truncate a~1/rank to zeros and break autodiff
            shape, dt = w.q.shape, w.scale.dtype
        else:
            shape, dt = w.shape, w.dtype
        L, din, dout = shape
        lora_layers[f"{name}_a"] = (
            jax.random.normal(k, (L, din, lcfg.rank), jnp.float32) / lcfg.rank
        ).astype(dt)
        lora_layers[f"{name}_b"] = jnp.zeros((L, lcfg.rank, dout), dt)
    return {"layers": lora_layers}


def delta(x: jax.Array, a: jax.Array, b: jax.Array, scale: float) -> jax.Array:
    """(x @ a) @ b * scale in f32 — the on-the-fly low-rank path."""
    xa = jnp.dot(x, a, preferred_element_type=jnp.float32).astype(x.dtype)
    return jnp.dot(xa, b, preferred_element_type=jnp.float32) * scale


def merge(params: dict, lora_params: dict, lcfg: LoRAConfig) -> dict:
    """Fold adapters into a copy of the base weights (for serving)."""
    merged_layers = dict(params["layers"])
    for name in lcfg.targets:
        a = lora_params["layers"][f"{name}_a"]
        b = lora_params["layers"][f"{name}_b"]
        w = params["layers"][name]
        merged_layers[name] = (
            w.astype(jnp.float32)
            + jnp.einsum("lir,lro->lio", a.astype(jnp.float32), b.astype(jnp.float32))
            * lcfg.scale
        ).astype(w.dtype)
    out = dict(params)
    out["layers"] = merged_layers
    return out


def param_count(lora_params: dict) -> int:
    return sum(x.size for x in jax.tree.leaves(lora_params))


# -- generic tree LoRA (diffusion / any model) -------------------------------

#: the MMDiT attention + MLP projections — the dreambooth target set
#: (diffusers_lora_finetune.py:205-213 targets to_q/to_k/to_v/to_out +
#: ff projections; these are their names in models.diffusion.mmdit_init)
DIT_TARGETS = (
    "img_wq", "img_wk", "img_wv", "img_wo",
    "ctx_wq", "ctx_wk", "ctx_wv", "ctx_wo",
    "img_fc1", "img_fc2", "ctx_fc1", "ctx_fc2",
)


def init_lora_tree(
    key: jax.Array, params: dict, lcfg: LoRAConfig
) -> dict:
    """Adapters for an ARBITRARY nested param dict: every leaf whose dict
    key is in ``lcfg.targets`` and has >= 2 dims gets an (a, b) pair with
    any leading (stack) dims preserved — ``[..., din, dout]`` becomes
    ``a [..., din, r]`` + ``b [..., r, dout]``. The returned tree mirrors
    the nesting, so it checkpoints/commits like any param tree.

    This is the diffusion-model fine-tuning path (dreambooth,
    diffusers_lora_finetune.py): llama has its own dedicated
    ``init_lora`` whose adapters feed the on-the-fly ``delta`` inside the
    jitted forward; diffusion training merges per step instead
    (``merge_tree``) — cheap at DiT scale, zero changes to the forward.
    """
    flat = []

    def walk(node, out):
        for name, v in node.items():
            if isinstance(v, dict):
                sub: dict = {}
                walk(v, sub)
                if sub:
                    out[name] = sub
            elif name in lcfg.targets and getattr(v, "ndim", 0) >= 2:
                flat.append((out, name, v))
                out[name] = None  # placeholder, filled below
        return out

    tree: dict = {}
    walk(params, tree)
    if not flat:
        raise ValueError(
            f"no leaves matched targets {lcfg.targets!r}; check the names "
            "against the model's param tree"
        )
    keys = jax.random.split(key, len(flat))
    for k, (parent, name, w) in zip(keys, flat):
        *stack, din, dout = w.shape
        parent[name] = {
            "a": (
                jax.random.normal(k, (*stack, din, lcfg.rank), jnp.float32)
                / lcfg.rank
            ).astype(w.dtype),
            "b": jnp.zeros((*stack, lcfg.rank, dout), w.dtype),
        }
    return tree


def merge_tree(params: dict, lora_tree: dict, lcfg: LoRAConfig) -> dict:
    """Base tree + low-rank deltas, structure-preserving. Inside a jitted
    loss this is how diffusion LoRA trains: grads flow only to the (a, b)
    leaves, the base stays a constant — XLA fuses the a@b expansion into
    the consuming matmuls, so no persistent merged copy exists."""

    def walk(p_node, l_node):
        out = {}
        for name, v in p_node.items():
            l_v = l_node.get(name) if isinstance(l_node, dict) else None
            if isinstance(v, dict):
                out[name] = walk(v, l_v or {})
            elif isinstance(l_v, dict) and "a" in l_v:
                a = l_v["a"].astype(jnp.float32)
                b = l_v["b"].astype(jnp.float32)
                out[name] = (
                    v.astype(jnp.float32)
                    + jnp.einsum("...ir,...ro->...io", a, b) * lcfg.scale
                ).astype(v.dtype)
            else:
                out[name] = v
        return out

    return walk(params, lora_tree)
