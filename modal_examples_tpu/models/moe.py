"""Mixture-of-Experts: top-k routing + expert parallelism over a mesh axis.

The reference serves MoE models (Gemma-4-26B-A4B via vllm_inference.py:54-58,
Qwen MoE, DeepSeek configs) but leaves expert parallelism inside the CUDA
engines (SURVEY.md §2.3: "MoE routing + expert sharding on mesh axis;
all_to_all over ICI" is ours to build). This module implements the GShard
dispatch TPU-natively:

- top-k softmax routing with per-(group, expert) capacity and position-in-
  expert assignment (static shapes: dropped tokens are zeroed, not ragged);
- ``moe_mlp``: the single-device ground truth (groups = what shards will
  see, so the EP result is bit-identical);
- ``moe_mlp_ep``: the same math under shard_map with experts sharded over an
  ``expert`` mesh axis — dispatch/return ride two ``all_to_all``s (ICI on a
  real slice);
- the standard load-balancing auxiliary loss.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from ..parallel.mesh import shard_map_compat


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    d_model: int = 64
    d_ff: int = 128

    def capacity(self, tokens_per_group: int) -> int:
        c = int(self.capacity_factor * self.top_k * tokens_per_group / self.n_experts)
        return max(c, 1)


def init_params(key: jax.Array, cfg: MoEConfig, dtype=jnp.float32) -> dict:
    k1, k2, k3 = jax.random.split(key, 3)
    E, D, F = cfg.n_experts, cfg.d_model, cfg.d_ff
    s_in, s_out = D**-0.5, F**-0.5
    return {
        "router": jax.random.normal(k1, (D, E), dtype) * s_in,
        "w_in": jax.random.normal(k2, (E, D, F), dtype) * s_in,
        "w_out": jax.random.normal(k3, (E, F, D), dtype) * s_out,
    }


def _route(x: jax.Array, router: jax.Array, cfg: MoEConfig, capacity: int):
    """Per-group dispatch/combine tensors.

    x: [T, D] (one group). Returns (dispatch [T, E, C] bool-ish f32,
    combine [T, E, C] f32 weights, aux_loss scalar).
    """
    T = x.shape[0]
    E = cfg.n_experts
    logits = jnp.dot(x, router, preferred_element_type=jnp.float32)  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # aux load-balance loss (Switch): mean prob mass * mean assignment frac
    top1 = jnp.argmax(probs, axis=-1)
    frac_tokens = jnp.mean(jax.nn.one_hot(top1, E), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)

    topk_p, topk_idx = jax.lax.top_k(probs, cfg.top_k)  # [T, k]
    topk_p = topk_p / jnp.sum(topk_p, axis=-1, keepdims=True)  # renormalize

    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    counts = jnp.zeros((E,), jnp.int32)  # slots used per expert so far
    for k in range(cfg.top_k):
        e_k = topk_idx[:, k]  # [T]
        onehot = jax.nn.one_hot(e_k, E, dtype=jnp.int32)  # [T, E]
        # position of each token within its expert (prior ks first)
        pos_in_e = jnp.cumsum(onehot, axis=0) - 1 + counts[None, :]  # [T, E]
        pos = jnp.take_along_axis(pos_in_e, e_k[:, None], 1)[:, 0]  # [T]
        keep = pos < capacity
        slot = jnp.clip(pos, 0, capacity - 1)
        d_k = (
            jax.nn.one_hot(e_k, E)[:, :, None]
            * jax.nn.one_hot(slot, capacity)[:, None, :]
            * keep[:, None, None]
        )
        dispatch = dispatch + d_k
        combine = combine + d_k * topk_p[:, k][:, None, None]
        counts = counts + jnp.sum(onehot * keep[:, None].astype(jnp.int32), axis=0)
    return dispatch, combine, aux


def _expert_ffn(w_in, w_out, h):
    """h: [..., C, D] per expert; gelu MLP with that expert's weights."""
    return jnp.einsum(
        "...cf,fd->...cd",
        jax.nn.gelu(jnp.einsum("...cd,df->...cf", h, w_in)),
        w_out,
    )


def moe_mlp(
    params: dict, x: jax.Array, cfg: MoEConfig, *, groups: int = 1
) -> tuple[jax.Array, jax.Array]:
    """Ground-truth MoE layer. x: [T, D]; ``groups`` partitions tokens the
    way EP shards would (so capacities — and therefore drops — match the
    sharded version exactly). Returns (out [T, D], aux_loss)."""
    T, D = x.shape
    assert T % groups == 0
    tg = T // groups
    cap = cfg.capacity(tg)
    xg = x.reshape(groups, tg, D)

    def per_group(xg_i):
        dispatch, combine, aux = _route(xg_i, params["router"], cfg, cap)
        expert_in = jnp.einsum("tec,td->ecd", dispatch, xg_i)  # [E, C, D]
        expert_out = jax.vmap(_expert_ffn)(
            params["w_in"], params["w_out"], expert_in
        )  # [E, C, D]
        out = jnp.einsum("tec,ecd->td", combine, expert_out)
        return out, aux

    out, aux = jax.vmap(per_group)(xg)
    return out.reshape(T, D), jnp.mean(aux)


def _mm(h, w):
    """h @ w where w may be an int8 QuantizedWeight (serving decode streams
    every expert's weights; int8 halves that HBM traffic exactly like the
    dense matmuls — models.quantize.LLAMA_TARGETS includes moe_gate/up/down).
    Delegates to layers.mm (the one quantized-matmul dispatch) and rounds
    back to h's dtype."""
    from .layers import mm

    return mm(h, w).astype(h.dtype)


def _swiglu_expert(w_gate, w_up, w_down, h):
    """SwiGLU expert FFN (Mixtral w1/w3/w2): h [T, D] -> [T, D]."""
    a = _mm(h, w_gate)
    b = _mm(h, w_up)
    return _mm(jax.nn.silu(a) * b, w_down)


def moe_swiglu_nodrop(
    router: jax.Array,  # [D, E]
    w_gate: jax.Array,  # [E, D, F]
    w_up: jax.Array,  # [E, D, F]
    w_down: jax.Array,  # [E, F, D]
    x: jax.Array,  # [T, D]
    top_k: int,
) -> tuple[jax.Array, jax.Array]:
    """Top-k routed SwiGLU experts with NO capacity drops — the serving
    formulation (and the per-token ground truth the capacity-routed training
    path approximates).

    Routing is per-token, so incremental decode reproduces full-sequence
    results token-for-token — the property the engine's exact-vs-dense MoE
    test relies on. Every expert runs on every token (a grouped-matmul over
    the full expert set); at decode batch sizes all experts' weights are the
    HBM-bandwidth floor anyway, and the [T, F] intermediate stays bounded by
    scanning over experts rather than materializing [T, E, F].

    Replaces the engine-internal MoE the reference serves via vLLM/SGLang
    (vllm_inference.py:54-58 Gemma MoE, sglang_low_latency.py:67 Qwen MoE).
    Returns (out [T, D] float32, aux load-balance loss).
    """
    E = w_gate.shape[0]
    xf = x.astype(jnp.float32)
    logits = jnp.einsum("td,de->te", xf, router.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]

    top1 = jnp.argmax(probs, axis=-1)
    aux = E * jnp.sum(
        jnp.mean(jax.nn.one_hot(top1, E), axis=0) * jnp.mean(probs, axis=0)
    )

    topk_p, topk_idx = jax.lax.top_k(probs, top_k)  # [T, k]
    topk_p = topk_p / jnp.sum(topk_p, axis=-1, keepdims=True)
    # [T, E] combine weights, zero off the top-k
    w_full = jnp.zeros_like(probs)
    w_full = jax.vmap(lambda w, p, i: w.at[i].add(p))(w_full, topk_p, topk_idx)

    def body(acc, ew):
        wg, wu, wd, we = ew  # we: [T] this expert's combine weight per token
        return acc + we[:, None] * _swiglu_expert(wg, wu, wd, xf), None

    out, _ = jax.lax.scan(
        body,
        jnp.zeros_like(xf),
        (w_gate, w_up, w_down, w_full.T),
    )
    return out, aux


def moe_swiglu_capacity(
    router: jax.Array,  # [D, E]
    w_gate: jax.Array,  # [E, D, F]
    w_up: jax.Array,  # [E, D, F]
    w_down: jax.Array,  # [E, F, D]
    x: jax.Array,  # [T, D]
    top_k: int,
    capacity_factor: float,
) -> tuple[jax.Array, jax.Array]:
    """Capacity-routed SwiGLU experts (GShard dispatch): each expert computes
    only its capacity slots, ~top_k/E of the no-drop cost — the right
    formulation for compute-bound prefill/training at scale (tokens over
    capacity are dropped, so it is NOT bit-identical to the no-drop serving
    path). Returns (out [T, D] float32, aux load-balance loss)."""
    from .quantize import QuantizedWeight, dequantize_weight

    # the capacity path is compute-bound (training/prefill scale): int8
    # weights buy nothing here, so materialize bf16 instead of threading
    # QuantizedWeight through the batched dispatch einsums
    w_gate, w_up, w_down = (
        dequantize_weight(w) if isinstance(w, QuantizedWeight) else w
        for w in (w_gate, w_up, w_down)
    )
    E, D, F = w_gate.shape
    cfg = MoEConfig(
        n_experts=E, top_k=top_k, capacity_factor=capacity_factor,
        d_model=D, d_ff=F,
    )
    xf = x.astype(jnp.float32)
    cap = cfg.capacity(x.shape[0])
    dispatch, combine, aux = _route(xf, router.astype(jnp.float32), cfg, cap)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, xf)  # [E, C, D]
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, w_gate)) * jnp.einsum(
        "ecd,edf->ecf", expert_in, w_up
    )
    expert_out = jnp.einsum("ecf,efd->ecd", h, w_down)
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out, aux


def moe_mlp_ep(
    params: dict, x: jax.Array, cfg: MoEConfig, mesh, *, axis: str = "expert"
) -> tuple[jax.Array, jax.Array]:
    """Expert-parallel MoE: tokens AND experts sharded over ``axis``; the
    dispatched activations cross shards via all_to_all (ICI), compute runs
    on each shard's local experts, results ride all_to_all back."""
    from jax.sharding import PartitionSpec as P

    n_shards = mesh.shape[axis]
    E_loc = cfg.n_experts // n_shards
    T = x.shape[0]
    cap = cfg.capacity(T // n_shards)

    def shard_fn(router, w_in, w_out, x_loc):
        D = x_loc.shape[-1]
        dispatch, combine, aux = _route(x_loc, router, cfg, cap)  # [t, E, C]
        expert_in = jnp.einsum("tec,td->ecd", dispatch, x_loc)  # [E, C, D]
        # global expert e = owner_shard * E_loc + e_loc (blocked layout):
        # send each owner its slice, receive every shard's tokens for OUR
        # local experts. untiled all_to_all on dim 0: consumed, and the
        # received blocks stack as a new leading dim of size S.
        send = expert_in.reshape(n_shards, E_loc, cap, D)
        recv = jax.lax.all_to_all(send, axis, 0, 0, tiled=False)  # [S, E_loc, C, D]
        h = recv.transpose(1, 0, 2, 3).reshape(E_loc, n_shards * cap, D)
        out_loc = jax.vmap(_expert_ffn)(w_in, w_out, h)  # [E_loc, S*C, D]
        # return every shard's results to it, then reassemble global E order
        back = jax.lax.all_to_all(
            out_loc.reshape(E_loc, n_shards, cap, D).transpose(1, 0, 2, 3),
            axis, 0, 0, tiled=False,
        )  # [S, E_loc, C, D] — block j = my tokens through shard j's experts
        expert_out = back.reshape(cfg.n_experts, cap, D)
        out = jnp.einsum("tec,ecd->td", combine, expert_out)
        return out, aux[None]  # rank-1 so shards concatenate over the axis

    out, aux = shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(axis)),
        check_vma=False,
    )(params["router"], params["w_in"], params["w_out"], x)
    return out, jnp.mean(aux)
