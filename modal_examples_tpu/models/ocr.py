"""Optical character recognition: conv + transformer + CTC, TPU-first.

The reference's OCR job queue runs the Marker/Datalab model stack on CUDA
(/root/reference/09_job_queues/doc_ocr_jobs.py:38 — marker-pdf downloads
torch checkpoints). This module is the TPU-native counterpart at the
architecture level the field actually uses for text-line recognition
(CRNN/TrOCR family): a strided conv stem collapses the image height into a
width-wise sequence of visual features, a bidirectional transformer
encoder contextualizes it, and CTC aligns the unsegmented character
sequence — no bounding boxes, no per-character labels.

TPU-first: NHWC convs (channels-last keeps the MXU contraction on the
minor dim), one static input shape per config (lines are padded to
``width``), scanned encoder layers, and ``optax.ctc_loss`` for training.
Zero egress means no published OCR checkpoint exists here: the example
trains this model from scratch on synthetically RENDERED text (PIL
rasterizes strings; the model genuinely learns glyphs — the same
train-on-rendered-text recipe synthetic-data OCR systems use).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import layers

#: recognized alphabet; index 0 is the CTC blank
CHARSET = " ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789.$:-/#"


@dataclasses.dataclass(frozen=True)
class OCRConfig:
    height: int = 32
    width: int = 256
    channels: int = 1
    dim: int = 128  # encoder width
    n_layers: int = 2
    n_heads: int = 4
    n_classes: int = len(CHARSET) + 1  # + blank at index 0
    norm_eps: float = 1e-6
    dtype: str = "float32"

    @property
    def seq_len(self) -> int:  # width positions after the conv stem
        return self.width // 4

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)


def encode_text(s: str) -> list[int]:
    """chars -> label ids (1-based; 0 is the CTC blank)."""
    return [CHARSET.index(c) + 1 for c in s.upper() if c in CHARSET]


def decode_labels(ids) -> str:
    return "".join(CHARSET[i - 1] for i in ids if 1 <= i <= len(CHARSET))


def init_params(key: jax.Array, cfg: OCRConfig) -> dict:
    dt = cfg.jnp_dtype
    D, L = cfg.dim, cfg.n_layers
    ks = iter(jax.random.split(key, 16))

    def dense(*shape, scale=None):
        return layers.init_dense(next(ks), shape, scale=scale, dtype=dt)

    def conv(k, cin, cout):
        return dense(k, k, cin, cout, scale=(k * k * cin) ** -0.5)

    # stem: H x W -> (H/8) x (W/4); the residual height collapses into the
    # feature dim so each width position sees the full glyph column
    c1, c2, c3 = 32, 64, D
    return {
        "conv1": conv(3, cfg.channels, c1),  # stride (2, 2)
        "conv2": conv(3, c1, c2),  # stride (2, 2)
        "conv3": conv(3, c2, c3),  # stride (2, 1)
        "col_proj": dense((cfg.height // 8) * c3, D),
        "pos_emb": dense(cfg.seq_len, D, scale=0.02),
        "layers": {
            "ln1_s": jnp.ones((L, D), dt), "ln1_b": jnp.zeros((L, D), dt),
            "wq": dense(L, D, D), "wk": dense(L, D, D),
            "wv": dense(L, D, D), "wo": dense(L, D, D),
            "ln2_s": jnp.ones((L, D), dt), "ln2_b": jnp.zeros((L, D), dt),
            "fc": dense(L, D, 4 * D), "fc_b": jnp.zeros((L, 4 * D), dt),
            "proj": dense(L, 4 * D, D), "proj_b": jnp.zeros((L, D), dt),
        },
        "head": dense(D, cfg.n_classes),
        "head_b": jnp.zeros((cfg.n_classes,), dt),
    }


def _conv2d(x, w, stride):
    return jax.lax.conv_general_dilated(
        x, w, stride, "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


def forward(params: dict, images: jax.Array, cfg: OCRConfig) -> jax.Array:
    """[B, H, W, 1] in [0, 1] -> CTC logits [B, seq_len, n_classes]."""
    B = images.shape[0]
    x = jax.nn.relu(_conv2d(images.astype(cfg.jnp_dtype), params["conv1"], (2, 2)))
    x = jax.nn.relu(_conv2d(x, params["conv2"], (2, 2)))
    x = jax.nn.relu(_conv2d(x, params["conv3"], (2, 1)))  # [B, H/8, W/4, D]
    # width becomes the sequence; the glyph column flattens into features
    x = x.transpose(0, 2, 1, 3).reshape(B, cfg.seq_len, -1)
    h = x @ params["col_proj"] + params["pos_emb"][None]

    def norm(v, s, b):
        mu = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        return (v - mu) * jax.lax.rsqrt(var + cfg.norm_eps) * s + b

    hd = cfg.dim // cfg.n_heads

    def layer_fn(h, l):
        a = norm(h, l["ln1_s"], l["ln1_b"])
        q = (a @ l["wq"]).reshape(B, cfg.seq_len, cfg.n_heads, hd)
        k = (a @ l["wk"]).reshape(B, cfg.seq_len, cfg.n_heads, hd)
        v = (a @ l["wv"]).reshape(B, cfg.seq_len, cfg.n_heads, hd)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * hd**-0.5  # bidirectional: CTC needs context from both sides
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, cfg.seq_len, cfg.dim)
        h = h + o @ l["wo"]
        a = norm(h, l["ln2_s"], l["ln2_b"])
        a = jax.nn.relu(a @ l["fc"] + l["fc_b"]) @ l["proj"] + l["proj_b"]
        return h + a, None

    h, _ = jax.lax.scan(layer_fn, h, params["layers"])
    return h @ params["head"] + params["head_b"]  # [B, T, n_classes]


def ctc_loss(
    params: dict,
    images: jax.Array,  # [B, H, W, 1]
    labels: jax.Array,  # [B, N] int32, 0-padded (0 is also the blank)
    cfg: OCRConfig,
) -> jax.Array:
    import optax

    logits = forward(params, images, cfg)
    B, T, _ = logits.shape
    logit_pad = jnp.zeros((B, T), jnp.float32)  # full width always valid
    label_pad = (labels == 0).astype(jnp.float32)
    per_seq = optax.ctc_loss(logits, logit_pad, labels, label_pad, blank_id=0)
    return jnp.mean(per_seq)


def greedy_decode(params: dict, images: jax.Array, cfg: OCRConfig) -> list[str]:
    """Argmax CTC decode: collapse repeats, drop blanks (host-side)."""
    import numpy as np

    logits = forward(params, images, cfg)
    best = np.asarray(jnp.argmax(logits, axis=-1))  # [B, T]
    out = []
    for row in best:
        chars = []
        prev = -1
        for t in row.tolist():
            if t != prev and t != 0:
                chars.append(t)
            prev = t
        out.append(decode_labels(chars))
    return out


# -- synthetic rendered-text data -------------------------------------------


def render_line(text: str, cfg: OCRConfig, *, jitter_rng=None):
    """Rasterize one text line to [H, W, 1] float32 in [0, 1] (ink = 1)."""
    import numpy as np
    from PIL import Image, ImageDraw, ImageFont

    img = Image.new("L", (cfg.width, cfg.height), 0)
    draw = ImageDraw.Draw(img)
    font = ImageFont.load_default()
    x, y = 4, cfg.height // 2 - 6
    if jitter_rng is not None:
        x += int(jitter_rng.integers(0, 8))
        y += int(jitter_rng.integers(-3, 4))
    draw.text((x, y), text.upper(), font=font, fill=255)
    arr = np.asarray(img, np.float32) / 255.0
    if jitter_rng is not None:
        arr = np.clip(
            arr + jitter_rng.normal(0, 0.05, arr.shape).astype(np.float32),
            0.0, 1.0,
        )
    return arr[:, :, None]


def synthetic_batch(np_rng, batch: int, cfg: OCRConfig, *, max_len: int = 12):
    """Random rendered lines + padded labels (the training corpus)."""
    import numpy as np

    texts = []
    for _ in range(batch):
        n = int(np_rng.integers(3, max_len))
        # sample over the FULL charset including spaces (index 0) — the
        # documents the recognizer will read contain them; edge spaces are
        # stripped (they render as nothing), with a fallback for all-space
        texts.append(
            "".join(
                CHARSET[int(np_rng.integers(0, len(CHARSET)))]
                for _ in range(n)
            ).strip() or "A"
        )
    images = np.stack([render_line(t, cfg, jitter_rng=np_rng) for t in texts])
    labels = np.zeros((batch, max_len + 2), np.int32)
    for i, t in enumerate(texts):
        ids = encode_text(t)
        labels[i, : len(ids)] = ids
    return images, labels, texts
