"""VAE: the latent-space autoencoder of the SD family (AutoencoderKL).

The reference serves SD3.5/Flux pipelines whose image side is a conv VAE
(text_to_image.py:99-137 loads the full diffusers pipeline; the VAE decodes
latents to pixels). This is the TPU-native counterpart: a diffusers
AutoencoderKL-shape model in JAX/NHWC with an HF safetensors loader, so a
standard `vae/diffusion_pytorch_model.safetensors` checkout drops in.

Architecture (diffusers AutoencoderKL):
- encoder: conv_in -> down blocks (2 resnets each, downsample conv between
  levels) -> mid (resnet, attention, resnet) -> group-norm -> conv_out
  producing 2*latent_channels (mean, logvar);
- decoder: conv_in -> mid (resnet, attention, resnet) -> up blocks
  (3 resnets each, nearest-2x upsample + conv between levels) -> conv_out;
- scaling: latents are multiplied by ``scaling_factor`` after encode and
  divided before decode (the SD convention diffusion models train against).

NHWC layout throughout (TPU conv convention); weights stored HWIO.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    base: int = 128  # first-level width
    channel_mults: tuple = (1, 2, 4, 4)  # SD: 128/256/512/512, 8x down
    scaling_factor: float = 0.18215  # SD1/2; SD3 uses 1.5305 (+shift)
    shift_factor: float = 0.0  # SD3: 0.0609
    norm_groups: int = 32
    dtype: str = "float32"

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def downscale(self) -> int:
        return 2 ** (len(self.channel_mults) - 1)

    @staticmethod
    def sd_shape() -> "VAEConfig":
        """The SD1/2/XL VAE shape (4-ch latents, 8x downsample)."""
        return VAEConfig()

    @staticmethod
    def sd3_shape() -> "VAEConfig":
        """SD3/Flux VAE: 16-channel latents."""
        return VAEConfig(
            latent_channels=16, scaling_factor=1.5305, shift_factor=0.0609
        )

    @staticmethod
    def tiny() -> "VAEConfig":
        return VAEConfig(base=32, channel_mults=(1, 2), norm_groups=8)


def _conv_init(key, k, cin, cout, dtype):
    scale = (k * k * cin) ** -0.5
    return jax.random.normal(key, (k, k, cin, cout), dtype) * scale


def _resnet_init(ks, cin, cout, dt):
    k1, k2, k3 = jax.random.split(ks, 3)
    p = {
        "norm1_scale": jnp.ones((cin,), dt), "norm1_bias": jnp.zeros((cin,), dt),
        "conv1": _conv_init(k1, 3, cin, cout, dt),
        "conv1_b": jnp.zeros((cout,), dt),
        "norm2_scale": jnp.ones((cout,), dt), "norm2_bias": jnp.zeros((cout,), dt),
        "conv2": _conv_init(k2, 3, cout, cout, dt),
        "conv2_b": jnp.zeros((cout,), dt),
    }
    if cin != cout:
        p["shortcut"] = _conv_init(k3, 1, cin, cout, dt)
        p["shortcut_b"] = jnp.zeros((cout,), dt)
    return p


def _attn_init(ks, c, dt):
    k1, k2, k3, k4 = jax.random.split(ks, 4)
    s = c**-0.5
    return {
        "norm_scale": jnp.ones((c,), dt), "norm_bias": jnp.zeros((c,), dt),
        "q": jax.random.normal(k1, (c, c), dt) * s,
        "q_b": jnp.zeros((c,), dt),
        "k": jax.random.normal(k2, (c, c), dt) * s,
        "k_b": jnp.zeros((c,), dt),
        "v": jax.random.normal(k3, (c, c), dt) * s,
        "v_b": jnp.zeros((c,), dt),
        "o": jax.random.normal(k4, (c, c), dt) * s,
        "o_b": jnp.zeros((c,), dt),
    }


def init_params(key: jax.Array, cfg: VAEConfig) -> dict:
    dt = cfg.jnp_dtype
    widths = [cfg.base * m for m in cfg.channel_mults]
    ks = iter(jax.random.split(key, 64))
    enc = {
        "conv_in": _conv_init(next(ks), 3, cfg.in_channels, widths[0], dt),
        "conv_in_b": jnp.zeros((widths[0],), dt),
        "down": [],
        "mid_res1": _resnet_init(next(ks), widths[-1], widths[-1], dt),
        "mid_attn": _attn_init(next(ks), widths[-1], dt),
        "mid_res2": _resnet_init(next(ks), widths[-1], widths[-1], dt),
        "norm_out_scale": jnp.ones((widths[-1],), dt),
        "norm_out_bias": jnp.zeros((widths[-1],), dt),
        "conv_out": _conv_init(next(ks), 3, widths[-1], 2 * cfg.latent_channels, dt),
        "conv_out_b": jnp.zeros((2 * cfg.latent_channels,), dt),
    }
    cin = widths[0]
    for i, w in enumerate(widths):
        blk = {
            "res1": _resnet_init(next(ks), cin, w, dt),
            "res2": _resnet_init(next(ks), w, w, dt),
        }
        if i < len(widths) - 1:
            blk["downsample"] = _conv_init(next(ks), 3, w, w, dt)
            blk["downsample_b"] = jnp.zeros((w,), dt)
        enc["down"].append(blk)
        cin = w

    dec = {
        "conv_in": _conv_init(next(ks), 3, cfg.latent_channels, widths[-1], dt),
        "conv_in_b": jnp.zeros((widths[-1],), dt),
        "mid_res1": _resnet_init(next(ks), widths[-1], widths[-1], dt),
        "mid_attn": _attn_init(next(ks), widths[-1], dt),
        "mid_res2": _resnet_init(next(ks), widths[-1], widths[-1], dt),
        "up": [],
        "norm_out_scale": jnp.ones((widths[0],), dt),
        "norm_out_bias": jnp.zeros((widths[0],), dt),
        "conv_out": _conv_init(next(ks), 3, widths[0], cfg.in_channels, dt),
        "conv_out_b": jnp.zeros((cfg.in_channels,), dt),
    }
    cin = widths[-1]
    for i, w in enumerate(reversed(widths)):
        blk = {
            "res1": _resnet_init(next(ks), cin, w, dt),
            "res2": _resnet_init(next(ks), w, w, dt),
            "res3": _resnet_init(next(ks), w, w, dt),
        }
        if i < len(widths) - 1:
            blk["upsample"] = _conv_init(next(ks), 3, w, w, dt)
            blk["upsample_b"] = jnp.zeros((w,), dt)
        dec["up"].append(blk)
        cin = w
    return {"encoder": enc, "decoder": dec}


def _conv(x, w, b, stride=1):
    y = jax.lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + b


def _gn(x, scale, bias, groups, eps=1e-6):
    B, H, W, C = x.shape
    xg = x.reshape(B, H, W, groups, C // groups)
    mu = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mu) * jax.lax.rsqrt(var + eps)
    return xg.reshape(B, H, W, C) * scale + bias


def _resnet(p, x, groups):
    h = jax.nn.silu(_gn(x, p["norm1_scale"], p["norm1_bias"], groups))
    h = _conv(h, p["conv1"], p["conv1_b"])
    h = jax.nn.silu(_gn(h, p["norm2_scale"], p["norm2_bias"], groups))
    h = _conv(h, p["conv2"], p["conv2_b"])
    if "shortcut" in p:
        x = _conv(x, p["shortcut"], p["shortcut_b"])
    return x + h


def _attn(p, x, groups):
    B, H, W, C = x.shape
    h = _gn(x, p["norm_scale"], p["norm_bias"], groups)
    flat = h.reshape(B, H * W, C)
    q = flat @ p["q"] + p["q_b"]
    k = flat @ p["k"] + p["k_b"]
    v = flat @ p["v"] + p["v_b"]
    s = jnp.einsum("bqc,bkc->bqk", q, k, preferred_element_type=jnp.float32)
    a = jax.nn.softmax(s * C**-0.5, axis=-1).astype(v.dtype)
    o = jnp.einsum("bqk,bkc->bqc", a, v) @ p["o"] + p["o_b"]
    return x + o.reshape(B, H, W, C)


def encode(
    params: dict, images: jax.Array, cfg: VAEConfig, *, key=None
) -> jax.Array:
    """images [B, H, W, C] in [-1, 1] -> latents [B, H/8, W/8, Cl] (scaled).
    With ``key`` the posterior is sampled; without, the mean is returned."""
    g = cfg.norm_groups
    p = params["encoder"]
    x = _conv(images.astype(cfg.jnp_dtype), p["conv_in"], p["conv_in_b"])
    for i, blk in enumerate(p["down"]):
        x = _resnet(blk["res1"], x, g)
        x = _resnet(blk["res2"], x, g)
        if "downsample" in blk:
            x = _conv(x, blk["downsample"], blk["downsample_b"], stride=2)
    x = _resnet(p["mid_res1"], x, g)
    x = _attn(p["mid_attn"], x, g)
    x = _resnet(p["mid_res2"], x, g)
    x = jax.nn.silu(_gn(x, p["norm_out_scale"], p["norm_out_bias"], g))
    x = _conv(x, p["conv_out"], p["conv_out_b"])
    if "quant_conv" in params:  # SD1/2 checkpoints; SD3/Flux drop it
        x = _conv(x, params["quant_conv"], params["quant_conv_b"])
    mean, logvar = jnp.split(x, 2, axis=-1)
    if key is not None:
        std = jnp.exp(0.5 * jnp.clip(logvar, -30, 20))
        mean = mean + std * jax.random.normal(key, mean.shape, mean.dtype)
    return (mean - cfg.shift_factor) * cfg.scaling_factor


def decode(params: dict, latents: jax.Array, cfg: VAEConfig) -> jax.Array:
    """latents (scaled) -> images [B, H, W, C] in [-1, 1]."""
    g = cfg.norm_groups
    p = params["decoder"]
    z = latents.astype(cfg.jnp_dtype) / cfg.scaling_factor + cfg.shift_factor
    if "post_quant_conv" in params:
        z = _conv(z, params["post_quant_conv"], params["post_quant_conv_b"])
    x = _conv(z, p["conv_in"], p["conv_in_b"])
    x = _resnet(p["mid_res1"], x, g)
    x = _attn(p["mid_attn"], x, g)
    x = _resnet(p["mid_res2"], x, g)
    for i, blk in enumerate(p["up"]):
        x = _resnet(blk["res1"], x, g)
        x = _resnet(blk["res2"], x, g)
        x = _resnet(blk["res3"], x, g)
        if "upsample" in blk:
            B, H, W, C = x.shape
            x = jax.image.resize(x, (B, 2 * H, 2 * W, C), "nearest")
            x = _conv(x, blk["upsample"], blk["upsample_b"])
    x = jax.nn.silu(_gn(x, p["norm_out_scale"], p["norm_out_bias"], g))
    x = _conv(x, p["conv_out"], p["conv_out_b"])
    return jnp.clip(x, -1.0, 1.0)


# -- HF (diffusers AutoencoderKL) interop ------------------------------------


def _t_conv(arr):
    """torch conv [out, in, kh, kw] -> HWIO [kh, kw, in, out]."""
    return arr.transpose(2, 3, 1, 0)


def load_hf_weights(model_dir: str | Path, cfg: VAEConfig, dtype=None) -> dict:
    """Map a diffusers AutoencoderKL safetensors checkpoint
    (vae/diffusion_pytorch_model.safetensors naming) into this tree.
    Proven by the synthesize->load->compare roundtrip in tests
    (zero-egress environment: real checkpoints drop in unchanged)."""
    import numpy as np
    from safetensors import safe_open

    dt = dtype or cfg.jnp_dtype
    raw = {}
    for f in sorted(Path(model_dir).glob("*.safetensors")):
        with safe_open(str(f), framework="np") as sf:
            for name in sf.keys():
                raw[name] = sf.get_tensor(name)

    def conv(name):
        return jnp.asarray(_t_conv(raw.pop(name + ".weight")), dt)

    def bias(name):
        return jnp.asarray(raw.pop(name + ".bias"), dt)

    def vec(name):
        return jnp.asarray(raw.pop(name), dt)

    def resnet(prefix, cin, cout):
        p = {
            "norm1_scale": vec(f"{prefix}.norm1.weight"),
            "norm1_bias": vec(f"{prefix}.norm1.bias"),
            "conv1": conv(f"{prefix}.conv1"),
            "conv1_b": bias(f"{prefix}.conv1"),
            "norm2_scale": vec(f"{prefix}.norm2.weight"),
            "norm2_bias": vec(f"{prefix}.norm2.bias"),
            "conv2": conv(f"{prefix}.conv2"),
            "conv2_b": bias(f"{prefix}.conv2"),
        }
        if f"{prefix}.conv_shortcut.weight" in raw:
            p["shortcut"] = conv(f"{prefix}.conv_shortcut")
            p["shortcut_b"] = bias(f"{prefix}.conv_shortcut")
        return p

    def attn(prefix):
        # diffusers Attention: linear [out, in] -> ours [in, out]
        def lin(n):
            return jnp.asarray(raw.pop(f"{prefix}.{n}.weight").T, dt)

        return {
            "norm_scale": vec(f"{prefix}.group_norm.weight"),
            "norm_bias": vec(f"{prefix}.group_norm.bias"),
            "q": lin("to_q"), "q_b": vec(f"{prefix}.to_q.bias"),
            "k": lin("to_k"), "k_b": vec(f"{prefix}.to_k.bias"),
            "v": lin("to_v"), "v_b": vec(f"{prefix}.to_v.bias"),
            "o": lin("to_out.0"), "o_b": vec(f"{prefix}.to_out.0.bias"),
        }

    widths = [cfg.base * m for m in cfg.channel_mults]
    enc = {
        "conv_in": conv("encoder.conv_in"),
        "conv_in_b": bias("encoder.conv_in"),
        "down": [],
        "mid_res1": resnet("encoder.mid_block.resnets.0", widths[-1], widths[-1]),
        "mid_attn": attn("encoder.mid_block.attentions.0"),
        "mid_res2": resnet("encoder.mid_block.resnets.1", widths[-1], widths[-1]),
        "norm_out_scale": vec("encoder.conv_norm_out.weight"),
        "norm_out_bias": vec("encoder.conv_norm_out.bias"),
        "conv_out": conv("encoder.conv_out"),
        "conv_out_b": bias("encoder.conv_out"),
    }
    cin = widths[0]
    for i, w in enumerate(widths):
        blk = {
            "res1": resnet(f"encoder.down_blocks.{i}.resnets.0", cin, w),
            "res2": resnet(f"encoder.down_blocks.{i}.resnets.1", w, w),
        }
        if i < len(widths) - 1:
            blk["downsample"] = conv(f"encoder.down_blocks.{i}.downsamplers.0.conv")
            blk["downsample_b"] = bias(f"encoder.down_blocks.{i}.downsamplers.0.conv")
        enc["down"].append(blk)
        cin = w

    dec = {
        "conv_in": conv("decoder.conv_in"),
        "conv_in_b": bias("decoder.conv_in"),
        "mid_res1": resnet("decoder.mid_block.resnets.0", widths[-1], widths[-1]),
        "mid_attn": attn("decoder.mid_block.attentions.0"),
        "mid_res2": resnet("decoder.mid_block.resnets.1", widths[-1], widths[-1]),
        "up": [],
        "norm_out_scale": vec("decoder.conv_norm_out.weight"),
        "norm_out_bias": vec("decoder.conv_norm_out.bias"),
        "conv_out": conv("decoder.conv_out"),
        "conv_out_b": bias("decoder.conv_out"),
    }
    cin = widths[-1]
    for i, w in enumerate(reversed(widths)):
        blk = {
            "res1": resnet(f"decoder.up_blocks.{i}.resnets.0", cin, w),
            "res2": resnet(f"decoder.up_blocks.{i}.resnets.1", w, w),
            "res3": resnet(f"decoder.up_blocks.{i}.resnets.2", w, w),
        }
        if i < len(widths) - 1:
            blk["upsample"] = conv(f"decoder.up_blocks.{i}.upsamplers.0.conv")
            blk["upsample_b"] = bias(f"decoder.up_blocks.{i}.upsamplers.0.conv")
        dec["up"].append(blk)
        cin = w
    # quant convs (1x1) exist in SD1/2 checkpoints; SD3/Flux drop them.
    params = {"encoder": enc, "decoder": dec}
    if "quant_conv.weight" in raw:
        params["quant_conv"] = conv("quant_conv")
        params["quant_conv_b"] = bias("quant_conv")
        params["post_quant_conv"] = conv("post_quant_conv")
        params["post_quant_conv_b"] = bias("post_quant_conv")
    return params
