"""Shared transformer building blocks (pure-functional JAX).

Replaces the torch module zoo the reference leans on (HF transformers /
vLLM / unsloth internals) with TPU-first primitives: parameters are plain
pytrees (nested dicts of jax arrays) so sharding is a PartitionSpec tree and
checkpointing is orbax-native; compute is bf16 on the MXU with f32 for norms
and softmax; attention goes through ops.flash_attention (training/prefill)
or ops.paged_decode_attention (serving decode).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops import flash_attention


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """RMSNorm in f32, cast back to input dtype (llama-family norm)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)


def layer_norm(
    x: jax.Array, weight: jax.Array, bias: jax.Array, eps: float = 1e-5
) -> jax.Array:
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (normed * weight + bias).astype(x.dtype)


def rotary_embedding(
    positions: jax.Array,  # [..., S] int32
    head_dim: int,
    theta: float = 10000.0,
    dtype=jnp.float32,
    rope_scaling: dict | None = None,
) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for RoPE at the given positions: [..., S, head_dim/2].

    ``rope_scaling`` supports the llama3.1 scheme (HF config keys:
    factor, low_freq_factor, high_freq_factor, original_max_position_
    embeddings): low-frequency components are stretched by ``factor``,
    high-frequency kept, mid-band smoothly interpolated — the context
    extension used by llama-3.1/3.2 checkpoints.
    """
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if rope_scaling:
        factor = float(rope_scaling.get("factor", 8.0))
        low = float(rope_scaling.get("low_freq_factor", 1.0))
        high = float(rope_scaling.get("high_freq_factor", 4.0))
        orig = float(
            rope_scaling.get("original_max_position_embeddings", 8192)
        )
        wavelen = 2.0 * jnp.pi / freqs
        low_wavelen = orig / low
        high_wavelen = orig / high
        # smooth factor in [0,1]: 1 at high-freq end, 0 at low-freq end
        smooth = jnp.clip(
            (orig / wavelen - low) / jnp.maximum(high - low, 1e-6), 0.0, 1.0
        )
        scaled = jnp.where(
            wavelen > low_wavelen,
            freqs / factor,  # low frequency: stretch fully
            jnp.where(
                wavelen < high_wavelen,
                freqs,  # high frequency: keep
                (1 - smooth) * freqs / factor + smooth * freqs,
            ),
        )
        freqs = scaled
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., S, half]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate pairs (split-half convention, matching llama weights).

    x: [B, H, S, D]; cos/sin: [B, S, D/2] or [S, D/2].
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    if cos.ndim == 2:  # [S, half] -> broadcast over B, H
        cos_b = cos[None, None]
        sin_b = sin[None, None]
    else:  # [B, S, half] -> broadcast over H
        cos_b = cos[:, None]
        sin_b = sin[:, None]
    o1 = x1 * cos_b - x2 * sin_b
    o2 = x2 * cos_b + x1 * sin_b
    return jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)


def mm(x: jax.Array, w) -> jax.Array:
    """x @ w with f32 accumulation; ``w`` may be an int8 QuantizedWeight
    (weights upcast tile-wise into the MXU, then per-channel rescale)."""
    from .quantize import QuantizedWeight

    if isinstance(w, QuantizedWeight):
        y = jnp.dot(x, w.q.astype(x.dtype), preferred_element_type=jnp.float32)
        return y * w.scale
    return jnp.dot(x, w, preferred_element_type=jnp.float32)


def _proj_f32(x, w, name, lora, lora_scale):
    """x @ w in f32 accumulation, plus the LoRA low-rank delta when an
    adapter targets ``name``. Returns f32 (caller decides when to round)."""
    out = mm(x, w)
    if lora is not None and f"{name}_a" in lora:
        from .lora import delta

        out = out + delta(x, lora[f"{name}_a"], lora[f"{name}_b"], lora_scale)
    return out


def _proj(x, w, name, lora, lora_scale):
    return _proj_f32(x, w, name, lora, lora_scale).astype(x.dtype)


def swiglu_mlp(
    params: dict, x: jax.Array, lora: dict | None = None, lora_scale: float = 1.0
) -> jax.Array:
    """SwiGLU feed-forward: silu(x W_gate) * (x W_up) W_down.

    gate/up stay f32 through the silu product (one rounding at the end),
    matching f32-accumulated MXU semantics.
    """
    gate = _proj_f32(x, params["gate"], "gate", lora, lora_scale)
    up = _proj_f32(x, params["up"], "up", lora, lora_scale)
    h = (jax.nn.silu(gate) * up).astype(x.dtype)
    return _proj(h, params["down"], "down", lora, lora_scale)


def quick_gelu(x: jax.Array) -> jax.Array:
    """CLIP's activation: x * sigmoid(1.702 x) (published CLIP towers and
    text encoders use this, not tanh/erf GELU)."""
    return x * jax.nn.sigmoid(1.702 * x)


def gelu_mlp(params: dict, x: jax.Array, *, exact: bool = False) -> jax.Array:
    """GELU feed-forward with biases. ``exact`` selects erf-GELU (BERT/
    Whisper convention) vs the default tanh approximation (GPT-2's
    gelu_new) — the flavors differ by ~1e-3 and published checkpoints mix
    them, so the model picks."""
    h = jnp.dot(x, params["fc_w"], preferred_element_type=jnp.float32) + params[
        "fc_b"
    ].astype(jnp.float32)
    h = jax.nn.gelu(h, approximate=not exact).astype(x.dtype)
    return (
        jnp.dot(h, params["proj_w"], preferred_element_type=jnp.float32)
        + params["proj_b"].astype(jnp.float32)
    ).astype(x.dtype)


def attention_op(q, k, v, causal: bool, impl: str = "flash") -> jax.Array:
    """Dispatch between the Pallas flash kernel and XLA attention.

    ``flash``: the Pallas kernel — use on a single chip or inside shard_map
    (where operands are shard-local). ``xla``: plain einsum attention that
    XLA auto-partitions — use under multi-device jit with sharded params,
    where a pallas_call can't be partitioned by the compiler.
    """
    if impl == "flash":
        return flash_attention(q, k, v, causal)
    from ..ops import reference

    return reference.attention(q, k, v, causal=causal)


def causal_self_attention(
    params: dict,
    x: jax.Array,  # [B, S, E]
    *,
    n_heads: int,
    n_kv_heads: int,
    cos: jax.Array | None = None,
    sin: jax.Array | None = None,
    causal: bool = True,
    attn_impl: str = "flash",
    lora: dict | None = None,
    lora_scale: float = 1.0,
) -> jax.Array:
    """Projection + (optional RoPE) + fused attention + output projection."""
    B, S, E = x.shape
    D = E // n_heads
    q = _proj(x, params["wq"], "wq", lora, lora_scale)
    k = _proj(x, params["wk"], "wk", lora, lora_scale)
    v = _proj(x, params["wv"], "wv", lora, lora_scale)
    q = q.reshape(B, S, n_heads, D).transpose(0, 2, 1, 3)
    k = k.reshape(B, S, n_kv_heads, D).transpose(0, 2, 1, 3)
    v = v.reshape(B, S, n_kv_heads, D).transpose(0, 2, 1, 3)
    if cos is not None:
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
    o = attention_op(q, k, v, causal, attn_impl)
    o = o.transpose(0, 2, 1, 3).reshape(B, S, E)
    return _proj(o, params["wo"], "wo", lora, lora_scale)


def init_dense(key, shape, scale: float | None = None, dtype=jnp.bfloat16):
    # fan-in is the contraction dim: shape[-2] for (possibly layer-stacked)
    # [..., in, out] weights, not shape[0] (which is n_layers when stacked)
    fan_in = shape[-2] if len(shape) >= 2 else shape[0]
    if scale is None:
        scale = fan_in**-0.5
    # sample directly in the target dtype: a 7B bf16 init must never
    # materialize an f32 copy (2x HBM) on a 16GB chip
    return jax.random.normal(key, shape, dtype) * jnp.asarray(scale, dtype)
