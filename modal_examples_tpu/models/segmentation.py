"""Promptable segmentation (SAM-family architecture at demo scale).

The reference's segmentation tier runs Meta's Segment Anything on torch
CUDA (/root/reference/06_gpu_and_ml/sam/segment_anything.py: load
checkpoint, embed image once, decode masks per prompt). This module is the
TPU-native counterpart at the architecture level: an image encoder
computes a reusable feature map ONCE; a prompt encoder embeds click
points; a lightweight mask decoder cross-attends prompt tokens to image
features and predicts a mask + its estimated IoU — so one image embedding
serves many interactive prompts, SAM's defining property.

TPU-first: NHWC convs into a static-shape feature grid, one scanned
decoder block, mask upsampling as reshape-style depth-to-space matmuls
(no dynamic shapes anywhere). Zero egress: no SAM checkpoint exists here;
the example trains this model from scratch on synthetic multi-object
scenes where the task is real (click a shape -> segment THAT shape, not
the others).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import layers


@dataclasses.dataclass(frozen=True)
class SAMConfig:
    image_size: int = 64
    stride: int = 8  # fixed by the encoder: three stride-2 convs = 8x
    dim: int = 128
    n_heads: int = 4
    n_decoder_layers: int = 2
    norm_eps: float = 1e-6
    dtype: str = "float32"

    def __post_init__(self):
        if self.stride != 8:
            raise ValueError(
                "stride is fixed at 8 (the encoder is three stride-2 convs); "
                "change image_size to change the grid"
            )
        if self.image_size % 8:
            raise ValueError("image_size must be a multiple of 8")

    @property
    def grid(self) -> int:
        return self.image_size // self.stride

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)


def _conv(key, k, cin, cout, dtype):
    return layers.init_dense(
        key, (k, k, cin, cout), scale=(k * k * cin) ** -0.5, dtype=dtype
    )


def init_params(key: jax.Array, cfg: SAMConfig) -> dict:
    dt = cfg.jnp_dtype
    D, L = cfg.dim, cfg.n_decoder_layers
    ks = iter(jax.random.split(key, 20))

    def dense(*shape, scale=None):
        return layers.init_dense(next(ks), shape, scale=scale, dtype=dt)

    return {
        # image encoder: 3 stride-2 convs -> [grid, grid, D]
        "enc1": _conv(next(ks), 3, 3, D // 4, dt),
        "enc2": _conv(next(ks), 3, D // 4, D // 2, dt),
        "enc3": _conv(next(ks), 3, D // 2, D, dt),
        "enc_pos": dense(cfg.grid * cfg.grid, D, scale=0.02),
        # prompt encoder: click (x, y) -> sinusoid features -> D
        "prompt_proj": dense(4 * 16, D),
        # mask decoder: prompt + learned mask token cross-attend to image
        "mask_token": dense(D, scale=0.02),
        "dec": {
            # token self-attention FIRST: without it the mask token never
            # sees the prompt and the output is click-independent (caught
            # by tests/test_segmentation.py's promptability probe)
            "ln0_s": jnp.ones((L, D), dt), "ln0_b": jnp.zeros((L, D), dt),
            "swq": dense(L, D, D), "swk": dense(L, D, D),
            "swv": dense(L, D, D), "swo": dense(L, D, D),
            "ln1_s": jnp.ones((L, D), dt), "ln1_b": jnp.zeros((L, D), dt),
            "wq": dense(L, D, D), "wk": dense(L, D, D),
            "wv": dense(L, D, D), "wo": dense(L, D, D),
            "ln2_s": jnp.ones((L, D), dt), "ln2_b": jnp.zeros((L, D), dt),
            "fc": dense(L, D, 2 * D), "fc_b": jnp.zeros((L, 2 * D), dt),
            "proj": dense(L, 2 * D, D), "proj_b": jnp.zeros((L, D), dt),
        },
        # per-pixel mask head: feature-map dot the mask token (SAM's
        # hypernetwork-lite), then depth-to-space x8 refinement
        "mask_up": dense(D, cfg.stride * cfg.stride),
        "iou_head": dense(D, 1),
    }


def _point_features(points: jax.Array, cfg: SAMConfig) -> jax.Array:
    """[B, 2] click coords in [0, 1] -> [B, 64] sinusoid features."""
    freqs = 2.0 ** jnp.arange(16)
    args = points[:, :, None] * freqs[None, None] * jnp.pi  # [B, 2, 16]
    feats = jnp.concatenate(
        [jnp.sin(args), jnp.cos(args)], axis=-1
    )  # [B, 2, 32]
    return feats.reshape(points.shape[0], -1)


def encode_image(params: dict, images: jax.Array, cfg: SAMConfig) -> jax.Array:
    """[B, S, S, 3] -> feature map [B, grid*grid, D] (computed ONCE per
    image; every prompt reuses it — sam's interactive-use contract)."""

    def conv(x, w):
        return jax.nn.relu(
            jax.lax.conv_general_dilated(
                x, w, (2, 2), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        )

    x = conv(images.astype(cfg.jnp_dtype), params["enc1"])
    x = conv(x, params["enc2"])
    x = conv(x, params["enc3"])  # [B, grid, grid, D]
    B = x.shape[0]
    return x.reshape(B, cfg.grid * cfg.grid, cfg.dim) + params["enc_pos"][None]


def decode_mask(
    params: dict,
    image_features: jax.Array,  # [B, grid*grid, D] from encode_image
    points: jax.Array,  # [B, 2] click in [0, 1] (x, y)
    cfg: SAMConfig,
) -> tuple[jax.Array, jax.Array]:
    """One prompt -> (mask logits [B, S, S], predicted IoU [B])."""
    B = image_features.shape[0]
    D = cfg.dim
    prompt = _point_features(points, cfg) @ params["prompt_proj"]  # [B, D]
    tokens = jnp.stack(
        [jnp.broadcast_to(params["mask_token"][None], (B, D)), prompt], axis=1
    )  # [B, 2, D]: mask token + prompt token

    def norm(v, s, b):
        mu = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        return (v - mu) * jax.lax.rsqrt(var + cfg.norm_eps) * s + b

    hd = D // cfg.n_heads

    def layer_fn(tok, l):
        # 1) self-attention among the (mask, prompt) tokens — the channel
        # through which the click conditions the mask
        a = norm(tok, l["ln0_s"], l["ln0_b"])
        sq = (a @ l["swq"]).reshape(B, 2, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        sk = (a @ l["swk"]).reshape(B, 2, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        sv = (a @ l["swv"]).reshape(B, 2, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        ss = jnp.einsum(
            "bhqd,bhkd->bhqk", sq, sk, preferred_element_type=jnp.float32
        ) * hd**-0.5
        sp = jax.nn.softmax(ss, axis=-1).astype(sv.dtype)
        so = jnp.einsum("bhqk,bhkd->bhqd", sp, sv)
        tok = tok + so.transpose(0, 2, 1, 3).reshape(B, 2, D) @ l["swo"]

        # 2) cross-attention: tokens query the image features
        a = norm(tok, l["ln1_s"], l["ln1_b"])
        q = (a @ l["wq"]).reshape(B, 2, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        k = (image_features @ l["wk"]).reshape(
            B, -1, cfg.n_heads, hd
        ).transpose(0, 2, 1, 3)
        v = (image_features @ l["wv"]).reshape(
            B, -1, cfg.n_heads, hd
        ).transpose(0, 2, 1, 3)
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * hd**-0.5
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, 2, D)
        tok = tok + o @ l["wo"]
        a = norm(tok, l["ln2_s"], l["ln2_b"])
        a = jax.nn.relu(a @ l["fc"] + l["fc_b"]) @ l["proj"] + l["proj_b"]
        return tok + a, None

    tokens, _ = jax.lax.scan(layer_fn, tokens, params["dec"])
    mask_tok = tokens[:, 0]  # [B, D]
    iou = jax.nn.sigmoid(tokens[:, 1] @ params["iou_head"])[:, 0]  # [B]

    # per-grid-cell logits = feature . mask_token, refined to per-pixel by
    # a depth-to-space head (each cell predicts its stride x stride block)
    cell = jnp.einsum("bnd,bd->bn", image_features, mask_tok)  # [B, G*G]
    block = image_features @ params["mask_up"]  # [B, G*G, stride*stride]
    logits = cell[:, :, None] + block  # coarse + fine
    G, st = cfg.grid, cfg.stride
    logits = logits.reshape(B, G, G, st, st)
    logits = logits.transpose(0, 1, 3, 2, 4).reshape(
        B, cfg.image_size, cfg.image_size
    )
    return logits, iou


def segmentation_loss(
    params: dict,
    images: jax.Array,  # [B, S, S, 3]
    points: jax.Array,  # [B, 2]
    masks: jax.Array,  # [B, S, S] float {0, 1} ground truth
    cfg: SAMConfig,
) -> jax.Array:
    """BCE on pixels + L2 on the IoU prediction (SAM's training recipe,
    minus its focal/dice mixture — BCE suffices at demo scale)."""
    feats = encode_image(params, images, cfg)
    logits, iou_pred = decode_mask(params, feats, points, cfg)
    bce = jnp.mean(
        jnp.maximum(logits, 0) - logits * masks
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )
    pred_mask = (logits > 0).astype(jnp.float32)
    inter = jnp.sum(pred_mask * masks, axis=(1, 2))
    union = jnp.sum(jnp.maximum(pred_mask, masks), axis=(1, 2))
    true_iou = inter / jnp.maximum(union, 1.0)
    return bce + 0.1 * jnp.mean((iou_pred - true_iou) ** 2)


# -- synthetic promptable-segmentation scenes --------------------------------


def synthetic_scene(key: jax.Array, cfg: SAMConfig):
    """A scene with two colored shapes; returns (image [S, S, 3],
    point [2] clicking ONE shape, mask [S, S] of the clicked shape).

    The click disambiguates: the same image with a different click must
    produce a different mask — the property that makes this SAM's task
    and not plain semantic segmentation. Shapes occupy disjoint bands so
    every click lands on a visible pixel of its own shape.
    """
    S = cfg.image_size
    ks = jax.random.split(key, 8)
    yy, xx = jnp.mgrid[0:S, 0:S]

    def shape_mask(k, kind, x_lo, x_hi):
        kc = jax.random.split(k, 3)
        cx = jax.random.randint(kc[0], (), x_lo, x_hi)
        cy = jax.random.randint(kc[1], (), S // 5, 4 * S // 5)
        r = jax.random.randint(kc[2], (), S // 12, S // 10)
        if kind == 0:  # disc
            m = (xx - cx) ** 2 + (yy - cy) ** 2 <= r**2
        else:  # square
            m = (jnp.abs(xx - cx) <= r) & (jnp.abs(yy - cy) <= r)
        return m.astype(jnp.float32), jnp.stack(
            [cx / S, cy / S]
        ).astype(jnp.float32)

    # the two shapes live in disjoint horizontal bands (radius < S/10,
    # centers >= S/5 apart), so a click is ALWAYS on a visible pixel of
    # its own shape and never supervises a contradictory/empty mask
    m0, c0 = shape_mask(ks[0], 0, S // 6, 2 * S // 5)
    m1, c1 = shape_mask(ks[1], 1, 3 * S // 5, 5 * S // 6)
    # draw: background noise, shape 0 red-ish, shape 1 blue-ish
    img = 0.1 * jax.random.uniform(ks[2], (S, S, 3))
    col0 = jnp.array([0.9, 0.2, 0.1])
    col1 = jnp.array([0.1, 0.3, 0.9])
    img = img * (1 - m0[:, :, None]) + m0[:, :, None] * col0
    img = img * (1 - m1[:, :, None]) + m1[:, :, None] * col1
    pick = jax.random.bernoulli(ks[3])
    mask = jnp.where(pick, m1, m0)
    point = jnp.where(pick, c1, c0)
    return img, point, mask


def synthetic_batch(key: jax.Array, batch: int, cfg: SAMConfig):
    ks = jax.random.split(key, batch)
    imgs, pts, msks = [], [], []
    for k in ks:
        i, p, m = synthetic_scene(k, cfg)
        imgs.append(i)
        pts.append(p)
        msks.append(m)
    return jnp.stack(imgs), jnp.stack(pts), jnp.stack(msks)
