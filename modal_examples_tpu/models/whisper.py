"""Whisper-family encoder-decoder ASR.

The model behind the reference's Whisper north-star config
(06_gpu_and_ml/openai_whisper/fine_tune_asr.py, finetuning/train/train.py —
HF Seq2SeqTrainer fine-tuning; speech-to-text/batched_whisper.py — dynamic
batched inference). Architecture (whisper geometry): audio encoder = two
GELU convs (stride 1, 2) over log-mel + sinusoidal positions + pre-LN
transformer; text decoder = learned positions + causal self-attention +
cross-attention + tied output head.

JAX-first: per-layer weights stacked for lax.scan, greedy decode as a
fixed-length scan (static shapes; no dynamic host loop), fine-tuning via the
same Trainer as every other model.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import layers


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    n_mels: int = 80
    n_audio_ctx: int = 1500  # encoder frames after stride-2 conv
    n_text_ctx: int = 448
    vocab_size: int = 51865
    dim: int = 512
    n_heads: int = 8
    n_audio_layers: int = 6
    n_text_layers: int = 6
    norm_eps: float = 1e-5
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def base() -> "WhisperConfig":
        return WhisperConfig()

    @staticmethod
    def tiny_en() -> "WhisperConfig":
        return WhisperConfig(dim=384, n_heads=6, n_audio_layers=4, n_text_layers=4)

    @staticmethod
    def test_tiny() -> "WhisperConfig":
        """Cheap-mode config (SURVEY.md §4 tiny-workload switches)."""
        return WhisperConfig(
            n_mels=80, n_audio_ctx=100, n_text_ctx=32, vocab_size=300,
            dim=64, n_heads=2, n_audio_layers=2, n_text_layers=2,
        )


def _sinusoids(length: int, channels: int) -> jnp.ndarray:
    """Fixed sinusoidal position table (whisper encoder convention)."""
    log_timescale = jnp.log(10000.0) / (channels // 2 - 1)
    inv = jnp.exp(-log_timescale * jnp.arange(channels // 2))
    scaled = jnp.arange(length)[:, None] * inv[None, :]
    return jnp.concatenate([jnp.sin(scaled), jnp.cos(scaled)], axis=1)


def init_params(key: jax.Array, cfg: WhisperConfig) -> dict:
    dt = cfg.jnp_dtype
    D, F = cfg.dim, 4 * cfg.dim
    ks = iter(jax.random.split(key, 24))

    def dense(*shape, scale=None):
        return layers.init_dense(next(ks), shape, scale=scale, dtype=dt)

    def enc_dec_layers(L, cross: bool):
        p = {
            "ln1_w": jnp.ones((L, D), dt), "ln1_b": jnp.zeros((L, D), dt),
            "wq": dense(L, D, D), "bq": jnp.zeros((L, D), dt),
            "wk": dense(L, D, D),
            "wv": dense(L, D, D), "bv": jnp.zeros((L, D), dt),
            "wo": dense(L, D, D), "bo": jnp.zeros((L, D), dt),
            "ln2_w": jnp.ones((L, D), dt), "ln2_b": jnp.zeros((L, D), dt),
            "fc_w": dense(L, D, F), "fc_b": jnp.zeros((L, F), dt),
            "proj_w": dense(L, F, D), "proj_b": jnp.zeros((L, D), dt),
        }
        if cross:
            p.update({
                "xln_w": jnp.ones((L, D), dt), "xln_b": jnp.zeros((L, D), dt),
                "xwq": dense(L, D, D), "xbq": jnp.zeros((L, D), dt),
                "xwk": dense(L, D, D),
                "xwv": dense(L, D, D), "xbv": jnp.zeros((L, D), dt),
                "xwo": dense(L, D, D), "xbo": jnp.zeros((L, D), dt),
            })
        return p

    return {
        "conv1_w": dense(3, cfg.n_mels, D, scale=0.02),  # [k, in, out]
        "conv1_b": jnp.zeros((D,), dt),
        "conv2_w": dense(3, D, D, scale=0.02),
        "conv2_b": jnp.zeros((D,), dt),
        "enc": enc_dec_layers(cfg.n_audio_layers, cross=False),
        "enc_ln_w": jnp.ones((D,), dt),
        "enc_ln_b": jnp.zeros((D,), dt),
        "tok_emb": dense(cfg.vocab_size, D, scale=0.02),
        "pos_emb": dense(cfg.n_text_ctx, D, scale=0.02),
        "dec": enc_dec_layers(cfg.n_text_layers, cross=True),
        "dec_ln_w": jnp.ones((D,), dt),
        "dec_ln_b": jnp.zeros((D,), dt),
    }


def _mha(q, k, v, n_heads, causal: bool) -> jax.Array:
    B, Sq, D = q.shape
    Sk = k.shape[1]
    hd = D // n_heads
    q = q.reshape(B, Sq, n_heads, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, Sk, n_heads, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, Sk, n_heads, hd).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * hd**-0.5
    if causal:
        mask = jnp.tril(jnp.ones((Sq, Sk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o.transpose(0, 2, 1, 3).reshape(B, Sq, D)


def encode(params: dict, mel: jax.Array, cfg: WhisperConfig) -> jax.Array:
    """log-mel [B, T, n_mels] -> audio states [B, T//2, D]."""
    dn = ("NWC", "WIO", "NWC")
    # explicit (1, 1) padding, NOT "SAME": for the stride-2 conv XLA's SAME
    # resolves to (0, 1), shifting every window one frame versus torch's
    # padding=1 — caught by the transformers cross-implementation test
    # (tests/test_hf_cross_impl.py; encoder max-abs error 0.23 -> 1e-5)
    x = jax.lax.conv_general_dilated(
        mel, params["conv1_w"], (1,), [(1, 1)], dimension_numbers=dn
    ) + params["conv1_b"]
    x = jax.nn.gelu(x, approximate=False)
    x = jax.lax.conv_general_dilated(
        x, params["conv2_w"], (2,), [(1, 1)], dimension_numbers=dn
    ) + params["conv2_b"]
    x = jax.nn.gelu(x, approximate=False)
    x = x + _sinusoids(x.shape[1], cfg.dim).astype(x.dtype)[None]

    def layer_fn(x, l):
        h = layers.layer_norm(x, l["ln1_w"], l["ln1_b"], cfg.norm_eps)
        q = jnp.dot(h, l["wq"]) + l["bq"]
        k = jnp.dot(h, l["wk"])  # whisper: no bias on key
        v = jnp.dot(h, l["wv"]) + l["bv"]
        o = _mha(q, k, v, cfg.n_heads, causal=False)
        x = x + jnp.dot(o, l["wo"]) + l["bo"]
        h = layers.layer_norm(x, l["ln2_w"], l["ln2_b"], cfg.norm_eps)
        h = layers.gelu_mlp(
            {n: l[n] for n in ("fc_w", "fc_b", "proj_w", "proj_b")}, h,
            exact=True,  # whisper uses erf-GELU
        )
        return x + h, None

    x, _ = jax.lax.scan(layer_fn, x, params["enc"])
    return layers.layer_norm(x, params["enc_ln_w"], params["enc_ln_b"], cfg.norm_eps)


def decode(
    params: dict,
    tokens: jax.Array,  # [B, S] int32
    audio_states: jax.Array,  # [B, Ta, D]
    cfg: WhisperConfig,
    *,
    return_cross_attn: bool = False,
):
    """Teacher-forced decoder. Returns logits ``[B, S, vocab]``; with
    ``return_cross_attn`` also the per-layer HEAD-MEAN cross-attention
    ``[L, B, S, Ta]`` (f32) — the word-timestamp alignment signal. One
    implementation for both paths so transcription and timing can never
    come from different models; the head mean is reduced INSIDE the scan
    so the full [L, B, H, S, Ta] tensor never materializes (whisper-large
    shapes would be GBs per batch element)."""
    B, S = tokens.shape
    x = params["tok_emb"][tokens] + params["pos_emb"][:S][None]
    hd = cfg.dim // cfg.n_heads

    def layer_fn(x, l):
        h = layers.layer_norm(x, l["ln1_w"], l["ln1_b"], cfg.norm_eps)
        q = jnp.dot(h, l["wq"]) + l["bq"]
        k = jnp.dot(h, l["wk"])
        v = jnp.dot(h, l["wv"]) + l["bv"]
        x = x + jnp.dot(
            _mha(q, k, v, cfg.n_heads, causal=True), l["wo"]
        ) + l["bo"]
        h = layers.layer_norm(x, l["xln_w"], l["xln_b"], cfg.norm_eps)
        xq = jnp.dot(h, l["xwq"]) + l["xbq"]
        xk = jnp.dot(audio_states, l["xwk"])
        xv = jnp.dot(audio_states, l["xwv"]) + l["xbv"]
        Ta = audio_states.shape[1]
        qh = xq.reshape(B, S, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        kh = xk.reshape(B, Ta, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        vh = xv.reshape(B, Ta, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        sc = jnp.einsum(
            "bhqd,bhkd->bhqk", qh, kh, preferred_element_type=jnp.float32
        ) * hd**-0.5
        p = jax.nn.softmax(sc, axis=-1)  # [B, H, S, Ta] f32
        o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vh.dtype), vh)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, cfg.dim)
        x = x + jnp.dot(o, l["xwo"]) + l["xbo"]
        h = layers.layer_norm(x, l["ln2_w"], l["ln2_b"], cfg.norm_eps)
        h = layers.gelu_mlp(
            {n: l[n] for n in ("fc_w", "fc_b", "proj_w", "proj_b")}, h,
            exact=True,  # whisper uses erf-GELU
        )
        aux = jnp.mean(p, axis=1) if return_cross_attn else None
        return x + h, aux

    x, attn = jax.lax.scan(layer_fn, x, params["dec"])
    x = layers.layer_norm(x, params["dec_ln_w"], params["dec_ln_b"], cfg.norm_eps)
    logits = jnp.dot(x, params["tok_emb"].T, preferred_element_type=jnp.float32)
    if return_cross_attn:
        return logits, attn
    return logits


def forward(params, mel, tokens, cfg: WhisperConfig) -> jax.Array:
    """Teacher-forced forward (the fine-tuning loss path)."""
    return decode(params, tokens, encode(params, mel, cfg), cfg)


# -- word-level timestamp alignment ------------------------------------------
#
# The whisperx_transcribe.py capability (word timestamps) via Whisper's OWN
# mechanism (openai/whisper's word_timestamps=True): the decoder's
# cross-attention concentrates on the audio frames a token was read from, so
# a monotonic DTW path through the token x audio-frame attention matrix
# assigns each token a frame span. No second aligner model (whisperx bolts
# on wav2vec2 because its backend discards attention; ours doesn't have to).
# ``decode(return_cross_attn=True)`` supplies the signal.


def dtw_path(cost) -> "np.ndarray":  # [S] -> frame index per row
    """Monotonic DTW through a [S, T] cost matrix (lower = better match);
    returns, per token row, the LAST audio frame on the optimal path —
    the token's end frame. Plain numpy: alignment is offline per
    utterance, not a jitted hot path."""
    import numpy as np

    S, T = cost.shape
    D = np.full((S + 1, T + 1), np.inf, np.float64)
    D[0, 0] = 0.0  # path runs corner to corner: the tokens COVER the audio
    step = np.zeros((S + 1, T + 1), np.int8)
    for i in range(1, S + 1):
        for j in range(1, T + 1):
            # moves: down (next token, same frame), diagonal, right (same
            # token, next frame) — tokens advance monotonically in time
            opts = (D[i - 1, j], D[i - 1, j - 1], D[i, j - 1])
            a = int(np.argmin(opts))
            D[i, j] = cost[i - 1, j - 1] + opts[a]
            step[i, j] = a
    ends = np.zeros((S,), np.int64)
    i, j = S, T  # backtrack from the corner (whisper's timing DTW shape)
    while i > 0:
        ends[i - 1] = max(ends[i - 1], j - 1)
        a = step[i, j]
        if a == 0:
            i -= 1
        elif a == 1:
            i -= 1
            j -= 1
        else:
            j -= 1
    return ends


def align_tokens(
    params: dict,
    mel: jax.Array,  # [B, T, n_mels]
    tokens: jax.Array,  # [B, S] int32 (the transcribed sequence)
    cfg: WhisperConfig,
    *,
    frame_seconds: float = 0.02,  # 10 ms mel hop x2 encoder downsample
    bos_id: int | None = None,
):
    """Per-token (start_s, end_s) via cross-attention DTW.

    Returns ``times [B, S, 2]`` float seconds, row i for ``tokens[:, i]``.
    Token ``i`` is aligned by the attention at the position that PREDICTED
    it (teacher forcing offsets by one; same convention as openai/whisper's
    timing pass). ``tokens`` should start with BOS; sequences WITHOUT it —
    ``greedy_transcribe`` strips BOS from its output — pass ``bos_id=`` and
    it is prepended internally (every returned row still matches the input
    tokens). Uses the top half of the decoder layers' heads averaged (the
    alignment signal concentrates in late layers; openai/whisper selects
    per-model alignment heads — a per-checkpoint refinement that plugs in
    here). Adjacent token spans TOUCH (end_k == start_{k+1}), the
    openai/whisper boundary convention.
    """
    import numpy as np

    stripped = bos_id is not None
    if stripped:
        B0 = tokens.shape[0]
        tokens = jnp.concatenate(
            [jnp.full((B0, 1), bos_id, tokens.dtype), tokens], axis=1
        )

    def _attn_mean(params, mel, tokens):
        audio_states = encode(params, mel, cfg)
        _, attn = decode(
            params, tokens, audio_states, cfg, return_cross_attn=True
        )
        L = attn.shape[0]
        return jnp.mean(attn[L // 2 :], axis=0)  # [B, S, Ta]

    # jitted so the unused logits head (a [B, S, vocab] matmul) is DCE'd
    w = np.asarray(jax.jit(_attn_mean)(params, mel, tokens), np.float64)
    B, S, Ta = w.shape
    times = np.zeros((B, S, 2), np.float32)
    for b in range(B):
        # rows 0..S-2 predicted tokens 1..S-1; normalize, cost = -log p
        rows = w[b, :-1]
        rows = rows / np.maximum(rows.sum(-1, keepdims=True), 1e-9)
        ends = dtw_path(-np.log(np.maximum(rows, 1e-9)))
        starts = np.concatenate([[0], ends[:-1] + 1])  # touching boundaries
        times[b, 1:, 0] = starts * frame_seconds
        times[b, 1:, 1] = (ends + 1) * frame_seconds
        times[b, 0] = 0.0  # BOS carries no audio span
    return times[:, 1:] if stripped else times


def words_with_times(
    token_ids, times, decode_fn, *, space_ids=(32,), eos_ids=()
) -> list[dict]:
    """Group one sequence's token times into word spans.

    ``decode_fn(ids) -> str`` is the tokenizer; ``space_ids`` mark word
    boundaries (byte tokenizer: the space byte); processing stops at the
    first id in ``eos_ids`` (``greedy_transcribe`` output is eos-padded).
    Returns ``[{"word", "start", "end"}]`` — the whisperx output shape."""
    words: list[dict] = []
    cur: list[int] = []
    t0 = None
    last = len(token_ids)
    for i, tok in enumerate(token_ids):
        tok = int(tok)
        if tok in eos_ids:
            last = i
            break
        if tok in space_ids:
            if cur:
                words.append(
                    {"word": decode_fn(cur), "start": float(t0),
                     "end": float(times[i - 1][1])}
                )
                cur, t0 = [], None
            continue
        if t0 is None:
            t0 = times[i][0]
        cur.append(tok)
    if cur:
        words.append(
            {"word": decode_fn(cur), "start": float(t0),
             "end": float(times[last - 1][1])}
        )
    return words


def load_hf_weights(model_dir, cfg: WhisperConfig, dtype=None) -> dict:
    """Map an HF openai/whisper-* safetensors checkpoint into this tree.

    HF layout: model.encoder.conv{1,2} (torch conv1d [out,in,k]),
    encoder/decoder.layers.{i}.self_attn (no k bias), decoder encoder_attn,
    fc1/fc2 MLPs, learned decoder positions, tied proj_out.
    """
    from pathlib import Path

    import numpy as np
    from safetensors import safe_open

    dt = dtype or cfg.jnp_dtype
    files = sorted(Path(model_dir).glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no safetensors under {model_dir}")
    raw: dict[str, np.ndarray] = {}
    for f in files:
        with safe_open(str(f), framework="np") as sf:
            for name in sf.keys():
                raw[name.removeprefix("model.")] = sf.get_tensor(name)

    # pop as we convert: never hold checkpoint + converted copies at once
    def g(name, transpose=False):
        arr = raw.pop(name)
        return jnp.asarray(arr.T if transpose else arr, dtype=dt)

    def stack(side: str, fmt: str, L: int, transpose=False):
        mats = [raw.pop(f"{side}.layers.{i}.{fmt}") for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return jnp.asarray(np.stack(mats), dtype=dt)

    def block(side: str, L: int, cross: bool) -> dict:
        p = {
            "ln1_w": stack(side, "self_attn_layer_norm.weight", L),
            "ln1_b": stack(side, "self_attn_layer_norm.bias", L),
            "wq": stack(side, "self_attn.q_proj.weight", L, True),
            "bq": stack(side, "self_attn.q_proj.bias", L),
            "wk": stack(side, "self_attn.k_proj.weight", L, True),
            "wv": stack(side, "self_attn.v_proj.weight", L, True),
            "bv": stack(side, "self_attn.v_proj.bias", L),
            "wo": stack(side, "self_attn.out_proj.weight", L, True),
            "bo": stack(side, "self_attn.out_proj.bias", L),
            "ln2_w": stack(side, "final_layer_norm.weight", L),
            "ln2_b": stack(side, "final_layer_norm.bias", L),
            "fc_w": stack(side, "fc1.weight", L, True),
            "fc_b": stack(side, "fc1.bias", L),
            "proj_w": stack(side, "fc2.weight", L, True),
            "proj_b": stack(side, "fc2.bias", L),
        }
        if cross:
            p.update({
                "xln_w": stack(side, "encoder_attn_layer_norm.weight", L),
                "xln_b": stack(side, "encoder_attn_layer_norm.bias", L),
                "xwq": stack(side, "encoder_attn.q_proj.weight", L, True),
                "xbq": stack(side, "encoder_attn.q_proj.bias", L),
                "xwk": stack(side, "encoder_attn.k_proj.weight", L, True),
                "xwv": stack(side, "encoder_attn.v_proj.weight", L, True),
                "xbv": stack(side, "encoder_attn.v_proj.bias", L),
                "xwo": stack(side, "encoder_attn.out_proj.weight", L, True),
                "xbo": stack(side, "encoder_attn.out_proj.bias", L),
            })
        return p

    return {
        # torch conv1d [out, in, k] -> ours [k, in, out]
        "conv1_w": jnp.asarray(
            raw.pop("encoder.conv1.weight").transpose(2, 1, 0), dtype=dt
        ),
        "conv1_b": g("encoder.conv1.bias"),
        "conv2_w": jnp.asarray(
            raw.pop("encoder.conv2.weight").transpose(2, 1, 0), dtype=dt
        ),
        "conv2_b": g("encoder.conv2.bias"),
        "enc": block("encoder", cfg.n_audio_layers, cross=False),
        "enc_ln_w": g("encoder.layer_norm.weight"),
        "enc_ln_b": g("encoder.layer_norm.bias"),
        "tok_emb": g("decoder.embed_tokens.weight"),
        "pos_emb": g("decoder.embed_positions.weight"),
        "dec": block("decoder", cfg.n_text_layers, cross=True),
        "dec_ln_w": g("decoder.layer_norm.weight"),
        "dec_ln_b": g("decoder.layer_norm.bias"),
    }


def greedy_transcribe(
    params: dict,
    mel: jax.Array,  # [B, T, n_mels]
    cfg: WhisperConfig,
    *,
    bos_id: int,
    eos_id: int,
    max_tokens: int | None = None,
) -> jax.Array:  # [B, max_tokens] (eos-padded)
    """Greedy decode as a fixed-length scan — static shapes end to end."""
    B = mel.shape[0]
    S = max_tokens or cfg.n_text_ctx
    audio_states = encode(params, mel, cfg)
    buf = jnp.full((B, S), eos_id, jnp.int32).at[:, 0].set(bos_id)

    def step(carry, pos):
        buf, done = carry
        logits = decode(params, buf, audio_states, cfg)  # [B, S, V]
        nxt = jnp.argmax(logits[:, pos - 1], axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, eos_id, nxt)
        buf = buf.at[:, pos].set(nxt)
        done = done | (nxt == eos_id)
        return (buf, done), None

    (buf, _), _ = jax.lax.scan(
        step, (buf, jnp.zeros((B,), bool)), jnp.arange(1, S)
    )
    return buf[:, 1:]
