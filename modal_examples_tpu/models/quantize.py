"""Weight-only int8/int4 quantization for serving.

The reference's quantized-LLM story is bitsandbytes 4/8-bit (unsloth loads
4-bit, unsloth_finetune.py:187-197; misc/falcon_bitsandbytes.py is the
negative baseline). TPU-native: weights live in HBM as int8 (or packed
int4) with per-output-channel f32 scales (symmetric, AQT-style) — halving
(quartering) weight HBM traffic and footprint vs bf16 — and matmuls upcast
tiles to bf16 on the way into the MXU (XLA fuses the cast;
ops.quantized_matmul is the Pallas alternative when profiling says so).

int4 uses the native ``jnp.int4`` dtype (XLA packs two nibbles per byte in
TPU HBM); per-output-channel symmetric scaling is cruder than the
group-wise schemes real 4-bit checkpoints use (AWQ/GPTQ group 128), which
is acceptable for the bench's random weights and documented for real ones.

``QuantizedWeight`` is a pytree node, so quantized params flow through
scan/jit/sharding like any other weights.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantizedWeight:
    q: jax.Array  # int8, [..., din, dout]
    scale: jax.Array  # f32, [..., 1, dout]

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype


#: quantization modes every entry point accepts (engine, loaders, CLI)
SUPPORTED = (None, "int8", "int4")


def _qmax(bits: int) -> float:
    if bits == 8:
        return 127.0
    if bits == 4:
        return 7.0
    raise ValueError(f"unsupported quantization bits {bits!r} (4 or 8)")


def quantize_weight(w: jax.Array, bits: int = 8) -> QuantizedWeight:
    """Symmetric per-output-channel int8/int4 over the contraction dim (-2)."""
    qmax = _qmax(bits)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -qmax, qmax)
    return QuantizedWeight(
        q=q.astype(jnp.int8 if bits == 8 else jnp.int4), scale=scale
    )


def dequantize_weight(qw: QuantizedWeight, dtype=jnp.bfloat16) -> jax.Array:
    return (qw.q.astype(jnp.float32) * qw.scale).astype(dtype)


#: the matmul weights worth quantizing in a llama tree — dense AND MoE expert
#: matmuls (norms/embeddings/router stay high precision: tiny, and
#: precision-critical). ONE list shared by every quantization entry point
#: (quantize_llama, init_quantized_llama, llama.load_hf_weights) so
#: quantization="int8" means the same precision tree no matter how the
#: params arrive (ADVICE r3).
LLAMA_TARGETS = (
    "wq", "wk", "wv", "wo", "gate", "up", "down",
    "moe_gate", "moe_up", "moe_down",
)


def bits_of(quantization: str) -> int:
    if quantization not in ("int8", "int4"):
        raise ValueError(f"unknown quantization {quantization!r}")
    return 8 if quantization == "int8" else 4


def quantize_llama(
    params: dict, targets=LLAMA_TARGETS, *, bits: int = 8
) -> dict:
    """Quantize the layer matmuls (and lm_head) of a llama param tree.

    Device-side path for caller-provided trees. Peak HBM is bf16 + int
    together; callers that own the tree outright should random-init via
    ``init_quantized_llama`` (fused, no bf16 peak) or load checkpoints via
    ``llama.load_hf_weights(quantization=...)`` (host-side quantize).
    """
    out = dict(params)
    out["layers"] = {
        name: quantize_weight(w, bits) if name in targets else w
        for name, w in params["layers"].items()
    }
    if "lm_head" in params:
        out["lm_head"] = quantize_weight(params["lm_head"], bits)
    return out


def init_quantized_llama(key, cfg, *, bits: int = 8) -> dict:
    """Random-init a quantized llama tree in ONE jitted program.

    init -> quantize as separate device steps peaks at bf16 + int together
    (~20 GB at 7B — over the v5e ceiling, and the tunneled backend does not
    reliably reclaim deleted buffers across queued ops). Fusing both into a
    single executable makes every bf16 leaf an XLA-internal temporary: the
    compiler frees it inside the program, so peak HBM is the quantized tree
    plus one transient leaf.
    """
    from . import llama

    return jax.jit(
        lambda k: quantize_llama(llama.init_params(k, cfg), bits=bits)
    )(key)


def quantize_weight_host(w: "np.ndarray", bits: int = 8) -> QuantizedWeight:
    """Host-side (numpy) quantization: the checkpoint-load path. The bf16
    tensor never touches the device — only the int payload and scales are
    transferred, so loading a 7B model costs ~7 GB (int8) / ~3.5 GB (int4)
    of HBM, not 20."""
    import ml_dtypes
    import numpy as np

    qmax = _qmax(bits)
    wf = np.asarray(w, dtype=np.float32)
    amax = np.max(np.abs(wf), axis=-2, keepdims=True)
    scale = np.where(amax > 0, amax / qmax, 1.0).astype(np.float32)
    q = np.clip(np.round(wf / scale), -qmax, qmax)
    q = q.astype(np.int8 if bits == 8 else ml_dtypes.int4)
    return QuantizedWeight(q=jnp.asarray(q), scale=jnp.asarray(scale))


def param_bytes(params) -> int:
    """True HBM bytes of a param tree; int4 counts as 4 bits per element
    (XLA packs two nibbles per byte on TPU even though ml_dtypes reports
    itemsize 1)."""
    total = 0
    for x in jax.tree.leaves(params):
        if not hasattr(x, "size"):
            continue
        if str(x.dtype) == "int4":
            total += (x.size + 1) // 2
        else:
            total += x.size * x.dtype.itemsize
    return total
