"""Weight-only int8 quantization for serving.

The reference's quantized-LLM story is bitsandbytes 4/8-bit (unsloth loads
4-bit, unsloth_finetune.py:187-197; misc/falcon_bitsandbytes.py is the
negative baseline). TPU-native: weights live in HBM as int8 with per-output-
channel f32 scales (symmetric, AQT-style) — HALVING weight HBM traffic and
footprint vs bf16 (a 7B llama drops to ~7GB, fitting a 16GB v5e with room
for KV) — and matmuls upcast tiles to bf16 on the way into the MXU (XLA
fuses the cast; ops.quantized_matmul is the Pallas alternative when
profiling says so).

``QuantizedWeight`` is a pytree node, so quantized params flow through
scan/jit/sharding like any other weights.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantizedWeight:
    q: jax.Array  # int8, [..., din, dout]
    scale: jax.Array  # f32, [..., 1, dout]

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype


def quantize_weight(w: jax.Array) -> QuantizedWeight:
    """Symmetric per-output-channel int8 over the contraction dim (-2)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.round(w.astype(jnp.float32) / scale).astype(jnp.int8)
    return QuantizedWeight(q=q, scale=scale)


def dequantize_weight(qw: QuantizedWeight, dtype=jnp.bfloat16) -> jax.Array:
    return (qw.q.astype(jnp.float32) * qw.scale).astype(dtype)


#: the matmul weights worth quantizing in a llama tree — dense AND MoE expert
#: matmuls (norms/embeddings/router stay high precision: tiny, and
#: precision-critical). ONE list shared by every quantization entry point
#: (quantize_llama, init_quantized_llama, llama.load_hf_weights) so
#: quantization="int8" means the same precision tree no matter how the
#: params arrive (ADVICE r3).
LLAMA_TARGETS = (
    "wq", "wk", "wv", "wo", "gate", "up", "down",
    "moe_gate", "moe_up", "moe_down",
)


def quantize_llama(params: dict, targets=LLAMA_TARGETS) -> dict:
    """Quantize the layer matmuls (and lm_head) of a llama param tree.

    Device-side path for caller-provided trees. Peak HBM is bf16 + int8
    together; callers that own the tree outright should random-init via
    ``init_quantized_llama`` (fused, no bf16 peak) or load checkpoints via
    ``llama.load_hf_weights(quantization="int8")`` (host-side quantize).
    """
    out = dict(params)
    out["layers"] = {
        name: quantize_weight(w) if name in targets else w
        for name, w in params["layers"].items()
    }
    if "lm_head" in params:
        out["lm_head"] = quantize_weight(params["lm_head"])
    return out


def init_quantized_llama(key, cfg) -> dict:
    """Random-init an int8-quantized llama tree in ONE jitted program.

    init -> quantize as separate device steps peaks at bf16 + int8 together
    (~20 GB at 7B — over the v5e ceiling, and the tunneled backend does not
    reliably reclaim deleted buffers across queued ops). Fusing both into a
    single executable makes every bf16 leaf an XLA-internal temporary: the
    compiler frees it inside the program, so peak HBM is the int8 tree plus
    one transient leaf.
    """
    from . import llama

    return jax.jit(lambda k: quantize_llama(llama.init_params(k, cfg)))(key)


def quantize_weight_host(w: "np.ndarray") -> QuantizedWeight:
    """Host-side (numpy) quantization: the checkpoint-load path. The bf16
    tensor never touches the device — only the int8 payload and scales are
    transferred, so loading a 7B model costs ~7 GB of HBM, not 20."""
    import numpy as np

    wf = np.asarray(w, dtype=np.float32)
    amax = np.max(np.abs(wf), axis=-2, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(wf / scale), -127, 127).astype(np.int8)
    return QuantizedWeight(q=jnp.asarray(q), scale=jnp.asarray(scale))


def param_bytes(params) -> int:
    return sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves(params)
        if hasattr(x, "size")
    )
