"""Vision-language model: CLIP-style ViT tower + projector over the llama
decoder (LLaVA-family architecture) — the TPU-native counterpart of the
reference's VLM serving examples, which delegate to SGLang/vLLM CUDA engines
(/root/reference/06_gpu_and_ml/llm-serving/sglang_vlm.py — Qwen-VL behind an
OpenAI endpoint; chat_with_pdf_vision.py — image+text RAG chat).

TPU-first design:
- the vision tower is a pre-LN ViT over non-overlapping patches: the patch
  embedding is ONE matmul of [B, n_patches, p*p*3] against [p*p*3, D] (an
  unfold + MXU contraction — no conv shapes for XLA to rewrite), and the
  encoder blocks are the same scanned-layer structure every other model in
  the package uses (one compiled block regardless of depth);
- a 2-layer MLP projector maps patch states into the LLM embedding space
  (the LLaVA recipe);
- the language model IS ``models.llama`` — multimodal prompts enter the
  serving engine as ``input_embeds`` for the first ``n_patches`` positions
  of an ordinary prefill (llama.prefill), after which paged decode is
  completely unchanged: image tokens are just cache entries.

``encode_image`` is jittable and fuses into the engine's multimodal prefill
program, so image encoding rides the same dispatch as the prefill itself.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import jax
import jax.numpy as jnp

from . import layers


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 14
    dim: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    mlp_dim: int = 4096
    norm_eps: float = 1e-5
    dtype: str = "float32"

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def clip_vit_l_14() -> "ViTConfig":
        """openai/clip-vit-large-patch14 — the LLaVA-1.5 vision tower."""
        return ViTConfig()

    @staticmethod
    def tiny(image_size: int = 16, patch_size: int = 8) -> "ViTConfig":
        """Test-tier config (cheap-mode switch, SURVEY.md §4)."""
        return ViTConfig(
            image_size=image_size, patch_size=patch_size, dim=32,
            n_layers=2, n_heads=2, mlp_dim=64,
        )


@dataclasses.dataclass(frozen=True)
class VLMConfig:
    """Vision tower + projector + the llama language model it feeds."""

    vision: ViTConfig
    llm_dim: int  # == LlamaConfig.dim of the paired language model

    @property
    def n_image_tokens(self) -> int:
        return self.vision.n_patches


def init_vision_params(key: jax.Array, cfg: VLMConfig) -> dict:
    v = cfg.vision
    dt = v.jnp_dtype
    D, L = v.dim, v.n_layers
    patch_in = v.patch_size * v.patch_size * 3
    ks = iter(jax.random.split(key, 16))

    def dense(*shape):
        return layers.init_dense(next(ks), shape, dtype=dt)

    return {
        "patch_proj": dense(patch_in, D),
        # class token: real CLIP prepends it and it PARTICIPATES in
        # attention (every patch state depends on it); the projector
        # consumes patch states only, but the token must be in the tower
        "class_emb": layers.init_dense(next(ks), (D,), scale=0.02, dtype=dt),
        "pos_emb": layers.init_dense(
            next(ks), (v.n_patches + 1, D), scale=0.02, dtype=dt
        ),
        "pre_ln_scale": jnp.ones((D,), dt),
        "pre_ln_bias": jnp.zeros((D,), dt),
        "layers": {
            "ln1_scale": jnp.ones((L, D), dt),
            "ln1_bias": jnp.zeros((L, D), dt),
            "wq": dense(L, D, D), "bq": jnp.zeros((L, D), dt),
            "wk": dense(L, D, D), "bk": jnp.zeros((L, D), dt),
            "wv": dense(L, D, D), "bv": jnp.zeros((L, D), dt),
            "wo": dense(L, D, D), "bo": jnp.zeros((L, D), dt),
            "ln2_scale": jnp.ones((L, D), dt),
            "ln2_bias": jnp.zeros((L, D), dt),
            "fc1": dense(L, D, v.mlp_dim),
            "fc1_b": jnp.zeros((L, v.mlp_dim), dt),
            "fc2": dense(L, v.mlp_dim, D),
            "fc2_b": jnp.zeros((L, D), dt),
        },
        # LLaVA-style 2-layer GELU projector into the LLM embedding space
        "proj1": dense(D, cfg.llm_dim),
        "proj1_b": jnp.zeros((cfg.llm_dim,), dt),
        "proj2": dense(cfg.llm_dim, cfg.llm_dim),
        "proj2_b": jnp.zeros((cfg.llm_dim,), dt),
    }


def _ln(x, scale, bias, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias


def patchify(images: jax.Array, patch: int) -> jax.Array:
    """[B, S, S, 3] -> [B, n_patches, patch*patch*3] (row-major patches)."""
    B, H, W, C = images.shape
    gh, gw = H // patch, W // patch
    x = images.reshape(B, gh, patch, gw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, gh, gw, p, p, C]
    return x.reshape(B, gh * gw, patch * patch * C)


def encode_image(
    params: dict,
    images: jax.Array,  # [B, S, S, 3] float in [0, 1]
    cfg: VLMConfig,
) -> jax.Array:  # [B, n_patches, llm_dim]
    """ViT encode + project: image -> LLM-space prefix embeddings."""
    v = cfg.vision
    B = images.shape[0]
    x = patchify(images.astype(v.jnp_dtype), v.patch_size)
    x = layers.mm(x, params["patch_proj"]).astype(v.jnp_dtype)
    cls = jnp.broadcast_to(params["class_emb"][None, None], (B, 1, v.dim))
    x = jnp.concatenate([cls, x], axis=1)  # [B, 1 + n_patches, D]
    x = x + params["pos_emb"][None]
    x = _ln(x, params["pre_ln_scale"], params["pre_ln_bias"], v.norm_eps)
    S = v.n_patches + 1
    hd = v.dim // v.n_heads

    def layer_fn(x, l):
        h = _ln(x, l["ln1_scale"], l["ln1_bias"], v.norm_eps)
        q = (h @ l["wq"] + l["bq"]).reshape(B, S, v.n_heads, hd)
        k = (h @ l["wk"] + l["bk"]).reshape(B, S, v.n_heads, hd)
        val = (h @ l["wv"] + l["bv"]).reshape(B, S, v.n_heads, hd)
        q, k, val = (t.transpose(0, 2, 1, 3) for t in (q, k, val))
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * hd**-0.5  # bidirectional: no mask
        a = jax.nn.softmax(s, axis=-1).astype(val.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", a, val)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, v.dim)
        x = x + (o @ l["wo"] + l["bo"])
        h = _ln(x, l["ln2_scale"], l["ln2_bias"], v.norm_eps)
        h = layers.quick_gelu(h @ l["fc1"] + l["fc1_b"]) @ l["fc2"] + l["fc2_b"]
        return x + h, None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    x = x[:, 1:]  # drop the class token: the projector eats patch states
    # LLaVA projects the (un-normed) penultimate patch states; with the
    # scanned-stack structure the final states stand in — the projector is
    # trained against whatever the tower emits
    h = jax.nn.gelu(
        x @ params["proj1"] + params["proj1_b"], approximate=False
    )  # LLaVA's projector uses exact GELU
    return (h @ params["proj2"] + params["proj2_b"]).astype(jnp.float32)


def preprocess_image(img, image_size: int):
    """PIL image / ndarray -> [S, S, 3] float32 in [0, 1] (host-side)."""
    import numpy as np

    if hasattr(img, "convert"):  # PIL
        img = img.convert("RGB").resize((image_size, image_size))
        arr = np.asarray(img, dtype=np.float32) / 255.0
    else:
        src = np.asarray(img)
        arr = src.astype(np.float32)
        # integer dtypes are 0..255 by definition; float inputs are taken
        # as already-normalized [0, 1] (a max()-based heuristic would send
        # a near-black uint8 image through un-scaled)
        if np.issubdtype(src.dtype, np.integer):
            arr = arr / 255.0
        if arr.shape[:2] != (image_size, image_size):
            try:
                from PIL import Image

                arr = np.asarray(
                    Image.fromarray((arr * 255).astype(np.uint8)).resize(
                        (image_size, image_size)
                    ),
                    dtype=np.float32,
                ) / 255.0
            except Exception as e:
                raise ValueError(
                    f"image shape {arr.shape} != {(image_size, image_size, 3)} "
                    "and PIL resize unavailable"
                ) from e
    if arr.ndim == 2:
        arr = np.repeat(arr[:, :, None], 3, axis=2)
    return arr[:, :, :3]


# -- HF (transformers CLIPVisionModel) interop -------------------------------


def load_hf_vision_weights(
    model_dir: str | Path, cfg: VLMConfig, dtype=None
) -> dict:
    """Map a transformers CLIPVisionModel safetensors checkpoint
    (vision_model.* naming) + a LLaVA-style mm projector
    (multi_modal_projector.linear_1/linear_2) into this tree.

    The CLIP conv1 patch embedding [D, 3, p, p] flattens to our
    [p*p*3, D] matmul ordering (patch pixels row-major, channels minor —
    matching ``patchify``). The class token rides through the tower (it
    participates in attention) and is dropped before the projector (the
    LLaVA recipe).
    """
    import numpy as np
    from safetensors import safe_open

    v = cfg.vision
    dt = dtype or v.jnp_dtype
    raw: dict[str, np.ndarray] = {}
    for f in sorted(Path(model_dir).glob("*.safetensors")):
        with safe_open(str(f), framework="np") as sf:
            for name in sf.keys():
                raw[name] = sf.get_tensor(name)

    P = "vision_model."
    E = P + "encoder.layers.{}."

    def stack(fmt, transpose=True):
        mats = [
            raw.pop(fmt.format(i)).T if transpose else raw.pop(fmt.format(i))
            for i in range(v.n_layers)
        ]
        return jnp.asarray(np.stack(mats), dt)

    # conv1 [D, 3, p, p] -> [p, p, 3, D] -> [p*p*3, D] (pixels row-major,
    # channels innermost — the patchify() ordering)
    conv = raw.pop(P + "embeddings.patch_embedding.weight")
    patch_proj = jnp.asarray(
        conv.transpose(2, 3, 1, 0).reshape(-1, v.dim), dt
    )
    pos = raw.pop(P + "embeddings.position_embedding.weight")

    params = {
        "patch_proj": patch_proj,
        "class_emb": jnp.asarray(
            raw.pop(P + "embeddings.class_embedding"), dt
        ),
        "pos_emb": jnp.asarray(pos, dt),
        "pre_ln_scale": jnp.asarray(raw.pop(P + "pre_layrnorm.weight"), dt),
        "pre_ln_bias": jnp.asarray(raw.pop(P + "pre_layrnorm.bias"), dt),
        "layers": {
            "ln1_scale": stack(E + "layer_norm1.weight", False),
            "ln1_bias": stack(E + "layer_norm1.bias", False),
            "wq": stack(E + "self_attn.q_proj.weight"),
            "bq": stack(E + "self_attn.q_proj.bias", False),
            "wk": stack(E + "self_attn.k_proj.weight"),
            "bk": stack(E + "self_attn.k_proj.bias", False),
            "wv": stack(E + "self_attn.v_proj.weight"),
            "bv": stack(E + "self_attn.v_proj.bias", False),
            "wo": stack(E + "self_attn.out_proj.weight"),
            "bo": stack(E + "self_attn.out_proj.bias", False),
            "ln2_scale": stack(E + "layer_norm2.weight", False),
            "ln2_bias": stack(E + "layer_norm2.bias", False),
            "fc1": stack(E + "mlp.fc1.weight"),
            "fc1_b": stack(E + "mlp.fc1.bias", False),
            "fc2": stack(E + "mlp.fc2.weight"),
            "fc2_b": stack(E + "mlp.fc2.bias", False),
        },
        "proj1": jnp.asarray(
            raw.pop("multi_modal_projector.linear_1.weight").T, dt
        ),
        "proj1_b": jnp.asarray(
            raw.pop("multi_modal_projector.linear_1.bias"), dt
        ),
        "proj2": jnp.asarray(
            raw.pop("multi_modal_projector.linear_2.weight").T, dt
        ),
        "proj2_b": jnp.asarray(
            raw.pop("multi_modal_projector.linear_2.bias"), dt
        ),
    }
    return params
