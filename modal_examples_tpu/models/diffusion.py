"""Text-to-image diffusion: DiT-style transformer + rectified flow.

The model family behind the reference's stable_diffusion workloads
(text_to_image.py serves SD3.5-Large-Turbo — an MMDiT rectified-flow model;
flux.py, image_to_image.py). TPU-first choices:

- **DiT, not UNet**: a patchified transformer maps straight onto the MXU
  (large fused matmuls, no conv plumbing) — the same architectural family as
  SD3/Flux's MMDiT;
- **rectified flow** (x_t = (1-t)x0 + t*eps, v-target = eps - x0) with an
  Euler sampler — few-step generation like the served Turbo checkpoints;
- **adaLN-zero** conditioning on (timestep + pooled text), cross-attention
  to per-token text states (any encoder producing [B, S, text_dim] works —
  the examples use the BERT encoder from models.bert);
- classifier-free guidance via a learned null-text embedding.

Pixel-space at demo sizes; a VAE stage slots in front without changing this
module (latents are just smaller images).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import layers


@dataclasses.dataclass(frozen=True)
class DiTConfig:
    img_size: int = 32
    channels: int = 3
    patch: int = 2
    dim: int = 256
    n_layers: int = 6
    n_heads: int = 8
    text_dim: int = 64
    text_len: int = 16
    norm_eps: float = 1e-6
    dtype: str = "float32"

    @property
    def n_patches(self) -> int:
        return (self.img_size // self.patch) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch * self.patch * self.channels

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def tiny() -> "DiTConfig":
        return DiTConfig(img_size=16, patch=2, dim=128, n_layers=4, n_heads=4)


def timestep_embedding(t: jax.Array, dim: int) -> jax.Array:
    """Sinusoidal embedding of t in [0, 1] -> [B, dim]."""
    half = dim // 2
    freqs = jnp.exp(-jnp.log(10000.0) * jnp.arange(half) / half)
    args = t[:, None] * 1000.0 * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def init_params(key: jax.Array, cfg: DiTConfig) -> dict:
    dt = cfg.jnp_dtype
    D, L = cfg.dim, cfg.n_layers
    ks = iter(jax.random.split(key, 20))

    def dense(*shape, scale=None):
        return layers.init_dense(next(ks), shape, scale=scale, dtype=dt)

    return {
        "patch_proj": dense(cfg.patch_dim, D, scale=0.02),
        "pos_emb": dense(cfg.n_patches, D, scale=0.02),
        "t_mlp1": dense(D, D),
        "t_mlp2": dense(D, D),
        "text_proj": dense(cfg.text_dim, D, scale=0.02),
        "null_text": dense(cfg.text_len, cfg.text_dim, scale=0.02),
        "layers": {
            # adaLN-zero: 6 modulation vectors per block, zero-init gates
            "mod_w": jnp.zeros((L, D, 6 * D), dt),
            "mod_b": jnp.zeros((L, 6 * D), dt),
            "wq": dense(L, D, D),
            "wk": dense(L, D, D),
            "wv": dense(L, D, D),
            "wo": dense(L, D, D),
            "xwq": dense(L, D, D),
            "xwk": dense(L, D, D),
            "xwv": dense(L, D, D),
            "xwo": jnp.zeros((L, D, D), dt),  # zero-init cross-attn output
            "fc_w": dense(L, D, 4 * D),
            "fc_b": jnp.zeros((L, 4 * D), dt),
            "proj_w": dense(L, 4 * D, D),
            "proj_b": jnp.zeros((L, D), dt),
        },
        "final_mod_w": jnp.zeros((D, 2 * D), dt),
        "final_mod_b": jnp.zeros((2 * D,), dt),
        "final_proj": jnp.zeros((D, cfg.patch_dim), dt),  # zero-init output
    }


def patchify(x: jax.Array, cfg: DiTConfig) -> jax.Array:
    """[B, H, W, C] -> [B, n_patches, patch_dim]."""
    B, H, W, C = x.shape
    p = cfg.patch
    x = x.reshape(B, H // p, p, W // p, p, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, (H // p) * (W // p), p * p * C)


def unpatchify(x: jax.Array, cfg: DiTConfig) -> jax.Array:
    B = x.shape[0]
    p, C = cfg.patch, cfg.channels
    hw = cfg.img_size // p
    x = x.reshape(B, hw, hw, p, p, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, cfg.img_size, cfg.img_size, C)


def _modulate(x, shift, scale):
    return x * (1 + scale[:, None, :]) + shift[:, None, :]


def forward(
    params: dict,
    x_t: jax.Array,  # [B, H, W, C] noised image
    t: jax.Array,  # [B] in [0, 1]
    text_states: jax.Array,  # [B, S, text_dim]
    cfg: DiTConfig,
) -> jax.Array:  # predicted velocity [B, H, W, C]
    B = x_t.shape[0]
    h = patchify(x_t, cfg) @ params["patch_proj"] + params["pos_emb"][None]
    temb = timestep_embedding(t, cfg.dim)
    temb = jnp.dot(jax.nn.silu(temb @ params["t_mlp1"]), params["t_mlp2"])
    text = text_states @ params["text_proj"]  # [B, S, D]
    cond = temb + text.mean(axis=1)  # pooled text joins the adaLN signal

    def norm(v):
        mu = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        return (v - mu) * jax.lax.rsqrt(var + cfg.norm_eps)

    def layer_fn(h, l):
        mod = jax.nn.silu(cond) @ l["mod_w"] + l["mod_b"]  # [B, 6D]
        s1, sc1, g1, s2, sc2, g2 = jnp.split(mod, 6, axis=-1)
        # self-attention with adaLN-zero gating
        a = _modulate(norm(h), s1, sc1)
        q, k, v = a @ l["wq"], a @ l["wk"], a @ l["wv"]
        a = _mha(q, k, v, cfg.n_heads)
        h = h + g1[:, None, :] * (a @ l["wo"])
        # cross-attention to text (zero-init output: starts as identity)
        xq = norm(h) @ l["xwq"]
        xk, xv = text @ l["xwk"], text @ l["xwv"]
        h = h + _mha(xq, xk, xv, cfg.n_heads) @ l["xwo"]
        # MLP with adaLN-zero gating
        m = _modulate(norm(h), s2, sc2)
        m = jax.nn.gelu(m @ l["fc_w"] + l["fc_b"]) @ l["proj_w"] + l["proj_b"]
        return h + g2[:, None, :] * m, None

    h, _ = jax.lax.scan(layer_fn, h, params["layers"])
    fmod = jax.nn.silu(cond) @ params["final_mod_w"] + params["final_mod_b"]
    shift, scale = jnp.split(fmod, 2, axis=-1)
    h = _modulate(norm(h), shift, scale) @ params["final_proj"]
    return unpatchify(h, cfg)


def _null_text(params: dict, shape: tuple) -> jax.Array:
    """Broadcast the learned null embedding to [B, S, text_dim] for any S."""
    B, S, Dt = shape
    stored = params["null_text"]
    n = min(S, stored.shape[0])
    base = jnp.zeros((S, Dt), stored.dtype).at[:n].set(stored[:n])
    return jnp.broadcast_to(base[None], (B, S, Dt))


def _mha(q, k, v, n_heads):
    B, Sq, D = q.shape
    Sk = k.shape[1]
    hd = D // n_heads
    q = q.reshape(B, Sq, n_heads, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, Sk, n_heads, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, Sk, n_heads, hd).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    p = jax.nn.softmax(s * hd**-0.5, axis=-1).astype(v.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o.transpose(0, 2, 1, 3).reshape(B, Sq, D)


# -- rectified flow training + sampling -------------------------------------


def flow_loss(
    params: dict,
    key: jax.Array,
    images: jax.Array,  # [B, H, W, C] in [-1, 1]
    text_states: jax.Array,
    cfg: DiTConfig,
    *,
    null_prob: float = 0.1,
) -> jax.Array:
    """Rectified-flow matching loss with classifier-free-guidance dropout."""
    B = images.shape[0]
    k1, k2, k3 = jax.random.split(key, 3)
    t = jax.random.uniform(k1, (B,))
    eps = jax.random.normal(k2, images.shape)
    x_t = (1 - t[:, None, None, None]) * images + t[:, None, None, None] * eps
    target_v = eps - images
    # CFG dropout: sometimes train unconditionally on the null embedding
    drop = jax.random.bernoulli(k3, null_prob, (B,))
    null = _null_text(params, text_states.shape)
    text_in = jnp.where(drop[:, None, None], null, text_states)
    pred = forward(params, x_t, t, text_in, cfg)
    return jnp.mean((pred - target_v) ** 2)


def sample(
    params: dict,
    key: jax.Array,
    text_states: jax.Array,  # [B, S, text_dim]
    cfg: DiTConfig,
    *,
    steps: int = 8,
    guidance: float = 3.0,
) -> jax.Array:  # [B, H, W, C] in [-1, 1]
    """Euler integration of the learned flow from noise (t=1) to data (t=0),
    with classifier-free guidance — the few-step regime the served Turbo
    models use (text_to_image.py:11-13: 4-step SD3.5)."""
    B = text_states.shape[0]
    x = jax.random.normal(key, (B, cfg.img_size, cfg.img_size, cfg.channels))
    null = _null_text(params, text_states.shape)
    ts = jnp.linspace(1.0, 0.0, steps + 1)

    def step_fn(x, i):
        t_cur, t_nxt = ts[i], ts[i + 1]
        tb = jnp.full((B,), t_cur)
        v_cond = forward(params, x, tb, text_states, cfg)
        v_null = forward(params, x, tb, null, cfg)
        v = v_null + guidance * (v_cond - v_null)
        x = x + (t_nxt - t_cur) * v  # dx/dt = v; integrating t: 1 -> 0
        return x, None

    x, _ = jax.lax.scan(step_fn, x, jnp.arange(steps))
    return jnp.clip(x, -1.0, 1.0)
