"""Text-to-image diffusion: DiT-style transformer + rectified flow.

The model family behind the reference's stable_diffusion workloads
(text_to_image.py serves SD3.5-Large-Turbo — an MMDiT rectified-flow model;
flux.py, image_to_image.py). TPU-first choices:

- **DiT, not UNet**: a patchified transformer maps straight onto the MXU
  (large fused matmuls, no conv plumbing) — the same architectural family as
  SD3/Flux's MMDiT;
- **rectified flow** (x_t = (1-t)x0 + t*eps, v-target = eps - x0) with an
  Euler sampler — few-step generation like the served Turbo checkpoints;
- **adaLN-zero** conditioning on (timestep + pooled text), cross-attention
  to per-token text states (any encoder producing [B, S, text_dim] works —
  the examples use the BERT encoder from models.bert);
- classifier-free guidance via a learned null-text embedding.

Pixel-space at demo sizes; a VAE stage slots in front without changing this
module (latents are just smaller images).

Two model classes live here:
- ``DiTConfig``/``forward``: the compact cross-attention DiT used by the
  trained examples;
- ``MMDiTConfig``/``mmdit_forward``: the SD3/Flux architecture proper —
  two token streams (text context, image patches) with per-stream
  modulation/projections and JOINT attention over their concatenation,
  matching diffusers' SD3Transformer2DModel so real checkpoints map in via
  ``load_mmdit_hf_weights`` (this environment has zero egress, so the
  mapping is proven by a synthesize->load->compare roundtrip instead of a
  live SD3.5 download; the pipeline is sd3_shape-capable by construction).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import layers


@dataclasses.dataclass(frozen=True)
class DiTConfig:
    img_size: int = 32
    channels: int = 3
    patch: int = 2
    dim: int = 256
    n_layers: int = 6
    n_heads: int = 8
    text_dim: int = 64
    text_len: int = 16
    norm_eps: float = 1e-6
    dtype: str = "float32"
    # ControlNet-style spatial conditioning: adds the zero-init
    # control_proj leaf. OPT-IN so pre-existing checkpoints (whose trees
    # lack the leaf) keep restoring against init_params templates.
    control: bool = False

    @property
    def n_patches(self) -> int:
        return (self.img_size // self.patch) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch * self.patch * self.channels

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def tiny() -> "DiTConfig":
        return DiTConfig(img_size=16, patch=2, dim=128, n_layers=4, n_heads=4)


def timestep_embedding(t: jax.Array, dim: int) -> jax.Array:
    """Sinusoidal embedding of t in [0, 1] -> [B, dim]."""
    half = dim // 2
    freqs = jnp.exp(-jnp.log(10000.0) * jnp.arange(half) / half)
    args = t[:, None] * 1000.0 * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def init_params(key: jax.Array, cfg: DiTConfig) -> dict:
    dt = cfg.jnp_dtype
    D, L = cfg.dim, cfg.n_layers
    ks = iter(jax.random.split(key, 20))

    def dense(*shape, scale=None):
        return layers.init_dense(next(ks), shape, scale=scale, dtype=dt)

    return {
        "patch_proj": dense(cfg.patch_dim, D, scale=0.02),
        # spatial conditioning (ControlNet analog, cfg.control=True): the
        # control map patchifies like the image and enters through a
        # ZERO-INIT projection, so a fresh model ignores it and training
        # grows the conditioning pathway from the unconditional behavior
        # (controlnet_gradio_demos.py serves this capability via diffusers)
        **(
            {"control_proj": jnp.zeros((cfg.patch_dim, D), dt)}
            if cfg.control
            else {}
        ),
        "pos_emb": dense(cfg.n_patches, D, scale=0.02),
        "t_mlp1": dense(D, D),
        "t_mlp2": dense(D, D),
        "text_proj": dense(cfg.text_dim, D, scale=0.02),
        "null_text": dense(cfg.text_len, cfg.text_dim, scale=0.02),
        "layers": {
            # adaLN-zero: 6 modulation vectors per block, zero-init gates
            "mod_w": jnp.zeros((L, D, 6 * D), dt),
            "mod_b": jnp.zeros((L, 6 * D), dt),
            "wq": dense(L, D, D),
            "wk": dense(L, D, D),
            "wv": dense(L, D, D),
            "wo": dense(L, D, D),
            "xwq": dense(L, D, D),
            "xwk": dense(L, D, D),
            "xwv": dense(L, D, D),
            "xwo": jnp.zeros((L, D, D), dt),  # zero-init cross-attn output
            "fc_w": dense(L, D, 4 * D),
            "fc_b": jnp.zeros((L, 4 * D), dt),
            "proj_w": dense(L, 4 * D, D),
            "proj_b": jnp.zeros((L, D), dt),
        },
        "final_mod_w": jnp.zeros((D, 2 * D), dt),
        "final_mod_b": jnp.zeros((2 * D,), dt),
        "final_proj": jnp.zeros((D, cfg.patch_dim), dt),  # zero-init output
    }


def patchify(x: jax.Array, cfg: DiTConfig) -> jax.Array:
    """[B, H, W, C] -> [B, n_patches, patch_dim]."""
    B, H, W, C = x.shape
    p = cfg.patch
    x = x.reshape(B, H // p, p, W // p, p, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, (H // p) * (W // p), p * p * C)


def unpatchify(x: jax.Array, cfg: DiTConfig) -> jax.Array:
    B = x.shape[0]
    p, C = cfg.patch, cfg.channels
    hw = cfg.img_size // p
    x = x.reshape(B, hw, hw, p, p, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, cfg.img_size, cfg.img_size, C)


def _modulate(x, shift, scale):
    return x * (1 + scale[:, None, :]) + shift[:, None, :]


def forward(
    params: dict,
    x_t: jax.Array,  # [B, H, W, C] noised image
    t: jax.Array,  # [B] in [0, 1]
    text_states: jax.Array,  # [B, S, text_dim]
    cfg: DiTConfig,
    control: jax.Array | None = None,  # [B, H, W, C] spatial conditioning
    control_tokens: jax.Array | None = None,  # precomputed (sample() hoists)
) -> jax.Array:  # predicted velocity [B, H, W, C]
    B = x_t.shape[0]
    h = patchify(x_t, cfg) @ params["patch_proj"] + params["pos_emb"][None]
    if control_tokens is not None:
        h = h + control_tokens
    elif control is not None:
        if "control_proj" not in params:
            raise ValueError(
                "control= given but params have no control_proj leaf — "
                "train with DiTConfig(control=True)"
            )
        h = h + patchify(control, cfg) @ params["control_proj"]
    temb = timestep_embedding(t, cfg.dim)
    temb = jnp.dot(jax.nn.silu(temb @ params["t_mlp1"]), params["t_mlp2"])
    text = text_states @ params["text_proj"]  # [B, S, D]
    cond = temb + text.mean(axis=1)  # pooled text joins the adaLN signal

    def norm(v):
        mu = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        return (v - mu) * jax.lax.rsqrt(var + cfg.norm_eps)

    def layer_fn(h, l):
        mod = jax.nn.silu(cond) @ l["mod_w"] + l["mod_b"]  # [B, 6D]
        s1, sc1, g1, s2, sc2, g2 = jnp.split(mod, 6, axis=-1)
        # self-attention with adaLN-zero gating
        a = _modulate(norm(h), s1, sc1)
        q, k, v = a @ l["wq"], a @ l["wk"], a @ l["wv"]
        a = _mha(q, k, v, cfg.n_heads)
        h = h + g1[:, None, :] * (a @ l["wo"])
        # cross-attention to text (zero-init output: starts as identity)
        xq = norm(h) @ l["xwq"]
        xk, xv = text @ l["xwk"], text @ l["xwv"]
        h = h + _mha(xq, xk, xv, cfg.n_heads) @ l["xwo"]
        # MLP with adaLN-zero gating
        m = _modulate(norm(h), s2, sc2)
        m = jax.nn.gelu(m @ l["fc_w"] + l["fc_b"]) @ l["proj_w"] + l["proj_b"]
        return h + g2[:, None, :] * m, None

    h, _ = jax.lax.scan(layer_fn, h, params["layers"])
    fmod = jax.nn.silu(cond) @ params["final_mod_w"] + params["final_mod_b"]
    shift, scale = jnp.split(fmod, 2, axis=-1)
    h = _modulate(norm(h), shift, scale) @ params["final_proj"]
    return unpatchify(h, cfg)


def _null_text(params: dict, shape: tuple) -> jax.Array:
    """Broadcast the learned null embedding to [B, S, text_dim] for any S."""
    B, S, Dt = shape
    stored = params["null_text"]
    n = min(S, stored.shape[0])
    base = jnp.zeros((S, Dt), stored.dtype).at[:n].set(stored[:n])
    return jnp.broadcast_to(base[None], (B, S, Dt))


def _mha(q, k, v, n_heads):
    B, Sq, D = q.shape
    Sk = k.shape[1]
    hd = D // n_heads
    q = q.reshape(B, Sq, n_heads, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, Sk, n_heads, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, Sk, n_heads, hd).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    p = jax.nn.softmax(s * hd**-0.5, axis=-1).astype(v.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o.transpose(0, 2, 1, 3).reshape(B, Sq, D)


# -- rectified flow training + sampling -------------------------------------


def flow_loss(
    params: dict,
    key: jax.Array,
    images: jax.Array,  # [B, H, W, C] in [-1, 1]
    text_states: jax.Array,
    cfg: DiTConfig,
    *,
    null_prob: float = 0.1,
    control: jax.Array | None = None,  # spatial conditioning (ControlNet)
) -> jax.Array:
    """Rectified-flow matching loss with classifier-free-guidance dropout."""
    B = images.shape[0]
    k1, k2, k3 = jax.random.split(key, 3)
    t = jax.random.uniform(k1, (B,))
    eps = jax.random.normal(k2, images.shape)
    x_t = (1 - t[:, None, None, None]) * images + t[:, None, None, None] * eps
    target_v = eps - images
    # CFG dropout: sometimes train unconditionally on the null embedding
    drop = jax.random.bernoulli(k3, null_prob, (B,))
    null = _null_text(params, text_states.shape)
    text_in = jnp.where(drop[:, None, None], null, text_states)
    pred = forward(params, x_t, t, text_in, cfg, control=control)
    return jnp.mean((pred - target_v) ** 2)


def sample(
    params: dict,
    key: jax.Array,
    text_states: jax.Array,  # [B, S, text_dim]
    cfg: DiTConfig,
    *,
    steps: int = 8,
    guidance: float = 3.0,
    control: jax.Array | None = None,  # spatial conditioning (ControlNet)
) -> jax.Array:  # [B, H, W, C] in [-1, 1]
    """Euler integration of the learned flow from noise (t=1) to data (t=0),
    with classifier-free guidance — the few-step regime the served Turbo
    models use (text_to_image.py:11-13: 4-step SD3.5)."""
    B = text_states.shape[0]
    x = jax.random.normal(key, (B, cfg.img_size, cfg.img_size, cfg.channels))
    null = _null_text(params, text_states.shape)
    ts = jnp.linspace(1.0, 0.0, steps + 1)
    ctrl_tokens = None
    if control is not None:
        if "control_proj" not in params:
            raise ValueError(
                "control= given but params have no control_proj leaf — "
                "train with DiTConfig(control=True)"
            )
        # loop-invariant: computed ONCE, not 2x per Euler step (XLA does
        # not hoist out of scan bodies)
        ctrl_tokens = patchify(control, cfg) @ params["control_proj"]

    def step_fn(x, i):
        t_cur, t_nxt = ts[i], ts[i + 1]
        tb = jnp.full((B,), t_cur)
        v_cond = forward(
            params, x, tb, text_states, cfg, control_tokens=ctrl_tokens
        )
        v_null = forward(
            params, x, tb, null, cfg, control_tokens=ctrl_tokens
        )
        v = v_null + guidance * (v_cond - v_null)
        x = x + (t_nxt - t_cur) * v  # dx/dt = v; integrating t: 1 -> 0
        return x, None

    x, _ = jax.lax.scan(step_fn, x, jnp.arange(steps))
    return jnp.clip(x, -1.0, 1.0)


# -- MMDiT (SD3/Flux-class joint-attention transformer) ----------------------


@dataclasses.dataclass(frozen=True)
class MMDiTConfig:
    """SD3-family MMDiT: joint attention over [context; image] streams.

    ``sd3_shape()`` reproduces SD3-Medium's dimensions (diffusers
    SD3Transformer2DModel); ``tiny()`` is the test-tier shape.
    """

    img_size: int = 32  # latent H=W
    channels: int = 16  # latent channels (SD3 VAE)
    patch: int = 2
    dim: int = 256
    n_layers: int = 4
    n_heads: int = 4
    text_dim: int = 64  # per-token text-state width (joint stream input)
    pooled_dim: int = 64  # pooled text embedding width
    qk_norm: bool = True  # RMS q/k norm (SD3.5)
    norm_eps: float = 1e-6
    dtype: str = "float32"
    # diffusers SD3Transformer2DModel builds its LAST JointTransformerBlock
    # with context_pre_only=True: a 2*dim continuous context norm, no
    # attn.to_add_out, no ff_context — real SD3/SD3.5 checkpoints only load
    # with this on (the params tree then carries a separate "last_block")
    context_pre_only_last: bool = False

    @property
    def n_patches(self) -> int:
        return (self.img_size // self.patch) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch * self.patch * self.channels

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def sd3_shape() -> "MMDiTConfig":
        """SD3-Medium dims: 24 blocks, width 1536, 16-ch latents, CLIP-L+G
        pooled (2048) and 4096-wide joint text states (T5/CLIP concat)."""
        return MMDiTConfig(
            img_size=64, channels=16, patch=2, dim=1536, n_layers=24,
            n_heads=24, text_dim=4096, pooled_dim=2048, dtype="bfloat16",
            context_pre_only_last=True,
        )

    @staticmethod
    def tiny() -> "MMDiTConfig":
        return MMDiTConfig()


def mmdit_init(key: jax.Array, cfg: MMDiTConfig) -> dict:
    dt = cfg.jnp_dtype
    D = cfg.dim
    # with context_pre_only_last, the final block has its own (smaller)
    # leaf set under "last_block"; the scan stack holds the uniform L-1
    L = cfg.n_layers - int(cfg.context_pre_only_last)
    ks = iter(jax.random.split(key, 48))

    def dense(*shape, scale=None):
        return layers.init_dense(next(ks), shape, scale=scale, dtype=dt)

    def per_layer(*shape, scale=None):
        return layers.init_dense(next(ks), (L, *shape), scale=scale, dtype=dt)

    last_block = None
    if cfg.context_pre_only_last:
        last_block = {
            "img_mod_w": jnp.zeros((D, 6 * D), dt),
            "img_mod_b": jnp.zeros((6 * D,), dt),
            # continuous context norm: (scale, shift) only — no gates
            "ctx_mod_w": jnp.zeros((D, 2 * D), dt),
            "ctx_mod_b": jnp.zeros((2 * D,), dt),
            "img_wq": dense(D, D), "img_bq": jnp.zeros((D,), dt),
            "img_wk": dense(D, D), "img_bk": jnp.zeros((D,), dt),
            "img_wv": dense(D, D), "img_bv": jnp.zeros((D,), dt),
            "img_wo": dense(D, D), "img_bo": jnp.zeros((D,), dt),
            "ctx_wq": dense(D, D), "ctx_bq": jnp.zeros((D,), dt),
            "ctx_wk": dense(D, D), "ctx_bk": jnp.zeros((D,), dt),
            "ctx_wv": dense(D, D), "ctx_bv": jnp.zeros((D,), dt),
            "img_qnorm": jnp.ones((cfg.head_dim,), dt),
            "img_knorm": jnp.ones((cfg.head_dim,), dt),
            "ctx_qnorm": jnp.ones((cfg.head_dim,), dt),
            "ctx_knorm": jnp.ones((cfg.head_dim,), dt),
            "img_fc1": dense(D, 4 * D), "img_fc1_b": jnp.zeros((4 * D,), dt),
            "img_fc2": dense(4 * D, D), "img_fc2_b": jnp.zeros((D,), dt),
        }

    tree = {
        "patch_proj": dense(cfg.patch_dim, D, scale=0.02),
        "patch_bias": jnp.zeros((D,), dt),
        "pos_emb": dense(cfg.n_patches, D, scale=0.02),
        "t_mlp1": dense(256, D), "t_mlp1_b": jnp.zeros((D,), dt),
        "t_mlp2": dense(D, D), "t_mlp2_b": jnp.zeros((D,), dt),
        "pool_mlp1": dense(cfg.pooled_dim, D), "pool_mlp1_b": jnp.zeros((D,), dt),
        "pool_mlp2": dense(D, D), "pool_mlp2_b": jnp.zeros((D,), dt),
        "ctx_proj": dense(cfg.text_dim, D), "ctx_proj_b": jnp.zeros((D,), dt),
        "blocks": {
            # per-stream adaLN (6 vectors each), zero-init like adaLN-zero
            "img_mod_w": jnp.zeros((L, D, 6 * D), dt),
            "img_mod_b": jnp.zeros((L, 6 * D), dt),
            "ctx_mod_w": jnp.zeros((L, D, 6 * D), dt),
            "ctx_mod_b": jnp.zeros((L, 6 * D), dt),
            # per-stream qkv/out projections
            "img_wq": per_layer(D, D), "img_bq": jnp.zeros((L, D), dt),
            "img_wk": per_layer(D, D), "img_bk": jnp.zeros((L, D), dt),
            "img_wv": per_layer(D, D), "img_bv": jnp.zeros((L, D), dt),
            "img_wo": per_layer(D, D), "img_bo": jnp.zeros((L, D), dt),
            "ctx_wq": per_layer(D, D), "ctx_bq": jnp.zeros((L, D), dt),
            "ctx_wk": per_layer(D, D), "ctx_bk": jnp.zeros((L, D), dt),
            "ctx_wv": per_layer(D, D), "ctx_bv": jnp.zeros((L, D), dt),
            "ctx_wo": per_layer(D, D), "ctx_bo": jnp.zeros((L, D), dt),
            # qk rms-norm scales (SD3.5)
            "img_qnorm": jnp.ones((L, cfg.head_dim), dt),
            "img_knorm": jnp.ones((L, cfg.head_dim), dt),
            "ctx_qnorm": jnp.ones((L, cfg.head_dim), dt),
            "ctx_knorm": jnp.ones((L, cfg.head_dim), dt),
            # per-stream MLPs
            "img_fc1": per_layer(D, 4 * D), "img_fc1_b": jnp.zeros((L, 4 * D), dt),
            "img_fc2": per_layer(4 * D, D), "img_fc2_b": jnp.zeros((L, D), dt),
            "ctx_fc1": per_layer(D, 4 * D), "ctx_fc1_b": jnp.zeros((L, 4 * D), dt),
            "ctx_fc2": per_layer(4 * D, D), "ctx_fc2_b": jnp.zeros((L, D), dt),
        },
        "final_mod_w": jnp.zeros((D, 2 * D), dt),
        "final_mod_b": jnp.zeros((2 * D,), dt),
        "final_proj": jnp.zeros((D, cfg.patch_dim), dt),
        "final_proj_b": jnp.zeros((cfg.patch_dim,), dt),
    }
    if last_block is not None:
        tree["last_block"] = last_block
    return tree


def _rms(x, scale, eps=1e-6):
    return x * jax.lax.rsqrt(jnp.mean(x**2, -1, keepdims=True) + eps) * scale


def mmdit_forward(
    params: dict,
    x_t: jax.Array,  # [B, H, W, C] noised latents
    t: jax.Array,  # [B] in [0, 1]
    text_states: jax.Array,  # [B, S, text_dim] per-token (T5/CLIP states)
    pooled: jax.Array,  # [B, pooled_dim] pooled text embedding
    cfg: MMDiTConfig,
) -> jax.Array:  # predicted velocity [B, H, W, C]
    B = x_t.shape[0]
    dcfg = DiTConfig(
        img_size=cfg.img_size, channels=cfg.channels, patch=cfg.patch
    )
    img = patchify(x_t.astype(cfg.jnp_dtype), dcfg) @ params["patch_proj"]
    img = img + params["patch_bias"] + params["pos_emb"][None]
    ctx = text_states.astype(cfg.jnp_dtype) @ params["ctx_proj"] + params["ctx_proj_b"]

    temb = timestep_embedding(t, 256).astype(cfg.jnp_dtype)
    temb = (
        jax.nn.silu(temb @ params["t_mlp1"] + params["t_mlp1_b"])
        @ params["t_mlp2"] + params["t_mlp2_b"]
    )
    pvec = (
        jax.nn.silu(
            pooled.astype(cfg.jnp_dtype) @ params["pool_mlp1"]
            + params["pool_mlp1_b"]
        )
        @ params["pool_mlp2"] + params["pool_mlp2_b"]
    )
    cond = jax.nn.silu(temb + pvec)  # [B, D]

    def norm(v):
        mu = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        return (v - mu) * jax.lax.rsqrt(var + cfg.norm_eps)

    H, hd = cfg.n_heads, cfg.head_dim
    Si = img.shape[1]

    def heads(v):
        return v.reshape(B, -1, H, hd).transpose(0, 2, 1, 3)

    def joint_block(img, ctx, l, pre_only: bool):
        """One MMDiT block. ``pre_only`` mirrors diffusers'
        JointTransformerBlock(context_pre_only=True) — SD3's FINAL block:
        the context stream is normed with a continuous adaLN (2*dim:
        (scale, shift), no gates), contributes q/k/v to the joint
        attention, but its output is discarded (no to_add_out, no
        ff_context)."""
        im = cond @ l["img_mod_w"] + l["img_mod_b"]
        i_s1, i_sc1, i_g1, i_s2, i_sc2, i_g2 = jnp.split(im, 6, axis=-1)
        cm = cond @ l["ctx_mod_w"] + l["ctx_mod_b"]
        if pre_only:
            # AdaLayerNormContinuous chunk order is (scale, shift) —
            # opposite of AdaLayerNormZero's (shift, scale, ...)
            c_sc1, c_s1 = jnp.split(cm, 2, axis=-1)
        else:
            c_s1, c_sc1, c_g1, c_s2, c_sc2, c_g2 = jnp.split(cm, 6, axis=-1)

        ia = _modulate(norm(img), i_s1, i_sc1)
        ca = _modulate(norm(ctx), c_s1, c_sc1)
        qi = heads(ia @ l["img_wq"] + l["img_bq"])
        ki = heads(ia @ l["img_wk"] + l["img_bk"])
        vi = heads(ia @ l["img_wv"] + l["img_bv"])
        qc = heads(ca @ l["ctx_wq"] + l["ctx_bq"])
        kc = heads(ca @ l["ctx_wk"] + l["ctx_bk"])
        vc = heads(ca @ l["ctx_wv"] + l["ctx_bv"])
        if cfg.qk_norm:
            qi, ki = _rms(qi, l["img_qnorm"]), _rms(ki, l["img_knorm"])
            qc, kc = _rms(qc, l["ctx_qnorm"]), _rms(kc, l["ctx_knorm"])
        # JOINT attention over [context; image]
        q = jnp.concatenate([qc, qi], axis=2)
        k = jnp.concatenate([kc, ki], axis=2)
        v = jnp.concatenate([vc, vi], axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
        a = jax.nn.softmax(s * hd**-0.5, axis=-1).astype(v.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", a, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, -1, cfg.dim)
        oc, oi = o[:, : -Si], o[:, -Si:]
        img = img + i_g1[:, None] * (oi @ l["img_wo"] + l["img_bo"])
        m = _modulate(norm(img), i_s2, i_sc2)
        m = jax.nn.gelu(m @ l["img_fc1"] + l["img_fc1_b"], approximate=True)
        img = img + i_g2[:, None] * (m @ l["img_fc2"] + l["img_fc2_b"])
        if pre_only:
            return img, ctx  # context output discarded
        ctx = ctx + c_g1[:, None] * (oc @ l["ctx_wo"] + l["ctx_bo"])
        m = _modulate(norm(ctx), c_s2, c_sc2)
        m = jax.nn.gelu(m @ l["ctx_fc1"] + l["ctx_fc1_b"], approximate=True)
        ctx = ctx + c_g2[:, None] * (m @ l["ctx_fc2"] + l["ctx_fc2_b"])
        return img, ctx

    def block_fn(carry, l):
        img, ctx = carry
        img, ctx = joint_block(img, ctx, l, pre_only=False)
        return (img, ctx), None

    (img, ctx), _ = jax.lax.scan(block_fn, (img, ctx), params["blocks"])
    if cfg.context_pre_only_last:
        img, _ = joint_block(img, ctx, params["last_block"], pre_only=True)
    fmod = cond @ params["final_mod_w"] + params["final_mod_b"]
    # norm_out is AdaLayerNormContinuous: chunk order (scale, shift)
    scale, shift = jnp.split(fmod, 2, axis=-1)
    out = _modulate(norm(img), shift, scale) @ params["final_proj"]
    out = out + params["final_proj_b"]
    return unpatchify(out, dcfg).astype(jnp.float32)


def mmdit_sample(
    params: dict,
    key: jax.Array,
    text_states: jax.Array,  # [B, S, text_dim]
    pooled: jax.Array,  # [B, pooled_dim]
    null_states: jax.Array,  # same shapes for the unconditional branch
    null_pooled: jax.Array,
    cfg: MMDiTConfig,
    *,
    steps: int = 8,
    guidance: float = 4.0,
) -> jax.Array:  # [B, H, W, C] latents
    """Euler rectified-flow sampler with CFG over the MMDiT — the SD3.5
    inference loop (text_to_image.py: 4-step Turbo)."""
    B = text_states.shape[0]
    x = jax.random.normal(
        key, (B, cfg.img_size, cfg.img_size, cfg.channels)
    )
    ts = jnp.linspace(1.0, 0.0, steps + 1)

    def step_fn(x, i):
        tb = jnp.full((B,), ts[i])
        v_c = mmdit_forward(params, x, tb, text_states, pooled, cfg)
        v_u = mmdit_forward(params, x, tb, null_states, null_pooled, cfg)
        v = v_u + guidance * (v_c - v_u)
        return x + (ts[i + 1] - ts[i]) * v, None

    x, _ = jax.lax.scan(step_fn, x, jnp.arange(steps))
    return x


def mmdit_flow_loss(
    params: dict,
    key: jax.Array,
    latents: jax.Array,  # [B, H, W, C]
    text_states: jax.Array,
    pooled: jax.Array,
    cfg: MMDiTConfig,
) -> jax.Array:
    """Rectified-flow matching loss for the MMDiT (training/fine-tune)."""
    B = latents.shape[0]
    k1, k2 = jax.random.split(key)
    t = jax.random.uniform(k1, (B,))
    eps = jax.random.normal(k2, latents.shape)
    x_t = (1 - t[:, None, None, None]) * latents + t[:, None, None, None] * eps
    pred = mmdit_forward(params, x_t, t, text_states, pooled, cfg)
    return jnp.mean((pred - (eps - latents)) ** 2)


# -- HF (diffusers SD3Transformer2DModel) interop ----------------------------


def load_mmdit_hf_weights(model_dir, cfg: MMDiTConfig, dtype=None) -> dict:
    """Map a diffusers SD3Transformer2DModel safetensors checkpoint
    (transformer/diffusion_pytorch_model.safetensors naming) into the
    mmdit tree. Zero-egress proof: synthesize->load->compare roundtrip in
    tests (TestMMDiT); a real SD3/SD3.5 checkout maps through the same
    names, including the context_pre_only FINAL block (no attn.to_add_out /
    ff_context.*, 2*dim norm1_context) — set
    ``cfg.context_pre_only_last=True`` for real checkpoints (sd3_shape()
    does)."""
    from pathlib import Path

    import numpy as np
    from safetensors import safe_open

    dt = dtype or cfg.jnp_dtype
    raw = {}
    for f in sorted(Path(model_dir).glob("*.safetensors")):
        with safe_open(str(f), framework="np") as sf:
            for name in sf.keys():
                raw[name] = sf.get_tensor(name)

    L = cfg.n_layers - int(cfg.context_pre_only_last)

    def lin(name):
        return jnp.asarray(raw.pop(name + ".weight").T, dt)

    def b(name):
        return jnp.asarray(raw.pop(name + ".bias"), dt)

    def vec(name):
        return jnp.asarray(raw.pop(name), dt)

    def stack_lin(fmt):
        return jnp.asarray(
            np.stack([raw.pop(fmt.format(i) + ".weight").T for i in range(L)]), dt
        )

    def stack_b(fmt):
        return jnp.asarray(
            np.stack([raw.pop(fmt.format(i) + ".bias") for i in range(L)]), dt
        )

    def stack_vec(fmt):
        return jnp.asarray(
            np.stack([raw.pop(fmt.format(i))for i in range(L)]), dt
        )

    T = "transformer_blocks.{}."
    # patch embed: conv [D, C, p, p] -> [p*p*C, D] matching patchify order
    # (row-major (ph, pw, c) flattening == conv weight (c, ph, pw) reordered)
    pw = raw.pop("pos_embed.proj.weight")  # [D, C, p, p]
    D_, C_, p_, _ = pw.shape
    patch_proj = jnp.asarray(
        pw.transpose(2, 3, 1, 0).reshape(p_ * p_ * C_, D_), dt
    )
    last_block = None
    if cfg.context_pre_only_last:
        Tl = T.format(cfg.n_layers - 1)
        last_block = {
            "img_mod_w": lin(Tl + "norm1.linear"),
            "img_mod_b": b(Tl + "norm1.linear"),
            "ctx_mod_w": lin(Tl + "norm1_context.linear"),  # [D, 2D]
            "ctx_mod_b": b(Tl + "norm1_context.linear"),
            "img_wq": lin(Tl + "attn.to_q"), "img_bq": b(Tl + "attn.to_q"),
            "img_wk": lin(Tl + "attn.to_k"), "img_bk": b(Tl + "attn.to_k"),
            "img_wv": lin(Tl + "attn.to_v"), "img_bv": b(Tl + "attn.to_v"),
            "img_wo": lin(Tl + "attn.to_out.0"),
            "img_bo": b(Tl + "attn.to_out.0"),
            "ctx_wq": lin(Tl + "attn.add_q_proj"),
            "ctx_bq": b(Tl + "attn.add_q_proj"),
            "ctx_wk": lin(Tl + "attn.add_k_proj"),
            "ctx_bk": b(Tl + "attn.add_k_proj"),
            "ctx_wv": lin(Tl + "attn.add_v_proj"),
            "ctx_bv": b(Tl + "attn.add_v_proj"),
            "img_qnorm": vec(Tl + "attn.norm_q.weight"),
            "img_knorm": vec(Tl + "attn.norm_k.weight"),
            "ctx_qnorm": vec(Tl + "attn.norm_added_q.weight"),
            "ctx_knorm": vec(Tl + "attn.norm_added_k.weight"),
            "img_fc1": lin(Tl + "ff.net.0.proj"),
            "img_fc1_b": b(Tl + "ff.net.0.proj"),
            "img_fc2": lin(Tl + "ff.net.2"),
            "img_fc2_b": b(Tl + "ff.net.2"),
        }
    tree = {
        "patch_proj": patch_proj,
        "patch_bias": jnp.asarray(raw.pop("pos_embed.proj.bias"), dt),
        "pos_emb": jnp.asarray(raw.pop("pos_embed.pos_embed")[0], dt),
        "t_mlp1": lin("time_text_embed.timestep_embedder.linear_1"),
        "t_mlp1_b": b("time_text_embed.timestep_embedder.linear_1"),
        "t_mlp2": lin("time_text_embed.timestep_embedder.linear_2"),
        "t_mlp2_b": b("time_text_embed.timestep_embedder.linear_2"),
        "pool_mlp1": lin("time_text_embed.text_embedder.linear_1"),
        "pool_mlp1_b": b("time_text_embed.text_embedder.linear_1"),
        "pool_mlp2": lin("time_text_embed.text_embedder.linear_2"),
        "pool_mlp2_b": b("time_text_embed.text_embedder.linear_2"),
        "ctx_proj": lin("context_embedder"),
        "ctx_proj_b": b("context_embedder"),
        "blocks": {
            "img_mod_w": stack_lin(T + "norm1.linear"),
            "img_mod_b": stack_b(T + "norm1.linear"),
            "ctx_mod_w": stack_lin(T + "norm1_context.linear"),
            "ctx_mod_b": stack_b(T + "norm1_context.linear"),
            "img_wq": stack_lin(T + "attn.to_q"),
            "img_bq": stack_b(T + "attn.to_q"),
            "img_wk": stack_lin(T + "attn.to_k"),
            "img_bk": stack_b(T + "attn.to_k"),
            "img_wv": stack_lin(T + "attn.to_v"),
            "img_bv": stack_b(T + "attn.to_v"),
            "img_wo": stack_lin(T + "attn.to_out.0"),
            "img_bo": stack_b(T + "attn.to_out.0"),
            "ctx_wq": stack_lin(T + "attn.add_q_proj"),
            "ctx_bq": stack_b(T + "attn.add_q_proj"),
            "ctx_wk": stack_lin(T + "attn.add_k_proj"),
            "ctx_bk": stack_b(T + "attn.add_k_proj"),
            "ctx_wv": stack_lin(T + "attn.add_v_proj"),
            "ctx_bv": stack_b(T + "attn.add_v_proj"),
            "ctx_wo": stack_lin(T + "attn.to_add_out"),
            "ctx_bo": stack_b(T + "attn.to_add_out"),
            "img_qnorm": stack_vec(T + "attn.norm_q.weight"),
            "img_knorm": stack_vec(T + "attn.norm_k.weight"),
            "ctx_qnorm": stack_vec(T + "attn.norm_added_q.weight"),
            "ctx_knorm": stack_vec(T + "attn.norm_added_k.weight"),
            "img_fc1": stack_lin(T + "ff.net.0.proj"),
            "img_fc1_b": stack_b(T + "ff.net.0.proj"),
            "img_fc2": stack_lin(T + "ff.net.2"),
            "img_fc2_b": stack_b(T + "ff.net.2"),
            "ctx_fc1": stack_lin(T + "ff_context.net.0.proj"),
            "ctx_fc1_b": stack_b(T + "ff_context.net.0.proj"),
            "ctx_fc2": stack_lin(T + "ff_context.net.2"),
            "ctx_fc2_b": stack_b(T + "ff_context.net.2"),
        },
        "final_mod_w": lin("norm_out.linear"),
        "final_mod_b": b("norm_out.linear"),
        "final_proj": lin("proj_out"),
        "final_proj_b": b("proj_out"),
    }
    if last_block is not None:
        tree["last_block"] = last_block
    return tree
